"""ServingFrontend: replica registration + round-robin predict routing
with retry/circuit-breaker failover.

The write path got its process-level membership in PR 2
(``parallel/supervisor.py``); the read path reuses exactly that machinery
-- an :class:`~asyncframework_tpu.parallel.supervisor.ElasticSupervisor`
in ``adopt=False`` mode: replicas HELLO in (proc token, pid, host, serve
port), every successful RPC refreshes last-contact, a SIGKILLed local
replica is declared dead by the pid probe within one monitor scan and a
remote one by silence, and a restarted replica's re-HELLO revives its
slot.  Dead replicas simply leave the rotation; there is nothing to
adopt -- any healthy replica can answer any request.

Routing: round-robin over live slots, each RPC under a short
:class:`~asyncframework_tpu.net.RetryPolicy` with the shared per-endpoint
circuit breakers -- a replica that keeps failing is skipped breaker-fast
-- and failover walks the remaining replicas until the per-request
deadline (``async.serve.failover.deadline.s``).  An UNHEALTHY reply (the
replica's own freshness-SLO gate) counts as failover, not error: the
frontend prefers a fresh replica over a stale answer and only raises
:class:`PredictError` when NOBODY healthy answered in time.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from asyncframework_tpu.metrics import flightrec as _flight
from asyncframework_tpu.net import RetryPolicy
from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.net.health import RttSuspector
from asyncframework_tpu.net.retry import breaker_for
from asyncframework_tpu.parallel.supervisor import (
    DEAD,
    SUSPECT,
    ElasticSupervisor,
)
from asyncframework_tpu.serving import metrics as smetrics
from asyncframework_tpu.serving.server import FramedServer

_send_msg = _frame.send_msg
_recv_msg = _frame.recv_msg


class PredictError(ConnectionError):
    """No healthy replica answered within the failover deadline."""


class _ReplicaChannel:
    """A pooled set of persistent connections to one replica,
    reconnect-on-error under a short retry policy (failover wants fast
    verdicts, not patience -- patience is the frontend's job, across
    replicas).  Pooling, not a single locked socket: concurrent client
    requests to the same replica must not serialize on the frontend --
    the replica's per-connection handler threads are the concurrency
    unit, so each in-flight RPC gets its own connection and idle ones
    are reused."""

    MAX_IDLE = 8

    def __init__(self, host: str, port: int, proc: str,
                 retry: RetryPolicy):
        self.host, self.port = host, int(port)
        self.endpoint = f"{host}:{self.port}"
        self.proc = proc
        self.retry = retry
        self._lock = threading.Lock()  # guards the idle list only
        self._idle: List[socket.socket] = []
        self._closed = False

    def _checkout(self) -> socket.socket:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return _frame.connect((self.host, self.port),
                              timeout=self.retry.attempt_timeout_s)

    def _checkin(self, sock: socket.socket) -> None:
        with self._lock:
            if not self._closed and len(self._idle) < self.MAX_IDLE:
                self._idle.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def _drop_idle(self) -> None:
        """One transport error condemns the whole idle pool: its sockets
        share the failed connection's fate (replica died/restarted) and
        burning a retry attempt per stale socket would eat the failover
        budget."""
        with self._lock:
            socks, self._idle = self._idle, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass

    def call(self, header: dict, payload: bytes = b""
             ) -> Tuple[dict, bytes]:
        def attempt() -> Tuple[dict, bytes]:
            sock = self._checkout()
            try:
                _send_msg(sock, header, payload)
                out = _recv_msg(sock)
            except OSError:
                try:
                    sock.close()
                except OSError:
                    pass
                self._drop_idle()
                raise
            self._checkin(sock)
            return out

        return self.retry.call(attempt, endpoint=self.endpoint)

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._drop_idle()


class ServingFrontend(FramedServer):
    """Round-robin predict router over registered replicas.

    Library use: ``fe = ServingFrontend([(host, port), ...]).start()``
    then ``fe.predict(X)``.  Daemon use: ``fe.serve(port)`` additionally
    binds a front door that accepts replica HELLOs (dynamic registration)
    and client PREDICT frames (proxied through :meth:`predict_ex`).
    """

    def __init__(self, replicas: Optional[Sequence[Tuple[str, int]]] = None,
                 deadline_s: Optional[float] = None,
                 max_replicas: Optional[int] = None,
                 dead_after_s: Optional[float] = None,
                 retry: Optional[RetryPolicy] = None):
        from asyncframework_tpu.conf import (
            ELASTIC_DEAD_AFTER_S,
            SERVE_DEADLINE_S,
            SERVE_MAX_REPLICAS,
            global_conf,
        )

        conf = global_conf()
        super().__init__("serve-frontend")
        self.deadline_s = (float(deadline_s) if deadline_s is not None
                           else float(conf.get(SERVE_DEADLINE_S)))
        cap = (int(max_replicas) if max_replicas is not None
               else int(conf.get(SERVE_MAX_REPLICAS)))
        dead_after = (float(dead_after_s) if dead_after_s is not None
                      else float(conf.get(ELASTIC_DEAD_AFTER_S)))
        # PR 2's membership machinery, serving mode: HELLO registration,
        # pid-probe/silence death detection, rejoin revival -- no adoption
        self.supervisor = ElasticSupervisor(
            cap, dead_after_s=dead_after, check_interval_s=0.2,
            adopt=False,
        )
        # ONE attempt per replica per sweep: failover IS the retry (the
        # predict loop re-sweeps the rotation until the deadline, so a
        # transient blip on one replica is retried on the next sweep),
        # and the attempt timeout is a QUARTER of the request deadline so
        # a blackholed replica (partition, SIGSTOP -- it times out rather
        # than refusing) can never eat the whole budget before the other
        # replicas get their turn.  Breakers shared process-wide by
        # endpoint.
        self.retry = retry if retry is not None else RetryPolicy.from_conf(
            max_attempts=1, base_ms=20.0, max_ms=200.0,
            attempt_timeout_s=max(0.25, self.deadline_s / 4.0),
        )
        self._lock = threading.Lock()
        self._channels: List[_ReplicaChannel] = []
        self._by_endpoint: Dict[str, int] = {}
        self._rr = 0
        # gray-failure detection: every answered predict's round trip
        # feeds a cohort RTT suspector; a replica that answers at a
        # multiple of its peers is SUSPECT -- demoted to the back of the
        # rotation (with the dead and breaker-open), never evicted on
        # latency alone
        self._gray = RttSuspector()
        for host, port in (replicas or ()):
            self.add_replica(host, port)

    # --------------------------------------------------------- registration
    def add_replica(self, host: str, port: int,
                    proc: Optional[str] = None,
                    pid: Optional[int] = None,
                    hostname: Optional[str] = None,
                    pid_start: Optional[float] = None) -> int:
        """Register (or revive) a replica; returns its slot index.  The
        proc token defaults to the endpoint, so a restarted replica on
        the same address re-HELLOs into its old slot."""
        endpoint = f"{host}:{int(port)}"
        proc = proc or endpoint
        member = self.supervisor.membership()
        with self._lock:
            idx = self._by_endpoint.get(endpoint)
            if idx is None and len(self._channels) >= \
                    self.supervisor.num_workers:
                # at capacity: reclaim a DEAD slot before refusing --
                # replica churn under k8s hands every replacement pod a
                # fresh IP, so without reclamation the slot table fills
                # with corpses and new replicas can never join
                for i, ch in enumerate(self._channels):
                    if member.get(i, {}).get("state") == DEAD:
                        ch.close()
                        del self._by_endpoint[ch.endpoint]
                        self._channels[i] = _ReplicaChannel(
                            host, port, proc, self.retry
                        )
                        self._by_endpoint[endpoint] = i
                        idx = i
                        smetrics.bump("replicas_registered")
                        break
                if idx is None:
                    raise ValueError(
                        f"replica capacity {self.supervisor.num_workers} "
                        f"exhausted (async.serve.max.replicas) and no "
                        f"dead slot to reclaim"
                    )
            elif idx is None:
                idx = len(self._channels)
                self._channels.append(
                    _ReplicaChannel(host, port, proc, self.retry)
                )
                self._by_endpoint[endpoint] = idx
                smetrics.bump("replicas_registered")
            else:
                self._channels[idx].proc = proc
        self.supervisor.register(proc, [idx], pid=pid, host=hostname,
                                 pid_start=pid_start)
        return idx

    def replica_count(self) -> int:
        with self._lock:
            return len(self._channels)

    def membership(self) -> Dict:
        """Per-slot membership view (the supervisor's, keyed by endpoint)."""
        member = self.supervisor.membership()
        with self._lock:
            return {
                ch.endpoint: member.get(i, {})
                for i, ch in enumerate(self._channels)
            }

    # -------------------------------------------------------------- routing
    def _rotation(self) -> List[_ReplicaChannel]:
        """Live replicas in round-robin order for ONE request: start
        rotates per call; supervisor-dead, SUSPECT (silence past the
        suspect threshold, or a gray-failure RTT outlier), and
        breaker-open slots sort to the back (still tried last -- a
        half-open probe is how a breaker closes, a revived replica is
        how a dead slot comes back, and a suspect that answers fast
        again un-suspects itself)."""
        member = self.supervisor.membership()
        with self._lock:
            n = len(self._channels)
            if n == 0:
                return []
            start = self._rr % n
            self._rr += 1
            order = [self._channels[(start + i) % n] for i in range(n)]
        preferred, backoff = [], []
        for ch in order:
            slot = self._by_endpoint.get(ch.endpoint, 0)
            state = member.get(slot, {}).get("state")
            if state == DEAD:
                # a corpse's frozen RTT EWMA must leave the cohort, or
                # it skews every later suspicion median; a revived
                # replica re-learns from scratch
                self._gray.forget(ch.endpoint)
            if state == SUSPECT and not self._gray.is_suspect(ch.endpoint):
                # the RTT suspicion expired (demotion starved the slot of
                # the traffic that would clear it -- the suspector's TTL
                # is the recovery path), or the suspicion was silence-
                # based, which a demoted replica can also never clear
                # (only predicts touch it): restore the slot to the
                # rotation and let it re-earn its verdict live
                self.supervisor.unsuspect(slot)
                state = None
            tripped = breaker_for(ch.endpoint).open
            (backoff if state in (DEAD, SUSPECT) or tripped
             else preferred).append(ch)
        return preferred + backoff

    def predict(self, X) -> np.ndarray:
        y, _meta = self.predict_ex(X)
        return y

    def predict_ex(self, X) -> Tuple[np.ndarray, Dict]:
        """Route one PREDICT; returns ``(predictions, meta)`` where meta
        carries the answering endpoint, served version, and freshness lag
        (versions + ms).  Raises :class:`PredictError` when no healthy
        replica answers within ``deadline_s``."""
        X = np.ascontiguousarray(np.atleast_2d(np.asarray(X, np.float32)))
        n = int(X.shape[0])
        payload = X.tobytes()
        deadline = time.monotonic() + self.deadline_s
        last_err: Optional[BaseException] = None
        first_try = True
        while True:
            rotation = self._rotation()
            for ch in rotation:
                if time.monotonic() >= deadline:
                    break
                t0 = time.monotonic()
                try:
                    hdr, body = ch.call({"op": "PREDICT", "n": n}, payload)
                except (ConnectionError, OSError) as e:
                    last_err = e
                    smetrics.observe_predict(ch.endpoint, 0.0, 0, 0.0, 0,
                                             ok=False)
                    if not first_try or len(rotation) > 1:
                        smetrics.bump("failovers")
                        # flight-recorder breadcrumb: a frontend dump
                        # ends with which replica it last failed over
                        # from (no-op when no recorder is installed)
                        _flight.note("failover", endpoint=ch.endpoint)
                    first_try = False
                    continue
                first_try = False
                slot = self._by_endpoint.get(ch.endpoint)
                if hdr.get("op") == "UNHEALTHY":
                    # the replica is alive but past its freshness SLO:
                    # contact still counts for membership, the answer
                    # does not
                    if slot is not None:
                        self.supervisor.touch(slot, ch.proc)
                    smetrics.note_attempt()
                    smetrics.bump("unhealthy_rejects")
                    smetrics.bump("failovers")
                    continue
                if hdr.get("op") != "PREDICTION":
                    # ERR-shaped failure (e.g. a malformed batch): this
                    # request failed for the caller -- it must count in
                    # the error view like the deadline path does
                    smetrics.bump("predict_errors")
                    raise PredictError(
                        f"replica {ch.endpoint} answered "
                        f"{hdr.get('op')!r}: {hdr.get('msg')}"
                    )
                if slot is not None:
                    self.supervisor.touch(slot, ch.proc)
                dur_ms = (time.monotonic() - t0) * 1e3
                if slot is not None:
                    # gray-failure feed: this answered RTT vs the cohort
                    if self._gray.observe(ch.endpoint, dur_ms):
                        self.supervisor.suspect(slot)
                    else:
                        self.supervisor.unsuspect(slot)
                meta = {
                    "endpoint": ch.endpoint,
                    "ts": int(hdr.get("ts", 0)),
                    "lag_versions": int(hdr.get("lag_versions", 0)),
                    "lag_ms": float(hdr.get("lag_ms", 0.0)),
                    "dur_ms": dur_ms,
                }
                smetrics.observe_predict(
                    ch.endpoint, dur_ms, meta["lag_versions"],
                    meta["lag_ms"], meta["ts"],
                )
                return np.frombuffer(body, np.float32).copy(), meta
            if time.monotonic() >= deadline:
                break
            # full sweep failed (or nothing registered yet): pace before
            # the next sweep -- open breakers fail fast, and a tight loop
            # here would spin the deadline away
            time.sleep(0.02)
        smetrics.bump("predict_errors")
        raise PredictError(
            f"no healthy replica answered within {self.deadline_s}s "
            f"({self.replica_count()} registered)"
        ) from last_err

    # ------------------------------------------------------------ front door
    def start(self) -> "ServingFrontend":
        """Start the membership monitor (library mode: no front door)."""
        self.supervisor.start()
        return self

    def serve(self, port: int = 0, host: str = "0.0.0.0"
              ) -> "ServingFrontend":
        """Additionally bind the front door: replica HELLOs (dynamic
        registration) and client PREDICT/STATUS frames."""
        self.start()
        self.bind(host, port)
        self.start_accepting()
        return self

    def handle_op(self, conn: socket.socket, op: Optional[str],
                  header: dict, payload: bytes) -> bool:
        if op == "HELLO" and header.get("replica"):
            # dynamic registration: connect back to the peer's IP (its
            # hostname may not resolve here) on its announced serve port;
            # pid+hostname feed the supervisor's local-pid death probe.
            # A refused registration (capacity truly exhausted) is an ERR
            # reply, never a dead handler thread.
            peer_ip = conn.getpeername()[0]
            try:
                idx = self.add_replica(
                    peer_ip, int(header["port"]),
                    proc=str(header.get("proc")),
                    pid=header.get("pid"),
                    hostname=header.get("host"),
                    pid_start=header.get("pstart"),
                )
            except ValueError as e:
                _send_msg(conn, {"op": "ERR", "msg": str(e)[:200]})
                return True
            _send_msg(conn, {"op": "WELCOME", "slot": idx})
        elif op == "PREDICT":
            n = int(header.get("n", 0))
            X = np.frombuffer(payload, np.float32)
            try:
                X = X.reshape(n, -1) if n > 0 else X
                y, meta = self.predict_ex(X)
            except (PredictError, ValueError) as e:
                _send_msg(conn, {"op": "ERR", "msg": str(e)[:200]})
                return True
            _send_msg(conn, {"op": "PREDICTION", **meta},
                      np.ascontiguousarray(y, np.float32).tobytes())
        elif op == "STATUS":
            _send_msg(conn, {
                "op": "STATUS",
                "replicas": self.membership(),
                "serving": smetrics.serving_snapshot(),
                "rtt": self._gray.snapshot(),
            })
        else:
            return False
        return True

    def stop(self) -> None:
        self.stop_server()
        self.supervisor.stop()
        with self._lock:
            for ch in self._channels:
                ch.close()
