"""Shared framed-TCP server scaffolding for the serving tier.

The replica's predict server and the frontend's front door are the same
shape: a listening socket with a short accept timeout, one daemon thread
per connection, finished-handler reaping on append (the thread-leak class
PR 5 fixed in the PS's copy of this loop), a recv/dispatch loop over
``net/frame.py`` messages with shared BYE -> ACK and bad-op -> ERR
handling, and a stop that closes the listener and drops requests already
in flight (a stopped server must fail over, not serve one last possibly-
stale answer).  One base class so a fix to this pattern lands once, not
per daemon; subclasses implement only :meth:`handle_op`.
"""

from __future__ import annotations

import socket
import threading
from typing import List, Optional

from asyncframework_tpu.net import frame as _frame

_send_msg = _frame.send_msg
_recv_msg = _frame.recv_msg


class FramedServer:
    """Accept-loop + per-connection dispatch over the ``net/`` framing.

    Subclasses call :meth:`bind` (immediately or lazily), then
    :meth:`start_accepting`; :meth:`handle_op` returns True when it
    answered the op, False for the shared bad-op ERR reply."""

    def __init__(self, name: str):
        self._name = name
        self._srv: Optional[socket.socket] = None
        self.port: Optional[int] = None
        self._threads: List[threading.Thread] = []
        self._accept_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ lifecycle
    def bind(self, host: str, port: int) -> None:
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.2)
        self.port = self._srv.getsockname()[1]

    def start_accepting(self) -> None:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"{self._name}-accept",
            daemon=True,
        )
        self._accept_thread.start()

    def stop_server(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        self._threads = [t for t in self._threads if t.is_alive()]

    # -------------------------------------------------------------- serving
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name=f"{self._name}-conn-{conn.fileno()}", daemon=True
            )
            t.start()
            # reap on append: a long-lived daemon accepts a fresh
            # connection per client reconnect -- finished handler threads
            # must not accumulate for the life of the process
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._stop.is_set():
                header, payload = _recv_msg(conn)
                if self._stop.is_set():
                    # stopped while blocked in recv: drop the request
                    # instead of serving one last (possibly stale) answer
                    # -- the caller's failover handles it
                    return
                op = header.get("op")
                if op == "BYE":
                    _send_msg(conn, {"op": "ACK"})
                    return
                if not self.handle_op(conn, op, header, payload):
                    _send_msg(conn, {"op": "ERR", "msg": f"bad op {op}"})
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def handle_op(self, conn: socket.socket, op: Optional[str],
                  header: dict, payload: bytes) -> bool:
        raise NotImplementedError
