"""Serving-plane counters and latency/freshness accounting.

Process-global like every other observability module (net counters,
recovery totals, pipeline totals): the ServingFrontend and any in-process
ModelReplica bump these, ``serving_totals()`` feeds the live UI's per-run
delta machinery (flat ints only), ``serving_snapshot()`` adds the derived
views -- predict latency p50/p95/p99, freshness lag in versions AND ms,
per-replica breakdown -- and ``reset_serving_totals()`` is wired into
``asyncframework_tpu.metrics.reset_totals`` so a second serve run in one
process starts from zero instead of inheriting the first run's QPS/lag
totals (the same per-run-isolation contract PR 3 established for the
net/recovery counters).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from asyncframework_tpu.metrics.system import Histogram

_lock = threading.Lock()
_totals: Dict[str, int] = {}
#: per-replica flat views: endpoint -> {predicts, errors, lag_versions,
#: lag_ms, ts} (last-observed values; counts monotone)
_replicas: Dict[str, Dict[str, float]] = {}
_predict_ms = Histogram(capacity=4096)
_lag_versions = Histogram(capacity=4096)
_lag_ms = Histogram(capacity=4096)
#: monotonic time of the first/last observed predict (per-process QPS)
_t_first: Optional[float] = None
_t_last: Optional[float] = None
#: monotonic time + observed lag of the last SUCCESSFUL predict: the
#: freshness-lag SLO input (metrics/slo.py).  While predicts keep
#: succeeding this tracks the served lag; when every replica is down the
#: last success recedes into the past and the derived value grows.
_t_last_ok: Optional[float] = None
_last_ok_lag_ms: float = 0.0


def bump(key: str, n: int = 1) -> None:
    """Monotone serving counter (predicts, predict_errors [whole request
    failed], attempt_errors [one replica RPC failed], failovers,
    unhealthy_rejects, refreshes, refresh_nm/xdelta/full,
    refresh_fallbacks, refresh_errors, replica_predicts,
    replicas_registered)."""
    with _lock:
        _totals[key] = _totals.get(key, 0) + n


def observe_predict(endpoint: str, dur_ms: float, lag_versions: int,
                    lag_ms: float, ts: int, ok: bool = True) -> None:
    """One answered (or failed) PREDICT against ``endpoint``: latency and
    the freshness lag the reply was served at."""
    global _t_first, _t_last, _t_last_ok, _last_ok_lag_ms
    now = time.monotonic()
    with _lock:
        if ok:
            _t_last_ok = now
            _last_ok_lag_ms = float(lag_ms)
        _totals["predicts"] = _totals.get("predicts", 0) + int(ok)
        if not ok:
            # per-ATTEMPT failure (one replica, one RPC); requests that
            # ultimately fail after every failover bump predict_errors
            _totals["attempt_errors"] = _totals.get("attempt_errors", 0) + 1
        rep = _replicas.setdefault(endpoint, {"predicts": 0, "errors": 0})
        if ok:
            rep["predicts"] += 1
            rep["lag_versions"] = int(lag_versions)
            rep["lag_ms"] = round(float(lag_ms), 3)
            rep["ts"] = int(ts)
        else:
            rep["errors"] += 1
        if _t_first is None:
            _t_first = now
        _t_last = now
    if ok:
        _predict_ms.update(float(dur_ms))
        _lag_versions.update(float(lag_versions))
        _lag_ms.update(float(lag_ms))


def note_attempt() -> None:
    """A PREDICT attempt reached the serving plane without producing a
    servable answer (e.g. every replica rejected it UNHEALTHY): advances
    the demand clock :func:`freshness_lag_ms` grows against.  RPC-level
    failures take the same clock via ``observe_predict(ok=False)``."""
    global _t_first, _t_last
    now = time.monotonic()
    with _lock:
        if _t_first is None:
            _t_first = now
        _t_last = now


def freshness_lag_ms() -> Optional[float]:
    """The serve-freshness SLO signal: the model-content lag observed at
    the last successful predict, grown by how far the last predict
    ATTEMPT (ok or failed) has receded past it.  While traffic is being
    answered this tracks the true served lag; when attempts keep failing
    (replicas dead or all UNHEALTHY) the value grows with the failing
    demand -- exactly the "reads are going stale" condition a freshness
    SLO exists to catch.  A traffic lull with healthy replicas holds the
    last observed lag instead of growing (nobody is being served stale
    when nobody is reading), and None until the first successful predict
    (an idle frontend is not an outage)."""
    with _lock:
        if _t_last_ok is None:
            return None
        ref = _t_last_ok if _t_last is None else max(_t_last, _t_last_ok)
        return round(_last_ok_lag_ms + (ref - _t_last_ok) * 1e3, 3)


def serving_totals() -> Dict[str, int]:
    """Flat monotone counters (live-UI ``_delta`` compatible)."""
    with _lock:
        return dict(_totals)


def serving_snapshot() -> Dict:
    """The dashboard view: totals + derived latency/lag percentiles, QPS
    over the observed predict window, and the per-replica breakdown."""
    with _lock:
        totals = dict(_totals)
        replicas = {e: dict(v) for e, v in _replicas.items()}
        window = ((_t_last - _t_first)
                  if _t_first is not None and _t_last is not None else 0.0)
    n = totals.get("predicts", 0)
    return {
        **totals,
        "qps": round(n / window, 1) if window > 0 else float(n),
        "freshness_lag_ms": freshness_lag_ms(),
        "predict_ms": _predict_ms.snapshot(),
        "lag_versions": _lag_versions.snapshot(),
        "lag_ms": _lag_ms.snapshot(),
        "replicas": replicas,
    }


def reset_serving_totals() -> None:
    """Zero every serving counter, ring, and per-replica view (per-run
    isolation; see ``asyncframework_tpu.metrics.reset_totals``)."""
    global _predict_ms, _lag_versions, _lag_ms, _t_first, _t_last
    global _t_last_ok, _last_ok_lag_ms
    with _lock:
        _totals.clear()
        _replicas.clear()
        _t_first = _t_last = None
        _t_last_ok = None
        _last_ok_lag_ms = 0.0
    _predict_ms = Histogram(capacity=4096)
    _lag_versions = Histogram(capacity=4096)
    _lag_ms = Histogram(capacity=4096)
