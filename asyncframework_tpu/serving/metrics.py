"""Serving-plane counters and latency/freshness accounting.

Process-global like every other observability module (net counters,
recovery totals, pipeline totals): the ServingFrontend and any in-process
ModelReplica bump these, ``serving_totals()`` feeds the live UI's per-run
delta machinery (flat ints only), ``serving_snapshot()`` adds the derived
views -- predict latency p50/p95/p99, freshness lag in versions AND ms,
per-replica breakdown -- and ``reset_serving_totals()`` is wired into
``asyncframework_tpu.metrics.reset_totals`` so a second serve run in one
process starts from zero instead of inheriting the first run's QPS/lag
totals (the same per-run-isolation contract PR 3 established for the
net/recovery counters).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from asyncframework_tpu.metrics.system import Histogram

_lock = threading.Lock()
_totals: Dict[str, int] = {}
#: per-replica flat views: endpoint -> {predicts, errors, lag_versions,
#: lag_ms, ts} (last-observed values; counts monotone)
_replicas: Dict[str, Dict[str, float]] = {}
_predict_ms = Histogram(capacity=4096)
_lag_versions = Histogram(capacity=4096)
_lag_ms = Histogram(capacity=4096)
#: monotonic time of the first/last observed predict (per-process QPS)
_t_first: Optional[float] = None
_t_last: Optional[float] = None


def bump(key: str, n: int = 1) -> None:
    """Monotone serving counter (predicts, predict_errors [whole request
    failed], attempt_errors [one replica RPC failed], failovers,
    unhealthy_rejects, refreshes, refresh_nm/xdelta/full,
    refresh_fallbacks, refresh_errors, replica_predicts,
    replicas_registered)."""
    with _lock:
        _totals[key] = _totals.get(key, 0) + n


def observe_predict(endpoint: str, dur_ms: float, lag_versions: int,
                    lag_ms: float, ts: int, ok: bool = True) -> None:
    """One answered (or failed) PREDICT against ``endpoint``: latency and
    the freshness lag the reply was served at."""
    global _t_first, _t_last
    now = time.monotonic()
    with _lock:
        _totals["predicts"] = _totals.get("predicts", 0) + int(ok)
        if not ok:
            # per-ATTEMPT failure (one replica, one RPC); requests that
            # ultimately fail after every failover bump predict_errors
            _totals["attempt_errors"] = _totals.get("attempt_errors", 0) + 1
        rep = _replicas.setdefault(endpoint, {"predicts": 0, "errors": 0})
        if ok:
            rep["predicts"] += 1
            rep["lag_versions"] = int(lag_versions)
            rep["lag_ms"] = round(float(lag_ms), 3)
            rep["ts"] = int(ts)
        else:
            rep["errors"] += 1
        if _t_first is None:
            _t_first = now
        _t_last = now
    if ok:
        _predict_ms.update(float(dur_ms))
        _lag_versions.update(float(lag_versions))
        _lag_ms.update(float(lag_ms))


def serving_totals() -> Dict[str, int]:
    """Flat monotone counters (live-UI ``_delta`` compatible)."""
    with _lock:
        return dict(_totals)


def serving_snapshot() -> Dict:
    """The dashboard view: totals + derived latency/lag percentiles, QPS
    over the observed predict window, and the per-replica breakdown."""
    with _lock:
        totals = dict(_totals)
        replicas = {e: dict(v) for e, v in _replicas.items()}
        window = ((_t_last - _t_first)
                  if _t_first is not None and _t_last is not None else 0.0)
    n = totals.get("predicts", 0)
    return {
        **totals,
        "qps": round(n / window, 1) if window > 0 else float(n),
        "predict_ms": _predict_ms.snapshot(),
        "lag_versions": _lag_versions.snapshot(),
        "lag_ms": _lag_ms.snapshot(),
        "replicas": replicas,
    }


def reset_serving_totals() -> None:
    """Zero every serving counter, ring, and per-replica view (per-run
    isolation; see ``asyncframework_tpu.metrics.reset_totals``)."""
    global _predict_ms, _lag_versions, _lag_ms, _t_first, _t_last
    with _lock:
        _totals.clear()
        _replicas.clear()
        _t_first = _t_last = None
    _predict_ms = Histogram(capacity=4096)
    _lag_versions = Histogram(capacity=4096)
    _lag_ms = Histogram(capacity=4096)
