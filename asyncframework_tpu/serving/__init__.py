"""The serving tier: snapshot-subscribing predict replicas with
freshness-lag SLOs.

Training is half of "serve millions of users"; this package is the other
half -- the first READ path in the codebase.  :class:`ModelReplica`
subscribes to the ParameterServer's versioned snapshots over the existing
``net/`` plane (delta-mode ``have=`` pulls on a background refresh loop,
CRC-gated, full-pull fallback), holds the current model behind an atomic
swap, and answers PREDICT RPCs while training continues;
:class:`ServingFrontend` registers replicas (HELLO, the PR 2 membership
machinery in ``adopt=False`` mode) and round-robins client requests with
retry/circuit-breaker failover, so a SIGKILLed replica mid-load degrades
to a failover, never an outage.  Every reply carries its freshness lag
(PS clock minus served version, in versions and ms); replicas past the
``async.serve.max.staleness.ms`` SLO answer UNHEALTHY and the frontend
routes around them.

Knobs: ``async.serve.*`` (conf.py).  Entry point: ``bin/async-serve``
(``python -m asyncframework_tpu.serving.cli``).  Benchmark:
``bench.py --serve`` (QPS vs freshness lag, with training running and
with the chaos fabric killing a replica mid-load).
"""

from asyncframework_tpu.serving.frontend import PredictError, ServingFrontend
from asyncframework_tpu.serving.metrics import (
    reset_serving_totals,
    serving_snapshot,
    serving_totals,
)
from asyncframework_tpu.serving.replica import ModelReplica

__all__ = [
    "ModelReplica",
    "ServingFrontend",
    "PredictError",
    "serving_totals",
    "serving_snapshot",
    "reset_serving_totals",
]
