"""Blocking calls lexically inside ``with <lock>:`` -- the static twin
of ``net/lockwatch.py``.

The dynamic watchdog catches socket I/O under a *watched* lock at
runtime, on the paths a given run happens to execute.  This rule is its
lexical complement: it flags blocking calls written inside ANY
``with``-block whose context expression looks like a lock (identifier
containing ``lock``, or a ``cv``/``cond`` condition variable), on every
path, executed or not.  Code inside nested ``def``/``lambda`` bodies is
excluded (it runs later, outside the hold), and ``Condition.wait`` is
NOT flagged (it releases the lock while blocking -- that is its job).

Flagged callees:

- ``time.sleep``
- socket verbs: ``connect``/``accept``/``recv``/``recv_into``/
  ``recvmsg``/``sendall``/``sendmsg``
- the framing/RPC choke points: ``send_msg``/``recv_msg``/
  ``send_msg_vectored``/``recv_exact``/``_send_msg``/``_recv_msg``/
  ``_oneshot``/``_call``/``_call_raw``/``.call(...)`` (retry-policy and
  channel RPC)
- subprocess: ``communicate``, ``os.waitpid``, ``.wait()`` on a
  receiver named like a process (``proc``/``popen``/``child``)
- thread joins: ``.join()`` with no positional argument (``str.join``
  always has exactly one), or any ``.join`` on a receiver named like a
  thread

A true positive here is one slow peer stalling every thread that needs
the lock -- the exact convoy the PR 5 lock-free pull path removed.
"""

from __future__ import annotations

import ast
import re
from typing import List

from asyncframework_tpu.analysis.core import (
    Finding,
    LintContext,
    dotted_name,
    tail_name,
    walk_excluding_nested_defs,
)

_LOCK_NAME_RE = re.compile(r"(?:^|_)(?:lock|locks|cv|cond)\d*$|lock$",
                           re.IGNORECASE)

_SOCKET_VERBS = {"connect", "accept", "recv", "recv_into", "recvmsg",
                 "sendall", "sendmsg"}
_FRAME_VERBS = {"send_msg", "recv_msg", "send_msg_vectored", "recv_exact",
                "_send_msg", "_recv_msg", "_oneshot", "_call", "_call_raw",
                "call"}
_PROC_RE = re.compile(r"proc|popen|child", re.IGNORECASE)
_THREAD_RE = re.compile(r"thread|^_?t\d?$|^th$", re.IGNORECASE)


def _is_lock_expr(node: ast.AST) -> str:
    """The lock-ish identifier a with-item acquires, or ''."""
    name = tail_name(node)
    if name and _LOCK_NAME_RE.search(name):
        return name
    return ""


def _blocking_callee(call: ast.Call) -> str:
    """Why this call blocks, or '' if it does not match the catalog."""
    func = call.func
    dn = dotted_name(func)
    attr = tail_name(func)
    if dn in ("time.sleep", "sleep") or dn.endswith(".time.sleep"):
        return "time.sleep"
    if dn == "os.waitpid":
        return "os.waitpid"
    if attr in _SOCKET_VERBS and isinstance(func, ast.Attribute):
        return f"socket .{attr}()"
    if attr in _FRAME_VERBS:
        return f"{attr}() wire I/O"
    if attr == "communicate":
        return "subprocess .communicate()"
    if attr == "wait" and isinstance(func, ast.Attribute) and \
            _PROC_RE.search(tail_name(func.value) or ""):
        return "process .wait()"
    if attr == "join" and isinstance(func, ast.Attribute):
        recv = tail_name(func.value) or ""
        positional = [a for a in call.args]
        if not positional or _THREAD_RE.search(recv):
            return "thread .join()"
    return ""


def check(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for path, sf in ctx.files.items():
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.With):
                continue
            lock_names = [n for n in
                          (_is_lock_expr(item.context_expr)
                           for item in node.items) if n]
            if not lock_names:
                continue
            for sub in walk_excluding_nested_defs(node.body):
                if not isinstance(sub, ast.Call):
                    continue
                why = _blocking_callee(sub)
                if why:
                    # token carries the LOCK name too: an allowlist
                    # entry for one lock's documented contract must not
                    # suppress the same callee under a different lock
                    # in the same file
                    findings.append(Finding(
                        "lock-blocking-call", path, sub.lineno,
                        f"{lock_names[0]}:"
                        f"{tail_name(sub.func) or 'call'}",
                        f"{why} lexically inside "
                        f"`with {lock_names[0]}:` -- blocking under a "
                        f"held lock convoys every waiter "
                        f"(net/lockwatch.py is the dynamic twin of "
                        f"this rule)"))
    return findings
