"""``bin/async-lint`` entry point.

Exit status: 0 = clean (suppressions allowed, findings not), 1 = any
finding, 2 = usage/internal error.  ``--json`` emits the machine-readable
report (findings + suppressions with reasons) for CI annotation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="async-lint",
        description="Repo-invariant static analysis: conf-key "
                    "discipline, wire-protocol coverage "
                    "(net/protocol.py), blocking-calls-under-lock, "
                    "thread hygiene, counter-family registration.",
    )
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detected from this "
                        "file's location)")
    p.add_argument("--rule", action="append", default=None,
                   choices=["conf", "protocol", "locks", "threads",
                            "metrics"],
                   help="run only this rule group (repeatable; "
                        "default: all)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--no-allowlist", action="store_true",
                   help="show raw findings, ignoring "
                        "analysis/allowlist.py")
    p.add_argument("--list-allow", action="store_true",
                   help="print every suppression with its reason and "
                        "exit")
    return p


def _detect_root() -> str:
    # analysis/cli.py -> asyncframework_tpu/ -> repo root
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from asyncframework_tpu.analysis import core
    from asyncframework_tpu.analysis.allowlist import ALLOWLIST

    root = args.root or _detect_root()

    if args.list_allow:
        for a in ALLOWLIST:
            print(f"[{a.rule}] {a.path} :: {a.token}\n    reason: "
                  f"{a.reason}")
        print(f"{len(ALLOWLIST)} suppression(s)")
        return 0

    try:
        result = core.run_lint(
            root, rules=args.rule,
            allowlist=[] if args.no_allowlist else None)
    except ValueError as e:
        print(f"async-lint: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps(result.to_json(), indent=2, sort_keys=True))
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.format())
    tail = (f"async-lint: {len(result.findings)} finding(s), "
            f"{len(result.suppressed)} suppressed, "
            f"{result.files_scanned} files")
    print(tail if result.findings else f"async-lint: clean -- {tail}")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
