"""The repo's lint suppressions.  EVERY entry carries a reason string --
``run_lint`` refuses an empty one -- and ``bin/async-lint --list-allow``
renders this file, so the allowlist is itself documentation.  There is
no inline-pragma escape hatch: a suppression that is not visible here
does not exist.

Policy (ARCHITECTURE.md "Correctness tooling"): an entry is acceptable
only when the flagged code is (a) correct for a reason the rule's
heuristic cannot see, and (b) the reason is written down well enough
that a reviewer can re-check it when the code changes.  Prefer fixing
the code; the list shrinking over time is the healthy direction.
"""

from __future__ import annotations

from typing import Tuple

from asyncframework_tpu.analysis.core import Allow

ALLOWLIST: Tuple[Allow, ...] = (
    # ------------------------------------------------------------- locks
    # The lock rule exists for SERVER hot locks (the PS model lock class:
    # many threads convoy behind one holder's I/O).  The entries below
    # are client-side locks whose entire JOB is to serialize I/O on one
    # channel; the "convoy" is one known peer thread, by design.
    Allow(
        "lock-blocking-call", "asyncframework_tpu/parallel/ps_dcn.py",
        "_win_lock:connect",
        "pipelined push window (_win_lock): reconnect+replay must be "
        "atomic against push_start sends or replayed and fresh pushes "
        "interleave out of FIFO order and ACK pairing breaks; "
        "contention is exactly two threads (sender + reaper), the "
        "documented window contract",
    ),
    Allow(
        "lock-blocking-call", "asyncframework_tpu/parallel/shardgroup.py",
        "_restart_lock:wait",
        "shard restart path (_restart_lock): serializing "
        "kill->wait->respawn per controller is the point -- two "
        "monitors relaunching the same shard concurrently would "
        "double-spawn it; only the monitor thread ever takes this lock",
    ),
    Allow(
        "lock-blocking-call", "asyncframework_tpu/parallel/shardgroup.py",
        "_restart_lock:_oneshot",
        "shard restart path (_restart_lock): the post-relaunch SETMAP "
        "epoch fan-out must complete before another restart can "
        "re-plan the map; same single-monitor-thread lock as above",
    ),
    Allow(
        "lock-blocking-call", "asyncframework_tpu/streaming/log_net.py",
        "_lock:call",
        "RemoteLogTopic._call (client channel lock): one framed "
        "connection, one in-flight op -- the lock IS the channel's "
        "serialization contract for thread-safe producers; a convoy "
        "here is callers of the same client object taking turns, "
        "which is the documented semantics",
    ),
    # ---------------------------------------------------------- metrics
    Allow(
        "metrics-unregistered-totals",
        "asyncframework_tpu/metrics/registry.py", "all_totals",
        "the registry's own aggregator: it IS the walk over every "
        "registered family, registering it would recurse",
    ),
    Allow(
        "metrics-unregistered-totals",
        "asyncframework_tpu/net/retry.py", "retry_totals",
        "aggregated INTO the registered `net` family by net_totals() "
        "(same exemption as the PR 7 runtime audit): registering it "
        "separately would double-count every retry on /metrics",
    ),
)
