"""Conf-key discipline: every ``async.*`` read declared, every declared
knob read.

The PR 8 ``global_conf()`` footgun and the PR 5 thread-leak were both
silent-conf-drift bugs: a knob read that nothing declared (so nothing
documented, defaulted, or CLI-exposed it) or a declared knob that
nothing read (so operators tuned a no-op).  ~66 distinct conf keys are
now read across the tree; this rule pins them to ``conf.py``'s
ConfigEntry registry:

- ``conf-undeclared-read``: an ``"async.*"`` string literal used
  anywhere outside ``conf.py`` that is not a registered key;
- ``conf-dead-knob``: a registered key that is neither referenced by
  its entry constant (``conf.TRACE_SAMPLE``) nor by its key literal
  anywhere outside ``conf.py`` (tests do not count: a knob only tests
  read is dead in production);
- ``conf-field-map``: a ``CONF_TO_FIELD`` entry whose key is not
  registered or whose field is not a ``SolverConfig`` attribute;
- ``conf-env-alias``: an ``ASYNCTPU_ASYNC*`` env-var literal that does
  not round-trip to a registered key (the alias grammar is mechanical:
  ``ASYNCTPU_`` + key upper-cased, dots to underscores -- a typo'd env
  literal silently configures nothing);
- ``conf-tunable``: the adaptive-controller actuation surface
  (``parallel/controller.py``).  Every knob the controller actuates --
  a ``CONTROLLER_TUNABLES`` key or an ``_actuate("<key>", ...)``
  literal -- must be a registered ConfigEntry carrying ``tunable=True``
  WITH declared ``floor``/``ceiling`` bounds, and every declared
  tunable must carry both bounds.  Undeclaring a tunable (or actuating
  an undeclared key) therefore fails the lint -- a controller may only
  move knobs whose hard bounds an operator can read off conf.py.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set

from asyncframework_tpu.analysis.core import (
    Finding,
    LintContext,
    const_str,
    tail_name,
)

CONF_PATH = "asyncframework_tpu/conf.py"
CLI_PATH = "asyncframework_tpu/cli.py"
SOLVER_BASE_PATH = "asyncframework_tpu/solvers/base.py"
CONTROLLER_PATH = "asyncframework_tpu/parallel/controller.py"

# key segments are dot-separated and underscore-FREE: the ASYNCTPU_ env
# alias maps dots to underscores, so an underscore inside a segment
# would make the reverse mapping ambiguous -- the grammar forbids it and
# conf-key-grammar flags any declaration that violates it
_KEY_RE = re.compile(r"^async\.[a-z0-9]+(\.[a-z0-9]+)*$")
_ENV_RE = re.compile(r"^ASYNCTPU_ASYNC[A-Z0-9_]*$")


def declared_entries(ctx: LintContext) -> Dict[str, str]:
    """key -> entry constant name, parsed from conf.py's
    ``NAME = ConfigEntry("key", ...)`` assignments."""
    sf = ctx.get(CONF_PATH)
    out: Dict[str, str] = {}
    if sf is None:
        return out
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Assign) and
                isinstance(node.value, ast.Call) and
                tail_name(node.value.func) == "ConfigEntry" and
                node.value.args):
            continue
        key = const_str(node.value.args[0])
        if key is None:
            continue
        name = ""
        if node.targets and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
        out[key] = name
    return out


def declared_tunables(ctx: LintContext) -> Dict[str, "tuple[bool, bool, int]"]:
    """key -> (has_floor, has_ceiling, line) for every ConfigEntry
    declared with ``tunable=True`` (constant keyword) in conf.py."""
    sf = ctx.get(CONF_PATH)
    out: Dict[str, tuple] = {}
    if sf is None:
        return out
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call) and
                tail_name(node.func) == "ConfigEntry" and node.args):
            continue
        key = const_str(node.args[0])
        if key is None:
            continue
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        tn = kw.get("tunable")
        if not (isinstance(tn, ast.Constant) and tn.value is True):
            continue

        def has_bound(name):
            v = kw.get(name)
            return (isinstance(v, ast.Constant)
                    and isinstance(v.value, (int, float))
                    and not isinstance(v.value, bool))

        out[key] = (has_bound("floor"), has_bound("ceiling"), node.lineno)
    return out


def _actuated_keys(ctx: LintContext) -> List["tuple[str, int]"]:
    """(key, line) for every knob the controller actuates: the
    ``CONTROLLER_TUNABLES`` table's literal keys plus the first-arg
    string literal of every ``_actuate(...)`` call in controller.py."""
    sf = ctx.get(CONTROLLER_PATH)
    out: List[tuple] = []
    if sf is None:
        return out
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            target = (node.targets[0] if isinstance(node, ast.Assign)
                      and node.targets else getattr(node, "target", None))
            value = node.value
            if (target is not None and value is not None
                    and tail_name(target) == "CONTROLLER_TUNABLES"
                    and isinstance(value, ast.Dict)):
                for k in value.keys:
                    key = const_str(k)
                    if key is not None:
                        out.append((key, k.lineno))
        elif isinstance(node, ast.Call) and \
                tail_name(node.func) == "_actuate" and node.args:
            key = const_str(node.args[0])
            if key is not None:
                out.append((key, node.lineno))
    return out


def _conf_to_field(ctx: LintContext) -> Dict[str, "tuple[str, int]"]:
    """CONF_TO_FIELD key -> (field, line) from cli.py's dict literal."""
    sf = ctx.get(CLI_PATH)
    out: Dict[str, tuple] = {}
    if sf is None:
        return out
    for node in ast.walk(sf.tree):
        # both plain and ANNOTATED assignment: the real cli.py declares
        # `CONF_TO_FIELD: Dict[str, str] = {...}` (ast.AnnAssign)
        if isinstance(node, ast.Assign) and node.targets:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            target, value = node.target, node.value
        else:
            continue
        if not (tail_name(target) == "CONF_TO_FIELD" and
                isinstance(value, ast.Dict)):
            continue
        for k, v in zip(value.keys, value.values):
            key, fld = const_str(k), const_str(v)
            if key is not None and fld is not None:
                out[key] = (fld, k.lineno)
    return out


def _solver_fields(ctx: LintContext) -> Set[str]:
    """SolverConfig's declared attribute names (AnnAssign/Assign targets
    in the class body)."""
    sf = ctx.get(SOLVER_BASE_PATH)
    fields: Set[str] = set()
    if sf is None:
        return fields
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) and node.name == "SolverConfig":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name):
                    fields.add(stmt.target.id)
                elif isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            fields.add(t.id)
    return fields


def check(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    entries = declared_entries(ctx)
    declared_keys = set(entries)
    entry_names = {n for n in entries.values() if n}

    # every async.* literal read + every entry-constant reference,
    # anywhere outside conf.py
    read_keys: Set[str] = set()
    referenced_names: Set[str] = set()
    for path, sf in ctx.files.items():
        is_conf = path == CONF_PATH
        for node in ast.walk(sf.tree):
            s = const_str(node)
            if s is not None and _KEY_RE.match(s):
                if not is_conf:
                    read_keys.add(s)
                    if s not in declared_keys:
                        findings.append(Finding(
                            "conf-undeclared-read", path, node.lineno, s,
                            f"conf key {s!r} is read here but not "
                            f"declared in conf.py -- register a "
                            f"ConfigEntry (default + doc) or drop the "
                            f"read"))
                continue
            if is_conf:
                continue
            name = tail_name(node)
            if name in entry_names and isinstance(
                    node, (ast.Name, ast.Attribute)):
                referenced_names.add(name)

    # dead knobs: declared but neither key literal nor constant is
    # referenced anywhere in the linted tree outside conf.py
    conf_sf = ctx.get(CONF_PATH)
    decl_lines: Dict[str, int] = {}
    if conf_sf is not None:
        for node in ast.walk(conf_sf.tree):
            if (isinstance(node, ast.Call) and
                    tail_name(node.func) == "ConfigEntry" and node.args):
                key = const_str(node.args[0])
                if key is not None:
                    decl_lines[key] = node.lineno
    for key, name in sorted(entries.items()):
        if not _KEY_RE.match(key):
            findings.append(Finding(
                "conf-key-grammar", CONF_PATH, decl_lines.get(key, 0),
                key,
                f"declared key {key!r} violates the key grammar "
                f"(lowercase dot-separated segments, no underscores) "
                f"-- an underscore-bearing segment makes the "
                f"ASYNCTPU_ env-alias reverse mapping ambiguous"))
            continue
        if key in read_keys or (name and name in referenced_names):
            continue
        findings.append(Finding(
            "conf-dead-knob", CONF_PATH, decl_lines.get(key, 0), key,
            f"declared knob {key!r} ({name or 'unnamed'}) is never read "
            f"outside conf.py -- wire it up or delete the declaration"))

    # CONF_TO_FIELD consistency
    fields = _solver_fields(ctx)
    for key, (fld, line) in sorted(_conf_to_field(ctx).items()):
        if key not in declared_keys:
            findings.append(Finding(
                "conf-field-map", CLI_PATH, line, key,
                f"CONF_TO_FIELD maps unregistered key {key!r}"))
        if fields and fld not in fields:
            findings.append(Finding(
                "conf-field-map", CLI_PATH, line, key,
                f"CONF_TO_FIELD maps {key!r} to SolverConfig.{fld}, "
                f"which does not exist"))

    # tunable discipline: every declared tunable carries both bounds,
    # and the controller actuates ONLY declared tunables
    tunables = declared_tunables(ctx)
    for key, (has_floor, has_ceiling, line) in sorted(tunables.items()):
        if not (has_floor and has_ceiling):
            findings.append(Finding(
                "conf-tunable", CONF_PATH, line, key,
                f"tunable knob {key!r} must declare numeric floor AND "
                f"ceiling bounds (the controller clamps every decision "
                f"to them; a boundless tunable is unactuatable)"))
    for key, line in _actuated_keys(ctx):
        if key not in tunables:
            findings.append(Finding(
                "conf-tunable", CONTROLLER_PATH, line, key,
                f"controller actuates {key!r}, which is not declared "
                f"tunable=True in conf.py -- the controller may only "
                f"move declared tunables (add the marker + bounds or "
                f"drop the actuation)"))

    # env-alias grammar: ASYNCTPU_ASYNC* literals must round-trip
    for path, sf in ctx.files.items():
        for node in ast.walk(sf.tree):
            s = const_str(node)
            if s is None or not _ENV_RE.match(s):
                continue
            key = s[len("ASYNCTPU_"):].lower().replace("_", ".")
            if key not in declared_keys:
                findings.append(Finding(
                    "conf-env-alias", path, node.lineno, s,
                    f"env literal {s!r} does not alias any registered "
                    f"conf key (expected ASYNCTPU_<KEY_UPPER_WITH_"
                    f"UNDERSCORES> of a declared key; got back "
                    f"{key!r})"))
    return findings
