"""Wire-protocol coverage matrix against ``net/protocol.py``.

The table declares every op's obligations; this rule statically
cross-checks the planes' dispatch code against it, so a new op missing
its DedupWindow route or ``ep`` stamp is a lint failure, not a
chaos-suite lottery:

- ``proto-undeclared-op``: an uppercase op literal used in a protocol
  module (``{"op": "X"}`` construction, ``op == "X"`` dispatch,
  mutating-set membership, fault-preset pattern) that has no table row;
- ``proto-unhandled-op``: a table op whose declared server module has
  no dispatch branch for it (SERVER_DISPATCH coverage);
- ``proto-dedup-gate``: a dedup-gated op whose ps_dcn dispatch branch
  does not route through the DedupWindow, or a server module whose
  ``_MUTATING_OPS`` is hand-rolled instead of derived from
  ``protocol.dedup_gated_ops(...)`` (the drift that re-opens the
  round-5 duplicate-APPEND bug);
- ``proto-fence-gate``: a fence-stamped op whose ps_dcn dispatch branch
  never calls ``_fence_reject`` (server side), or a PS client module
  that no longer stamps ``ep`` anywhere (client side);
- ``proto-fault-target``: a non-test fault-schedule preset targeting an
  op the table does not mark fault-schedulable.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from asyncframework_tpu.analysis.core import (
    Finding,
    LintContext,
    SourceFile,
    const_str,
    tail_name,
)
from asyncframework_tpu.net import protocol

_OP_RE = re.compile(r"^[A-Z][A-Z_]+$")
PS_DCN_PATH = "asyncframework_tpu/parallel/ps_dcn.py"
FAULTS_PATH = "asyncframework_tpu/net/faults.py"

#: server modules that owe fence-stamped ops a ``_fence_reject``
#: admission call in their dispatch branches (the PS plane and the
#: relaycast plane -- the two places a zombie incarnation could serve
#: or mutate state it no longer owns)
FENCE_SERVER_PATHS = (
    PS_DCN_PATH,
    "asyncframework_tpu/relaycast/node.py",
)

#: the client-side fencing stamp choke points, path -> stamping function:
#: every PS-plane client op header flows through PSClient._proc_hdr (the
#: sharded facade and serving replicas ride PSClient sub-clients, so
#: there is exactly one), and every relay hop through
#: RelaySource._stamped.  The rule requires the ``["ep"]`` assignment
#: INSIDE the named function -- an ``ep`` write elsewhere (a server
#: advertising its epoch on replies) must not satisfy the client-stamp
#: obligation.
FENCE_CLIENT_STAMPS = {
    PS_DCN_PATH: "_proc_hdr",
    "asyncframework_tpu/relaycast/source.py": "_stamped",
    # the replication stream's choke point: every REPL_SYNC/REPL_APPEND
    # frame carries the primary's current epoch, so a deposed
    # incarnation's appends are exactly the stale-stamp shape the
    # standby's admission rejects
    "asyncframework_tpu/parallel/replication.py": "_stamped",
}
# legacy aliases (kept: the acceptance tests and docs name them)
FENCE_CLIENT_PATHS = tuple(FENCE_CLIENT_STAMPS)
FENCE_STAMP_FN = FENCE_CLIENT_STAMPS[PS_DCN_PATH]


def _is_op_compare(node: ast.Compare) -> bool:
    """``op == "X"`` / ``op in (...)`` where the left side is an ``op``
    variable or a ``.get("op")`` call -- the dispatch shapes the planes
    use."""
    left = node.left
    if isinstance(left, (ast.Name, ast.Attribute)) and \
            tail_name(left) == "op":
        return True
    if isinstance(left, ast.Call) and tail_name(left.func) == "get" and \
            left.args and const_str(left.args[0]) == "op":
        return True
    return False


def _compare_ops(node: ast.Compare) -> Iterable[Tuple[str, int]]:
    for comp in node.comparators:
        s = const_str(comp)
        if s is not None:
            yield s, comp.lineno
        elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
            for elt in comp.elts:
                s = const_str(elt)
                if s is not None:
                    yield s, elt.lineno


def _op_literals(sf: SourceFile) -> List[Tuple[str, int, str]]:
    """(op, line, context) for every op literal in one protocol module.

    Contexts: 'construct' ({"op": X} headers), 'dispatch' (op == X),
    'mutset' (_MUTATING_OPS membership), 'fault' (fault-preset
    patterns, split on '|')."""
    out: List[Tuple[str, int, str]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if const_str(k) == "op":
                    s = const_str(v)
                    if s is not None:
                        out.append((s, v.lineno, "construct"))
        elif isinstance(node, ast.Compare) and _is_op_compare(node):
            for s, line in _compare_ops(node):
                out.append((s, line, "dispatch"))
        elif isinstance(node, ast.Assign) and node.targets and \
                tail_name(node.targets[0]) == "_MUTATING_OPS":
            for sub in ast.walk(node.value):
                s = const_str(sub)
                if s is not None:
                    out.append((s, sub.lineno, "mutset"))
    if sf.relpath == FAULTS_PATH:
        # preset op patterns: alternations ("PUSH|PUSH_SAGA") anywhere,
        # plus the op argument of schedule.add()/add_delay() calls --
        # bare all-caps strings elsewhere in faults.py (env-var names,
        # fault-kind constants) are not op patterns
        for node in ast.walk(sf.tree):
            s = const_str(node)
            if s is not None and "|" in s:
                parts = s.split("|")
                if all(_OP_RE.match(p) for p in parts):
                    for p in parts:
                        out.append((p, node.lineno, "fault"))
            elif isinstance(node, ast.Call) and \
                    tail_name(node.func) in ("add", "add_delay") and \
                    len(node.args) >= 2:
                s = const_str(node.args[1])
                if s is not None and s != "*" and _OP_RE.match(s):
                    out.append((s, node.args[1].lineno, "fault"))
    return out


def _dispatch_branches(sf: SourceFile, op: str) -> List[ast.If]:
    """Every ``if``/``elif`` whose test compares the op variable against
    ``op`` (a file can dispatch the same verb in more than one place --
    server loop and windowed-client reply reaping, say)."""
    out: List[ast.If] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.If):
            continue
        for sub in ast.walk(node.test):
            if isinstance(sub, ast.Compare) and _is_op_compare(sub) and \
                    any(s == op for s, _ in _compare_ops(sub)):
                out.append(node)
                break
    return out


def _branch_scope(branch: ast.If) -> Iterable[ast.AST]:
    """The test + taken-body of a dispatch branch (not the elif chain)."""
    yield from ast.walk(branch.test)
    for stmt in branch.body:
        yield from ast.walk(stmt)


def _file_has_dispatch(sf: SourceFile, op: str) -> bool:
    if _dispatch_branches(sf, op):
        return True
    # master-style tables: membership in a set the dispatch consults
    for s, _line, kind in _op_literals(sf):
        if s == op and kind == "mutset":
            return True
    return False


def _mutset_derived(sf: SourceFile) -> Optional[bool]:
    """None = module has no ``_MUTATING_OPS``; else whether it derives
    from ``protocol.dedup_gated_ops(...)``."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and node.targets and \
                tail_name(node.targets[0]) == "_MUTATING_OPS":
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Call) and \
                        tail_name(sub.func) == "dedup_gated_ops":
                    return True
            return False
    return None


def check(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    declared = protocol.table()

    # 1. every op literal in a protocol module is declared
    seen_ops: Dict[str, Set[str]] = {}
    for path in protocol.PROTOCOL_MODULES:
        sf = ctx.get(path)
        if sf is None:
            continue
        for op, line, kind in _op_literals(sf):
            if not _OP_RE.match(op):
                continue
            seen_ops.setdefault(op, set()).add(path)
            if op not in declared:
                findings.append(Finding(
                    "proto-undeclared-op", path, line, op,
                    f"wire op {op!r} ({kind}) has no row in "
                    f"net/protocol.py -- declare it (mutating? "
                    f"dedup-gated? fence-stamped? fault-schedulable?) "
                    f"before shipping it"))

    # 2. server coverage matrix
    for op, servers in sorted(protocol.SERVER_DISPATCH.items()):
        for path in servers:
            sf = ctx.get(path)
            if sf is None or _file_has_dispatch(sf, op):
                continue
            findings.append(Finding(
                "proto-unhandled-op", path, 1, op,
                f"net/protocol.py declares {op!r} served by this "
                f"module, but no dispatch branch handles it"))

    # 3. dedup gating
    ps = ctx.get(PS_DCN_PATH)
    for op in sorted(protocol.dedup_gated_ops(protocol.PS)):
        if ps is None:
            break
        branches = _dispatch_branches(ps, op)
        if not branches:
            continue  # already a proto-unhandled-op finding
        gated = any(
            isinstance(n, ast.Attribute) and n.attr == "check"
            and "dedup" in tail_name(n.value).lower()
            for branch in branches for n in _branch_scope(branch))
        if not gated:
            findings.append(Finding(
                "proto-dedup-gate", PS_DCN_PATH, branches[0].lineno, op,
                f"dispatch branch for dedup-gated op {op!r} does not "
                f"consult the DedupWindow (net/session.py) -- a retried "
                f"{op} after a lost reply double-applies"))
    for plane, path in ((protocol.MASTER,
                         "asyncframework_tpu/deploy/master.py"),
                        (protocol.TOPIC,
                         "asyncframework_tpu/streaming/log_net.py")):
        sf = ctx.get(path)
        if sf is None:
            continue
        derived = _mutset_derived(sf)
        if derived is None:
            findings.append(Finding(
                "proto-dedup-gate", path, 1, plane,
                f"module serves dedup-gated {plane!r} ops but declares "
                f"no _MUTATING_OPS set"))
        elif not derived:
            findings.append(Finding(
                "proto-dedup-gate", path, 1, plane,
                f"_MUTATING_OPS is hand-rolled -- derive it from "
                f"protocol.dedup_gated_ops({plane!r}) so the table "
                f"stays the single source of truth"))

    # 4. fencing: server-side admission per branch, client-side stamp
    for path in FENCE_SERVER_PATHS:
        sf = ctx.get(path)
        if sf is None:
            continue
        for op in sorted(protocol.fence_stamped_ops()):
            branches = _dispatch_branches(sf, op)
            if not branches:
                continue
            fenced = any(
                isinstance(n, (ast.Attribute, ast.Name))
                and tail_name(n) == "_fence_reject"
                for branch in branches for n in _branch_scope(branch))
            if not fenced:
                findings.append(Finding(
                    "proto-fence-gate", path, branches[0].lineno, op,
                    f"dispatch branch for fence-stamped op {op!r} never "
                    f"calls _fence_reject -- a zombie incarnation would "
                    f"serve/apply it (async.fence.enabled)"))
    if protocol.fence_stamped_ops():
        for path, stamp_fn in FENCE_CLIENT_STAMPS.items():
            sf = ctx.get(path)
            if sf is None:
                continue
            stamps = any(
                isinstance(fn, ast.FunctionDef)
                and fn.name == stamp_fn
                and any(
                    isinstance(node, ast.Assign) and node.targets and
                    isinstance(node.targets[0], ast.Subscript) and
                    const_str(node.targets[0].slice) == "ep"
                    for node in ast.walk(fn))
                for fn in ast.walk(sf.tree))
            if not stamps:
                findings.append(Finding(
                    "proto-fence-gate", path, 1, "ep-stamp",
                    f"net/protocol.py declares fence-stamped ops but "
                    f"the client stamp choke point "
                    f"{stamp_fn}() no longer assigns the 'ep' "
                    f"header"))

    # 5. fault presets may only target schedulable ops
    faults_sf = ctx.get(FAULTS_PATH)
    if faults_sf is not None:
        schedulable = protocol.fault_schedulable_ops()
        for op, line, kind in _op_literals(faults_sf):
            if kind == "fault" and op not in schedulable:
                findings.append(Finding(
                    "proto-fault-target", FAULTS_PATH, line, op,
                    f"fault preset targets {op!r}, which "
                    f"net/protocol.py does not mark fault-schedulable"))
    return findings
