"""Native-oracle discipline: every ctypes entry point keeps a Python twin.

The native data plane (PR 19: ``native/*.cc`` via ctypes) is an
*optimization*, never a capability: every native symbol a module
configures must have a registered pure-Python oracle in that module's
``NATIVE_ORACLES`` table, the oracle must exist, and every dispatch site
that calls into the shared library must keep its guarded fallback
wired.  The property suites (``tests/test_wire_native.py``) prove the
two implementations bit-identical at runtime, but only for the pairs
they know about; this rule is the static registry that keeps the pair
set complete as entry points are added -- a native symbol without a twin
is a box that silently changes behavior when the toolchain disappears.

``NATIVE_ORACLES`` values come in two shapes, matching the two fallback
idioms in the tree:

- ``"_py_fn"`` -- a module-level function: the dispatch function that
  calls ``lib.<sym>`` must also (on its guarded branch) call a declared
  oracle function, in the SAME function body.  Deleting the fallback
  branch fires ``native-fallback-missing``.
- ``"_PyBackend.method"`` -- a class-shaped twin (``storage/kvstore.py``
  style, where backend selection happens once at construction): the
  class and method must exist, and the class must be instantiated
  somewhere in the module (the backend-selection fallback site).

Directions checked:

- ``native-oracle-missing``: a configured ctypes symbol
  (``lib.<sym>.restype = ...``) with no ``NATIVE_ORACLES`` entry;
- ``native-oracle-undefined``: an entry whose oracle does not exist at
  module level (a rename that silently orphaned the twin);
- ``native-oracle-stale``: an entry whose native symbol is no longer
  configured anywhere in the module (drift the other way);
- ``native-fallback-missing``: a dispatch function calling a native
  symbol with no oracle call in its body (function-shaped oracles), or
  a class-shaped twin that is never instantiated.

Scope: modules that call ``native_build.ensure_built`` -- the one
gateway to the shared libraries (loading a ``.so`` any other way is
already unidiomatic here).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from asyncframework_tpu.analysis.core import (
    Finding,
    LintContext,
    SourceFile,
    const_str,
    tail_name,
    walk_excluding_nested_defs,
)

ORACLE_TABLE = "NATIVE_ORACLES"


def _calls_ensure_built(sf: SourceFile) -> bool:
    return any(
        isinstance(n, ast.Call) and tail_name(n.func) == "ensure_built"
        for n in ast.walk(sf.tree))


def _oracle_table(sf: SourceFile) -> Tuple[Optional[Dict[str, str]], int]:
    """The module-level ``NATIVE_ORACLES`` dict literal (native symbol ->
    oracle name) + its line; (None, 0) when the module declares none."""
    for node in sf.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == ORACLE_TABLE
                   for t in node.targets):
            continue
        if not isinstance(node.value, ast.Dict):
            return None, node.lineno
        table: Dict[str, str] = {}
        for k, v in zip(node.value.keys, node.value.values):
            ks, vs = const_str(k), const_str(v)
            if ks is not None and vs is not None:
                table[ks] = vs
        return table, node.lineno
    return None, 0


def _configured_symbols(sf: SourceFile) -> Dict[str, int]:
    """Native symbols this module configures: every
    ``<handle>.<sym>.restype = ...`` assignment (the ctypes idiom makes
    restype configuration the one unskippable step)."""
    out: Dict[str, int] = {}
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute) and t.attr == "restype"
                    and isinstance(t.value, ast.Attribute)):
                out.setdefault(t.value.attr, node.lineno)
    return out


def _loader_names(sf: SourceFile) -> Set[str]:
    """Functions that hold restype/argtypes configuration -- the loaders
    themselves, exempt from the fallback-call check."""
    out: Set[str] = set()
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for n in walk_excluding_nested_defs(fn.body):
            if (isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Attribute)
                    and t.attr in ("restype", "argtypes")
                    for t in n.targets)):
                out.add(fn.name)
                break
    return out


def _module_defs(sf: SourceFile) -> Tuple[Set[str], Dict[str, Set[str]]]:
    """(module-level function/class names, class name -> method names)."""
    funcs: Set[str] = set()
    classes: Dict[str, Set[str]] = {}
    for node in sf.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.add(node.name)
        elif isinstance(node, ast.ClassDef):
            classes[node.name] = {
                m.name for m in node.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
    return funcs, classes


def _check_module(path: str, sf: SourceFile) -> List[Finding]:
    configured = _configured_symbols(sf)
    if not configured:
        return []
    findings: List[Finding] = []
    table, table_line = _oracle_table(sf)
    if table is None:
        line = min(configured.values())
        findings.append(Finding(
            "native-oracle-missing", path, line, ORACLE_TABLE,
            f"module configures native symbols "
            f"{sorted(configured)} but declares no {ORACLE_TABLE} "
            f"table -- every ctypes entry point needs a registered "
            f"pure-Python twin"))
        return findings

    funcs, classes = _module_defs(sf)

    # direction 1: configured symbol with no oracle entry
    for sym in sorted(set(configured) - set(table)):
        findings.append(Finding(
            "native-oracle-missing", path, configured[sym], sym,
            f"native symbol {sym!r} is configured but has no "
            f"{ORACLE_TABLE} entry -- register its pure-Python twin "
            f"(the bit-identity property suite keys off this table)"))

    # direction 2: oracle entries must resolve; collect the fallback
    # name sets the call-site check accepts
    plain_oracles: Set[str] = set()
    twin_classes: Set[str] = set()
    for sym, oracle in sorted(table.items()):
        if sym not in configured:
            findings.append(Finding(
                "native-oracle-stale", path, table_line, sym,
                f"{ORACLE_TABLE} entry {sym!r} names a native symbol "
                f"this module no longer configures -- drop or fix the "
                f"entry"))
            continue
        if "." in oracle:
            cls, _, meth = oracle.partition(".")
            if cls not in classes or meth not in classes[cls]:
                findings.append(Finding(
                    "native-oracle-undefined", path, table_line, sym,
                    f"oracle {oracle!r} for native symbol {sym!r} does "
                    f"not exist (no module-level class {cls!r} with "
                    f"method {meth!r})"))
                continue
            twin_classes.add(cls)
        else:
            if oracle not in funcs:
                findings.append(Finding(
                    "native-oracle-undefined", path, table_line, sym,
                    f"oracle {oracle!r} for native symbol {sym!r} is "
                    f"not a module-level function"))
                continue
            plain_oracles.add(oracle)

    # direction 3a: class-shaped twins must actually be constructed
    # somewhere (the backend-selection fallback site)
    instantiated = {
        tail_name(n.func) for n in ast.walk(sf.tree)
        if isinstance(n, ast.Call)}
    for cls in sorted(twin_classes):
        if cls not in instantiated:
            findings.append(Finding(
                "native-fallback-missing", path, table_line, cls,
                f"class-shaped twin {cls!r} is declared in "
                f"{ORACLE_TABLE} but never instantiated -- the "
                f"backend-selection fallback site is gone"))

    # direction 3b: every dispatch function calling a native symbol with
    # a function-shaped oracle must keep a guarded oracle call in its
    # own body (the degrade path)
    loaders = _loader_names(sf)
    plain_syms = {s for s in configured
                  if s in table and "." not in table[s]}
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name in loaders or fn.name in plain_oracles:
            continue
        native_called: Dict[str, int] = {}
        oracle_called = False
        for n in walk_excluding_nested_defs(fn.body):
            if not isinstance(n, ast.Call):
                continue
            callee = tail_name(n.func)
            if callee in plain_syms and isinstance(n.func, ast.Attribute):
                native_called.setdefault(callee, n.lineno)
            elif callee in plain_oracles:
                oracle_called = True
        if native_called and not oracle_called:
            for sym, line in sorted(native_called.items()):
                findings.append(Finding(
                    "native-fallback-missing", path, line, sym,
                    f"function {fn.name!r} calls native symbol {sym!r} "
                    f"but no declared oracle -- the pure-Python "
                    f"fallback branch is missing (toolchain-absent "
                    f"boxes would lose this code path)"))
    return findings


def check(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for path, sf in sorted(ctx.files.items()):
        if path == "asyncframework_tpu/native_build.py":
            continue  # the build gateway itself, not a dispatch module
        if not _calls_ensure_built(sf):
            continue
        findings.extend(_check_module(path, sf))
    return findings
