"""Counter-family registration: the static equivalent of the PR 7
runtime audit (``tests/test_telemetry.py``'s pkgutil walk).

``metrics/registry.py`` is the ONE list of counter families; four
consumers iterate it (``reset_totals``, live-UI baselines, the sampler,
/metrics).  The runtime audit only fires when the telemetry suite runs
and only sees modules that import cleanly in that environment; this rule
fires on every lint of every tree state:

- ``metrics-unregistered-totals``: a public module-level ``*_totals``
  function in the package that no ``CounterFamily`` row references --
  the "second run inherits counts" bug waiting to happen;
- ``metrics-dangling-family``: a registry row whose (module, attr)
  provider does not exist in the tree (a rename that silently emptied a
  dashboard section);
- ``metrics-series-family``: a time-series key written anywhere (a
  ``register_source`` family, a ``record_flat`` prefix, a dotted
  ``record`` literal) must parse as ``family.metric`` with the family
  declared in ``metrics/registry.py`` (counter family or
  ``DYNAMIC_SERIES_FAMILIES``).  An undeclared family is a series the
  SLO grammar, the per-run dashboards, and the cluster observer's
  scrape surface all silently cannot see.
- ``prof-zone``: the same discipline for the continuous-profiling
  plane's zone table (``metrics/profiler.py`` ``ZONES``): every zone
  literal an accumulator or classifier uses (``zone(...)``,
  ``zone_ns(...)``, ``zoned(...)``, ``wrap_dispatch(fn, zone)``, a
  ``_zrule(...)`` classifier row) must be declared there, and every
  declared zone must be attributed by at least one such site -- an
  undeclared literal is a zone no table/flamegraph/diff will ever
  show; an unattributed declaration is a dashboard row that can never
  light up.

Aggregator functions that roll other families up (``registry.all_totals``
itself, ``net/retry.retry_totals`` inside ``net_totals``) are suppressed
in ``analysis/allowlist.py`` with their reasons -- the same exemptions
the runtime audit documents.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from asyncframework_tpu.analysis.core import (
    Finding,
    LintContext,
    const_str,
    tail_name,
)

PKG_PREFIX = "asyncframework_tpu/"

#: the series-key grammar the sampler's ``<family>.<key>`` naming
#: produces: only literals shaped like this are series keys (other
#: ``.record(...)`` APIs -- dedup windows, calibrators -- take dicts or
#: numbers and never match)
_SERIES_KEY_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[A-Za-z0-9_.]+)+$")
_FAMILY_RE = re.compile(r"^[a-z][a-z0-9_]*$")


def _module_name(path: str) -> str:
    mod = path[:-3] if path.endswith(".py") else path
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _registered(ctx: LintContext) -> Set[Tuple[str, str]]:
    """(module, attr) provider pairs from metrics/registry.py."""
    from asyncframework_tpu.metrics import registry

    out: Set[Tuple[str, str]] = set()
    for fam in registry.families().values():
        out.add((fam.module, fam.totals_attr))
        out.add((fam.module, fam.reset_attr))
    return out


def _declared_series_families() -> Set[str]:
    from asyncframework_tpu.metrics import registry

    return set(registry.series_families())


def _check_series_keys(ctx: LintContext) -> List[Finding]:
    """metrics-series-family: every literal series key written anywhere
    must carry a declared family."""
    declared = _declared_series_families()
    findings: List[Finding] = []
    for path, sf in ctx.files.items():
        if path == "asyncframework_tpu/metrics/registry.py":
            continue  # the declaration table itself
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            callee = tail_name(node.func)
            lit = const_str(node.args[0])
            if lit is None:
                continue
            family = None
            if callee == "register_source":
                if _FAMILY_RE.match(lit):
                    family = lit
            elif callee == "record_flat":
                if _FAMILY_RE.match(lit):
                    family = lit
            elif callee == "record":
                if _SERIES_KEY_RE.match(lit):
                    family = lit.split(".", 1)[0]
            if family is None or family in declared:
                continue
            findings.append(Finding(
                "metrics-series-family", path, node.lineno, family,
                f"series key {lit!r} writes undeclared family "
                f"{family!r} -- declare it in metrics/registry.py (a "
                f"CounterFamily or DYNAMIC_SERIES_FAMILIES) so the SLO "
                f"grammar, dashboards, and the cluster observer can "
                f"see it"))
    return findings


PROF_PATH = PKG_PREFIX + "metrics/profiler.py"

#: callee tail -> index of the positional arg holding the zone literal
_ZONE_CALLS = {"zone": 0, "zone_ns": 0, "zoned": 0, "wrap_dispatch": 1}


def _declared_zones(ctx: LintContext) -> Tuple[Set[str], int]:
    """The ``ZONES`` tuple from metrics/profiler.py's AST (static, like
    every other declaration-table read here) + its line number."""
    sf = ctx.get(PROF_PATH)
    if sf is None:
        return set(), 0
    for node in sf.tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == "ZONES"
                   for t in targets):
            continue
        value = node.value
        if isinstance(value, (ast.Tuple, ast.List)):
            zones = {z for z in (const_str(e) for e in value.elts)
                     if z is not None}
            return zones, node.lineno
    return set(), 0


def _check_prof_zones(ctx: LintContext) -> List[Finding]:
    """prof-zone, both directions: undeclared literal at an attribution
    site / declared zone with no attribution site anywhere."""
    declared, zones_line = _declared_zones(ctx)
    if not declared:
        return []  # no zone table in this tree (fixture snippets)
    findings: List[Finding] = []
    attributed: Set[str] = set()
    for path, sf in ctx.files.items():
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = tail_name(node.func)
            if callee in _ZONE_CALLS:
                idx = _ZONE_CALLS[callee]
                if len(node.args) <= idx:
                    continue
                lit = const_str(node.args[idx])
            elif callee == "_zrule":
                lit = const_str(node.args[-1]) if node.args else None
            else:
                continue
            if lit is None:
                continue
            if callee in ("zone", "zone_ns") and "." not in lit \
                    and lit not in declared:
                # ``zone()`` is a common name; a dotless literal that is
                # not a declared zone is some other API's first arg
                # (e.g. a k8s zone selector), not a profiler site
                continue
            if lit not in declared:
                findings.append(Finding(
                    "prof-zone", path, node.lineno, lit,
                    f"zone literal {lit!r} at a profiler attribution "
                    f"site ({callee}) is not declared in the ZONES "
                    f"table ({PROF_PATH}) -- no table, flamegraph, or "
                    f"diff will ever show it"))
            else:
                attributed.add(lit)
    for z in sorted(declared - attributed):
        findings.append(Finding(
            "prof-zone", PROF_PATH, zones_line, z,
            f"declared zone {z!r} has no attribution site (zone/"
            f"zone_ns/zoned/wrap_dispatch/_zrule) anywhere in the "
            f"tree -- a dashboard row that can never light up"))
    return findings


def check(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = _check_series_keys(ctx)
    findings.extend(_check_prof_zones(ctx))
    registered = _registered(ctx)

    providers: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for path, sf in ctx.files.items():
        if not path.startswith(PKG_PREFIX):
            continue
        mod = _module_name(path)
        for node in sf.tree.body:  # module level only, like the pkgutil walk
            if isinstance(node, ast.FunctionDef) and \
                    node.name.endswith("_totals") and \
                    not node.name.startswith(("_", "reset")):
                providers[(mod, node.name)] = (path, node.lineno)

    for (mod, attr), (path, line) in sorted(providers.items()):
        if (mod, attr) in registered:
            continue
        # a provider re-exported via a package __init__ may be registered
        # under the package name (net_totals lives in net/__init__.py)
        if any(rm.startswith(mod) or mod.startswith(rm)
               for rm, ra in registered if ra == attr):
            continue
        findings.append(Finding(
            "metrics-unregistered-totals", path, line, attr,
            f"public counter provider {mod}.{attr} is not referenced by "
            f"any CounterFamily in metrics/registry.py -- register it "
            f"(wires reset_totals, live-UI baselines, the sampler and "
            f"/metrics) or rename it private"))

    # dangling registry rows: provider module/attr must exist in-tree
    known_paths = set(ctx.files)
    for (mod, attr) in sorted(registered):
        relpath = mod.replace(".", "/")
        candidates = (relpath + ".py", relpath + "/__init__.py")
        sf = next((ctx.files[c] for c in candidates if c in known_paths),
                  None)
        if sf is None:
            continue  # outside lint scope
        present = any(
            (isinstance(n, ast.FunctionDef) and n.name == attr) or
            (isinstance(n, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == attr
                for t in n.targets)) or
            (isinstance(n, (ast.Import, ast.ImportFrom)) and any(
                (a.asname or a.name) == attr for a in n.names))
            for n in sf.tree.body)
        if not present:
            findings.append(Finding(
                "metrics-dangling-family", "asyncframework_tpu/metrics/"
                "registry.py", 1, f"{mod}.{attr}",
                f"registry references provider {mod}.{attr}, which does "
                f"not exist at module level in {sf.relpath}"))
    return findings
