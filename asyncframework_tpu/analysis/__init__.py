"""Repo-invariant static analysis (``bin/async-lint``).

Nine PRs of engine growth accumulated load-bearing invariants that were
enforced only at runtime (``net/lockwatch.py``, the PR 7 registration
audit) or not at all: every mutating wire op rides the exactly-once
dedup window, fence-stamped ops carry ``ep``, no socket I/O under the
model lock, every ``threading.Thread`` is named/daemon-explicit/guarded,
every counter family is registered, every ``async.*`` knob is declared.
This package makes them *build-time* invariants: an AST pass with
repo-specific rules, wired into tier-1 (``tests/test_analysis.py``) so
the whole tree must self-lint clean.

Rules (see ``analysis/rules_*.py`` and the ARCHITECTURE.md
"Correctness tooling" catalog):

- ``conf-*``     -- conf-key discipline against ``conf.py``'s registry
- ``proto-*``    -- wire-protocol coverage matrix against
  ``net/protocol.py``
- ``lock-*``     -- blocking calls lexically under a lock (the static
  twin of the dynamic ``net/lockwatch.py`` watchdog)
- ``thread-*``   -- thread hygiene at every ``threading.Thread(...)``
  site
- ``metrics-*``  -- counter-family registration against
  ``metrics/registry.py``

Suppressions live ONLY in ``analysis/allowlist.py`` and every entry
carries a reason string; there is no inline-pragma escape hatch.
"""

from asyncframework_tpu.analysis.core import (  # noqa: F401
    Finding,
    LintContext,
    run_lint,
)
