"""Lint core: source-tree walk, AST cache, findings, allowlist, runner.

The analyzer is *static*: it parses the tree with ``ast`` and never
imports the modules it checks (so a lint run cannot trigger jax
initialization, socket binds, or conf mutation).  The only modules it
imports are the three declaration tables the rules cross-check against
-- ``conf.py``, ``net/protocol.py``, ``metrics/registry.py`` -- all of
which are dependency-light by contract (their docstrings say so; the
lint would be the first thing to break if that regressed).
"""

from __future__ import annotations

import ast
import fnmatch
import os
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple


@dataclass(frozen=True)
class Finding:
    """One lint violation.

    ``token`` is the stable detail key allowlist entries match against
    (a conf key, an op name, a lock name, a callee) -- line numbers
    drift, tokens do not."""

    rule: str
    path: str      # repo-relative, forward slashes
    line: int
    token: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_json(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "token": self.token, "message": self.message}


@dataclass(frozen=True)
class Allow:
    """One suppression: rule + path glob + token (exact or ``*``) and a
    MANDATORY human reason.  Reasons are rendered by ``--list-allow`` and
    the ARCHITECTURE.md catalog; an empty reason fails the lint run
    itself."""

    rule: str
    path: str
    token: str
    reason: str

    def matches(self, f: Finding) -> bool:
        return (self.rule == f.rule
                and fnmatch.fnmatch(f.path, self.path)
                and (self.token == "*" or self.token == f.token))


class SourceFile:
    """One parsed file: source text, AST, and a parent map (ast has no
    parent links; the thread rule needs them to see how a Thread(...)
    call is used)."""

    def __init__(self, root: str, relpath: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.abspath = os.path.join(root, relpath)
        with open(self.abspath, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.tree = ast.parse(self.source, filename=self.relpath)
        self._parents: Optional[Dict[int, ast.AST]] = None

    def parents(self) -> Dict[int, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[id(child)] = node
        return self._parents

    def parent_of(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents().get(id(node))


#: directories under the repo root that are linted (tests/ hosts the
#: deliberately-bad rule fixtures, so it is out of scope by design;
#: examples/ are user-facing scripts linted for conf/thread hygiene too)
LINT_DIRS = ("asyncframework_tpu", "bin", "examples")
LINT_FILES = ("bench.py",)
_SKIP_DIRS = {"__pycache__", ".git", "native"}


def iter_lint_paths(root: str) -> Iterable[str]:
    """Repo-relative paths of every linted source file.  ``bin/`` holds
    extensionless Python launchers -- anything parseable is in scope."""
    for base in LINT_DIRS:
        basedir = os.path.join(root, base)
        if not os.path.isdir(basedir):
            continue
        for dirpath, dirnames, filenames in os.walk(basedir):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                if fn.endswith(".py"):
                    yield rel
                elif base == "bin" and "." not in fn:
                    with open(os.path.join(root, rel), "rb") as f:
                        head = f.read(64)
                    if b"python" in head.split(b"\n", 1)[0]:
                        yield rel
    for fn in LINT_FILES:
        if os.path.isfile(os.path.join(root, fn)):
            yield fn


class LintContext:
    """Shared state for one lint run: parsed files + declaration tables."""

    def __init__(self, root: str, paths: Optional[List[str]] = None):
        self.root = os.path.abspath(root)
        self.files: Dict[str, SourceFile] = {}
        self.parse_errors: List[Finding] = []
        for rel in (paths if paths is not None
                    else iter_lint_paths(self.root)):
            try:
                sf = SourceFile(self.root, rel)
            except (SyntaxError, UnicodeDecodeError) as e:
                self.parse_errors.append(Finding(
                    "parse-error", rel.replace(os.sep, "/"),
                    getattr(e, "lineno", 0) or 0, "syntax",
                    f"cannot parse: {e}"))
                continue
            self.files[sf.relpath] = sf

    def get(self, relpath: str) -> Optional[SourceFile]:
        return self.files.get(relpath)


RuleFn = Callable[[LintContext], List[Finding]]


def _rules() -> Dict[str, RuleFn]:
    # imported lazily so `analysis.core` stays importable from fixtures
    # that construct a LintContext over a single snippet
    from asyncframework_tpu.analysis import (
        rules_conf,
        rules_locks,
        rules_metrics,
        rules_native,
        rules_protocol,
        rules_threads,
    )

    return {
        "conf": rules_conf.check,
        "protocol": rules_protocol.check,
        "locks": rules_locks.check,
        "threads": rules_threads.check,
        "metrics": rules_metrics.check,
        "native": rules_native.check,
    }


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Tuple[Finding, Allow]] = field(default_factory=list)
    files_scanned: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_json(self) -> Dict[str, object]:
        return {
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "findings": [f.to_json() for f in self.findings],
            "suppressed": [
                {**f.to_json(), "reason": a.reason}
                for f, a in self.suppressed
            ],
        }


def run_lint(root: str, rules: Optional[List[str]] = None,
             allowlist: Optional[List[Allow]] = None,
             paths: Optional[List[str]] = None) -> LintResult:
    """Run the rule set over the tree at ``root``.

    ``rules``: subset of rule-group names (None = all).  ``allowlist``:
    None = the repo's declared list (``analysis/allowlist.py``); pass
    ``[]`` to see raw findings.  ``paths``: explicit repo-relative file
    list (fixtures); None = the standard tree walk."""
    if allowlist is None:
        from asyncframework_tpu.analysis.allowlist import ALLOWLIST
        allowlist = list(ALLOWLIST)
    for a in allowlist:
        if not str(a.reason or "").strip():
            raise ValueError(
                f"allowlist entry {a.rule}:{a.path}:{a.token} has no "
                f"reason -- every suppression carries one (policy)")

    ctx = LintContext(root, paths=paths)
    result = LintResult(files_scanned=len(ctx.files))
    raw: List[Finding] = list(ctx.parse_errors)
    table = _rules()
    for name, fn in table.items():
        if rules is not None and name not in rules:
            continue
        raw.extend(fn(ctx))
    raw.sort(key=lambda f: (f.path, f.line, f.rule, f.token))
    for f in raw:
        allow = next((a for a in allowlist if a.matches(f)), None)
        if allow is not None:
            result.suppressed.append((f, allow))
        else:
            result.findings.append(f)
    return result


# ----------------------------------------------------------- AST helpers
def const_str(node: ast.AST) -> Optional[str]:
    """The string value of a Constant node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def dotted_name(node: ast.AST) -> str:
    """``a.b.c`` for Name/Attribute chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def tail_name(node: ast.AST) -> str:
    """The final identifier of a Name/Attribute chain ('' otherwise)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def walk_excluding_nested_defs(body: Iterable[ast.stmt]) -> Iterable[ast.AST]:
    """Walk statements lexically, NOT descending into nested function /
    lambda bodies (code in them runs later, outside the enclosing
    ``with``)."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # its body runs later, outside the hold
        yield node
        stack.extend(ast.iter_child_nodes(node))
