"""Thread hygiene at every ``threading.Thread(...)`` construction site.

The PR 5 thread-leak (one Thread object per connection ever accepted)
and a fleet of anonymous daemon threads made post-mortems read like
``Thread-47``: this rule pins the discipline the tree converged on:

- ``thread-unnamed``: every Thread names itself (``name=...``) --
  anonymous threads make stack dumps, lockwatch reports, and the live
  UI's thread table unreadable;
- ``thread-implicit-daemon``: daemonness is explicit (``daemon=...``)
  -- inheriting it from the spawner is how a should-be-daemon thread
  ends up wedging interpreter shutdown (or a must-survive thread dies
  with a daemon spawner);
- ``thread-unguarded``: the site either RETAINS the thread object (so
  someone can join/reap/health-check it: assignment, appended to a
  registry, returned) or wraps its target in the exception policy
  (``utils/threads.guarded``) -- a fire-and-forget
  ``threading.Thread(...).start()`` whose target raises dies silently,
  the PR 5-class reap gap.

The constructor-kwarg check is lexical on purpose: a wrapper that
forwards ``**kwargs`` to Thread is invisible to it, so the repo's one
sanctioned wrapper (``utils/threads.py``) is itself allowlisted with a
reason, and everything else constructs Thread directly.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from asyncframework_tpu.analysis.core import (
    Finding,
    LintContext,
    SourceFile,
    dotted_name,
    tail_name,
)


def _is_thread_ctor(call: ast.Call) -> bool:
    dn = dotted_name(call.func)
    return dn in ("threading.Thread", "Thread") or \
        dn.endswith(".threading.Thread")


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_retained(sf: SourceFile, call: ast.Call) -> bool:
    """True when the Thread object outlives the statement: assigned,
    appended/registered, returned, yielded, or passed to a call other
    than its own ``.start()``."""
    node: ast.AST = call
    while True:
        parent = sf.parent_of(node)
        if parent is None:
            return False
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                               ast.NamedExpr, ast.Return, ast.Yield,
                               ast.List, ast.Tuple, ast.Dict,
                               ast.ListComp, ast.GeneratorExp)):
            return True
        if isinstance(parent, ast.Call) and parent is not call:
            # an argument to some call (e.g. registry.append(Thread(...)))
            return True
        if isinstance(parent, ast.Attribute):
            # Thread(...).start() -- whatever happens to the RESULT of
            # that method call (None), the Thread object itself is lost:
            # `t = threading.Thread(...).start()` binds None, not the
            # thread, so the chain is not-retained, full stop
            return False
        if isinstance(parent, ast.Expr):
            return False
        node = parent


def _target_guarded(call: ast.Call) -> bool:
    """target=guarded(...) -- the utils/threads.py exception policy (or
    a local ``_guarded`` copy where importing the package is off-limits,
    e.g. bench.py's probe path)."""
    target = _kwarg(call, "target")
    return (isinstance(target, ast.Call)
            and tail_name(target.func).lstrip("_") == "guarded")


def check(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for path, sf in ctx.files.items():
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                continue
            token = ""
            tgt = _kwarg(node, "target")
            if tgt is not None:
                token = tail_name(tgt) or tail_name(
                    tgt.func if isinstance(tgt, ast.Call) else tgt) or ""
            token = token or f"line{node.lineno}"
            if _kwarg(node, "name") is None:
                findings.append(Finding(
                    "thread-unnamed", path, node.lineno, token,
                    "Thread(...) without name= -- anonymous threads "
                    "make dumps and lockwatch reports unreadable"))
            if _kwarg(node, "daemon") is None:
                findings.append(Finding(
                    "thread-implicit-daemon", path, node.lineno, token,
                    "Thread(...) without explicit daemon= -- "
                    "daemonness inherited from the spawner is a "
                    "shutdown-wedge (or surprise-death) footgun"))
            if not _is_retained(sf, node) and not _target_guarded(node):
                findings.append(Finding(
                    "thread-unguarded", path, node.lineno, token,
                    "fire-and-forget Thread whose target is not "
                    "wrapped in utils/threads.guarded(...) -- an "
                    "exception in it dies silently and nothing can "
                    "reap or health-check the thread"))
    return findings
