"""Typed configuration system.

Parity: the reference has two layers -- a string k/v ``SparkConf`` and a typed
``ConfigEntry``/``ConfigBuilder`` registry (``core/.../internal/config/
package.scala:26``) with precedence CLI > conf file > defaults.  This module
provides both: :class:`ConfigEntry` (typed, documented, defaulted, registered)
and :class:`AsyncConf` (k/v store with env-var and dict overlays).

The ASYNC knobs themselves (the 13 positional driver args of
``SparkASGDThread.scala:28-48``) are registered here as first-class entries so
solvers can be configured programmatically, from CLI, or from files.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Generic, Optional, TypeVar

T = TypeVar("T")

_REGISTRY: Dict[str, "ConfigEntry"] = {}


@dataclass(frozen=True)
class ConfigEntry(Generic[T]):
    """A typed, registered configuration key.

    ``tunable=True`` marks a knob the adaptive controller
    (``parallel/controller.py``) is allowed to actuate at runtime; a
    tunable MUST declare ``floor`` and ``ceiling`` -- the hard bounds
    every controller decision is clamped to (async-lint's
    ``conf-tunable`` rule enforces both directions: a tunable without
    bounds, or a controller actuation of a non-tunable key, fails the
    lint).  For ``async.step.size`` the bounds apply to the step-DAMP
    multiplier (the controller scales the effective step, never the
    configured gamma itself)."""

    key: str
    default: T
    value_type: Callable[[str], T]
    doc: str = ""
    tunable: bool = False
    floor: Optional[float] = None
    ceiling: Optional[float] = None

    def __post_init__(self):
        _REGISTRY[self.key] = self

    def from_string(self, s: str) -> T:
        if self.value_type is bool:
            return s.strip().lower() in ("1", "true", "yes", "on")  # type: ignore
        return self.value_type(s)


def registry() -> Dict[str, ConfigEntry]:
    return dict(_REGISTRY)


_GLOBAL_CONF: Optional["AsyncConf"] = None


def set_global_conf(conf: Optional["AsyncConf"]) -> None:
    """Install the process's effective configuration (the CLI does this
    with its --conf overlays) so components constructed without an explicit
    conf -- e.g. receivers resolving backpressure defaults -- see the same
    values the run was submitted with."""
    global _GLOBAL_CONF
    _GLOBAL_CONF = conf


def global_conf() -> "AsyncConf":
    """The installed process conf; created AND INSTALLED on first use.

    The lazily-created default is installed (not discarded): before this,
    ``global_conf().set(...)`` on a process that never called
    :func:`set_global_conf` silently mutated a throwaway instance and the
    next ``global_conf()`` call returned a fresh one -- the classic
    lost-write footgun.  Now the first call pins the instance, so sets
    stick regardless of whether the CLI installed overlays first."""
    global _GLOBAL_CONF
    if _GLOBAL_CONF is None:
        _GLOBAL_CONF = AsyncConf()
    return _GLOBAL_CONF


class AsyncConf:
    """String/typed k/v configuration with precedence: explicit set > env
    (``ASYNCTPU_<KEY_UPPER_WITH_UNDERSCORES>``) > registered default."""

    ENV_PREFIX = "ASYNCTPU_"

    def __init__(self, initial: Optional[Dict[str, Any]] = None):
        self._store: Dict[str, Any] = {}
        if initial:
            self._store.update(initial)

    def set(self, key: str, value: Any) -> "AsyncConf":
        self._store[key] = value
        return self

    def set_all(self, kv: Dict[str, Any]) -> "AsyncConf":
        self._store.update(kv)
        return self

    def contains(self, key: str) -> bool:
        return key in self._store or self._env_name(key) in os.environ

    def _env_name(self, key: str) -> str:
        return self.ENV_PREFIX + key.upper().replace(".", "_")

    def get(self, entry_or_key, default: Any = None) -> Any:
        if isinstance(entry_or_key, ConfigEntry):
            entry = entry_or_key
            if entry.key in self._store:
                v = self._store[entry.key]
                return entry.from_string(v) if isinstance(v, str) else v
            env = os.environ.get(self._env_name(entry.key))
            if env is not None:
                return entry.from_string(env)
            return entry.default
        key = entry_or_key
        entry = _REGISTRY.get(key)
        if key in self._store:
            v = self._store[key]
            if entry is not None and isinstance(v, str):
                return entry.from_string(v)
            return v
        env = os.environ.get(self._env_name(key))
        if env is not None:
            return entry.from_string(env) if entry is not None else env
        if entry is not None:
            return entry.default
        return default

    def to_dict(self) -> Dict[str, Any]:
        d = {k: e.default for k, e in _REGISTRY.items()}
        d.update(self._store)
        return d

    def __repr__(self) -> str:  # pragma: no cover
        return f"AsyncConf({self._store!r})"


# --------------------------------------------------------------------------
# Registered entries: engine knobs + the reference's 13 driver args.
# --------------------------------------------------------------------------
NUM_WORKERS = ConfigEntry("async.num.workers", 8, int, "Logical workers (device slots).")
NUM_ITERATIONS = ConfigEntry("async.num.iterations", 1000, int, "Total accepted updates.")
STEP_SIZE = ConfigEntry("async.step.size", 0.1, float, "Base step size gamma.",
                        # tunable: the controller's per-push delay-adaptive
                        # DAMP multiplier is clamped to [floor, ceiling] --
                        # it scales the effective step, never gamma itself
                        tunable=True, floor=0.05, ceiling=1.0)
TAW = ConfigEntry("async.taw", 2**31 - 1, int, "Staleness bound tau.")
BATCH_RATE = ConfigEntry("async.batch.rate", 0.1, float, "Per-round Bernoulli sample rate b.")
BUCKET_RATIO = ConfigEntry("async.bucket.ratio", 0.5, float,
                           "Cohort availability threshold.",
                           # tunable: the controller re-clamps the partial-
                           # barrier cohort between floor*P (never solo
                           # unless P=1) and ceiling*P (the configured b is
                           # its own upper bound when smaller)
                           tunable=True, floor=0.125, ceiling=1.0)
PRINTER_FREQ = ConfigEntry("async.printer.freq", 100, int, "Trajectory snapshot period.")
DELAY_COEFF = ConfigEntry("async.delay.coeff", 0.0, float,
                          "Straggler delay intensity; -1 = cloud long-tail model.")
SEED = ConfigEntry("async.seed", 42, int, "Root PRNG seed.")
# async.mode, async.updater.drain.max, async.heartbeat.interval and
# async.heartbeat.timeout were declared here for reference parity but
# never read (async-lint conf-dead-knob): mode is selected by driver
# alias (asgd vs asgd-sync), drain batching rides async.drain.batch, and
# executor heartbeats ride async.heartbeat.timeout.ms -- deleted rather
# than left as operator-facing no-ops.
MODEL_VERSIONS = ConfigEntry("async.broadcast.versions", 4, int,
                             "Model versions kept live in the versioned store "
                             "(SolverConfig.max_live_versions).")
DRAIN_BATCH = ConfigEntry("async.drain.batch", 1, int,
                          "Queued gradients folded into one device dispatch.")
UI_PORT = ConfigEntry("async.ui.port", -1, int,
                      "Live dashboard HTTP port (0 = ephemeral, -1 = off) "
                      "-- spark.ui.port analog.")
RECEIVER_MAX_BUFFER = ConfigEntry(
    "async.streaming.receiver.max.buffer", 0, int,
    "Receiver bounded-buffer size (0 = unbounded) -- block generator cap.")
RECEIVER_MAX_RATE = ConfigEntry(
    "async.streaming.receiver.max.rate", 0.0, float,
    "Receiver ingest cap, elements/sec (0 = unlimited) -- "
    "spark.streaming.receiver.maxRate analog.")
BACKPRESSURE = ConfigEntry(
    "async.streaming.backpressure.enabled", False, bool,
    "PID-estimated receiver rate control -- "
    "spark.streaming.backpressure.enabled analog.")
SPECULATION_QUANTILE = ConfigEntry(
    "async.speculation.quantile", 0.75, float,
    "Fraction of tasks that must finish before speculating.")
SPECULATION_MULTIPLIER = ConfigEntry(
    "async.speculation.multiplier", 1.5, float,
    "Running task speculated past multiplier * median duration.")
SPECULATION_MIN_MS = ConfigEntry(
    "async.speculation.min.ms", 100.0, float,
    "Never speculate tasks younger than this.")
ALLOCATION_MAX_EXTRA = ConfigEntry(
    "async.allocation.max.extra", 1, int,
    "Max sibling executors added per slot by dynamic allocation.")
ALLOCATION_BACKLOG = ConfigEntry(
    "async.allocation.backlog.threshold", 2, int,
    "Queued tasks per slot that trigger a sibling (sustained).")
ALLOCATION_IDLE_S = ConfigEntry(
    "async.allocation.idle.timeout.s", 1.0, float,
    "Idle seconds before a sibling executor retires.")
HEARTBEAT_TIMEOUT_MS = ConfigEntry(
    "async.heartbeat.timeout.ms", 2000.0, float,
    "Solver-run heartbeat timeout (ms), see SolverConfig.")
MAX_SLOT_FAILURES = ConfigEntry(
    "async.max.slot.failures", 2, int,
    "Repeated executor deaths on a slot before its shard re-homes.")
SHUFFLE_SPILL_BYTES = ConfigEntry(
    "async.shuffle.spill.bytes", 256 * 1024 * 1024, int,
    "Driver-side shuffle routing buffer bound; past it routed entries "
    "spill to disk runs (0 = unbounded) -- "
    "SortShuffleManager/UnifiedMemoryManager role.")
SHUFFLE_DATA_PLANE = ConfigEntry(
    "async.shuffle.data.plane", "auto", str,
    "Array-pair reduce_by_key route: 'device' (jitted all_to_all shuffle), "
    "'host' (vectorized numpy sort/bincount), or 'auto' -- device on "
    "accelerator backends, host on CPU (the measured winner per rig; see "
    "ops/shuffle.py).")
# ------------------------------------------------------------- net plane
# The shared robustness layer (net/retry.py, net/session.py, net/faults.py):
# every DCN client (PS workers, remote topics, deploy daemons) resolves its
# retry policy from these, and every server sizes its dedup window from
# them -- one set of knobs for the whole control + data plane.
NET_RETRY_MAX_ATTEMPTS = ConfigEntry(
    "async.net.retry.max.attempts", 5, int,
    "Attempts per logical op before the retry layer gives up.")
NET_RETRY_BASE_MS = ConfigEntry(
    "async.net.retry.base.ms", 50.0, float,
    "Backoff floor (decorrelated jitter draws start here).")
NET_RETRY_MAX_MS = ConfigEntry(
    "async.net.retry.max.ms", 2000.0, float,
    "Backoff cap per sleep.")
NET_RETRY_ATTEMPT_TIMEOUT_S = ConfigEntry(
    "async.net.retry.attempt.timeout.s", 120.0, float,
    "Per-attempt socket timeout clients apply to their connections.")
NET_RETRY_DEADLINE_S = ConfigEntry(
    "async.net.retry.deadline.s", 0.0, float,
    "Overall deadline across attempts (0 = attempts bound alone).")
NET_BREAKER_THRESHOLD = ConfigEntry(
    "async.net.breaker.threshold", 5, int,
    "Consecutive failures that open an endpoint's circuit breaker.")
NET_BREAKER_COOLDOWN_S = ConfigEntry(
    "async.net.breaker.cooldown.s", 1.0, float,
    "Open-state fail-fast window before the half-open probe.")
NET_DEDUP_WINDOW = ConfigEntry(
    "async.net.dedup.window", 128, int,
    "Applied (sid, seq) ops each server remembers per client session "
    "(exactly-once-applied retry dedup).")
NET_FAULT_SCHEDULE = ConfigEntry(
    "async.net.fault.schedule", "", str,
    "Deterministic fault schedule as inline JSON or @/path/to/file "
    "(net/faults.py); empty = injection off.")
NET_FAULT_SEED = ConfigEntry(
    "async.net.fault.seed", 0, int,
    "Seed chaos runs hand to retry policies so backoff walks replay.")
# ------------------------------------------------------------- data plane
# The DCN throughput knobs (net/wiredelta.py + parallel/ps_dcn.py): PULL
# reply negotiation and the PS-side fused gradient apply.
PULL_MODE = ConfigEntry(
    "async.pull.mode", "full", str,
    "PULL reply negotiation: 'full' ships the whole model every pull "
    "(byte-identical legacy wire, the safe default); 'delta' sends "
    "have=<ts> so the PS can answer NOT_MODIFIED (zero payload), a "
    "byte-exact XOR sparse delta, or the full model -- whichever is "
    "smallest.  Decode mismatch or cache miss falls back to a full pull.")
PULL_DELTA_VERSIONS = ConfigEntry(
    "async.pull.delta.versions", 4, int,
    "Recent model versions the PS keeps host-side for delta encoding "
    "(un-overridden, the PS auto-scales this to 4*num_workers+2 -- a "
    "worker's basis is ~P versions old by its next pull); oldest "
    "versions evict first, and the cache is only maintained once a "
    "delta client shows up.  0 disables the cache: delta-mode pulls are "
    "answered NOT_MODIFIED on an exact-version match (needs no cache) "
    "or full otherwise.")
PS_SHARDS = ConfigEntry(
    "async.ps.shards", 1, int,
    "Parameter-server shard processes the launcher provisions "
    "(parallel/shardgroup.py): the model is range-partitioned across "
    "this many ParameterServer processes behind a shard map workers "
    "resolve at HELLO.  A PULL becomes per-shard parallel sub-pulls "
    "(each reusing the have= NM/XDELTA/FULL negotiation and CRC "
    "gating), a PUSH fans out per-shard rows under per-shard (sid, "
    "seq) exactly-once sessions, and the staleness contract becomes a "
    "per-shard version vector.  Shard 0 (the primary) keeps the wave "
    "gate, the elastic supervisor, and the eval plane; secondaries "
    "serve their ranges ungated.  1 (the default) is the classic "
    "single-PS path, byte- and step-identical.")
PS_STANDBY = ConfigEntry(
    "async.ps.standby", 0, int,
    "Warm standby processes per PS shard (parallel/replication.py): 1 "
    "provisions one standby child behind every shard primary; the "
    "primary streams accepted merge batches to it (REPL_SYNC bootstrap "
    "+ REPL_APPEND per drained batch -- post-dedup, with each item's "
    "(sid, seq) stamp and verdict, stamped with the primary's merge "
    "clock and fencing epoch), and on lease expiry the ShardGroup "
    "controller PROMOTEs the standby under the next fencing epoch "
    "instead of relaunching from checkpoint -- failover is bounded by "
    "suspicion time, not checkpoint replay, and the deposed primary's "
    "writes are REJECT_FENCED.  Standbys double as read replicas "
    "(SUBSCRIBE / relaycast roots) with staleness priced by their "
    "replication lag (ps.standby_lag series, standby_lag SLO rule).  "
    "0 (the default) keeps the classic restart-from-checkpoint "
    "recovery.  Promotion additionally requires async.fence.enabled "
    "and shards >= 2 (a map to re-announce the moved endpoint "
    "through); otherwise a standby is a warm read replica only.")
PUSH_MERGE = ConfigEntry(
    "async.push.merge", 8, int,
    "Upper bound on PUSHes the PS coalesces into one fused device apply "
    "when the model lock is contended (bit-identical to the serial apply "
    "order; 1 = classic one-dispatch-per-push path).",
    # tunable: the controller resizes the EFFECTIVE budget within
    # [floor, min(ceiling, configured value)] -- the fused kernel
    # compiles once at the configured bound, so the ceiling can never
    # grow a compiled shape
    tunable=True, floor=1, ceiling=64)
PIPELINE_DEPTH = ConfigEntry(
    "async.pipeline.depth", 0, int,
    "DCN worker update-loop pipelining: 0 = the classic serial "
    "pull -> compute -> push loop (byte- and step-identical legacy "
    "behavior); >= 1 = a prefetch thread on a second PS connection pulls "
    "model v(k+1) while step k computes, and pushes are handed to a "
    "bounded in-flight sender (at most this many unacknowledged pushes) "
    "so the next compute starts before the push ACK returns.  Gradient "
    "staleness stays bounded: the PS's taw admission prices the extra "
    "in-flight steps, and a taw rejection makes the worker discard its "
    "prefetched model and re-pull fresh.  ASAGA ignores this (its "
    "PS-side sampling requires strict pull->push alternation per "
    "worker).",
    # tunable: with pipelining ON the controller auto-sizes the live
    # in-flight window within [floor, min(ceiling, configured depth)]
    # from measured pull RTT vs compute time; it never flips 0 <-> >=1
    # (the loop SHAPE is chosen at worker start)
    tunable=True, floor=1, ceiling=8)
MESH_DEVICES = ConfigEntry(
    "async.mesh.devices", 0, int,
    "Devices in each DCN worker's LOCAL compute mesh (parallel/mesh.py): "
    "0 = the classic single-device gradient step (byte- and step-"
    "identical legacy behavior); >= 2 = the worker computes each "
    "mini-batch gradient batch-parallel over a dp mesh of this many "
    "chips -- its shard rows are padded+sharded into HBM once at loop "
    "start (ops/steps.make_mesh_asgd_worker_step / "
    "make_mesh_saga_dcn_worker_step), per-device partial gradients "
    "lax.psum-reduce locally, and the worker still emits ONE fused "
    "gradient per step (wire protocol unchanged).  A value beyond the "
    "rig's device count clamps (logged); a clamped value below 2, or a "
    "sparse (padded-ELL) shard, degrades to the serial single-device "
    "path instead of crashing the worker daemon.")
DEBUG_LOCKWATCH = ConfigEntry(
    "async.debug.lockwatch", False, bool,
    "Debug lock watchdog (net/lockwatch.py): the PS model lock becomes a "
    "watched lock -- any socket send/recv attempted while it is held "
    "raises AssertionError, and hold counts / max hold time are reported "
    "in the live UI.  Enabled for the chaos suite and bin/chaos_sweep.py "
    "so the lock-free PULL-serving claim is continuously checked; off by "
    "default (zero hot-path cost).")
# ------------------------------------------------------------- codec plane
# Wire-compression codecs (net/wirecodec.py): quantized gradient pushes
# with per-worker error feedback, and lossless compression of snapshot
# deltas on the relaycast distribution plane.
CODEC_PUSH = ConfigEntry(
    "async.codec.push", "off", str,
    "Gradient PUSH quantization (net/wirecodec.py): 'off' (the default) "
    "ships raw f32 -- byte-identical legacy wire; 'fp16' halves and "
    "'int8' (per-push max-abs scale) quarters the dense gradient bytes, "
    "with the quantization residual kept in a per-worker error-feedback "
    "accumulator and folded into the next push, so the model's deviation "
    "from the uncompressed trajectory stays bounded by ONE step's "
    "quantization error.  Non-finite gradients, fp16-overflowing "
    "magnitudes, sparse-encoded pushes, and ASAGA (exact history "
    "scalars) always fall back to the raw wire.")
# ------------------------------------------------------------ native plane
# Native hot-path data plane (native/wiredelta.cc, native/wirecodec.cc,
# native/shmring.cc behind native_build.py): GIL-free C++ twins of the
# pure-Python wire codecs, plus a shared-memory ring transport for
# colocated roles.  Both default OFF = byte-identical legacy wire; the
# async-cluster launcher flips them on.
NATIVE_ENABLED = ConfigEntry(
    "async.native.enabled", False, bool,
    "Route the wire hot paths (XOR delta encode/decode + CRC32 in "
    "net/wiredelta.py, int8/fp16 quantize + byte-shuffle + delta-index "
    "transform in net/wirecodec.py, the frame pump's gather copy in "
    "net/frame.py) through the ctypes-loaded C++ extensions, releasing "
    "the GIL for the whole pass.  Every native entry point has a "
    "registered pure-Python bit-identity oracle (the pre-native "
    "implementation) and silently degrades to it when no toolchain is "
    "present -- the wire is byte-identical either way, only the "
    "interpreter time changes (metrics family 'native' says which path "
    "actually ran).  Off by default.")
SHM_ENABLED = ConfigEntry(
    "async.shm.enabled", False, bool,
    "Shared-memory ring transport for COLOCATED roles (net/shmring.py): "
    "after the normal TCP dial, a loopback connection is upgraded via "
    "an SHM_OPEN handshake to a pair of lock-free SPSC rings in "
    "/dev/shm, and REPL_APPEND / SUBSCRIBE frames move through them "
    "instead of the loopback socket.  The framed BYTES are identical "
    "and still pass the net/frame.py choke point (CRC, fencing, dedup, "
    "byte counters, fault injection all unchanged); only the kernel "
    "socket hop is bypassed.  Any ring failure (peer death, handshake "
    "refusal) degrades to the plain socket path.  Off by default = "
    "byte-identical legacy transport.")
SHM_RING_KB = ConfigEntry(
    "async.shm.ring.kb", 4096, int,
    "Per-direction shared-memory ring capacity in KiB (net/shmring.py). "
    "A frame larger than the ring falls back to chunked writes; sizing "
    "the ring to a few model payloads keeps the writer from ever "
    "spinning on a healthy reader.",
    tunable=True, floor=64, ceiling=262144)
# ------------------------------------------------------------- relay plane
# Relaycast (asyncframework_tpu/relaycast/): peer-relayed versioned model
# distribution -- replicas form a k-ary tree rooted at the PS, the root's
# direct children SUBSCRIBE as usual, and every deeper node RELAY_FETCHes
# CRC-gated XOR deltas from its parent and re-serves them to its own
# children, so PS egress per version is O(fanout), not O(replicas).
RELAY_FANOUT = ConfigEntry(
    "async.relay.fanout", 2, int,
    "Children per node in the relaycast distribution tree (the PS root "
    "included: it accepts at most this many relay-child registrations "
    "for its RELAY_OFFER push path; k8s/CLI tree plans use the same "
    "arity).  Tree depth is log_fanout(replicas).")
RELAY_COMPRESS = ConfigEntry(
    "async.relay.compress", True, bool,
    "Lossless zlib compression of relay-hop model payloads "
    "(net/wirecodec.py): XOR deltas of a training step compress "
    "severalfold (agreeing sign/exponent bits, ascending index half); "
    "losslessness keeps the CRC gate exact.  On by default -- the relay "
    "plane is new wire with no byte-identity legacy to preserve; "
    "payloads that would not shrink ship raw automatically.")
RELAY_VERSIONS = ConfigEntry(
    "async.relay.versions", 8, int,
    "Recent model versions a relay node keeps for delta-encoding "
    "children's RELAY_FETCH have= requests (oldest evict first; a "
    "missing basis answers full, exactly like the PS delta cache).")
RELAY_PARENT_RETRY_S = ConfigEntry(
    "async.relay.parent.retry.s", 5.0, float,
    "After a relay parent fails (dead, fenced, CRC mismatch) the child "
    "re-homes to the ROOT (direct SUBSCRIBE -- the always-safe path) "
    "and only re-tries its parent after this many seconds, so a "
    "flapping interior node cannot oscillate the subtree.")
# ------------------------------------------------------------ trace plane
# Distributed tracing for the async update loop (metrics/trace.py): spans
# are sampled per update lifecycle, propagated over the wire as an optional
# frame-header field, and folded into per-stage latency histograms.
TRACE_SAMPLE = ConfigEntry(
    "async.trace.sample", 1.0 / 64.0, float,
    "Per-update trace sampling rate (1 = every update, 0 = tracing off; "
    "counter-based per worker, so the first update is always sampled when "
    "> 0 and runs of any length yield >= 1 trace).  This default governs "
    "the DCN plane (PSClient/ParameterServer), whose stages are network-"
    "dominated; the in-process engine traces only on explicit opt-in "
    "(SolverConfig.trace_sample / --trace-sample) because its updater "
    "thread is itself the measured hot path.")
TRACE_BUFFER = ConfigEntry(
    "async.trace.buffer", 512, int,
    "Completed-span ring-buffer capacity per worker process (bounded, "
    "lock-light; oldest spans dropped, counted).")
# ---------------------------------------------------------- elastic plane
# The process-level membership supervisor (parallel/supervisor.py): worker
# death detection, shard adoption, rejoin, degraded-cohort clamping for
# the multi-process DCN training path.
ELASTIC_ENABLED = ConfigEntry(
    "async.elastic.enabled", True, bool,
    "Run the DCN parameter server with the elastic membership supervisor "
    "(worker-death detection + shard adoption + rejoin).")
ELASTIC_DEAD_AFTER_S = ConfigEntry(
    "async.elastic.dead.after.s", 5.0, float,
    "Silence past this declares a worker dead (local process exit is "
    "detected immediately via its registered pid).")
ELASTIC_CHECK_INTERVAL_S = ConfigEntry(
    "async.elastic.check.interval.s", 0.5, float,
    "Supervisor monitor scan period.")
ELASTIC_BOOT_GRACE_S = ConfigEntry(
    "async.elastic.boot.grace.s", 10.0, float,
    "Never-contacted shards are not handed out for adoption before this "
    "much run time has passed (covers slow worker bring-up/compile).")
# ---------------------------------------------------------- fencing plane
# Partition-tolerant membership (parallel/supervisor.py, parallel/ps_dcn.py,
# parallel/shardgroup.py): time-bounded leases granted at HELLO and renewed
# on any op, a SUSPECT state between live and dead, and monotonic fencing
# epochs minted per member so a partitioned-but-alive zombie can never
# mutate or serve a range it no longer owns (servers answer REJECT_FENCED
# to stale-epoch ops).
FENCE_ENABLED = ConfigEntry(
    "async.fence.enabled", False, bool,
    "Epoch fencing for the PS plane: servers mint a monotonic fencing "
    "epoch (persisted in their checkpoints, bumped every incarnation and "
    "every lease-expiry failover), clients stamp it on every "
    "PULL/PUSH/SUBSCRIBE (ep header), and a server rejects ops whose "
    "epoch is not current (REJECT_FENCED) -- so a zombie shard behind a "
    "healed partition, or a deposed worker replaying its buffered "
    "pushes, can never double-apply against the replacement's state.  "
    "Off (the default) the wire is byte-identical legacy (no ep keys, "
    "epoch 0 everywhere); async-cluster flips it on.")
LEASE_S = ConfigEntry(
    "async.lease.s", 0.0, float,
    "Membership lease duration: granted at HELLO, renewed by any op; a "
    "member whose lease expires is declared dead and (with fencing on) "
    "its replacement is launched under a bumped fencing epoch.  0 (the "
    "default) aliases async.elastic.dead.after.s -- the lease IS the "
    "silence bound, named for what it grants.")
SUSPECT_AFTER_S = ConfigEntry(
    "async.suspect.after.s", 0.0, float,
    "Silence past this marks a member SUSPECT (surfaced in membership, "
    "metrics, and routing demotion) without declaring death -- the "
    "partition-tolerant middle state between live and dead.  0 (the "
    "default) = half the lease.")
GRAY_RTT_FACTOR = ConfigEntry(
    "async.gray.rtt.factor", 3.0, float,
    "Gray-failure detection (net/health.py): an endpoint whose op-RTT "
    "EWMA exceeds this multiple of the cohort median (and the floor "
    "below) is latency-SUSPECT -- slow-but-alive members are demoted in "
    "routing and surfaced in membership without being declared dead.")
GRAY_RTT_MIN_MS = ConfigEntry(
    "async.gray.rtt.min.ms", 50.0, float,
    "Gray-failure RTT floor: an endpoint is never latency-suspected "
    "while its EWMA is under this many ms (micro-jitter on a fast local "
    "cohort is not a gray failure).")
# ----------------------------------------------------------- serving plane
# The read path (asyncframework_tpu/serving/): ModelReplica processes
# subscribe to the PS's versioned snapshots (SUBSCRIBE = a wave-gate-free
# delta-negotiated pull) and answer PREDICT RPCs while training runs; a
# ServingFrontend round-robins client requests over registered replicas
# with retry/circuit-breaker failover.
SERVE_REFRESH_S = ConfigEntry(
    "async.serve.refresh.interval.s", 0.05, float,
    "Replica background refresh period: how often a ModelReplica sends a "
    "SUBSCRIBE (delta-mode have= pull, CRC-gated, full-pull fallback) to "
    "the PS.  Bounds the replica's freshness lag when training is "
    "advancing the model.")
SERVE_MAX_STALE_MS = ConfigEntry(
    "async.serve.max.staleness.ms", 2000.0, float,
    "A replica whose last SUCCESSFUL refresh is older than this marks "
    "itself unhealthy: PREDICT is answered UNHEALTHY (the frontend fails "
    "over) until a refresh lands again.  0 disables the health gate -- "
    "the replica serves its last model forever (bounded-staleness reads "
    "degrade to eventual consistency).")
SERVE_REPLICAS = ConfigEntry(
    "async.serve.replicas", 2, int,
    "Replica count launchers (bench --serve, k8s manifests) provision.")
SERVE_MAX_REPLICAS = ConfigEntry(
    "async.serve.max.replicas", 16, int,
    "Registration slots a ServingFrontend allocates (the ElasticSupervisor "
    "membership table is sized once).")
SERVE_DEADLINE_S = ConfigEntry(
    "async.serve.failover.deadline.s", 2.0, float,
    "Frontend per-request budget across failover attempts: a PREDICT that "
    "cannot be answered by ANY healthy replica within this raises "
    "PredictError to the caller.")
# --------------------------------------------------------- telemetry plane
# Continuous telemetry (metrics/timeseries.py, metrics/prom.py,
# metrics/slo.py): every process samples its counter families into a
# bounded time-series store, exposes Prometheus text exposition on
# /metrics, folds convergence samples into loss-vs-wallclock /
# loss-vs-version curves, and evaluates declarative SLO rules over
# time-series windows.
METRICS_PORT = ConfigEntry(
    "async.metrics.port", -1, int,
    "Per-process telemetry HTTP port serving /metrics (Prometheus text "
    "exposition) and /api/status (-1 = off, 0 = ephemeral).  Processes "
    "that already serve a live UI (async.ui.port) expose /metrics there "
    "too; this knob adds the endpoint to processes with no dashboard -- "
    "workers, serving replicas, frontends, the master.  k8s manifests "
    "set it to 9095 via env and annotate pods for scraping.")
METRICS_INTERVAL_S = ConfigEntry(
    "async.metrics.interval.s", 1.0, float,
    "Telemetry sampler period: every tick records each counter family "
    "and derived source into the bounded time-series store and runs one "
    "SLO evaluation pass.  <= 0 disables sampling (the /metrics "
    "exposition still serves instantaneous values).")
METRICS_RETENTION = ConfigEntry(
    "async.metrics.retention", 512, int,
    "Samples retained per time series (bounded ring; oldest evict "
    "first, counted).  At the default 1 s interval this is ~8.5 min of "
    "history per series; RAM is O(series x retention) small floats.")
CONV_SAMPLE = ConfigEntry(
    "async.convergence.sample", 0, int,
    "Worker-side convergence sampling: every Nth update per logical "
    "worker computes its shard's mean loss (one extra jitted eval) and "
    "the gradient norm, and piggybacks (version, loss, grad_norm) on "
    "the next PUSH header (cv entry) for the PS to fold into the "
    "loss-vs-wallclock / loss-vs-version curves.  0 = off (the default: "
    "the piggyback adds header bytes, and byte-identity suites compare "
    "exact wires); async-cluster flips it to 16.")
SLO_RULES = ConfigEntry(
    "async.slo.rules",
    "serve_freshness: p95(serving.freshness_lag_ms) < 2000 over 15s "
    "for 2s; "
    "predict_p99: max(serving.predict_ms_p99) < 500 over 30s for 5s; "
    "staleness_ms: max(trace.staleness_ms_p95) < 60000 over 30s for 5s; "
    "updates_floor: rate(ps.accepted) > 0.5 over 30s for 10s "
    "unless ps.done; "
    "shard_availability: max(ps_shards.dark_ranges) < 1 over 15s "
    "for 3s unless ps_shards.done; "
    "standby_lag: max(ps.standby_lag) < 512 over 15s for 5s "
    "unless ps.done; "
    "fenced_writes: rate(recovery.fenced_rejects) < 1 over 30s for 10s; "
    "controller_converged: rate(control.changes) < 0.5 over 20s for 5s "
    "unless observer.fleet_done; "
    "fleet_stragglers: max(observer.straggler_score) < 2.5 over 30s "
    "for 10s unless observer.fleet_done; "
    "fleet_freshness: max(observer.freshness_lag_ms) < 5000 over 30s "
    "for 5s unless observer.fleet_done; "
    "fleet_roles: max(observer.roles_down) < 1 over 30s for 10s "
    "unless observer.fleet_done",
    str,
    "Declarative SLO rule set (metrics/slo.py grammar: '<name>: "
    "<agg>(<series>) <op> <threshold> [over Ns] [for Ns] "
    "[unless <series>]', clauses ';'-separated; 'unless' gates a rule "
    "to no_data while its series' last sample is truthy -- the "
    "updates/s floor stands down once the run is DONE instead of "
    "firing forever on a finished-but-still-serving PS).  Evaluated "
    "over time-series windows each sampler "
    "tick; rule states (ok/pending/firing/no_data, with burn "
    "durations) surface as the /api/status 'health' section and the "
    "async_slo_state gauges on /metrics.  Rules whose series never "
    "produce samples report no_data and never fire.")
# -------------------------------------------------------- adaptive control
# The closed loop from cluster telemetry to the async knobs
# (parallel/controller.py): an AsyncController on the primary PS
# periodically reads the observed signals (PS-local per-worker
# staleness/RTT/compute EWMAs; observer.* straggler scores and fleet
# freshness when a collector is attached) and actuates the declared
# tunables -- per-push delay-adaptive step damping, partial-barrier
# cohort size, pipeline depth, push-merge budget.  Decisions propagate
# through the existing SETMAP/WELCOME control path as a CTRL payload
# next to the shard map and epoch vector.
CONTROL_ENABLED = ConfigEntry(
    "async.control.enabled", False, bool,
    "Run the adaptive asynchrony controller on the primary PS.  Off "
    "(the default) the wire is byte-identical legacy -- no CTRL "
    "payloads anywhere; async-cluster flips it on (straggler-heavy "
    "runs stop needing hand-tuned b/depth/merge/step conf).")
CONTROL_INTERVAL_S = ConfigEntry(
    "async.control.interval.s", 0.5, float,
    "Controller decision period: every tick reads the observed "
    "signals and re-evaluates every knob target.  <= 0 disables the "
    "loop thread (tick() still works on demand -- the ManualClock "
    "test surface).")
CONTROL_HYSTERESIS = ConfigEntry(
    "async.control.hysteresis", 0.25, float,
    "Relative dead-band per knob: a recomputed target actuates only "
    "when it differs from the current value by more than this "
    "fraction (and by >= 1 for integer knobs).  The first defense "
    "against knob flapping; the oscillation guard is the second.")
CONTROL_COOLDOWN_S = ConfigEntry(
    "async.control.cooldown.s", 2.0, float,
    "Minimum seconds between successive changes of the SAME knob -- "
    "a decision needs time to show up in the signals it was made "
    "from (staleness EWMAs, queue depth) before being revised.")
CONTROL_OSC_REVERSALS = ConfigEntry(
    "async.control.osc.reversals", 3, int,
    "Oscillation guard: this many direction REVERSALS of one knob "
    "within the freeze window trips the guard -- the knob freezes at "
    "its current value for async.control.osc.freeze.s and the trip "
    "is counted (control.osc_trips) and surfaced in /api/status.")
CONTROL_OSC_FREEZE_S = ConfigEntry(
    "async.control.osc.freeze.s", 10.0, float,
    "How long an oscillation-tripped knob stays frozen before the "
    "controller may move it again (reversal history cleared).")
CONTROL_DAMP_FREE = ConfigEntry(
    "async.control.damp.free", -1.0, float,
    "Staleness slack before delay-adaptive step damping engages: a "
    "push at staleness tau is damped by 1/(1 + tau - free) only past "
    "this threshold (floored at the async.step.size tunable floor).  "
    "-1 (the default) auto-sizes to num_workers + pipeline depth + 2: "
    "with P workers and a depth-D in-flight window the steady-state "
    "staleness is ~P-1+D, so only ABNORMAL delay damps -- damping the "
    "healthy steady state just slows convergence at a fixed budget.")
# -------------------------------------------------------- cluster observer
# Central collector (metrics/observer.py + bin/async-mon): discovers every
# role, scrapes /api/status + /metrics over the net/ retry plane, persists
# a durable per-run per-role history store, derives cross-role signals
# (straggler scores, merge-queue pressure, fleet freshness) as the
# ``observer.*`` series the fleet SLO rules watch, and harvests crash
# flight-recorder dumps.
OBSERVER_INTERVAL_S = ConfigEntry(
    "async.observer.interval.s", 1.0, float,
    "Collector scrape period: every tick fetches each discovered role's "
    "/api/status, folds the numbers into the per-run history store, and "
    "recomputes the derived observer.* signals.  <= 0 disables the "
    "scrape loop (scrape_once() still works on demand).")
OBSERVER_ENDPOINTS = ConfigEntry(
    "async.observer.endpoints", "", str,
    "Static scrape targets beside discovery, ';'-separated "
    "'name=role@host:port' entries (role and name optional: "
    "'host:port' scrapes as role 'process').  The k8s observer "
    "Deployment passes the per-role Services here.")
OBSERVER_HISTORY_DIR = ConfigEntry(
    "async.observer.history.dir", "", str,
    "Root directory of the durable run-history store (one run-<id>/ "
    "subdir per observed run: meta.json + per-role compacted series + "
    "harvested flight-recorder dumps; bin/async-history renders an "
    "index over it).  Empty = in-memory only, nothing persisted.")
OBSERVER_HISTORY_POINTS = ConfigEntry(
    "async.observer.history.points", 512, int,
    "Per-series capacity of the run-history store.  At capacity every "
    "other point is dropped and the acceptance stride doubles "
    "(ConvergenceHistory's compaction), so a persisted series spans "
    "the WHOLE run at bounded disk/RAM instead of forgetting its "
    "start.")
OBSERVER_PERSIST_S = ConfigEntry(
    "async.observer.persist.s", 5.0, float,
    "How often the collector persists the run-history store to disk "
    "(atomic per-role files via checkpoint.durable_replace; also "
    "persisted once at stop).  <= 0 persists only at stop.")
OBSERVER_STRAGGLER_FACTOR = ConfigEntry(
    "async.observer.straggler.factor", 2.5, float,
    "A worker whose straggler score (max over the compute / push-RTT / "
    "push-interval / staleness dimensions of worker_value over "
    "cohort_median) reaches this factor is flagged in the fleet view "
    "and counted in observer.stragglers_flagged -- the input surface "
    "for delay-adaptive control (ROADMAP item 2).")
# --------------------------------------------------------- flight recorder
FLIGHT_DIR = ConfigEntry(
    "async.flight.dir", "", str,
    "Crash flight recorder dump directory (metrics/flightrec.py): when "
    "set, this process keeps a bounded in-memory ring of recent "
    "events/spans/counter deltas and writes it to "
    "flight-<role>-<pid>.json here -- atomically on a cadence, plus a "
    "final dump on SIGTERM/SIGINT/atexit -- so even a SIGKILL leaves a "
    "post-mortem at most one flush behind.  The cluster observer "
    "harvests these into the run-history store.  Empty = off (the "
    "default: zero hot-path work).")
FLIGHT_EVENTS = ConfigEntry(
    "async.flight.events", 256, int,
    "Flight-recorder ring capacity in events (oldest evict first, "
    "counted).  Bounds both RAM and the dump file size.")
FLIGHT_FLUSH_S = ConfigEntry(
    "async.flight.flush.s", 0.5, float,
    "Flight-recorder flush cadence: how stale an uncatchable-kill "
    "(SIGKILL) post-mortem can be.  Each flush also records one "
    "counter-delta event (non-zero registry family deltas since the "
    "previous flush).  <= 0 disables the flush thread (dumps only on "
    "fatal signal / exit).")
# --------------------------------------------------- continuous profiling
PROF_ENABLED = ConfigEntry(
    "async.prof.enabled", 0, int,
    "Continuous profiling plane (metrics/profiler.py): 1 starts the "
    "stack sampler and arms the exact zone accumulators at the wire/"
    "merge/dispatch choke points; snapshots ride /api/status, the "
    "observer run history, and every flight-recorder dump.  0 (the "
    "default) is asserted byte-identical on the wire and zero-overhead "
    "on the hot path: zone() returns the shared no-op context manager "
    "and wrap_dispatch() returns the step callable unchanged.")
PROF_HZ = ConfigEntry(
    "async.prof.hz", 97.0, float,
    "Sampling-profiler frequency in Hz (prime, to avoid lockstep with "
    "periodic work).  Sampling error for a zone with true share p "
    "after N samples is ~sqrt(p(1-p)/N): 97 Hz resolves a 10% zone to "
    "+-0.4% over a 60 s window.  <= 0 keeps the exact zone "
    "accumulators but starts no sampler thread.")
PROF_STACKS = ConfigEntry(
    "async.prof.stacks", 256, int,
    "Bound on DISTINCT collapsed stacks the sampler keeps (bounds RAM "
    "and snapshot size).  Beyond it, new stacks are dropped and "
    "counted in profile.stack_overflow -- never evicted, which would "
    "bias long-running hot stacks out of the flamegraph.")
