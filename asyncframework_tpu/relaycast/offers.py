"""ChildRegistry: the ONE relay-child registry + offer fan-out.

Both offer senders -- the PS root's offer loop (``parallel/ps_dcn.py``)
and every interior :class:`~asyncframework_tpu.relaycast.node.RelayNode`
-- need the same machinery: a fanout-bounded registry of learned child
endpoints, LRU semantics so a child that stopped subscribing is
displaced by one that still does (a deep node that fell back to the
root ONCE must not squat a root offer slot forever -- its slot goes to
the planned direct child the moment that child registers), strike
bookkeeping that drops a dead child after a few failed offers, and the
short-timeout connect-send-recv-close offer send itself.  One class so
a fix lands once.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Tuple

from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.relaycast import metrics as rmetrics

#: consecutive offer failures before a child is dropped (its next
#: registering fetch/subscribe re-adds it)
OFFER_STRIKES = 3


class ChildRegistry:
    """Fanout-bounded LRU registry of relay-child endpoints."""

    def __init__(self, cap: int, timeout_s: float = 0.5):
        self.cap = max(1, int(cap))
        self.timeout_s = float(timeout_s)
        #: (host, port) -> consecutive offer failures, LRU order --
        #: front is the child that registered/re-registered longest ago
        self._children: "OrderedDict[Tuple[str, int], int]" = OrderedDict()
        self._lock = threading.Lock()

    def register(self, host: str, port: int) -> None:
        """Record (or refresh) a child.  At capacity the least-recently
        registering child is EVICTED in its favor: registration renews
        on every fetch/subscribe, so live children keep their slots and
        a child that re-homed away is displaced by one still here."""
        key = (host, int(port))
        with self._lock:
            if key in self._children:
                self._children[key] = 0
                self._children.move_to_end(key)
                return
            while len(self._children) >= self.cap:
                self._children.popitem(last=False)
                rmetrics.bump("children_evicted")
            self._children[key] = 0

    def children(self) -> List[Tuple[str, int]]:
        with self._lock:
            return list(self._children.keys())

    def offer(self, hdr: dict) -> int:
        """Send ``hdr`` (a RELAY_OFFER) to every registered child;
        returns the delivered count.  Sends happen OUTSIDE the lock
        with short timeouts; ``OFFER_STRIKES`` consecutive failures
        drop a child."""
        delivered = 0
        for key in self.children():
            try:
                sock = _frame.connect(key, timeout=self.timeout_s)
                try:
                    _frame.send_msg(sock, hdr)
                    _frame.recv_msg(sock)
                finally:
                    sock.close()
                delivered += 1
                rmetrics.bump("offers_sent")
                with self._lock:
                    if key in self._children:
                        self._children[key] = 0
            except (ConnectionError, OSError):
                with self._lock:
                    strikes = self._children.get(key)
                    if strikes is not None:
                        if strikes + 1 >= OFFER_STRIKES:
                            del self._children[key]
                            rmetrics.bump("children_dropped")
                        else:
                            self._children[key] = strikes + 1
        return delivered
