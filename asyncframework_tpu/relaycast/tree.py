"""Relaycast tree plan: deterministic k-ary distribution forest.

The reference's ``TorrentBroadcast`` shapes its swarm dynamically; this
plane keeps the ASYNC stance that correctness machinery should be
*deterministic and inspectable*: given (replica count, fanout) every
launcher -- tests, k8s StatefulSet ordinals, serving CLI -- computes the
SAME tree with no coordination, so the topology is a pure function, not
a protocol.  Repair is not re-planning: a node whose parent dies falls
back to the ROOT (the PS -- the always-safe direct SUBSCRIBE path) for
``async.relay.parent.retry.s`` and then re-tries its planned parent;
the plan itself never changes mid-run.

Layout: replicas ``0..n-1``; nodes ``0..k-1`` are children of the root
(the PS, denoted index ``ROOT == -1``); node ``i >= k`` has parent
``i // k - 1``.  Depth is ``O(log_k n)``, every node has at most ``k``
children, and the child sets partition ``1..n-1`` -- properties the
relaycast test suite asserts over a sweep of (n, k).
"""

from __future__ import annotations

from typing import List

#: the PS root's index in a tree plan
ROOT = -1


def parent_index(i: int, fanout: int) -> int:
    """Planned parent of replica ``i`` (``ROOT`` for the first ``fanout``
    replicas, which SUBSCRIBE directly to the PS)."""
    if i < 0:
        raise ValueError(f"replica index must be >= 0, got {i}")
    if fanout < 1:
        raise ValueError(f"fanout must be >= 1, got {fanout}")
    if i < fanout:
        return ROOT
    return i // fanout - 1


def children_of(i: int, n: int, fanout: int) -> List[int]:
    """Planned children of replica ``i`` among ``n`` replicas."""
    lo = (i + 1) * fanout
    return [c for c in range(lo, min(lo + fanout, n))]


def depth_of(i: int, fanout: int) -> int:
    """Hops from replica ``i`` to the root (direct children are 1)."""
    d = 1
    while parent_index(i, fanout) != ROOT:
        i = parent_index(i, fanout)
        d += 1
    return d
