"""Relaycast: peer-relayed versioned model distribution (ISSUE 12).

The ASYNCbroadcast/TorrentBroadcast analog for the serving fleet:
replicas form a deterministic k-ary tree rooted at the PS
(:mod:`~asyncframework_tpu.relaycast.tree`), the root's direct children
SUBSCRIBE as usual, and every deeper node RELAY_FETCHes CRC-gated XOR
deltas from its parent and re-serves them to its own children
(:mod:`~asyncframework_tpu.relaycast.node`), so PS snapshot egress per
version is O(fanout) instead of O(replicas).  Every hop is epoch-gated
(PR 9 fencing) and falls back to a direct root SUBSCRIBE on any
mismatch (:mod:`~asyncframework_tpu.relaycast.source`).
"""

from asyncframework_tpu.relaycast.node import RelayNode
from asyncframework_tpu.relaycast.source import (
    DecodeMismatch,
    ParentEmpty,
    ParentError,
    RelaySource,
)
from asyncframework_tpu.relaycast.tree import (
    ROOT,
    children_of,
    depth_of,
    parent_index,
)

__all__ = [
    "ROOT",
    "DecodeMismatch",
    "ParentEmpty",
    "ParentError",
    "RelayNode",
    "RelaySource",
    "children_of",
    "depth_of",
    "parent_index",
]
