"""RelayNode: one hop of the relaycast distribution tree.

Every relay-enabled replica runs one of these next to its predict
server.  The node is a tiny versioned model store behind a
:class:`~asyncframework_tpu.serving.server.FramedServer`:

- the replica's fetch path (:class:`~asyncframework_tpu.relaycast.source.
  RelaySource`) **publishes** each CRC-validated version it obtains
  (from its parent or from the root) into the store;
- children send ``RELAY_FETCH have=<ts>`` and get the same negotiated
  NM / XOR-delta / FULL reply shapes as the PS serves (``net/
  wiredelta.py`` -- byte-exact reconstruction, version CRC on every
  reply), optionally zlib-compressed (``net/wirecodec.py``,
  ``async.relay.compress``);
- a node that lands a new version **offers** it to its registered
  children (``RELAY_OFFER`` -- advisory: a lost offer costs nothing,
  the children's poll loops fetch on their next tick);
- every hop is **epoch-gated** (PR 9 fencing): requests stamped with a
  stale epoch are REJECT_FENCED, and stored versions carry the epoch
  they were fetched under (``vep``) so a child can refuse data from a
  parent that is itself behind -- a deposed or stale peer can never
  poison the subtree; the fallback on ANY mismatch is a direct root
  SUBSCRIBE, the existing safe path.

Children are learned, not configured: a fetch whose header carries the
child's own relay port registers it for offers (bounded by
``async.relay.fanout``); repeated offer failures drop it.
"""

from __future__ import annotations

import socket
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.net import wirecodec, wiredelta
from asyncframework_tpu.relaycast import metrics as rmetrics
from asyncframework_tpu.relaycast.offers import ChildRegistry
from asyncframework_tpu.serving.server import FramedServer

_send_msg = _frame.send_msg
_recv_msg = _frame.recv_msg


class _Stored:
    """One immutable stored model version (atomic-reference discipline:
    a fetch handler that read the reference serves a coherent
    (ts, bytes, crc, epoch, freshness) tuple no matter how many
    publishes land meanwhile)."""

    __slots__ = ("ts", "wire", "crc", "vep", "clock", "k", "age_ms",
                 "born_mono", "done")

    def __init__(self, ts: int, wire: bytes, crc: int, vep: int,
                 clock: int, k: int, age_ms: float, done: bool):
        self.ts = ts
        self.wire = wire
        self.crc = crc
        self.vep = vep
        self.clock = clock
        self.k = k
        self.age_ms = age_ms
        self.born_mono = time.monotonic()
        self.done = done


class RelayNode(FramedServer):
    """Versioned model store + RELAY_FETCH/RELAY_OFFER server."""

    def __init__(self, rid: int = 0, host: str = "0.0.0.0", port: int = 0,
                 versions: Optional[int] = None,
                 compress: Optional[bool] = None,
                 fanout: Optional[int] = None,
                 on_offer: Optional[Callable[[], None]] = None):
        from asyncframework_tpu.conf import (
            RELAY_COMPRESS,
            RELAY_FANOUT,
            RELAY_VERSIONS,
            global_conf,
        )

        conf = global_conf()
        super().__init__(f"relay-{int(rid)}")
        self.rid = int(rid)
        self.versions = (int(versions) if versions is not None
                         else int(conf.get(RELAY_VERSIONS)))
        self.compress = (bool(compress) if compress is not None
                         else bool(conf.get(RELAY_COMPRESS)))
        self.fanout = (int(fanout) if fanout is not None
                       else int(conf.get(RELAY_FANOUT)))
        #: fencing epoch this node believes current (0 = fencing off);
        #: monotone, learned from root replies / fetch traffic
        self.epoch = 0
        #: newest version a parent has offered (monotone; the fetch path
        #: uses it to decide an immediate re-fetch is worthwhile)
        self.offered_ts = 0
        #: the current version, ATOMIC reference swap (serving reads one
        #: reference; publish replaces it whole)
        self._cur: Optional[_Stored] = None
        #: recent versions for delta encoding (ts -> _Stored), insertion
        #: order = version age (ts is monotone)
        self._store: "OrderedDict[int, _Stored]" = OrderedDict()
        self._store_lock = threading.Lock()
        #: learned children (shared registry/offer machinery with the
        #: PS root's offer loop -- relaycast/offers.py)
        self._registry = ChildRegistry(self.fanout)
        #: offer fan-out runs on ITS OWN lazily-started thread (the PS
        #: root's discipline): the publishing/refresh path must never
        #: block on a dark child's connect timeout -- request_offers()
        #: just sets an event, and consecutive publishes coalesce into
        #: one offer round carrying the newest version
        self._offer_event = threading.Event()
        self._offer_thread: Optional[threading.Thread] = None
        self._offer_thread_lock = threading.Lock()
        self.on_offer = on_offer
        # local observability (shipped on RELAY STATUS)
        self.fetches = 0
        self.offers_in = 0
        self.fenced = 0
        self._stats_lock = threading.Lock()
        self.bind(host, port)

    # ------------------------------------------------------------- store
    def publish(self, ts: int, wire: bytes, crc: int, clock: int, k: int,
                age_ms: float, done: bool, epoch: int = 0) -> None:
        """Install a CRC-validated version (the RelaySource calls this
        after every successful parent/root fetch).  Monotone: an older
        ts than the current one is ignored (a late parent reply must
        not roll the subtree back)."""
        if epoch > self.epoch:
            self.epoch = epoch
        cur = self._cur
        if cur is not None and ts < cur.ts:
            return
        item = _Stored(ts, wire, crc, int(epoch or self.epoch),
                       clock, k, age_ms, done)
        with self._store_lock:
            self._store[ts] = item
            while len(self._store) > max(self.versions, 1):
                self._store.popitem(last=False)
        self._cur = item

    def current(self) -> Optional[_Stored]:
        return self._cur

    def basis_for(self, ts: int) -> Optional[np.ndarray]:
        with self._store_lock:
            item = self._store.get(ts)
        if item is None:
            return None
        return np.frombuffer(item.wire, np.float32)

    # ---------------------------------------------------------- children
    def register_child(self, host: str, port: int) -> None:
        self._registry.register(host, port)

    def children(self) -> List[Tuple[str, int]]:
        return self._registry.children()

    def offer_children(self) -> int:
        """One SYNCHRONOUS offer round: announce the current version to
        every registered child (ChildRegistry: short per-child
        timeouts, strike-based drops, LRU eviction at fanout).  Returns
        the number delivered.  Production callers use
        :meth:`request_offers` -- this blocks on dark children's
        timeouts and exists for the offer thread and for tests."""
        cur = self._cur
        if cur is None:
            return 0
        hdr = {"op": "RELAY_OFFER", "ts": cur.ts, "crc": cur.crc,
               "rid": self.rid}
        if self.epoch:
            hdr["ep"] = self.epoch
        return self._registry.offer(hdr)

    def request_offers(self) -> None:
        """Wake the (lazily-started) offer thread -- the non-blocking
        publish-path entry point.  A dark child's connect timeout burns
        the offer thread, never the refresh path that produced the
        version; back-to-back publishes coalesce (the thread always
        offers the CURRENT version)."""
        if self._cur is None:
            return
        if self._offer_thread is None:
            with self._offer_thread_lock:
                if self._offer_thread is None:
                    from asyncframework_tpu.utils.threads import guarded

                    self._offer_thread = threading.Thread(
                        target=guarded(self._offer_loop,
                                       f"relay-{self.rid}-offers"),
                        name=f"relay-{self.rid}-offers", daemon=True,
                    )
                    self._offer_thread.start()
        self._offer_event.set()

    def _offer_loop(self) -> None:
        while not self._stop.is_set():
            if not self._offer_event.wait(0.2):
                continue
            self._offer_event.clear()
            self.offer_children()

    # ------------------------------------------------------------ serving
    def handle_op(self, conn: socket.socket, op: Optional[str],
                  header: dict, payload: bytes) -> bool:
        if op == "RELAY_FETCH":
            if not self._fence_reject(conn, header):
                self._handle_fetch(conn, header)
        elif op == "RELAY_OFFER":
            if not self._fence_reject(conn, header):
                self._handle_offer(conn, header)
        elif op == "STATUS":
            _send_msg(conn, {"op": "STATUS", **self.status()})
        else:
            return False
        return True

    def _fence_reject(self, conn: socket.socket, header: dict) -> bool:
        """Epoch-fencing admission for relay hops, the PS's semantics
        (ps_dcn._fence_reject) on the read plane: with fencing off
        (``self.epoch == 0``) or an unstamped op, serve; a STALE-epoch
        peer is answered REJECT_FENCED with the newest epoch this node
        knows (it self-heals and re-fetches, or falls back to the
        root); a NEWER-epoch peer advances our belief -- we are the
        stale party, and our next root fetch lands on the current
        incarnation (our stored versions keep their old ``vep``, so
        children reject them client-side meanwhile)."""
        if not self.epoch:
            return False
        ep = header.get("ep")
        if ep is None:
            return False
        ep = int(ep)
        if ep >= self.epoch:
            if ep > self.epoch:
                self.epoch = ep
            return False
        with self._stats_lock:
            self.fenced += 1
        rmetrics.bump("fenced_hops")
        _send_msg(conn, {"op": "REJECT_FENCED", "epoch": self.epoch})
        return True

    def _handle_fetch(self, conn: socket.socket, header: dict) -> None:
        rp = header.get("rport")
        if rp is not None:
            try:
                peer = conn.getpeername()[0]
            except OSError:
                peer = None
            if peer is not None:
                self.register_child(peer, int(rp))
        cur = self._cur
        if cur is None:
            _send_msg(conn, {"op": "ERR", "msg": "relay node holds no "
                                                 "model yet"})
            return
        have = header.get("have")
        basis = self.basis_for(int(have)) if have is not None else None
        cur_arr = np.frombuffer(cur.wire, np.float32)
        wenc, model_part, nnz = wiredelta.encode(cur_arr, basis,
                                                 cur_bytes=cur.wire)
        if wenc == wiredelta.FULL and basis is not None \
                and basis.shape == cur_arr.shape and self.compress:
            # dense change (sparse xdelta would not be smaller): ship
            # the dense XOR form instead -- same size raw, but its high
            # byte planes are near-zero for a training step, which is
            # exactly what the shuffle+deflate transform below crunches.
            # Gated on compress: without the transform XFULL is
            # FULL-sized anyway and only ADDS a basis requirement (an
            # extra failure mode for zero wire savings)
            wenc = wiredelta.XFULL
            model_part = wiredelta.encode_xfull(cur_arr, basis)
        hdr: dict = {"op": "RELAY_MODEL", "ts": cur.ts, "wenc": wenc,
                     "crc": cur.crc, "vep": cur.vep, "clock": cur.clock,
                     "k": cur.k, "done": cur.done,
                     "age_ms": round(
                         cur.age_ms
                         + (time.monotonic() - cur.born_mono) * 1e3, 3)}
        if wenc == wiredelta.XDELTA:
            hdr["nnz"] = nnz
        if self.compress:
            cfields, model_part = wirecodec.compress_model_part(
                wenc, model_part, nnz)
            hdr.update(cfields)
        hdr["wlen"] = len(model_part)
        if self.epoch:
            hdr["ep"] = self.epoch
        with self._stats_lock:
            self.fetches += 1
        rmetrics.bump("fetches_served")
        rmetrics.bump(f"fetch_{wenc}")
        rmetrics.bump("fetch_bytes_out", len(model_part))
        _frame.send_msg_vectored(conn, hdr, (model_part,))

    def _handle_offer(self, conn: socket.socket, header: dict) -> None:
        ts = int(header.get("ts", 0))
        with self._stats_lock:
            self.offers_in += 1
        rmetrics.bump("offers_received")
        cur = self._cur
        fresh = ts > (cur.ts if cur is not None else -1) \
            and ts > self.offered_ts
        if fresh:
            self.offered_ts = ts
        else:
            rmetrics.bump("offers_stale")
        # ACK before the (possibly slow) fetch: the parent's offer loop
        # must not block on this subtree's whole refresh chain
        _send_msg(conn, {"op": "ACK", "fresh": fresh})
        if fresh and self.on_offer is not None:
            self.on_offer()

    def status(self) -> Dict:
        cur = self._cur
        with self._stats_lock:
            out = {
                "rid": self.rid, "port": self.port, "epoch": self.epoch,
                "fetches": self.fetches, "offers_in": self.offers_in,
                "fenced": self.fenced,
                "children": [list(c) for c in self.children()],
            }
        with self._store_lock:
            out["stored_versions"] = len(self._store)
        if cur is not None:
            out.update(ts=cur.ts, crc=cur.crc, vep=cur.vep,
                       clock=cur.clock, done=cur.done)
        return out

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "RelayNode":
        self.start_accepting()
        return self

    def stop(self) -> None:
        self.stop_server()
