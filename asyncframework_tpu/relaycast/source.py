"""RelaySource: a replica's model-fetch path through the relay tree.

Duck-types the slice of :class:`~asyncframework_tpu.parallel.ps_dcn.
PSClient` that :class:`~asyncframework_tpu.serving.replica.ModelReplica`
consumes (``subscribe() -> (ts, w, clock, k, age_ms, done)``,
``pull_wenc``, ``delta_fallbacks``, ``bye()``), so the replica's
refresh/publish machinery is untouched -- only where the bytes come
from changes:

- a node with a planned **parent** sends ``RELAY_FETCH have=<ts>`` up
  the tree and reconstructs via the stock ``net/wiredelta.py`` decode
  (CRC-gated; full replies from a PEER are additionally CRC-verified --
  only the PS root's full payload is authoritative by itself);
- ANY parent failure -- dead endpoint, REJECT_FENCED, stale version
  epoch, CRC/decode mismatch, corrupt compression -- **re-homes the
  node to the root** (direct SUBSCRIBE, the existing safe path) and
  backs off the parent for ``async.relay.parent.retry.s``;
- every validated version is **published** into the local
  :class:`~asyncframework_tpu.relaycast.node.RelayNode` and offered to
  this node's own children, which is what makes the tree a tree.

Epoch discipline: the node's believed epoch stamps every relay hop
(``_stamped`` -- the relay plane's client-side fencing choke point,
pinned by ``bin/async-lint`` exactly like ``PSClient._proc_hdr``); root
replies advance it through the stock PSClient epoch tracking.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.net import wirecodec, wiredelta
from asyncframework_tpu.relaycast import metrics as rmetrics
from asyncframework_tpu.relaycast.node import RelayNode

_send_msg = _frame.send_msg
_recv_msg = _frame.recv_msg


class ParentError(ConnectionError):
    """The planned parent cannot serve this node right now (dead,
    fenced, or served bytes that failed validation): re-home to the
    root and back the parent off."""


class ParentEmpty(ParentError):
    """The parent is alive but holds no model yet (boot ordering: a
    subtree can come up before its ancestors' first fetch).  Fall back
    to the root for THIS round only -- no cooloff, the parent usually
    has the version one poll tick later."""


class DecodeMismatch(ParentError):
    """The parent's PAYLOAD failed reconstruction (basis/CRC/compression
    mismatch) -- the one failure class a full refetch can actually fix.
    Header-level rejects (fenced, stale version epoch) raise plain
    :class:`ParentError`: refetching from the same parent is futile."""


class RelaySource:
    """Parent-preferring, root-falling-back model source for a relay
    replica.  NOT thread-safe by itself -- the replica's refresh lock
    serializes callers, same as the stock PSClient contract."""

    def __init__(self, ps_host: str, ps_port: int, node: RelayNode,
                 parent: Optional[Tuple[str, int]] = None, rid: int = 0,
                 retry_parent_s: Optional[float] = None):
        from asyncframework_tpu.conf import (
            RELAY_PARENT_RETRY_S,
            global_conf,
        )

        self.ps_host, self.ps_port = ps_host, int(ps_port)
        self.node = node
        self.parent = (parent[0], int(parent[1])) if parent else None
        self.rid = int(rid)
        self.retry_parent_s = (
            float(retry_parent_s) if retry_parent_s is not None
            else float(global_conf().get(RELAY_PARENT_RETRY_S))
        )
        # the PSClient-compatible observability surface
        self.pull_wenc: Dict[str, int] = {"full": 0, "nm": 0, "xdelta": 0}
        self.delta_fallbacks = 0
        self.via_parent = 0
        self.via_root = 0
        self._root = None               # lazy PSClient (direct SUBSCRIBE)
        self._psock = None              # persistent framed conn to parent
        self._parent_dark_until = 0.0
        self._lock = threading.Lock()   # guards the parent socket swap

    # ------------------------------------------------------------- fencing
    def _stamped(self, hdr: dict) -> dict:
        """The relay plane's client-side epoch stamp choke point (the
        ``_proc_hdr`` analog ``bin/async-lint`` pins)."""
        if self.node.epoch:
            hdr["ep"] = self.node.epoch
        return hdr

    # ------------------------------------------------------------ plumbing
    def _drop_parent_sock(self) -> None:
        with self._lock:
            if self._psock is not None:
                try:
                    self._psock.close()
                except OSError:
                    pass
                self._psock = None

    def _parent_call(self, hdr: dict) -> Tuple[dict, bytes]:
        """One framed round trip to the parent on the persistent
        connection; one re-dial on a dead socket.  The replica's
        refresh lock serializes callers, so the dial happens unlocked
        (``_lock`` only guards the close-vs-swap race with ``bye``)."""
        for attempt in (0, 1):
            try:
                sock = self._psock
                if sock is None:
                    sock = _frame.connect(self.parent, timeout=5.0)
                    with self._lock:
                        self._psock = sock
                _send_msg(sock, hdr)
                return _recv_msg(sock)
            except (ConnectionError, OSError):
                self._drop_parent_sock()
                if attempt:
                    raise
        raise ConnectionError("unreachable")  # pragma: no cover

    # ------------------------------------------------------ parent fetch
    def _decode_reply(self, header: dict, payload: bytes
                      ) -> Tuple[int, np.ndarray, int]:
        """RELAY_MODEL -> (ts, w, crc); raises ParentError on anything
        that must re-home this node."""
        op = header.get("op")
        if op == "REJECT_FENCED":
            # the parent fenced OUR stamp: adopt the newer epoch (our
            # next hop -- root or retried parent -- is stamped current)
            # and re-home for this round; self-healing without ever
            # accepting bytes across the fence
            srv = int(header.get("epoch", 0))
            if srv > self.node.epoch:
                self.node.epoch = srv
            rmetrics.bump("fenced_hops")
            raise ParentError(f"parent fenced us at epoch {srv}")
        if op == "ERR":
            raise ParentEmpty(str(header.get("msg", "parent empty")))
        if op != "RELAY_MODEL":
            raise ParentError(f"parent answered {op!r}")
        srv_ep = header.get("ep")
        if srv_ep is not None and int(srv_ep) > self.node.epoch:
            self.node.epoch = int(srv_ep)
        vep = int(header.get("vep", 0))
        if self.node.epoch and vep and vep < self.node.epoch:
            # the parent's stored version predates the epoch we believe
            # current: a stale peer must not feed the subtree
            rmetrics.bump("stale_epoch_rejects")
            raise ParentError(f"parent serves stale epoch {vep} "
                              f"(< {self.node.epoch})")
        ts = int(header["ts"])
        want_crc = int(header["crc"])
        try:
            model_part = wirecodec.decompress_model_part(header, payload)
        except ValueError as e:
            rmetrics.bump("crc_rejects")
            raise DecodeMismatch(str(e))
        wenc = header.get("wenc", wiredelta.FULL)
        cur = self.node.current()
        basis = None
        basis_crc = None
        if cur is not None:
            basis = np.frombuffer(cur.wire, np.float32)
            basis_crc = cur.crc
        w = wiredelta.decode(wenc, model_part,
                             int(header.get("nnz", 0)), basis,
                             want_crc, basis_crc)
        if w is not None and wenc == wiredelta.FULL \
                and wiredelta.crc(w) != want_crc:
            # a peer's FULL payload is NOT authoritative (it may be
            # mid-death); only the PS root earns that trust
            w = None
        if w is None:
            rmetrics.bump("crc_rejects")
            raise DecodeMismatch("relay payload failed CRC/decode")
        self.pull_wenc[wenc] = self.pull_wenc.get(wenc, 0) + 1
        rmetrics.bump("parent_bytes_in", len(model_part))
        return ts, w, want_crc

    def _fetch_parent(self) -> Tuple[int, np.ndarray, int, int, float,
                                     bool, int]:
        """(ts, w, clock, k, age_ms, done, crc) from the parent, one
        ``have=`` negotiation plus one full-refetch fallback (exactly
        the PSClient delta discipline)."""
        hdr = {"op": "RELAY_FETCH", "rid": self.rid,
               "rport": self.node.port}
        cur = self.node.current()
        if cur is not None:
            hdr["have"] = cur.ts
        header, payload = self._parent_call(self._stamped(dict(hdr)))
        try:
            ts, w, crc = self._decode_reply(header, payload)
        except DecodeMismatch:
            if "have" not in hdr:
                raise
            # the PAYLOAD failed against our basis: ONE full refetch
            # (cache miss/corruption degrades to full, never to wrong).
            # Header-level rejects (fenced, stale vep) raise plain
            # ParentError above this class and skip the refetch -- the
            # same parent would reject the full identically.
            self.delta_fallbacks += 1
            hdr.pop("have", None)
            header, payload = self._parent_call(self._stamped(dict(hdr)))
            ts, w, crc = self._decode_reply(header, payload)
        rmetrics.bump("parent_fetches")
        return (ts, w, int(header.get("clock", ts)),
                int(header.get("k", 0)),
                float(header.get("age_ms", 0.0)),
                bool(header.get("done", False)), crc)

    # --------------------------------------------------------- root fetch
    def _ensure_root(self):
        if self._root is None:
            from asyncframework_tpu.parallel.ps_dcn import PSClient

            self._root = PSClient(self.ps_host, self.ps_port,
                                  pull_mode="delta",
                                  epoch=self.node.epoch)
        return self._root

    def _root_subscribe(self, wid: int):
        cl = self._ensure_root()
        if self.node.epoch > cl.epoch:
            cl.epoch = self.node.epoch
        before = dict(cl.pull_wenc)
        fb = cl.delta_fallbacks
        # rport rides the SUBSCRIBE: the PS registers this node as a
        # direct relay child and its offer loop announces new versions
        got = cl.subscribe(wid, extra={"rport": self.node.port})
        for shape, n in cl.pull_wenc.items():
            d = n - before.get(shape, 0)
            if d:
                self.pull_wenc[shape] = self.pull_wenc.get(shape, 0) + d
        self.delta_fallbacks += cl.delta_fallbacks - fb
        if cl.epoch > self.node.epoch:
            self.node.epoch = cl.epoch
        if got is None:  # pragma: no cover - SUBSCRIBE never says DONE
            return None
        ts, w, clock, k, age_ms, done = got
        basis = cl._basis.get(wid)
        crc = basis[2] if basis is not None and basis[0] == ts \
            else wiredelta.crc(np.ascontiguousarray(w, np.float32))
        return ts, w, clock, k, age_ms, done, crc

    # ------------------------------------------------------------- facade
    def subscribe(self, wid: int = 0
                  ) -> Optional[Tuple[int, np.ndarray, int, int,
                                      float, bool]]:
        """The ModelReplica-facing fetch: parent when planned and not
        backed off, root otherwise; publishes + offers on success."""
        got = None
        now = time.monotonic()
        if self.parent is not None and now >= self._parent_dark_until:
            try:
                got = self._fetch_parent()
                self.via_parent += 1
            except ParentEmpty:
                pass  # alive-but-empty parent: root this round, no cooloff
            except (ParentError, ConnectionError, OSError) as e:
                self._parent_dark_until = now + self.retry_parent_s
                self._drop_parent_sock()
                rmetrics.bump("rehomes")
                print(f"relay-{self.rid}: parent {self.parent} failed "
                      f"({e}); re-homing to root for "
                      f"{self.retry_parent_s:.1f}s",
                      file=sys.stderr, flush=True)
        if got is None:
            if self.parent is not None:
                rmetrics.bump("root_fallbacks")
            got = self._root_subscribe(wid)
            if got is None:  # pragma: no cover
                return None
            self.via_root += 1
        ts, w, clock, k, age_ms, done, crc = got
        cur = self.node.current()
        if cur is None or ts > cur.ts:
            self.node.publish(ts, w.tobytes(), crc, clock, k, age_ms,
                              done, epoch=self.node.epoch)
            # async fan-out: a dark child's offer timeout must never
            # stall THIS node's refresh cadence (the whole subtree's
            # freshness rides on it)
            self.node.request_offers()
        elif ts < cur.ts:
            # monotone RETURN, not just monotone store: a straggler
            # parent reply (e.g. the parent is still behind after this
            # node re-homed to the root) must not roll the replica's
            # SERVED model back either -- answer from the local store,
            # which holds the newest validated version
            rmetrics.bump("stale_replies")
            return (cur.ts, np.frombuffer(cur.wire, np.float32),
                    cur.clock, cur.k,
                    cur.age_ms
                    + (time.monotonic() - cur.born_mono) * 1e3,
                    cur.done)
        return ts, w, clock, k, age_ms, done

    def bye(self) -> None:
        self._drop_parent_sock()
        if self._root is not None:
            self._root.bye()
