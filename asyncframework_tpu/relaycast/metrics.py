"""Relaycast-plane counters (metrics/registry.py ``relay`` family).

Process-global flat monotone counters, the same shape as every other
observability module: nodes and sources bump them, ``relay_totals()``
feeds the live UI / sampler / Prometheus exposition through the central
registry, and ``reset_relay_totals()`` rides ``metrics.reset_totals``
for per-run isolation.
"""

from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_totals: Dict[str, int] = {}


def bump(key: str, n: int = 1) -> None:
    """Monotone relay counter (fetches_served, fetch_nm/fetch_xdelta/
    fetch_full, fetch_bytes_out, offers_sent, offers_received,
    offers_stale, parent_fetches, parent_bytes_in, root_fallbacks,
    rehomes, fenced_hops, crc_rejects, stale_epoch_rejects,
    children_dropped)."""
    with _lock:
        _totals[key] = _totals.get(key, 0) + n


def relay_totals() -> Dict[str, int]:
    with _lock:
        return dict(_totals)


def reset_relay_totals() -> None:
    with _lock:
        _totals.clear()
