"""Reduction / aggregation collectives.

The reference's collectives are all *driver-mediated*: ``reduce`` /
``aggregate`` ship per-partition results to the driver which folds them
(``rdd/RDD.scala:1227-1261``), and ``treeReduce`` / ``treeAggregate``
(``rdd/RDD.scala:1181-1205,1358+``) add intermediate combine rounds to keep
the driver from being the bottleneck.  That design exists because the driver
is the only reduction point a TCP cluster has.

On TPU the mesh *is* the reduction network: ``jax.lax.psum`` over an ICI axis
is a hardware all-reduce.  This module provides

- :func:`psum_over_mesh` -- the SPMD all-reduce used by the synchronous
  solvers (replaces ``treeAggregate``);
- :func:`tree_combine` -- a host-side pairwise tree fold used by the async
  driver when it *chooses* to combine several queued partial results in one
  updater wake (parity with treeReduce's combine topology, depth log2);
- :func:`shard_sum_matvec` -- a shard_map'd X^T(mask*r) with psum, the one-jit
  data-parallel gradient used by ``minibatch_sgd`` and the dryrun path.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, List, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def psum_over_mesh(x: jax.Array, axis_name: str = "dp") -> jax.Array:
    """All-reduce sum over a mesh axis (call inside shard_map/pjit)."""
    return jax.lax.psum(x, axis_name)


def tree_combine(items: Sequence[Any], op: Callable[[Any, Any], Any]) -> Any:
    """Pairwise tree fold on the host: log2(n) depth, parity with treeReduce's
    combine topology.  ``op`` must be commutative+associative (reference
    requirement for ``reduce``)."""
    items = list(items)
    if not items:
        raise ValueError("tree_combine over empty sequence")
    while len(items) > 1:
        nxt: List[Any] = []
        for i in range(0, len(items) - 1, 2):
            nxt.append(op(items[i], items[i + 1]))
        if len(items) % 2 == 1:
            nxt.append(items[-1])
        items = nxt
    return items[0]


def data_parallel_grad_fn(grad_sum_fn: Callable, mesh: Mesh, axis: str = "dp"):
    """Build a one-jit SPMD data-parallel summed-gradient function.

    ``grad_sum_fn(X, y, w, mask) -> g`` is a per-shard summed gradient (e.g.
    :func:`ops.gradients.least_squares_grad_sum`).  Returns a function over
    globally-sharded ``X (n, d)``, ``y (n,)``, ``mask (n,)`` (sharded on the
    batch dim) and replicated ``w (d,)`` computing the *global* gradient sum
    via an ICI psum -- the TPU-native ``treeAggregate``.
    """

    # lazy: ops.__init__ is imported from parallel-side modules, so a
    # top-level ops -> parallel import would be cyclic
    from asyncframework_tpu.parallel.mesh import resolve_shard_map

    @partial(
        resolve_shard_map(),
        mesh=mesh,
        in_specs=(P(axis, None), P(axis), P(None), P(axis)),
        out_specs=P(None),
    )
    def _sharded(X, y, w, mask):
        g = grad_sum_fn(X, y, w, mask)
        return jax.lax.psum(g, axis)

    return jax.jit(_sharded)
