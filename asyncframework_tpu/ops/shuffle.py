"""Device-side shuffle for array-typed pair data.

Parity role: ``shuffle/sort/SortShuffleManager.scala:69`` -- the engine
component that moves (key, value) records to their key's partition and
reduces them there.  The reference sorts spill files and fetches blocks over
TCP because its partitions live in different JVMs; the TPU build's pair ops
normally route through the driver (data/pairs.py -- fine at control-plane
sizes).  THIS module is the data-plane path for numeric-array payloads: the
whole shuffle -- hash partitioning, bucketing, the exchange, and the
reduce -- is jitted XLA, and the exchange is ONE ``lax.all_to_all`` over a
device mesh (ICI, no host round-trip).

Pipeline (per device, all inside one shard_map):

1. map-side combine: sort local keys, segment-reduce duplicates (the
   reference's map-side ``Aggregator``),
2. bucket by target partition ``key mod P`` into a (P, cap) send buffer
   (sentinel key -1 pads unused slots),
3. ``all_to_all`` the buffers (tiled: row i of every sender lands on
   device i),
4. reduce-side: mask sentinels, sort received keys, segment-reduce into
   the output partition (padded; hosts strip sentinels on materialize).

Keys must be non-negative int32/int64 (word ids, user ids -- the shapes the
data plane exists for); arbitrary Python keys stay on the host path.
Single-device meshes skip the collective and run ONE fused
sort + segment-reduce over the concatenated blocks (round 5: a single
dispatch -- on a tunneled chip the per-dispatch RTT dominates the old
per-partition multi-stage pipeline).

:func:`host_reduce_by_key` is the vectorized HOST twin (numpy
bincount / sort+reduceat) for CPU backends, where round 3 measured the
emulated collective losing 2.4-9x to host execution.  The dispatch rule
lives in ``data/pairs.py`` (``async.shuffle.data.plane``); measured
crossover on this rig is recorded in ROUND5.md.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

SENTINEL = -1  # invalid-slot key; real keys must be >= 0

_OPS = ("sum", "max", "min")


def _identity(op: str, dtype):
    """Reduction identity valid for the VALUE dtype (inf converted to an
    int dtype is implementation-defined in XLA -- integers use iinfo
    extremes instead)."""
    if op == "sum":
        return jnp.zeros((), dtype)
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.min if op == "max" else info.max, dtype)
    return jnp.asarray(-jnp.inf if op == "max" else jnp.inf, dtype)


def _reduce_into(seg, vals, n: int, op: str):
    init = jnp.full(n, _identity(op, vals.dtype), vals.dtype)
    at = init.at[seg]
    if op == "sum":
        return at.add(vals, indices_are_sorted=True, mode="drop")
    if op == "max":
        return at.max(vals, indices_are_sorted=True, mode="drop")
    return at.min(vals, indices_are_sorted=True, mode="drop")


def _segment_reduce(keys: jax.Array, vals: jax.Array, op: str,
                    out_cap: int) -> Tuple[jax.Array, jax.Array]:
    """Sorted segment reduction with sentinel padding.

    ``keys`` may contain SENTINEL entries (sorted to the FRONT as -1);
    output: (out_keys, out_vals) with distinct keys leading, sentinel-padded
    to ``out_cap``.
    """
    order = jnp.argsort(keys)
    sk = keys[order]
    sv = vals[order]
    valid = sk != SENTINEL
    # segment boundaries among VALID sorted keys
    first = valid & jnp.concatenate(
        [jnp.ones(1, bool), sk[1:] != sk[:-1]]
    )
    seg = jnp.cumsum(first) - 1  # -1 for leading invalid run; clamp below
    seg = jnp.where(valid, seg, out_cap)  # invalid slots dropped by mode
    out_vals = _reduce_into(seg, jnp.where(valid, sv, 0), out_cap, op)
    out_keys = jnp.full(out_cap, SENTINEL, sk.dtype).at[seg].set(
        sk, indices_are_sorted=True, mode="drop"
    )
    if op in ("max", "min"):
        out_vals = jnp.where(
            out_keys == SENTINEL, jnp.zeros((), out_vals.dtype), out_vals
        )
    return out_keys, out_vals


def _bucket(keys: jax.Array, vals: jax.Array, p: int, cap: int):
    """(P, cap) send buffers: row t holds this device's pairs for target
    partition t = key mod P, sentinel-padded."""
    t = jnp.where(keys == SENTINEL, p, keys % p)
    order = jnp.argsort(t)
    sk, sv, st = keys[order], vals[order], t[order]
    counts = jnp.bincount(st, length=p + 1)[:p]
    offsets = jnp.concatenate([jnp.zeros(1, counts.dtype),
                               jnp.cumsum(counts)[:-1]])
    col = jnp.arange(sk.shape[0]) - offsets[jnp.clip(st, 0, p - 1)]
    ok = (st < p) & (col < cap)
    # invalid entries scatter OUT OF BOUNDS and are dropped -- routing them
    # to any real slot would race a valid entry's write (duplicate-index
    # .set order is unspecified)
    rows = jnp.where(ok, st, p)
    cols = jnp.where(ok, col, 0)
    bk = jnp.full((p, cap), SENTINEL, sk.dtype).at[rows, cols].set(
        sk, mode="drop"
    )
    bv = jnp.zeros((p, cap), sv.dtype).at[rows, cols].set(sv, mode="drop")
    return bk, bv


def host_reduce_by_key(
    parts: Dict[int, Tuple[np.ndarray, np.ndarray]],
    op: str = "sum",
) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
    """Vectorized host shuffle-reduce: the same contract as
    :func:`device_reduce_by_key` (key-mod-P output partitioning) computed
    with numpy -- ``bincount`` when the key range is dense enough, else one
    stable sort + ``reduceat``.  The CPU-backend winner: ~10x the
    driver-routed dict path and well ahead of the EMULATED collective on
    10M pairs (ROUND5.md)."""
    if op not in _OPS:
        raise ValueError(f"op must be one of {sorted(_OPS)}, got {op!r}")
    pids = sorted(parts)
    p = len(pids)
    if p == 0:
        return {}
    ks = np.concatenate([np.asarray(parts[pid][0]) for pid in pids])
    vs = np.concatenate([np.asarray(parts[pid][1]) for pid in pids])
    if ks.size == 0:
        return {pid: (ks[:0], vs[:0]) for pid in pids}
    uk = uv = None
    if op == "sum" and ks.dtype.kind in "iu":
        kmax = int(ks.max())
        # dense-enough key space: one bincount beats the sort.  Bound the
        # count/sum temporaries by the INPUT size (not a multiple of it):
        # a sparse 40M-key space over 10M pairs would otherwise allocate
        # ~640 MB of scratch where the sort path needs none
        if kmax + 1 <= max(ks.size, 1 << 20):
            present = np.bincount(ks, minlength=kmax + 1) > 0
            inexact = False
            if vs.dtype.kind in "iu":
                # bincount's float64 weight sums silently round integer
                # totals past 2^53.  |any key's sum| <= max|v| * n, so only
                # cross to exact accumulation when that bound can round --
                # wordcount-shaped inputs (small values, many pairs) keep
                # the fast bincount path
                bound = max(abs(int(vs.min())), abs(int(vs.max()))) * ks.size
                inexact = bound >= (1 << 53)
            if inexact:
                # exact int64 accumulation (np.add.at is slower than
                # bincount, but correctness beats speed past the boundary)
                sums = np.zeros(kmax + 1, np.int64)
                np.add.at(sums, ks, vs.astype(np.int64, copy=False))
            else:
                sums = np.bincount(ks, weights=vs, minlength=kmax + 1)
            uk = np.nonzero(present)[0].astype(ks.dtype)
            uv = sums[uk].astype(vs.dtype, copy=False)
    if uk is None:
        order = np.argsort(ks, kind="stable")
        sk, sv = ks[order], vs[order]
        first = np.ones(sk.size, bool)
        first[1:] = sk[1:] != sk[:-1]
        idx = np.nonzero(first)[0]
        uk = sk[idx]
        red = {"sum": np.add, "max": np.maximum, "min": np.minimum}[op]
        uv = red.reduceat(sv, idx).astype(vs.dtype, copy=False)
    t = uk % p
    order2 = np.argsort(t, kind="stable")
    st, suk, suv = t[order2], uk[order2], uv[order2]
    bounds = np.searchsorted(st, np.arange(p + 1))
    return {
        pid: (suk[bounds[i]:bounds[i + 1]], suv[bounds[i]:bounds[i + 1]])
        for i, pid in enumerate(pids)
    }


@functools.partial(jax.jit, static_argnames=("op", "out_cap"))
def _segment_reduce_kernel(keys, vals, op, out_cap):
    return _segment_reduce(keys, vals, op, out_cap)


def device_reduce_by_key(
    parts: Dict[int, Tuple[jax.Array, jax.Array]],
    op: str = "sum",
    devices: Optional[Sequence] = None,
    distinct_hint: Optional[int] = None,
) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
    """All-device shuffle-reduce: ``{pid: (keys, vals)}`` ->
    ``{pid: (unique_keys, reduced_vals)}`` with key-mod-P partitioning.

    When the partitions sit on P distinct devices the exchange is one
    ``lax.all_to_all`` inside a shard_map over a (P,) mesh; a shared/single
    device skips the collective (the data never needed to move).  Returns
    HOST arrays with sentinels stripped (the payload boundary).

    ``distinct_hint``: an upper bound on distinct keys per partition block
    (e.g. the vocabulary size for a word count).  It caps the post-combine
    buffer sizes -- without it every stage sizes for the worst case (all
    pairs distinct, all to one target).  Too small a hint DROPS overflow
    keys; it is a capacity promise, not a suggestion.
    """
    if op not in _OPS:
        raise ValueError(f"op must be one of {sorted(_OPS)}, got {op!r}")
    pids = sorted(parts)
    p = len(pids)
    if p == 0:
        return {}
    n_max = max(int(parts[pid][0].shape[0]) for pid in pids)
    n_max = max(n_max, 1)
    key_dt = jnp.asarray(parts[pids[0]][0]).dtype
    val_dt = jnp.asarray(parts[pids[0]][1]).dtype

    devs = []
    for pid in pids:
        k = jnp.asarray(parts[pid][0])
        devs.append(list(k.devices())[0] if hasattr(k, "devices") else None)
    distinct = len(set(devs)) == p and None not in devs

    # post-combine block size: worst case n_max, capped by the caller's
    # distinct-keys promise
    comb = n_max if distinct_hint is None else min(n_max, int(distinct_hint))
    comb = max(comb, 1)
    cap = comb  # worst case: every combined pair targets one partition
    out_cap = p * cap

    if distinct and p > 1:
        # pad local blocks to one common length so every device runs the
        # same program (static shapes)
        padded_k: List[jax.Array] = []
        padded_v: List[jax.Array] = []
        for pid in pids:
            k, v = parts[pid]
            k = jnp.asarray(k)
            v = jnp.asarray(v)
            pad = n_max - k.shape[0]
            if pad:
                k = jnp.concatenate([k, jnp.full(pad, SENTINEL, key_dt)])
                v = jnp.concatenate([v, jnp.zeros(pad, val_dt)])
            padded_k.append(k)
            padded_v.append(v)
        mesh = Mesh(np.array([d for d in devs]), ("w",))
        # lazy: ops.__init__ is imported from parallel-side modules, so a
        # top-level ops -> parallel import would be cyclic
        from asyncframework_tpu.parallel.mesh import resolve_shard_map

        @functools.partial(
            resolve_shard_map(), mesh=mesh,
            in_specs=(P("w"), P("w")), out_specs=(P("w"), P("w")),
        )
        def shuffle(k, v):
            k = k.reshape(-1)
            v = v.reshape(-1)
            ck, cv = _segment_reduce(k, v, op, comb)  # map-side combine
            bk, bv = _bucket(ck, cv, p, cap)
            rk = jax.lax.all_to_all(bk, "w", split_axis=0, concat_axis=0,
                                    tiled=True)
            rv = jax.lax.all_to_all(bv, "w", split_axis=0, concat_axis=0,
                                    tiled=True)
            ok, ov = _segment_reduce(rk.reshape(-1), rv.reshape(-1), op,
                                     out_cap)
            return ok[None, :], ov[None, :]

        # assemble the global sharded views IN PLACE: every block is already
        # on its own device, so this is metadata-only (no host round-trip)
        sharding = jax.sharding.NamedSharding(mesh, P("w"))
        gk = jax.make_array_from_single_device_arrays(
            (p, n_max), sharding, [k.reshape(1, -1) for k in padded_k]
        )
        gv = jax.make_array_from_single_device_arrays(
            (p, n_max), sharding, [v.reshape(1, -1) for v in padded_v]
        )
        ok, ov = shuffle(gk, gv)
        ok_h = np.asarray(ok)
        ov_h = np.asarray(ov)
        out = {}
        for i, pid in enumerate(pids):
            keep = ok_h[i] != SENTINEL
            out[pid] = (ok_h[i][keep], ov_h[i][keep])
        return out

    # shared-device (or host-backed) path: the blocks already live
    # together, so the whole shuffle is ONE fused sort + segment-reduce
    # over the concatenated pairs (single dispatch; round 3's
    # per-partition pipeline paid ~3 kernel launches x P, which a tunneled
    # chip turns into milliseconds of RTT each), then a tiny host split of
    # the distinct set by key mod P
    n_total = sum(int(parts[pid][0].shape[0]) for pid in pids)
    if n_total == 0:
        empty_k = np.empty(0, np.dtype(key_dt))
        empty_v = np.empty(0, np.dtype(val_dt))
        return {pid: (empty_k, empty_v) for pid in pids}
    gk = jnp.concatenate([jnp.asarray(parts[pid][0]) for pid in pids])
    gv = jnp.concatenate([jnp.asarray(parts[pid][1]) for pid in pids])
    cap_global = (n_total if distinct_hint is None
                  else min(n_total, int(distinct_hint) * p))
    ok, ov = _segment_reduce_kernel(gk, gv, op=op, out_cap=cap_global)
    ok_h = np.asarray(ok)
    ov_h = np.asarray(ov)
    keep = ok_h != SENTINEL
    uk, uv = ok_h[keep], ov_h[keep]
    t = uk % p
    return {pid: (uk[t == i], uv[t == i]) for i, pid in enumerate(pids)}
