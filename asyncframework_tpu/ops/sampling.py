"""Deterministic distributed sampling.

Parity: the reference samples mini-batches two ways --
- ``RDD.sample(false, b, seed + k + 1)`` per round (ASGD,
  ``SparkASGDThread.scala:311``): per-element Bernoulli(b) with a
  round-indexed seed;
- seeded re-sampling on workers: ``new Random(cTime)`` walked over the
  partition's rows in global index order (ASAGA,
  ``SparkASAGAThread.scala:365-369``), so the driver can reproduce exactly
  which global indices each worker drew.

TPU-native equivalent: stateless ``jax.random`` keys.  A round's mask for one
worker is a pure function of ``(root_seed, round_token, worker_id)`` -- both
driver and worker can derive it independently (the property the reference gets
from sharing ``cTime``), and it is reproducible across runs, unlike the
reference's wall-clock seed.  Masks keep shapes static for XLA.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def round_key(root_seed: int, round_token: int) -> jax.Array:
    """Key shared by all workers of one round (parity: ``Random(cTime)``)."""
    return jax.random.fold_in(jax.random.PRNGKey(root_seed), round_token)


def worker_key(root_seed: int, round_token: int, worker_id: int) -> jax.Array:
    """Per-(round, worker) key -- the driver can re-derive any worker's draw."""
    return jax.random.fold_in(round_key(root_seed, round_token), worker_id)


@functools.partial(jax.jit, static_argnums=(1,))
def bernoulli_mask(key: jax.Array, n: int, rate: float) -> jax.Array:
    """float {0,1} mask of shape (n,): per-element Bernoulli(rate).

    Parity: ``sample(false, b, seed)`` / ``r.nextDouble() < b`` filters, with
    masking instead of filtering to keep static shapes.
    """
    return jax.random.bernoulli(key, rate, (n,)).astype(jnp.float32)


def host_mask(root_seed: int, round_token: int, worker_id: int, n: int, rate: float):
    """Driver-side reproduction of a worker's mask as numpy (ASAGA parity:
    the driver pre-computing ``sampledMap`` from the shared seed)."""
    import numpy as np

    m = bernoulli_mask(worker_key(root_seed, round_token, worker_id), n, rate)
    return np.asarray(m)
