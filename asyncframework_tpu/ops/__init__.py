from asyncframework_tpu.ops import blas, gradients, sampling, collectives  # noqa: F401
