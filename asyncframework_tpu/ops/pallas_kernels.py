"""Pallas TPU kernels for the gradient hot path.

The reference's compute hot loop bottoms out in native BLAS through JNI
(``LeastSquaresGradient.compute`` -> ``BLAS.axpy/dot`` ->
``mllib-local/.../BLAS.scala:20-35`` netlib).  The TPU equivalent is mostly
*just XLA* -- the fused sample+gradient jit already runs on the MXU.  This
module is the layer below that for cases XLA's fusion does not cover:

- :func:`fused_masked_grad` -- one-pass tiled kernel for
  ``g = X^T (mask * (X w - y))``: streams X through VMEM row-tiles, keeps
  the residual entirely on-chip (never materialized in HBM), accumulates
  ``g`` in a VMEM-resident f32 block across grid steps.  This is the ASGD
  worker step's core contraction with the HBM round-trip for the
  n-vector residual removed -- exactly the kind of fusion worth hand-
  scheduling when ``n`` is millions of rows (mnist8m).
- :func:`chunk_attention` -- block attention with local softmax stats for
  the long-context path: two MXU matmuls + exp per (batch, head) program
  entirely in VMEM, returning the (o, m, l) flash triple so
  ``parallel/ring.py`` can merge ring steps with the cheap rescale
  (``ring_attention(..., block_kernel="pallas")``).
- For rcv1-style sparse data the SURVEY-prescribed alternative (densify
  per batch, then this kernel) lives in the data layer; a scatter/gather
  CSR kernel is deliberately NOT attempted -- vector gather does not map
  onto the VPU's strided units, padding to blocked-ELL densifies anyway.

All kernels run under ``interpret=True`` on CPU (tests) and compile natively
on TPU.  Tile sizes honor the f32 (8, 128) tiling constraint.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _grad_kernel(x_ref, y_ref, m_ref, w_ref, g_ref):
    """One row-tile step: r = mask*(X_t w - y_t); g += X_t^T r."""
    @pl.when(pl.program_id(0) == 0)
    def _():
        g_ref[:] = jnp.zeros_like(g_ref)

    r = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
    r = (r - y_ref[:]) * m_ref[:]
    g_ref[:] += jnp.dot(
        x_ref[:].T, r, preferred_element_type=jnp.float32
    )


@functools.partial(jax.jit, static_argnames=("row_tile", "interpret"))
def _fused_masked_grad_padded(X, y2, m2, w2, row_tile: int, interpret: bool):
    n, d = X.shape
    grid = (n // row_tile,)
    return pl.pallas_call(
        _grad_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((row_tile, d), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((d, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((d, 1), jnp.float32),
        interpret=interpret,
    )(X, y2, m2, w2)


def fused_masked_grad(
    X,
    y,
    w,
    mask: Optional[jax.Array] = None,
    row_tile: int = 256,
    interpret: bool = False,
):
    """``g = X^T (mask * (X w - y))`` in one pass over ``X``.

    ``X``: (n, d) f32; ``y``/``mask``: (n,); ``w``: (d,).  Rows and the
    feature dim are zero-padded to tile multiples internally (padded rows
    carry mask 0, padded feature columns produce zero gradient entries that
    are sliced off), so any shape is accepted.
    """
    X = jnp.asarray(X, jnp.float32)
    n, d = X.shape
    if n == 0:
        return jnp.zeros(d, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    m = (
        jnp.ones(n, jnp.float32)
        if mask is None
        else jnp.asarray(mask, jnp.float32)
    )
    # tiling constraint: row tiles must be sublane multiples (f32: 8), and
    # no larger than the row count rounded up to one
    row_tile = 8 * ((max(row_tile, 8) + 7) // 8)
    row_tile = min(row_tile, 8 * ((n + 7) // 8))
    pad_n = (-n) % row_tile
    pad_d = (-d) % 128
    if pad_n:
        X = jnp.pad(X, ((0, pad_n), (0, 0)))
        y = jnp.pad(y, (0, pad_n))
        m = jnp.pad(m, (0, pad_n))  # zero mask: padded rows contribute 0
    if pad_d:
        X = jnp.pad(X, ((0, 0), (0, pad_d)))
    w2 = jnp.pad(jnp.asarray(w, jnp.float32), (0, pad_d))[:, None]
    g = _fused_masked_grad_padded(
        X, y[:, None], m[:, None], w2, row_tile, interpret
    )
    return g[:d, 0]


def reference_masked_grad(X, y, w, mask=None):
    """Plain-XLA oracle for the fused kernel."""
    X = jnp.asarray(X, jnp.float32)
    r = X @ jnp.asarray(w, jnp.float32) - jnp.asarray(y, jnp.float32)
    if mask is not None:
        r = r * jnp.asarray(mask, jnp.float32)
    return X.T @ r


# --------------------------------------------------------------- attention
def _chunk_attn_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, m_ref, l_ref,
                       *, scale: float):
    """One (batch*head) program: block attention with LOCAL softmax stats.

    s = (q k^T) * scale masked to _NEG_BIG; emits (o = p v, m = rowmax,
    l = rowsum) so the caller can merge blocks with the standard flash
    rescale -- the kernel is the heavy part (two MXU matmuls + exp), the
    merge is cheap elementwise XLA.
    """
    s = jnp.dot(
        q_ref[0], k_ref[0].T, preferred_element_type=jnp.float32
    ) * scale
    s = jnp.where(mask_ref[:] > 0, s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)          # (Tq, 1)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)          # (Tq, 1)
    o_ref[0] = jnp.dot(p, v_ref[0], preferred_element_type=jnp.float32)
    m_ref[0] = m
    l_ref[0] = l


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret", "vma")
)
def _chunk_attn_padded(q, k, v, mask, scale: float, interpret: bool, vma):
    bh, tq, dp = q.shape
    tk = k.shape[1]
    kw = {} if vma is None else {"vma": frozenset(vma)}
    return pl.pallas_call(
        functools.partial(_chunk_attn_kernel, scale=scale),
        grid=(bh,),
        in_specs=[
            pl.BlockSpec((1, tq, dp), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, tk, dp), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, tk, dp), lambda i: (i, 0, 0)),
            pl.BlockSpec((tq, tk), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tq, dp), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, tq, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, tq, 1), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, dp), jnp.float32, **kw),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32, **kw),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32, **kw),
        ],
        interpret=interpret,
    )(q, k, v, mask)


def chunk_attention(q, k, v, mask=None, interpret: bool = False, vma=None):
    """Block attention with softmax stats: ``(o, m, l)`` per query row.

    ``q``: (B, Tq, H, D); ``k``/``v``: (B, Tk, H, D); ``mask``: (Tq, Tk)
    bool/0-1 (True = attend) or None.  Returns ``o`` (B, Tq, H, D) f32
    un-normalized, ``m``/``l`` (B, H, Tq) f32 -- exactly the running-state
    triple :func:`asyncframework_tpu.parallel.ring._block_accumulate`
    folds, so a ring step can offload its block compute to this kernel
    and keep the (cheap) rescale-merge in XLA.

    Padding: Tq/Tk to sublane multiples (8), D to the 128-lane tile.
    Padded K columns are masked out; padded D columns are zero so they
    contribute nothing; padded Q rows are sliced off.

    ``vma``: when called inside ``shard_map`` with vma checking, the mesh
    axes the outputs vary over (e.g. ``("sp",)``) -- pallas outputs must
    declare their varying-axes explicitly.
    """
    import math

    B, tq, H, D = q.shape
    tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    pad_q = (-tq) % 8
    pad_k = (-tk) % 8
    pad_d = (-D) % 128

    if mask is None:
        mask_f = jnp.ones((tq, tk), jnp.float32)
    else:
        mask_f = jnp.asarray(mask, jnp.float32)
    mask_f = jnp.pad(mask_f, ((0, pad_q), (0, pad_k)))  # padded K masked

    def to_bhd(x, pad_t):
        x = jnp.asarray(x, jnp.float32)
        x = jnp.pad(x, ((0, 0), (0, pad_t), (0, 0), (0, pad_d)))
        # (B, T, H, D) -> (B*H, T, Dp)
        return x.transpose(0, 2, 1, 3).reshape(
            B * H, x.shape[1], D + pad_d
        )

    o, m, l = _chunk_attn_padded(
        to_bhd(q, pad_q), to_bhd(k, pad_k), to_bhd(v, pad_k),
        mask_f, scale, interpret, tuple(vma) if vma else None,
    )
    o = o.reshape(B, H, tq + pad_q, D + pad_d)[:, :, :tq, :D]
    o = o.transpose(0, 2, 1, 3)                      # (B, Tq, H, D)
    m = m.reshape(B, H, tq + pad_q)[:, :, :tq]
    l = l.reshape(B, H, tq + pad_q)[:, :, :tq]
    return o, m, l
