"""Pallas TPU kernels for the gradient hot path.

The reference's compute hot loop bottoms out in native BLAS through JNI
(``LeastSquaresGradient.compute`` -> ``BLAS.axpy/dot`` ->
``mllib-local/.../BLAS.scala:20-35`` netlib).  The TPU equivalent is mostly
*just XLA* -- the fused sample+gradient jit already runs on the MXU.  This
module is the layer below that for cases XLA's fusion does not cover:

- :func:`fused_masked_grad` -- one-pass tiled kernel for
  ``g = X^T (mask * (X w - y))``: streams X through VMEM row-tiles, keeps
  the residual entirely on-chip (never materialized in HBM), accumulates
  ``g`` in a VMEM-resident f32 block across grid steps.  This is the ASGD
  worker step's core contraction with the HBM round-trip for the
  n-vector residual removed -- exactly the kind of fusion worth hand-
  scheduling when ``n`` is millions of rows (mnist8m).
- For rcv1-style sparse data the SURVEY-prescribed alternative (densify
  per batch, then this kernel) lives in the data layer; a scatter/gather
  CSR kernel is deliberately NOT attempted -- vector gather does not map
  onto the VPU's strided units, padding to blocked-ELL densifies anyway.

All kernels run under ``interpret=True`` on CPU (tests) and compile natively
on TPU.  Tile sizes honor the f32 (8, 128) tiling constraint.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _grad_kernel(x_ref, y_ref, m_ref, w_ref, g_ref):
    """One row-tile step: r = mask*(X_t w - y_t); g += X_t^T r."""
    @pl.when(pl.program_id(0) == 0)
    def _():
        g_ref[:] = jnp.zeros_like(g_ref)

    r = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
    r = (r - y_ref[:]) * m_ref[:]
    g_ref[:] += jnp.dot(
        x_ref[:].T, r, preferred_element_type=jnp.float32
    )


@functools.partial(jax.jit, static_argnames=("row_tile", "interpret"))
def _fused_masked_grad_padded(X, y2, m2, w2, row_tile: int, interpret: bool):
    n, d = X.shape
    grid = (n // row_tile,)
    return pl.pallas_call(
        _grad_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((row_tile, d), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((d, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((d, 1), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((d, 1), jnp.float32),
        interpret=interpret,
    )(X, y2, m2, w2)


def fused_masked_grad(
    X,
    y,
    w,
    mask: Optional[jax.Array] = None,
    row_tile: int = 256,
    interpret: bool = False,
):
    """``g = X^T (mask * (X w - y))`` in one pass over ``X``.

    ``X``: (n, d) f32; ``y``/``mask``: (n,); ``w``: (d,).  Rows and the
    feature dim are zero-padded to tile multiples internally (padded rows
    carry mask 0, padded feature columns produce zero gradient entries that
    are sliced off), so any shape is accepted.
    """
    X = jnp.asarray(X, jnp.float32)
    n, d = X.shape
    if n == 0:
        return jnp.zeros(d, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    m = (
        jnp.ones(n, jnp.float32)
        if mask is None
        else jnp.asarray(mask, jnp.float32)
    )
    # tiling constraint: row tiles must be sublane multiples (f32: 8), and
    # no larger than the row count rounded up to one
    row_tile = 8 * ((max(row_tile, 8) + 7) // 8)
    row_tile = min(row_tile, 8 * ((n + 7) // 8))
    pad_n = (-n) % row_tile
    pad_d = (-d) % 128
    if pad_n:
        X = jnp.pad(X, ((0, pad_n), (0, 0)))
        y = jnp.pad(y, (0, pad_n))
        m = jnp.pad(m, (0, pad_n))  # zero mask: padded rows contribute 0
    if pad_d:
        X = jnp.pad(X, ((0, 0), (0, pad_d)))
    w2 = jnp.pad(jnp.asarray(w, jnp.float32), (0, pad_d))[:, None]
    g = _fused_masked_grad_padded(
        X, y[:, None], m[:, None], w2, row_tile, interpret
    )
    return g[:d, 0]


def reference_masked_grad(X, y, w, mask=None):
    """Plain-XLA oracle for the fused kernel."""
    X = jnp.asarray(X, jnp.float32)
    r = X @ jnp.asarray(w, jnp.float32) - jnp.asarray(y, jnp.float32)
    if mask is not None:
        r = r * jnp.asarray(mask, jnp.float32)
    return X.T @ r
