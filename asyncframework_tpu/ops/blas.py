"""Level-1 BLAS surface used by the driver-side updater hot loop.

Parity: ``mllib/.../BLASUtil.scala:6-19`` re-exports mllib's private
``BLAS.{axpy,dot,scal}`` as ``axpyOp``/``dotOp``/``scalOp`` returning the
mutated vector; those bottom out in netlib JNI (the reference's native math
substrate, ``mllib-local/.../BLAS.scala:20-35``).

On the TPU build the *worker* math is XLA (see :mod:`ops.gradients`); the
*updater* runs on the host against a small dense ``w`` (<= ~47k dims for the
reference workloads), where numpy's C loops are the right tool.  These helpers
mutate in place exactly like the reference ops so the updater is a true
in-place axpy loop, and also accept jax arrays (returning new arrays, since
jax values are immutable) so the same solver code can run fully on-device.
"""

from __future__ import annotations

import numpy as np


def _axpy_numpy(a: float, x: np.ndarray, y: np.ndarray) -> np.ndarray:
    if a == 1.0:
        np.add(y, x, out=y)
    else:
        # y += a*x without an extra temporary beyond the scaled buffer
        y += np.multiply(x, a)
    return y


def axpy_op(a: float, x, y):
    """Parity alias for ``BLASUtil.axpyOp`` -- y := a*x + y, returned.

    Mutates ``y`` in place when it is a writable numpy buffer (the updater's
    host-owned ``w``); falls back to out-of-place for read-only views -- e.g.
    ``np.asarray(jax_array)`` exposes the device-to-host buffer read-only.
    """
    if isinstance(y, np.ndarray):
        if not y.flags.writeable:
            return y + np.multiply(x, a)
        return _axpy_numpy(float(a), np.asarray(x), y)
    return y + a * x


def dot_op(x, y) -> float:
    """Parity alias for ``BLASUtil.dotOp`` -- always a Python float (forces a
    device sync on jax inputs, like the reference's blocking driver-side dot)."""
    if isinstance(x, np.ndarray) or isinstance(y, np.ndarray):
        return float(np.dot(np.asarray(x), np.asarray(y)))
    import jax.numpy as jnp

    return float(jnp.dot(x, y))


def scal_op(a: float, x):
    """Parity alias for ``BLASUtil.scalOp`` -- x := a*x, returned.

    In place for writable numpy buffers, out-of-place otherwise (device
    results surfaced via ``np.asarray`` are read-only views).
    """
    if isinstance(x, np.ndarray):
        if not x.flags.writeable:
            return x * a
        x *= a
        return x
    return x * a
