"""Loss gradients as jitted XLA computations (the worker hot path).

Parity targets (semantics only; the implementation is batched XLA, not
per-sample JNI BLAS):

- Least-squares ``gradfun`` of the ASYNC drivers
  (``ASYNCsamples/.../SparkASGDThread.scala:420-435``):
  per sample, ``grad = (x . w - y) * x``; a partition's task result is the
  *sum* of sampled per-sample gradients (the drivers' ``comOp`` is vector add).
- MLlib ``LeastSquaresGradient`` / ``LogisticGradient``
  (``mllib/.../optimization/Gradient.scala:285,166``).
- ASAGA per-sample scalar form (``SparkASAGAThread.scala:500-515``): for least
  squares the gradient is ``scalar * x`` with ``scalar = x . w - y``, so the
  history table stores one scalar per sample.

TPU mapping: a whole shard's sampled mini-batch gradient is two matmuls --
``r = X @ w - y`` then ``g = X^T @ (mask * r)`` -- which XLA fuses and tiles
onto the MXU.  Sampling is a Bernoulli *mask* (static shapes; no dynamic
gather), so a "sampled subset" costs one elementwise multiply instead of a
shape-changing filter.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp


def mm_f32(A: jax.Array, v: jax.Array) -> jax.Array:
    """Matmul in ``A``'s storage dtype with f32 accumulation.

    The bf16 data path: shards stored bfloat16 hit the MXU at native rate
    while partial sums accumulate in float32 (``preferred_element_type``) --
    the standard mixed-precision recipe.  For f32 ``A`` this is exactly the
    plain matmul, so every gradient below is dtype-polymorphic over the
    shard's storage dtype; ``w``/``y``/gradients stay f32 throughout.
    Casting ``v`` down to ``A.dtype`` (rather than promoting ``A`` up) is
    what keeps an (n, d) bf16 shard from being materialized in f32.
    """
    return jnp.matmul(A, v.astype(A.dtype), preferred_element_type=jnp.float32)


@jax.jit
def least_squares_residual(X: jax.Array, y: jax.Array, w: jax.Array) -> jax.Array:
    """Per-sample scalar ``x_i . w - y_i`` (the ASAGA 'scalar' form)."""
    return mm_f32(X, w) - y


@jax.jit
def least_squares_grad_sum(
    X: jax.Array, y: jax.Array, w: jax.Array, mask: jax.Array
) -> jax.Array:
    """Sum over masked samples of ``(x_i . w - y_i) x_i``.

    ``mask`` is {0,1} (or weights) of shape ``(n,)``; equivalent to the
    reference's sample-then-map-then-reduce with vector-add comOp.
    """
    r = mm_f32(X, w) - y
    return mm_f32(X.T, mask * r)


@jax.jit
def least_squares_loss(X: jax.Array, y: jax.Array, w: jax.Array) -> jax.Array:
    """Mean squared error over the shard: sum_i (x_i.w - y_i)^2 (unnormalized).

    The drivers print ``sum_i (x_i.w - y_i)^2 / N`` per trajectory snapshot
    (``SparkASGDThread.scala:386-401``); normalization by N happens at the
    caller, which knows the global N.
    """
    r = mm_f32(X, w) - y
    return jnp.sum(r * r)


@jax.jit
def logistic_grad_sum(
    X: jax.Array, y: jax.Array, w: jax.Array, mask: jax.Array
) -> jax.Array:
    """Sum over masked samples of the logistic-loss gradient.

    Parity: ``LogisticGradient`` (binary case) -- labels in {0,1};
    ``grad_i = (sigmoid(x_i.w) - y_i) x_i``.
    """
    margin = mm_f32(X, w)
    p = jax.nn.sigmoid(margin)
    return mm_f32(X.T, mask * (p - y))


@jax.jit
def logistic_loss(X: jax.Array, y: jax.Array, w: jax.Array) -> jax.Array:
    """Unnormalized logistic loss, numerically stable log1p(exp(.)) form."""
    margin = mm_f32(X, w)
    # log(1+e^m) - y*m, stable for both signs of margin
    return jnp.sum(jnp.logaddexp(0.0, margin) - y * margin)


@jax.jit
def saga_shard_step(
    X: jax.Array,
    y: jax.Array,
    w: jax.Array,
    alpha: jax.Array,
    mask: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """One ASAGA worker computation over a shard.

    Returns ``(g, diff)`` where ``diff_i = x_i.w - y_i`` are the *candidate*
    new history scalars and
    ``g = sum_i mask_i * (diff_i - alpha_i) * x_i``
    is the history-corrected gradient contribution (parity with the worker map
    in ``SparkASAGAThread.scala:369-380``: ``gradfun`` minus
    ``scalar_hist * x`` summed by ``ASYNCaggregate``'s vector-add).

    The history ``alpha`` slice stays in device HBM; committing
    ``alpha[i] <- diff_i`` for masked i is a separate op
    (:func:`saga_commit_history`) issued by the updater only for *accepted*
    (non-stale) results -- the reference's driver-side ScalarMap merge.
    """
    diff = mm_f32(X, w) - y
    g = mm_f32(X.T, mask * (diff - alpha))
    return g, diff


# ------------------------------------------------------------------ sparse
# rcv1-class data in padded-ELL form (data/sparse.py): cols/vals are
# (n, K) with zero padding; w stays dense (the PS applies dense updates).

@jax.jit
def sparse_residual(
    cols: jax.Array, vals: jax.Array, y: jax.Array, w: jax.Array
) -> jax.Array:
    """Per-sample ``x_i . w - y_i`` via gather: padding contributes 0."""
    return jnp.sum(vals * w[cols], axis=1) - y


def make_sparse_grad_sum(d: int):
    """jit (cols, vals, coeff) -> dense (d,) gradient via SORTED scatter-add.

    ``g = sum_i coeff_i * x_i`` -- the sparse analog of ``X.T @ coeff``.
    The updates are sorted by destination column first: TPU XLA executes an
    unsorted colliding scatter nearly serially, while a bitonic argsort +
    ``indices_are_sorted=True`` scatter runs vectorized (measured on v5e at
    rcv1's compacted shape, 349k updates into d=47,236: ~110 ms unsorted ->
    ~5 ms sorted, ~20x).
    """

    @jax.jit
    def grad_sum(cols, vals, coeff):
        contrib = (vals * coeff[:, None]).ravel()
        flat = cols.ravel()
        order = jnp.argsort(flat)
        return jnp.zeros(d, vals.dtype).at[flat[order]].add(
            contrib[order], indices_are_sorted=True, mode="drop"
        )

    return grad_sum


@functools.partial(jax.jit, donate_argnums=(1,))
def saga_commit_history(
    alpha: jax.Array, diff: jax.Array, mask: jax.Array
) -> jax.Array:
    """alpha[i] <- diff[i] where mask_i else unchanged (accepted update).

    ``diff`` (the worker's candidate scalars) is donated -- it is dead after
    the commit, and the new table slice is written into its buffer.  ``alpha``
    is NOT donated: an in-flight worker task dispatched before this commit may
    still hold the old slice's handle (routine under async overlap).
    """
    return jnp.where(mask > 0, diff, alpha)
