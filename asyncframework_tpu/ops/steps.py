"""Fused on-device step functions for the async parameter server.

The profiling reality of TPU hot paths (and the design rule that follows):
compute dispatch costs microseconds, but any *blocking* host<->device transfer
costs the interconnect round-trip.  So the whole per-update cycle --
mask sampling, gradient, tau-accepted model update, SAGA history commit --
stays on device; the host threads shuttle only opaque array *handles* and
integer metadata.  JAX array immutability gives model/history versioning for
free: every update produces a new handle, and an old handle IS an old version
(the ``ASYNCbroadcast`` stale-read capability with zero copies).

Parity notes per builder:
- ``make_asgd_worker_step``: the per-round sample+gradient task
  (``SparkASGDThread.scala:311-318``): Bernoulli(b) mask + summed
  least-squares gradient.  The PRNG key is a device-resident chain split
  inside the step (no per-call host->device seed transfer).
- ``make_asgd_apply``: the updater's accept path
  (``SparkASGDThread.scala:185-189``): ``w -= gamma/sqrt(k/numPart+1) *
  g/(b*N/numPart)`` with the iteration counter ``k`` ALSO device-resident.
- ``make_sync_apply``: the sync drain's update (``SparkASGDSync.scala:267-272``):
  ``w -= gamma/sqrt(k+1) * accGrad/(b*N)``.
- ``make_saga_worker_step`` / ``make_saga_apply`` / ``saga_commit_history``:
  the ASAGA decomposition (``SparkASAGAThread.scala:199-213,369-380``) with
  the per-sample scalar history table resident in HBM, sharded by worker.
- ``make_trajectory_loss_eval``: the drivers' final one-pass objective
  evaluation over all snapshots (``SparkASGDThread.scala:386-401``) -- all
  snapshots stacked into one (S, d) matrix so a shard's whole trajectory
  costs a single matmul.
"""

from __future__ import annotations

import functools
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from asyncframework_tpu.metrics import profiler as _prof
from asyncframework_tpu.ops.gradients import (
    least_squares_grad_sum,
    least_squares_residual,
    logistic_grad_sum,
    mm_f32,
    saga_commit_history,  # re-exported: the solvers' committed-history op
)


# ---------------------------------------------------------------- builders
def make_pipelined_transfer(device) -> Tuple[Callable, Callable]:
    """``(stage, readback)`` -- the two host<->device overlap points of
    the pipelined DCN worker loop (``parallel/ps_dcn.py``,
    ``async.pipeline.depth`` >= 1).

    ``device`` may be a single ``jax.Device`` or any ``Sharding`` --
    the mesh worker path passes ``replicated_sharding(mesh)`` so the
    staged put replicates the pulled model over every mesh device (P
    transfer-engine copies behind the same double buffer).

    ``stage(w_host)`` puts the NEXT model version on the device.  It is
    called on the prefetch thread the moment the pull reply decodes, and
    ``jax.device_put`` dispatches asynchronously -- so the host->device
    copy of model v(k+1) rides the transfer engine while step k's compute
    is still running (double buffering: two model versions briefly live
    on device; the old one is dropped when the loop advances).

    ``readback(g)`` completes a gradient's device->host copy (blocking
    ``np.asarray``).  In the pipelined loop the push that follows it is
    a bare windowed send -- the ACK wait that serialized the serial
    loop's readback -> push -> pull chain is a separate reaper thread's
    problem.
    """

    def stage(w_host: np.ndarray):
        return jax.device_put(w_host, device)

    def readback(g) -> np.ndarray:
        return np.asarray(g)

    return stage, readback


def make_asgd_worker_step(batch_rate: float, loss: str = "least_squares"):
    """jit (X, y, w, key) -> (g_sum, new_key); mask drawn on device.

    For ``batch_rate <= 0.5`` the sampled rows are **compacted** first
    (``jnp.nonzero(size=...)`` -- static capacity = E[count] + 6 sigma, see
    :func:`sparse_step_capacity`): the two matmuls then touch only ~b of
    the shard instead of streaming all of it through a mask.  The full-shard
    step is HBM-bandwidth-bound (an mnist8m shard is 1.6 GB bf16 read twice
    per task), so at b=0.1 compaction cuts per-task traffic ~5x.  The
    gradient is the reference's sampled-sum exactly, up to the vanishing
    (~1e-9/step) chance of the draw exceeding capacity, where the excess
    rows are dropped for that step.
    """
    if loss == "least_squares":
        grad_sum = least_squares_grad_sum
    elif loss == "logistic":
        grad_sum = logistic_grad_sum
    else:
        raise ValueError(f"unknown loss {loss!r}")

    if batch_rate > 0.5:
        # dense sampling: masking the full shard moves less data than a
        # near-full gather copy would
        @jax.jit
        def step(X, y, w, key):
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(
                sub, batch_rate, (X.shape[0],)
            ).astype(jnp.float32)
            return grad_sum(X, y, w, mask), key

        return step

    @jax.jit
    def step(X, y, w, key):
        n_rows = X.shape[0]  # static at trace time
        cap = sparse_step_capacity(batch_rate, n_rows)
        key, sub = jax.random.split(key)
        mask = jax.random.bernoulli(sub, batch_rate, (n_rows,))
        (idx,) = jnp.nonzero(mask, size=cap, fill_value=0)
        valid = (jnp.arange(cap) < jnp.sum(mask)).astype(jnp.float32)
        Xs = X[idx]
        return grad_sum(Xs, y[idx], w, valid), key

    return _prof.wrap_dispatch(step, "kernel.dispatch", "asgd_worker_step")


def make_asgd_apply(gamma: float, batch_rate: float, n: int, num_workers: int):
    """jit (w, g, k) -> (w', k+1).  ``k`` is a device f32 scalar.

    Buffer donation: ``g`` and ``k`` are donated -- XLA writes ``w'`` into the
    dead gradient's buffer, so the accept path allocates nothing at steady
    state.  ``w`` itself is NOT donated: an old ``w`` handle IS an old model
    version (in-flight workers and trajectory snapshots hold them), and
    donating it would invalidate every retained version.
    """
    par_recs = batch_rate * n / num_workers

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def apply(w, g, k):
        lr = gamma / jnp.sqrt(k / num_workers + 1.0)
        return w - (lr / par_recs) * g, k + 1.0

    return _prof.wrap_dispatch(apply, "kernel.dispatch", "asgd_apply")


def make_sync_apply(gamma: float, batch_rate: float, n: int):
    """jit (w, acc_g, k) -> (w', k+1) -- full-drain synchronous update.

    ``acc_g`` and ``k`` are donated (dead after the round); ``w`` is kept
    alive for snapshots -- see :func:`make_asgd_apply`.
    """

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def apply(w, acc_g, k):
        lr = gamma / jnp.sqrt(k + 1.0)
        return w - (lr / (batch_rate * n)) * acc_g, k + 1.0

    return _prof.wrap_dispatch(apply, "kernel.dispatch", "sync_apply")


def make_saga_worker_step(batch_rate: float):
    """jit (X, y, w, alpha, key) -> (g, diff, mask, new_key).

    ``g = X^T (mask * (diff - alpha))`` is the history-corrected gradient sum;
    ``diff`` are candidate new history scalars (committed only on accept).
    """

    @jax.jit
    def step(X, y, w, alpha, key):
        key, sub = jax.random.split(key)
        mask = jax.random.bernoulli(sub, batch_rate, (X.shape[0],)).astype(
            jnp.float32
        )
        diff = least_squares_residual(X, y, w)
        g = mm_f32(X.T, mask * (diff - alpha))
        return g, diff, mask, key

    return _prof.wrap_dispatch(step, "kernel.dispatch", "saga_worker_step")


def make_saga_apply(
    gamma: float,
    batch_rate: float,
    n: int,
    num_workers: int,
    donate_g: bool = True,
):
    """jit (w, alpha_bar, g, delta) -> (w', alpha_bar').

    ``w' = w - gamma*g/parRecs - gamma*alpha_bar``;
    ``alpha_bar' = alpha_bar + delta/N`` (``SparkASAGAThread.scala:210-213``
    uses ``delta == g``; see :func:`make_saga_table_delta` for why the TPU
    build distinguishes them).

    Donation: ``alpha_bar`` is always donated (its old value is never
    retained).  ``g`` is donated only when ``donate_g`` -- the sync drain
    passes the SAME accumulator buffer as both ``g`` and ``delta``, and a
    buffer may not be donated while also read through another argument, so
    the sync instance sets ``donate_g=False``.  ``w`` is never donated (old
    handles are live model versions).
    """
    par_recs = batch_rate * n / num_workers
    donate = (1, 2) if donate_g else (1,)

    @functools.partial(jax.jit, donate_argnums=donate)
    def apply(w, alpha_bar, g, delta):
        w2 = w - (gamma / par_recs) * g - gamma * alpha_bar
        ab2 = alpha_bar + delta / n
        return w2, ab2

    return _prof.wrap_dispatch(apply, "kernel.dispatch", "saga_apply")


def make_saga_table_delta():
    """jit (X, diff, mask, alpha_cur) -> X^T (mask * (diff - alpha_cur)).

    The exact change the commit makes to the mean history gradient.  The
    reference advances ``alphaBar`` by the *worker-computed* ``g``, which was
    built against the history as of dispatch time; when a worker is
    re-dispatched before the updater committed its previous result (routine
    here -- device turnaround is microseconds), ``alphaBar`` then drifts away
    from the table's true mean and constant-step ASAGA destabilizes over long
    runs (measured: diverges after ~500 accepted updates at overlap 0.5).
    Recomputing the delta against the *current* table slice at commit time
    keeps the ``alpha_bar == mean(table)`` invariant exact at the cost of one
    extra matvec per accepted update.
    """

    @jax.jit
    def delta(X, diff, mask, alpha_cur):
        return X.T @ (mask * (diff - alpha_cur))

    return delta


def make_asgd_apply_batch(
    gamma: float, batch_rate: float, n: int, num_workers: int, m: int
):
    """jit (w, G (m, d), mask (m,), k) -> (w', k') -- ``m`` queued gradients
    applied in ONE dispatch.

    Exactness: the sequential accept path is ``w <- w - c_j g_j`` with step
    sizes ``c_j = (gamma / sqrt(k_j/P + 1)) / parRecs`` that do not depend on
    ``w``, so a drained batch folds into one masked weighted sum --
    numerically the same model (up to float addition order) at 1/m the
    dispatch cost.  The reference drains its whole queue per updater wake for
    the same reason (``SparkASGDThread.scala:154-158``); here the drain is
    also one device op.  ``mask`` marks accepted entries (stale slots are 0);
    ``k`` advances by the number accepted.
    """
    par_recs = batch_rate * n / num_workers

    # only k is donated: no output matches G/mask shapes, so donating them
    # would just emit unusable-buffer warnings
    @functools.partial(jax.jit, donate_argnums=(3,))
    def apply_batch(w, G, mask, k):
        accepted_before = jnp.cumsum(mask) - mask  # per-slot accepted count
        kk = k + accepted_before
        lr = gamma / jnp.sqrt(kk / num_workers + 1.0)
        coeff = (lr / par_recs) * mask
        return w - coeff @ G, k + jnp.sum(mask)

    del m  # shape is carried by G itself; kept in the signature for intent
    return _prof.wrap_dispatch(apply_batch, "kernel.dispatch", "asgd_apply_batch")


def make_asgd_apply_merge(
    gamma: float, batch_rate: float, n: int, num_workers: int,
    donate_model: bool = False,
):
    """jit (w, G (m, d), mask (m,), k) -> (w', k') -- ``m`` coalesced PUSH
    gradients applied in ONE device dispatch, **bit-identical** to running
    :func:`make_asgd_apply` serially over the masked slots.

    Unlike :func:`make_asgd_apply_batch` (the in-process updater's masked
    weighted sum, exact only up to float addition order), this folds the
    slots through a ``lax.scan`` whose body is the serial apply expression
    verbatim -- same per-element operation sequence, so the DCN merge
    queue's fused apply can be asserted equal to the serial path bit for
    bit.  One compile per (m, d) shape; the PS pads short batches to its
    merge bound so only one shape ever exists.

    ``donate_model=True`` additionally donates ``w``: XLA writes ``w'``
    into the dead input's buffer, so a steady-state drain allocates
    NOTHING (donation changes aliasing only, never values -- asserted
    bit-identical to the undonated kernel in tests/test_meshgrad.py).
    The caller owns the lifetime discipline: every retained copy of the
    model (snapshot stack, checkpoint capture, published pull snapshots)
    must be a HOST copy taken before the next donated apply, because the
    old device handle dies at dispatch -- see ``ParameterServer``'s
    drain, which only routes a drain through the donated kernel when the
    outgoing version is already host-published.

    Delay-adaptive damping (``parallel/controller.py``): a mask slot is
    the per-item step-DAMP factor, not just a keep bit -- 0 skips the
    slot exactly as before, 1.0 is the undamped apply (``1.0 * x`` is
    exact in f32, so the legacy path stays bit-identical), and a
    controller-damped push carries its bounded ``1/(1+tau)``-family
    factor here, scaling that item's effective step with no change to
    the clock/accept semantics (``k`` still advances by 1 per kept
    slot).  :func:`make_asgd_apply_damped` is the serial twin with the
    SAME expression, so the fused and serial paths agree bit for bit at
    every damp value.
    """
    par_recs = batch_rate * n / num_workers

    @functools.partial(
        jax.jit, donate_argnums=(0, 3) if donate_model else (3,)
    )
    def apply_merge(w, G, mask, k):
        def body(carry, xs):
            w, k = carry
            g, a = xs
            lr = gamma / jnp.sqrt(k / num_workers + 1.0)
            w2 = w - (a * (lr / par_recs)) * g
            keep = a > 0
            return (jnp.where(keep, w2, w), jnp.where(keep, k + 1.0, k)), None

        (w, k), _ = jax.lax.scan(body, (w, k), (G, mask))
        return w, k

    return _prof.wrap_dispatch(apply_merge, "kernel.dispatch", "asgd_apply_merge")


def make_asgd_apply_damped(gamma: float, batch_rate: float, n: int,
                           num_workers: int):
    """jit (w, g, k, a) -> (w', k+1): :func:`make_asgd_apply` with a
    per-call step-DAMP scalar ``a`` (delay-adaptive step sizes per
    arXiv:1601.04033, actuated by ``parallel/controller.py``).

    The expression is VERBATIM the damped merge-kernel body
    (``w - (a * (lr/par_recs)) * g``), so the serial one-dispatch path
    and the fused drain produce bit-identical models at every damp
    value -- and at ``a == 1.0`` bit-identical to the undamped
    :func:`make_asgd_apply` (multiplication by 1.0 is exact in f32).
    Same donation discipline: ``g`` and ``k`` die here, ``w`` is a live
    model version.
    """
    par_recs = batch_rate * n / num_workers

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def apply(w, g, k, a):
        lr = gamma / jnp.sqrt(k / num_workers + 1.0)
        return w - (a * (lr / par_recs)) * g, k + 1.0

    return _prof.wrap_dispatch(apply, "kernel.dispatch", "asgd_apply_damped")


def make_saga_apply_merge(
    gamma: float, batch_rate: float, n: int, num_workers: int,
    donate_model: bool = False,
):
    """jit (w, alpha_bar, G (m, d), mask (m,)) -> (w', alpha_bar') -- the
    ASAGA face of the merge-queue fused apply (``delta == g`` over DCN,
    see ``ParameterServer.__init__``), scanning the serial
    :func:`make_saga_apply` expression over the masked slots so the fused
    result is bit-identical to the one-dispatch-per-push path.

    ``donate_model=True`` donates ``w`` alongside the always-donated
    ``alpha_bar`` -- same zero-allocation drain and same caller-side
    lifetime discipline as :func:`make_asgd_apply_merge`.
    """
    par_recs = batch_rate * n / num_workers

    @functools.partial(
        jax.jit, donate_argnums=(0, 1) if donate_model else (1,)
    )
    def apply_merge(w, alpha_bar, G, mask):
        def body(carry, xs):
            w, ab = carry
            g, a = xs
            w2 = w - (gamma / par_recs) * g - gamma * ab
            ab2 = ab + g / n
            keep = a > 0
            return (jnp.where(keep, w2, w), jnp.where(keep, ab2, ab)), None

        (w, alpha_bar), _ = jax.lax.scan(body, (w, alpha_bar), (G, mask))
        return w, alpha_bar

    return _prof.wrap_dispatch(apply_merge, "kernel.dispatch", "saga_apply_merge")


# ------------------------------------------------------------- mesh steps
# Multi-chip worker compute plane (ISSUE 11 / ROADMAP item 1): a DCN
# worker whose host has N chips computes its mini-batch gradient
# batch-parallel over a local ``dp`` mesh (parallel/mesh.py::make_mesh)
# instead of on one device.  Decomposition per arXiv:1505.04956
# (Hogwild-style data parallelism): each device holds a static row block
# of the worker's shard (placed ONCE via pad_and_shard, resident in HBM
# for the whole run), computes the partial gradient of its rows, and a
# ``lax.psum`` over ``dp`` reduces the partials locally -- the worker
# still emits ONE fused gradient per step, so the PS wire protocol is
# untouched (one PUSH per cohort member, same payload shape).


def make_mesh_asgd_worker_step(
    batch_rate: float, mesh, loss: str = "least_squares", axis: str = "dp"
):
    """jit (Xs, ys, valid, w, key) -> (g_sum, new_key) over a ``dp`` mesh.

    ``Xs``/``ys``/``valid`` are the pad_and_shard placements of the
    worker's shard (rows split over ``axis``); ``w`` and ``key`` are
    replicated.  Sampling is device-count-invariant: every device draws
    the IDENTICAL full-length Bernoulli mask (replicated subkey, global
    padded shape) and slices its own row block, so the sampled row set
    is a function of (key, padded length) alone, not of how many chips
    the worker happens to have.  On an unpadded shard the draw is
    bit-identical to :func:`make_asgd_worker_step`'s dense mask.

    The per-device partial is the same masked ``grad_sum`` the
    single-device step runs on its rows; ``lax.psum`` folds the partials
    (on this rig's CPU backend the all-reduce is a sequential
    device-order fold -- the oracle tests/test_meshgrad.py pins bit-for-
    bit).  The mesh path always uses the masked full-block compute: the
    single-device step's sparse-compaction shortcut would need a
    per-device capacity draw and buys nothing once the rows are already
    split P ways.
    """
    if loss == "least_squares":
        grad_sum = least_squares_grad_sum
    elif loss == "logistic":
        grad_sum = logistic_grad_sum
    else:
        raise ValueError(f"unknown loss {loss!r}")
    from jax.sharding import PartitionSpec as P

    from asyncframework_tpu.parallel.mesh import resolve_shard_map

    n_dev = mesh.shape[axis]

    @functools.partial(
        resolve_shard_map(),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(None), P(None)),
        out_specs=(P(None), P(None)),
    )
    def _step(Xl, yl, vl, w, key):
        key2, sub = jax.random.split(key)
        n_l = Xl.shape[0]  # static local block length
        p = jax.lax.axis_index(axis)
        # replicated full-length draw, then slice my block: the mask is
        # identical on every device and invariant to the mesh size
        mask_full = jax.random.bernoulli(sub, batch_rate, (n_l * n_dev,))
        ml = jax.lax.dynamic_slice_in_dim(
            mask_full.astype(jnp.float32), p * n_l, n_l
        ) * vl
        g_local = grad_sum(Xl, yl, w, ml)
        return jax.lax.psum(g_local, axis), key2

    return jax.jit(_step)


def make_mesh_saga_dcn_worker_step(mesh, axis: str = "dp"):
    """jit (Xs, ys, w, idx, alpha_sel, n_valid) -> (g, diff_sel) -- the
    mesh face of :func:`make_saga_dcn_worker_step`.

    The PS samples row ids ``idx`` into the worker's shard and ships the
    current history scalars ``alpha_sel`` with the model (both
    replicated); the shard's rows live row-sharded over ``axis``.  Each
    sampled slot is OWNED by exactly one device (the one holding that
    row): the owner gathers its row locally, computes the candidate
    scalar ``diff_j = x_j . w - y_j`` and the slot's gradient
    contribution ``(diff_j - alpha_j) x_j``; non-owners contribute exact
    zeros.  Two psums assemble the full (cap,) candidate vector and the
    fused (d,) gradient -- the same values the single-device step
    produces, decomposed by row ownership.
    """
    from jax.sharding import PartitionSpec as P

    from asyncframework_tpu.parallel.mesh import resolve_shard_map

    @functools.partial(
        resolve_shard_map(),
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(None), P(None), P(None), P()),
        out_specs=(P(None), P(None)),
    )
    def _step(Xl, yl, w, idx, alpha_sel, n_valid):
        cap = idx.shape[0]
        n_l = Xl.shape[0]
        p = jax.lax.axis_index(axis)
        valid = jnp.arange(cap) < n_valid
        local = idx - p * n_l
        mine = valid & (local >= 0) & (local < n_l)
        li = jnp.clip(local, 0, n_l - 1)
        vm = mine.astype(jnp.float32)
        Xs_ = Xl[li]  # (cap, d) LOCAL gather -- only my rows are real
        diff_l = (mm_f32(Xs_, w) - yl[li]) * vm
        g_l = mm_f32(Xs_.T, (diff_l - alpha_sel) * vm)
        # each slot has exactly one owner: the psums add zeros to the
        # owner's value (slot-exact) and fold the per-device gradient
        # partials (device-order, like the ASGD mesh step)
        g, diff = jax.lax.psum((g_l, diff_l), axis)
        return g, diff

    return jax.jit(_step)


# ------------------------------------------------------------------ sparse
def sparse_step_capacity(batch_rate: float, n_rows: int) -> int:
    """Static slot count for the compacted sparse step: E[count] + 6 sigma
    of the Bernoulli draw, lane-rounded and capped at the shard size.
    Overflow probability per step is ~1e-9; overflowing rows are dropped
    (the sample is fractionally smaller that step, nothing corrupts).
    """
    import math

    mean = batch_rate * n_rows
    sigma = math.sqrt(max(batch_rate * (1.0 - batch_rate) * n_rows, 0.0))
    cap = int(math.ceil(mean + 6.0 * sigma))
    cap = max(8, ((cap + 7) // 8) * 8)
    return min(cap, n_rows)


def _sparse_compacted_gradient(cols, vals, y, w, sub, batch_rate, grad_sum):
    """Shared core of the compacted sparse least-squares step: Bernoulli(b)
    sample packed to static capacity, only those rows gathered/scattered.
    ONE definition, used by the engine worker step AND the fused rounds --
    the fused path's sampling-parity claim depends on these staying
    bit-identical."""
    n_rows = y.shape[0]  # static at trace time
    cap = sparse_step_capacity(batch_rate, n_rows)
    mask = jax.random.bernoulli(sub, batch_rate, (n_rows,))
    (idx,) = jnp.nonzero(mask, size=cap, fill_value=0)
    valid = (jnp.arange(cap) < jnp.sum(mask)).astype(vals.dtype)
    c_sel = cols[idx]
    v_sel = vals[idx] * valid[:, None]  # unfilled slots contribute 0
    r = jnp.sum(v_sel * w[c_sel], axis=1) - y[idx] * valid
    return grad_sum(c_sel, v_sel, r)


def make_sparse_asgd_worker_step(batch_rate: float, d: int):
    """jit (cols, vals, y, w, key) -> (g_sum (d,), new_key).

    The sparse analog of :func:`make_asgd_worker_step` for padded-ELL shards
    (rcv1-class data), with **masked-row compaction**: a Bernoulli(b) sample
    touches only ~b of the shard's rows, so gathering/scattering the FULL
    (n_p, K) arrays wastes (1-b) of the memory traffic (measured on v5e:
    ~47 ms gather + ~47 ms scatter at 87k x 80, dominated by padded volume,
    not useful work).  Instead the sampled row ids are compacted into a
    static-capacity index vector (``jnp.nonzero(size=...)`` -- static
    shapes, jit-stable), and only those rows' cols/vals are gathered and
    scatter-added: ~b of the traffic for the identical gradient.  The
    returned gradient is dense because the parameter server applies dense
    updates (the reference's driver-side axpy is dense too).
    """
    from asyncframework_tpu.ops.gradients import make_sparse_grad_sum

    grad_sum = make_sparse_grad_sum(d)

    @jax.jit
    def step(cols, vals, y, w, key):
        key, sub = jax.random.split(key)
        g = _sparse_compacted_gradient(
            cols, vals, y, w, sub, batch_rate, grad_sum
        )
        return g, key

    return step


def _sparse_saga_compacted(cols, vals, y, w, alpha, sub, batch_rate,
                           grad_sum):
    """Shared core of the compacted sparse ASAGA worker computation
    (sampling, gather, candidate scalars, history-corrected gradient).
    ONE definition, used by the engine worker step AND the fused rounds --
    the fused path's sampling-parity claim depends on these staying
    bit-identical (same discipline as :func:`_sparse_compacted_gradient`).
    """
    n_rows = y.shape[0]  # static at trace time
    cap = sparse_step_capacity(batch_rate, n_rows)
    mask = jax.random.bernoulli(sub, batch_rate, (n_rows,))
    (idx,) = jnp.nonzero(mask, size=cap, fill_value=0)
    valid = (jnp.arange(cap) < jnp.sum(mask)).astype(vals.dtype)
    c_sel = cols[idx]
    v_sel = vals[idx] * valid[:, None]  # unfilled slots contribute 0
    diff_sel = jnp.sum(v_sel * w[c_sel], axis=1) - y[idx] * valid
    g = grad_sum(c_sel, v_sel, diff_sel - alpha[idx])
    return g, diff_sel, idx, valid, c_sel, v_sel


def _sparse_saga_commit_expr(alpha, diff_sel, idx, valid):
    """The ScalarMap commit as a traceable expression (shared by the
    jitted engine commit and the fused scan): ``alpha[idx_j] <- diff_sel_j``
    for valid slots; padding slots scatter OUT OF BOUNDS and drop --
    routing them anywhere real would race a valid write at the same index.
    ``idx`` is ascending (``jnp.nonzero`` order) with padding at the tail,
    so the scatter runs with ``indices_are_sorted``."""
    n = alpha.shape[0]
    tgt = jnp.where(valid > 0, idx, n)
    return alpha.at[tgt].set(diff_sel, indices_are_sorted=True, mode="drop")


def make_sparse_saga_worker_step(batch_rate: float, d: int):
    """jit (cols, vals, y, w, alpha, key) ->
    (g, diff_sel, idx, valid, c_sel, v_sel, new_key) -- COMPACTED.

    Sparse ASAGA worker computation with the same masked-row compaction as
    the ASGD step: the Bernoulli-sampled row ids pack into a static-capacity
    index vector and only those rows' cols/vals/history are touched (~b of
    the full-shard gather/scatter volume).  ``diff_sel`` are the candidate
    history scalars FOR THE SELECTED ROWS; ``idx``/``valid`` say where they
    go; ``c_sel``/``v_sel`` (validity-zeroed) ride along so the updater's
    exact table delta needs no second row gather.
    """
    from asyncframework_tpu.ops.gradients import make_sparse_grad_sum

    grad_sum = make_sparse_grad_sum(d)

    @jax.jit
    def step(cols, vals, y, w, alpha, key):
        key, sub = jax.random.split(key)
        g, diff_sel, idx, valid, c_sel, v_sel = _sparse_saga_compacted(
            cols, vals, y, w, alpha, sub, batch_rate, grad_sum
        )
        return g, diff_sel, idx, valid, c_sel, v_sel, key

    return step


def make_sparse_saga_commit():
    """jit (alpha, diff_sel, idx, valid) -> alpha'; see
    :func:`_sparse_saga_commit_expr` for the semantics."""

    @jax.jit
    def commit(alpha, diff_sel, idx, valid):
        return _sparse_saga_commit_expr(alpha, diff_sel, idx, valid)

    return commit


def make_sparse_table_delta(d: int):
    """jit (c_sel, v_sel, diff_sel, alpha_cur, idx) -> exact table delta.

    The compacted analog of :func:`make_saga_table_delta`: the change the
    commit makes to the mean history gradient, computed against the CURRENT
    table slice (``alpha_cur[idx]``) at commit time -- see the dense
    variant's docstring for why dispatch-time history drifts.
    """
    from asyncframework_tpu.ops.gradients import make_sparse_grad_sum

    grad_sum = make_sparse_grad_sum(d)

    @jax.jit
    def delta(c_sel, v_sel, diff_sel, alpha_cur, idx):
        return grad_sum(c_sel, v_sel, diff_sel - alpha_cur[idx])

    return delta


def make_sparse_trajectory_loss_eval():
    """jit (cols, vals, y, W (S,d)) -> (S,) per-snapshot loss sums.

    Scans over snapshots so peak memory stays one (n_p, K) gather, not
    (S, n_p, K).
    """

    @jax.jit
    def eval_shard(cols, vals, y, W):
        def one(w):
            r = jnp.sum(vals * w[cols], axis=1) - y
            return jnp.sum(r * r)

        return jax.lax.map(one, W)

    return eval_shard


def make_fused_asgd_rounds(
    gamma: float,
    batch_rate: float,
    n: int,
    shards,
    loss: str = "least_squares",
    rounds_per_call: int = 16,
    sparse_d: "int | None" = None,
):
    """jit (w, k, keys (nw,2)) -> (w', k', keys', W_snap (R, d)) -- R full
    cohort rounds with ZERO host involvement (the device-resident accept
    loop, VERDICT r3 item 2).

    Semantics: at ``taw = inf`` with a full-wave cohort, the async engine's
    accept path reduces to "the whole cohort reads one model version; its
    gradients are applied in order with the ``gamma/sqrt(k/P+1)`` schedule"
    (``SparkASGDThread.scala:154-189`` with the tau filter never firing).
    That is a pure function of (w, k, keys), so R rounds fuse into one
    ``lax.scan`` -- the host's ~1 ms/update dispatch bound (BASELINE.md
    round 3) disappears; per-update cost becomes device compute.  The
    engine path stays the general case (finite taw, stragglers,
    speculation, fault tolerance cannot live inside a scan); this is the
    recipe-matched fast path for the reference's own headline runs, which
    all use ``taw = inf`` (``README.md:64``).

    ``shards``: list of (X, y) dense -- or, with ``sparse_d`` set, of
    (cols, vals, y) padded-ELL -- device arrays, all resident on the SAME
    device (the PS chip); per-worker PRNG chains ride in ``keys`` exactly
    as the engine keeps them, so sampling parity per worker is preserved.
    """
    if loss == "least_squares":
        grad_sum = least_squares_grad_sum
    elif loss == "logistic":
        grad_sum = logistic_grad_sum
    else:
        raise ValueError(f"unknown loss {loss!r}")
    nw = len(shards)
    par_recs = batch_rate * n / nw
    sp_grad_sum = None
    if sparse_d is not None:
        if loss != "least_squares":
            raise ValueError(
                "sparse fused rounds support least_squares only (the "
                "compacted residual is least-squares); got " + loss
            )
        from asyncframework_tpu.ops.gradients import make_sparse_grad_sum

        sp_grad_sum = make_sparse_grad_sum(sparse_d)

    def one_gradient(shard, w, key):
        key, sub = jax.random.split(key)
        if sparse_d is not None:
            # the SAME compacted core the engine worker step runs
            cols, vals, y = shard
            g = _sparse_compacted_gradient(
                cols, vals, y, w, sub, batch_rate, sp_grad_sum
            )
            return g, key
        X, y = shard
        n_rows = X.shape[0]
        if batch_rate > 0.5:
            mask = jax.random.bernoulli(
                sub, batch_rate, (n_rows,)
            ).astype(jnp.float32)
            return grad_sum(X, y, w, mask), key
        cap = sparse_step_capacity(batch_rate, n_rows)
        mask = jax.random.bernoulli(sub, batch_rate, (n_rows,))
        (idx,) = jnp.nonzero(mask, size=cap, fill_value=0)
        valid = (jnp.arange(cap) < jnp.sum(mask)).astype(jnp.float32)
        return grad_sum(X[idx], y[idx], w, valid), key

    def round_fn(carry, _x):
        w, k, keys = carry
        gs = []
        new_keys = []
        for i, shard in enumerate(shards):  # static unroll over workers
            g, nk = one_gradient(shard, w, keys[i])
            gs.append(g)
            new_keys.append(nk)
        G = jnp.stack(gs)
        kk = k + jnp.arange(nw, dtype=jnp.float32)
        lr = gamma / jnp.sqrt(kk / nw + 1.0)
        w2 = w - (lr / par_recs) @ G
        return (w2, k + float(nw), jnp.stack(new_keys)), w2

    @jax.jit
    def run_rounds(w, k, keys):
        (w2, k2, keys2), W_snap = jax.lax.scan(
            round_fn, (w, k, keys), None, length=rounds_per_call
        )
        return w2, k2, keys2, W_snap

    return run_rounds


def make_fused_saga_rounds(
    gamma: float,
    batch_rate: float,
    n: int,
    shards,
    rounds_per_call: int = 16,
    sparse_d: "int | None" = None,
):
    """jit (w, ab, alphas, keys) -> (w', ab', alphas', keys', W_snap) --
    R full ASAGA cohort rounds fused on one device (the ASAGA face of the
    device-resident accept loop; see :func:`make_fused_asgd_rounds` for
    the taw=inf semantics argument).

    Per round: every worker computes its history-corrected gradient
    ``g_i = X_i^T (mask_i * (diff_i - alpha_i))`` against the round-start
    model and its OWN (current) history slice; the accepts then fold
    sequentially -- ``w <- w - gamma*(g_j/parRecs + ab); ab <- ab + g_j/N``
    (``SparkASAGAThread.scala:210-213``) -- and each worker's candidate
    scalars commit into its slice.  ``delta == g`` is exact here for the
    same reason as the DCN PS: slices are worker-disjoint and one wave
    carries one result per worker, so the alpha a gradient was computed
    against IS the alpha at commit.  Least-squares only (the scalar
    history compression requires it, like the solver).

    ``sparse_d``: padded-ELL shards as (cols, vals, y) tuples -- the
    worker computation mirrors the engine's compacted sparse SAGA step
    (sampled rows gathered; candidate scalars committed by a scatter
    whose padding slots drop out of bounds; see
    make_sparse_saga_worker_step / make_sparse_saga_commit).
    """
    nw = len(shards)
    par_recs = batch_rate * n / nw
    sp_grad_sum = None
    if sparse_d is not None:
        from asyncframework_tpu.ops.gradients import make_sparse_grad_sum

        sp_grad_sum = make_sparse_grad_sum(sparse_d)

    def one_sparse(shard, w, alpha, key):
        # the SAME compacted core + commit the engine worker step runs
        cols, vals, y = shard
        key, sub = jax.random.split(key)
        g, diff_sel, idx, valid, _c, _v = _sparse_saga_compacted(
            cols, vals, y, w, alpha, sub, batch_rate, sp_grad_sum
        )
        alpha2 = _sparse_saga_commit_expr(alpha, diff_sel, idx, valid)
        return g, alpha2, key

    def round_fn(carry, _x):
        w, ab, alphas, keys = carry
        gs = []
        new_alphas = []
        new_keys = []
        for i, shard in enumerate(shards):  # static unroll over workers
            if sparse_d is not None:
                g, a2, key = one_sparse(shard, w, alphas[i], keys[i])
                gs.append(g)
                new_alphas.append(a2)
                new_keys.append(key)
                continue
            X, y = shard
            key, sub = jax.random.split(keys[i])
            mask = jax.random.bernoulli(
                sub, batch_rate, (X.shape[0],)
            ).astype(jnp.float32)
            diff = least_squares_residual(X, y, w)
            g = mm_f32(X.T, mask * (diff - alphas[i]))
            gs.append(g)
            # commit the wave's candidate scalars into the slice
            new_alphas.append(jnp.where(mask > 0, diff, alphas[i]))
            new_keys.append(key)
        # sequential accept fold (ab advances between the nw applies)
        w2, ab2 = w, ab
        for g in gs:
            w2 = w2 - (gamma / par_recs) * g - gamma * ab2
            ab2 = ab2 + g / n
        return (w2, ab2, tuple(new_alphas), jnp.stack(new_keys)), w2

    @jax.jit
    def run_rounds(w, ab, alphas, keys):
        (w2, ab2, alphas2, keys2), W_snap = jax.lax.scan(
            round_fn, (w, ab, tuple(alphas), keys), None,
            length=rounds_per_call,
        )
        return w2, ab2, alphas2, keys2, W_snap

    return run_rounds


def make_saga_dcn_worker_step():
    """jit (X, y, w, idx, alpha_sel, n_valid) -> (g, diff_sel).

    The DCN-ASAGA worker computation (``SparkASAGAThread.scala:280-294``,
    ``sampledMap``): the PS owns the scalar-history table and SAMPLES for the
    worker, shipping padded row ids ``idx`` and their current history scalars
    ``alpha_sel`` with the model; the worker gathers only those rows,
    computes candidate scalars ``diff_sel = x_i . w - y_i`` and the
    history-corrected gradient ``g = sum_i (diff_i - alpha_i) x_i``, and
    ships both back.  Padding slots (``>= n_valid``) contribute zero.
    Static shapes: ``idx``/``alpha_sel`` are capacity-padded by the PS
    (:func:`sparse_step_capacity`), so one executable serves every round.
    """

    @jax.jit
    def step(X, y, w, idx, alpha_sel, n_valid):
        cap = idx.shape[0]
        valid = (jnp.arange(cap) < n_valid).astype(jnp.float32)
        Xs = X[idx]
        diff = (mm_f32(Xs, w) - y[idx]) * valid
        g = mm_f32(Xs.T, (diff - alpha_sel) * valid)
        return g, diff

    return _prof.wrap_dispatch(step, "kernel.dispatch", "saga_dcn_worker_step")


def make_saga_dcn_sparse_worker_step(d: int):
    """jit (cols, vals, y, w, idx, alpha_sel, n_valid) -> (g, diff_sel).

    Sparse (padded-ELL) variant of :func:`make_saga_dcn_worker_step` for
    rcv1-class shards: the PS-sampled row ids gather only those rows'
    cols/vals, and the history-corrected gradient scatter-adds into a dense
    (d,) vector (the PS applies dense updates).  Padding rows are zeroed
    through ``v_sel`` so they contribute nothing.
    """
    from asyncframework_tpu.ops.gradients import make_sparse_grad_sum

    grad_sum = make_sparse_grad_sum(d)

    @jax.jit
    def step(cols, vals, y, w, idx, alpha_sel, n_valid):
        cap = idx.shape[0]
        valid = (jnp.arange(cap) < n_valid).astype(vals.dtype)
        c_sel = cols[idx]
        v_sel = vals[idx] * valid[:, None]
        diff = (jnp.sum(v_sel * w[c_sel], axis=1) - y[idx]) * valid
        # invalid rows have v_sel == 0, so their (diff - alpha) is inert
        g = grad_sum(c_sel, v_sel, diff - alpha_sel)
        return g, diff

    return step


@functools.partial(jax.jit, donate_argnums=(0,))
def add_grads(a, b):
    """Associative combine for the sync drain (comOp parity: vector add).

    The running accumulator ``a`` is donated: the drain's ``acc`` is dead the
    moment the next partial arrives, so the sum is built in one buffer.
    """
    return a + b


def make_trajectory_loss_eval(loss: str = "least_squares"):
    """jit (X, y, W_stack (S,d)) -> (S,) per-snapshot loss sums over a shard."""

    @jax.jit
    def eval_shard(X, y, W):
        R = mm_f32(X, W.T)  # (n, S); bf16 shards stay bf16 in the matmul
        if loss == "least_squares":
            E = R - y[:, None]
            return jnp.sum(E * E, axis=0)
        elif loss == "logistic":
            return jnp.sum(
                jnp.logaddexp(0.0, R) - y[:, None] * R, axis=0
            )
        else:
            raise ValueError(f"unknown loss {loss!r}")

    return _prof.wrap_dispatch(eval_shard, "kernel.dispatch", "trajectory_loss_eval")


def make_predict_step(loss: str = "least_squares"):
    """jit (X (n,d) f32, w (d,) f32) -> (n,) f32 predictions -- the serving
    tier's PREDICT kernel (serving/replica.py).

    least_squares serves the raw regression score ``X @ w``; logistic
    serves the positive-class probability ``sigmoid(X @ w)``.  One jitted
    executable per (loss, batch shape); replicas bucket batch sizes to
    powers of two so a mixed request stream compiles O(log n) variants,
    not one per request.
    """
    if loss not in ("least_squares", "logistic"):
        raise ValueError(f"unknown loss {loss!r}")

    @jax.jit
    def predict(X, w):
        z = mm_f32(X, w)
        if loss == "logistic":
            return jax.nn.sigmoid(z)
        return z

    return _prof.wrap_dispatch(predict, "kernel.dispatch", "predict_step")
