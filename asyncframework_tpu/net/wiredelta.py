"""Byte-exact model-delta codec for version-gated PULL replies.

ASAP (arXiv:1612.08608) showed async data-parallel systems win by sending
deltas instead of full state; the asynchronous-SGD transfer-volume line of
work (arXiv:1505.04956) identifies parameter bytes as the dominant DCN cost
at scale.  The blocker for deltas in a *correctness-first* PS protocol is
float arithmetic: ``basis + (current - basis)`` is NOT bit-equal to
``current`` in IEEE-754, and a worker whose reconstructed model drifts by
even one ulp is silently training against a model the PS never held.

This codec sidesteps arithmetic entirely: the delta is the **XOR of the
raw float32 bit patterns** (viewed as ``uint32``).  XOR is exact, so
``basis_bits ^ delta_bits == current_bits`` byte-for-byte, and entries the
update never touched XOR to zero -- the delta of a model that changed in
few coordinates is naturally sparse.  Encoding picks the smallest wire
form:

- ``nm``     -- basis bytes == current bytes: header-only NOT_MODIFIED.
- ``xdelta`` -- ``(idx u32, xorword u32)`` pairs for the changed entries,
  chosen when ``nnz * 8 < d * 4``.
- ``full``   -- the raw float32 payload (the delta would not be smaller,
  or the server no longer caches the basis).

Every non-full reply carries the CRC32 of the *current* model bytes; the
decoder recomputes (or, for ``nm``, compares its cached basis CRC) and
signals mismatch so the client can fall back to a full pull -- a delta
path can degrade to the legacy wire, never to a wrong model.

Native fast path (``async.native.enabled``, native/wiredelta.cc): the
XOR/CRC passes dispatch to GIL-free C twins loaded via ctypes; the numpy
implementations below (``_py_*``) are the registered bit-identity
oracles (``NATIVE_ORACLES``, enforced by the ``native-oracle`` lint) and
the fallback whenever the knob is off or no toolchain is present.  The
bytes produced are identical either way -- property-tested in
tests/test_native.py -- so flipping the knob never changes the wire.
"""

from __future__ import annotations

import ctypes
import zlib
from typing import Optional, Tuple

import numpy as np

from asyncframework_tpu.metrics import profiler as _prof
from asyncframework_tpu.native_build import bump_native as _bump_native

#: wire-encoding tags carried in the MODEL header's ``wenc`` field
FULL = "full"
NOT_MODIFIED = "nm"
XDELTA = "xdelta"
#: dense XOR form (relaycast plane only -- the PS never emits it): the
#: raw ``cur_bits ^ basis_bits`` words with NO index list, same size as
#: FULL but structurally compressible (consecutive training versions
#: agree in sign/exponent/top-mantissa bits, so the xor's high byte
#: planes are near-zero -- see net/wirecodec.py's shuffle transform).
#: Still byte-exact and CRC-gated like every other form.
XFULL = "xfull"

# --------------------------------------------------------- native loading
#: native symbol -> the same-module pure-Python oracle it must bit-match
#: (the ``native-oracle`` lint's declaration table; tests/test_native.py
#: property-tests each pair)
NATIVE_ORACLES = {
    "wd_crc32": "_py_crc",
    "wd_encode": "_py_encode",
    "wd_xor_dense": "_py_encode_xfull",
    "wd_apply_xdelta": "_py_decode",
}

_NATIVE = None


def _native_lib():
    global _NATIVE
    if _NATIVE is not None:
        return _NATIVE or None
    lib = None
    try:
        from asyncframework_tpu.native_build import ensure_built

        built = ensure_built("wiredelta")
        if built:
            lib = ctypes.CDLL(built)
            lib.wd_crc32.restype = ctypes.c_uint32
            lib.wd_crc32.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
            lib.wd_encode.restype = ctypes.c_longlong
            lib.wd_encode.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_longlong,
            ]
            lib.wd_xor_dense.restype = None
            lib.wd_xor_dense.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_longlong,
            ]
            lib.wd_apply_xdelta.restype = ctypes.c_int
            lib.wd_apply_xdelta.argtypes = [
                ctypes.c_void_p, ctypes.c_longlong, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_longlong,
            ]
    except Exception:  # noqa: BLE001 - fall back to Python
        lib = None
    _NATIVE = lib or False
    return lib


def _use_native():
    """The per-call dispatch decision: the loaded library when
    ``async.native.enabled`` is on and the build exists, else None.
    A wanted-but-unavailable native path bumps ``python_fallbacks`` --
    the silent degrade the ``native`` metrics family exists to surface."""
    from asyncframework_tpu.conf import NATIVE_ENABLED, global_conf

    if not global_conf().get(NATIVE_ENABLED):
        return None
    lib = _native_lib()
    if lib is None:
        _bump_native("python_fallbacks")
    return lib


def _u8(buf) -> np.ndarray:
    """A zero-copy uint8 view over any contiguous buffer (raises
    ValueError on non-contiguous input -- callers fall back to Python)."""
    return np.frombuffer(memoryview(buf).cast("B"), np.uint8)


# ----------------------------------------------------------------- oracles
def _py_crc(model_buf) -> int:
    return zlib.crc32(model_buf) & 0xFFFFFFFF


def _py_encode(cur: np.ndarray, basis: np.ndarray,
               full) -> Tuple[str, bytes, int]:
    cur_bits = cur.view(np.uint32)
    xor = cur_bits ^ basis.view(np.uint32)
    (nz,) = np.nonzero(xor)
    if nz.size == 0:
        return NOT_MODIFIED, b"", 0
    if nz.size * 8 < cur.nbytes:
        payload = (nz.astype(np.uint32).tobytes()
                   + np.ascontiguousarray(xor[nz]).tobytes())
        return XDELTA, payload, int(nz.size)
    return full()


def _py_encode_xfull(cur: np.ndarray, basis: np.ndarray) -> bytes:
    return (cur.view(np.uint32) ^ basis.view(np.uint32)).tobytes()


def _py_decode(basis: np.ndarray, idx: np.ndarray,
               xwords: np.ndarray) -> Optional[np.ndarray]:
    if idx.size and int(idx.max()) >= basis.size:
        return None
    bits = basis.view(np.uint32).copy()
    bits[idx] ^= xwords
    return bits.view(np.float32)


# --------------------------------------------------------------------- API
@_prof.zoned("wire.crc")
def crc(model_buf) -> int:
    """CRC32 of a model payload (the integrity check on every delta/NM
    reply).  Accepts any buffer-protocol object -- pass the contiguous
    float32 array itself, no ``tobytes`` copy needed.  ~GB/s on commodity
    hosts: microseconds at DCN model sizes."""
    lib = _use_native()
    if lib is not None:
        try:
            a = _u8(model_buf)
        except (ValueError, TypeError):
            a = None
        if a is not None:
            _bump_native("native_calls.crc")
            return int(lib.wd_crc32(
                ctypes.c_void_p(a.ctypes.data), a.size))
    _bump_native("python_calls.crc")
    return _py_crc(model_buf)


@_prof.zoned("wire.xor")
def encode(cur: np.ndarray, basis: Optional[np.ndarray],
           cur_bytes: Optional[bytes] = None) -> Tuple[str, bytes, int]:
    """Encode ``cur`` (float32) against ``basis`` (float32 or None).

    Returns ``(wenc, payload, nnz)``: the chosen wire form, its model-part
    payload bytes, and the changed-entry count (0 for ``nm``/``full``).
    ``cur_bytes`` lets a caller with an already-serialized current model
    (the PS's per-version encoded cache) avoid a redundant ``tobytes``.
    """
    def full() -> Tuple[str, bytes, int]:
        return FULL, (cur_bytes if cur_bytes is not None
                      else cur.tobytes()), 0

    if basis is None or basis.shape != cur.shape:
        return full()
    lib = _use_native()
    if (lib is not None and cur.flags.c_contiguous
            and basis.flags.c_contiguous):
        n = int(cur.size)
        # the XDELTA cutoff shared with the oracle: acceptable while
        # nnz * 8 < nbytes, i.e. nnz < n / 2, so the largest acceptable
        # count (wd_encode treats max_nnz as inclusive) is (n - 1) // 2
        max_nnz = max(0, (n - 1) // 2)
        idx = np.empty(max_nnz, np.uint32)
        xw = np.empty(max_nnz, np.uint32)
        nnz = lib.wd_encode(
            ctypes.c_void_p(cur.ctypes.data),
            ctypes.c_void_p(basis.ctypes.data), n,
            ctypes.c_void_p(idx.ctypes.data),
            ctypes.c_void_p(xw.ctypes.data), max_nnz,
        )
        _bump_native("native_calls.xor")
        if nnz < 0:
            return full()
        if nnz == 0:
            return NOT_MODIFIED, b"", 0
        return XDELTA, idx[:nnz].tobytes() + xw[:nnz].tobytes(), int(nnz)
    _bump_native("python_calls.xor")
    return _py_encode(cur, basis, full)


@_prof.zoned("wire.xor")
def encode_xfull(cur: np.ndarray, basis: np.ndarray) -> bytes:
    """The dense XOR payload (``XFULL``): exact by construction, FULL-
    sized on the wire but built for the wirecodec shuffle+deflate
    transform.  Caller guarantees matching shapes."""
    lib = _use_native()
    if (lib is not None and cur.flags.c_contiguous
            and basis.flags.c_contiguous):
        out = np.empty(cur.size, np.uint32)
        lib.wd_xor_dense(ctypes.c_void_p(cur.ctypes.data),
                         ctypes.c_void_p(basis.ctypes.data),
                         ctypes.c_void_p(out.ctypes.data), int(cur.size))
        _bump_native("native_calls.xor")
        return out.tobytes()
    _bump_native("python_calls.xor")
    return _py_encode_xfull(cur, basis)


@_prof.zoned("wire.xor")
def decode(wenc: str, payload, nnz: int, basis: Optional[np.ndarray],
           want_crc: Optional[int], basis_crc: Optional[int] = None
           ) -> Optional[np.ndarray]:
    """Reconstruct the current model (float32) from a delta-form reply.

    ``basis`` is the client's cached basis array; ``want_crc`` the CRC the
    server stamped for the current version; ``basis_crc`` the client's
    cached CRC of its basis bytes (lets ``nm`` validate in O(1)).

    Returns the reconstructed array, or **None** on any mismatch -- cache
    miss, shape drift, CRC disagreement -- in which case the caller MUST
    fall back to a full pull.  Never returns a model that failed its CRC.
    """
    if wenc == FULL:
        return np.frombuffer(payload, np.float32)
    if basis is None:
        return None
    if wenc == NOT_MODIFIED:
        if want_crc is None:
            return None
        have = basis_crc if basis_crc is not None else crc(basis)
        return basis if have == want_crc else None
    if wenc == XFULL:
        if len(payload) != basis.nbytes:
            return None
        lib = _use_native()
        if lib is not None and basis.flags.c_contiguous:
            xw = np.frombuffer(payload, np.uint32)
            out = np.empty(basis.size, np.uint32)
            lib.wd_xor_dense(ctypes.c_void_p(basis.ctypes.data),
                             ctypes.c_void_p(xw.ctypes.data),
                             ctypes.c_void_p(out.ctypes.data),
                             int(basis.size))
            _bump_native("native_calls.xor")
            out = out.view(np.float32)
        else:
            _bump_native("python_calls.xor")
            bits = (basis.view(np.uint32)
                    ^ np.frombuffer(payload, np.uint32))
            out = bits.view(np.float32)
        if want_crc is None or crc(out) != want_crc:
            return None
        return out
    if wenc != XDELTA:
        return None
    if len(payload) != 8 * nnz or nnz <= 0:
        return None
    idx = np.frombuffer(payload[: 4 * nnz], np.uint32)
    xwords = np.frombuffer(payload[4 * nnz:], np.uint32)
    lib = _use_native()
    if lib is not None and basis.flags.c_contiguous:
        bits = basis.view(np.uint32).copy()
        rc = lib.wd_apply_xdelta(
            ctypes.c_void_p(bits.ctypes.data), int(basis.size),
            ctypes.c_void_p(idx.ctypes.data),
            ctypes.c_void_p(xwords.ctypes.data), int(nnz))
        _bump_native("native_calls.xor")
        out = None if rc != 0 else bits.view(np.float32)
    else:
        _bump_native("python_calls.xor")
        out = _py_decode(basis, idx, xwords)
    if out is None:
        return None
    if want_crc is None or crc(out) != want_crc:
        return None
    return out
