"""Byte-exact model-delta codec for version-gated PULL replies.

ASAP (arXiv:1612.08608) showed async data-parallel systems win by sending
deltas instead of full state; the asynchronous-SGD transfer-volume line of
work (arXiv:1505.04956) identifies parameter bytes as the dominant DCN cost
at scale.  The blocker for deltas in a *correctness-first* PS protocol is
float arithmetic: ``basis + (current - basis)`` is NOT bit-equal to
``current`` in IEEE-754, and a worker whose reconstructed model drifts by
even one ulp is silently training against a model the PS never held.

This codec sidesteps arithmetic entirely: the delta is the **XOR of the
raw float32 bit patterns** (viewed as ``uint32``).  XOR is exact, so
``basis_bits ^ delta_bits == current_bits`` byte-for-byte, and entries the
update never touched XOR to zero -- the delta of a model that changed in
few coordinates is naturally sparse.  Encoding picks the smallest wire
form:

- ``nm``     -- basis bytes == current bytes: header-only NOT_MODIFIED.
- ``xdelta`` -- ``(idx u32, xorword u32)`` pairs for the changed entries,
  chosen when ``nnz * 8 < d * 4``.
- ``full``   -- the raw float32 payload (the delta would not be smaller,
  or the server no longer caches the basis).

Every non-full reply carries the CRC32 of the *current* model bytes; the
decoder recomputes (or, for ``nm``, compares its cached basis CRC) and
signals mismatch so the client can fall back to a full pull -- a delta
path can degrade to the legacy wire, never to a wrong model.
"""

from __future__ import annotations

import zlib
from typing import Optional, Tuple

import numpy as np

from asyncframework_tpu.metrics import profiler as _prof

#: wire-encoding tags carried in the MODEL header's ``wenc`` field
FULL = "full"
NOT_MODIFIED = "nm"
XDELTA = "xdelta"
#: dense XOR form (relaycast plane only -- the PS never emits it): the
#: raw ``cur_bits ^ basis_bits`` words with NO index list, same size as
#: FULL but structurally compressible (consecutive training versions
#: agree in sign/exponent/top-mantissa bits, so the xor's high byte
#: planes are near-zero -- see net/wirecodec.py's shuffle transform).
#: Still byte-exact and CRC-gated like every other form.
XFULL = "xfull"


@_prof.zoned("wire.crc")
def crc(model_buf) -> int:
    """CRC32 of a model payload (the integrity check on every delta/NM
    reply).  Accepts any buffer-protocol object -- pass the contiguous
    float32 array itself, no ``tobytes`` copy needed.  ~GB/s on commodity
    hosts: microseconds at DCN model sizes."""
    return zlib.crc32(model_buf) & 0xFFFFFFFF


@_prof.zoned("wire.xor")
def encode(cur: np.ndarray, basis: Optional[np.ndarray],
           cur_bytes: Optional[bytes] = None) -> Tuple[str, bytes, int]:
    """Encode ``cur`` (float32) against ``basis`` (float32 or None).

    Returns ``(wenc, payload, nnz)``: the chosen wire form, its model-part
    payload bytes, and the changed-entry count (0 for ``nm``/``full``).
    ``cur_bytes`` lets a caller with an already-serialized current model
    (the PS's per-version encoded cache) avoid a redundant ``tobytes``.
    """
    def full() -> Tuple[str, bytes, int]:
        return FULL, (cur_bytes if cur_bytes is not None
                      else cur.tobytes()), 0

    if basis is None or basis.shape != cur.shape:
        return full()
    cur_bits = cur.view(np.uint32)
    xor = cur_bits ^ basis.view(np.uint32)
    (nz,) = np.nonzero(xor)
    if nz.size == 0:
        return NOT_MODIFIED, b"", 0
    if nz.size * 8 < cur.nbytes:
        payload = (nz.astype(np.uint32).tobytes()
                   + np.ascontiguousarray(xor[nz]).tobytes())
        return XDELTA, payload, int(nz.size)
    return full()


@_prof.zoned("wire.xor")
def encode_xfull(cur: np.ndarray, basis: np.ndarray) -> bytes:
    """The dense XOR payload (``XFULL``): exact by construction, FULL-
    sized on the wire but built for the wirecodec shuffle+deflate
    transform.  Caller guarantees matching shapes."""
    return (cur.view(np.uint32) ^ basis.view(np.uint32)).tobytes()


@_prof.zoned("wire.xor")
def decode(wenc: str, payload, nnz: int, basis: Optional[np.ndarray],
           want_crc: Optional[int], basis_crc: Optional[int] = None
           ) -> Optional[np.ndarray]:
    """Reconstruct the current model (float32) from a delta-form reply.

    ``basis`` is the client's cached basis array; ``want_crc`` the CRC the
    server stamped for the current version; ``basis_crc`` the client's
    cached CRC of its basis bytes (lets ``nm`` validate in O(1)).

    Returns the reconstructed array, or **None** on any mismatch -- cache
    miss, shape drift, CRC disagreement -- in which case the caller MUST
    fall back to a full pull.  Never returns a model that failed its CRC.
    """
    if wenc == FULL:
        return np.frombuffer(payload, np.float32)
    if basis is None:
        return None
    if wenc == NOT_MODIFIED:
        if want_crc is None:
            return None
        have = basis_crc if basis_crc is not None else crc(basis)
        return basis if have == want_crc else None
    if wenc == XFULL:
        if len(payload) != basis.nbytes:
            return None
        bits = basis.view(np.uint32) ^ np.frombuffer(payload, np.uint32)
        out = bits.view(np.float32)
        if want_crc is None or crc(out) != want_crc:
            return None
        return out
    if wenc != XDELTA:
        return None
    if len(payload) != 8 * nnz or nnz <= 0:
        return None
    idx = np.frombuffer(payload[: 4 * nnz], np.uint32)
    xwords = np.frombuffer(payload[4 * nnz:], np.uint32)
    if idx.size and int(idx.max()) >= basis.size:
        return None
    bits = basis.view(np.uint32).copy()
    bits[idx] ^= xwords
    out = bits.view(np.float32)
    if want_crc is None or crc(out) != want_crc:
        return None
    return out
