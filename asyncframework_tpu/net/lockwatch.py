"""Debug lock watchdog: no socket I/O while a watched lock is held.

The pipelined data plane's central claim is that ``_handle_pull`` serves
model replies WITHOUT touching the PS model lock (the lock guards the
updater's apply path only), and that nothing anywhere does wire I/O while
holding it -- a send or recv under the model lock would let one slow
worker's socket stall every merge in the process.  That claim is easy to
break silently in a refactor, so this module makes it checkable at
runtime:

- :class:`WatchedLock` is a drop-in ``threading.Lock`` replacement that
  tracks, per thread, which watched locks are currently held, plus hold
  counts and the max hold time (reported in the live UI's ``lockwatch``
  section).
- ``net/frame.py`` calls :func:`check_io` at its send/recv choke points;
  when the watchdog is enabled and the calling thread holds any watched
  lock, the call raises ``AssertionError`` naming the lock -- the
  violation is also counted, so soak harnesses can assert on totals.

Enablement is process-global and off by default (one module-flag check
per frame when disabled).  ``async.debug.lockwatch`` turns it on via
conf/env (subprocess chaos children inherit
``ASYNCTPU_ASYNC_DEBUG_LOCKWATCH=1``); :func:`enable` turns it on
programmatically (the chaos suite's autouse fixture).  The PS installs a
watched model lock whenever either source says so, and the other
contended locks of the training plane ride :func:`named_lock` -- plain
``threading.Lock`` when the watchdog is off (zero hot-path cost),
watched when it is on.

**Lock-order race detection** (the dynamic half of the async-lint
story): every acquisition of a watched lock B while the thread already
holds watched lock A folds an A->B edge into a process-global
acquisition-order graph.  A cycle in that graph is a POTENTIAL DEADLOCK
-- two threads taking the same pair of locks in opposite orders need
only the right interleaving to wedge forever, which is exactly the kind
of bug a chaos run exhibits once a year and a graph exhibits on the
first pass.  Cycles are counted and rendered in :func:`totals` (the
live UI ``lockwatch`` section), :func:`assert_no_cycles` raises with
the rendered cycles (the chaos suite's autouse fixture calls it at
teardown, and ``bin/chaos_sweep.py`` arms the detector every seed), and
``lock_order_edges``/``lock_order_cycles`` expose the raw graph for
tests.  The static twin -- blocking calls lexically under a lock --
lives in ``asyncframework_tpu/analysis/rules_locks.py``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Tuple

_enabled = False

_tls = threading.local()

_totals_lock = threading.Lock()
_holds = 0
_violations = 0
_max_hold_ms = 0.0
# acquisition-order graph: (held, acquired) -> observation count; cycles
# keyed by their canonical rotation so each distinct cycle reports once
_edges: Dict[Tuple[str, str], int] = {}
_cycles: Dict[Tuple[str, ...], str] = {}
# sticky cycle history: reset_totals() FOLDS current cycles here instead
# of erasing them -- a cycle is a correctness verdict, not a per-run
# counter, so a suite resetting the graph for isolation must not be able
# to erase another suite's potential deadlock before the session-wide
# gate (tests/conftest.py) sees it.  Cleared only by
# clear_cycle_history() (tests that drive cycles DELIBERATELY).
_cycles_ever: List[str] = []


def enable(flag: bool = True) -> None:
    """Turn the watchdog on/off process-wide (tests/suites; conf-driven
    daemons go through :func:`enabled_for`)."""
    global _enabled
    _enabled = bool(flag)


def enabled() -> bool:
    return _enabled


def enabled_for(conf=None) -> bool:
    """Should a freshly constructed server run with a watched lock?
    True when the watchdog was enabled programmatically OR
    ``async.debug.lockwatch`` is set; a conf hit also flips the process
    flag so the frame choke points start checking."""
    if _enabled:
        return True
    from asyncframework_tpu.conf import DEBUG_LOCKWATCH, global_conf

    conf = conf if conf is not None else global_conf()
    if bool(conf.get(DEBUG_LOCKWATCH)):
        enable(True)
        return True
    return False


def named_lock(name: str):
    """A lock for a contended structure: :class:`WatchedLock` when the
    watchdog is armed (hold stats + I/O assert + lock-order edges),
    plain ``threading.Lock`` otherwise.  Construction-time resolution,
    same contract as the PS model lock."""
    if enabled_for():
        return WatchedLock(name)
    return threading.Lock()


def held() -> List[str]:
    """Names of the watched locks the calling thread currently holds."""
    return list(getattr(_tls, "stack", ()))


# ------------------------------------------------------------ lock order
def _canonical(cycle: Tuple[str, ...]) -> Tuple[str, ...]:
    """Rotate a cycle (no repeated terminal) so its min element leads --
    one key per distinct cycle regardless of discovery point."""
    i = cycle.index(min(cycle))
    return cycle[i:] + cycle[:i]


def _record_edges(held_now: List[str], acquired: str) -> None:
    """Fold held->acquired edges into the graph; on a NEW edge, scan for
    cycles it closes (DFS from ``acquired`` back to the edge's tail).
    Called under ``_totals_lock``; the graph is names, small."""
    for h in held_now:
        if h == acquired:
            continue
        edge = (h, acquired)
        seen = _edges.get(edge, 0)
        _edges[edge] = seen + 1
        if seen:
            continue  # old edge cannot close a new cycle
        # DFS: path acquired ->* h closes the cycle h -> acquired -> ... -> h
        stack: List[Tuple[str, Tuple[str, ...]]] = [(acquired, (h, acquired))]
        while stack:
            node, path = stack.pop()
            for (a, b) in _edges:
                if a != node or b in path[1:]:
                    continue
                if b == h:
                    key = _canonical(path)
                    if key not in _cycles:
                        _cycles[key] = " -> ".join(path + (h,))
                elif len(path) < 16:
                    stack.append((b, path + (b,)))


def lock_order_edges() -> Dict[Tuple[str, str], int]:
    """The observed acquisition-order graph (edge -> count)."""
    with _totals_lock:
        return dict(_edges)


def lock_order_cycles() -> List[str]:
    """Rendered potential-deadlock cycles ('a -> b -> a'), one per
    distinct cycle, discovery order."""
    with _totals_lock:
        return list(_cycles.values())


def cycle_history() -> List[str]:
    """Every cycle observed since the last :func:`clear_cycle_history`,
    including ones folded in by intervening ``reset_totals()`` calls."""
    with _totals_lock:
        cur = [c for c in _cycles.values() if c not in _cycles_ever]
        return list(_cycles_ever) + cur


def set_cycle_history(cycles: List[str]) -> None:
    """Replace the sticky cycle log.  ONLY for tests/harnesses that
    create cycles deliberately (tests/test_analysis.py's detector
    units, chaos_sweep's lockorder_sanity): they snapshot
    :func:`cycle_history` BEFORE driving their cycle and RESTORE the
    snapshot afterwards -- wholesale clearing would also erase a real
    cycle an earlier armed suite left for the session-wide gate."""
    with _totals_lock:
        _cycles_ever[:] = list(cycles)


def clear_cycle_history() -> None:
    """``set_cycle_history([])`` -- see the restore-don't-clear caveat
    there."""
    set_cycle_history([])


def assert_no_cycles(include_history: bool = False) -> None:
    """Raise AssertionError naming every observed lock-order cycle --
    the chaos suite's teardown check and chaos_sweep's per-seed gate.
    ``include_history=True`` (the session-wide conftest gate) also
    counts cycles a reset_totals() folded into the sticky history."""
    cycles = cycle_history() if include_history else lock_order_cycles()
    if cycles:
        raise AssertionError(
            "lockwatch: potential deadlock -- lock-order cycle(s) "
            "observed: " + "; ".join(cycles))


def check_io(what: str) -> None:
    """Choke-point assert (``net/frame.py``): socket I/O under a watched
    lock is the exact contention the lock-free pull path exists to
    remove.  No-op when disabled."""
    if not _enabled:
        return
    stack = getattr(_tls, "stack", None)
    if stack:
        global _violations
        with _totals_lock:
            _violations += 1
        raise AssertionError(
            f"lockwatch: socket {what} while holding watched lock(s) "
            f"{list(stack)}"
        )


class WatchedLock:
    """``threading.Lock`` with per-thread hold tracking + hold-time
    stats.  Context-manager and acquire/release compatible; the tracking
    cost is two thread-local list ops per hold."""

    __slots__ = ("name", "_lock", "_t0")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        # per-holder acquire time; single writer (the holder), so a plain
        # attribute is enough
        self._t0 = 0.0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            stack = getattr(_tls, "stack", None)
            if stack is None:
                stack = _tls.stack = []
            if stack:
                # nested hold: fold acquisition-order edges (held -> new)
                # into the process-global graph and scan for cycles
                with _totals_lock:
                    _record_edges(stack, self.name)
            stack.append(self.name)
            self._t0 = time.monotonic()
        return got

    def release(self) -> None:
        global _holds, _max_hold_ms
        hold_ms = (time.monotonic() - self._t0) * 1e3
        stack = getattr(_tls, "stack", None)
        if stack and self.name in stack:
            stack.remove(self.name)
        with _totals_lock:
            _holds += 1
            if hold_ms > _max_hold_ms:
                _max_hold_ms = hold_ms
        self._lock.release()

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()


def totals() -> Dict[str, object]:
    """Watchdog report for the live UI: enabled flag, hold count, max
    single hold in ms, violations caught (0 is the claim holding)."""
    with _totals_lock:
        return {
            "enabled": _enabled,
            "holds": _holds,
            "violations": _violations,
            "max_hold_ms": round(_max_hold_ms, 3),
            # lock-order race detector: observed acquisition-order edges
            # and the potential-deadlock cycles among them (0 = claim
            # holding); cycles rendered for the dashboard, capped
            "order_edges": len(_edges),
            "order_cycles": len(_cycles),
            "cycles": list(_cycles.values())[:8],
        }


def reset_totals() -> None:
    """Zero the counters and the acquisition-order graph (per-run
    isolation; enabled flag untouched).  Cycles are FOLDED into the
    sticky history, not erased -- see ``_cycles_ever``."""
    global _holds, _violations, _max_hold_ms
    with _totals_lock:
        _holds = 0
        _violations = 0
        _max_hold_ms = 0.0
        for c in _cycles.values():
            if c not in _cycles_ever:
                _cycles_ever.append(c)
        _edges.clear()
        _cycles.clear()
