"""Debug lock watchdog: no socket I/O while a watched lock is held.

The pipelined data plane's central claim is that ``_handle_pull`` serves
model replies WITHOUT touching the PS model lock (the lock guards the
updater's apply path only), and that nothing anywhere does wire I/O while
holding it -- a send or recv under the model lock would let one slow
worker's socket stall every merge in the process.  That claim is easy to
break silently in a refactor, so this module makes it checkable at
runtime:

- :class:`WatchedLock` is a drop-in ``threading.Lock`` replacement that
  tracks, per thread, which watched locks are currently held, plus hold
  counts and the max hold time (reported in the live UI's ``lockwatch``
  section).
- ``net/frame.py`` calls :func:`check_io` at its send/recv choke points;
  when the watchdog is enabled and the calling thread holds any watched
  lock, the call raises ``AssertionError`` naming the lock -- the
  violation is also counted, so soak harnesses can assert on totals.

Enablement is process-global and off by default (one module-flag check
per frame when disabled).  ``async.debug.lockwatch`` turns it on via
conf/env (subprocess chaos children inherit
``ASYNCTPU_ASYNC_DEBUG_LOCKWATCH=1``); :func:`enable` turns it on
programmatically (the chaos suite's autouse fixture).  The PS installs a
watched model lock whenever either source says so.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List

_enabled = False

_tls = threading.local()

_totals_lock = threading.Lock()
_holds = 0
_violations = 0
_max_hold_ms = 0.0


def enable(flag: bool = True) -> None:
    """Turn the watchdog on/off process-wide (tests/suites; conf-driven
    daemons go through :func:`enabled_for`)."""
    global _enabled
    _enabled = bool(flag)


def enabled() -> bool:
    return _enabled


def enabled_for(conf=None) -> bool:
    """Should a freshly constructed server run with a watched lock?
    True when the watchdog was enabled programmatically OR
    ``async.debug.lockwatch`` is set; a conf hit also flips the process
    flag so the frame choke points start checking."""
    if _enabled:
        return True
    from asyncframework_tpu.conf import DEBUG_LOCKWATCH, global_conf

    conf = conf if conf is not None else global_conf()
    if bool(conf.get(DEBUG_LOCKWATCH)):
        enable(True)
        return True
    return False


def held() -> List[str]:
    """Names of the watched locks the calling thread currently holds."""
    return list(getattr(_tls, "stack", ()))


def check_io(what: str) -> None:
    """Choke-point assert (``net/frame.py``): socket I/O under a watched
    lock is the exact contention the lock-free pull path exists to
    remove.  No-op when disabled."""
    if not _enabled:
        return
    stack = getattr(_tls, "stack", None)
    if stack:
        global _violations
        with _totals_lock:
            _violations += 1
        raise AssertionError(
            f"lockwatch: socket {what} while holding watched lock(s) "
            f"{list(stack)}"
        )


class WatchedLock:
    """``threading.Lock`` with per-thread hold tracking + hold-time
    stats.  Context-manager and acquire/release compatible; the tracking
    cost is two thread-local list ops per hold."""

    __slots__ = ("name", "_lock", "_t0")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        # per-holder acquire time; single writer (the holder), so a plain
        # attribute is enough
        self._t0 = 0.0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            stack = getattr(_tls, "stack", None)
            if stack is None:
                stack = _tls.stack = []
            stack.append(self.name)
            self._t0 = time.monotonic()
        return got

    def release(self) -> None:
        global _holds, _max_hold_ms
        hold_ms = (time.monotonic() - self._t0) * 1e3
        stack = getattr(_tls, "stack", None)
        if stack and self.name in stack:
            stack.remove(self.name)
        with _totals_lock:
            _holds += 1
            if hold_ms > _max_hold_ms:
                _max_hold_ms = hold_ms
        self._lock.release()

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lock.locked()


def totals() -> Dict[str, object]:
    """Watchdog report for the live UI: enabled flag, hold count, max
    single hold in ms, violations caught (0 is the claim holding)."""
    with _totals_lock:
        return {
            "enabled": _enabled,
            "holds": _holds,
            "violations": _violations,
            "max_hold_ms": round(_max_hold_ms, 3),
        }


def reset_totals() -> None:
    """Zero the counters (per-run isolation; enabled flag untouched)."""
    global _holds, _violations, _max_hold_ms
    with _totals_lock:
        _holds = 0
        _violations = 0
        _max_hold_ms = 0.0
