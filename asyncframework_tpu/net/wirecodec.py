"""Wire-compression codec layer: quantized gradients + compressed deltas.

ASAP (arXiv:1612.08608) argues the right trade for asynchronous data-
parallel systems is *approximate with bounded error* on the wire; the
gradient-compression line of work (1-bit SGD, QSGD, error-feedback SGD)
makes that concrete for the PUSH path: quantize each gradient, keep the
quantization residual in a per-worker **error-feedback accumulator**, and
fold it into the next gradient before quantizing again.  The model then
never drifts unboundedly: after T pushes the applied sum equals the true
gradient sum minus only the CURRENT residual, and the residual is bounded
by one step's quantization error (see :func:`grad_error_bound`).

Two independent codecs live here, both conf-gated and both **off by
default = byte-identical wire** (the repo-wide discipline: every plane's
legacy wire is asserted byte-identical via per-op frame totals when its
knob is absent):

- **gradient quantization** (``async.codec.push`` = ``fp16`` | ``int8``):
  lossy-but-error-fed encode of dense ASGD PUSH payloads.  fp16 halves
  the gradient bytes; int8 (per-push max-abs scale) quarters them.
  Non-finite gradients (NaN/inf), fp16-overflowing magnitudes, sparse-
  encoded pushes, and ASAGA pushes (whose history scalars must be exact)
  all fall back to the raw f32 wire -- the codec degrades to exact,
  never to poisoned.

- **snapshot-delta compression** (the relaycast plane's
  ``async.relay.compress``): **lossless** zlib over the XOR-delta /
  full model payloads of ``net/wiredelta.py``.  XOR deltas of a
  training step are structurally compressible (sign/exponent bits of
  consecutive versions agree, so xor words lead with zero bytes, and
  the index half is ascending u32), and losslessness means the
  CRC-gating contract is untouched: decompress, then the stock decode
  verifies the version CRC exactly as before.

Native fast path (``async.native.enabled``, native/wirecodec.cc): the
quantize/dequantize passes (error-feedback fold included) and the
byte-shuffle / delta-index transforms dispatch to GIL-free C twins; the
numpy implementations (``_py_*``) stay the registered bit-identity
oracles (``NATIVE_ORACLES``, ``native-oracle`` lint) and the fallback
without a toolchain.  zlib itself already runs in C with the GIL
released, so deflate stays on the stdlib.  Bit-identical either way --
property-tested in tests/test_native.py incl. NaN/inf/-0.
"""

from __future__ import annotations

import ctypes
import threading
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from asyncframework_tpu.metrics import profiler as _prof
from asyncframework_tpu.native_build import bump_native as _bump_native

#: gradient-codec names (``async.codec.push`` values)
OFF = "off"
FP16 = "fp16"
INT8 = "int8"
GRAD_CODECS = (OFF, FP16, INT8)

#: fp16 magnitudes past this overflow to inf; ship such pushes raw
_FP16_SAFE_MAX = 6.0e4
#: fp16 relative quantization error (one ulp at 11 significand bits)
_FP16_REL = 2.0 ** -11
#: fp16 subnormal floor (absolute error near zero)
_FP16_ABS = 6.0e-8

_lock = threading.Lock()
_totals: Dict[str, int] = {}


def _bump(key: str, n: int = 1) -> None:
    with _lock:
        _totals[key] = _totals.get(key, 0) + n


def codec_totals() -> Dict[str, int]:
    """Flat monotone counters (metrics/registry.py ``codec`` family):
    grad_enc_fp16/int8, grad_enc_raw_fallback, grad_dec, grad_bytes_raw/
    grad_bytes_wire, snap_compressed, snap_incompressible,
    snap_bytes_raw/snap_bytes_wire, snap_decompressed."""
    with _lock:
        return dict(_totals)


def reset_codec_totals() -> None:
    with _lock:
        _totals.clear()


# --------------------------------------------------------- native loading
#: native symbol -> same-module pure-Python oracle (``native-oracle``
#: lint table; every pair is property-tested for bit identity)
NATIVE_ORACLES = {
    "wc_enc_fp16": "_py_enc_fp16",
    "wc_enc_int8": "_py_enc_int8",
    "wc_dec_fp16": "_py_dec_fp16",
    "wc_dec_int8": "_py_dec_int8",
    "wc_shuffle4": "_py_shuffle4",
    "wc_unshuffle4": "_py_unshuffle4",
    "wc_delta_idx": "_py_delta_idx",
    "wc_cumsum_idx": "_py_cumsum_idx",
}

_NATIVE = None


def _native_lib():
    global _NATIVE
    if _NATIVE is not None:
        return _NATIVE or None
    lib = None
    try:
        from asyncframework_tpu.native_build import ensure_built

        built = ensure_built("wirecodec")
        if built:
            lib = ctypes.CDLL(built)
            P, LL = ctypes.c_void_p, ctypes.c_longlong
            lib.wc_enc_fp16.restype = ctypes.c_int
            lib.wc_enc_fp16.argtypes = [P, P, LL, P, P, ctypes.c_double]
            lib.wc_enc_int8.restype = ctypes.c_int
            lib.wc_enc_int8.argtypes = [P, P, LL, P, P, P]
            lib.wc_dec_fp16.restype = None
            lib.wc_dec_fp16.argtypes = [P, LL, P]
            lib.wc_dec_int8.restype = None
            lib.wc_dec_int8.argtypes = [P, LL, ctypes.c_float, P]
            lib.wc_shuffle4.restype = None
            lib.wc_shuffle4.argtypes = [P, LL, P]
            lib.wc_unshuffle4.restype = None
            lib.wc_unshuffle4.argtypes = [P, LL, P]
            lib.wc_delta_idx.restype = None
            lib.wc_delta_idx.argtypes = [P, LL, P]
            lib.wc_cumsum_idx.restype = None
            lib.wc_cumsum_idx.argtypes = [P, LL, P]
    except Exception:  # noqa: BLE001 - fall back to Python
        lib = None
    _NATIVE = lib or False
    return lib


def _use_native():
    from asyncframework_tpu.conf import NATIVE_ENABLED, global_conf

    if not global_conf().get(NATIVE_ENABLED):
        return None
    lib = _native_lib()
    if lib is None:
        _bump_native("python_fallbacks")
    return lib


def _addr(a: np.ndarray) -> ctypes.c_void_p:
    return ctypes.c_void_p(a.ctypes.data)


# ------------------------------------------------------------ gradient path
def grad_error_bound(codec: str, absmax: float) -> float:
    """Per-coordinate quantization error bound of ONE encode whose input
    (gradient + carried residual) has max-abs ``absmax``.  This is also
    the bound on the error-feedback residual itself, and therefore on
    the model's deviation from the uncompressed trajectory at any time
    (times the step size): the residual never compounds, because every
    encode folds the previous residual back in before quantizing."""
    if codec == INT8:
        # scale = absmax/127, rint rounds to the nearest level: s/2
        return absmax / 254.0
    if codec == FP16:
        return absmax * _FP16_REL + _FP16_ABS
    return 0.0


def _py_enc_fp16(x: np.ndarray, absmax: float):
    """fp16 oracle: returns (hdr, payload, new_err) or None (overflow).
    ``x`` is the residual-folded f32 input, known finite."""
    if absmax > _FP16_SAFE_MAX:
        return None
    q = x.astype(np.float16)
    applied = q.astype(np.float32)
    return {"gq": FP16}, q.tobytes(), x - applied


def _py_enc_int8(x: np.ndarray, absmax: float):
    """int8 oracle: returns (hdr, payload, new_err); never refuses."""
    scale = absmax / 127.0
    if scale > 0.0:
        q = np.clip(np.rint(x / scale), -127, 127).astype(np.int8)
        applied = q.astype(np.float32) * np.float32(scale)
    else:
        q = np.zeros(x.shape, np.int8)
        applied = np.zeros(x.shape, np.float32)
    return {"gq": INT8, "gs": float(scale)}, q.tobytes(), x - applied


@_prof.zoned("wire.quantize")
def encode_grad(g: np.ndarray, codec: str, err: Optional[np.ndarray]
                ) -> Optional[Tuple[dict, bytes, np.ndarray]]:
    """Quantize ``g`` (float32) with error feedback.

    ``err`` is this worker's carried residual (None on the first push).
    Returns ``(header_fields, payload, new_err)``, or **None** when the
    push must ship raw f32: codec off, non-finite input (a NaN/inf
    gradient quantizes to garbage -- exactness is the only safe
    encoding), or an fp16-overflowing magnitude.  On the None path the
    residual is NOT consumed -- it simply rides to the next quantized
    push (the raw push is exact, so skipping the fold loses nothing).
    """
    if codec == OFF:
        return None
    if codec not in GRAD_CODECS:
        raise ValueError(f"unknown gradient codec {codec!r}")
    lib = _use_native()
    if (lib is not None and g.flags.c_contiguous
            and (err is None
                 or (err.flags.c_contiguous and err.size == g.size))):
        # the C twin folds the residual, scans finiteness, and
        # quantizes in ONE GIL-free pass; refusal statuses mirror the
        # oracle's None paths exactly
        n = int(g.size)
        new_err = np.empty(n, np.float32).reshape(g.shape)
        earg = _addr(err) if err is not None else None
        _bump_native("native_calls.quantize")
        if codec == FP16:
            q16 = np.empty(n, np.uint16)
            st = lib.wc_enc_fp16(_addr(g), earg, n, _addr(q16),
                                 _addr(new_err), _FP16_SAFE_MAX)
            if st != 0:  # 1 = non-finite, 2 = overflow
                _bump("grad_enc_raw_fallback")
                return None
            hdr, payload = {"gq": FP16}, q16.tobytes()
            _bump("grad_enc_fp16")
        else:  # INT8
            q8 = np.empty(n, np.int8)
            sc = ctypes.c_double()
            st = lib.wc_enc_int8(_addr(g), earg, n, _addr(q8),
                                 _addr(new_err), ctypes.byref(sc))
            if st != 0:
                _bump("grad_enc_raw_fallback")
                return None
            hdr, payload = {"gq": INT8, "gs": float(sc.value)}, q8.tobytes()
            _bump("grad_enc_int8")
        _bump("grad_bytes_raw", int(g.nbytes))
        _bump("grad_bytes_wire", len(payload))
        return hdr, payload, new_err
    _bump_native("python_calls.quantize")
    x = g + err if err is not None else np.array(g, np.float32)
    if not np.isfinite(x).all():
        _bump("grad_enc_raw_fallback")
        return None
    absmax = float(np.max(np.abs(x))) if x.size else 0.0
    if codec == FP16:
        enc = _py_enc_fp16(x, absmax)
        if enc is None:
            _bump("grad_enc_raw_fallback")
            return None
        _bump("grad_enc_fp16")
    else:  # INT8
        enc = _py_enc_int8(x, absmax)
        _bump("grad_enc_int8")
    hdr, payload, new_err = enc
    _bump("grad_bytes_raw", int(g.nbytes))
    _bump("grad_bytes_wire", len(payload))
    return hdr, payload, new_err


def _py_dec_fp16(payload) -> np.ndarray:
    return np.frombuffer(payload, np.float16).astype(np.float32)


def _py_dec_int8(payload, gs: float) -> np.ndarray:
    return (np.frombuffer(payload, np.int8).astype(np.float32)
            * np.float32(gs))


@_prof.zoned("wire.quantize")
def decode_grad(header: dict, payload, d: int) -> np.ndarray:
    """Server-side decode of a quantized PUSH payload back to float32.
    Raises ``ValueError`` on a malformed frame (wrong codec tag or
    payload length) -- the server answers ERR instead of applying."""
    gq = header.get("gq")
    lib = _use_native()
    if gq == FP16:
        if len(payload) != 2 * d:
            raise ValueError(f"fp16 push wants {2 * d} bytes, "
                             f"got {len(payload)}")
        if lib is not None:
            q = np.frombuffer(payload, np.uint16)
            g = np.empty(d, np.float32)
            lib.wc_dec_fp16(_addr(q), d, _addr(g))
            _bump_native("native_calls.quantize")
        else:
            _bump_native("python_calls.quantize")
            g = _py_dec_fp16(payload)
    elif gq == INT8:
        if len(payload) != d:
            raise ValueError(f"int8 push wants {d} bytes, "
                             f"got {len(payload)}")
        gs = header.get("gs")
        if gs is None or not np.isfinite(float(gs)) or float(gs) < 0.0:
            # a missing/garbage scale must answer ERR, not silently
            # apply an all-zero (or poisoned) gradient
            raise ValueError(f"int8 push with bad scale {gs!r}")
        if lib is not None:
            q = np.frombuffer(payload, np.int8)
            g = np.empty(d, np.float32)
            lib.wc_dec_int8(_addr(q), d,
                            ctypes.c_float(np.float32(gs)), _addr(g))
            _bump_native("native_calls.quantize")
        else:
            _bump_native("python_calls.quantize")
            g = _py_dec_int8(payload, gs)
    else:
        raise ValueError(f"unknown gradient codec tag {gq!r}")
    _bump("grad_dec")
    return g


# ------------------------------------------------------------ snapshot path
#: do not bother compressing payloads under this (zlib header overhead)
_SNAP_MIN_BYTES = 64
#: deflate level for snapshot deltas: the relay plane trades a little
#: encode CPU for wire bytes by design (one encode serves a subtree)
_SNAP_LEVEL = 6


def _py_shuffle4(payload: bytes) -> bytes:
    return np.frombuffer(payload, np.uint8).reshape(-1, 4).T.tobytes()


def _py_unshuffle4(payload: bytes) -> bytes:
    a = np.frombuffer(payload, np.uint8).reshape(4, -1).T
    return np.ascontiguousarray(a).tobytes()


def _py_delta_idx(idx: np.ndarray) -> np.ndarray:
    return np.diff(idx, prepend=np.uint32(0)).astype(np.uint32)


def _py_cumsum_idx(idxd: np.ndarray) -> np.ndarray:
    return np.cumsum(idxd.astype(np.uint64)).astype(np.uint32)


def _shuffle4(payload: bytes) -> bytes:
    """Byte-plane transposition over 4-byte words (the Blosc/HDF5
    shuffle filter): all byte-0s, then all byte-1s, ...  XOR words of
    consecutive training versions agree in their high bytes, so the
    transposed planes are runs deflate actually crunches.  Exact
    inverse in :func:`_unshuffle4`; requires word alignment."""
    lib = _use_native()
    if lib is not None:
        src = np.frombuffer(payload, np.uint8)
        dst = np.empty(src.size, np.uint8)
        lib.wc_shuffle4(_addr(src), src.size, _addr(dst))
        _bump_native("native_calls.shuffle")
        return dst.tobytes()
    _bump_native("python_calls.shuffle")
    return _py_shuffle4(payload)


def _unshuffle4(payload: bytes) -> bytes:
    lib = _use_native()
    if lib is not None:
        src = np.frombuffer(payload, np.uint8)
        dst = np.empty(src.size, np.uint8)
        lib.wc_unshuffle4(_addr(src), src.size, _addr(dst))
        _bump_native("native_calls.shuffle")
        return dst.tobytes()
    _bump_native("python_calls.shuffle")
    return _py_unshuffle4(payload)


def _delta_idx(idx: np.ndarray) -> np.ndarray:
    lib = _use_native()
    if lib is not None:
        out = np.empty(idx.size, np.uint32)
        lib.wc_delta_idx(_addr(idx), int(idx.size), _addr(out))
        _bump_native("native_calls.shuffle")
        return out
    _bump_native("python_calls.shuffle")
    return _py_delta_idx(idx)


def _cumsum_idx(idxd: np.ndarray) -> np.ndarray:
    lib = _use_native()
    if lib is not None:
        out = np.empty(idxd.size, np.uint32)
        lib.wc_cumsum_idx(_addr(idxd), int(idxd.size), _addr(out))
        _bump_native("native_calls.shuffle")
        return out
    _bump_native("python_calls.shuffle")
    return _py_cumsum_idx(idxd)


@_prof.zoned("wire.compress")
def compress_model_part(wenc: str, payload: bytes, nnz: int = 0
                        ) -> Tuple[dict, bytes]:
    """LOSSLESS compression of a model-part payload for the relay wire.

    Structure-aware, tag carried as the ``cz`` header field:

    - ``zd`` (sparse XOR delta with known ``nnz``): the ascending index
      half is delta-encoded (consecutive differences -- small ints with
      three near-zero byte planes) and both halves byte-shuffled before
      deflate;
    - ``zs`` (any word-aligned payload -- XFULL dense xor, FULL f32):
      byte-shuffle + deflate;
    - ``z``: plain deflate (unaligned fallback).

    Whichever candidate is smallest ships; if none beats raw, the
    payload ships unchanged (fields empty).  The consumer inverts the
    transform BEFORE ``wiredelta.decode``, so CRC gating sees exactly
    the original bytes -- compression can fail to help, never corrupt.
    """
    n = len(payload)
    if n < _SNAP_MIN_BYTES:
        return {}, payload
    best = ({}, payload)
    if wenc == "xdelta" and nnz > 0 and n == 8 * nnz:
        idx = np.frombuffer(payload[: 4 * nnz], np.uint32)
        idxd = _delta_idx(idx)
        z = zlib.compress(_shuffle4(idxd.tobytes())
                          + _shuffle4(payload[4 * nnz:]), _SNAP_LEVEL)
        if len(z) < len(best[1]):
            best = ({"cz": "zd", "ulen": n}, z)
    if n % 4 == 0:
        z = zlib.compress(_shuffle4(payload), _SNAP_LEVEL)
        if len(z) < len(best[1]):
            best = ({"cz": "zs", "ulen": n}, z)
    else:
        z = zlib.compress(payload, 1)
        if len(z) < len(best[1]):
            best = ({"cz": "z", "ulen": n}, z)
    if not best[0]:
        _bump("snap_incompressible")
        return best
    _bump("snap_compressed")
    _bump("snap_bytes_raw", n)
    _bump("snap_bytes_wire", len(best[1]))
    return best


@_prof.zoned("wire.compress")
def decompress_model_part(header: dict, payload) -> bytes:
    """Undo :func:`compress_model_part` (no-op for an uncompressed
    reply).  Raises ``ValueError`` on corrupt/length-mismatched data --
    callers treat it like a CRC mismatch (full-refetch fallback)."""
    cz = header.get("cz")
    if cz is None:
        return bytes(payload)
    if cz not in ("z", "zs", "zd"):
        raise ValueError(f"unknown compression tag {cz!r}")
    try:
        out = zlib.decompress(bytes(payload))
    except zlib.error as e:
        raise ValueError(f"corrupt compressed payload: {e}") from e
    ulen = int(header.get("ulen", -1))
    if len(out) != ulen:
        raise ValueError(f"decompressed to {len(out)} bytes, "
                         f"header says {ulen}")
    if cz == "zd":
        nnz = int(header.get("nnz", 0))
        if ulen != 8 * nnz or nnz <= 0:
            raise ValueError(f"zd payload: ulen={ulen} vs nnz={nnz}")
        idxd = np.frombuffer(_unshuffle4(out[: 4 * nnz]), np.uint32)
        xorw = _unshuffle4(out[4 * nnz:])
        idx = _cumsum_idx(idxd)
        out = idx.tobytes() + xorw
    elif cz == "zs":
        if ulen % 4 != 0:
            raise ValueError(f"zs payload: unaligned ulen={ulen}")
        out = _unshuffle4(out)
    _bump("snap_decompressed")
    return out
