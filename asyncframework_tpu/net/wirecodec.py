"""Wire-compression codec layer: quantized gradients + compressed deltas.

ASAP (arXiv:1612.08608) argues the right trade for asynchronous data-
parallel systems is *approximate with bounded error* on the wire; the
gradient-compression line of work (1-bit SGD, QSGD, error-feedback SGD)
makes that concrete for the PUSH path: quantize each gradient, keep the
quantization residual in a per-worker **error-feedback accumulator**, and
fold it into the next gradient before quantizing again.  The model then
never drifts unboundedly: after T pushes the applied sum equals the true
gradient sum minus only the CURRENT residual, and the residual is bounded
by one step's quantization error (see :func:`grad_error_bound`).

Two independent codecs live here, both conf-gated and both **off by
default = byte-identical wire** (the repo-wide discipline: every plane's
legacy wire is asserted byte-identical via per-op frame totals when its
knob is absent):

- **gradient quantization** (``async.codec.push`` = ``fp16`` | ``int8``):
  lossy-but-error-fed encode of dense ASGD PUSH payloads.  fp16 halves
  the gradient bytes; int8 (per-push max-abs scale) quarters them.
  Non-finite gradients (NaN/inf), fp16-overflowing magnitudes, sparse-
  encoded pushes, and ASAGA pushes (whose history scalars must be exact)
  all fall back to the raw f32 wire -- the codec degrades to exact,
  never to poisoned.

- **snapshot-delta compression** (the relaycast plane's
  ``async.relay.compress``): **lossless** zlib over the XOR-delta /
  full model payloads of ``net/wiredelta.py``.  XOR deltas of a
  training step are structurally compressible (sign/exponent bits of
  consecutive versions agree, so xor words lead with zero bytes, and
  the index half is ascending u32), and losslessness means the
  CRC-gating contract is untouched: decompress, then the stock decode
  verifies the version CRC exactly as before.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from asyncframework_tpu.metrics import profiler as _prof

#: gradient-codec names (``async.codec.push`` values)
OFF = "off"
FP16 = "fp16"
INT8 = "int8"
GRAD_CODECS = (OFF, FP16, INT8)

#: fp16 magnitudes past this overflow to inf; ship such pushes raw
_FP16_SAFE_MAX = 6.0e4
#: fp16 relative quantization error (one ulp at 11 significand bits)
_FP16_REL = 2.0 ** -11
#: fp16 subnormal floor (absolute error near zero)
_FP16_ABS = 6.0e-8

_lock = threading.Lock()
_totals: Dict[str, int] = {}


def _bump(key: str, n: int = 1) -> None:
    with _lock:
        _totals[key] = _totals.get(key, 0) + n


def codec_totals() -> Dict[str, int]:
    """Flat monotone counters (metrics/registry.py ``codec`` family):
    grad_enc_fp16/int8, grad_enc_raw_fallback, grad_dec, grad_bytes_raw/
    grad_bytes_wire, snap_compressed, snap_incompressible,
    snap_bytes_raw/snap_bytes_wire, snap_decompressed."""
    with _lock:
        return dict(_totals)


def reset_codec_totals() -> None:
    with _lock:
        _totals.clear()


# ------------------------------------------------------------ gradient path
def grad_error_bound(codec: str, absmax: float) -> float:
    """Per-coordinate quantization error bound of ONE encode whose input
    (gradient + carried residual) has max-abs ``absmax``.  This is also
    the bound on the error-feedback residual itself, and therefore on
    the model's deviation from the uncompressed trajectory at any time
    (times the step size): the residual never compounds, because every
    encode folds the previous residual back in before quantizing."""
    if codec == INT8:
        # scale = absmax/127, rint rounds to the nearest level: s/2
        return absmax / 254.0
    if codec == FP16:
        return absmax * _FP16_REL + _FP16_ABS
    return 0.0


@_prof.zoned("wire.quantize")
def encode_grad(g: np.ndarray, codec: str, err: Optional[np.ndarray]
                ) -> Optional[Tuple[dict, bytes, np.ndarray]]:
    """Quantize ``g`` (float32) with error feedback.

    ``err`` is this worker's carried residual (None on the first push).
    Returns ``(header_fields, payload, new_err)``, or **None** when the
    push must ship raw f32: codec off, non-finite input (a NaN/inf
    gradient quantizes to garbage -- exactness is the only safe
    encoding), or an fp16-overflowing magnitude.  On the None path the
    residual is NOT consumed -- it simply rides to the next quantized
    push (the raw push is exact, so skipping the fold loses nothing).
    """
    if codec == OFF:
        return None
    if codec not in GRAD_CODECS:
        raise ValueError(f"unknown gradient codec {codec!r}")
    x = g + err if err is not None else np.array(g, np.float32)
    if not np.isfinite(x).all():
        _bump("grad_enc_raw_fallback")
        return None
    absmax = float(np.max(np.abs(x))) if x.size else 0.0
    if codec == FP16:
        if absmax > _FP16_SAFE_MAX:
            _bump("grad_enc_raw_fallback")
            return None
        q = x.astype(np.float16)
        applied = q.astype(np.float32)
        hdr = {"gq": FP16}
        payload = q.tobytes()
        _bump("grad_enc_fp16")
    else:  # INT8
        scale = absmax / 127.0
        if scale > 0.0:
            q = np.clip(np.rint(x / scale), -127, 127).astype(np.int8)
            applied = q.astype(np.float32) * np.float32(scale)
        else:
            q = np.zeros(x.shape, np.int8)
            applied = np.zeros(x.shape, np.float32)
        hdr = {"gq": INT8, "gs": float(scale)}
        payload = q.tobytes()
        _bump("grad_enc_int8")
    new_err = x - applied
    _bump("grad_bytes_raw", int(g.nbytes))
    _bump("grad_bytes_wire", len(payload))
    return hdr, payload, new_err


@_prof.zoned("wire.quantize")
def decode_grad(header: dict, payload, d: int) -> np.ndarray:
    """Server-side decode of a quantized PUSH payload back to float32.
    Raises ``ValueError`` on a malformed frame (wrong codec tag or
    payload length) -- the server answers ERR instead of applying."""
    gq = header.get("gq")
    if gq == FP16:
        if len(payload) != 2 * d:
            raise ValueError(f"fp16 push wants {2 * d} bytes, "
                             f"got {len(payload)}")
        g = np.frombuffer(payload, np.float16).astype(np.float32)
    elif gq == INT8:
        if len(payload) != d:
            raise ValueError(f"int8 push wants {d} bytes, "
                             f"got {len(payload)}")
        gs = header.get("gs")
        if gs is None or not np.isfinite(float(gs)) or float(gs) < 0.0:
            # a missing/garbage scale must answer ERR, not silently
            # apply an all-zero (or poisoned) gradient
            raise ValueError(f"int8 push with bad scale {gs!r}")
        g = (np.frombuffer(payload, np.int8).astype(np.float32)
             * np.float32(gs))
    else:
        raise ValueError(f"unknown gradient codec tag {gq!r}")
    _bump("grad_dec")
    return g


# ------------------------------------------------------------ snapshot path
#: do not bother compressing payloads under this (zlib header overhead)
_SNAP_MIN_BYTES = 64
#: deflate level for snapshot deltas: the relay plane trades a little
#: encode CPU for wire bytes by design (one encode serves a subtree)
_SNAP_LEVEL = 6


def _shuffle4(payload: bytes) -> bytes:
    """Byte-plane transposition over 4-byte words (the Blosc/HDF5
    shuffle filter): all byte-0s, then all byte-1s, ...  XOR words of
    consecutive training versions agree in their high bytes, so the
    transposed planes are runs deflate actually crunches.  Exact
    inverse in :func:`_unshuffle4`; requires word alignment."""
    return np.frombuffer(payload, np.uint8).reshape(-1, 4).T.tobytes()


def _unshuffle4(payload: bytes) -> bytes:
    a = np.frombuffer(payload, np.uint8).reshape(4, -1).T
    return np.ascontiguousarray(a).tobytes()


@_prof.zoned("wire.compress")
def compress_model_part(wenc: str, payload: bytes, nnz: int = 0
                        ) -> Tuple[dict, bytes]:
    """LOSSLESS compression of a model-part payload for the relay wire.

    Structure-aware, tag carried as the ``cz`` header field:

    - ``zd`` (sparse XOR delta with known ``nnz``): the ascending index
      half is delta-encoded (consecutive differences -- small ints with
      three near-zero byte planes) and both halves byte-shuffled before
      deflate;
    - ``zs`` (any word-aligned payload -- XFULL dense xor, FULL f32):
      byte-shuffle + deflate;
    - ``z``: plain deflate (unaligned fallback).

    Whichever candidate is smallest ships; if none beats raw, the
    payload ships unchanged (fields empty).  The consumer inverts the
    transform BEFORE ``wiredelta.decode``, so CRC gating sees exactly
    the original bytes -- compression can fail to help, never corrupt.
    """
    n = len(payload)
    if n < _SNAP_MIN_BYTES:
        return {}, payload
    best = ({}, payload)
    if wenc == "xdelta" and nnz > 0 and n == 8 * nnz:
        idx = np.frombuffer(payload[: 4 * nnz], np.uint32)
        idxd = np.diff(idx, prepend=np.uint32(0)).astype(np.uint32)
        z = zlib.compress(_shuffle4(idxd.tobytes())
                          + _shuffle4(payload[4 * nnz:]), _SNAP_LEVEL)
        if len(z) < len(best[1]):
            best = ({"cz": "zd", "ulen": n}, z)
    if n % 4 == 0:
        z = zlib.compress(_shuffle4(payload), _SNAP_LEVEL)
        if len(z) < len(best[1]):
            best = ({"cz": "zs", "ulen": n}, z)
    else:
        z = zlib.compress(payload, 1)
        if len(z) < len(best[1]):
            best = ({"cz": "z", "ulen": n}, z)
    if not best[0]:
        _bump("snap_incompressible")
        return best
    _bump("snap_compressed")
    _bump("snap_bytes_raw", n)
    _bump("snap_bytes_wire", len(best[1]))
    return best


@_prof.zoned("wire.compress")
def decompress_model_part(header: dict, payload) -> bytes:
    """Undo :func:`compress_model_part` (no-op for an uncompressed
    reply).  Raises ``ValueError`` on corrupt/length-mismatched data --
    callers treat it like a CRC mismatch (full-refetch fallback)."""
    cz = header.get("cz")
    if cz is None:
        return bytes(payload)
    if cz not in ("z", "zs", "zd"):
        raise ValueError(f"unknown compression tag {cz!r}")
    try:
        out = zlib.decompress(bytes(payload))
    except zlib.error as e:
        raise ValueError(f"corrupt compressed payload: {e}") from e
    ulen = int(header.get("ulen", -1))
    if len(out) != ulen:
        raise ValueError(f"decompressed to {len(out)} bytes, "
                         f"header says {ulen}")
    if cz == "zd":
        nnz = int(header.get("nnz", 0))
        if ulen != 8 * nnz or nnz <= 0:
            raise ValueError(f"zd payload: ulen={ulen} vs nnz={nnz}")
        idxd = np.frombuffer(_unshuffle4(out[: 4 * nnz]), np.uint32)
        xorw = _unshuffle4(out[4 * nnz:])
        idx = np.cumsum(idxd.astype(np.uint64)).astype(np.uint32)
        out = idx.tobytes() + xorw
    elif cz == "zs":
        if ulen % 4 != 0:
            raise ValueError(f"zs payload: unaligned ulen={ulen}")
        out = _unshuffle4(out)
    _bump("snap_decompressed")
    return out
