"""Deterministic, schedule-driven network fault injection.

The DCN plane's chaos story was "kill -9 and hope": real, but neither
replayable nor precise.  This module is the network-layer sibling of
``engine/straggler.py``'s compute delays -- faults are *scheduled*, keyed
by ``(endpoint, op, nth-occurrence)``, and a run with the same schedule
and the same client-side op sequence fires the same faults at the same
protocol points, so a chaos result can be replayed bit-for-bit.

Fault kinds (where in the exchange they bite):

- ``connect_refused``  -- the dial itself fails (daemon not up / port
  blackholed).  Nothing was sent.
- ``cut_mid_frame``    -- the request frame is truncated on the wire and
  the connection dies.  The server never applied the op.
- ``stall_read``       -- the request was delivered (and applied!) but the
  reply never arrives; the client's read times out.
- ``drop_reply``       -- the request was delivered and applied; the reply
  is lost.  The classic duplicate-generator: a naive client re-sends.

``stall_read`` and ``drop_reply`` are the cases that make bare retry
UNSAFE and are exactly what ``net/session.py``'s dedup windows exist for.

Hook points live in ``net/frame.py`` (:func:`connect`, :func:`send_msg`,
:func:`recv_msg`); installation is process-global (:func:`install` /
:func:`clear` / the :func:`injected` context manager), with
:func:`maybe_install_from_conf` for daemons configured via
``async.net.fault.schedule``.  Endpoint patterns: exact ``host:port``,
``*:port`` (any host), or ``*`` (any endpoint).
"""

from __future__ import annotations

import json
import threading
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

CONNECT_REFUSED = "connect_refused"
CUT_MID_FRAME = "cut_mid_frame"
STALL_READ = "stall_read"
DROP_REPLY = "drop_reply"

KINDS = (CONNECT_REFUSED, CUT_MID_FRAME, STALL_READ, DROP_REPLY)

#: the pseudo-op a ``connect_refused`` event matches (the dial has no header)
CONNECT_OP = "CONNECT"

_totals_lock = threading.Lock()
_faults_fired = 0


def faults_fired_total() -> int:
    """Process-wide count of injected faults (metrics/live UI)."""
    with _totals_lock:
        return _faults_fired


def reset_faults_fired_total() -> None:
    """Zero the process-wide counter (per-run isolation; see
    ``asyncframework_tpu.metrics.reset_totals``)."""
    global _faults_fired
    with _totals_lock:
        _faults_fired = 0


def _bump_fired() -> None:
    global _faults_fired
    with _totals_lock:
        _faults_fired += 1


@dataclass
class FaultEvent:
    """One scheduled fault: fires on the ``nth`` matching occurrence of
    ``op`` toward ``endpoint`` (1-based; each event fires exactly once).

    ``op`` may be an exact op, ``*`` (any), or a ``|``-alternation such as
    ``"PUSH|PUSH_SAGA"`` -- one event covering a protocol family (the DCN
    ASAGA ops ride their own verbs so schedules can tell the two solvers'
    streams apart, but a schedule aimed at "any gradient push" should not
    need two events with independent counters)."""

    endpoint: str
    op: str
    nth: int
    kind: str
    _count: int = field(default=0, repr=False)
    fired: bool = field(default=False, repr=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {KINDS}")
        if self.nth < 1:
            raise ValueError("nth is 1-based and must be >= 1")

    def matches(self, endpoint: str, op: str) -> bool:
        if self.op != "*" and op not in self.op.split("|"):
            return False
        pat = self.endpoint
        if pat == "*" or pat == endpoint:
            return True
        if pat.startswith("*:"):
            return endpoint.rsplit(":", 1)[-1] == pat[2:]
        return False


@dataclass
class FaultSchedule:
    """A replayable list of :class:`FaultEvent`, plus the seed chaos runs
    hand to their retry policies (one number pins the whole run)."""

    events: List[FaultEvent] = field(default_factory=list)
    seed: int = 0

    def add(self, endpoint: str, op: str, nth: int, kind: str
            ) -> "FaultSchedule":
        self.events.append(FaultEvent(endpoint, op, nth, kind))
        return self

    def to_json(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "events": [
                {"endpoint": e.endpoint, "op": e.op,
                 "nth": e.nth, "kind": e.kind}
                for e in self.events
            ],
        })

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        raw = json.loads(text)
        sched = cls(seed=int(raw.get("seed", 0)))
        for e in raw.get("events", []):
            sched.add(e["endpoint"], e["op"], int(e["nth"]), e["kind"])
        return sched


class FaultInjector:
    """Evaluates a :class:`FaultSchedule` against the live op stream.

    Each event keeps its own occurrence counter, so matching is
    deterministic per (endpoint, op) stream regardless of what other
    endpoints are doing.  ``fired`` is the journal a replay asserts
    against."""

    def __init__(self, schedule: FaultSchedule):
        self.schedule = schedule
        self._lock = threading.Lock()
        # id(sock) -> (weakref(sock), kind) for that socket's next recv;
        # the weakref guards against CPython id() reuse handing a stale
        # fault to an unrelated future socket
        self._armed: Dict[int, Tuple[weakref.ref, str]] = {}
        self.fired: List[Dict] = []

    # ------------------------------------------------------------- matching
    def _fire(self, endpoint: str, op: str) -> Optional[str]:
        """Count this occurrence against every live matching event; return
        the kind of the first event whose ``nth`` is reached."""
        with self._lock:
            hit: Optional[FaultEvent] = None
            for ev in self.schedule.events:
                if ev.fired or not ev.matches(endpoint, op):
                    continue
                ev._count += 1
                if hit is None and ev._count == ev.nth:
                    ev.fired = True
                    hit = ev
            if hit is None:
                return None
            self.fired.append({"endpoint": endpoint, "op": op,
                               "nth": hit.nth, "kind": hit.kind})
        _bump_fired()
        return hit.kind

    # ----------------------------------------------------------- hook sites
    def check_connect(self, endpoint: str) -> None:
        kind = self._fire(endpoint, CONNECT_OP)
        if kind == CONNECT_REFUSED:
            raise ConnectionRefusedError(
                f"fault-injected: connection refused to {endpoint}"
            )

    def check_send(self, endpoint: str, op: str) -> Optional[str]:
        return self._fire(endpoint, op)

    def arm(self, sock, kind: str) -> None:
        with self._lock:
            self._armed[id(sock)] = (weakref.ref(sock), kind)

    def disarm(self, sock) -> Optional[str]:
        with self._lock:
            entry = self._armed.pop(id(sock), None)
        if entry is None:
            return None
        ref, kind = entry
        return kind if ref() is sock else None

    # -------------------------------------------------------------- reports
    def remaining(self) -> List[FaultEvent]:
        with self._lock:
            return [e for e in self.schedule.events if not e.fired]


_active_lock = threading.Lock()
_active: Optional[FaultInjector] = None


def active() -> Optional[FaultInjector]:
    return _active


def install(injector: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Install (or, with None, clear) the process's fault injector."""
    global _active
    with _active_lock:
        _active = injector
    return injector


def clear() -> None:
    install(None)


class injected:
    """``with faults.injected(schedule) as inj: ...`` -- scoped install."""

    def __init__(self, schedule: FaultSchedule):
        self.injector = FaultInjector(schedule)

    def __enter__(self) -> FaultInjector:
        install(self.injector)
        return self.injector

    def __exit__(self, *exc) -> None:
        clear()


def maybe_install_from_conf(conf=None) -> Optional[FaultInjector]:
    """Daemon entry points call this: when ``async.net.fault.schedule`` is
    set (inline JSON, or ``@/path/to/file``), install the injector so a
    subprocess chaos run needs no code changes -- just conf/env."""
    from asyncframework_tpu.conf import (
        NET_FAULT_SCHEDULE,
        NET_FAULT_SEED,
        global_conf,
    )

    conf = conf if conf is not None else global_conf()
    text = str(conf.get(NET_FAULT_SCHEDULE) or "").strip()
    if not text:
        return None
    if text.startswith("@"):
        with open(text[1:]) as f:
            text = f.read()
    sched = FaultSchedule.from_json(text)
    if "seed" not in json.loads(text):
        # a schedule without its own seed inherits the conf seed, so one
        # env var can re-pin a whole daemon fleet's chaos run
        sched.seed = int(conf.get(NET_FAULT_SEED))
    return install(FaultInjector(sched))
