"""Deterministic, schedule-driven network fault injection.

The DCN plane's chaos story was "kill -9 and hope": real, but neither
replayable nor precise.  This module is the network-layer sibling of
``engine/straggler.py``'s compute delays -- faults are *scheduled*, keyed
by ``(endpoint, op, nth-occurrence)``, and a run with the same schedule
and the same client-side op sequence fires the same faults at the same
protocol points, so a chaos result can be replayed bit-for-bit.

Fault kinds (where in the exchange they bite):

- ``connect_refused``  -- the dial itself fails (daemon not up / port
  blackholed).  Nothing was sent.
- ``cut_mid_frame``    -- the request frame is truncated on the wire and
  the connection dies.  The server never applied the op.
- ``stall_read``       -- the request was delivered (and applied!) but the
  reply never arrives; the client's read times out.
- ``drop_reply``       -- the request was delivered and applied; the reply
  is lost.  The classic duplicate-generator: a naive client re-sends.
- ``delay``            -- the op goes through, late: ``delay_ms`` plus
  seeded ``jitter_ms`` of added latency per matching op, for ``count``
  occurrences starting at the ``nth`` (0 = every one from there on).
  The slow-but-alive member -- the gray failure the suspicion state
  machine (parallel/supervisor.py) exists to catch.

``stall_read`` and ``drop_reply`` are the cases that make bare retry
UNSAFE and are exactly what ``net/session.py``'s dedup windows exist for.

**Partitions** are first-class, separate from one-shot events: a
:class:`PartitionEvent` blackholes every exchange with matching remote
endpoints for a scheduled window (``start_s``..``start_s + duration_s``
relative to injector install; ``duration_s=0`` holds until
:meth:`FaultInjector.heal_partitions`).  The drop is bidirectional at the
frame choke point -- dials refuse, sends die before any byte leaves, and
reads time out -- in whichever process the injector is installed; a
cross-process cut installs the complementary schedule on each side via
``async.net.fault.schedule``.  Unlike a kill, the partitioned peer keeps
running: it is the zombie the lease/epoch-fencing machinery
(parallel/supervisor.py, parallel/ps_dcn.py) must make harmless.

Hook points live in ``net/frame.py`` (:func:`connect`, :func:`send_msg`,
:func:`recv_msg`); installation is process-global (:func:`install` /
:func:`clear` / the :func:`injected` context manager), with
:func:`maybe_install_from_conf` for daemons configured via
``async.net.fault.schedule``.  Endpoint patterns: exact ``host:port``,
``*:port`` (any host), or ``*`` (any endpoint).
"""

from __future__ import annotations

import json
import random
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from asyncframework_tpu.metrics import flightrec as _flight

CONNECT_REFUSED = "connect_refused"
CUT_MID_FRAME = "cut_mid_frame"
STALL_READ = "stall_read"
DROP_REPLY = "drop_reply"
DELAY = "delay"
#: pseudo-kind the partition hooks report in the fired journal
PARTITION = "partition"

KINDS = (CONNECT_REFUSED, CUT_MID_FRAME, STALL_READ, DROP_REPLY, DELAY)

#: the pseudo-op a ``connect_refused`` event matches (the dial has no header)
CONNECT_OP = "CONNECT"

_totals_lock = threading.Lock()
_faults_fired = 0


def faults_fired_total() -> int:
    """Process-wide count of injected faults (metrics/live UI)."""
    with _totals_lock:
        return _faults_fired


def reset_faults_fired_total() -> None:
    """Zero the process-wide counter (per-run isolation; see
    ``asyncframework_tpu.metrics.reset_totals``)."""
    global _faults_fired
    with _totals_lock:
        _faults_fired = 0


def _bump_fired() -> None:
    global _faults_fired
    with _totals_lock:
        _faults_fired += 1


def _endpoint_matches(pat: str, endpoint: str) -> bool:
    if pat == "*" or pat == endpoint:
        return True
    if pat.startswith("*:"):
        return endpoint.rsplit(":", 1)[-1] == pat[2:]
    return False


@dataclass
class FaultEvent:
    """One scheduled fault: fires on the ``nth`` matching occurrence of
    ``op`` toward ``endpoint`` (1-based; each event fires exactly once).

    ``op`` may be an exact op, ``*`` (any), or a ``|``-alternation such as
    ``"PUSH|PUSH_SAGA"`` -- one event covering a protocol family (the DCN
    ASAGA ops ride their own verbs so schedules can tell the two solvers'
    streams apart, but a schedule aimed at "any gradient push" should not
    need two events with independent counters).

    ``delay`` events are the exception to fires-exactly-once: they bite
    occurrences ``nth`` .. ``nth + count - 1`` (``count=0`` = every
    occurrence from ``nth`` on), adding ``delay_ms`` plus a seeded
    uniform ``jitter_ms`` of latency while letting the op through."""

    endpoint: str
    op: str
    nth: int
    kind: str
    delay_ms: float = 0.0
    jitter_ms: float = 0.0
    count: int = 1
    _count: int = field(default=0, repr=False)
    fired: bool = field(default=False, repr=False)

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {KINDS}")
        if self.nth < 1:
            raise ValueError("nth is 1-based and must be >= 1")
        if self.count < 0:
            raise ValueError("count must be >= 0 (0 = unbounded)")

    def matches(self, endpoint: str, op: str) -> bool:
        if self.op != "*" and op not in self.op.split("|"):
            return False
        return _endpoint_matches(self.endpoint, endpoint)


@dataclass
class PartitionEvent:
    """A scheduled network partition: every exchange with a remote
    endpoint matching any pattern in ``endpoints`` is dropped while the
    event is active -- from ``start_s`` after injector install until
    ``start_s + duration_s`` (``duration_s=0`` = until
    :meth:`FaultInjector.heal_partitions`).  The blackhole is
    bidirectional at the choke point: dials refuse, sends die before a
    byte leaves, reads time out."""

    endpoints: List[str]
    start_s: float = 0.0
    duration_s: float = 0.0
    healed: bool = field(default=False, repr=False)

    def matches(self, endpoint: str) -> bool:
        return any(_endpoint_matches(p, endpoint) for p in self.endpoints)

    def active(self, elapsed_s: float) -> bool:
        if self.healed or elapsed_s < self.start_s:
            return False
        if self.duration_s <= 0:
            return True
        return elapsed_s < self.start_s + self.duration_s


@dataclass
class FaultSchedule:
    """A replayable list of :class:`FaultEvent` + :class:`PartitionEvent`,
    plus the seed chaos runs hand to their retry policies (one number
    pins the whole run)."""

    events: List[FaultEvent] = field(default_factory=list)
    partitions: List[PartitionEvent] = field(default_factory=list)
    seed: int = 0

    def add(self, endpoint: str, op: str, nth: int, kind: str
            ) -> "FaultSchedule":
        self.events.append(FaultEvent(endpoint, op, nth, kind))
        return self

    def add_delay(self, endpoint: str, op: str, delay_ms: float,
                  jitter_ms: float = 0.0, nth: int = 1, count: int = 1
                  ) -> "FaultSchedule":
        self.events.append(FaultEvent(endpoint, op, nth, DELAY,
                                      delay_ms=float(delay_ms),
                                      jitter_ms=float(jitter_ms),
                                      count=int(count)))
        return self

    def add_partition(self, endpoints: Sequence[str], start_s: float = 0.0,
                      duration_s: float = 0.0) -> "FaultSchedule":
        self.partitions.append(PartitionEvent(
            [str(e) for e in endpoints], float(start_s), float(duration_s)
        ))
        return self

    def to_json(self) -> str:
        events = []
        for e in self.events:
            rec = {"endpoint": e.endpoint, "op": e.op,
                   "nth": e.nth, "kind": e.kind}
            if e.kind == DELAY:
                rec.update(delay_ms=e.delay_ms, jitter_ms=e.jitter_ms,
                           count=e.count)
            events.append(rec)
        out = {"seed": self.seed, "events": events}
        if self.partitions:
            out["partitions"] = [
                {"endpoints": list(p.endpoints), "start_s": p.start_s,
                 "duration_s": p.duration_s}
                for p in self.partitions
            ]
        return json.dumps(out)

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        raw = json.loads(text)
        sched = cls(seed=int(raw.get("seed", 0)))
        for e in raw.get("events", []):
            if e.get("kind") == DELAY:
                sched.add_delay(e["endpoint"], e["op"],
                                float(e.get("delay_ms", 0.0)),
                                jitter_ms=float(e.get("jitter_ms", 0.0)),
                                nth=int(e.get("nth", 1)),
                                count=int(e.get("count", 1)))
            else:
                sched.add(e["endpoint"], e["op"], int(e["nth"]), e["kind"])
        for p in raw.get("partitions", []):
            sched.add_partition(p["endpoints"],
                                start_s=float(p.get("start_s", 0.0)),
                                duration_s=float(p.get("duration_s", 0.0)))
        return sched


class FaultInjector:
    """Evaluates a :class:`FaultSchedule` against the live op stream.

    Each event keeps its own occurrence counter, so matching is
    deterministic per (endpoint, op) stream regardless of what other
    endpoints are doing.  ``fired`` is the journal a replay asserts
    against."""

    #: fired-journal cap: a partition blackholing a retry storm must not
    #: grow the journal without bound (the counter keeps exact totals)
    JOURNAL_MAX = 4096

    def __init__(self, schedule: FaultSchedule):
        self.schedule = schedule
        self._lock = threading.Lock()
        # id(sock) -> (weakref(sock), kind) for that socket's next recv;
        # the weakref guards against CPython id() reuse handing a stale
        # fault to an unrelated future socket
        self._armed: Dict[int, Tuple[weakref.ref, str]] = {}
        self.fired: List[Dict] = []
        # partition clock: event windows are relative to install time
        self._t0 = time.monotonic()
        # seeded per-event jitter chains for delay events: deterministic
        # given (schedule.seed, event index), independent across events
        self._jitter: Dict[int, random.Random] = {
            i: random.Random((int(schedule.seed) << 16) ^ i)
            for i, ev in enumerate(schedule.events) if ev.kind == DELAY
        }

    def _journal(self, rec: Dict) -> None:
        if len(self.fired) < self.JOURNAL_MAX:
            self.fired.append(rec)
        # flight-recorder breadcrumb (metrics/flightrec.py): a chaos
        # post-mortem shows which scheduled faults fired right before
        # the end (no-op when no recorder is installed; the record rides
        # as one field -- its own "kind" key is the FAULT kind)
        _flight.note("fault", event=dict(rec))

    # ------------------------------------------------------------- matching
    def _fire(self, endpoint: str, op: str) -> Optional[str]:
        """Count this occurrence against every live matching event; return
        the kind of the first event whose ``nth`` is reached."""
        with self._lock:
            hit: Optional[FaultEvent] = None
            for ev in self.schedule.events:
                if ev.fired or ev.kind == DELAY \
                        or not ev.matches(endpoint, op):
                    continue
                ev._count += 1
                if hit is None and ev._count == ev.nth:
                    ev.fired = True
                    hit = ev
            if hit is None:
                return None
            self._journal({"endpoint": endpoint, "op": op,
                           "nth": hit.nth, "kind": hit.kind})
        _bump_fired()
        return hit.kind

    def delay_for(self, endpoint: str, op: str) -> float:
        """Seconds of injected latency this (endpoint, op) occurrence owes
        across every matching ``delay`` event.  Counts the occurrence per
        event; the caller sleeps OUTSIDE the injector lock."""
        total_ms = 0.0
        with self._lock:
            for i, ev in enumerate(self.schedule.events):
                if ev.kind != DELAY or ev.fired \
                        or not ev.matches(endpoint, op):
                    continue
                ev._count += 1
                if ev._count < ev.nth:
                    continue
                if ev.count and ev._count >= ev.nth + ev.count - 1:
                    ev.fired = True  # last occurrence this event bites
                ms = ev.delay_ms
                if ev.jitter_ms > 0:
                    ms += self._jitter[i].uniform(0.0, ev.jitter_ms)
                total_ms += ms
                self._journal({"endpoint": endpoint, "op": op,
                               "nth": ev._count, "kind": DELAY,
                               "delay_ms": round(ms, 3)})
        if total_ms > 0:
            _bump_fired()
        return total_ms / 1e3

    # ----------------------------------------------------------- partitions
    def partition_active(self, endpoint: str) -> bool:
        """Is ``endpoint`` currently on the far side of a partition?"""
        elapsed = time.monotonic() - self._t0
        with self._lock:
            return any(p.active(elapsed) and p.matches(endpoint)
                       for p in self.schedule.partitions)

    def note_partition_drop(self, endpoint: str, where: str) -> None:
        """Journal + count one exchange the partition ate."""
        with self._lock:
            self._journal({"endpoint": endpoint, "op": where,
                           "kind": PARTITION})
        _bump_fired()

    def heal_partitions(self) -> None:
        """End every partition now (the heals-on-schedule path needs no
        call; this is the explicit heal for duration_s=0 events and for
        tests that gate the heal on an assertion)."""
        with self._lock:
            for p in self.schedule.partitions:
                p.healed = True

    # ----------------------------------------------------------- hook sites
    def check_connect(self, endpoint: str) -> None:
        if self.partition_active(endpoint):
            self.note_partition_drop(endpoint, CONNECT_OP)
            raise ConnectionRefusedError(
                f"fault-injected: partitioned from {endpoint}"
            )
        kind = self._fire(endpoint, CONNECT_OP)
        if kind == CONNECT_REFUSED:
            raise ConnectionRefusedError(
                f"fault-injected: connection refused to {endpoint}"
            )

    def check_send(self, endpoint: str, op: str) -> Optional[str]:
        dly = self.delay_for(endpoint, op)
        if dly > 0:
            time.sleep(dly)
        return self._fire(endpoint, op)

    def arm(self, sock, kind: str) -> None:
        with self._lock:
            self._armed[id(sock)] = (weakref.ref(sock), kind)

    def disarm(self, sock) -> Optional[str]:
        with self._lock:
            entry = self._armed.pop(id(sock), None)
        if entry is None:
            return None
        ref, kind = entry
        return kind if ref() is sock else None

    # -------------------------------------------------------------- reports
    def remaining(self) -> List[FaultEvent]:
        with self._lock:
            return [e for e in self.schedule.events if not e.fired]


_active_lock = threading.Lock()
_active: Optional[FaultInjector] = None


def active() -> Optional[FaultInjector]:
    return _active


def install(injector: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Install (or, with None, clear) the process's fault injector."""
    global _active
    with _active_lock:
        _active = injector
    return injector


def clear() -> None:
    install(None)


class injected:
    """``with faults.injected(schedule) as inj: ...`` -- scoped install."""

    def __init__(self, schedule: FaultSchedule):
        self.injector = FaultInjector(schedule)

    def __enter__(self) -> FaultInjector:
        install(self.injector)
        return self.injector

    def __exit__(self, *exc) -> None:
        clear()


def maybe_install_from_conf(conf=None) -> Optional[FaultInjector]:
    """Daemon entry points call this: when ``async.net.fault.schedule`` is
    set (inline JSON, or ``@/path/to/file``), install the injector so a
    subprocess chaos run needs no code changes -- just conf/env."""
    from asyncframework_tpu.conf import (
        NET_FAULT_SCHEDULE,
        NET_FAULT_SEED,
        global_conf,
    )

    conf = conf if conf is not None else global_conf()
    text = str(conf.get(NET_FAULT_SCHEDULE) or "").strip()
    if not text:
        return None
    if text.startswith("@"):
        with open(text[1:]) as f:
            text = f.read()
    sched = FaultSchedule.from_json(text)
    if "seed" not in json.loads(text):
        # a schedule without its own seed inherits the conf seed, so one
        # env var can re-pin a whole daemon fleet's chaos run
        sched.seed = int(conf.get(NET_FAULT_SEED))
    return install(FaultInjector(sched))


# ------------------------------------------------------------ net profiles
def wan_profile_schedule(seed: int) -> FaultSchedule:
    """The ``--net-profile wan`` preset (bin/chaos_sweep.py): every op
    pays 15 ms + U(0, 15) ms of seeded latency, and a handful of seeded
    loss events (dropped replies, mid-frame cuts) land across the run --
    a deterministic stand-in for a jittery lossy wide-area link.  Suites
    OPT IN by merging it into their own schedules
    (:func:`profile_schedule_from_env` + :func:`merge_schedules`; the
    fencing/partition suite does) -- exact-replay suites keep their
    pinned schedules, since a merged profile would break the byte-replay
    determinism they assert."""
    sched = FaultSchedule(seed=int(seed))
    sched.add_delay("*", "*", delay_ms=15.0, jitter_ms=15.0,
                    nth=1, count=0)
    rng = random.Random(int(seed) ^ 0x5A5A)
    for op in ("PUSH|PUSH_SAGA", "PULL|PULL_SAGA", "SUBSCRIBE"):
        sched.add("*", op, rng.randint(3, 30), DROP_REPLY)
        sched.add("*", op, rng.randint(3, 30), CUT_MID_FRAME)
    return sched


def profile_schedule_from_env(seed: int = 0) -> Optional[FaultSchedule]:
    """The net-profile preset selected via ``ASYNC_CHAOS_NET_PROFILE``
    (set by ``bin/chaos_sweep.py --net-profile``); None when unset.
    Chaos tests MERGE this into their own schedules (see
    :func:`merge_schedules`) so every seeded scenario also runs under the
    profile's latency/loss floor."""
    import os

    name = os.environ.get("ASYNC_CHAOS_NET_PROFILE", "").strip()
    if not name or name == "none":
        return None
    if name == "wan":
        return wan_profile_schedule(seed)
    raise ValueError(f"unknown net profile {name!r} (know: wan)")


def merge_schedules(base: FaultSchedule,
                    extra: Optional[FaultSchedule]) -> FaultSchedule:
    """``base`` with ``extra``'s events/partitions appended (fresh event
    objects -- counters never shared across injectors); base's seed
    wins."""
    if extra is None:
        return base
    merged = FaultSchedule.from_json(base.to_json())
    for e in FaultSchedule.from_json(extra.to_json()).events:
        merged.events.append(e)
    for p in extra.partitions:
        merged.add_partition(p.endpoints, p.start_s, p.duration_s)
    return merged
