"""Gray-failure detection: per-endpoint op-RTT EWMA vs the cohort.

Crash failures announce themselves (a dead pid, a refused dial, silence
past the lease).  *Gray* failures do not: the member answers every probe,
renews its lease on every op, and is still useless -- a wedged disk, a
half-dead NIC, a CPU-starved pod.  The classic signature is relative
latency: the member's op round trips drift to a multiple of its cohort's
while everything else about it looks alive.

:class:`RttSuspector` is that detector, deliberately tiny: callers feed
it every op RTT they already measure (the ShardGroup's liveness probes,
the ServingFrontend's predict round trips), it keeps one EWMA per
endpoint, and an endpoint becomes **suspect** when its EWMA exceeds
``async.gray.rtt.factor`` times the median EWMA of its cohort peers (and
the ``async.gray.rtt.min.ms`` floor -- micro-jitter on a fast local
cohort is not a gray failure).  Suspicion is comparative by design: with
no peers to compare against it never fires (a uniformly slow link is a
deployment property, not a member failure).

Suspicion feeds the same membership state machine as silence
(``parallel/supervisor.py`` SUSPECT state): the member is demoted in
routing (frontend rotation, shard-probe reporting) and surfaced in
membership/metrics, but never *killed* on latency alone -- only lease
expiry or process exit escalates to DEAD.  That split is the point:
partitions and stragglers heal; a false kill plus a checkpoint-restored
replacement is a split brain.
"""

from __future__ import annotations

import statistics
import threading
from typing import Dict, Optional, Set

_totals_lock = threading.Lock()
_totals: Dict[str, int] = {}


def gray_totals() -> Dict[str, int]:
    """Process-global gray-failure counters: ``suspicions`` (endpoint
    transitions into latency-suspect), ``recoveries`` (transitions back
    out)."""
    with _totals_lock:
        return dict(_totals)


def reset_gray_totals() -> None:
    """Zero the process-global counters (per-run isolation; see
    ``asyncframework_tpu.metrics.reset_totals``)."""
    with _totals_lock:
        _totals.clear()


def _bump(key: str, n: int = 1) -> None:
    with _totals_lock:
        _totals[key] = _totals.get(key, 0) + n


class RttSuspector:
    """Per-endpoint RTT EWMA with cohort-relative suspicion.

    ``observe(endpoint, ms)`` folds one measured round trip and returns
    whether the endpoint is suspect NOW; ``is_suspect``/``suspects`` read
    the current verdicts without folding.  Thread-safe; one instance per
    cohort (the comparison set is "every endpoint this instance has
    seen")."""

    def __init__(self, factor: Optional[float] = None,
                 min_ms: Optional[float] = None, alpha: float = 0.25,
                 min_samples: int = 5, ttl_s: float = 30.0):
        if factor is None or min_ms is None:
            from asyncframework_tpu.conf import (
                GRAY_RTT_FACTOR,
                GRAY_RTT_MIN_MS,
                global_conf,
            )

            conf = global_conf()
            factor = factor if factor is not None \
                else conf.get(GRAY_RTT_FACTOR)
            min_ms = min_ms if min_ms is not None \
                else conf.get(GRAY_RTT_MIN_MS)
        self.factor = float(factor)
        self.min_ms = float(min_ms)
        self.alpha = float(alpha)
        self.min_samples = max(1, int(min_samples))
        # suspicion TTL: a verdict is only as fresh as its observations.
        # Routing demotes suspects, which can starve them of the very
        # traffic that would clear them (the frontend's predicts only
        # measure replicas that answer) -- so a suspicion older than
        # ``ttl_s`` without a new observation EXPIRES and the endpoint
        # re-earns its verdict from fresh samples.  Probe-driven callers
        # (the ShardGroup, which measures every member every tick) never
        # hit the TTL; traffic-driven callers need it for recovery.
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._ewma: Dict[str, float] = {}
        self._n: Dict[str, int] = {}
        self._last_obs: Dict[str, float] = {}
        self._suspect: Set[str] = set()

    @staticmethod
    def _now() -> float:
        import time

        return time.monotonic()

    def observe(self, endpoint: str, ms: float) -> bool:
        """Fold one RTT; returns True iff ``endpoint`` is suspect now."""
        ms = max(0.0, float(ms))
        with self._lock:
            prev = self._ewma.get(endpoint)
            self._ewma[endpoint] = (
                ms if prev is None
                else prev + self.alpha * (ms - prev)
            )
            self._n[endpoint] = self._n.get(endpoint, 0) + 1
            self._last_obs[endpoint] = self._now()
            return self._judge_locked(endpoint)

    def _expire_locked(self, endpoint: str) -> None:
        """Drop a suspicion whose observations went stale (the endpoint
        is starved of traffic BECAUSE it is demoted): it re-earns its
        verdict from fresh samples."""
        if endpoint not in self._suspect or self.ttl_s <= 0:
            return
        last = self._last_obs.get(endpoint)
        if last is not None and self._now() - last > self.ttl_s:
            self._suspect.discard(endpoint)
            self._ewma.pop(endpoint, None)
            self._n.pop(endpoint, None)
            _bump("recoveries")

    def _cohort_median_locked(self, endpoint: str) -> Optional[float]:
        peers = [
            v for e, v in self._ewma.items()
            if e != endpoint and self._n.get(e, 0) >= self.min_samples
        ]
        return statistics.median(peers) if peers else None

    def _judge_locked(self, endpoint: str) -> bool:
        was = endpoint in self._suspect
        sus = False
        if self._n.get(endpoint, 0) >= self.min_samples:
            med = self._cohort_median_locked(endpoint)
            if med is not None:
                threshold = max(self.min_ms, self.factor * med)
                sus = self._ewma[endpoint] > threshold
        if sus and not was:
            self._suspect.add(endpoint)
            _bump("suspicions")
        elif was and not sus:
            self._suspect.discard(endpoint)
            _bump("recoveries")
        return sus

    def is_suspect(self, endpoint: str) -> bool:
        with self._lock:
            self._expire_locked(endpoint)
            return endpoint in self._suspect

    def suspects(self) -> Set[str]:
        with self._lock:
            for e in list(self._suspect):
                self._expire_locked(e)
            return set(self._suspect)

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        """Per-endpoint {ewma_ms, samples, suspect} (status pages)."""
        with self._lock:
            return {
                e: {"ewma_ms": round(v, 3),
                    "samples": self._n.get(e, 0),
                    "suspect": e in self._suspect}
                for e, v in self._ewma.items()
            }

    def forget(self, endpoint: str) -> None:
        """Drop an endpoint (a deregistered replica, a remapped shard)."""
        with self._lock:
            self._ewma.pop(endpoint, None)
            self._n.pop(endpoint, None)
            self._last_obs.pop(endpoint, None)
            self._suspect.discard(endpoint)
