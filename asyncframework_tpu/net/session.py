"""Exactly-once-applied client sessions: op sequence numbers + server-side
dedup windows.

The retry layer (``net/retry.py``) makes lost-reply faults *survivable*;
this module makes retrying them *safe*.  Round-5 ADVICE caught the concrete
hole: ``RemoteLogTopic._call`` re-sent APPEND after a lost reply and the
topic grew duplicate records.  The same hazard sits under PUSH (a gradient
applied twice) and SUBMIT_APP (an app scheduled twice).

Mechanism (the classic at-least-once -> exactly-once-applied bridge):

- a client mints a :class:`ClientSession` -- a process-unique ``sid`` plus
  a monotonically increasing per-op ``seq``.  A *logical* op is stamped
  once; every retry re-sends the SAME ``(sid, seq)``.
- a server keeps a :class:`DedupWindow`: for each session, the last
  ``window`` applied seqs with their cached replies.  A request whose
  ``(sid, seq)`` is already present is NOT re-applied -- the cached reply
  is re-sent (the reply the wire ate).

Windows are bounded two ways (per-session entries, total sessions, both
LRU) because sessions come and go with worker churn; a legitimate retry
arrives within one retry-policy deadline, not hours later.  Unstamped
requests pass straight through -- old clients keep working, they just
keep the old at-least-once semantics.

Sharded-PS contract (``parallel/shardgroup.py``): sessions are strictly
**per shard**.  Each of a ``ShardedPSClient``'s sub-clients mints its own
:class:`ClientSession`, each shard keeps its own :class:`DedupWindow`,
and each window rides its shard's durable checkpoint
(``state()``/``load_state()``, captured under the model lock) -- so when
a fan-out round is abandoned mid-flight and replayed, every shard judges
its OWN ``(sid, seq)`` history independently: the sub-pushes that landed
before the fault are re-answered from cache (on a restarted shard, from
the RESTORED window), the ones that never arrived apply fresh.  Nothing
in this module is shard-aware; the guarantee composes because the stamps
never cross shard boundaries.

Epoch-fencing contract (``async.fence.enabled``, parallel/ps_dcn.py):
dedup STRICTLY precedes fencing on the server -- an op this incarnation
already applied re-answers its cached verdict whatever epochs say (the
applied state is the truth), and a REJECT_FENCED verdict is itself
``record()``-ed so retries of a fenced stamp re-answer the fence rather
than racing a fresh admission.  Windows and fences therefore never
disagree: a stamp is applied-once, fenced-once, or unseen.
"""

from __future__ import annotations

import base64
import threading
import uuid
from collections import OrderedDict
from typing import Optional, Tuple

_totals_lock = threading.Lock()
_dedup_hits_total = 0


def dedup_hits_total() -> int:
    """Process-wide dedup hits across every server window (live UI)."""
    with _totals_lock:
        return _dedup_hits_total


def reset_dedup_hits_total() -> None:
    """Zero the process-wide counter (per-run isolation; see
    ``asyncframework_tpu.metrics.reset_totals``)."""
    global _dedup_hits_total
    with _totals_lock:
        _dedup_hits_total = 0


def _bump_hits() -> None:
    global _dedup_hits_total
    with _totals_lock:
        _dedup_hits_total += 1


class ClientSession:
    """Mints ``(sid, seq)`` stamps.  One per client object; thread-safe so
    a client shared across threads still never reuses a seq."""

    def __init__(self, sid: Optional[str] = None):
        self.sid = sid if sid is not None else uuid.uuid4().hex[:16]
        self._seq = 0
        self._lock = threading.Lock()

    def next_seq(self) -> int:
        with self._lock:
            self._seq += 1
            return self._seq

    def stamp(self, header: dict) -> dict:
        """A NEW header carrying this session's next seq.  Stamp once per
        logical op -- retries re-send the stamped header verbatim."""
        h = dict(header)
        h["sid"] = self.sid
        h["seq"] = self.next_seq()
        return h


class DedupWindow:
    """Server-side (sid, seq) -> cached-reply window.

    ``check(header)`` returns the cached ``(reply_header, payload)`` for a
    duplicate, else None; ``record(header, reply, payload)`` stores a
    freshly applied op's reply.  Both are no-ops for unstamped headers.
    """

    def __init__(self, window: int = 128, max_sessions: int = 1024):
        self.window = max(1, int(window))
        self.max_sessions = max(1, int(max_sessions))
        self._lock = threading.Lock()
        # sid -> (seq -> (reply_header, payload)), both LRU-ordered
        self._sessions: "OrderedDict[str, OrderedDict]" = OrderedDict()
        self.hits = 0
        self.recorded = 0

    @staticmethod
    def _key(header: dict) -> Optional[Tuple[str, int]]:
        sid, seq = header.get("sid"), header.get("seq")
        if sid is None or seq is None:
            return None
        return str(sid), int(seq)

    def check(self, header: dict) -> Optional[Tuple[dict, bytes]]:
        key = self._key(header)
        if key is None:
            return None
        sid, seq = key
        with self._lock:
            ops = self._sessions.get(sid)
            if ops is None:
                return None
            self._sessions.move_to_end(sid)
            hit = ops.get(seq)
            if hit is None:
                return None
            self.hits += 1
        _bump_hits()
        return hit

    def record(self, header: dict, reply_header: dict,
               payload: bytes = b"") -> None:
        key = self._key(header)
        if key is None:
            return
        sid, seq = key
        with self._lock:
            ops = self._sessions.get(sid)
            if ops is None:
                ops = OrderedDict()
                self._sessions[sid] = ops
                while len(self._sessions) > self.max_sessions:
                    self._sessions.popitem(last=False)
            else:
                self._sessions.move_to_end(sid)
            ops[seq] = (reply_header, payload)
            ops.move_to_end(seq)
            while len(ops) > self.window:
                ops.popitem(last=False)
            self.recorded += 1

    # ------------------------------------------------------- checkpointing
    # A window is in-memory, so a bare server restart empties it and a
    # retry spanning the restart silently re-applies (the old at-least-once
    # edge).  A server that checkpoints its own state persists the window
    # WITH it: state()/load_state() round-trip the (sid, seq) -> reply map
    # through JSON, keeping exactly-once-applied true ACROSS a kill -9 +
    # restart-from-checkpoint as long as the window and the applied state
    # are captured under the same lock (the PS does).

    def state(self) -> dict:
        """JSON-serializable snapshot of every session's applied window
        (LRU order preserved -- dicts keep insertion order)."""
        with self._lock:
            return {
                "sessions": {
                    sid: [
                        [seq, hdr,
                         base64.b64encode(payload).decode("ascii")]
                        for seq, (hdr, payload) in ops.items()
                    ]
                    for sid, ops in self._sessions.items()
                }
            }

    def load_state(self, state: Optional[dict]) -> None:
        """Replace this window's contents with a :meth:`state` snapshot."""
        with self._lock:
            self._sessions.clear()
            for sid, entries in (state or {}).get("sessions", {}).items():
                ops: OrderedDict = OrderedDict()
                for seq, hdr, payload_b64 in entries:
                    ops[int(seq)] = (hdr, base64.b64decode(payload_b64))
                self._sessions[str(sid)] = ops
