"""The declared wire-protocol table: every op, one row, one place.

Nine PRs grew four framed-TCP planes -- the parameter server
(``parallel/ps_dcn.py`` + ``parallel/shardgroup.py``), the serving tier
(``serving/replica.py`` / ``serving/frontend.py``), the deploy control
plane (``deploy/master.py`` / ``deploy/worker.py``), and the log-topic
stream (``streaming/log_net.py``) -- and with them a set of per-op
obligations that were, until this module, encoded only as scattered
``frozenset`` literals and dispatch branches:

- **dedup gating**: a mutating, non-idempotent op (PUSH, APPEND,
  SUBMIT_APP, ...) must ride the ``net/session.py`` ``(sid, seq)``
  DedupWindow, or a retry after a lost reply applies it twice -- the
  exact double-apply the ASYNC staleness bookkeeping cannot survive;
- **epoch stamping**: with ``async.fence.enabled``, PS-plane ops carry
  the ``ep`` fencing stamp and servers must run fencing admission, or a
  zombie incarnation silently mutates a range it no longer owns;
- **fault schedulability**: chaos presets (``net/faults.py``) name ops
  by pattern; a renamed op silently drops out of every chaos schedule.

This table declares those obligations per op.  Servers derive their
mutating-op sets from it (:func:`dedup_gated_ops` -- ``deploy/master.py``
and ``streaming/log_net.py`` import theirs), and the static analyzer
(``asyncframework_tpu/analysis/``, ``bin/async-lint``) cross-checks every
dispatch branch, dedup route, and fence-admission call in the tree
against it: a new op missing its DedupWindow route or ``ep`` stamp is a
lint failure, not a chaos-suite lottery.

Pure data -- this module imports nothing from the package and is safe to
import from any layer (including ``analysis/``, which must not drag in
jax-heavy modules).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, Tuple

#: direction values
REQUEST = "request"
REPLY = "reply"
BOTH = "both"      # same verb is used as a request and as a reply shape

#: planes (who serves the op)
PS = "ps"               # parallel/ps_dcn.py (+ shardgroup fan-out)
SERVING = "serving"     # serving/replica.py, serving/frontend.py
MASTER = "master"       # deploy/master.py
WORKER = "worker"       # deploy/worker.py order socket
TOPIC = "topic"         # streaming/log_net.py
RELAY = "relay"         # relaycast/node.py (peer-relayed distribution)
PSEUDO = "pseudo"       # protocol-less hook points (fault injection)


@dataclass(frozen=True)
class WireOp:
    """One wire verb and its protocol obligations.

    ``mutating`` is "changes server state at all"; ``dedup_gated`` is the
    stronger "non-idempotent, MUST ride the (sid, seq) DedupWindow".
    Every mutating-but-ungated op carries its idempotence argument in
    ``doc`` -- that argument is the thing a reviewer must re-check when
    the handler changes.  ``fence_stamped`` ops carry the ``ep`` epoch
    stamp client-side and pass fencing admission server-side when
    ``async.fence.enabled`` is on.  ``fault_schedulable`` ops are legal
    targets for non-test fault-schedule presets (tests may target
    anything)."""

    name: str
    plane: str
    direction: str = REQUEST
    mutating: bool = False
    dedup_gated: bool = False
    fence_stamped: bool = False
    fault_schedulable: bool = False
    doc: str = ""

    def __post_init__(self):
        if self.dedup_gated and not self.mutating:
            raise ValueError(f"{self.name}: dedup_gated implies mutating")
        if self.direction == REPLY and (self.mutating or self.dedup_gated):
            raise ValueError(f"{self.name}: a reply cannot be mutating")


_OPS: Dict[str, WireOp] = {}


def _op(*args, **kw) -> None:
    op = WireOp(*args, **kw)
    if op.name in _OPS:
        raise ValueError(f"duplicate wire op {op.name}")
    _OPS[op.name] = op


# ---------------------------------------------------------------- PS plane
_op("PULL", PS, fence_stamped=True, fault_schedulable=True,
    doc="Wave-gated model read; idempotent and unstamped, safe to retry.")
_op("PULL_SAGA", PS, fence_stamped=True, fault_schedulable=True,
    doc="ASAGA's PULL verb (own name so fault schedules can target the "
        "ASAGA stream without also counting ASGD ops).")
_op("PUSH", PS, mutating=True, dedup_gated=True, fence_stamped=True,
    fault_schedulable=True,
    doc="Gradient contribution; THE double-apply hazard.  Dedup strictly "
        "precedes fencing (net/session.py contract).")
_op("PUSH_SAGA", PS, mutating=True, dedup_gated=True, fence_stamped=True,
    fault_schedulable=True,
    doc="ASAGA's PUSH verb; same exactly-once obligations as PUSH.")
_op("SUBSCRIBE", PS, fence_stamped=True, fault_schedulable=True,
    doc="Serving-tier snapshot read: wave-gate-free, membership-free "
        "PULL that keeps answering after DONE.")
_op("HELLO", PS, mutating=True, fault_schedulable=True,
    doc="Worker/replica introduction (also served by the serving "
        "frontend).  Mutates membership but is idempotent: re-HELLO of "
        "the same proc token re-registers, it never double-allocates.")
_op("SHARDMAP", PS, direction=BOTH,
    doc="Shard-map query and its reply verb; read-only.")
_op("SETMAP", PS, mutating=True,
    doc="Controller installs the assembled shard map/epoch vector; "
        "idempotent -- re-install of the same map is a no-op by value.")
_op("FINISH", PS, mutating=True,
    doc="Group-wide DONE broadcast; idempotent by construction (sets an "
        "already-set event).")
_op("SNAPSHOTS", PS, direction=BOTH,
    doc="Trajectory snapshot-stack read (eval plane) and its reply.")
_op("EVAL_RESULT", PS, mutating=True,
    doc="Worker's end-of-run eval vector; stamped client-side but "
        "idempotent server-side (same-wid overwrite of the same array).")
_op("BYE", PS, mutating=True,
    doc="Departing client's final piggybacks (spans/pl/cv).  Sent once "
        "per connection, never retried; span folds dedup by span_id.")
_op("SHM_OPEN", PS, fault_schedulable=True,
    doc="Transport upgrade handshake (net/shmring.py): a colocated "
        "client offers two shared-memory ring segments; the server "
        "attaches and ACKs, after which the SAME framed protocol "
        "continues over the rings instead of the TCP socket (which is "
        "retained for identity/liveness).  Non-mutating and trivially "
        "idempotent: it changes the TRANSPORT of a connection, never "
        "server state -- a refused or lost upgrade leaves the TCP "
        "conversation exactly where it was, and admission checks "
        "(dedup, fencing) run unchanged over either transport.")
_op("REPL_APPEND", PS, mutating=True, fence_stamped=True,
    fault_schedulable=True,
    doc="Primary->standby replication of one accepted merge batch "
        "(parallel/replication.py): the post-dedup drained items with "
        "their (sid, seq) stamps, verdicts, and staleness, stamped with "
        "the primary's merge clock (pre) and fencing epoch; accepted "
        "gradients ride as the payload.  Mutating but NOT dedup-gated: "
        "idempotence is the clock compare -- a batch entirely at-or-"
        "below the standby's applied clock re-ACKs as a duplicate, a "
        "batch starting exactly AT the clock applies, anything else is "
        "refused with resync=True (never applied twice; the stream is "
        "strictly serial per connection).  A deposed primary's post-"
        "promotion appends are REJECT_FENCED -- that admission IS the "
        "promotion-safety argument.")
_op("REPL_SYNC", PS, mutating=True, fence_stamped=True,
    fault_schedulable=True,
    doc="Full-state bootstrap of a (re)connecting standby: the "
        "primary's checkpoint image (model + clock + dedup window + "
        "trajectory) as one payload.  Idempotent: installing the same "
        "image twice converges to the same state, and a newer sync "
        "simply supersedes an older one.")
_op("PROMOTE", PS, mutating=True,
    doc="Controller order promoting a standby to range primary under "
        "the NEXT fencing epoch.  Deliberately NOT fence_stamped: its "
        "whole job is to raise the epoch past the deposed primary's.  "
        "Idempotent by monotone epoch compare -- re-delivery of the "
        "same (or an older) epoch re-answers ACK without demoting "
        "anything.")
_op("MODEL", PS, direction=REPLY,
    doc="PULL/SUBSCRIBE reply: full / NOT_MODIFIED / XOR-delta payload "
        "with version CRC.")
_op("WELCOME", PS, direction=REPLY,
    doc="HELLO reply (PS and serving frontend): elastic flag, shard "
        "map, epoch vector, slot index.")
_op("REJECT_FENCED", PS, direction=REPLY,
    doc="Fencing admission verdict; carries the highest known epoch so "
        "a deposed client self-heals.")
_op("RELEASED", PS, direction=REPLY,
    doc="PUSH reply deposing a surrogate after the owner rejoined.")
_op("DONE", PS, direction=REPLY,
    doc="PUSH reply: run complete, stop contributing.")
# ----------------------------------------------------------- serving plane
_op("PREDICT", SERVING, fault_schedulable=True,
    doc="Inference read (frontend round-robins it over replicas).")
_op("STATUS", SERVING, direction=BOTH,
    doc="Replica/frontend introspection read and its reply verb.")
_op("PREDICTION", SERVING, direction=REPLY,
    doc="PREDICT reply with row-major payload.")
_op("UNHEALTHY", SERVING, direction=REPLY,
    doc="Replica past its staleness SLO refusing to serve; frontend "
        "fails over.")
# ------------------------------------------------------------- relay plane
_op("RELAY_FETCH", RELAY, fence_stamped=True, fault_schedulable=True,
    doc="Peer fetch of a relayed model version (``have=``-negotiated "
        "NM/XDELTA/FULL, optionally zlib-compressed, always CRC-gated); "
        "read-only and idempotent, safe to retry.  A fetch whose stamped "
        "epoch is stale is REJECT_FENCED; a fetch whose REPLY carries a "
        "stale version epoch is discarded client-side (the child falls "
        "back to a direct root SUBSCRIBE either way).")
_op("RELAY_OFFER", RELAY, mutating=True, fence_stamped=True,
    fault_schedulable=True,
    doc="Parent's new-version announcement down the distribution tree "
        "(the PS root's offer loop and every interior node send it).  "
        "Mutating only as 'remember the newest offered version and wake "
        "the fetch path'; idempotent by construction -- re-delivery of "
        "the same (ts, crc) is a no-op by monotone version compare, so "
        "no dedup window is needed, and a LOST offer costs nothing (the "
        "child's poll loop fetches on its next tick).")
_op("RELAY_MODEL", RELAY, direction=REPLY,
    doc="RELAY_FETCH reply: negotiated model payload with wenc/CRC, the "
        "version's fencing epoch, and freshness metadata "
        "(clock/k/age_ms/done) so every hop keeps pricing its lag.")
# ------------------------------------------------------------ master plane
_op("REGISTER_WORKER", MASTER, mutating=True,
    doc="Worker daemon introduction; idempotent re-register by "
        "worker_id.")
_op("HEARTBEAT", MASTER, mutating=True,
    doc="Liveness renewal; idempotent (monotone last-seen update).")
_op("EXECUTOR_EXIT", MASTER, mutating=True,
    doc="Executor-death report; idempotent (set-insert by exec id).")
_op("SUBMIT_APP", MASTER, mutating=True, dedup_gated=True,
    fault_schedulable=True,
    doc="App scheduling; one retry storm must schedule exactly one app.")
_op("KILL_APP", MASTER, mutating=True, dedup_gated=True,
    doc="App kill fan-out; gated so a retried kill is answered from "
        "cache instead of re-fanning KILL orders.")
_op("APP_STATUS", MASTER, doc="App state read.")
_op("LIST_WORKERS", MASTER, doc="Membership read.")
_op("REGISTERED", MASTER, direction=REPLY, doc="REGISTER_WORKER reply.")
_op("RECONNECT", MASTER, direction=REPLY,
    doc="HEARTBEAT reply: master restarted, re-introduce yourself.")
_op("STANDBY", MASTER, direction=REPLY,
    doc="Not-leader refusal during HA election; never dedup-cached "
        "(routing answer, not an outcome).")
_op("SUBMITTED", MASTER, direction=REPLY, doc="SUBMIT_APP reply.")
_op("KILLED", MASTER, direction=REPLY, doc="KILL_APP reply.")
_op("APP", MASTER, direction=REPLY, doc="APP_STATUS reply.")
_op("WORKERS", MASTER, direction=REPLY, doc="LIST_WORKERS reply.")
# ------------------------------------------------------------ worker plane
_op("LAUNCH", WORKER, mutating=True,
    doc="Executor launch order.  Idempotent per app_id: a re-LAUNCH of "
        "a killed app_id is refused by the worker's killed-set.")
_op("KILL", WORKER, mutating=True,
    doc="Executor kill order; idempotent (kill of the dead is a no-op).")
# ------------------------------------------------------------- topic plane
_op("APPEND", TOPIC, mutating=True, dedup_gated=True,
    fault_schedulable=True,
    doc="Log append; the round-5 duplicate-record bug is exactly an "
        "ungated APPEND retry.")
_op("COMMIT", TOPIC, mutating=True, dedup_gated=True,
    doc="Consumer-group offset commit; non-idempotent against "
        "concurrent commits from a rebalanced consumer.")
_op("READ", TOPIC, doc="Record-range read.")
_op("END", TOPIC, direction=BOTH,
    doc="End-offset query and its reply verb.")
_op("COMMITTED", TOPIC, direction=BOTH,
    doc="Committed-offset query (request) and COMMIT's reply verb.")
_op("APPENDED", TOPIC, direction=REPLY, doc="APPEND reply.")
_op("RECORDS", TOPIC, direction=REPLY, doc="READ reply with payload.")
_op("OFFSET", TOPIC, direction=REPLY, doc="COMMITTED-query reply.")
# ------------------------------------------------------------------ shared
_op("ACK", PS, direction=REPLY,
    doc="Generic applied/accepted reply (every plane).")
_op("ERR", PS, direction=REPLY,
    doc="Generic refusal/bad-op reply (every plane).")
_op("CONNECT", PSEUDO, fault_schedulable=True,
    doc="Pseudo-op fault schedules use to target the dial itself "
        "(net/faults.py CONNECT_OP; the dial has no header).")


# ------------------------------------------------------------------ access
def table() -> Dict[str, WireOp]:
    """The full op table, name -> row (a copy; the table is immutable)."""
    return dict(_OPS)


def get(name: str) -> WireOp:
    return _OPS[name]


def is_declared(name: str) -> bool:
    return name in _OPS


def ops(plane: str = None) -> Tuple[WireOp, ...]:
    """Rows, optionally filtered by plane."""
    return tuple(op for op in _OPS.values()
                 if plane is None or op.plane == plane)


def dedup_gated_ops(plane: str) -> FrozenSet[str]:
    """The (sid, seq)-gated mutating verbs of one plane -- servers derive
    their ``_MUTATING_OPS`` sets from this, so the table is the single
    point where an op's exactly-once obligation is declared (and
    ``bin/async-lint`` checks the derivation is in place)."""
    return frozenset(op.name for op in _OPS.values()
                     if op.plane == plane and op.dedup_gated)


def fence_stamped_ops() -> FrozenSet[str]:
    """Verbs that carry the ``ep`` fencing stamp (all PS-plane)."""
    return frozenset(op.name for op in _OPS.values() if op.fence_stamped)


def fault_schedulable_ops() -> FrozenSet[str]:
    """Verbs non-test chaos presets may legally target."""
    return frozenset(op.name for op in _OPS.values()
                     if op.fault_schedulable)


#: modules the protocol linter scans for op literals (repo-relative).
#: sql/ also compares a variable named ``op`` against uppercase strings
#: (UNION/EXCEPT) -- protocol scanning is scoped to the wire planes, not
#: keyed on variable names alone.
PROTOCOL_MODULES: Tuple[str, ...] = (
    "asyncframework_tpu/parallel/ps_dcn.py",
    "asyncframework_tpu/parallel/shardgroup.py",
    "asyncframework_tpu/parallel/replication.py",
    "asyncframework_tpu/serving/replica.py",
    "asyncframework_tpu/serving/frontend.py",
    "asyncframework_tpu/serving/server.py",
    "asyncframework_tpu/deploy/master.py",
    "asyncframework_tpu/deploy/worker.py",
    "asyncframework_tpu/deploy/client.py",
    "asyncframework_tpu/streaming/log_net.py",
    "asyncframework_tpu/relaycast/node.py",
    "asyncframework_tpu/relaycast/source.py",
    "asyncframework_tpu/net/faults.py",
)

#: request-op -> server modules whose dispatch must handle it (the
#: coverage matrix the linter enforces).  HELLO/SUBSCRIBE/PREDICT appear
#: under every server that answers them.
SERVER_DISPATCH: Dict[str, Tuple[str, ...]] = {
    "PULL": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "PULL_SAGA": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "PUSH": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "PUSH_SAGA": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "SUBSCRIBE": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "HELLO": ("asyncframework_tpu/parallel/ps_dcn.py",
              "asyncframework_tpu/serving/frontend.py"),
    "SHARDMAP": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "SETMAP": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "FINISH": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "SHM_OPEN": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "REPL_APPEND": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "REPL_SYNC": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "PROMOTE": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "SNAPSHOTS": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "EVAL_RESULT": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "BYE": ("asyncframework_tpu/parallel/ps_dcn.py",),
    "PREDICT": ("asyncframework_tpu/serving/replica.py",
                "asyncframework_tpu/serving/frontend.py"),
    "STATUS": ("asyncframework_tpu/serving/replica.py",
               "asyncframework_tpu/serving/frontend.py"),
    "REGISTER_WORKER": ("asyncframework_tpu/deploy/master.py",),
    "HEARTBEAT": ("asyncframework_tpu/deploy/master.py",),
    "EXECUTOR_EXIT": ("asyncframework_tpu/deploy/master.py",),
    "SUBMIT_APP": ("asyncframework_tpu/deploy/master.py",),
    "KILL_APP": ("asyncframework_tpu/deploy/master.py",),
    "APP_STATUS": ("asyncframework_tpu/deploy/master.py",),
    "LIST_WORKERS": ("asyncframework_tpu/deploy/master.py",),
    "LAUNCH": ("asyncframework_tpu/deploy/worker.py",),
    "KILL": ("asyncframework_tpu/deploy/worker.py",),
    "RELAY_FETCH": ("asyncframework_tpu/relaycast/node.py",),
    "RELAY_OFFER": ("asyncframework_tpu/relaycast/node.py",),
    "APPEND": ("asyncframework_tpu/streaming/log_net.py",),
    "COMMIT": ("asyncframework_tpu/streaming/log_net.py",),
    "READ": ("asyncframework_tpu/streaming/log_net.py",),
    "END": ("asyncframework_tpu/streaming/log_net.py",),
    "COMMITTED": ("asyncframework_tpu/streaming/log_net.py",),
}
