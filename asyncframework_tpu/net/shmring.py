"""Shared-memory ring transport for colocated roles.

An ``async-cluster`` box runs several roles as separate PROCESSES on one
host -- PS shards, the hot standby, serving replicas -- and their
REPL_APPEND / SUBSCRIBE traffic crosses the loopback stack: two syscalls
plus two kernel copies per frame, with the GIL held on each end.  This
module moves those bytes through a lock-free SPSC ring in a shared-memory
segment instead: one mmap'd file per direction, writer and reader in
different processes, release/acquire counter publishes ordering the data
copies (native/shmring.cc; a layout-identical pure-Python
``struct.pack_into`` twin drives the SAME segment when the toolchain is
absent, and the two implementations are cross-tested against each other
in both directions).

The crucial design decision: the ring replaces the SOCKET, not the
PROTOCOL.  :class:`ShmSocket` exposes the socket-method subset
``net/frame.py`` uses (``sendall``/``sendmsg``/``recv_into``/timeouts/
``getpeername``/``shutdown``/``close``), so the exact same framed bytes
-- length-prefixed JSON header, payload, CRC fields, session dedup
stamps, fence epochs -- flow through ``send_msg``/``recv_msg`` unchanged
and every admission check at the server choke point still runs.  Nothing
above the transport can tell the difference, which is what makes the
byte-identity acceptance test possible.

Handshake (``SHM_OPEN``, net/protocol.py): after the normal TCP connect,
a client that finds ``async.shm.enabled`` set and the peer on loopback
creates the two ring files (0600, in /dev/shm when present), stamps its
pid, and sends their paths over the TCP connection; the server attaches
and answers OK, the client then UNLINKS the files -- both processes hold
the mappings, so a SIGKILL on either side cannot leak a name in /dev/shm.
Any refusal (conf off on the server, attach failure, non-colocated peer
that cannot see the paths) answers ERR and the TCP connection continues
unchanged -- the upgrade is strictly opportunistic.

Degrade path: a dead or wedged peer is detected by pid liveness
(``os.kill(pid, 0)``) while stalled on a full/empty ring, surfacing as
``ConnectionError``/``socket.timeout`` -- the SAME exceptions the TCP
paths raise -- so every existing reconnect/degrade loop (replication's
resync machinery, PSClient's retry policy) handles a ring failure by
falling back to a fresh TCP dial with no new code.  Counters
(``native`` family): shm_upgrades, shm_upgrade_refused, shm_degrades,
shm_frames_sent, shm_bytes_sent / shm_bytes_recv.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import socket
import struct
import tempfile
import time
from typing import Optional, Tuple

import numpy as np

from asyncframework_tpu.native_build import bump_native as _bump_native

_MAGIC = 0x53524E47  # 'SRNG'
_VERSION = 2
_HDR = 192  # ring header bytes; data region follows
# v2 layout: head and tail each own a full cache line (v1 packed them 8
# bytes apart, and the two sides' counter publishes invalidated each
# other's hot line on every call -- measured at >4x streaming slowdown)
_OFF_HEAD = 64  # u64, reader-owned: bytes consumed
_OFF_TAIL = 128  # u64, writer-owned: bytes produced
_OFF_WPID = 32  # u32 writer pid / u32 reader pid at 36 (liveness checks)
_OFF_RPID = 36
_OFF_FLAGS = 40  # bit0 = writer closed, bit1 = reader closed

# ---------------------------------------------------------- native loading
#: native symbol -> same-module pure-Python oracle (``native-oracle``
#: lint); the twins operate on the same mmap layout, so a native writer
#: and a Python reader interoperate (cross-tested in tests/test_native.py)
NATIVE_ORACLES = {
    "shm_ring_init": "_py_ring_init",
    "shm_ring_ok": "_py_ring_ok",
    "shm_ring_close": "_py_ring_close",
    "shm_ring_write": "_py_ring_write",
    "shm_ring_read": "_py_ring_read",
}

_NATIVE = None


def _native_lib():
    global _NATIVE
    if _NATIVE is not None:
        return _NATIVE or None
    lib = None
    try:
        from asyncframework_tpu.native_build import ensure_built

        built = ensure_built("shmring")
        if built:
            lib = ctypes.CDLL(built)
            P, LL = ctypes.c_void_p, ctypes.c_longlong
            lib.shm_ring_init.restype = ctypes.c_int
            lib.shm_ring_init.argtypes = [P, ctypes.c_ulonglong]
            lib.shm_ring_ok.restype = ctypes.c_int
            lib.shm_ring_ok.argtypes = [P]
            lib.shm_ring_close.restype = None
            lib.shm_ring_close.argtypes = [P, ctypes.c_int]
            lib.shm_ring_write.restype = LL
            lib.shm_ring_write.argtypes = [P, P, LL]
            lib.shm_ring_read.restype = LL
            lib.shm_ring_read.argtypes = [P, P, LL]
    except Exception:  # noqa: BLE001 - fall back to Python
        lib = None
    _NATIVE = lib or False
    return lib


def _use_native():
    from asyncframework_tpu.conf import NATIVE_ENABLED, global_conf

    if not global_conf().get(NATIVE_ENABLED):
        return None
    lib = _native_lib()
    if lib is None:
        _bump_native("python_fallbacks")
    return lib


# ------------------------------------------------------- pure-Python twin
# The oracle implementations.  CPython gives no explicit memory fences,
# but each op is a handful of bytecodes whose stores the interpreter
# cannot reorder, and on the TSO hardware this targets a plain store
# after the data copy is exactly the release-publish the native twin
# does.  The one semantic gap: ``_py_ring_close`` is a read-modify-write
# of the flags word without atomic OR, so two sides closing in the same
# microsecond can drop one bit -- the peer then learns of the close one
# pid-liveness check later instead of immediately.  Harmless (a closed
# side is on its way out of the process anyway), and only reachable in
# the mixed shutdown race.
def _py_ring_init(mm, capacity: int) -> int:
    if capacity <= 0:
        return -1
    mm[0:_HDR] = b"\0" * _HDR
    struct.pack_into("<IIQ", mm, 0, _MAGIC, _VERSION, capacity)
    return 0


def _py_ring_ok(mm) -> int:
    magic, ver = struct.unpack_from("<II", mm, 0)
    return 1 if (magic == _MAGIC and ver == _VERSION) else 0


def _py_ring_close(mm, writer: int) -> None:
    (flags,) = struct.unpack_from("<I", mm, _OFF_FLAGS)
    struct.pack_into("<I", mm, _OFF_FLAGS, flags | (1 if writer else 2))


def _py_ring_write(mm, data, n: int) -> int:
    (flags,) = struct.unpack_from("<I", mm, _OFF_FLAGS)
    if flags & 2:
        return -1
    (cap,) = struct.unpack_from("<Q", mm, 8)
    (head,) = struct.unpack_from("<Q", mm, _OFF_HEAD)
    (tail,) = struct.unpack_from("<Q", mm, _OFF_TAIL)
    take = min(n, cap - (tail - head))
    if not take:
        return 0
    pos = tail % cap
    first = min(take, cap - pos)
    mm[_HDR + pos:_HDR + pos + first] = data[:first]
    if take > first:
        mm[_HDR:_HDR + take - first] = data[first:take]
    struct.pack_into("<Q", mm, _OFF_TAIL, tail + take)
    return take


def _py_ring_read(mm, maxn: int):
    """Bytes read (possibly ``b""`` for an empty ring), or ``-1`` for
    empty-and-writer-closed (clean EOF)."""
    (cap,) = struct.unpack_from("<Q", mm, 8)
    (head,) = struct.unpack_from("<Q", mm, _OFF_HEAD)
    (tail,) = struct.unpack_from("<Q", mm, _OFF_TAIL)
    avail = tail - head
    if not avail:
        (flags,) = struct.unpack_from("<I", mm, _OFF_FLAGS)
        return -1 if flags & 1 else b""
    take = min(maxn, avail)
    pos = head % cap
    first = min(take, cap - pos)
    out = mm[_HDR + pos:_HDR + pos + first]
    if take > first:
        out += mm[_HDR:_HDR + take - first]
    struct.pack_into("<Q", mm, _OFF_HEAD, head + take)
    return out


# ------------------------------------------------------------------- ring
class ShmRing:
    """One direction of the transport: an mmap'd SPSC byte ring.

    Exactly one process writes and one reads; both may independently run
    the native or the Python implementation per call (the layout is the
    contract, not the code).
    """

    def __init__(self, mm: mmap.mmap, path: str, capacity: int):
        self._mm = mm
        self.path = path
        self.capacity = capacity
        # pin the buffer once for native calls; released in close()
        self._cbuf = ctypes.c_char.from_buffer(mm)
        self._addr = ctypes.addressof(self._cbuf)
        # backend resolved ONCE per ring: the data-plane calls run at
        # poll rates where even the conf lookup in _use_native() shows
        # up; rings are constructed after conf is settled (upgrade time)
        self._lib = _use_native()

    # -- lifecycle
    @classmethod
    def create(cls, capacity: int, directory: Optional[str] = None
               ) -> "ShmRing":
        d = directory or ("/dev/shm" if os.path.isdir("/dev/shm")
                          else tempfile.gettempdir())
        fd, path = tempfile.mkstemp(prefix="async-shm-", suffix=".ring",
                                    dir=d)
        try:
            os.ftruncate(fd, _HDR + capacity)
            mm = mmap.mmap(fd, _HDR + capacity)
        except OSError:
            os.close(fd)
            os.unlink(path)
            raise
        os.close(fd)
        ring = cls(mm, path, capacity)
        if ring._lib is not None:
            rc = ring._lib.shm_ring_init(ring._addr, capacity)
        else:
            rc = _py_ring_init(mm, capacity)
        if rc != 0:
            ring.close()
            os.unlink(path)
            raise ValueError(f"bad ring capacity {capacity}")
        return ring

    @classmethod
    def attach(cls, path: str) -> "ShmRing":
        with open(path, "r+b") as f:
            size = os.fstat(f.fileno()).st_size
            if size <= _HDR:
                raise ValueError(f"ring file too small: {path}")
            mm = mmap.mmap(f.fileno(), size)
        ring = cls(mm, path, size - _HDR)
        ok = (ring._lib.shm_ring_ok(ring._addr) if ring._lib is not None
              else _py_ring_ok(mm))
        if not ok:
            ring.close()
            raise ValueError(f"not a ring segment: {path}")
        return ring

    def close(self, as_writer: Optional[bool] = None) -> None:
        """Release the mapping; with ``as_writer`` given, first latch the
        matching closed flag so the peer sees EOF (reader) or stops
        writing (writer) instead of waiting out a liveness check."""
        if self._mm is None:
            return
        if as_writer is not None:
            try:
                self.latch_closed(as_writer)
            except (OSError, ValueError):  # pragma: no cover - racing unmap
                pass
        self._cbuf = None  # unpin before closing the mapping
        try:
            self._mm.close()
        except BufferError:  # pragma: no cover - stray export
            pass
        self._mm = None

    def latch_closed(self, as_writer: bool) -> None:
        """Set this side's closed flag without unmapping (shutdown())."""
        if self._lib is not None:
            self._lib.shm_ring_close(self._addr, 1 if as_writer else 0)
        else:
            _py_ring_close(self._mm, 1 if as_writer else 0)

    # -- pid stamping (liveness checks read the OTHER side's slot)
    def stamp_pid(self, as_writer: bool) -> None:
        struct.pack_into("<I", self._mm,
                         _OFF_WPID if as_writer else _OFF_RPID, os.getpid())

    def peer_pid(self, i_am_writer: bool) -> int:
        (pid,) = struct.unpack_from(
            "<I", self._mm, _OFF_RPID if i_am_writer else _OFF_WPID)
        return pid

    def available(self) -> int:
        """Readable bytes right now (layout peek; no side effects)."""
        (head,) = struct.unpack_from("<Q", self._mm, _OFF_HEAD)
        (tail,) = struct.unpack_from("<Q", self._mm, _OFF_TAIL)
        return int(tail - head)

    # -- data plane (per-call native/Python dispatch)
    def write(self, buf) -> int:
        """Bytes accepted (0 = full, caller paces); -1 = reader closed."""
        view = memoryview(buf)
        if self._lib is not None:
            a = np.frombuffer(view, np.uint8)
            return int(self._lib.shm_ring_write(
                self._addr, ctypes.c_void_p(a.ctypes.data), a.size))
        return _py_ring_write(self._mm, view, len(view))

    def read_into(self, view) -> int:
        """Bytes filled into ``view`` (0 = empty); -1 = clean EOF."""
        if self._lib is not None:
            a = np.frombuffer(view, np.uint8)
            return int(self._lib.shm_ring_read(
                self._addr, ctypes.c_void_p(a.ctypes.data), a.size))
        got = _py_ring_read(self._mm, len(view))
        if isinstance(got, int):
            return got
        view[: len(got)] = got
        return len(got)


# ------------------------------------------------------------ duck socket
def _peer_alive(pid: int) -> bool:
    if pid <= 0:
        return True  # not yet stamped; give it the benefit of the doubt
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:  # pragma: no cover - cross-uid colocations
        return True


#: stall loop tuning: busy-poll briefly (one frame turnaround is usually
#: microseconds), then back off -- first by YIELDING the core (the peer
#: may be runnable on this very CPU; ``sleep(0)`` is sched_yield), then
#: by short sleeps; consult peer liveness about every _LIVENESS_EVERY_S
#: of accumulated waiting.  On a single-CPU box spinning can only steal
#: the peer's timeslice, so the spin window collapses to zero there.
_SPIN_ITERS = 200 if (os.cpu_count() or 1) > 1 else 0
_YIELD_ITERS = 32
_SLEEP_S = 0.0002
_LIVENESS_EVERY_S = 0.05


class ShmSocket:
    """The socket-shaped face of a duplex ring pair.

    Implements exactly the surface ``net/frame.py`` touches --
    ``sendall``/``sendmsg`` (gather), ``recv_into``, timeout get/set
    (honouring the retry-deadline caps), ``getpeername`` (delegated to
    the RETAINED TCP connection, so fault-schedule endpoint addressing
    and log lines are unchanged), ``shutdown``/``close``/``fileno`` --
    so the framing, tracing, fault-injection, and byte-accounting choke
    point runs unmodified over shared memory.  Weakref-able by design
    (frame.py's resting-timeout stash requires it).
    """

    def __init__(self, rd: ShmRing, wr: ShmRing, tcp: socket.socket):
        self._rd = rd
        self._wr = wr
        self._tcp = tcp
        self._timeout = tcp.gettimeout()

    # -- timeouts (frame._deadline_cap drives these)
    def gettimeout(self) -> Optional[float]:
        return self._timeout

    def settimeout(self, t: Optional[float]) -> None:
        self._timeout = t

    def getpeername(self):
        return self._tcp.getpeername()

    def fileno(self) -> int:
        return self._tcp.fileno()

    def readable(self) -> bool:
        """Zero-wait readiness probe (``select`` cannot see ring bytes
        on the retained TCP fd; prefetch hit/miss accounting asks here)."""
        try:
            return self._rd.available() > 0
        except (TypeError, struct.error):  # pragma: no cover - closed
            return False

    # -- stall handling shared by both directions
    def _stall(self, started: float, slept: float, stalls: int,
               ring: ShmRing, i_am_writer: bool, what: str
               ) -> Tuple[float, float]:
        now = time.monotonic()
        if self._timeout is not None and now - started >= self._timeout:
            raise socket.timeout(f"shm ring {what} timed out")
        if now - started >= slept + _LIVENESS_EVERY_S:
            slept = now - started
            if not _peer_alive(ring.peer_pid(i_am_writer)):
                _bump_native("shm_degrades")
                raise ConnectionError(f"shm peer died mid-{what}")
        # yield first: when the peer shares this CPU, handing it the
        # core moves a whole ring's worth per switch; sleep only once
        # yielding has demonstrably not unblocked us
        time.sleep(0 if stalls <= _SPIN_ITERS + _YIELD_ITERS else _SLEEP_S)
        return started, slept

    # -- send side
    def _write_all(self, view) -> None:
        a = np.frombuffer(view, np.uint8)
        off = 0
        started = time.monotonic()
        slept = 0.0
        spins = 0
        while off < a.size:
            w = self._wr.write(a[off:])
            if w == -1:
                _bump_native("shm_degrades")
                raise ConnectionError("shm peer closed the ring")
            if w > 0:
                off += w
                started = time.monotonic()  # progress resets the clock
                slept = 0.0
                spins = 0
                continue
            spins += 1
            if spins <= _SPIN_ITERS:
                continue
            started, slept = self._stall(started, slept, spins,
                                         self._wr, True, "write")

    def sendall(self, data) -> None:
        view = memoryview(data).cast("B")
        self._write_all(view)
        _bump_native("shm_frames_sent")
        _bump_native("shm_bytes_sent", len(view))

    def sendmsg(self, buffers) -> int:
        """Write EVERY buffer before returning (a blocking socket may
        legally do so); one ``_sendmsg_all`` call therefore maps to one
        frame, which is what makes ``shm_frames_sent`` a frame count."""
        views = [memoryview(b).cast("B") for b in buffers]
        total = 0
        for v in views:
            if len(v):
                self._write_all(v)
                total += len(v)
        _bump_native("shm_frames_sent")
        _bump_native("shm_bytes_sent", total)
        return total

    # -- receive side
    def recv_into(self, buf, nbytes: int = 0) -> int:
        view = memoryview(buf).cast("B")
        if nbytes:
            view = view[:nbytes]
        if not len(view):
            return 0
        started = time.monotonic()
        slept = 0.0
        spins = 0
        while True:
            got = self._rd.read_into(view)
            if got == -1:
                return 0  # EOF: recv_exact raises ConnectionError
            if got > 0:
                _bump_native("shm_bytes_recv", got)
                return got
            spins += 1
            if spins <= _SPIN_ITERS:
                continue
            started, slept = self._stall(started, slept, spins,
                                         self._rd, False, "read")

    # -- teardown
    def shutdown(self, how: int) -> None:
        for ring, as_writer in ((self._wr, True), (self._rd, False)):
            try:
                ring.latch_closed(as_writer)
            except (OSError, ValueError, AttributeError, TypeError):
                pass
        try:
            self._tcp.shutdown(how)
        except OSError:
            pass

    def close(self) -> None:
        self._wr.close(as_writer=True)
        self._rd.close(as_writer=False)
        try:
            self._tcp.close()
        except OSError:  # pragma: no cover
            pass


# -------------------------------------------------------------- handshake
def _colocated(sock: socket.socket) -> bool:
    try:
        host = sock.getpeername()[0]
    except OSError:
        return False
    return host.startswith("127.") or host == "::1" or host == "localhost"


def maybe_upgrade(sock: socket.socket) -> Tuple[object, bool]:
    """Client side: opportunistically swap ``sock`` for a ring transport.

    Returns ``(transport, upgraded)``.  Refusals of every kind -- conf
    off, non-loopback peer, segment creation failure, server ERR --
    return the original socket untouched; a handshake that dies MID-WIRE
    raises (the connection is in an unknown framing state, and the
    caller's normal drop-and-redial error path is the correct recovery).
    """
    from asyncframework_tpu.conf import (SHM_ENABLED, SHM_RING_KB,
                                         global_conf)
    from asyncframework_tpu.net import frame as _frame

    conf = global_conf()
    if not conf.get(SHM_ENABLED) or not _colocated(sock):
        return sock, False
    cap = int(conf.get(SHM_RING_KB)) * 1024
    try:
        c2s = ShmRing.create(cap)
    except (OSError, ValueError):
        return sock, False
    try:
        s2c = ShmRing.create(cap)
    except (OSError, ValueError):
        c2s.close()
        os.unlink(c2s.path)
        return sock, False
    c2s.stamp_pid(as_writer=True)
    s2c.stamp_pid(as_writer=False)
    refused = True
    try:
        _frame.send_msg(sock, {"op": "SHM_OPEN", "c2s": c2s.path,
                               "s2c": s2c.path, "pid": os.getpid()})
        header, _ = _frame.recv_msg(sock)
        if header.get("op") == "OK":
            refused = False
            _bump_native("shm_upgrades")
            return ShmSocket(rd=s2c, wr=c2s, tcp=sock), True
        _bump_native("shm_upgrade_refused")
        return sock, False
    finally:
        # the names are transient either way: on OK both sides hold the
        # mappings (unlink frees nothing until both unmap); on refusal
        # the segments are dead weight.  Unlinking HERE -- before the
        # first data frame -- is what makes a SIGKILL unable to leak a
        # /dev/shm entry.
        for ring in (c2s, s2c):
            try:
                os.unlink(ring.path)
            except OSError:
                pass
        if refused:
            c2s.close()
            s2c.close()


def serve_attach(conn: socket.socket, header: dict) -> Optional[ShmSocket]:
    """Server side of ``SHM_OPEN``: attach to the client's segments and
    ACK, or ERR and return None (caller keeps serving the TCP socket).
    The attach path trusts nothing: missing fields, unreadable paths,
    and bad magic all refuse."""
    from asyncframework_tpu.conf import SHM_ENABLED, global_conf
    from asyncframework_tpu.net import frame as _frame

    if not global_conf().get(SHM_ENABLED):
        _bump_native("shm_upgrade_refused")
        _frame.send_msg(conn, {"op": "ERR", "msg": "shm disabled"})
        return None
    try:
        rd = ShmRing.attach(str(header["c2s"]))
    except (OSError, ValueError, KeyError, TypeError):
        _bump_native("shm_upgrade_refused")
        _frame.send_msg(conn, {"op": "ERR", "msg": "shm attach failed"})
        return None
    try:
        wr = ShmRing.attach(str(header["s2c"]))
    except (OSError, ValueError, KeyError, TypeError):
        rd.close()
        _bump_native("shm_upgrade_refused")
        _frame.send_msg(conn, {"op": "ERR", "msg": "shm attach failed"})
        return None
    rd.stamp_pid(as_writer=False)
    wr.stamp_pid(as_writer=True)
    _bump_native("shm_upgrades")
    _frame.send_msg(conn, {"op": "OK"})
    return ShmSocket(rd=rd, wr=wr, tcp=conn)
