"""Shared network robustness layer for the DCN control + data plane.

One framing (``frame``), one retry/backoff policy with circuit breakers
(``retry``), exactly-once-applied client sessions with server-side dedup
windows (``session``), and deterministic schedule-driven fault injection
(``faults``).  The parameter server, the streaming topic server, and all
three standalone deploy daemons route through this package -- failure
handling is a subsystem here, not folklore at call sites.
"""

from __future__ import annotations

from typing import Dict

from asyncframework_tpu.net.retry import (  # noqa: F401
    CircuitBreaker,
    CircuitOpenError,
    RetryError,
    RetryPolicy,
)
from asyncframework_tpu.net.session import (  # noqa: F401
    ClientSession,
    DedupWindow,
)


def net_totals() -> Dict[str, int]:
    """Process-wide robustness counters (surfaced in the live UI next to
    the shuffle totals): retries taken, give-ups, breaker trips, dedup
    hits, faults fired."""
    from asyncframework_tpu.net import faults, retry, session

    out = dict(retry.retry_totals())
    out["dedup_hits"] = session.dedup_hits_total()
    out["faults_fired"] = faults.faults_fired_total()
    return out


def reset_net_totals() -> None:
    """Zero every process-wide net counter (retries/giveups/breaker trips,
    dedup hits, faults fired, wire-byte totals) so back-to-back runs in
    one process start from a clean slate.  Breaker *state* is left alone
    -- see ``retry.reset_breakers`` for that."""
    from asyncframework_tpu.net import faults, frame, lockwatch, retry, session

    retry.reset_retry_totals()
    session.reset_dedup_hits_total()
    faults.reset_faults_fired_total()
    frame.reset_bytes_totals()
    lockwatch.reset_totals()
