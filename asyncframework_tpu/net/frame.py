"""Length-prefixed JSON/payload framing: the one wire format of the DCN
control + data plane.

This is the framing that ``parallel/ps_dcn.py`` introduced and every other
networked layer (the topic server, the standalone master/worker/client
daemons) imported from it.  It now lives here so the robustness layer can
wrap ONE choke point: every frame sent or received anywhere in the
framework passes through :func:`send_msg` / :func:`recv_msg` /
:func:`connect`, and each consults the process's active
:class:`~asyncframework_tpu.net.faults.FaultInjector` (when installed) --
the network-plane sibling of ``engine/straggler.py``'s compute delays.

Frame layout (unchanged): ``!I``-prefixed JSON header line, then an
``!I``-prefixed raw payload (possibly empty).  The header always carries
``op``; mutating ops may carry ``sid``/``seq`` (see ``net/session.py``),
and a frame sent while a trace context is installed on the calling thread
(``metrics/trace.py``) carries it as an optional ``tc`` entry -- the wire
propagation of distributed tracing, stamped here at the one choke point so
every PULL/PUSH/PULL_SAGA/PUSH_SAGA, topic, and master op is covered.
With tracing off nothing consults the clock and frames are byte-identical
to the pre-trace wire.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Optional, Tuple

from asyncframework_tpu.metrics import trace as _trace
from asyncframework_tpu.net import faults

_HDR = struct.Struct("!I")  # 4-byte big-endian frame length


def endpoint_of(sock: socket.socket) -> str:
    """The remote peer as ``host:port`` (fault-schedule addressing)."""
    try:
        host, port = sock.getpeername()[:2]
        return f"{host}:{port}"
    except OSError:
        return "?:?"


def connect(addr: Tuple[str, int], timeout: Optional[float] = 10.0
            ) -> socket.socket:
    """``socket.create_connection`` with the fault hook: an armed
    connection-refused event fires here, before any real dial."""
    endpoint = f"{addr[0]}:{int(addr[1])}"
    inj = faults.active()
    if inj is not None:
        inj.check_connect(endpoint)
    return socket.create_connection(addr, timeout=timeout)


def send_msg(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    tc = _trace.wire_header()
    if tc is not None and "tc" not in header:
        # copy, never mutate: retries re-send the caller's header verbatim
        # (dedup stamps), and the ambient context at retry time still wins
        header = dict(header, tc=tc)
    head = json.dumps(header).encode()
    data = _HDR.pack(len(head)) + head + _HDR.pack(len(payload)) + payload
    inj = faults.active()
    if inj is not None:
        kind = inj.check_send(endpoint_of(sock), str(header.get("op", "")))
        if kind == faults.CUT_MID_FRAME:
            # a prefix of the frame goes out, then the connection dies: the
            # peer sees a short frame + EOF, the sender sees a reset.  The
            # request was NOT applied.
            sock.sendall(data[: max(1, len(data) // 3)])
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            raise ConnectionError(
                f"fault-injected: mid-frame cut to {endpoint_of(sock)}"
            )
        if kind in (faults.STALL_READ, faults.DROP_REPLY):
            # the request itself goes through (the peer WILL apply it); the
            # fault fires on this socket's next recv.  Arm only AFTER the
            # send succeeds -- a failed send never reaches the peer, and a
            # stale armed entry could fire on an unrelated future socket
            sock.sendall(data)
            inj.arm(sock, kind)
            return
    sock.sendall(data)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg_raw(sock: socket.socket) -> Tuple[dict, bytes]:
    (hlen,) = _HDR.unpack(recv_exact(sock, _HDR.size))
    header = json.loads(recv_exact(sock, hlen))
    (plen,) = _HDR.unpack(recv_exact(sock, _HDR.size))
    payload = recv_exact(sock, plen) if plen else b""
    return header, payload


def recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    inj = faults.active()
    if inj is not None:
        kind = inj.disarm(sock)
        if kind == faults.STALL_READ:
            # the reply never arrives within the attempt window; the unread
            # bytes stay in the kernel buffer, so the caller MUST drop this
            # connection (the retry layer does)
            raise socket.timeout(
                f"fault-injected: stalled read from {endpoint_of(sock)}"
            )
        if kind == faults.DROP_REPLY:
            # the peer applied the op and replied -- the reply is lost on
            # the wire.  Read and discard it so the injection point is
            # exactly "applied but unacknowledged".
            _recv_msg_raw(sock)
            raise ConnectionError(
                f"fault-injected: reply dropped after apply "
                f"({endpoint_of(sock)})"
            )
    return _recv_msg_raw(sock)
