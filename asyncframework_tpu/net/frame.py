"""Length-prefixed JSON/payload framing: the one wire format of the DCN
control + data plane.

This is the framing that ``parallel/ps_dcn.py`` introduced and every other
networked layer (the topic server, the standalone master/worker/client
daemons) imported from it.  It now lives here so the robustness layer can
wrap ONE choke point: every frame sent or received anywhere in the
framework passes through :func:`send_msg` / :func:`send_msg_vectored` /
:func:`recv_msg` / :func:`connect`, and each consults the process's active
:class:`~asyncframework_tpu.net.faults.FaultInjector` (when installed) --
the network-plane sibling of ``engine/straggler.py``'s compute delays.

Frame layout (unchanged on the wire): ``!I``-prefixed JSON header line,
then an ``!I``-prefixed raw payload (possibly empty).  The header always
carries ``op``; mutating ops may carry ``sid``/``seq`` (see
``net/session.py``), and a frame sent while a trace context is installed
on the calling thread (``metrics/trace.py``) carries it as an optional
``tc`` entry -- the wire propagation of distributed tracing, stamped here
at the one choke point so every PULL/PUSH/PULL_SAGA/PUSH_SAGA, topic, and
master op is covered.  With tracing off nothing consults the clock and
frames are byte-identical to the pre-trace wire.

Data-plane fast paths (the throughput overhaul):

- :func:`send_msg_vectored` frames a payload given as a *sequence of
  buffers* (``bytes``/``memoryview``/anything exporting the buffer
  protocol) through ``socket.sendmsg`` -- the kernel gathers the iovec, so
  a multi-megabyte model payload is never copied into a fresh frame
  buffer.  The bytes on the wire are identical to
  ``send_msg(sock, header, b"".join(parts))``.
- :func:`recv_exact` fills ONE preallocated ``bytearray`` via
  ``recv_into`` instead of accumulating per-``recv`` ``bytes`` chunks
  (which allocated O(frames) intermediates for large payloads).

Wire-bytes accounting: every frame sent or received here bumps a per-op
byte counter (frame bytes: both length prefixes + header + payload).
``bytes_totals()`` exposes them (live UI ``net.bytes`` section,
``bench.py`` bytes-per-update); ``metrics.reset_totals()`` zeroes them via
``net.reset_net_totals``.  The per-thread ``last_io_bytes()`` value lets a
client attach this RPC's wire cost to its pull.rtt/push.rtt trace span.
"""

from __future__ import annotations

import ctypes
import json
import socket
import struct
import threading
import weakref
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from asyncframework_tpu.metrics import profiler as _prof
from asyncframework_tpu.metrics import trace as _trace
from asyncframework_tpu.native_build import bump_native as _bump_native
from asyncframework_tpu.net import faults, lockwatch
from asyncframework_tpu.net import retry as _retry

_HDR = struct.Struct("!I")  # 4-byte big-endian frame length

# ---------------------------------------------------------- native gather
#: native symbol -> same-module pure-Python oracle (``native-oracle``
#: lint); wd_gather is the iovec-style memcpy loop of native/wiredelta.cc
NATIVE_ORACLES = {"wd_gather": "_py_gather"}

_NATIVE = None


def _native_lib():
    global _NATIVE
    if _NATIVE is not None:
        return _NATIVE or None
    lib = None
    try:
        from asyncframework_tpu.native_build import ensure_built

        built = ensure_built("wiredelta")
        if built:
            lib = ctypes.CDLL(built)
            lib.wd_gather.restype = ctypes.c_longlong
            lib.wd_gather.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                      ctypes.c_void_p, ctypes.c_longlong]
    except Exception:  # noqa: BLE001 - fall back to Python
        lib = None
    _NATIVE = lib or False
    return lib


def _use_native():
    from asyncframework_tpu.conf import NATIVE_ENABLED, global_conf

    if not global_conf().get(NATIVE_ENABLED):
        return None
    lib = _native_lib()
    if lib is None:
        _bump_native("python_fallbacks")
    return lib


def _py_gather(parts) -> bytes:
    return b"".join(bytes(memoryview(p)) for p in parts)


def gather(parts) -> bytes:
    """Materialize a frame from its buffer parts: ``b"".join`` semantics,
    but through the native iovec-memcpy helper when enabled, which
    releases the GIL for the copy of a multi-megabyte payload.  Used by
    the non-vectored send paths (fault-injection materialization, the
    no-``sendmsg`` fallback) and the shm-ring transport's frame staging
    (``net/shmring.py``); byte-identical to the join by construction and
    property-tested in tests/test_native.py."""
    lib = _use_native()
    if lib is not None and len(parts) > 1:
        arrs = [np.frombuffer(memoryview(p).cast("B"), np.uint8)
                for p in parts]
        arrs = [a for a in arrs if a.size]
        if len(arrs) > 1:
            total = int(sum(a.size for a in arrs))
            out = np.empty(total, np.uint8)
            n = len(arrs)
            srcs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrs])
            lens = (ctypes.c_longlong * n)(*[int(a.size) for a in arrs])
            got = lib.wd_gather(
                ctypes.c_void_p(out.ctypes.data),
                ctypes.cast(srcs, ctypes.c_void_p),
                ctypes.cast(lens, ctypes.c_void_p), n)
            if got == total:
                _bump_native("native_calls.gather")
                return out.tobytes()
    _bump_native("python_calls.gather")
    return _py_gather(parts)

# ------------------------------------------------------------ wire bytes
# Per-op frame byte counters (process-global, lock-guarded like every other
# net counter).  Keyed "sent.<OP>" / "recv.<OP>" so the live UI's _delta
# machinery (flat int dicts) applies unchanged.
_bytes_lock = threading.Lock()
_bytes_totals: Dict[str, int] = {}

# Per-thread bytes of the last send/recv on this thread: a client sums the
# two right after an RPC to stamp its rtt span with the wire cost.
_io_tls = threading.local()


def _count(direction: str, op: str, n: int) -> None:
    key = f"{direction}.{op or '?'}"
    with _bytes_lock:
        _bytes_totals[key] = _bytes_totals.get(key, 0) + n
        _bytes_totals[direction] = _bytes_totals.get(direction, 0) + n


def bytes_totals() -> Dict[str, int]:
    """Process-wide wire-byte counters: ``sent``/``recv`` grand totals plus
    ``sent.<OP>`` / ``recv.<OP>`` per-op breakdowns (frame bytes, i.e.
    prefixes + header + payload)."""
    with _bytes_lock:
        return dict(_bytes_totals)


def reset_bytes_totals() -> None:
    """Zero the wire-byte counters (per-run isolation; called from
    ``net.reset_net_totals`` -> ``metrics.reset_totals``)."""
    with _bytes_lock:
        _bytes_totals.clear()


def last_io_bytes() -> int:
    """Frame bytes of this thread's most recent send plus most recent
    receive -- the wire cost of the RPC that just completed.  Only valid
    for SYNCHRONOUS request/reply callers; windowed senders interleave
    frames from different RPCs on one thread and must pair
    :func:`last_sent_bytes` (captured at their send) with
    :func:`last_recv_bytes` (captured at their receive) instead."""
    return (getattr(_io_tls, "sent", 0) or 0) + (getattr(_io_tls, "recv", 0)
                                                 or 0)


def last_sent_bytes() -> int:
    """Frame bytes of this thread's most recent send alone."""
    return getattr(_io_tls, "sent", 0) or 0


def last_recv_bytes() -> int:
    """Frame bytes of this thread's most recent receive alone."""
    return getattr(_io_tls, "recv", 0) or 0


def free_port(host: str = "127.0.0.1") -> int:
    """Reserve-and-release one ephemeral port (the ONE copy of the
    bind-port-0 idiom: the local cluster launcher and the shard-group
    controller's telemetry-port pre-assignment both need a port known
    BEFORE the owning process binds it).  The tiny close-to-bind race
    is acceptable for local orchestration; k8s pins ports in the
    manifests instead."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def endpoint_of(sock: socket.socket) -> str:
    """The remote peer as ``host:port`` (fault-schedule addressing)."""
    try:
        host, port = sock.getpeername()[:2]
        return f"{host}:{port}"
    except OSError:
        return "?:?"


#: sock -> its RESTING timeout (the caller's attempt timeout), stashed
#: the first time a deadline cap tightens it so later ops can restore or
#: re-derive the right bound.  Without this, a cap is a ratchet: a call
#: finishing with 0.2 s of deadline left would leave settimeout(0.2) on
#: a REUSED connection (PSClient._sock, the frontend's pooled channels)
#: and every later call -- fresh deadline or none -- would inherit it.
_base_timeouts: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _deadline_cap(sock: Optional[socket.socket] = None,
                  timeout: Optional[float] = None) -> Optional[float]:
    """Cap a socket timeout to the calling thread's active retry deadline
    (net/retry.py): once the overall deadline is spent, raise
    ``socket.timeout`` immediately instead of letting a blocking syscall
    (a stalled read from a gray peer, a stall_read fault) hold the caller
    past the policy.  Returns the capped timeout; with ``sock`` given,
    installs ``min(resting timeout, remaining deadline)`` on the socket
    -- and with no deadline active, RESTORES the resting timeout a
    previous cap may have tightened."""
    rem = _retry.remaining_deadline_s()
    if sock is not None:
        try:
            cur = sock.gettimeout()
            base = _base_timeouts.get(sock, cur)
            if rem is None:
                if cur != base:
                    sock.settimeout(base)
            elif rem > 0:
                want = rem if base is None else min(base, rem)
                if cur != want:
                    _base_timeouts[sock] = base
                    sock.settimeout(want)
        except OSError:  # pragma: no cover - closed socket races
            pass
    if rem is None:
        return timeout
    if rem <= 0:
        raise socket.timeout("retry deadline exhausted")
    return rem if timeout is None else min(timeout, rem)


def connect(addr: Tuple[str, int], timeout: Optional[float] = 10.0
            ) -> socket.socket:
    """``socket.create_connection`` with the fault hook: an armed
    connection-refused event (or an active partition) fires here, before
    any real dial.  The dial itself is capped to the calling thread's
    retry deadline; the socket's RESTING timeout stays the caller's
    ``timeout`` (per-op deadline caps re-tighten as needed), so a reused
    connection never inherits one call's dying deadline."""
    endpoint = f"{addr[0]}:{int(addr[1])}"
    inj = faults.active()
    if inj is not None:
        inj.check_connect(endpoint)
    sock = socket.create_connection(addr,
                                    timeout=_deadline_cap(None, timeout))
    if sock.gettimeout() != timeout:
        sock.settimeout(timeout)
    return sock


def _stamped(header: dict) -> dict:
    tc = _trace.wire_header()
    if tc is not None and "tc" not in header:
        # copy, never mutate: retries re-send the caller's header verbatim
        # (dedup stamps), and the ambient context at retry time still wins
        header = dict(header, tc=tc)
    return header


_HAVE_SENDMSG = hasattr(socket.socket, "sendmsg")


def _sendmsg_all(sock: socket.socket, parts) -> None:
    """Gather-send every buffer in ``parts`` (memoryviews), handling short
    writes by advancing the iovec -- the vectored analog of ``sendall``."""
    views = [memoryview(p).cast("B") for p in parts if len(p)]
    while views:
        sent = sock.sendmsg(views)
        # advance past fully-sent buffers, slice the partial one
        while sent > 0 and views:
            if sent >= len(views[0]):
                sent -= len(views[0])
                views.pop(0)
            else:
                views[0] = views[0][sent:]
                sent = 0


def _send_frame(sock: socket.socket, header: dict, parts: Sequence) -> None:
    """Shared core of :func:`send_msg` / :func:`send_msg_vectored`: tc
    stamping, fault injection, byte accounting, then the wire write --
    vectored (zero-copy gather) when the platform has ``sendmsg`` and no
    injector needs to see a contiguous frame."""
    # lock watchdog (net/lockwatch.py): a frame sent while the caller
    # holds a watched lock (the PS model lock) is exactly the contention
    # the lock-free pull path removes -- fail loudly in debug runs
    lockwatch.check_io("send")
    with _prof.zone("serde"):
        header = _stamped(header)
        head = json.dumps(header).encode()
    # zone scope (profiler exact accumulator): everything past header
    # serialization is the frame pump proper -- byte accounting, fault
    # consult, and the kernel write(s).  Wall time, so a slow peer shows
    # up here (the sampler separates CPU from blocked time).
    with _prof.zone("wire.encode"):
        plen = sum(len(p) for p in parts)
        op = str(header.get("op", ""))
        total = 2 * _HDR.size + len(head) + plen
        _deadline_cap(sock)  # a spent deadline fails the write outright
        inj = faults.active()
        if inj is not None:
            endpoint = endpoint_of(sock)
            if inj.partition_active(endpoint):
                # blackholed: nothing leaves this host, the connection is
                # poisoned (the peer sees silence, exactly like a real cut)
                inj.note_partition_drop(endpoint, op)
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                raise ConnectionError(
                    f"fault-injected: partitioned from {endpoint}"
                )
            # chaos path: materialize the frame so mid-frame cuts slice the
            # exact same byte stream the plain path would have sent
            data = gather(
                [_HDR.pack(len(head)), head, _HDR.pack(plen), *parts])
            kind = inj.check_send(endpoint, op)
            if kind == faults.CUT_MID_FRAME:
                # a prefix of the frame goes out, then the connection dies:
                # the peer sees a short frame + EOF, the sender sees a
                # reset.  The request was NOT applied.
                sock.sendall(data[: max(1, len(data) // 3)])
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                raise ConnectionError(
                    f"fault-injected: mid-frame cut to {endpoint_of(sock)}"
                )
            if kind in (faults.STALL_READ, faults.DROP_REPLY):
                # the request itself goes through (the peer WILL apply it);
                # the fault fires on this socket's next recv.  Arm only
                # AFTER the send succeeds -- a failed send never reaches
                # the peer, and a stale armed entry could fire on an
                # unrelated future socket
                sock.sendall(data)
                inj.arm(sock, kind)
                _io_tls.sent = total
                _count("sent", op, total)
                return
            sock.sendall(data)
            _io_tls.sent = total
            _count("sent", op, total)
            return
        prefix = _HDR.pack(len(head)) + head + _HDR.pack(plen)
        if not plen:
            sock.sendall(prefix)
        elif _HAVE_SENDMSG:
            _sendmsg_all(sock, [prefix, *parts])
        else:  # pragma: no cover - platforms without sendmsg
            sock.sendall(gather([prefix, *parts]))
        _io_tls.sent = total
        _count("sent", op, total)


def send_msg(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    _send_frame(sock, header, (payload,) if payload else ())


def send_msg_vectored(sock: socket.socket, header: dict,
                      parts: Sequence) -> None:
    """Frame ``parts`` (a sequence of buffer-protocol objects) as ONE
    payload without concatenating them: the kernel gathers the iovec via
    ``socket.sendmsg``.  Byte-identical on the wire to
    ``send_msg(sock, header, b"".join(parts))``; same fault-injection and
    trace-stamping semantics (the choke point is shared)."""
    _send_frame(sock, header, tuple(parts))


def recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly ``n`` bytes into one preallocated buffer
    (``recv_into`` loop -- no per-chunk intermediate ``bytes``)."""
    if n == 0:
        return b""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if not r:
            raise ConnectionError("peer closed")
        got += r
    return bytes(buf)


def _recv_msg_raw(sock: socket.socket) -> Tuple[dict, bytes]:
    lockwatch.check_io("recv")
    _deadline_cap(sock)  # cap the blocking read to the retry deadline
    # zone boundary: the 4-byte length read carries the IDLE wait for
    # the next frame (a server handler parks here between requests) --
    # it stays outside wire.decode so the zone measures frame pumping,
    # not time spent waiting for a peer to speak
    (hlen,) = _HDR.unpack(recv_exact(sock, _HDR.size))
    with _prof.zone("wire.decode"):
        hbytes = recv_exact(sock, hlen)
    with _prof.zone("serde"):
        header = json.loads(hbytes)
    with _prof.zone("wire.decode"):
        (plen,) = _HDR.unpack(recv_exact(sock, _HDR.size))
        payload = recv_exact(sock, plen) if plen else b""
    total = 2 * _HDR.size + hlen + plen
    _io_tls.recv = total
    _count("recv", str(header.get("op", "")), total)
    return header, payload


def recv_msg(sock: socket.socket) -> Tuple[dict, bytes]:
    inj = faults.active()
    if inj is not None:
        endpoint = endpoint_of(sock)
        if inj.partition_active(endpoint):
            # the partition began (or still holds) while a reply was due:
            # the bytes never arrive -- same observable as a gray peer
            inj.note_partition_drop(endpoint, "RECV")
            raise socket.timeout(
                f"fault-injected: partitioned from {endpoint}"
            )
        kind = inj.disarm(sock)
        if kind == faults.STALL_READ:
            # the reply never arrives within the attempt window; the unread
            # bytes stay in the kernel buffer, so the caller MUST drop this
            # connection (the retry layer does)
            raise socket.timeout(
                f"fault-injected: stalled read from {endpoint_of(sock)}"
            )
        if kind == faults.DROP_REPLY:
            # the peer applied the op and replied -- the reply is lost on
            # the wire.  Read and discard it so the injection point is
            # exactly "applied but unacknowledged".
            _recv_msg_raw(sock)
            raise ConnectionError(
                f"fault-injected: reply dropped after apply "
                f"({endpoint_of(sock)})"
            )
    return _recv_msg_raw(sock)
