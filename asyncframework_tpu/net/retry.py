"""Unified retry/backoff policy + per-endpoint circuit breakers.

Before this module the DCN plane had three independent hand-rolled
reconnect loops (``ps_dcn.run_worker_process``'s bare "drop socket, back
off, re-pull", ``RemoteLogTopic._call``'s fixed-count loop, the deploy
daemons' rotate-and-sleep) -- each with its own backoff shape, none with a
deadline, none observable.  :class:`RetryPolicy` is the one policy they all
route through now:

- **exponential backoff with decorrelated jitter** (the AWS-style
  ``sleep = min(cap, U(base, 3 * prev))`` walk) -- fresh entropy per call
  by default so a fleet's retries decorrelate, seedable so a chaos replay
  sleeps the same schedule;
- **per-attempt timeout** (``attempt_timeout_s``: callers set it as the
  socket timeout -- the policy cannot bound a blocking syscall from
  outside) and an **overall deadline** across attempts;
- **retryable-error classification**: transport errors (``OSError`` --
  which covers ``ConnectionError`` and ``socket.timeout``) retry,
  everything else (protocol errors, bad requests) raises immediately;
- a **circuit breaker per endpoint**: after ``breaker_threshold``
  consecutive failures the endpoint is OPEN and calls fail fast with
  :class:`CircuitOpenError` for ``breaker_cooldown_s``, then one half-open
  probe either closes it or re-opens it.  Breakers are shared process-wide
  by endpoint string, so forty worker threads hammering one dead PS back
  off as a group.

Counters (retries, give-ups, breaker trips) are process-global and
surfaced in the live UI next to the shuffle totals.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional


class RetryError(ConnectionError):
    """All attempts exhausted (or deadline passed); ``__cause__`` is the
    last transport error.  Subclasses ConnectionError so existing
    "peer is gone" handlers need no new except clauses."""


class CircuitOpenError(ConnectionError):
    """Failing fast: the endpoint's breaker is open (no dial attempted)."""


_totals_lock = threading.Lock()
_totals = {"retries": 0, "giveups": 0, "breaker_trips": 0}

# -------------------------------------------------------- deadline plumbing
# The overall deadline, enforced AT THE SOCKET LAYER: the policy alone can
# only check the clock between attempts, so one attempt whose socket
# timeout (attempt_timeout_s, default 120 s) exceeds the remaining
# deadline used to hold the caller long past it -- a stalled read (gray
# peer, stall_read fault) outlived the policy.  call() publishes the
# absolute deadline in a thread-local for the attempt's duration;
# net/frame.py consults it before every blocking connect/send/recv and
# caps the socket timeout to the remaining budget (raising socket.timeout
# outright once it is spent).  Zero cost on the no-deadline path.
_deadline_tls = threading.local()


def remaining_deadline_s() -> Optional[float]:
    """Seconds left on the calling thread's active retry deadline; None
    when no deadline-bearing RetryPolicy.call is on the stack."""
    dl = getattr(_deadline_tls, "deadline", None)
    if dl is None:
        return None
    return dl - time.monotonic()


def _bump(key: str, n: int = 1) -> None:
    with _totals_lock:
        _totals[key] += n


def retry_totals() -> Dict[str, int]:
    with _totals_lock:
        return dict(_totals)


def reset_retry_totals() -> None:
    with _totals_lock:
        for k in _totals:
            _totals[k] = 0


class CircuitBreaker:
    """Consecutive-failure breaker: CLOSED -> OPEN (threshold reached) ->
    half-open probe after the cooldown -> CLOSED on success / OPEN again
    on failure."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: Optional[float] = None

    @property
    def open(self) -> bool:
        with self._lock:
            return (
                self._opened_at is not None
                and self._clock() - self._opened_at < self.cooldown_s
            )

    def allow(self) -> bool:
        """False only while OPEN and inside the cooldown; past it the call
        through is the half-open probe."""
        with self._lock:
            if self._opened_at is None:
                return True
            return self._clock() - self._opened_at >= self.cooldown_s

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None

    def record_failure(self) -> bool:
        """Returns True when THIS failure trips (or re-trips) the breaker."""
        with self._lock:
            self._failures += 1
            was_open = self._opened_at is not None
            if self._failures >= self.threshold:
                tripping = (not was_open
                            or self._clock() - self._opened_at
                            >= self.cooldown_s)
                self._opened_at = self._clock()
                return tripping
            return False


_breakers_lock = threading.Lock()
_breakers: Dict[str, CircuitBreaker] = {}


def breaker_for(endpoint: str, threshold: int = 5, cooldown_s: float = 1.0
                ) -> CircuitBreaker:
    """The process-wide breaker for an endpoint (first caller's settings
    win; all clients of one endpoint share one breaker by design)."""
    with _breakers_lock:
        br = _breakers.get(endpoint)
        if br is None:
            br = CircuitBreaker(threshold, cooldown_s)
            _breakers[endpoint] = br
        return br


def reset_breakers() -> None:
    """Drop all per-endpoint breakers (tests; ephemeral ports recycle)."""
    with _breakers_lock:
        _breakers.clear()


def default_classify(exc: BaseException) -> bool:
    """Retry transport faults, surface everything else immediately."""
    return isinstance(exc, OSError)


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 5
    base_ms: float = 50.0
    max_ms: float = 2000.0
    attempt_timeout_s: float = 120.0   # callers apply as the socket timeout
    deadline_s: float = 0.0            # 0 = no overall deadline
    # None = fresh entropy per call(): forty workers losing one PS must NOT
    # wake in lockstep (the thundering herd jitter exists to break).  Chaos
    # runs pin an int so the backoff walk replays.
    seed: Optional[int] = None
    breaker_threshold: int = 5
    breaker_cooldown_s: float = 1.0
    classify: Callable[[BaseException], bool] = field(
        default=default_classify, repr=False, compare=False
    )
    sleep: Callable[[float], None] = field(
        default=time.sleep, repr=False, compare=False
    )

    @classmethod
    def from_conf(cls, conf=None, **overrides) -> "RetryPolicy":
        from asyncframework_tpu import conf as C

        conf = conf if conf is not None else C.global_conf()
        kw = dict(
            max_attempts=conf.get(C.NET_RETRY_MAX_ATTEMPTS),
            base_ms=conf.get(C.NET_RETRY_BASE_MS),
            max_ms=conf.get(C.NET_RETRY_MAX_MS),
            attempt_timeout_s=conf.get(C.NET_RETRY_ATTEMPT_TIMEOUT_S),
            deadline_s=conf.get(C.NET_RETRY_DEADLINE_S),
            breaker_threshold=conf.get(C.NET_BREAKER_THRESHOLD),
            breaker_cooldown_s=conf.get(C.NET_BREAKER_COOLDOWN_S),
        )
        kw.update(overrides)
        return cls(**kw)

    def backoffs_ms(self):
        """The decorrelated-jitter walk this policy sleeps between
        attempts -- deterministic when ``seed`` is pinned, decorrelated
        across clients otherwise; exposed for tests and replay audits."""
        rng = random.Random(self.seed) if self.seed is not None \
            else random.Random()
        prev = self.base_ms
        while True:
            prev = min(self.max_ms, rng.uniform(self.base_ms, prev * 3))
            yield prev

    def call(self, fn: Callable, *, endpoint: Optional[str] = None,
             on_retry: Optional[Callable[[int, BaseException], None]] = None):
        """Run ``fn()`` under this policy.  ``endpoint`` opts into the
        shared circuit breaker; ``on_retry(attempt, exc)`` fires before
        each backoff sleep (callers use it to drop dead sockets)."""
        br = (breaker_for(endpoint, self.breaker_threshold,
                          self.breaker_cooldown_s)
              if endpoint is not None else None)
        backoff = self.backoffs_ms()
        deadline = (time.monotonic() + self.deadline_s
                    if self.deadline_s > 0 else None)
        # publish the absolute deadline for the socket layer (net/frame.py
        # caps connect/recv timeouts to the remaining budget); nested
        # policy calls see the TIGHTER of the two deadlines
        outer_dl = getattr(_deadline_tls, "deadline", None)
        if deadline is not None:
            _deadline_tls.deadline = (deadline if outer_dl is None
                                      else min(deadline, outer_dl))
        try:
            return self._call_inner(fn, br, backoff, deadline, endpoint,
                                    on_retry)
        finally:
            if deadline is not None:
                _deadline_tls.deadline = outer_dl

    def _call_inner(self, fn, br, backoff, deadline, endpoint, on_retry):
        last: Optional[BaseException] = None
        attempt = 0
        for attempt in range(1, self.max_attempts + 1):
            if br is not None and not br.allow():
                _bump("giveups")
                raise CircuitOpenError(
                    f"circuit open for {endpoint} "
                    f"(cooldown {self.breaker_cooldown_s}s)"
                ) from last
            try:
                out = fn()
            except BaseException as e:  # noqa: BLE001 - classified below
                if not self.classify(e):
                    raise
                last = e
                if br is not None and br.record_failure():
                    _bump("breaker_trips")
                if attempt >= self.max_attempts:
                    break
                if deadline is not None and time.monotonic() >= deadline:
                    break
                _bump("retries")
                if on_retry is not None:
                    on_retry(attempt, e)
                pause = next(backoff) / 1e3
                if deadline is not None:
                    pause = min(pause, max(0.0, deadline - time.monotonic()))
                self.sleep(pause)
                continue
            if br is not None:
                br.record_success()
            return out
        _bump("giveups")
        raise RetryError(
            f"gave up after {attempt} attempt(s)"
            + (f" to {endpoint}" if endpoint else "")
            + f": {last!r}"
        ) from last
