"""Columnar frame tests: the Spark SQL DataFrame capability analog.

Parity targets (SURVEY.md section 2.5, ``Dataset.scala:166`` surface):
select/filter/withColumn expression fusion, groupBy-agg, sort, equi-joins
(inner + left, duplicate keys), collected row semantics.  Ground truth is
hand-computed or plain NumPy.
"""

import numpy as np
import pytest

from asyncframework_tpu.sql import ColumnarFrame, col, lit


@pytest.fixture()
def sales():
    return ColumnarFrame({
        "region": np.array(["west", "east", "west", "south", "east", "west"]),
        "units": np.array([10, 3, 7, 1, 9, 2], np.int32),
        "price": np.array([1.5, 2.0, 1.0, 4.0, 0.5, 3.0], np.float32),
    })


class TestBasics:
    def test_construction_validates(self):
        with pytest.raises(ValueError, match="rows"):
            ColumnarFrame({"a": np.arange(3), "b": np.arange(4)})
        with pytest.raises(ValueError, match="1-d"):
            ColumnarFrame({"a": np.zeros((2, 2))})

    def test_select_and_expressions(self, sales):
        out = sales.select(
            "region", (col("units") * col("price")).alias("revenue")
        )
        assert out.columns == ["region", "revenue"]
        np.testing.assert_allclose(
            np.asarray(out["revenue"]), [15, 6, 7, 4, 4.5, 6]
        )

    def test_with_column_and_literals(self, sales):
        out = sales.with_column("discounted", col("price") * lit(0.9))
        np.testing.assert_allclose(
            np.asarray(out["discounted"]),
            np.asarray(sales["price"]) * 0.9,
            rtol=1e-6,
        )
        # original frame untouched (immutability)
        assert "discounted" not in sales.columns

    def test_missing_column_raises(self, sales):
        with pytest.raises(KeyError, match="nope"):
            sales.select(col("nope") + 1)


class TestFilterSort:
    def test_filter_predicates_compose(self, sales):
        out = sales.filter((col("units") > 2) & (col("price") < 2.0))
        assert out.collect() == [("west", 10, 1.5), ("west", 7, 1.0),
                                 ("east", 9, 0.5)]

    def test_filter_keeps_host_key_columns_aligned(self, sales):
        out = sales.filter(col("units") >= 9)
        assert list(out["region"]) == ["west", "east"]

    def test_sort(self, sales):
        out = sales.sort("units", ascending=False)
        assert list(np.asarray(out["units"])) == [10, 9, 7, 3, 2, 1]

    def test_negation(self, sales):
        out = sales.filter(~(col("region") == lit("west")))
        assert len(out) == 3


class TestGroupBy:
    def test_agg_sum_mean_min_max(self, sales):
        out = (
            sales.groupby("region")
            .agg(total=("units", "sum"), avg_price=("price", "mean"),
                 lo=("price", "min"), hi=("price", "max"))
            .sort("region")
        )
        # np.unique sorts keys: east, south, west
        assert list(out["region"]) == ["east", "south", "west"]
        np.testing.assert_allclose(np.asarray(out["total"]), [12, 1, 19])
        np.testing.assert_allclose(np.asarray(out["avg_price"]),
                                   [1.25, 4.0, 5.5 / 3], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(out["lo"]), [0.5, 4.0, 1.0])
        np.testing.assert_allclose(np.asarray(out["hi"]), [2.0, 4.0, 3.0])

    def test_count(self, sales):
        out = sales.groupby("region").count().sort("region")
        assert list(np.asarray(out["count"])) == [2, 1, 3]

    def test_whole_frame_agg(self, sales):
        out = sales.agg(n=("units", "count"), s=("units", "sum"))
        assert out == {"n": 6, "s": 32}

    def test_unknown_agg(self, sales):
        with pytest.raises(ValueError, match="unknown aggregate"):
            sales.groupby("region").agg(x=("units", "median"))


class TestJoin:
    def test_inner_join_with_duplicate_right_keys(self):
        left = ColumnarFrame({
            "k": np.array([1, 2, 3], np.int32),
            "l": np.array([10.0, 20.0, 30.0], np.float32),
        })
        right = ColumnarFrame({
            "k": np.array([2, 2, 4], np.int32),
            "r": np.array([5.0, 6.0, 7.0], np.float32),
        })
        out = left.join(right, on="k")
        # k=2 matches twice; k=1,3 drop
        rows = sorted(out.collect())
        assert rows == [(2, 20.0, 5.0), (2, 20.0, 6.0)]

    def test_left_join_fills_nan(self):
        left = ColumnarFrame({
            "k": np.array([1, 2], np.int32),
            "l": np.array([1.0, 2.0], np.float32),
        })
        right = ColumnarFrame({
            "k": np.array([2], np.int32),
            "r": np.array([9.0], np.float32),
        })
        out = left.join(right, on="k", how="left").sort("k")
        r = np.asarray(out["r"])
        assert np.isnan(r[0]) and r[1] == 9.0

    def test_join_on_string_keys(self, sales):
        lookup = ColumnarFrame({
            "region": np.array(["west", "east"]),
            "manager": np.array(["ada", "bob"]),
        })
        out = sales.join(lookup, on="region")
        assert len(out) == 5  # south has no match
        managers = set(out["manager"])
        assert managers == {"ada", "bob"}

    def test_name_collision_suffixes(self):
        left = ColumnarFrame({"k": np.array([1]), "v": np.array([1.0])})
        right = ColumnarFrame({"k": np.array([1]), "v": np.array([2.0])})
        out = left.join(right, on="k")
        assert set(out.columns) == {"k", "v", "v_right"}

    def test_bad_how(self):
        f = ColumnarFrame({"k": np.array([1])})
        with pytest.raises(ValueError, match="how"):
            f.join(f, on="k", how="outer")

    def test_left_join_masks_host_columns(self):
        """Unmatched rows must not leak the right frame's row-0 strings."""
        left = ColumnarFrame({"k": np.array([1, 2], np.int32)})
        right = ColumnarFrame({
            "k": np.array([2], np.int32),
            "name": np.array(["bob"]),
        })
        out = left.join(right, on="k", how="left").sort("k")
        assert list(out["name"]) == ["", "bob"]

    def test_left_join_empty_right(self):
        left = ColumnarFrame({
            "k": np.array([1, 2], np.int32),
            "l": np.array([1.0, 2.0], np.float32),
        })
        right = ColumnarFrame({
            "k": np.array([], np.int32),
            "r": np.array([], np.float32),
        })
        out = left.join(right, on="k", how="left")
        assert len(out) == 2
        assert np.isnan(np.asarray(out["r"])).all()
        assert left.join(right, on="k").count() == 0  # inner: no rows
