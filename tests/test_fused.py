"""Device-resident accept loop (VERDICT r3 item 2): the taw=inf fused path.

The semantics argument (steps.make_fused_asgd_rounds): at taw=inf with
full-wave cohorts the engine's accept path IS "cohort reads one version,
applies in order" -- a pure device function.  These tests pin (a)
convergence parity with the engine path on the same recipe, (b) the scope
guards, (c) accounting sanity.
"""

import numpy as np
import pytest

from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.solvers import ASGD, SolverConfig


def make_cfg(**kw):
    defaults = dict(
        num_workers=8, num_iterations=400, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=1.0, printer_freq=50, seed=42,
        calibration_iters=10, run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture(scope="module")
def planted(devices8):
    return ShardedDataset.generate_on_device(
        4096, 24, 8, devices=[devices8[0]] * 8, seed=11, noise=0.01
    )


class TestFusedASGD:
    def test_converges_to_same_band_as_engine(self, devices8, planted):
        cfg = make_cfg()
        fused = ASGD(planted, None, cfg, devices=[devices8[0]]).run_fused()
        engine = ASGD(planted, None, cfg, devices=[devices8[0]]).run()
        f_first, f_last = fused.trajectory[0][1], fused.trajectory[-1][1]
        e_last = engine.trajectory[-1][1]
        assert f_last < f_first * 0.05, fused.trajectory[-3:]
        # same recipe, same contraction band (interleaving differs)
        assert f_last < max(e_last * 3.0, 1e-8), (f_last, e_last)

    def test_accounting(self, devices8, planted):
        cfg = make_cfg(num_iterations=160)
        res = ASGD(planted, None, cfg, devices=[devices8[0]]).run_fused()
        assert res.accepted >= 160
        assert res.rounds == -(-160 // 8)
        assert res.dropped == 0
        assert res.extras["fused"] is True
        assert res.total_flops > 0
        assert res.updates_per_sec > 0
        # trajectory timestamps are monotonically non-decreasing
        ts = [t for t, _ in res.trajectory]
        assert all(a <= b for a, b in zip(ts, ts[1:]))

    def test_guards(self, devices8, planted):
        with pytest.raises(ValueError, match="taw"):
            ASGD(planted, None, make_cfg(taw=0),
                 devices=[devices8[0]]).run_fused()
        with pytest.raises(ValueError, match="straggler"):
            ASGD(planted, None, make_cfg(coeff=1.0),
                 devices=[devices8[0]]).run_fused()

    def test_finite_taw_admitted_when_filter_cannot_fire(
        self, devices8, planted
    ):
        """ASGD taw=64 >= nw-1=7: the fused wave's staleness never exceeds
        nw-1, so it is a valid bounded-staleness execution -- and it lands
        in the engine band for the same recipe."""
        cfg = make_cfg(taw=64, num_iterations=240)
        fused = ASGD(planted, None, cfg, devices=[devices8[0]]).run_fused()
        engine = ASGD(planted, None, cfg, devices=[devices8[0]]).run()
        assert fused.accepted >= 240
        f_last = fused.trajectory[-1][1]
        assert f_last < max(engine.trajectory[-1][1] * 3.0, 1e-8)

    def test_sparse_fused_matches_engine_band(self, devices8):
        """rcv1-class shards fuse too -- the dataset whose per-update host
        floor made its baseline unreachable through the engine loop.  Same
        engine-band parity contract as the dense test: a drifted validity
        mask or scaling in the fused sparse step would converge somewhere
        else."""
        from asyncframework_tpu.data.sparse import SparseShardedDataset

        ds = SparseShardedDataset.generate_on_device(
            4096, 512, 12, 8, devices=[devices8[0]] * 8, seed=9, noise=0.01
        )
        cfg = make_cfg(gamma=0.05 * 512, num_iterations=400)
        fused = ASGD(ds, None, cfg, devices=[devices8[0]]).run_fused()
        engine = ASGD(ds, None, cfg, devices=[devices8[0]]).run()
        f_first, f_last = fused.trajectory[0][1], fused.trajectory[-1][1]
        e_last = engine.trajectory[-1][1]
        assert f_last < f_first * 0.1, fused.trajectory[-3:]
        assert f_last < max(e_last * 3.0, 1e-8), (f_last, e_last)
        assert fused.extras["fused"] is True

    def test_sparse_fused_rejects_logistic(self, devices8):
        from asyncframework_tpu.ops import steps

        with pytest.raises(ValueError, match="least_squares"):
            steps.make_fused_asgd_rounds(
                1.0, 0.3, 100, [(None, None, None)], loss="logistic",
                sparse_d=16,
            )

    def test_deterministic_per_seed(self, devices8, planted):
        cfg = make_cfg(num_iterations=80)
        a = ASGD(planted, None, cfg, devices=[devices8[0]]).run_fused()
        b = ASGD(planted, None, cfg, devices=[devices8[0]]).run_fused()
        assert np.allclose(a.final_w, b.final_w)


class TestFusedASAGA:
    def test_matches_engine_band_and_history_invariant(
        self, devices8, planted
    ):
        from asyncframework_tpu.solvers import ASAGA

        cfg = make_cfg(gamma=0.35, num_iterations=320)
        fused = ASAGA(planted, None, cfg, devices=[devices8[0]]).run_fused()
        engine = ASAGA(planted, None, cfg, devices=[devices8[0]]).run()
        f_first, f_last = fused.trajectory[0][1], fused.trajectory[-1][1]
        e_last = engine.trajectory[-1][1]
        assert f_last < f_first * 0.05, fused.trajectory[-3:]
        assert f_last < max(e_last * 3.0, 1e-8), (f_last, e_last)
        assert fused.extras["fused"] is True
        # THE invariant: alpha_bar == (1/N) sum_i X_i^T alpha_i exactly
        # (delta == g is exact in a full wave) -- a dead commit path would
        # leave the table at zero while alpha_bar drifts, failing this
        ab = fused.extras["alpha_bar"]
        acc = np.zeros_like(ab, dtype=np.float64)
        for wid, a in fused.extras["alpha"].items():
            X = np.asarray(planted.shard(wid).X)
            acc += X.T @ a
        acc /= planted.n
        assert any(np.any(a != 0) for a in fused.extras["alpha"].values())
        np.testing.assert_allclose(ab, acc, rtol=2e-3, atol=2e-5)

    def test_sparse_fused_asaga_matches_engine_band(self, devices8):
        """The last cell of the fused matrix: sparse ASAGA.  Same
        engine-band parity contract; the in-scan commit mirrors the
        engine's compacted scatter (padding slots dropped)."""
        from asyncframework_tpu.data.sparse import SparseShardedDataset
        from asyncframework_tpu.solvers import ASAGA

        ds = SparseShardedDataset.generate_on_device(
            4096, 512, 12, 8, devices=[devices8[0]] * 8, seed=9, noise=0.01
        )
        cfg = make_cfg(gamma=1.5, num_iterations=400)
        fused = ASAGA(ds, None, cfg, devices=[devices8[0]]).run_fused()
        engine = ASAGA(ds, None, cfg, devices=[devices8[0]]).run()
        f_first, f_last = fused.trajectory[0][1], fused.trajectory[-1][1]
        e_last = engine.trajectory[-1][1]
        # contraction band widened 0.1 -> 0.2 (ISSUE 12 deflake):
        # trajectory[0] is the loss AFTER the first printer_freq=50
        # accepted updates, so f_first is itself partially converged and
        # the ratio is interleaving/load-dependent -- observed 0.106 on
        # an idle rig (loss 36 -> 0.855 by the first snapshot -> 0.091
        # final), i.e. a marginal trip of the old band, not a
        # regression.  The load-bearing contract is the ENGINE-parity
        # band below; this assert only guards against a flat trajectory.
        assert f_last < f_first * 0.2, fused.trajectory[-3:]
        assert f_last < max(e_last * 3.0, 1e-8), (f_last, e_last)
        # THE invariant, sparse form: alpha_bar == (1/N) sum_i A_i^T
        # alpha_i with A_i densified from the padded-ELL shard -- a dead
        # or wrong in-scan commit fails this
        ab = fused.extras["alpha_bar"]
        acc = np.zeros_like(ab, dtype=np.float64)
        for wid, a in fused.extras["alpha"].items():
            shard = ds.shard(wid)
            cols = np.asarray(shard.cols)
            vals = np.asarray(shard.vals)
            # np.add.at: fancy += would drop duplicate columns within a
            # row (a real col-0 feature collides with padding zeros)
            np.add.at(acc, cols.ravel(), (vals * a[:, None]).ravel())
        acc /= ds.n
        assert any(np.any(a != 0) for a in fused.extras["alpha"].values())
        np.testing.assert_allclose(ab, acc, rtol=5e-3, atol=5e-5)

    def test_guards(self, devices8, planted):
        from asyncframework_tpu.solvers import ASAGA

        # ASAGA's filter quirk binds on ITERATION COUNT (k - staleness <=
        # taw), so even a taw far above nw-1 is rejected when it is below
        # num_iterations -- the engine would drop updates past k ~ taw
        with pytest.raises(ValueError, match="num_iterations"):
            ASAGA(planted, None,
                  make_cfg(gamma=0.35, taw=64, num_iterations=320),
                  devices=[devices8[0]]).run_fused()
        with pytest.raises(ValueError, match="straggler"):
            ASAGA(planted, None, make_cfg(gamma=0.35, coeff=2.0),
                  devices=[devices8[0]]).run_fused()
