"""Smoke tests: every example's main() runs end-to-end with tiny sizes.

Parity with the reference shipping runnable ``examples/`` alongside the
framework; keeping them executed in CI prevents doc rot.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

EXAMPLES = Path(__file__).resolve().parent.parent / "examples"
sys.path.insert(0, str(EXAMPLES))


def test_asgd_async_example():
    import asgd_async

    res = asgd_async.main(n=2048, d=16, iters=150)
    assert res.accepted == 150
    assert np.isfinite(res.final_objective)


def test_asaga_history_example():
    import asaga_history

    res = asaga_history.main(n=2048, d=16, iters=120)
    assert res.accepted == 120


def test_streaming_example():
    import streaming_pipeline

    out = streaming_pipeline.main(n_batches=4, batch=32, d=8)
    assert len(out) == 4


def test_graph_example():
    import graph_pagerank

    r, cc = graph_pagerank.main(n=200, e=800)
    assert r.sum() == pytest.approx(1.0, abs=1e-3)
    assert cc.shape == (200,)


def test_ring_attention_example():
    import ring_attention_demo

    out = ring_attention_demo.main(t=64, h=4, d=8)
    assert np.isfinite(np.asarray(out)).all()


def test_log_topic_example():
    import log_topic_pipeline

    revenue, replayed = log_topic_pipeline.main(n_events=600, per_batch=200)
    assert len(revenue) == 3          # 600 events / 200 per batch
    assert all(r > 0 for r in revenue)
    assert replayed == []             # committed offsets: nothing replays


def test_network_topic_example(capsys):
    import network_topic_stream

    network_topic_stream.main(n_events=400, per_batch=100)
    out = capsys.readouterr().out
    assert "consumed exactly once" in out


def test_sql_explain_example(capsys):
    import sql_explain_optimizer

    sql_explain_optimizer.main()
    out = capsys.readouterr().out
    assert "Scan(dim)" in out                 # reorder visible
    assert "SetOp(union_all)" in out
    assert out.count("Shared(s)") == 2        # execute-once CTE


def test_sql_example():
    import sql_pipeline

    report = sql_pipeline.main(n=500)
    assert set(report.columns) >= {"region", "revenue", "manager"}
    assert len(report) == 3


def test_sql_ml_pipeline_example():
    import sql_ml_pipeline

    acc = sql_ml_pipeline.main(n=600, quiet=True)
    assert acc > 0.7


def test_sparse_asgd_example():
    import sparse_asgd

    res = sparse_asgd.main(n=512, d=4096, iters=60, quiet=True)
    assert res.accepted == 60


@pytest.mark.slow
def test_staleness_experiment_example():
    import staleness_experiment

    out = staleness_experiment.main(n=1024, d=16, iters=80, coeff=1.0,
                                    quiet=True)
    assert set(out) == {"sync + straggler", "async tau=inf", "async tau=8",
                        "async stale-read-2"}
    for res in out.values():
        assert res.trajectory[-1][1] < res.trajectory[0][1]


def test_streaming_kmeans_example():
    import streaming_kmeans_demo

    model, labels = streaming_kmeans_demo.main(n_batches=6, per_cluster=20)
    # centers tracked the drifting clusters: still well separated
    c = np.sort(model.centers[:, 0])
    assert c[1] - c[0] > 5.0
    assert len(labels) == 6


def test_sql_analytics_example():
    import sql_analytics

    heavy = sql_analytics.main(n=1000, n_users=20)
    totals = np.asarray(heavy["total"])
    assert np.all(totals > 500)
    assert np.all(np.diff(totals) <= 0)  # ORDER BY total DESC
