"""Graph layer tests: Pregel substrate + PageRank / connected components.

Parity targets: GraphX ``Pregel.scala`` iteration semantics and the
``lib/PageRank`` / ``lib/ConnectedComponents`` algorithms; correctness is
checked against dense NumPy reference implementations.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from asyncframework_tpu.graph import (
    Graph,
    connected_components,
    pagerank,
    pregel,
)
from asyncframework_tpu.graph.pregel import segment_combine


class TestGraph:
    def test_degrees(self):
        g = Graph.from_edges([(0, 1), (0, 2), (1, 2), (2, 0)])
        np.testing.assert_array_equal(g.out_degrees(), [2, 1, 1])
        np.testing.assert_array_equal(g.in_degrees(), [1, 1, 2])
        np.testing.assert_array_equal(g.degrees(), [3, 2, 3])
        assert g.num_vertices == 3 and g.num_edges == 4

    def test_reverse(self):
        g = Graph.from_edges([(0, 1), (1, 2)])
        r = g.reverse()
        np.testing.assert_array_equal(r.src, [1, 2])
        np.testing.assert_array_equal(r.dst, [0, 1])

    def test_validation(self):
        with pytest.raises(ValueError, match="equal length"):
            Graph([0, 1], [1])
        with pytest.raises(ValueError, match="num_vertices"):
            Graph([], [], num_vertices=None)
        with pytest.raises(ValueError, match="vertex_attr"):
            Graph([0], [1], num_vertices=2, vertex_attr=np.zeros(3))


class TestSegmentCombine:
    def test_sum_min_max(self):
        msgs = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        dst = jnp.asarray([0, 0, 1, 2])
        np.testing.assert_array_equal(
            segment_combine(msgs, dst, 4, "sum"), [3.0, 3.0, 4.0, 0.0]
        )
        out_min = segment_combine(msgs, dst, 4, "min")
        np.testing.assert_array_equal(out_min[:3], [1.0, 3.0, 4.0])
        assert np.isinf(out_min[3])  # identity for vertices with no messages

    def test_unknown_merge(self):
        with pytest.raises(ValueError, match="merge"):
            segment_combine(jnp.zeros(1), jnp.zeros(1, jnp.int32), 1, "mul")

    def test_integer_identities_exact(self):
        """Int messages get int identities (not inf cast to INT_MIN): a
        vertex with no incoming edges must be a true no-op under min/max."""
        msgs = jnp.asarray([5, 7], jnp.int32)
        dst = jnp.asarray([0, 0], jnp.int32)
        out_min = segment_combine(msgs, dst, 2, "min")
        assert int(out_min[0]) == 5
        assert int(out_min[1]) == jnp.iinfo(jnp.int32).max
        out_max = segment_combine(msgs, dst, 2, "max")
        assert int(out_max[0]) == 7
        assert int(out_max[1]) == jnp.iinfo(jnp.int32).min


class TestPregel:
    def test_sssp_min_plus(self):
        """Single-source shortest paths: the classic Pregel example."""
        edges = [(0, 1), (1, 2), (0, 2), (2, 3)]
        w = jnp.asarray([1.0, 1.0, 5.0, 1.0])
        g = Graph.from_edges(edges, num_vertices=5)
        g = Graph(g.src, g.dst, 5, edge_attr=w)
        inf = jnp.inf
        dist0 = jnp.asarray([0.0, inf, inf, inf, inf])

        def vprog(d, incoming):
            return jnp.minimum(d, incoming)

        def send(src_d, dst_d, e):
            return src_d + e

        out = pregel(g, dist0, vprog, send, merge="min", max_iterations=10)
        np.testing.assert_array_equal(out[:4], [0.0, 1.0, 2.0, 3.0])
        assert np.isinf(out[4])  # unreachable vertex

    def test_early_termination_on_convergence(self):
        """A fixed-point vprog must stop before max_iterations (while_loop
        cond), not run all of them: verify via a huge max_iterations that
        would time out if actually executed element-wise on host."""
        g = Graph.from_edges([(0, 1)], num_vertices=2)
        out = pregel(
            g,
            jnp.zeros(2),
            lambda a, m: a,  # fixed point immediately
            lambda s, d, e: s,
            merge="sum",
            max_iterations=10**9,
        )
        np.testing.assert_array_equal(out, [0.0, 0.0])


def numpy_pagerank(edges, n, alpha, iters):
    M = np.zeros((n, n))
    for s, d in edges:
        M[d, s] += 1.0
    outdeg = M.sum(axis=0)
    r = np.full(n, 1.0 / n)
    for _ in range(iters):
        contrib = np.where(outdeg > 0, r / np.maximum(outdeg, 1), 0.0)
        dangling = r[outdeg == 0].sum()
        r = (1 - alpha) / n + alpha * (M @ contrib + dangling / n)
    return r


class TestPageRank:
    def test_matches_dense_numpy(self):
        rs = np.random.default_rng(7)
        n, e = 30, 120
        edges = list({(int(a), int(b))
                      for a, b in rs.integers(0, n, size=(e, 2)) if a != b})
        g = Graph.from_edges(edges, num_vertices=n)
        got = np.asarray(pagerank(g, alpha=0.85, num_iterations=30))
        want = numpy_pagerank(edges, n, 0.85, 30)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
        assert got.sum() == pytest.approx(1.0, abs=1e-4)

    def test_star_graph_center_ranks_highest(self):
        edges = [(i, 0) for i in range(1, 6)]
        g = Graph.from_edges(edges, num_vertices=6)
        r = np.asarray(pagerank(g, num_iterations=30))
        assert r[0] == max(r)

    def test_tol_early_stop_close_to_fixed_iterations(self):
        edges = [(0, 1), (1, 2), (2, 0)]
        g = Graph.from_edges(edges)
        r_fixed = np.asarray(pagerank(g, num_iterations=100))
        r_tol = np.asarray(pagerank(g, num_iterations=100, tol=1e-7))
        np.testing.assert_allclose(r_tol, r_fixed, atol=1e-5)


class TestConnectedComponents:
    def test_two_components_and_isolate(self):
        # component {0,1,2}, component {3,4}, isolate {5}
        g = Graph.from_edges([(0, 1), (1, 2), (4, 3)], num_vertices=6)
        labels = np.asarray(connected_components(g))
        np.testing.assert_array_equal(labels, [0, 0, 0, 3, 3, 5])

    def test_chain_converges_to_min_id(self):
        n = 50
        g = Graph.from_edges([(i, i + 1) for i in range(n - 1)], num_vertices=n)
        labels = np.asarray(connected_components(g))
        np.testing.assert_array_equal(labels, np.zeros(n, np.int32))

    def test_direction_ignored(self):
        g = Graph.from_edges([(1, 0), (1, 2)], num_vertices=3)  # arrows differ
        labels = np.asarray(connected_components(g))
        np.testing.assert_array_equal(labels, [0, 0, 0])


class TestTriangleCount:
    def test_single_triangle(self):
        from asyncframework_tpu.graph import triangle_count

        g = Graph.from_edges([(0, 1), (1, 2), (2, 0), (2, 3)], 4)
        counts = np.asarray(triangle_count(g))
        np.testing.assert_array_equal(counts, [1, 1, 1, 0])

    def test_duplicate_and_self_edges_canonicalized(self):
        from asyncframework_tpu.graph import triangle_count

        g = Graph.from_edges(
            [(0, 1), (1, 0), (1, 2), (2, 0), (0, 0), (2, 2)], 3
        )
        np.testing.assert_array_equal(np.asarray(triangle_count(g)), [1, 1, 1])

    def test_k4_has_three_per_vertex(self):
        from asyncframework_tpu.graph import triangle_count

        edges = [(i, j) for i in range(4) for j in range(i + 1, 4)]
        g = Graph.from_edges(edges, 4)
        np.testing.assert_array_equal(
            np.asarray(triangle_count(g)), [3, 3, 3, 3]
        )


class TestLabelPropagation:
    def test_two_cliques_converge_to_two_labels(self):
        from asyncframework_tpu.graph import label_propagation

        clique = lambda vs: [(a, b) for a in vs for b in vs if a < b]
        g = Graph.from_edges(clique([0, 1, 2, 3]) + clique([4, 5, 6, 7])
                             + [(3, 4)], 8)
        labels = np.asarray(label_propagation(g, max_iterations=10))
        assert len(set(labels[:3])) == 1
        assert len(set(labels[5:])) == 1


class TestShortestPaths:
    def test_hop_counts_to_landmarks(self):
        from asyncframework_tpu.graph import shortest_paths

        # path 0-1-2-3, isolated 4
        g = Graph.from_edges([(0, 1), (1, 2), (2, 3)], 5)
        d = np.asarray(shortest_paths(g, landmarks=[0, 3]))
        np.testing.assert_array_equal(d[:, 0][:4], [0, 1, 2, 3])
        np.testing.assert_array_equal(d[:, 1][:4], [3, 2, 1, 0])
        assert np.isinf(d[4]).all()


class TestPartitionStrategies:
    def edges(self):
        rs = np.random.default_rng(0)
        return Graph.from_edges(rs.integers(0, 100, size=(2000, 2)), 100)

    @pytest.mark.parametrize("strategy", [
        "edge_1d", "edge_2d", "random_vertex_cut",
        "canonical_random_vertex_cut",
    ])
    def test_valid_deterministic_and_balanced(self, strategy):
        from asyncframework_tpu.graph import partition_edges

        g = self.edges()
        p1 = np.asarray(partition_edges(g, 8, strategy))
        p2 = np.asarray(partition_edges(g, 8, strategy))
        np.testing.assert_array_equal(p1, p2)
        assert p1.min() >= 0 and p1.max() < 8
        counts = np.bincount(p1, minlength=8)
        assert counts.max() < 4 * max(counts.min(), 1)  # rough balance

    def test_canonical_colocates_both_directions(self):
        from asyncframework_tpu.graph import partition_edges

        g = Graph.from_edges([(1, 7), (7, 1), (3, 9), (9, 3)], 10)
        p = np.asarray(partition_edges(g, 6, "canonical_random_vertex_cut"))
        assert p[0] == p[1] and p[2] == p[3]

    def test_edge_1d_groups_by_src(self):
        from asyncframework_tpu.graph import partition_edges

        g = Graph.from_edges([(5, 1), (5, 2), (5, 3)], 6)
        p = np.asarray(partition_edges(g, 4, "edge_1d"))
        assert len(set(p)) == 1


class TestStronglyConnectedComponents:
    def test_cycle_vs_chain(self):
        from asyncframework_tpu.graph import strongly_connected_components

        # 0->1->2->0 is a cycle (one SCC); 3->4 is a chain (two SCCs)
        g = Graph.from_edges([(0, 1), (1, 2), (2, 0), (2, 3), (3, 4)])
        lab = np.asarray(strongly_connected_components(g))
        assert lab[0] == lab[1] == lab[2] == 0
        assert lab[3] != lab[0] and lab[4] != lab[3]

    def test_two_cycles_bridged(self):
        from asyncframework_tpu.graph import strongly_connected_components

        g = Graph.from_edges(
            [(0, 1), (1, 0), (2, 3), (3, 2), (1, 2)]  # bridge 1->2 only
        )
        lab = np.asarray(strongly_connected_components(g))
        assert lab[0] == lab[1]
        assert lab[2] == lab[3]
        assert lab[0] != lab[2]

    def test_matches_scipy_on_random(self):
        from scipy.sparse import csr_matrix
        from scipy.sparse.csgraph import connected_components as scc

        from asyncframework_tpu.graph import strongly_connected_components

        rs = np.random.default_rng(8)
        n = 30
        dense = rs.random((n, n)) < 0.08
        np.fill_diagonal(dense, False)
        src, dst = np.nonzero(dense)
        g = Graph(jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32), n)
        lab = np.asarray(strongly_connected_components(g))
        _, want = scc(csr_matrix(dense), connection="strong")
        # same partition (labels may differ): compare co-membership
        same_ours = lab[:, None] == lab[None, :]
        same_want = want[:, None] == want[None, :]
        np.testing.assert_array_equal(same_ours, same_want)


class TestSVDPlusPlus:
    def test_fits_structured_ratings(self):
        from asyncframework_tpu.graph import svd_plus_plus

        # two user groups x two item groups with distinct mean ratings
        rs = np.random.default_rng(9)
        users, items, ratings = [], [], []
        for u in range(20):
            for i in range(20):
                if rs.random() < 0.6:
                    base = 4.5 if (u < 10) == (i < 10) else 1.5
                    users.append(u)
                    items.append(i)
                    ratings.append(base + 0.1 * rs.normal())
        users, items = np.asarray(users), np.asarray(items)
        ratings = np.asarray(ratings, np.float32)
        model = svd_plus_plus(
            users, items, ratings, rank=4, num_iterations=300, lr=0.5,
        )
        pred = model.predict(users, items)
        rmse = float(np.sqrt(np.mean((pred - ratings) ** 2)))
        base_rmse = float(np.std(ratings))
        assert rmse < 0.5 * base_rmse  # explains most block structure


class TestPersonalizedPageRank:
    def test_mass_concentrates_near_source(self):
        from asyncframework_tpu.graph import personalized_pagerank

        # chain 0 -> 1 -> 2 -> 3 -> 4: ranks must decay with distance
        g = Graph.from_edges([(0, 1), (1, 2), (2, 3), (3, 4)])
        r = np.asarray(personalized_pagerank(g, source=0,
                                             num_iterations=50))
        assert np.all(np.diff(r) < 0)  # strictly decaying along the chain
        np.testing.assert_allclose(r.sum(), 1.0, rtol=1e-4)

    def test_source_validation(self):
        from asyncframework_tpu.graph import personalized_pagerank

        g = Graph.from_edges([(0, 1)])
        with pytest.raises(ValueError):
            personalized_pagerank(g, source=5)

    def test_matches_dense_oracle(self):
        from asyncframework_tpu.graph import personalized_pagerank

        rs = np.random.default_rng(11)
        n = 20
        dense = rs.random((n, n)) < 0.15
        np.fill_diagonal(dense, False)
        src, dst = np.nonzero(dense)
        g = Graph(jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32), n)
        got = np.asarray(personalized_pagerank(g, 3, num_iterations=80))
        # dense power-iteration oracle with teleport+dangling to source
        A = dense.astype(np.float64)
        deg = A.sum(1)
        onehot = np.zeros(n); onehot[3] = 1.0
        r = onehot.copy()
        for _ in range(80):
            spread = np.where(deg > 0, r / np.maximum(deg, 1), 0.0)
            inc = A.T @ spread
            d_mass = r[deg == 0].sum()
            r = 0.15 * onehot + 0.85 * (inc + d_mass * onehot)
        np.testing.assert_allclose(got, r, rtol=1e-4, atol=1e-6)


class TestGraphViews:
    def test_aggregate_messages_degree_weighted(self):
        # per-vertex sum of incoming source attrs: the degree-matrix use
        g = Graph.from_edges([(0, 1), (0, 2), (1, 2)], 3)
        g = g.with_vertex_attr(jnp.asarray([1.0, 10.0, 100.0]))
        out = g.aggregate_messages(lambda sa, da, e: sa, merge="sum")
        np.testing.assert_allclose(np.asarray(out), [0.0, 1.0, 11.0])

    def test_aggregate_messages_with_edge_attr(self):
        g = Graph(jnp.asarray([0, 1], jnp.int32), jnp.asarray([1, 0], jnp.int32),
                  2, vertex_attr=jnp.asarray([2.0, 3.0]),
                  edge_attr=jnp.asarray([10.0, 100.0]))
        out = g.aggregate_messages(lambda sa, da, e: sa * e, merge="max")
        np.testing.assert_allclose(np.asarray(out), [300.0, 20.0])

    def test_subgraph_masks(self):
        g = Graph.from_edges([(0, 1), (1, 2), (2, 3)], 4)
        sub = g.subgraph(vertex_mask=np.array([True, True, True, False]))
        assert sub.num_edges == 2          # (2,3) dropped
        assert sub.num_vertices == 4       # vertex domain preserved
        sub2 = g.subgraph(edge_mask=np.array([True, False, True]))
        np.testing.assert_array_equal(np.asarray(sub2.src), [0, 2])

    def test_map_vertices_and_edges(self):
        g = Graph(jnp.asarray([0], jnp.int32), jnp.asarray([1], jnp.int32), 2,
                  vertex_attr=jnp.asarray([1.0, 2.0]),
                  edge_attr=jnp.asarray([5.0]))
        g2 = g.map_vertices(lambda a: a * 2).map_edges(lambda e: e + 1)
        np.testing.assert_allclose(np.asarray(g2.vertex_attr), [2.0, 4.0])
        np.testing.assert_allclose(np.asarray(g2.edge_attr), [6.0])
        with pytest.raises(ValueError):
            Graph.from_edges([(0, 1)]).map_vertices(lambda a: a)


class TestArbitraryVertexIds:
    """GraphX accepts arbitrary i64 vertex ids (and pays a routing table);
    Graph.from_edge_ids does the relabeling once at construction."""

    def test_pagerank_invariant_under_relabeling(self):
        from asyncframework_tpu.graph import Graph
        from asyncframework_tpu.graph.algorithms import pagerank

        # a small dense-id graph and the SAME graph under huge sparse ids
        src = np.asarray([0, 0, 1, 2, 3])
        dst = np.asarray([1, 2, 2, 3, 0])
        big = np.asarray(
            [10_000_000_007, 42, 9_876_543_210_123, 7, 2**40], np.int64
        )
        g_dense = Graph(src, dst)
        g_big = Graph.from_edge_ids(big[src], big[dst])
        pr_dense = np.asarray(pagerank(g_dense, num_iterations=30))
        pr_big = np.asarray(pagerank(g_big, num_iterations=30))
        # re-key both by original id and compare
        by_id_dense = {int(i): float(p) for i, p in
                       zip(g_dense.original_ids(), pr_dense)}
        by_id_big = {int(i): float(p) for i, p in
                     zip(g_big.original_ids(), pr_big)}
        assert set(by_id_big) == {int(big[i]) for i in range(4)}
        for i in range(4):
            assert by_id_big[int(big[i])] == pytest.approx(
                by_id_dense[i], rel=1e-5
            )

    def test_vertex_attrs_by_id(self):
        from asyncframework_tpu.graph import Graph

        g = Graph.from_edge_ids(
            np.asarray([100, 200], np.int64),
            np.asarray([200, 300], np.int64),
            vertex_attr_by_id={100: 1.0, 200: 2.0, 300: 3.0},
        )
        assert g.num_vertices == 3
        ids = list(g.original_ids())
        attrs = np.asarray(g.vertex_attr)
        assert {int(i): float(a) for i, a in zip(ids, attrs)} == {
            100: 1.0, 200: 2.0, 300: 3.0
        }

    def test_attr_only_id_becomes_isolated_vertex(self):
        from asyncframework_tpu.graph import Graph

        g = Graph.from_edge_ids(
            np.asarray([1]), np.asarray([2]),
            vertex_attr_by_id={1: 0.5, 2: 1.5, 9: 9.5},
        )
        assert g.num_vertices == 3  # vertex 9 kept as an isolate
        by_id = dict(zip(g.original_ids().tolist(),
                         np.asarray(g.vertex_attr).tolist()))
        assert by_id[9] == 9.5

    def test_views_preserve_original_ids(self):
        from asyncframework_tpu.graph import Graph

        g = Graph.from_edge_ids(
            np.asarray([100, 200], np.int64), np.asarray([200, 300], np.int64)
        )
        want = g.original_ids().tolist()
        assert g.reverse().original_ids().tolist() == want
        assert g.subgraph(
            edge_mask=np.asarray([True, False])
        ).original_ids().tolist() == want

    def test_missing_attr_id_rejected(self):
        from asyncframework_tpu.graph import Graph

        with pytest.raises(ValueError, match="missing ids"):
            Graph.from_edge_ids(
                np.asarray([1]), np.asarray([2]),
                vertex_attr_by_id={1: 0.0},
            )
