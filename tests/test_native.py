"""Native C++ component tests: libsvm parser, kvstore, hashing.

The native pieces mirror the reference's JNI substrate (SURVEY.md section
2.6): netlib BLAS -> XLA (tested elsewhere), leveldbjni -> kvstore.cc,
Hadoop-native text ingest -> libsvm_parser.cc, string_hash_code.c ->
string_hash_code.  Every native path has a pure-Python fallback speaking the
same format; these tests cross-check the two against each other.
"""

import numpy as np
import pytest

from asyncframework_tpu.data.libsvm import (
    _native_lib,
    load_libsvm,
    parse_libsvm_lines,
)
from asyncframework_tpu.native_build import ensure_built
from asyncframework_tpu.storage.kvstore import KVStore, string_hash_code

NATIVE_OK = ensure_built("kvstore") is not None and ensure_built(
    "libsvm_parser"
) is not None
needs_native = pytest.mark.skipif(not NATIVE_OK, reason="no C++ toolchain")


def write_libsvm(path, X, y):
    with open(path, "w") as f:
        for i in range(X.shape[0]):
            feats = " ".join(
                f"{j + 1}:{X[i, j]:.6g}" for j in range(X.shape[1]) if X[i, j] != 0
            )
            f.write(f"{y[i]:.6g} {feats}\n")


class TestLibsvmParser:
    @pytest.fixture()
    def dataset(self, tmp_path, rng):
        X = rng.normal(size=(64, 12)).astype(np.float32)
        X[rng.random(size=X.shape) < 0.5] = 0.0  # sparsity
        y = rng.normal(size=(64,)).astype(np.float32)
        p = tmp_path / "data.libsvm"
        write_libsvm(p, X, y)
        return p, X, y

    def test_python_parser_round_trip(self, dataset):
        p, X, y = dataset
        with open(p) as f:
            X2, y2 = parse_libsvm_lines(f, num_features=12)
        np.testing.assert_allclose(X2, X, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(y2, y, rtol=1e-4, atol=1e-5)

    @needs_native
    def test_native_matches_python(self, dataset):
        p, X, y = dataset
        assert _native_lib() is not None
        Xn, yn = load_libsvm(str(p), num_features=12, use_native=True)
        Xp, yp = load_libsvm(str(p), num_features=12, use_native=False)
        np.testing.assert_allclose(Xn, Xp, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(yn, yp, rtol=1e-5, atol=1e-6)

    @needs_native
    def test_native_handles_comments_blanks_exponents(self, tmp_path):
        p = tmp_path / "messy.libsvm"
        p.write_text(
            "# header comment\n"
            "\n"
            "1.5 1:2.5e-3 3:-4E2\n"
            "   \n"
            "-2 2:0.125\n"
        )
        X, y = load_libsvm(str(p), num_features=3, use_native=True)
        assert X.shape == (2, 3)
        np.testing.assert_allclose(y, [1.5, -2.0])
        np.testing.assert_allclose(X[0], [2.5e-3, 0.0, -400.0], rtol=1e-6)
        np.testing.assert_allclose(X[1], [0.0, 0.125, 0.0])

    @needs_native
    def test_native_rejects_out_of_range_index(self, tmp_path):
        p = tmp_path / "bad.libsvm"
        p.write_text("1 5:1.0\n")
        with pytest.raises(ValueError, match="-3"):
            load_libsvm(str(p), num_features=3, use_native=True)

    def test_native_huge_index_rejected_not_ub(self, tmp_path):
        # a 30-digit index would overflow a naive accumulator (UB); the
        # parser clamps it and reports out-of-range like any bad index
        p = tmp_path / "huge.libsvm"
        p.write_text("1 123456789012345678901234567890:1.0\n")
        with pytest.raises(ValueError, match="-3"):
            load_libsvm(str(p), num_features=3, use_native=True)


class TestKVStore:
    @pytest.mark.parametrize(
        "backend", ["python", pytest.param("native", marks=needs_native)]
    )
    def test_basic_ops_and_reopen(self, tmp_path, backend):
        path = tmp_path / "app.kv"
        with KVStore(path, backend=backend) as kv:
            assert kv.backend == backend
            kv.put("a", b"1")
            kv.put(b"b", "two")
            kv.put("a", b"updated")
            kv.delete("missing")
            assert kv.get("a") == b"updated"
            assert kv.get("b") == b"two"
            assert len(kv) == 2
            kv.delete("b")
            assert "b" not in kv and len(kv) == 1
        # reopen: log replay reconstructs the live set
        with KVStore(path, backend=backend) as kv:
            assert kv.get("a") == b"updated"
            assert len(kv) == 1

    @pytest.mark.parametrize(
        "backend", ["python", pytest.param("native", marks=needs_native)]
    )
    def test_compact_drops_dead_records(self, tmp_path, backend):
        path = tmp_path / "app.kv"
        with KVStore(path, backend=backend) as kv:
            for i in range(50):
                kv.put(f"k{i}", b"x" * 100)
            for i in range(40):
                kv.delete(f"k{i}")
            before = path.stat().st_size
            kv.compact()
            after = path.stat().st_size
            assert after < before
            assert len(kv) == 10
        with KVStore(path, backend=backend) as kv:
            assert sorted(kv.keys()) == sorted(
                f"k{i}".encode() for i in range(40, 50)
            )

    @needs_native
    @pytest.mark.parametrize("writer,reader", [("python", "native"),
                                               ("native", "python")])
    def test_cross_backend_interop(self, tmp_path, writer, reader):
        """Both implementations speak the identical AKV1 format."""
        path = tmp_path / "x.kv"
        with KVStore(path, backend=writer) as kv:
            kv.put("shared", b"payload")
            kv.put_obj("obj", {"a": [1, 2], "b": "s"})
            kv.put("gone", b"bye")
            kv.delete("gone")
        with KVStore(path, backend=reader) as kv:
            assert kv.backend == reader
            assert kv.get("shared") == b"payload"
            assert kv.get_obj("obj") == {"a": [1, 2], "b": "s"}
            assert "gone" not in kv

    @pytest.mark.parametrize(
        "backend", ["python", pytest.param("native", marks=needs_native)]
    )
    def test_torn_final_record_truncated(self, tmp_path, backend):
        """A crash-torn tail is cut off on open, so post-crash appends land
        on a record boundary and later reopens parse cleanly."""
        path = tmp_path / "torn.kv"
        with KVStore(path, backend=backend) as kv:
            kv.put("good", b"v")
        with open(path, "ab") as f:
            f.write(b"\x05\x00\x00\x00\x10\x00\x00\x00ab")  # truncated record
        with KVStore(path, backend=backend) as kv:
            assert kv.get("good") == b"v"
            assert len(kv) == 1
            kv.put("after", b"crash")
        # the other implementation must also read the repaired log
        other = "python" if backend == "native" else "native"
        if other == "native" and not NATIVE_OK:
            pytest.skip("cross-reader needs the native backend")
        with KVStore(path, backend=other) as kv:
            assert kv.get("good") == b"v"
            assert kv.get("after") == b"crash"
            assert len(kv) == 2

    @pytest.mark.parametrize(
        "backend", ["python", pytest.param("native", marks=needs_native)]
    )
    def test_short_file_reopens_as_fresh(self, tmp_path, backend):
        """A crash between creation and the magic write leaves a <4-byte
        file; later opens must recover (treat as fresh), not fail forever."""
        path = tmp_path / "short.kv"
        path.write_bytes(b"AK")  # torn magic
        with KVStore(path, backend=backend) as kv:
            assert len(kv) == 0
            kv.put("k", b"v")
        with KVStore(path, backend=backend) as kv:
            assert kv.get("k") == b"v"


class TestStringHashCode:
    def test_matches_java_semantics(self):
        # java "abc".hashCode() == 96354; "".hashCode() == 0
        assert string_hash_code("abc") == 96354
        assert string_hash_code("") == 0
        # int32 wraparound (java allows negatives)
        assert string_hash_code("asyncframework-tpu" * 10) < 2**31

    @needs_native
    def test_native_matches_python(self):
        import ctypes

        from asyncframework_tpu.storage.kvstore import _native_lib as kvlib

        lib = kvlib()
        for s in ("", "abc", "framework", "x" * 1000, "\xe9\xa0"):
            b = s.encode()
            assert lib.string_hash_code(b, len(b)) == string_hash_code(s)
