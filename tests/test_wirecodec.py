"""Wire-codec layer (ISSUE 12): quantized gradients + compressed deltas.

The correctness spine:

- error-feedback quantization NEVER diverges: after any prefix of
  pushes, (true gradient sum) - (applied dequantized sum) equals
  exactly the CURRENT residual, and the residual is bounded by ONE
  step's quantization error -- the property tests sweep random
  sequences including NaN/inf/-0 bit patterns (the test_dataplane
  XOR-delta discipline);
- anything the codec cannot encode safely ships RAW (non-finite
  gradients, fp16 overflow): degrade to exact, never to poisoned;
- snapshot-delta compression is LOSSLESS and tag-reversible -- the
  decompressed bytes are the original payload bit-for-bit, so CRC
  gating is untouched;
- codec off is BYTE-IDENTICAL to the knob absent, asserted via per-op
  frame-byte totals under a fixed seed (the repo-wide legacy-wire
  discipline).
"""

import numpy as np
import pytest

from asyncframework_tpu.conf import set_global_conf
from asyncframework_tpu.metrics import reset_totals
from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.net import wirecodec as wc
from asyncframework_tpu.net import wiredelta
from asyncframework_tpu.net.retry import reset_breakers

pytestmark = pytest.mark.relay


@pytest.fixture(autouse=True)
def _clean_state():
    reset_totals()
    reset_breakers()
    yield
    reset_totals()
    reset_breakers()
    set_global_conf(None)


# ------------------------------------------------------------- gradient path
class TestGradCodec:
    @pytest.mark.parametrize("codec", [wc.FP16, wc.INT8])
    def test_error_feedback_never_diverges(self, codec):
        """THE invariant: sum(true) - sum(applied) == current residual
        exactly (in exact arithmetic; float64 accounting below), and
        the residual is bounded by one step's quantization error -- so
        the model deviation is bounded for ANY sequence length."""
        rng = np.random.default_rng(7)
        d = 257  # odd on purpose
        err = None
        true_sum = np.zeros(d, np.float64)
        applied_sum = np.zeros(d, np.float64)
        for t in range(200):
            scale = 10.0 ** rng.integers(-4, 3)
            g = (scale * rng.normal(size=d)).astype(np.float32)
            out = wc.encode_grad(g, codec, err)
            assert out is not None
            hdr, payload, err = out
            applied = wc.decode_grad(hdr, payload, d)
            true_sum += g.astype(np.float64)
            applied_sum += applied.astype(np.float64)
            # residual identity (float64 slack for the accounting only)
            drift = np.abs((true_sum - applied_sum) - err)
            assert drift.max() < 1e-3 * max(1.0, np.abs(err).max() + 1), t
            # residual bound: one step's quantization error of x=g+err
            x_absmax = float(np.abs(applied + err).max()) + float(
                np.abs(err).max())
            bound = wc.grad_error_bound(codec, x_absmax)
            assert np.abs(err).max() <= bound * 1.5 + 1e-6, t

    @pytest.mark.parametrize("codec", [wc.FP16, wc.INT8])
    def test_server_applies_exactly_what_client_accounted(self, codec):
        """decode_grad(payload) must equal the client's ``applied``
        (x - new_err) bit-for-bit -- the server and the accumulator
        agree on what landed, or the bound above is fiction."""
        rng = np.random.default_rng(3)
        d = 64
        err = np.zeros(d, np.float32)
        g = rng.normal(size=d).astype(np.float32)
        hdr, payload, new_err = wc.encode_grad(g, codec, err)
        applied = wc.decode_grad(hdr, payload, d)
        np.testing.assert_array_equal(applied, (g + err) - new_err)

    @pytest.mark.parametrize("bad", [np.nan, np.inf, -np.inf])
    def test_nonfinite_ships_raw(self, bad):
        g = np.ones(16, np.float32)
        g[3] = bad
        err = np.full(16, 0.25, np.float32)
        assert wc.encode_grad(g, wc.INT8, err) is None
        assert wc.encode_grad(g, wc.FP16, err) is None
        # the residual was NOT consumed: it rides to the next push
        np.testing.assert_array_equal(err, np.full(16, 0.25, np.float32))

    def test_negative_zero_and_zero_grad(self):
        g = np.zeros(8, np.float32)
        g[1] = -0.0
        for codec in (wc.FP16, wc.INT8):
            hdr, payload, err = wc.encode_grad(g, codec, None)
            applied = wc.decode_grad(hdr, payload, 8)
            assert np.all(applied == 0.0)
            assert np.abs(err).max() == 0.0

    def test_fp16_overflow_ships_raw(self):
        g = np.ones(8, np.float32)
        g[0] = 1e5  # fp16 would quantize to inf -> poisoned residual
        assert wc.encode_grad(g, wc.FP16, None) is None
        # int8 handles any finite magnitude (per-push scale)
        assert wc.encode_grad(g, wc.INT8, None) is not None

    def test_off_and_unknown_codec(self):
        g = np.ones(4, np.float32)
        assert wc.encode_grad(g, wc.OFF, None) is None
        with pytest.raises(ValueError, match="unknown"):
            wc.encode_grad(g, "zstd", None)

    def test_decode_rejects_malformed(self):
        with pytest.raises(ValueError):
            wc.decode_grad({"gq": wc.FP16}, b"\x00" * 7, 4)
        with pytest.raises(ValueError):
            wc.decode_grad({"gq": wc.INT8, "gs": 1.0}, b"\x00" * 3, 4)
        with pytest.raises(ValueError):
            wc.decode_grad({"gq": "nope"}, b"\x00" * 16, 4)
        # review fix: a missing/garbage int8 scale must raise (answer
        # ERR), never silently apply an all-zero/poisoned gradient
        with pytest.raises(ValueError, match="scale"):
            wc.decode_grad({"gq": wc.INT8}, b"\x01" * 4, 4)
        with pytest.raises(ValueError, match="scale"):
            wc.decode_grad({"gq": wc.INT8, "gs": float("nan")},
                           b"\x01" * 4, 4)
        with pytest.raises(ValueError, match="scale"):
            wc.decode_grad({"gq": wc.INT8, "gs": -1.0}, b"\x01" * 4, 4)


# ------------------------------------------------------------- snapshot path
def _xdelta_payload(rng, d, nnz):
    idx = np.sort(rng.choice(d, size=nnz, replace=False)).astype(np.uint32)
    xor = rng.integers(0, 2 ** 32, size=nnz, dtype=np.uint64).astype(
        np.uint32)
    return idx.tobytes() + xor.tobytes()


class TestSnapshotCodec:
    def test_roundtrip_property_all_tags(self):
        """Random payloads through every tag path reconstruct
        bit-for-bit, including NaN/inf/-0 float bit patterns."""
        rng = np.random.default_rng(11)
        for trial in range(20):
            d = int(rng.integers(32, 1024))
            w = rng.normal(size=d).astype(np.float32)
            # plant the special bit patterns the XOR-delta suite uses
            w[rng.integers(0, d)] = np.nan
            w[rng.integers(0, d)] = np.inf
            w[rng.integers(0, d)] = -0.0
            cases = [
                ("full", w.tobytes(), 0),
                ("xfull", w.view(np.uint32).tobytes(), 0),
            ]
            nnz = max(1, d // 8)
            cases.append(("xdelta", _xdelta_payload(rng, d, nnz), nnz))
            for wenc, payload, nnz_ in cases:
                hdr, wire = wc.compress_model_part(wenc, payload, nnz_)
                full_hdr = dict(hdr)
                if nnz_:
                    full_hdr["nnz"] = nnz_
                out = wc.decompress_model_part(full_hdr, wire)
                assert out == payload, (trial, wenc, hdr)

    def test_structured_delta_compresses_2x(self):
        """The acceptance regime: a late-training dense update (small
        relative change per coordinate) as an XFULL payload, and a
        sparse update as an XDELTA payload, both cut >= 2x."""
        rng = np.random.default_rng(0)
        d = 4096
        w = rng.normal(size=d).astype(np.float32)
        w2 = (w * (1 + 1e-4 * rng.normal(size=d))).astype(np.float32)
        xfull = wiredelta.encode_xfull(w2, w)
        hdr, wire = wc.compress_model_part("xfull", xfull, 0)
        assert hdr.get("cz"), "xfull delta did not compress at all"
        assert len(xfull) >= 2 * len(wire), (len(xfull), len(wire))
        # sparse: idx half delta-encodes, xor half shuffles
        w3 = w.copy()
        idx = np.sort(rng.choice(d, size=d // 20, replace=False))
        w3[idx] = (w3[idx] * (1 + 1e-4 * rng.normal(size=idx.size))
                   ).astype(np.float32)
        wenc, payload, nnz = wiredelta.encode(w3, w)
        assert wenc == wiredelta.XDELTA
        hdr, wire = wc.compress_model_part(wenc, payload, nnz)
        assert hdr.get("cz") == "zd"
        assert len(payload) >= 2 * len(wire), (len(payload), len(wire))

    def test_incompressible_ships_raw(self):
        rng = np.random.default_rng(5)
        payload = rng.integers(0, 256, size=4096, dtype=np.uint8).tobytes()
        hdr, wire = wc.compress_model_part("full", payload, 0)
        assert hdr == {} and wire == payload
        assert wc.decompress_model_part({}, wire) == payload

    def test_small_payload_unchanged(self):
        hdr, wire = wc.compress_model_part("full", b"abcd", 0)
        assert hdr == {} and wire == b"abcd"

    def test_corrupt_payload_raises(self):
        payload = np.arange(256, dtype=np.uint32).tobytes()
        hdr, wire = wc.compress_model_part("xfull", payload, 0)
        assert hdr.get("cz") == "zs"
        with pytest.raises(ValueError):
            wc.decompress_model_part(hdr, wire[:-3])
        with pytest.raises(ValueError):
            wc.decompress_model_part({**hdr, "ulen": 17}, wire)
        with pytest.raises(ValueError):
            wc.decompress_model_part({**hdr, "cz": "??"}, wire)

    def test_xfull_decode_is_exact_and_crc_gated(self):
        rng = np.random.default_rng(9)
        d = 128
        basis = rng.normal(size=d).astype(np.float32)
        cur = (basis * 1.0001).astype(np.float32)
        payload = wiredelta.encode_xfull(cur, basis)
        out = wiredelta.decode(wiredelta.XFULL, payload, 0, basis,
                               wiredelta.crc(cur), None)
        assert out is not None and out.tobytes() == cur.tobytes()
        # wrong CRC -> None (fallback contract)
        assert wiredelta.decode(wiredelta.XFULL, payload, 0, basis,
                                12345, None) is None
        # wrong basis size -> None
        assert wiredelta.decode(wiredelta.XFULL, payload, 0,
                                basis[:-1], wiredelta.crc(cur),
                                None) is None


# ----------------------------------------------------------------- wire path
def make_cfg(**kw):
    from asyncframework_tpu.solvers import SolverConfig

    defaults = dict(
        num_workers=2, num_iterations=400, gamma=0.5, taw=2 ** 31 - 1,
        batch_rate=0.3, bucket_ratio=0.0, printer_freq=100, seed=42,
        calibration_iters=4, run_timeout_s=60.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


def _drive_pushes(ps_port, codec, n_pushes, d, scale=0.05):
    """A deterministic pull+push sequence through one client; returns
    the client (for its counters)."""
    from asyncframework_tpu.parallel.ps_dcn import PSClient

    cl = PSClient("127.0.0.1", ps_port, pull_mode="full",
                  push_codec=codec)
    rng = np.random.default_rng(123)
    for _ in range(n_pushes):
        ts, _w, _avg, _cal = cl.pull(0)
        g = (scale * rng.normal(size=d)).astype(np.float32)
        cl.push(0, ts, g)
    return cl


class TestCodecWire:
    def _final_model(self, devices, codec, d=64, n_pushes=30):
        import jax

        from asyncframework_tpu.parallel import ps_dcn

        reset_totals()
        ps = ps_dcn.ParameterServer(make_cfg(), d, 256,
                                    device=devices[0], port=0).start()
        try:
            _drive_pushes(ps.port, codec, n_pushes, d)
            w = np.array(ps._model_snap().w_host, np.float32)
            push_bytes = ps.push_bytes
        finally:
            ps.stop()
        return w, push_bytes

    def test_codec_off_matches_knob_absent_byte_identical(self, devices8):
        """'off' must be the legacy wire, asserted the repo way: per-op
        frame-byte totals identical under a fixed seed."""
        import jax

        from asyncframework_tpu.parallel import ps_dcn

        totals = {}
        for label, codec in (("absent", None), ("off", "off")):
            reset_totals()
            ps = ps_dcn.ParameterServer(make_cfg(), 32, 256,
                                        device=devices8[0],
                                        port=0).start()
            try:
                _drive_pushes(ps.port, codec, 12, 32)
            finally:
                ps.stop()
            totals[label] = {
                op: dict(v) for op, v in _frame.bytes_totals().items()
                if op in ("PUSH", "MODEL", "PULL", "ACK")
            }
        assert totals["absent"] == totals["off"]

    def test_int8_quarters_push_bytes_and_bounded_deviation(self,
                                                           devices8):
        d = 64
        w_off, bytes_off = self._final_model(devices8, "off", d=d)
        w_q, bytes_q = self._final_model(devices8, "int8", d=d)
        # dense f32 payload (d*4) -> int8 payload (d): ~4x fewer
        # gradient bytes on the wire
        assert bytes_q < 0.35 * bytes_off, (bytes_q, bytes_off)
        # error feedback keeps the trajectory deviation bounded: the
        # applied-sum identity means the models differ by the step
        # scale times ONE residual, not by anything cumulative
        denom = np.abs(w_off).max() + 1e-9
        assert np.abs(w_q - w_off).max() / denom < 0.05, (
            np.abs(w_q - w_off).max(), denom)

    def test_fp16_halves_push_bytes(self, devices8):
        d = 64
        _w_off, bytes_off = self._final_model(devices8, "off", d=d)
        w_q, bytes_q = self._final_model(devices8, "fp16", d=d)
        assert bytes_q < 0.6 * bytes_off, (bytes_q, bytes_off)
        assert np.isfinite(w_q).all()

    def test_push_codec_resolves_from_conf(self, devices8):
        """SolverConfig/conf plumbing: a client built with no explicit
        codec reads async.codec.push."""
        from asyncframework_tpu.conf import AsyncConf, set_global_conf
        from asyncframework_tpu.parallel import ps_dcn

        ps = ps_dcn.ParameterServer(make_cfg(), 16, 256,
                                    device=devices8[0], port=0).start()
        try:
            set_global_conf(AsyncConf({"async.codec.push": "int8"}))
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            assert cl.push_codec == "int8"
            ts, _w, _a, _c = cl.pull(0)
            cl.push(0, ts, np.ones(16, np.float32))
            assert wc.codec_totals().get("grad_enc_int8", 0) == 1
        finally:
            set_global_conf(None)
            ps.stop()
