"""Dynamic executor allocation (ExecutorAllocationManager.scala:82 parity):
backlogged slots gain sibling executors, idle siblings retire, and a solver
run with dynamic_allocation on completes and reports scale events.
"""

import threading
import time

import numpy as np
import pytest

from asyncframework_tpu.engine.allocation import ExecutorAllocationManager
from asyncframework_tpu.engine.scheduler import ASYNC, JobScheduler
from asyncframework_tpu.utils.clock import ManualClock


def _slow_task(gate: threading.Event):
    def fn():
        gate.wait(5.0)
        return 1

    return fn


class TestAllocationPolicy:
    def test_scale_up_on_sustained_backlog_then_down_when_idle(self):
        sched = JobScheduler(num_workers=2)
        sched.set_mode(ASYNC)
        clock = ManualClock()
        mgr = ExecutorAllocationManager(
            sched, max_extra_per_slot=1, backlog_threshold=2,
            sustained_ticks=2, idle_timeout_s=0.5, clock=clock,
        )
        gate = threading.Event()
        try:
            # three queued jobs on worker 0: one running + two backlogged
            for _ in range(3):
                sched.run_job({0: _slow_task(gate)}, lambda *a: None)
            assert sched.pool.slot_backlog(0) >= 2
            assert mgr.check_once() == []       # streak 1: not yet
            events = mgr.check_once()           # streak 2: scale up
            assert events == [(0, 1)]
            assert sched.pool.sibling_count(0) == 1
            # capped at max_extra_per_slot
            assert mgr.check_once() == []
            # release tasks; queue drains through primary + sibling
            gate.set()
            deadline = time.monotonic() + 5
            while sched.pool.slot_backlog(0) > 0:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            # idle, but not past the timeout yet
            assert mgr.check_once() == []
            clock.advance(600)
            events = mgr.check_once()
            assert events == [(0, -1)]
            assert sched.pool.sibling_count(0) == 0
            assert mgr.counts() == (1, 1)
        finally:
            gate.set()
            sched.shutdown()

    def test_no_scale_without_backlog(self):
        sched = JobScheduler(num_workers=2)
        mgr = ExecutorAllocationManager(sched, backlog_threshold=1)
        try:
            assert mgr.check_once() == []
            assert mgr.counts() == (0, 0)
        finally:
            sched.shutdown()

    def test_sibling_drains_backlog_faster_than_primary_alone(self):
        """The scheduler actually routes to the sibling: with one slot and
        a sibling added, two sleeping tasks run CONCURRENTLY."""
        sched = JobScheduler(num_workers=1)
        sched.set_mode(ASYNC)
        try:
            sched.pool.add_sibling(0)
            # burn the always-blocking first iteration (DAGScheduler
            # first_iter parity) so both measured jobs dispatch async
            sched.run_job({0: (lambda: 0)}, lambda *a: None)
            t0 = time.monotonic()
            waiters = [
                sched.run_job(
                    {0: (lambda: time.sleep(0.3) or 1)}, lambda *a: None
                )
                for _ in range(2)
            ]
            for w in waiters:
                w.await_result(timeout=5)
            elapsed = time.monotonic() - t0
            assert elapsed < 0.55, (
                f"two 0.3s tasks took {elapsed:.2f}s -- not concurrent, "
                "sibling not receiving work"
            )
        finally:
            sched.shutdown()

    def test_validation(self):
        sched = JobScheduler(num_workers=1)
        try:
            with pytest.raises(ValueError):
                ExecutorAllocationManager(sched, backlog_threshold=0)
        finally:
            sched.shutdown()


class TestAllocationInSolver:
    def test_async_run_with_dynamic_allocation(self, devices8, tiny_problem):
        from asyncframework_tpu.solvers import ASGD, SolverConfig

        X, y, _ = tiny_problem
        cfg = SolverConfig(
            num_workers=8, num_iterations=200, gamma=1.0,
            taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5,
            printer_freq=50, coeff=0.0, seed=42, calibration_iters=10,
            run_timeout_s=120.0, dynamic_allocation=True,
            allocation_backlog_threshold=1, allocation_idle_timeout_s=0.05,
        )
        res = ASGD(X, y, cfg, devices=devices8).run()
        assert res.accepted == 200
        assert "executors_added" in res.extras
        assert np.all(np.isfinite(res.final_w))


class TestSiblingFailureDetection:
    def test_dead_sibling_dropped_without_slot_escalation(self):
        from asyncframework_tpu.engine.heartbeat import HeartbeatMonitor

        sched = JobScheduler(num_workers=2)
        try:
            sib = sched.pool.add_sibling(1)
            lost, sib_events = [], []
            mon = HeartbeatMonitor(
                sched.pool, on_executor_lost=lost.append,
                timeout_ms=1000.0,
                on_sibling_lost=lambda w, q, r: sib_events.append(w),
            )
            assert mon.check_once() == []  # healthy
            sib.kill()  # simulated sibling death (not graceful)
            flagged = mon.check_once()
            # with a resubmission handler wired, sibling loss does NOT
            # escalate: the healthy primary's attempts must not inflate
            assert flagged == []
            assert sib_events == [1]
            assert sched.pool.sibling_count(1) == 0  # dropped from the pool
            assert lost == []
            # scan is idempotent once dropped (primary is healthy)
            assert mon.check_once() == []
        finally:
            sched.shutdown()

    def test_graceful_sibling_retirement_not_flagged(self):
        from asyncframework_tpu.engine.heartbeat import HeartbeatMonitor

        sched = JobScheduler(num_workers=1)
        try:
            sched.pool.add_sibling(0)
            mon = HeartbeatMonitor(
                sched.pool, on_executor_lost=lambda w: (_ for _ in ()).throw(
                    AssertionError("graceful retirement flagged as loss")
                ),
                timeout_ms=1000.0,
            )
            assert sched.pool.remove_idle_sibling(0)
            assert mon.check_once() == []
        finally:
            sched.shutdown()

    def test_hung_sibling_does_not_escalate_to_slot_loss(self):
        """A sibling stuck in a task must only resubmit ITS OWN work; the
        healthy primary's in-flight tasks keep their attempt counts."""
        from asyncframework_tpu.engine.heartbeat import HeartbeatMonitor
        from asyncframework_tpu.utils.clock import ManualClock

        clock = ManualClock()
        sched = JobScheduler(num_workers=1, clock=clock)
        sched.set_mode(ASYNC)
        lost, sib_events = [], []

        def sibling_lost(w, q, r):
            sib_events.append((w, q, r))
            sched.on_sibling_lost(w, q, r)  # as FaultTolerantRun wires it

        mon = HeartbeatMonitor(
            sched.pool, on_executor_lost=lost.append,
            timeout_ms=10_000.0, task_timeout_ms=500.0, clock=clock,
            on_sibling_lost=sibling_lost,
        )
        sib = sched.pool.add_sibling(0)
        gate_p = threading.Event()
        gate_s = threading.Event()
        try:
            # burn first-iter blocking with a trivial job
            sched.run_job({0: (lambda: 0)}, lambda *a: None)
            # primary takes job1 (released early -> healthy); the sibling
            # takes job2 and stays stuck past the hang threshold
            w1 = sched.run_job({0: _slow_task(gate_p)}, lambda *a: None)
            w2 = sched.run_job({0: _slow_task(gate_s)}, lambda *a: None)
            deadline = time.monotonic() + 5
            while not (sched.pool.executors[0].busy and sib.busy):
                assert time.monotonic() < deadline
                time.sleep(0.01)
            gate_p.set()
            w1.await_result(timeout=5)  # primary healthy again
            clock.advance(1_000)
            mon.check_once()
            # ONLY the sibling path fired; no slot escalation
            assert len(sib_events) == 1
            wid, queued, running = sib_events[0]
            assert wid == 0 and queued == [] and running is not None
            assert lost == []
            gate_s.set()
            w2.await_result(timeout=5)  # completes via the resubmitted copy
        finally:
            gate_p.set()
            gate_s.set()
            sched.shutdown()

    def test_sibling_loss_without_handler_escalates_to_slot(self):
        """No resubmission handler wired: sibling loss must fall back to
        the slot-loss path so the tasks are not silently dropped."""
        from asyncframework_tpu.engine.heartbeat import HeartbeatMonitor

        sched = JobScheduler(num_workers=1)
        try:
            sib = sched.pool.add_sibling(0)
            lost = []
            mon = HeartbeatMonitor(
                sched.pool, on_executor_lost=lost.append,
                timeout_ms=1000.0,
            )
            sib.kill()
            assert mon.check_once() == [0]
            assert lost == [0]
            assert sched.pool.sibling_count(0) == 0
        finally:
            sched.shutdown()

    def test_sibling_loss_clears_inflight_registry(self):
        """Relaunched sibling tasks must not leave stale _inflight entries
        (they would look forever-running to the speculation monitor)."""
        from asyncframework_tpu.engine.heartbeat import HeartbeatMonitor
        from asyncframework_tpu.utils.clock import ManualClock

        clock = ManualClock()
        sched = JobScheduler(num_workers=1, clock=clock)
        sched.set_mode(ASYNC)
        mon = HeartbeatMonitor(
            sched.pool, on_executor_lost=lambda w: None,
            timeout_ms=10_000.0, task_timeout_ms=500.0, clock=clock,
            on_sibling_lost=sched.on_sibling_lost,
        )
        sib = sched.pool.add_sibling(0)
        gate_p = threading.Event()
        gate_s = threading.Event()
        try:
            sched.run_job({0: (lambda: 0)}, lambda *a: None)
            w1 = sched.run_job({0: _slow_task(gate_p)}, lambda *a: None)
            w2 = sched.run_job({0: _slow_task(gate_s)}, lambda *a: None)
            deadline = time.monotonic() + 5
            while not (sched.pool.executors[0].busy and sib.busy):
                assert time.monotonic() < deadline
                time.sleep(0.01)
            gate_p.set()
            w1.await_result(timeout=5)  # primary healthy before the scan
            clock.advance(1_000)
            mon.check_once()
            gate_s.set()
            w2.await_result(timeout=5)
            deadline = time.monotonic() + 5
            while any(sched._inflight.values()):
                assert time.monotonic() < deadline, (
                    f"stale inflight: {sched._inflight}"
                )
                time.sleep(0.01)
        finally:
            gate_p.set()
            gate_s.set()
            sched.shutdown()
