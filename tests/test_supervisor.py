"""Elastic training plane (ISSUE 2): worker/PS crash survival mid-run.

The supervisor (parallel/supervisor.py) closes the loop between the
robustness primitives (heartbeats, shard re-homing, checkpoints, session
dedup) and the multi-process DCN training path: worker death -> shard
adoption by a survivor (full data coverage at degraded cohort size),
worker rejoin -> surrogate release, PS kill -9 -> restart-from-checkpoint
with exactly-once PUSH semantics ACROSS the restart, and a progress-aware
``wait_done`` that names silent workers instead of hanging.

Layers here mirror the repo's testing doctrine: pure-logic supervisor
tests on a ManualClock; in-process PS + client-thread "processes"
(deterministic interleavings); and one real-OS-process leg where a DCN
worker is SIGKILLed mid-ASGD-run (the acceptance scenario).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.parallel.supervisor import (
    DEAD,
    ElasticSupervisor,
    recovery_totals,
)
from asyncframework_tpu.solvers import SolverConfig
from asyncframework_tpu.utils.clock import ManualClock

CHILD = Path(__file__).parent / "ps_dcn_child.py"


def make_cfg(**kw):
    defaults = dict(
        num_workers=4, num_iterations=200, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.5, printer_freq=50, seed=42,
        calibration_iters=8, run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


class TestSupervisorLogic:
    """Pure membership logic on a ManualClock -- no sockets, no devices."""

    def _sup(self, nw=4, dead_after_s=1.0, boot_grace_s=5.0):
        clock = ManualClock()
        sup = ElasticSupervisor(nw, dead_after_s=dead_after_s,
                                check_interval_s=0.05,
                                boot_grace_s=boot_grace_s, clock=clock)
        return sup, clock

    def test_silence_declares_dead_and_plans_adoption(self):
        sup, clock = self._sup()
        sup.register("A", [0, 1])
        sup.register("B", [2, 3])
        for w in range(4):
            sup.touch(w, "A" if w < 2 else "B")
        clock.advance(600)
        for w in (0, 1):
            sup.touch(w, "A")   # A stays chatty; B goes silent
        clock.advance(600)      # B's wids now silent for 1.2s > 1.0s
        for w in (0, 1):
            sup.touch(w, "A")
        dead = sup.check_once()
        assert sorted(dead) == [2, 3]
        assert sup.live_worker_count() == 2
        # both orphans re-homed onto the surviving process
        assert sorted(sup.orders_for("A")) == [2, 3]
        assert sup.counters()["workers_lost"] == 2
        assert sup.counters()["shards_adopted"] == 2
        # deposed B may not push its old shards anymore
        assert not sup.owns("B", 2)
        assert sup.owns("A", 2)

    def test_adoption_order_redelivered_until_acked(self):
        sup, clock = self._sup()
        sup.register("A", [0, 1])
        sup.register("B", [2, 3])
        clock.advance(1200)
        sup.touch(0, "A")
        sup.touch(1, "A")
        sup.check_once()
        assert sorted(sup.orders_for("A")) == [2, 3]
        assert sorted(sup.orders_for("A")) == [2, 3]  # still pending
        sup.touch(2, "A")
        sup.ack_adoption("A", 2)   # adopter's first pull for the orphan
        assert sup.orders_for("A") == [3]

    def test_rejoin_takes_shards_back_and_releases_surrogate(self):
        sup, clock = self._sup()
        sup.register("A", [0, 1])
        sup.register("B", [2, 3])
        clock.advance(1200)
        sup.touch(0, "A")
        sup.touch(1, "A")
        sup.check_once()           # B dead, A adopts 2,3
        sup.touch(2, "A")
        sup.ack_adoption("A", 2)
        # B's replacement process comes back with a fresh token
        sup.register("B2", [2, 3])
        assert sup.owns("B2", 2) and sup.owns("B2", 3)
        assert not sup.owns("A", 2)      # surrogate deposed
        assert sup.orders_for("A") == []  # pending adoption revoked
        c = sup.counters()
        assert c["rejoins"] >= 2 and c["releases"] >= 1
        assert sup.live_worker_count() == 4

    def test_unclaimed_shards_wait_for_boot_grace(self):
        sup, clock = self._sup(boot_grace_s=5.0)
        sup.register("A", [0, 1])
        sup.touch(0, "A")
        clock.advance(2000)        # past dead_after, inside boot grace
        sup.touch(0, "A")
        sup.touch(1, "A")
        assert sup.check_once() == []     # 2,3 never claimed: not dead yet
        clock.advance(4000)
        sup.touch(0, "A")
        sup.touch(1, "A")
        dead = sup.check_once()           # grace over: hand them out
        assert sorted(dead) == [2, 3]
        assert sorted(sup.orders_for("A")) == [2, 3]

    def test_process_exit_detected_immediately_via_pid(self):
        import socket as socket_mod

        proc = subprocess.Popen([sys.executable, "-c", "pass"])
        proc.wait()
        sup, clock = self._sup(dead_after_s=60.0)
        # pid probes only apply to peers that HELLO'd from THIS host
        sup.register("gone", [2, 3], pid=proc.pid,
                     host=socket_mod.gethostname())
        sup.register("A", [0, 1])
        sup.touch(0, "A")
        clock.advance(100)        # far inside the silence window
        dead = sup.check_once()   # ...but the pid is gone: dead NOW
        assert sorted(dead) == [2, 3]

    def test_restarted_ps_rebuilds_membership_from_traffic(self):
        # a fresh supervisor (PS restarted) knows nobody; first contact
        # claims the wid instead of bouncing the worker
        sup, _clock = self._sup()
        assert sup.owns("A", 0)
        sup.touch(0, "A")
        assert sup.owns("A", 0) and not sup.owns("B", 0)

    def test_unacked_adoption_order_expires_and_replans(self):
        """An adopter that never acts on its order (failing shard_factory,
        or a classic client that ignores orders) must not strand the
        orphan: past the expiry the orphan re-enters the plan pool."""
        sup, clock = self._sup(dead_after_s=1.0)
        sup.register("A", [0, 1])
        sup.register("B", [2])
        sup.register("C", [3])
        clock.advance(1200)
        sup.touch(0, "A")
        sup.touch(1, "A")
        sup.touch(3, "C")
        sup.check_once()                     # wid 2 dead, order issued
        first_adopter = next(p for p in ("A", "C")
                             if sup.orders_for(p) == [2])
        # the adopter keeps pulling but never acks wid 2; past the
        # expiry (2x dead_after) the order is revoked and re-planned
        # (least-loaded-first may legitimately pick the same proc; the
        # point is the order stays LIVE, not pinned to a stale issue)
        clock.advance(2500)
        sup.touch(0, "A")
        sup.touch(1, "A")
        sup.touch(3, "C")
        before = sup.counters()["shards_adopted"]
        sup.check_once()
        assert sup.counters()["shards_adopted"] == before + 1
        assert any(sup.orders_for(p) == [2] for p in ("A", "C"))
        # once SOME adopter finally picks it up, the order clears
        sup.touch(2, first_adopter if sup.owns(first_adopter, 2) else "C")
        adopter = next(p for p in ("A", "C") if sup.orders_for(p) == [2])
        sup.ack_adoption(adopter, 2)
        assert all(sup.orders_for(p) == [] for p in ("A", "C"))

    def test_dead_adopter_triggers_replan(self):
        sup, clock = self._sup()
        sup.register("A", [0, 1])
        sup.register("B", [2, 3])
        sup.register("C", [])       # idle spare process
        clock.advance(1200)
        sup.touch(0, "A")
        sup.touch(2, "C")           # C chats too (keeps itself live)
        sup.check_once()            # B dead; orphans planned somewhere
        # now A dies as well before picking anything up
        clock.advance(1200)
        sup.touch(2, "C")
        sup.check_once()
        clock.advance(100)
        sup.touch(2, "C")
        sup.check_once()
        # every dead wid's pending adopter must be the only live proc
        pend = sup.orders_for("C")
        member = sup.membership()
        dead_wids = [w for w, m in member.items() if m["state"] == DEAD]
        for w in dead_wids:
            assert member[w]["owner"] == "C" or w in pend


class TestWaitDoneDiagnostic:
    def test_timeout_returns_falsy_diagnostic_not_bare_false(self, devices8):
        cfg = make_cfg(num_iterations=10**6)
        n, d = 256, 8
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port)
            got = cl.pull(0)
            assert got is not None
            cl.push(0, got[0], np.zeros(d, np.float32))
            cl.bye()
            res = ps.wait_done(timeout_s=0.5)
            assert not res                      # falsy like the old False
            s = str(res)
            assert "wid   0" in s and "last-contact" in s
            assert "pushes=1" in s
            # done-bitmap: wid 0 contributed, the rest never did
            assert "contributed-bitmap=1000" in s
            assert "wid   1" in s and "never" in s
        finally:
            ps.stop()

    def test_progress_timeout_fails_fast(self, devices8):
        """No worker contact + no clock movement -> return well before the
        full timeout, with the diagnostic."""
        cfg = make_cfg(num_iterations=10**6)
        ps = ps_dcn.ParameterServer(cfg, 8, 256, device=devices8[0],
                                    port=0).start()
        try:
            t0 = time.monotonic()
            res = ps.wait_done(timeout_s=60.0, progress_timeout_s=0.5)
            elapsed = time.monotonic() - t0
            assert not res and elapsed < 10.0, elapsed
            assert "stalled" in str(res)
        finally:
            ps.stop()

    def test_done_run_stays_truthy(self, devices8):
        cfg = make_cfg(num_iterations=20, bucket_ratio=0.0, num_workers=1)
        n, d = 256, 8
        ds = ShardedDataset.generate_on_device(n, d, 1,
                                               devices=devices8[:1], seed=3)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        ps_dcn.run_worker_process("127.0.0.1", ps.port, [0],
                                  {0: ds.shard(0)}, cfg, d, n,
                                  deadline_s=60.0)
        res = ps.wait_done(timeout_s=5.0)
        ps.stop()
        assert res and bool(res) is True and str(res) == "done"


class TestElasticInProcess:
    def test_silent_worker_group_adopted_run_covers_all_shards(
            self, devices8):
        """Proc B (wids 2,3) goes silent mid-run; the supervisor declares
        its workers dead and proc A adopts their shards via PULL-reply
        orders -- the run completes with EVERY shard still contributing
        accepted gradients after the death (data coverage), at a cohort
        clamped to live membership."""
        sup = ElasticSupervisor(4, dead_after_s=0.5, check_interval_s=0.1,
                                boot_grace_s=30.0)
        cfg = make_cfg(num_iterations=600, printer_freq=200)
        n, d = 1024, 16
        ds = ShardedDataset.generate_on_device(n, d, 4, devices=devices8[:4],
                                               seed=11, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0], port=0,
                                    supervisor=sup).start()
        doomed_stop = threading.Event()
        doomed_pushes = {"n": 0}

        def doomed():
            cls = {w: ps_dcn.PSClient("127.0.0.1", ps.port, proc="procB")
                   for w in (2, 3)}
            try:
                cls[2].hello("procB", [2, 3])
                while not doomed_stop.is_set():
                    for w, c in cls.items():
                        got = c.pull(w)
                        if got is None or doomed_stop.is_set():
                            return
                        c.push(w, got[0], np.zeros(d, np.float32))
                        doomed_pushes["n"] += 1
            except (ConnectionError, OSError):
                return

        t_doomed = threading.Thread(target=doomed, daemon=True)
        t_doomed.start()
        counts = {}

        def survivors():
            counts.update(ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, [0, 1],
                {0: ds.shard(0), 1: ds.shard(1)}, cfg, d, n,
                deadline_s=120.0, shard_factory=ds.shard,
                proc_token="procA",
            ))

        t_surv = threading.Thread(target=survivors, daemon=True)
        t_surv.start()
        deadline = time.monotonic() + 30
        while doomed_pushes["n"] < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        doomed_stop.set()
        with ps._lock:
            accepted_at_kill = dict(ps.accepted_by_wid)
        t_surv.join(timeout=120)
        res = ps.wait_done(timeout_s=15.0)
        ps.stop()
        assert res, str(res)
        assert ps.accepted == cfg.num_iterations
        # the dead group's workers were declared lost and their shards
        # adopted (recovery counters visible, incl. process-wide totals)
        c = sup.counters()
        assert c["workers_lost"] >= 2 and c["shards_adopted"] >= 2
        totals = recovery_totals()
        assert totals["workers_lost"] >= 2
        # full data coverage: every shard kept contributing AFTER the kill
        for w in range(4):
            assert ps.accepted_by_wid.get(w, 0) > 0
        for w in (2, 3):
            assert ps.accepted_by_wid[w] > accepted_at_kill.get(w, 0), (
                w, accepted_at_kill, ps.accepted_by_wid,
            )
            assert counts.get(w, 0) > 0   # served by the ADOPTER process

    def test_rejoining_worker_reclaims_shard_from_surrogate(self, devices8):
        """After adoption, a replacement process HELLOs with the dead
        worker's wids: the surrogate is RELEASED mid-run and the rejoiner
        serves its own shard again -- membership rebalances."""
        sup = ElasticSupervisor(2, dead_after_s=0.4, check_interval_s=0.1,
                                boot_grace_s=30.0)
        cfg = make_cfg(num_workers=2, num_iterations=10**6,
                       bucket_ratio=0.0, printer_freq=10**5)
        n, d = 512, 8
        ds = ShardedDataset.generate_on_device(n, d, 2, devices=devices8[:2],
                                               seed=5, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0], port=0,
                                    supervisor=sup).start()
        stop_b = threading.Event()
        b_pushes = {"n": 0}

        def proc_b(token, stop_ev, counter):
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, proc=token)
            try:
                cl.hello(token, [1])
                while not stop_ev.is_set():
                    got = cl.pull(1)
                    if got is None:
                        return cl.released
                    cl.push(1, got[0], np.zeros(d, np.float32))
                    counter["n"] += 1
            except (ConnectionError, OSError):
                return False
            finally:
                cl.bye()
            return False

        t_b = threading.Thread(target=proc_b, args=("procB", stop_b, b_pushes),
                               daemon=True)
        t_b.start()
        counts = {}
        t_a = threading.Thread(
            target=lambda: counts.update(ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, [0], {0: ds.shard(0)}, cfg, d, n,
                deadline_s=120.0, shard_factory=ds.shard,
                proc_token="procA")),
            daemon=True,
        )
        t_a.start()
        # let B participate, then crash it (silence)
        deadline = time.monotonic() + 30
        while b_pushes["n"] < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        stop_b.set()
        # wait for A to adopt shard 1
        while (sup.counters()["shards_adopted"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert sup.counters()["shards_adopted"] >= 1
        while counts.get(1, 0) == 0 and time.monotonic() < deadline:
            time.sleep(0.05)  # counts mutates live: adopter serving wid 1
        # rejoin: B2 takes wid 1 back; A's surrogate loop gets RELEASED
        stop_b2 = threading.Event()
        b2_pushes = {"n": 0}
        t_b2 = threading.Thread(target=proc_b,
                                args=("procB2", stop_b2, b2_pushes),
                                daemon=True)
        t_b2.start()
        deadline = time.monotonic() + 60
        while b2_pushes["n"] < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert b2_pushes["n"] >= 3, "rejoined process never served"
        c = sup.counters()
        assert c["rejoins"] >= 1 and c["releases"] >= 1
        # the run is open-ended (we tested mid-run membership, not
        # completion); end it -- every pull now answers DONE
        ps._done.set()
        stop_b2.set()
        t_a.join(timeout=30)
        assert not t_a.is_alive()
        ps.stop()
        assert ps.accepted > 0


class TestWorkerSigkill:
    def test_sigkill_dcn_worker_process_midrun_run_completes(
            self, devices8):
        """THE acceptance scenario: a real OS worker process (wids 4..7)
        is SIGKILLed mid-ASGD-run.  The supervisor detects the exit via
        the HELLO'd pid, re-homes all four shards onto the surviving
        process, and the run completes with every shard's samples
        contributing (coverage assert) and recovery counters visible."""
        sup = ElasticSupervisor(8, dead_after_s=1.0, check_interval_s=0.2,
                                boot_grace_s=60.0)
        cfg = make_cfg(num_workers=8, num_iterations=2000, printer_freq=500,
                       run_timeout_s=240.0)
        n, d = 4096, 24
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=11, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0], port=0,
                                    supervisor=sup).start()
        env_base = dict(os.environ)
        env_base.pop("JAX_PLATFORMS", None)
        env_base.pop("XLA_FLAGS", None)
        env = dict(
            env_base, PS_ROLE="worker", PS_PORT=str(ps.port),
            PS_WORKER_ID="1", PS_NUM_WORKER_PROCS="2",
            PS_WIDS="4,5,6,7", PS_EVAL="0", PS_NUM_ITER="2000",
        )
        doomed = subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        counts = {}
        try:
            t_surv = threading.Thread(
                target=lambda: counts.update(ps_dcn.run_worker_process(
                    "127.0.0.1", ps.port, [0, 1, 2, 3],
                    {w: ds.shard(w) for w in range(4)}, cfg, d, n,
                    eval_wid=0, deadline_s=240.0, shard_factory=ds.shard,
                    proc_token="survivor")),
                daemon=True,
            )
            t_surv.start()
            # wait until the doomed process has contributed for all its
            # wids, then kill -9 it mid-run
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                with ps._lock:
                    if all(ps.pushes_by_wid.get(w, 0) >= 2
                           for w in (4, 5, 6, 7)):
                        break
                time.sleep(0.05)
            with ps._lock:
                assert all(ps.pushes_by_wid.get(w, 0) >= 2
                           for w in (4, 5, 6, 7)), \
                    "doomed worker process never participated"
                accepted_at_kill = dict(ps.accepted_by_wid)
            doomed.send_signal(signal.SIGKILL)
            doomed.wait(timeout=10)
            t_surv.join(timeout=240)
            assert not t_surv.is_alive(), "survivor never finished"
            res = ps.wait_done(timeout_s=30.0)
            assert res, str(res)
            total = ps.collect_eval(num_worker_procs=1, timeout_s=60.0)
        finally:
            if doomed.poll() is None:
                doomed.kill()
            ps.stop()
        assert ps.accepted == cfg.num_iterations
        # recovery counters: 4 workers lost with the process, 4 shards
        # adopted by the survivor
        c = sup.counters()
        assert c["workers_lost"] >= 4 and c["shards_adopted"] >= 4, c
        # full data coverage: every shard contributed, and the dead
        # process's shards kept contributing AFTER the kill (adoption,
        # not leftovers)
        for w in range(8):
            assert ps.accepted_by_wid.get(w, 0) > 0, ps.accepted_by_wid
        post_kill = sum(
            ps.accepted_by_wid[w] - accepted_at_kill.get(w, 0)
            for w in (4, 5, 6, 7)
        )
        assert post_kill > 0, (accepted_at_kill, ps.accepted_by_wid)
        assert sum(counts.get(w, 0) for w in (4, 5, 6, 7)) > 0, counts
        # the run converged over the FULL dataset (survivor evaluated its
        # own + adopted shards = all 8)
        assert total is not None
        traj = np.asarray(total) / n
        assert traj[-1] < traj[0] * 0.05, traj


class TestRunSyncFailFast:
    def test_killed_executor_aborts_run_sync_promptly_with_diagnostic(
            self, devices8, monkeypatch):
        """SIGKILL-analog during the synchronous barrier: with heartbeat
        monitoring off, a dead executor used to hang the drain for the
        full run timeout; now it aborts within the dead-grace window and
        the error names the dead worker with per-worker liveness."""
        from asyncframework_tpu.solvers import asgd as asgd_mod
        from asyncframework_tpu.solvers.base import DeadWorkerError

        class SlowW2:
            """Worker 2's task holds the executor busy long enough for the
            kill to land mid-task deterministically."""

            def __init__(self, *a, **k):
                pass

            def delay_ms(self, wid):
                return 3000.0 if wid == 2 else 0.0

            def calibrate(self, avg_ms):
                pass

        monkeypatch.setattr(asgd_mod, "DelayModel", SlowW2)
        X = np.random.default_rng(0).normal(size=(256, 8)).astype(np.float32)
        y = X @ np.ones(8, np.float32)
        cfg = make_cfg(num_iterations=50, heartbeat=False,
                       run_timeout_s=300.0)
        solver = asgd_mod.ASGD(X, y, cfg, devices=devices8[:4])
        err = {}

        def run():
            try:
                solver.run_sync()
            except Exception as e:  # noqa: BLE001 - captured for asserts
                err["e"] = e

        t = threading.Thread(target=run, daemon=True)
        t.start()
        deadline = time.monotonic() + 60
        killed = False
        while time.monotonic() < deadline and not killed:
            sched = getattr(solver, "scheduler", None)
            if sched is not None:
                ex = sched.pool.executors.get(2)
                # kill mid-task but only from round 1 on: the scheduler's
                # FIRST job blocks inside run_job (first-iteration warm-up
                # semantics) before the drain loop ever runs
                if ex is not None and ex.busy and len(ex.metrics) >= 1:
                    ex.kill()   # mid-task: its result will never report
                    killed = True
            time.sleep(0.01)
        assert killed, "executor 2 never observed busy past round 0"
        t.join(timeout=30)   # must abort FAR below run_timeout_s=300
        assert not t.is_alive(), "run_sync hung after executor death"
        assert isinstance(err.get("e"), DeadWorkerError), err
        msg = str(err["e"])
        assert "wid   2" in msg and "DEAD" in msg
        assert "last-heartbeat" in msg
