"""File-backed replayable log (VERDICT r3 missing-item 6).

Parity target: the reference's direct Kafka stream
(DirectKafkaInputDStream.scala) -- offset-tracked ranged reads from a
durable log, commits after outputs, replay from the last commit on
failure.  The capability (exactly-once-ish ingest) without the Kafka
dependency.
"""

import json
import os

import pytest

from asyncframework_tpu.streaming import (
    DirectLogStream,
    LogTopic,
    StreamingContext,
)
from asyncframework_tpu.utils.clock import ManualClock


class TestLogTopic:
    def test_append_read_roundtrip(self, tmp_path):
        t = LogTopic(str(tmp_path / "t"))
        offs = [t.append({"i": i}) for i in range(10)]
        assert offs == list(range(10))
        vals, nxt = t.read(0)
        assert vals == [{"i": i} for i in range(10)]
        assert nxt == 10
        vals, nxt = t.read(7, max_records=2)
        assert vals == [{"i": 7}, {"i": 8}] and nxt == 9

    def test_segment_rollover_and_reopen(self, tmp_path):
        path = str(tmp_path / "t")
        t = LogTopic(path, segment_bytes=256)  # tiny: force many segments
        t.append_many([f"v{i:04d}" for i in range(200)])
        assert len([f for f in os.listdir(path) if f.endswith(".log")]) > 1
        # a fresh instance (restart) rebuilds offsets by scanning segments
        t2 = LogTopic(path, segment_bytes=256)
        assert t2.end_offset() == 200
        vals, nxt = t2.read(150)
        assert vals == [f"v{i:04d}" for i in range(150, 200)]
        # appends continue with contiguous offsets across the reopen
        first, end = t2.append_many(["tail"])
        assert (first, end) == (200, 201)

    def test_read_past_end_empty(self, tmp_path):
        t = LogTopic(str(tmp_path / "t"))
        t.append(1)
        vals, nxt = t.read(5)
        assert vals == [] and nxt == 5

    def test_live_tail_across_instances(self, tmp_path):
        """A consumer instance must see records appended by a DIFFERENT
        producer instance after the consumer was constructed -- the live
        tail a direct stream exists for."""
        path = str(tmp_path / "t")
        consumer = LogTopic(path)
        assert consumer.read(0) == ([], 0)
        producer = LogTopic(path)
        producer.append_many(["a", "b"])
        vals, nxt = consumer.read(0)
        assert vals == ["a", "b"] and nxt == 2
        # and across a segment roll by the other instance
        producer2 = LogTopic(path, segment_bytes=64)
        producer2.append_many([f"x{i}" for i in range(30)])
        vals, nxt = consumer.read(nxt)
        assert vals == [f"x{i}" for i in range(30)] and nxt == 32
        assert consumer.end_offset() == 32

    def test_consumer_groups_independent(self, tmp_path):
        t = LogTopic(str(tmp_path / "t"))
        t.commit_offset("a", 7)
        assert t.committed_offset("a") == 7
        assert t.committed_offset("b") == 0


class TestDirectLogStream:
    def _ssc(self):
        return StreamingContext(batch_interval_ms=100, clock=ManualClock())

    def test_batches_commit_and_resume(self, tmp_path):
        path = str(tmp_path / "t")
        topic = LogTopic(path)
        topic.append_many(list(range(25)))
        seen = []
        ssc = self._ssc()
        ds = DirectLogStream(ssc, topic, group="g", max_per_batch=10)
        ds.foreach_batch(lambda t, b: seen.append(list(b)))
        for i in range(1, 4):
            ssc.generate_batch(i * 100)
        assert seen == [list(range(10)), list(range(10, 20)),
                        list(range(20, 25))]
        assert topic.committed_offset("g") == 25

        # restart: a new context + stream on the same group resumes past
        # everything committed
        topic.append_many([100, 101])
        seen2 = []
        ssc2 = self._ssc()
        ds2 = DirectLogStream(ssc2, LogTopic(path), group="g")
        ds2.foreach_batch(lambda t, b: seen2.append(list(b)))
        ssc2.generate_batch(100)
        assert seen2 == [[100, 101]]

    def test_failed_output_replays_interval(self, tmp_path):
        """The exactly-once-ish contract: an interval whose output raises
        commits nothing, so the same records re-emit after restart."""
        path = str(tmp_path / "t")
        LogTopic(path).append_many(["a", "b", "c"])
        ssc = self._ssc()
        ds = DirectLogStream(ssc, path, group="g")
        boom = {"n": 0}

        def failing(_t, _b):
            boom["n"] += 1
            raise RuntimeError("output failed")

        ds.foreach_batch(failing)
        with pytest.raises(RuntimeError):
            ssc.generate_batch(100)
        assert boom["n"] == 1
        assert LogTopic(path).committed_offset("g") == 0  # no commit

        seen = []
        ssc2 = self._ssc()
        ds2 = DirectLogStream(ssc2, path, group="g")
        ds2.foreach_batch(lambda t, b: seen.append(list(b)))
        ssc2.generate_batch(100)
        assert seen == [["a", "b", "c"]]  # replayed in full
        assert LogTopic(path).committed_offset("g") == 3

    def test_empty_interval_emits_nothing(self, tmp_path):
        ssc = self._ssc()
        ds = DirectLogStream(ssc, str(tmp_path / "t"), group="g")
        seen = []
        ds.foreach_batch(lambda t, b: seen.append(b))
        assert ssc.generate_batch(100) == 0
        assert seen == []

    def test_transform_chain(self, tmp_path):
        """The log source composes with the DStream graph like any input."""
        path = str(tmp_path / "t")
        LogTopic(path).append_many([1, 2, 3, 4, 5])
        ssc = self._ssc()
        out = []
        (DirectLogStream(ssc, path)
            .map_batch(lambda b: [x * 10 for x in b])
            .foreach_batch(lambda t, b: out.append(b)))
        ssc.generate_batch(100)
        assert out == [[10, 20, 30, 40, 50]]
