"""Child process for the multi-process async-PS test (role via env).

Role PS: bind the server, print its port on stdout (flushed), run the
updater until done, print a result JSON line.
Role WORKER: connect to the PS, run the owned logical workers' loops,
evaluate the snapshot stack over the owned shards, print a JSON line.

Elastic-plane knobs (tests/test_supervisor.py): ``PS_ELASTIC=1`` runs the
PS with an ElasticSupervisor (``PS_DEAD_AFTER_S`` tunes death detection);
``PS_WIDS=4,5,6,7`` pins a worker process to explicit logical workers
(instead of the modulo split); ``PS_EVAL=0`` disables the post-run
snapshot evaluation (a worker destined to be SIGKILLed must not hold the
eval slot); ``PS_NUM_ITER`` overrides the iteration budget.

Trace-plane knobs (tests/test_trace.py): ``PS_EVENT_LOG=<path>`` attaches
a ListenerBus + EventLogWriter to the PS (TraceSpan/GradientMerged events
stream to JSONL); ``PS_UI=1`` also serves the live dashboard on an
ephemeral port (printed as ``ui_port`` on the first stdout line).  Worker
sampling itself is conf-driven: set ``ASYNCTPU_ASYNC_TRACE_SAMPLE`` in the
child env.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.solvers import SolverConfig

N, D, NW = 4096, 24, 8
NUM_ITER = int(os.environ.get("PS_NUM_ITER", "400"))


def config() -> SolverConfig:
    return SolverConfig(
        num_workers=NW, num_iterations=NUM_ITER,
        gamma=float(os.environ.get("PS_GAMMA", "1.2")),
        taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5, printer_freq=50,
        seed=42, calibration_iters=20, run_timeout_s=120.0,
    )


def dataset(devices):
    return ShardedDataset.generate_on_device(
        N, D, NW, devices=devices, seed=11, noise=0.01
    )


def main() -> None:
    role = os.environ["PS_ROLE"]
    algo = os.environ.get("PS_ALGO", "asgd")
    cfg = config()
    if role == "ps":
        sup = None
        if os.environ.get("PS_ELASTIC") == "1":
            from asyncframework_tpu.parallel.supervisor import (
                ElasticSupervisor,
            )

            sup = ElasticSupervisor(
                NW,
                dead_after_s=float(os.environ.get("PS_DEAD_AFTER_S", "2.0")),
                check_interval_s=0.2,
            )
        bus = writer = ui = None
        if os.environ.get("PS_EVENT_LOG") or os.environ.get("PS_UI") == "1":
            from asyncframework_tpu.metrics.bus import ListenerBus
            from asyncframework_tpu.metrics.eventlog import EventLogWriter

            bus = ListenerBus()
            if os.environ.get("PS_EVENT_LOG"):
                writer = EventLogWriter(os.environ["PS_EVENT_LOG"])
                bus.add_listener(writer)
            if os.environ.get("PS_UI") == "1":
                from asyncframework_tpu.metrics.live import (
                    LiveStateListener,
                    LiveUIServer,
                )

                state = LiveStateListener(NW)
                bus.add_listener(state)
                ui = LiveUIServer(state, port=0).start()
            bus.start()
        ps = ps_dcn.ParameterServer(
            cfg, D, N, port=int(os.environ.get("PS_BIND_PORT", "0")),
            algo=algo,
            checkpoint_path=os.environ.get("PS_CHECKPOINT") or None,
            supervisor=sup, bus=bus,
        ).start()
        hello = {"port": ps.port}
        if ui is not None:
            hello["ui_port"] = ui.port
        print(json.dumps(hello), flush=True)
        ok = ps.wait_done(timeout_s=120.0)
        total = ps.collect_eval(
            num_worker_procs=int(os.environ["PS_NUM_WORKER_PROCS"]),
            timeout_s=60.0,
        )
        traj = None
        if total is not None:
            times, _W = ps.snapshot_stack()
            traj = [[t, float(l) / N] for t, l in zip(times, total)]
        print(json.dumps({
            "role": "ps", "done": bool(ok), "accepted": ps.accepted,
            "dropped": ps.dropped, "max_staleness": ps.max_staleness,
            "resumed_from": ps.resumed_from_k,
            "accepted_by_wid": {
                str(w): c for w, c in ps.accepted_by_wid.items()
            },
            "recovery": sup.counters() if sup is not None else None,
            "trace_spans": ps.trace_spans,
            "diagnostic": None if ok else str(ok),
            "trajectory": traj,
        }), flush=True)
        ps.stop()
        if ui is not None:
            ui.stop()
        if bus is not None:
            bus.stop()
        if writer is not None:
            writer.close()
    else:
        port = int(os.environ["PS_PORT"])
        pid = int(os.environ["PS_WORKER_ID"])
        nproc = int(os.environ["PS_NUM_WORKER_PROCS"])
        # observability knobs (tests/test_observer.py): PS_METRICS=1
        # starts this worker's telemetry endpoint (conf-driven port,
        # ASYNCTPU_ASYNC_METRICS_PORT=0 for ephemeral) -- which also
        # installs the crash flight recorder when ASYNCTPU_ASYNC_FLIGHT_DIR
        # is set -- and announces the bound port as a first stdout line
        # so the parent can hand it to a collector.
        if os.environ.get("PS_METRICS") == "1":
            from asyncframework_tpu.metrics.live import (
                start_telemetry_from_conf,
            )

            srv = start_telemetry_from_conf(f"worker-{pid}",
                                            labels={"proc": str(pid)})
            print(json.dumps({
                "metrics_port": srv.port if srv is not None else None,
            }), flush=True)
        # chaos fabric by env, like the daemons (no-op when the conf key
        # is empty): lets a test DELAY-inject one worker child
        from asyncframework_tpu.net import faults

        faults.maybe_install_from_conf()
        devices = jax.devices()
        ds = dataset(devices)
        if os.environ.get("PS_WIDS"):
            wids = [int(w) for w in os.environ["PS_WIDS"].split(",")]
        else:
            wids = [w for w in range(NW) if w % nproc == pid]
        shards = {w: ds.shard(w) for w in wids}
        # every worker process scores its OWN shards; the PS sums the
        # per-process vectors -- together they cover the full dataset
        counts = ps_dcn.run_worker_process(
            "127.0.0.1", port, wids, shards, cfg, D, N,
            eval_wid=None if os.environ.get("PS_EVAL") == "0" else wids[0],
            deadline_s=120.0, algo=algo,
            shard_factory=ds.shard, proc_token=f"child-{os.getpid()}",
        )
        print(json.dumps({
            "role": "worker", "pid": pid,
            "gradients": int(sum(counts.values())),
        }), flush=True)


if __name__ == "__main__":
    main()
