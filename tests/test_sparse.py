"""Sparse (rcv1-class) end-to-end tests.

Round-2 requirement (VERDICT.md item 4): CSR shards resident on device in a
static-shape form, the worker step computing sparse gradients without ever
densifying the data, and an ASGD recipe on a 47k-dim ~0.2%-dense problem
converging -- through the CLI as well.
"""

import json

import numpy as np
import pytest

import jax

from asyncframework_tpu.data import (
    SparseShardedDataset,
    densify,
    make_sparse_regression,
    parse_libsvm_lines_sparse,
)
from asyncframework_tpu.ops import gradients, steps
from asyncframework_tpu.solvers import ASAGA, ASGD, SolverConfig


def small_sparse(n=512, d=256, density=0.05, seed=0):
    indptr, indices, values, y = make_sparse_regression(n, d, density, seed)
    return indptr, indices, values, y


class TestSparseData:
    def test_parse_libsvm_sparse(self):
        lines = ["1.0 3:2.5 7:1.0", "# comment", "-1 1:0.5"]
        indptr, indices, values, y = parse_libsvm_lines_sparse(lines, 8)
        assert list(indptr) == [0, 2, 3]
        assert list(indices) == [2, 6, 0]  # 0-based
        np.testing.assert_allclose(values, [2.5, 1.0, 0.5])
        np.testing.assert_allclose(y, [1.0, -1.0])

    def test_shards_padded_and_faithful(self, devices8):
        indptr, indices, values, y = small_sparse()
        ds = SparseShardedDataset(indptr, indices, values, y, 256, 8, devices8)
        assert ds.n == 512 and ds.d == 256
        s0 = ds.shard(0)
        assert s0.cols.shape == s0.vals.shape
        assert s0.cols.shape[1] % 8 == 0  # lane-padded
        # densify reproduces the CSR rows
        X, y2 = densify(ds)
        np.testing.assert_allclose(y2, y)
        i = 5  # spot-check one row
        a, b = indptr[i], indptr[i + 1]
        row = np.zeros(256, np.float32)
        row[indices[a:b]] = values[a:b]
        np.testing.assert_allclose(X[i], row)


class TestSparseOps:
    def test_sparse_grad_matches_dense(self, devices8):
        indptr, indices, values, y = small_sparse(128, 64, 0.1, seed=3)
        ds = SparseShardedDataset(indptr, indices, values, y, 64, 1, devices8[:1])
        s = ds.shard(0)
        rs = np.random.default_rng(1)
        w = rs.normal(size=(64,)).astype(np.float32)
        mask = (rs.random(128) < 0.5).astype(np.float32)
        X, _ = densify(ds)

        r = np.asarray(gradients.sparse_residual(s.cols, s.vals, s.y, w))
        np.testing.assert_allclose(r, X @ w - y, rtol=1e-4, atol=1e-5)

        grad_sum = gradients.make_sparse_grad_sum(64)
        g = np.asarray(grad_sum(s.cols, s.vals, mask * r))
        np.testing.assert_allclose(
            g, X.T @ (mask * (X @ w - y)), rtol=1e-3, atol=1e-3
        )

    def test_sparse_saga_step_matches_dense_formula(self, devices8):
        indptr, indices, values, y = small_sparse(64, 32, 0.2, seed=5)
        ds = SparseShardedDataset(indptr, indices, values, y, 32, 1, devices8[:1])
        s = ds.shard(0)
        rs = np.random.default_rng(2)
        w = rs.normal(size=(32,)).astype(np.float32)
        alpha = rs.normal(size=(64,)).astype(np.float32)
        step = steps.make_sparse_saga_worker_step(0.5, 32)
        g, diff, mask, _ = step(s.cols, s.vals, s.y, w, alpha, jax.random.PRNGKey(0))
        X, _ = densify(ds)
        np.testing.assert_allclose(np.asarray(diff), X @ w - y, rtol=1e-4, atol=1e-5)
        m = np.asarray(mask)
        expect = X.T @ (m * ((X @ w - y) - alpha))
        np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-3, atol=1e-3)


class TestSparseSolvers:
    def cfg(self, **kw):
        defaults = dict(
            num_workers=8, num_iterations=200, gamma=0.3,
            taw=2**31 - 1, batch_rate=0.2, bucket_ratio=0.5,
            printer_freq=50, coeff=0.0, seed=42,
            calibration_iters=10, run_timeout_s=120.0,
        )
        defaults.update(kw)
        return SolverConfig(**defaults)

    def test_asgd_converges_47kdim_sparse(self, devices8):
        # the VERDICT-prescribed shape: 47k dims at ~0.2% density
        indptr, indices, values, y = make_sparse_regression(
            2048, 47_236, density=0.002, seed=11
        )
        ds = SparseShardedDataset(
            indptr, indices, values, y, 47_236, 8, devices8
        )
        res = ASGD(ds, None, self.cfg(gamma=0.5), devices=devices8).run()
        assert res.accepted == 200
        first, last = res.trajectory[0][1], res.trajectory[-1][1]
        assert last < first * 0.7, res.trajectory

    def test_asgd_sync_sparse(self, devices8):
        indptr, indices, values, y = small_sparse(1024, 512, 0.01, seed=7)
        ds = SparseShardedDataset(indptr, indices, values, y, 512, 8, devices8)
        res = ASGD(ds, None, self.cfg(num_iterations=50, gamma=0.5),
                   devices=devices8).run_sync()
        assert res.rounds == 50
        assert res.trajectory[-1][1] < res.trajectory[0][1]

    def test_asaga_sparse_runs_and_converges(self, devices8):
        indptr, indices, values, y = small_sparse(1024, 512, 0.01, seed=9)
        ds = SparseShardedDataset(indptr, indices, values, y, 512, 8, devices8)
        res = ASAGA(ds, None, self.cfg(num_iterations=150, gamma=0.05),
                    devices=devices8).run()
        assert res.accepted == 150
        assert res.trajectory[-1][1] < res.trajectory[0][1]


class TestSparseCLI:
    def test_rcv1_shaped_recipe(self, capsys):
        from asyncframework_tpu import cli

        rc = cli.main([
            "SparkASGDThread", "synthetic", "x", "47236", "1024", "8", "60",
            "0.5", "2147483647", "0.2", "0.5", "20", "0", "42",
            "--quiet", "--sparse",
        ])
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()
        summary = json.loads(out[-1])
        assert summary["accepted"] == 60
        assert np.isfinite(summary["final_objective"])

    def test_sparse_rejected_for_mllib(self):
        from asyncframework_tpu import cli

        with pytest.raises(SystemExit):
            cli.main([
                "sgd-mllib", "synthetic", "x", "64", "256", "8", "5",
                "0.5", "0", "0.2", "0.5", "5", "0", "42", "--sparse",
            ])
