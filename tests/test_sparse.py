"""Sparse (rcv1-class) end-to-end tests.

Round-2 requirement (VERDICT.md item 4): CSR shards resident on device in a
static-shape form, the worker step computing sparse gradients without ever
densifying the data, and an ASGD recipe on a 47k-dim ~0.2%-dense problem
converging -- through the CLI as well.
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from asyncframework_tpu.data import (
    SparseShardedDataset,
    densify,
    make_sparse_regression,
    parse_libsvm_lines_sparse,
)
from asyncframework_tpu.ops import gradients, steps
from asyncframework_tpu.solvers import ASAGA, ASGD, SolverConfig


def small_sparse(n=512, d=256, density=0.05, seed=0):
    indptr, indices, values, y = make_sparse_regression(n, d, density, seed)
    return indptr, indices, values, y


class TestSparseData:
    def test_parse_libsvm_sparse(self):
        lines = ["1.0 3:2.5 7:1.0", "# comment", "-1 1:0.5"]
        indptr, indices, values, y = parse_libsvm_lines_sparse(lines, 8)
        assert list(indptr) == [0, 2, 3]
        assert list(indices) == [2, 6, 0]  # 0-based
        np.testing.assert_allclose(values, [2.5, 1.0, 0.5])
        np.testing.assert_allclose(y, [1.0, -1.0])

    def test_shards_padded_and_faithful(self, devices8):
        indptr, indices, values, y = small_sparse()
        ds = SparseShardedDataset(indptr, indices, values, y, 256, 8, devices8)
        assert ds.n == 512 and ds.d == 256
        s0 = ds.shard(0)
        assert s0.cols.shape == s0.vals.shape
        assert s0.cols.shape[1] % 8 == 0  # lane-padded
        # densify reproduces the CSR rows
        X, y2 = densify(ds)
        np.testing.assert_allclose(y2, y)
        i = 5  # spot-check one row
        a, b = indptr[i], indptr[i + 1]
        row = np.zeros(256, np.float32)
        row[indices[a:b]] = values[a:b]
        np.testing.assert_allclose(X[i], row)


class TestSparseOps:
    def test_sparse_grad_matches_dense(self, devices8):
        indptr, indices, values, y = small_sparse(128, 64, 0.1, seed=3)
        ds = SparseShardedDataset(indptr, indices, values, y, 64, 1, devices8[:1])
        s = ds.shard(0)
        rs = np.random.default_rng(1)
        w = rs.normal(size=(64,)).astype(np.float32)
        mask = (rs.random(128) < 0.5).astype(np.float32)
        X, _ = densify(ds)

        r = np.asarray(gradients.sparse_residual(s.cols, s.vals, s.y, w))
        np.testing.assert_allclose(r, X @ w - y, rtol=1e-4, atol=1e-5)

        grad_sum = gradients.make_sparse_grad_sum(64)
        g = np.asarray(grad_sum(s.cols, s.vals, mask * r))
        np.testing.assert_allclose(
            g, X.T @ (mask * (X @ w - y)), rtol=1e-3, atol=1e-3
        )

    def test_sparse_saga_step_matches_dense_formula(self, devices8):
        """The compacted sparse SAGA step reproduces the dense masked
        formula exactly: recover the selected-row mask from (idx, valid)
        and compare gradient, candidate scalars, and the commit."""
        indptr, indices, values, y = small_sparse(64, 32, 0.2, seed=5)
        ds = SparseShardedDataset(indptr, indices, values, y, 32, 1, devices8[:1])
        s = ds.shard(0)
        rs = np.random.default_rng(2)
        w = rs.normal(size=(32,)).astype(np.float32)
        alpha = rs.normal(size=(64,)).astype(np.float32)
        step = steps.make_sparse_saga_worker_step(0.5, 32)
        g, diff_sel, idx, valid, c_sel, v_sel, _ = step(
            s.cols, s.vals, s.y, w, alpha, jax.random.PRNGKey(0)
        )
        idx_h = np.asarray(idx)
        valid_h = np.asarray(valid)
        sel = idx_h[valid_h > 0]
        m = np.zeros(64, np.float32)
        m[sel] = 1.0
        X, _ = densify(ds)
        full_diff = X @ w - y
        # candidate scalars for the selected rows match the dense residual
        np.testing.assert_allclose(
            np.asarray(diff_sel)[valid_h > 0], full_diff[sel],
            rtol=1e-4, atol=1e-5,
        )
        expect = X.T @ (m * (full_diff - alpha))
        np.testing.assert_allclose(np.asarray(g), expect, rtol=1e-3, atol=1e-3)
        # the commit writes exactly the selected rows
        commit = steps.make_sparse_saga_commit()
        a2 = np.asarray(commit(jnp.asarray(alpha), diff_sel, idx, valid))
        want = np.where(m > 0, full_diff, alpha)
        np.testing.assert_allclose(a2, want, rtol=1e-4, atol=1e-5)
        # and the exact table delta equals the dense formulation
        delta = steps.make_sparse_table_delta(32)(
            c_sel, v_sel, diff_sel, jnp.asarray(alpha), idx
        )
        np.testing.assert_allclose(
            np.asarray(delta), expect, rtol=1e-3, atol=1e-3
        )


class TestSparseSolvers:
    def cfg(self, **kw):
        defaults = dict(
            num_workers=8, num_iterations=200, gamma=0.3,
            taw=2**31 - 1, batch_rate=0.2, bucket_ratio=0.5,
            printer_freq=50, coeff=0.0, seed=42,
            calibration_iters=10, run_timeout_s=120.0,
        )
        defaults.update(kw)
        return SolverConfig(**defaults)

    def test_asgd_converges_47kdim_sparse(self, devices8):
        # the VERDICT-prescribed shape: 47k dims at ~0.2% density
        indptr, indices, values, y = make_sparse_regression(
            2048, 47_236, density=0.002, seed=11
        )
        ds = SparseShardedDataset(
            indptr, indices, values, y, 47_236, 8, devices8
        )
        res = ASGD(ds, None, self.cfg(gamma=0.5), devices=devices8).run()
        assert res.accepted == 200
        first, last = res.trajectory[0][1], res.trajectory[-1][1]
        assert last < first * 0.7, res.trajectory

    def test_asgd_sync_sparse(self, devices8):
        indptr, indices, values, y = small_sparse(1024, 512, 0.01, seed=7)
        ds = SparseShardedDataset(indptr, indices, values, y, 512, 8, devices8)
        res = ASGD(ds, None, self.cfg(num_iterations=50, gamma=0.5),
                   devices=devices8).run_sync()
        assert res.rounds == 50
        assert res.trajectory[-1][1] < res.trajectory[0][1]

    def test_asaga_sparse_runs_and_converges(self, devices8):
        indptr, indices, values, y = small_sparse(1024, 512, 0.01, seed=9)
        ds = SparseShardedDataset(indptr, indices, values, y, 512, 8, devices8)
        res = ASAGA(ds, None, self.cfg(num_iterations=150, gamma=0.05),
                    devices=devices8).run()
        assert res.accepted == 150
        assert res.trajectory[-1][1] < res.trajectory[0][1]


class TestSparseCLI:
    def test_rcv1_shaped_recipe(self, capsys):
        from asyncframework_tpu import cli

        rc = cli.main([
            "SparkASGDThread", "synthetic", "x", "47236", "1024", "8", "60",
            "0.5", "2147483647", "0.2", "0.5", "20", "0", "42",
            "--quiet", "--sparse",
        ])
        assert rc == 0
        out = capsys.readouterr().out.strip().splitlines()
        summary = json.loads(out[-1])
        assert summary["accepted"] == 60
        assert np.isfinite(summary["final_objective"])

    def test_sparse_rejected_for_mllib(self):
        from asyncframework_tpu import cli

        with pytest.raises(SystemExit):
            cli.main([
                "sgd-mllib", "synthetic", "x", "64", "256", "8", "5",
                "0.5", "0", "0.2", "0.5", "5", "0", "42", "--sparse",
            ])


class TestSparseGenerateOnDevice:
    def test_shapes_conditioning_and_convergence(self, devices8):
        from asyncframework_tpu.data.sparse import SparseShardedDataset

        n, d, nnz = 4096, 512, 12
        ds = SparseShardedDataset.generate_on_device(
            n, d, nnz, 8, devices=devices8, seed=9
        )
        assert ds.n == n and ds.d == d
        s = ds.shard(0)
        K = s.cols.shape[1]
        assert K % 8 == 0 and K >= nnz
        cols = np.asarray(s.cols)
        vals = np.asarray(s.vals)
        # padding slots beyond nnz are exactly (col=0, val=0)
        assert (cols[:, nnz:] == 0).all() and (vals[:, nnz:] == 0).all()
        assert (cols[:, :nnz] < d).all() and (cols >= 0).all()
        # E[x x^T] = I/d conditioning: per-row squared norm ~ 1/nnz * nnz / ...
        row_sq = (vals ** 2).sum(axis=1)
        assert abs(row_sq.mean() - 1.0) < 0.15  # nnz * (1/nnz) = 1
        # the planted problem is learnable by sparse ASGD
        cfg = SolverConfig(
            num_workers=8, num_iterations=400, gamma=0.05 * d,
            batch_rate=0.3, bucket_ratio=0.5, printer_freq=50,
            seed=42, calibration_iters=10, run_timeout_s=120.0,
        )
        res = ASGD(ds, None, cfg, devices=devices8).run()
        first, last = res.trajectory[0][1], res.trajectory[-1][1]
        best = min(obj for _t, obj in res.trajectory)
        # learnability: the run reaches a deep minimum.  The FINAL point
        # rides the 1/sqrt(k) late phase of an async run at this recipe's
        # stability edge and oscillates run-to-run (observed 0.01-0.15x
        # first on the seed tree), so it gets a looser band than the dip
        # -- still tight enough that genuine divergence (>= 0.5x) fails.
        assert best < first * 0.1, res.trajectory
        assert last < first * 0.3, res.trajectory

    def test_deterministic_per_seed(self, devices8):
        from asyncframework_tpu.data.sparse import SparseShardedDataset

        a = SparseShardedDataset.generate_on_device(256, 64, 4, 8, devices=devices8, seed=3)
        b = SparseShardedDataset.generate_on_device(256, 64, 4, 8, devices=devices8, seed=3)
        c = SparseShardedDataset.generate_on_device(256, 64, 4, 8, devices=devices8, seed=4)
        np.testing.assert_array_equal(np.asarray(a.shard(1).cols), np.asarray(b.shard(1).cols))
        np.testing.assert_array_equal(np.asarray(a.shard(1).vals), np.asarray(b.shard(1).vals))
        assert not np.array_equal(np.asarray(a.shard(1).vals), np.asarray(c.shard(1).vals))


def _skewed_csr(n=400, d=1000, base_nnz=5, dense_every=50, dense_nnz=400, seed=0):
    """rcv1-like skew: mostly ~base_nnz rows, a few near-dense outliers."""
    rs = np.random.default_rng(seed)
    indptr = [0]
    indices = []
    values = []
    for i in range(n):
        k = dense_nnz if i % dense_every == 0 else base_nnz
        cols = rs.choice(d, size=k, replace=False)
        indices.extend(cols.tolist())
        values.extend(rs.normal(size=k).tolist())
        indptr.append(len(indices))
    y = rs.normal(size=n).astype(np.float32)
    return (np.asarray(indptr), np.asarray(indices, np.int32),
            np.asarray(values, np.float32), y)


class TestSkewGuard:
    def test_warning_on_skewed_data(self, devices8):
        import warnings

        from asyncframework_tpu.data.sparse import SparseShardedDataset

        indptr, indices, values, y = _skewed_csr()
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            ds = SparseShardedDataset(indptr, indices, values, y, 1000, 8,
                                      devices=devices8)
        assert any("nnz_partition" in str(w.message) for w in rec), (
            [str(w.message) for w in rec]
        )
        rep = ds.skew_report()
        assert rep["pad_overhead"] > SparseShardedDataset.PAD_OVERHEAD_WARN

    def test_nnz_partition_bounds_padding(self, devices8):
        import warnings

        from asyncframework_tpu.data.sparse import SparseShardedDataset, densify

        indptr, indices, values, y = _skewed_csr(dense_every=10)
        with pytest.warns(RuntimeWarning, match="nnz_partition"):
            plain = SparseShardedDataset(indptr, indices, values, y, 1000, 8,
                                         devices=devices8)
        with warnings.catch_warnings(record=True) as rec:
            warnings.simplefilter("always")
            sorted_ds = SparseShardedDataset(
                indptr, indices, values, y, 1000, 8, devices=devices8,
                nnz_partition=True,
            )
        assert not any("nnz_partition" in str(w.message) for w in rec)
        r0, r1 = plain.skew_report(), sorted_ds.skew_report()
        assert r0["nnz"] == r1["nnz"]  # same data, different layout
        # the guard's point: padding collapses from ~max-row-width everywhere
        # to near-true-nnz (dense rows cluster in one shard)
        assert r1["padded_nnz"] < r0["padded_nnz"] / 5
        assert r1["pad_overhead"] < 2.5

    def test_nnz_partition_rows_faithful(self, devices8):
        from asyncframework_tpu.data.sparse import SparseShardedDataset, densify

        indptr, indices, values, y = _skewed_csr(n=64, d=40, dense_nnz=30)
        ds = SparseShardedDataset(indptr, indices, values, y, 40, 8,
                                  devices=devices8, nnz_partition=True)
        Xp, yp = densify(ds)
        # reconstruct the original dense matrix and compare row-by-row via
        # the recorded permutation
        X0 = np.zeros((64, 40), np.float32)
        for i in range(64):
            X0[i, indices[indptr[i]:indptr[i + 1]]] = (
                values[indptr[i]:indptr[i + 1]]
            )
        np.testing.assert_allclose(Xp, X0[ds.row_perm], rtol=1e-6)
        np.testing.assert_allclose(yp, y[ds.row_perm], rtol=1e-6)

    def test_solver_runs_on_nnz_partitioned_data(self, devices8):
        from asyncframework_tpu.data.sparse import SparseShardedDataset, densify

        # planted labels so convergence is meaningful
        indptr, indices, values, _ = _skewed_csr(n=800, d=64, base_nnz=4,
                                                 dense_every=100, dense_nnz=48)
        rs = np.random.default_rng(1)
        w_true = rs.normal(size=64).astype(np.float32)
        X0 = np.zeros((800, 64), np.float32)
        for i in range(800):
            X0[i, indices[indptr[i]:indptr[i + 1]]] = (
                values[indptr[i]:indptr[i + 1]]
            )
        y = (X0 @ w_true + 0.01 * rs.normal(size=800)).astype(np.float32)
        ds = SparseShardedDataset(indptr, indices, values, y, 64, 8,
                                  devices=devices8, nnz_partition=True)
        cfg = SolverConfig(
            num_workers=8, num_iterations=300, gamma=0.5, batch_rate=0.3,
            bucket_ratio=0.5, printer_freq=50, seed=42,
            calibration_iters=10, run_timeout_s=120.0,
        )
        res = ASGD(ds, None, cfg, devices=devices8).run()
        assert res.trajectory[-1][1] < res.trajectory[0][1] * 0.5
