"""Data layer + versioned model store tests."""

import io

import numpy as np
import pytest

from asyncframework_tpu.broadcast import VersionedModelStore
from asyncframework_tpu.data import (
    ShardedDataset,
    load_libsvm,
    make_classification,
    make_regression,
    parse_libsvm_lines,
)

LIBSVM_FIXTURE = """\
1.0 1:0.5 3:1.5
-1.0 2:2.0
0.5 1:1.0 2:-1.0 3:0.25
"""


class TestLibSVM:
    def test_parse_lines(self):
        X, y = parse_libsvm_lines(io.StringIO(LIBSVM_FIXTURE))
        np.testing.assert_allclose(y, [1.0, -1.0, 0.5])
        expected = np.array(
            [[0.5, 0.0, 1.5], [0.0, 2.0, 0.0], [1.0, -1.0, 0.25]], np.float32
        )
        np.testing.assert_allclose(X, expected)

    def test_parse_with_fixed_num_features(self):
        X, _ = parse_libsvm_lines(io.StringIO(LIBSVM_FIXTURE), num_features=5)
        assert X.shape == (3, 5)

    def test_load_file(self, tmp_path):
        p = tmp_path / "tiny.libsvm"
        p.write_text(LIBSVM_FIXTURE)
        X, y = load_libsvm(str(p), num_features=3, use_native=False)
        assert X.shape == (3, 3) and y.shape == (3,)

    def test_blank_lines_and_comments_skipped(self):
        X, y = parse_libsvm_lines(io.StringIO("\n# c\n1.0 1:2.0\n"))
        assert X.shape == (1, 1) and y[0] == 1.0


class TestSynthetic:
    def test_regression_shapes_and_determinism(self):
        X1, y1, w1 = make_regression(100, 8, seed=7)
        X2, y2, w2 = make_regression(100, 8, seed=7)
        np.testing.assert_array_equal(X1, X2)
        np.testing.assert_array_equal(y1, y2)
        assert X1.shape == (100, 8) and y1.shape == (100,) and w1.shape == (8,)

    def test_classification_labels_binary(self):
        _, y, _ = make_classification(200, 4)
        assert set(np.unique(y)) <= {0.0, 1.0}


class TestShardedDataset:
    def test_balanced_partitioning_and_cum(self, devices8):
        X, y, _ = make_regression(103, 4)
        ds = ShardedDataset(X, y, num_workers=8, devices=devices8)
        sizes = ds.partition_sizes()
        assert sum(sizes.values()) == 103
        assert max(sizes.values()) - min(sizes.values()) <= 1
        # partitionCumList parity: cum[p] is the global index of shard p row 0
        assert ds.partition_cum[0] == 0 and ds.partition_cum[-1] == 103
        for w in range(8):
            assert ds.shard(w).start == ds.partition_cum[w]
            assert ds.shard(w).size == sizes[w]

    def test_shard_content_matches_rows(self, devices8):
        X, y, _ = make_regression(64, 4)
        ds = ShardedDataset(X, y, num_workers=8, devices=devices8)
        s = ds.shard(3)
        np.testing.assert_allclose(np.asarray(s.X), X[s.start : s.start + s.size])
        np.testing.assert_allclose(np.asarray(s.y), y[s.start : s.start + s.size])

    def test_shards_land_on_distinct_devices(self, devices8):
        X, y, _ = make_regression(64, 4)
        ds = ShardedDataset(X, y, num_workers=8, devices=devices8)
        placed = {list(ds.shard(w).X.devices())[0] for w in range(8)}
        assert len(placed) == 8

    def test_validation_errors(self, devices8):
        X, y, _ = make_regression(10, 2)
        with pytest.raises(ValueError, match="rows"):
            ShardedDataset(X, y[:5], 2, devices=devices8)
        with pytest.raises(ValueError, match="num_workers"):
            ShardedDataset(X, y, 11, devices=devices8)


class TestDeviceGeneratedDataset:
    def test_generate_on_device_shapes(self, devices8):
        ds = ShardedDataset.generate_on_device(1001, 16, 8, devices=devices8, seed=1)
        assert sum(ds.partition_sizes().values()) == 1001
        assert ds.partition_cum[-1] == 1001
        with pytest.raises(ValueError, match="generated on device"):
            ds.global_arrays()

    def test_generate_validates_num_workers(self, devices8):
        with pytest.raises(ValueError, match="num_workers"):
            ShardedDataset.generate_on_device(4, 8, 0, devices=devices8)
        with pytest.raises(ValueError, match="num_workers"):
            ShardedDataset.generate_on_device(4, 8, 8, devices=devices8)

    def test_solver_accepts_prebuilt_and_validates(self, devices8):
        from asyncframework_tpu.solvers import ASGD, SolverConfig
        from asyncframework_tpu.solvers.base import resolve_dataset

        ds = ShardedDataset.generate_on_device(256, 8, 8, devices=devices8)
        cfg = SolverConfig(num_workers=4)
        with pytest.raises(ValueError, match="workers"):
            ASGD(ds, None, cfg, devices=devices8)
        with pytest.raises(ValueError, match="y must be None"):
            resolve_dataset(ds, np.zeros(256), 8, devices8)
        # mismatched device order is rejected at construction time
        shuffled = list(devices8[1:]) + [devices8[0]]
        with pytest.raises(ValueError, match="rebuild the dataset"):
            resolve_dataset(ds, None, 8, shuffled)


class TestVersionedModelStore:
    def test_publish_snapshot_isolation(self):
        store = VersionedModelStore()
        w = np.zeros(4, np.float32)
        v0 = store.publish(w)
        w += 1.0  # updater keeps mutating its host w
        np.testing.assert_allclose(store.value(version=v0), np.zeros(4))

    def test_stale_read_and_eviction(self):
        store = VersionedModelStore(max_live_versions=2)
        versions = [store.publish(np.full(2, float(i))) for i in range(4)]
        assert store.live_versions() == versions[2:]
        np.testing.assert_allclose(store.value(version=versions[2]), [2.0, 2.0])
        with pytest.raises(KeyError):
            store.value(version=versions[0])  # evicted
        assert store.latest_version() == versions[3]

    def test_device_fanout_and_lazy_read(self, devices8):
        store = VersionedModelStore()
        w = np.arange(4, dtype=np.float32)
        store.publish(w, devices=devices8[:2])
        buf = store.value(device=devices8[1])
        assert list(buf.devices())[0] == devices8[1]
        np.testing.assert_allclose(np.asarray(buf), w)
        lazy = store.value(device=devices8[5])  # not in publish set
        assert list(lazy.devices())[0] == devices8[5]
        np.testing.assert_allclose(np.asarray(lazy), w)

    def test_empty_store_raises(self):
        store = VersionedModelStore()
        with pytest.raises(KeyError):
            store.latest_version()
