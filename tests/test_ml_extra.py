"""ML library breadth tests: ALS, feature transforms, statistics.

Parity targets: MLlib's ALS recommendation, ``feature/`` scalers, and
``Statistics.colStats``/``corr`` (SURVEY.md section 2.5); numerical ground
truth comes from dense NumPy equivalents.
"""

import numpy as np
import pytest

from asyncframework_tpu.ml import (
    ALS,
    MinMaxScaler,
    Normalizer,
    StandardScaler,
    col_stats,
    corr,
)
from asyncframework_tpu.parallel import make_mesh


class TestALS:
    @pytest.fixture()
    def planted(self, rng):
        """Low-rank planted ratings with 60% observed entries."""
        n_u, n_i, k = 40, 30, 4
        U = rng.normal(size=(n_u, k)).astype(np.float32)
        V = rng.normal(size=(n_i, k)).astype(np.float32)
        R = U @ V.T
        mask = (rng.random((n_u, n_i)) < 0.6).astype(np.float32)
        return R, mask

    def test_reconstructs_observed_entries(self, planted):
        R, mask = planted
        model = ALS(rank=4, reg=0.01, num_iterations=15).fit(R, mask)
        assert model.rmse(R, mask) < 0.05
        # and generalizes to HELD-OUT entries (low-rank structure recovered)
        holdout = 1.0 - mask
        assert model.rmse(R, holdout) < 0.5

    def test_rank_and_shapes(self, planted):
        R, mask = planted
        m = ALS(rank=3, num_iterations=2).fit(R, mask)
        assert m.user_factors.shape == (40, 3)
        assert m.item_factors.shape == (30, 3)
        pred = m.predict([0, 1], [5, 7])
        assert pred.shape == (2,)

    def test_default_mask_is_nonzero(self, rng):
        R = np.zeros((8, 6), np.float32)
        R[0, 0], R[3, 4] = 2.0, -1.0
        m = ALS(rank=2, num_iterations=3).fit(R)
        assert np.isfinite(m.predict_all()).all()

    def test_seed_determinism(self, planted):
        R, mask = planted
        a = ALS(rank=4, num_iterations=3, seed=1).fit(R, mask)
        b = ALS(rank=4, num_iterations=3, seed=1).fit(R, mask)
        np.testing.assert_array_equal(a.user_factors, b.user_factors)

    def test_reg_shrinks_factors(self, planted):
        R, mask = planted
        small = ALS(rank=4, reg=0.01, num_iterations=5).fit(R, mask)
        big = ALS(rank=4, reg=100.0, num_iterations=5).fit(R, mask)
        assert (
            np.linalg.norm(big.user_factors)
            < np.linalg.norm(small.user_factors)
        )


class TestFeature:
    def test_standard_scaler_matches_numpy(self, rng):
        X = rng.normal(loc=3.0, scale=2.0, size=(200, 5)).astype(np.float32)
        Z = np.asarray(StandardScaler().fit_transform(X))
        np.testing.assert_allclose(Z.mean(axis=0), 0.0, atol=1e-5)
        np.testing.assert_allclose(Z.std(axis=0, ddof=1), 1.0, atol=1e-4)

    def test_standard_scaler_constant_column_safe(self):
        X = np.ones((10, 2), np.float32)
        Z = np.asarray(StandardScaler().fit_transform(X))
        assert np.isfinite(Z).all()

    def test_minmax_scaler(self, rng):
        X = rng.normal(size=(50, 3)).astype(np.float32)
        Z = np.asarray(MinMaxScaler(0.0, 1.0).fit_transform(X))
        np.testing.assert_allclose(Z.min(axis=0), 0.0, atol=1e-6)
        np.testing.assert_allclose(Z.max(axis=0), 1.0, atol=1e-6)

    def test_normalizer_l2(self, rng):
        X = rng.normal(size=(20, 4)).astype(np.float32)
        X[3] = 0.0  # zero row passes through
        Z = np.asarray(Normalizer(2.0).transform(X))
        norms = np.linalg.norm(Z, axis=1)
        np.testing.assert_allclose(np.delete(norms, 3), 1.0, atol=1e-5)
        np.testing.assert_array_equal(Z[3], 0.0)


class TestStats:
    def test_col_stats_matches_numpy(self, rng):
        X = rng.normal(size=(128, 4)).astype(np.float32)
        X[X < -1.2] = 0.0
        s = col_stats(X)
        assert s.count == 128
        np.testing.assert_allclose(s.mean, X.mean(axis=0), atol=1e-5)
        np.testing.assert_allclose(
            s.variance, X.var(axis=0, ddof=1), rtol=1e-4
        )
        np.testing.assert_array_equal(s.num_nonzeros, (X != 0).sum(axis=0))
        np.testing.assert_allclose(s.max, X.max(axis=0))
        np.testing.assert_allclose(s.min, X.min(axis=0))

    def test_col_stats_sharded_equals_local(self, rng, devices8):
        X = rng.normal(size=(160, 6)).astype(np.float32)
        mesh = make_mesh(8, devices=devices8)
        local = col_stats(X)
        dist = col_stats(X, mesh=mesh)
        assert dist.count == local.count
        np.testing.assert_allclose(dist.mean, local.mean, atol=1e-5)
        np.testing.assert_allclose(dist.variance, local.variance, rtol=1e-4)
        np.testing.assert_array_equal(dist.num_nonzeros, local.num_nonzeros)

    def test_pearson_matches_numpy(self, rng):
        X = rng.normal(size=(300, 4)).astype(np.float32)
        X[:, 2] = 2.0 * X[:, 0] + 0.01 * rng.normal(size=300)
        C = corr(X, "pearson")
        np.testing.assert_allclose(C, np.corrcoef(X.T), atol=1e-4)
        assert C[0, 2] > 0.99

    def test_spearman_rank_invariance(self, rng):
        x = rng.normal(size=200).astype(np.float32)
        X = np.column_stack([x, np.exp(x)])  # monotone transform
        C = corr(X, "spearman")
        assert C[0, 1] == pytest.approx(1.0, abs=1e-5)

    def test_spearman_handles_ties(self):
        X = np.column_stack([
            np.array([1, 1, 2, 2, 3, 3], np.float32),
            np.array([2, 2, 4, 4, 6, 6], np.float32),
        ])
        C = corr(X, "spearman")
        assert C[0, 1] == pytest.approx(1.0, abs=1e-6)

    def test_unknown_method(self):
        with pytest.raises(ValueError):
            corr(np.zeros((4, 2)), "kendall")


class TestImplicitALS:
    def test_implicit_ranks_positives_above_negatives(self):
        import numpy as np

        from asyncframework_tpu.ml.recommendation import ALS

        rs = np.random.default_rng(0)
        n_u, n_i, k = 60, 40, 4
        U = rs.normal(size=(n_u, k))
        V = rs.normal(size=(n_i, k))
        affinity = U @ V.T
        # observed interaction counts where affinity is high
        R = np.where(affinity > 0.8, rs.poisson(3.0, affinity.shape), 0)
        R = R.astype(np.float32)
        model = ALS(rank=k, reg=0.05, num_iterations=15, seed=1,
                    implicit_prefs=True, alpha=10.0).fit(R)
        scores = model.predict_all()
        pos = scores[R > 0]
        neg = scores[R == 0]
        # AUC-style separation: positives score above negatives
        from asyncframework_tpu.ml import BinaryClassificationMetrics

        y = np.concatenate([np.ones(len(pos)), np.zeros(len(neg))])
        s = np.concatenate([pos, neg])
        auc = BinaryClassificationMetrics(s, y).area_under_roc()
        assert auc > 0.85, auc

    def test_negative_ratings_do_not_nan(self):
        import numpy as np

        from asyncframework_tpu.ml.recommendation import ALS

        rs = np.random.default_rng(2)
        R = (rs.random((20, 15)) < 0.3).astype(np.float32) * 3.0
        R[0, 0] = -2.0  # a "dislike"
        m = ALS(rank=3, implicit_prefs=True, alpha=5.0,
                num_iterations=8).fit(R)
        assert np.isfinite(m.user_factors).all()
        assert np.isfinite(m.item_factors).all()

    def test_mask_rejected_in_implicit_mode(self):
        import numpy as np
        import pytest as _pytest

        from asyncframework_tpu.ml.recommendation import ALS

        R = np.ones((4, 4), np.float32)
        with _pytest.raises(ValueError, match="implicit"):
            ALS(implicit_prefs=True).fit(R, mask=np.ones((4, 4)))
