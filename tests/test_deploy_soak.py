"""Cluster soak (VERDICT r4 #5): one run composing every fault-tolerance
mechanism the deploy + DCN layers claim.

Parity bar: ``core/src/test/scala/org/apache/spark/DistributedSuite.scala:38``
(kill-things-mid-job integration) + ``deploy/master/Master.scala:41`` (HA).
The composition: HA master pair + 3 worker daemons schedule a DCN **asgd**
app AND a DCN **asaga** app concurrently (each PS + 2 gradient workers,
checkpointing, supervised); mid-run the test

1. SIGKILLs the active master  -> the standby wins the flock lease and
   serves with apps still RUNNING,
2. kill -9s the asgd PS        -> its worker daemon supervises it back up
   on the same coordinator port; it resumes from its checkpoint and the
   gradient workers reconnect,
3. kill -9s an asaga gradient-worker executor -> supervised relaunch
   rejoins the run.

Both apps must reach FINISHED with every (final) exit 0, the asgd summary
must prove the checkpoint resume, and both objectives must converge.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from asyncframework_tpu.deploy import Master, Worker, wait_app
from asyncframework_tpu.deploy.client import _client as client_for


def _wait(pred, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what}")


def _find_proc(workers, app_id, proc_id):
    for w in workers:
        with w._procs_lock:
            for p in w._procs.get(app_id, ()):
                if getattr(p, "async_proc_id", None) == proc_id:
                    return p
    return None


@pytest.mark.slow
@pytest.mark.soak
class TestClusterSoak:
    def test_soak_master_failover_ps_kill9_worker_kill9(
        self, tmp_path, capsys
    ):
        ck = str(tmp_path / "ck")
        # active master: real OS process so SIGKILL exercises the kernel's
        # flock release
        active = subprocess.Popen(
            [sys.executable, "-m", "asyncframework_tpu.deploy.master",
             "--port", "0", "--persistence-dir", str(tmp_path), "--ha"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        standby = None
        workers = []
        try:
            line = active.stdout.readline()
            active_addr = line.split()[-2 if "(ha)" in line else -1]
            a_host, a_port = active_addr.rsplit(":", 1)
            _wait(lambda: self._master_up(a_host, int(a_port)), 30,
                  "active master serving")

            standby = Master(persistence_dir=str(tmp_path),
                             worker_timeout_s=2.0, ha=True).start()
            workers = [
                Worker(a_host, int(a_port), worker_id=f"w{i}",
                       heartbeat_s=0.3,
                       standby_masters=[f"127.0.0.1:{standby.port}"],
                       launch_env_extra={"ASYNCTPU_FORCE_CPU": "1",
                                         "JAX_PLATFORMS": "cpu"}).start()
                for i in range(3)
            ]
            ha_addr = f"{active_addr},127.0.0.1:{standby.port}"
            cl = client_for(ha_addr)

            # two concurrent DCN apps, supervised + checkpointing: budgets
            # sized for ~90s of runway so all three faults land mid-run
            asgd_id = cl.submit(
                ["--quiet", "asgd", "synthetic", "synthetic",
                 "16", "4096", "8", "60000", "0.5", "2147483647", "0.3",
                 "0.5", "200", "0", "42", "--checkpoint-dir", ck],
                num_processes=3, supervise=True,
            )
            asaga_id = cl.submit(
                ["--quiet", "asaga", "synthetic", "synthetic",
                 "16", "4096", "8", "60000", "0.35", "2147483647", "0.3",
                 "0.5", "200", "0", "42", "--checkpoint-dir", ck],
                num_processes=3, supervise=True,
            )
            for app in (asgd_id, asaga_id):
                _wait(lambda a=app: cl.status(a)["state"] == "RUNNING",
                      60, f"{app} RUNNING")

            # fault 1 precondition: the asgd PS has checkpointed at least
            # once (so the kill -9 resume has something to resume from)
            ck_file = os.path.join(ck, "ps_asgd.npz")
            _wait(lambda: os.path.exists(ck_file), 120,
                  "first asgd PS checkpoint")

            # ---- fault 1: SIGKILL the active master
            active.send_signal(signal.SIGKILL)
            active.wait(timeout=10)
            _wait(lambda: standby.active, 30, "standby lease takeover")
            assert cl.status(asgd_id)["state"] == "RUNNING"
            assert cl.status(asaga_id)["state"] == "RUNNING"

            # ---- fault 2: kill -9 the asgd PARAMETER SERVER executor
            ps_proc = _find_proc(workers, asgd_id, 0)
            assert ps_proc is not None, "asgd PS executor not found"
            os.kill(ps_proc.pid, signal.SIGKILL)

            # ---- fault 3: kill -9 an asaga GRADIENT WORKER executor
            gw_proc = _find_proc(workers, asaga_id, 1)
            assert gw_proc is not None, "asaga worker executor not found"
            os.kill(gw_proc.pid, signal.SIGKILL)

            # supervision must bring replacements up (same proc ids)
            _wait(lambda: (p := _find_proc(workers, asgd_id, 0)) is not None
                  and p is not ps_proc, 60, "asgd PS supervised relaunch")
            _wait(lambda: (p := _find_proc(workers, asaga_id, 1)) is not None
                  and p is not gw_proc, 60, "asaga worker supervised relaunch")

            # ---- both apps run to FINISHED through all three faults
            st_asgd = wait_app(ha_addr, asgd_id, timeout_s=600.0)
            st_asaga = wait_app(ha_addr, asaga_id, timeout_s=600.0)
            assert st_asgd["state"] == "FINISHED", st_asgd
            assert st_asaga["state"] == "FINISHED", st_asaga
            assert len(st_asgd["exits"]) == 3
            assert len(st_asaga["exits"]) == 3
            assert all(rc == 0 for rc in st_asgd["exits"].values())
            assert all(rc == 0 for rc in st_asaga["exits"].values())

            # give the exit watchers a beat to flush proc-0 stdout
            time.sleep(1.0)
        finally:
            for w in workers:
                w.stop()
            if standby is not None:
                standby.stop()
            if active.poll() is None:
                active.kill()

        # ---- convergence + resume evidence from the PS summaries
        out = capsys.readouterr().out
        summaries = {}
        for ln in out.splitlines():
            if ln.startswith("{"):
                try:
                    rec = json.loads(ln)
                except ValueError:
                    continue
                if "driver" in rec:
                    summaries[rec["driver"]] = rec
        asgd = summaries.get("asgd-dcn-ps")
        asaga = summaries.get("asaga-dcn-ps")
        assert asgd is not None and asaga is not None, sorted(summaries)
        assert asgd["done"] is True and asaga["done"] is True
        assert asgd["accepted"] == 60000 and asaga["accepted"] == 60000
        # the killed PS provably resumed from its checkpoint
        assert asgd["resumed_from"] is not None and asgd["resumed_from"] >= 200
        # both objectives converged (synthetic d=16 starts near 1.0)
        assert asgd["final_objective"] is not None
        assert asgd["final_objective"] < 0.05, asgd
        assert asaga["final_objective"] is not None
        assert asaga["final_objective"] < 0.05, asaga

    @staticmethod
    def _master_up(host, port) -> bool:
        from asyncframework_tpu.deploy.client import MasterClient

        try:
            MasterClient(host, port).workers()
            return True
        except (ConnectionError, OSError):
            return False
