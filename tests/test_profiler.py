"""Continuous profiling plane (ISSUE 18).

The correctness spine:

- ONE declared zone table: the sampling classifier, the exact
  accumulators at the wire/merge/dispatch choke points, and the lint
  rule all reference ``profiler.ZONES`` -- grammar, uniqueness and the
  classifier's claims are asserted here;
- OFF is really off: ``zone()`` hands back the one shared no-op,
  ``wrap_dispatch()`` returns its argument UNCHANGED (identity
  asserted), and the wire is byte-identical per-op with profiling on
  vs off -- observation must not perturb the thing observed;
- the exact collectors attribute real nanoseconds at the real choke
  points (frame pump, XOR delta, CRC, quantize, compress), and the
  ``profile`` counter family rides the registry (``reset_totals()``
  clears it like every other family);
- THE acceptance: a delta-pull + int8-push DCN run decomposes into the
  five wire zones separately and non-zero, ``/api/status`` serves the
  ``profile`` section, ``bin/async-prof --collapsed`` emits valid
  flamegraph collapsed-stack input, and ``--diff`` between codec-on
  and codec-off arms shows ``wire.quantize`` only in the codec arm;
- the chaos rider (every ``bin/chaos_sweep.py`` seed): a SIGKILLed
  worker child's harvested flight dump carries a non-empty profile
  snapshot -- the post-mortem answers "where were the cycles going"
  even when the process cannot.
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu.conf import AsyncConf, set_global_conf
from asyncframework_tpu.metrics import flightrec, profiler, reset_totals
from asyncframework_tpu.net import frame, wirecodec, wiredelta
from asyncframework_tpu.net import reset_net_totals

pytestmark = pytest.mark.prof

CHILD = Path(__file__).parent / "ps_dcn_child.py"
CHAOS_SEED = int(os.environ.get("ASYNC_CHAOS_SEED", "7"))

#: zone-name grammar: a family, optionally one dotted sub-zone
_ZONE_RE = re.compile(r"^[a-z]+(\.[a-z_]+)?$")
#: flamegraph collapsed line: semicolon-joined file:func frames, a
#: space, a positive count (what flamegraph.pl / inferno consume)
_COLLAPSED_RE = re.compile(r"^[^ ;]+(;[^ ;]+)* [0-9]+$")

_FIVE_WIRE_ZONES = ("wire.encode", "wire.decode", "wire.xor",
                    "wire.crc", "wire.quantize")


@pytest.fixture(autouse=True)
def _clean_state():
    conf = AsyncConf()
    conf.set("async.metrics.interval.s", 0)
    set_global_conf(conf)
    profiler.uninstall()
    profiler._last_final = None
    flightrec.uninstall()
    reset_net_totals()
    yield
    profiler.uninstall()
    profiler._last_final = None
    flightrec.uninstall()
    reset_net_totals()
    set_global_conf(None)


def _pump_frames(n=4, payload=b"\xab" * 4096):
    """Drive n request frames through a real socketpair; returns the
    per-op frame-byte totals the run produced."""
    frame.reset_bytes_totals()
    a, b = socket.socketpair()
    try:
        for i in range(n):
            frame.send_msg(a, {"op": "PING", "i": i}, payload)
            hdr, pl = frame.recv_msg(b)
            assert hdr["op"] == "PING" and pl == payload
    finally:
        a.close()
        b.close()
    return frame.bytes_totals()


# -------------------------------------------------------------- zone table
class TestZoneTable:
    def test_grammar_unique_and_fallback_last(self):
        assert len(set(profiler.ZONES)) == len(profiler.ZONES)
        for z in profiler.ZONES:
            assert _ZONE_RE.match(z), z
        # the declared fallback is the classifier's last row AND a zone
        assert profiler._CLASSIFIER[-1].zone == "gil.other"
        assert profiler._CLASSIFIER[-1].path == ""
        assert profiler._WIRE_ZONES == tuple(
            z for z in profiler.ZONES if z.startswith("wire."))

    def test_every_classifier_zone_is_declared(self):
        for rule in profiler._CLASSIFIER:
            assert rule.zone in profiler.ZONES, rule.zone

    @pytest.mark.parametrize("filename,func,zone", [
        ("/x/asyncframework_tpu/net/wiredelta.py", "crc", "wire.crc"),
        ("/x/asyncframework_tpu/net/wiredelta.py", "encode", "wire.xor"),
        ("/x/asyncframework_tpu/net/wirecodec.py", "encode_grad",
         "wire.quantize"),
        ("/x/asyncframework_tpu/net/wirecodec.py", "compress_model_part",
         "wire.compress"),
        ("/x/asyncframework_tpu/net/frame.py", "recv_exact", "wire.decode"),
        ("/x/asyncframework_tpu/net/frame.py", "_send_frame", "wire.encode"),
        ("/x/asyncframework_tpu/parallel/ps_dcn.py", "_drain_merge_locked",
         "merge.drain"),
        ("/usr/lib/python3.11/json/encoder.py", "iterencode", "serde"),
        ("/site-packages/jax/_src/api.py", "cache_miss", "kernel.dispatch"),
        ("/site-packages/jaxlib/xla_client.py", "execute", "kernel.dispatch"),
    ])
    def test_classify_single_frame_stacks(self, filename, func, zone):
        assert profiler.classify_stack([(filename, func)]) == zone

    def test_unclaimed_stack_falls_back_to_gil_other(self):
        stack = [("/x/myapp/train.py", "loop"), ("/x/myapp/main.py", "main")]
        assert profiler.classify_stack(stack) == "gil.other"
        assert profiler.classify_stack([]) == "gil.other"

    def test_innermost_claimed_frame_wins(self):
        # crc running UNDER decode: innermost claim (crc) wins, matching
        # the "where are the cycles actually burning" reading
        stack = [
            ("/x/asyncframework_tpu/net/wiredelta.py", "crc"),
            ("/x/asyncframework_tpu/net/wiredelta.py", "decode"),
            ("/x/asyncframework_tpu/parallel/ps_dcn.py", "_handle_pull"),
        ]
        assert profiler.classify_stack(stack) == "wire.crc"
        # an unclaimed app frame above a claimed one does not mask it
        stack2 = [("/x/myapp/helper.py", "pack"),
                  ("/x/asyncframework_tpu/net/frame.py", "_send_frame")]
        assert profiler.classify_stack(stack2) == "wire.encode"


# ---------------------------------------------------------------- off path
class TestOffPath:
    def test_zone_is_the_shared_noop(self):
        for z in profiler.ZONES:
            assert profiler.zone(z) is profiler._NOOP_ZONE
        with profiler.zone("wire.encode"):
            pass  # must be usable as a context manager

    def test_wrap_dispatch_is_identity(self):
        def step(x):
            return x + 1
        assert profiler.wrap_dispatch(step, "kernel.dispatch") is step

    def test_zoned_passthrough_and_empty_totals(self):
        # the production zoned codecs run fine with no profiler and
        # leave the registry family empty
        buf = np.arange(64, dtype=np.float32)
        assert wiredelta.crc(buf) == wiredelta.crc(buf)
        assert profiler.profile_totals() == {}
        profiler.reset_profile_totals()  # no-op, must not raise
        assert profiler.last_snapshot() is None
        assert profiler.active() is None

    def test_zoned_rejects_undeclared_zone_at_decoration(self):
        with pytest.raises(ValueError, match="undeclared"):
            profiler.zoned("wire.bogus")

    def test_wire_byte_identical_prof_on_vs_off(self):
        """Observation must not perturb: the exact same frame exchange
        produces the exact same per-op byte totals with profiling on."""
        off = _pump_frames()
        profiler.install("t-onoff", hz=0)
        on = _pump_frames()
        assert on == off
        assert off.get("sent.PING", 0) > 0  # the comparison saw traffic


# -------------------------------------------------------- exact collectors
class TestExactCollectors:
    def test_frame_and_codec_zones_accumulate(self, rng):
        p = profiler.install("t-exact", hz=0)
        _pump_frames()
        d = 256
        basis = rng.normal(size=d).astype(np.float32)
        cur = (basis * 1.0001).astype(np.float32)
        payload = wiredelta.encode_xfull(cur, basis)
        out = wiredelta.decode(wiredelta.XFULL, payload, 0, basis,
                               wiredelta.crc(cur), None)
        assert out is not None
        g = (0.1 * rng.normal(size=d)).astype(np.float32)
        hdr, qpayload, _err = wirecodec.encode_grad(g, wirecodec.INT8, None)
        wirecodec.decode_grad(hdr, qpayload, d)
        chdr, cpayload = wirecodec.compress_model_part(
            wiredelta.XFULL, payload)
        wirecodec.decompress_model_part(chdr, cpayload)
        totals = p.totals()
        for z in ("wire.encode", "wire.decode", "serde", "wire.xor",
                  "wire.crc", "wire.quantize", "wire.compress"):
            assert totals.get(f"zone_ns.{z}", 0) > 0, z
            assert totals.get(f"zone_calls.{z}", 0) > 0, z
        # the snapshot folds the same totals into per-zone rows
        zones = p.snapshot()["zones"]
        assert zones["wire.xor"]["calls"] >= 2  # encode_xfull + decode

    def test_registry_reset_totals_resets_profile_family(self):
        p = profiler.install("t-registry", hz=0)
        with profiler.zone("wire.encode"):
            pass
        assert profiler.profile_totals().get("zone_calls.wire.encode") == 1
        reset_totals()  # the one whole-process reset every suite uses
        assert profiler.profile_totals() == {}
        assert p.totals() == {}

    def test_zone_ns_direct_bump(self):
        profiler.install("t-direct", hz=0)
        profiler.zone_ns("wire.encode", 1_000_000)
        t = profiler.profile_totals()
        assert t["zone_ns.wire.encode"] == 1_000_000
        assert t["zone_calls.wire.encode"] == 1

    def test_wrap_dispatch_compile_then_dispatch_accounting(self):
        p = profiler.install("t-dispatch", hz=0)
        calls = []

        def step(x):
            calls.append(x)
            return x
        w = profiler.wrap_dispatch(step, "kernel.dispatch", "unit_step")
        assert w is not step  # enabled path wraps
        for i in range(4):
            assert w(i) == i
        snap = p.snapshot()
        assert snap["compile"]["count"] == 1  # first call = trace+compile
        assert snap["dispatch"]["count"] == 3
        assert snap["dispatch"]["ns"] >= 0
        assert "unit_step" in snap["dispatch"]["ewma_ms"]
        # only dispatch calls feed the zone (compile is its own bucket)
        assert snap["zones"]["kernel.dispatch"]["calls"] == 3

    def test_memory_gauges_host_rss_always(self):
        mem = profiler.memory_gauges()
        assert mem["host_rss_bytes"] > 0


# ------------------------------------------------------------------ sampler
class TestSampler:
    def test_sample_once_classifies_and_collapses(self):
        p = profiler.Profiler("t-sampler", hz=0)
        n = p.sample_once()
        assert n >= 1  # at least this thread
        snap = p.snapshot()
        assert snap["samples"] == n
        assert sum(z["samples"] for z in snap["zones"].values()) == n
        assert snap["stacks"]
        for line in profiler.collapsed_lines(snap):
            assert _COLLAPSED_RE.match(line), line

    def test_sampler_skips_its_own_thread(self):
        p = profiler.Profiler("t-skip", hz=0)
        before = p.sample_once(skip_tid=threading.get_ident())
        all_threads = p.sample_once()
        assert all_threads == before + 1

    def test_stack_table_bounded_drop_not_evict(self):
        """Beyond stacks_max, NEW stacks are dropped (and counted), the
        resident hot stacks keep counting -- eviction would bias the
        long-running stacks out of the flamegraph."""
        p = profiler.Profiler("t-bound", hz=0, stacks_max=1)
        stop = threading.Event()

        def parked_in_a():
            stop.wait(10.0)

        def parked_in_b():
            stop.wait(10.0)
        threads = [threading.Thread(target=parked_in_a, daemon=True),
                   threading.Thread(target=parked_in_b, daemon=True)]
        for t in threads:
            t.start()
        time.sleep(0.05)  # both parked in distinctly-named frames
        try:
            p.sample_once()
            p.sample_once()
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=5.0)
        totals = p.totals()
        assert len(p.snapshot()["stacks"]) == 1
        assert totals.get("stack_overflow", 0) >= 1
        # the one resident stack kept counting on the second pass
        assert max(p.snapshot()["stacks"].values()) >= 2

    def test_background_sampler_thread_collects(self):
        p = profiler.install("t-thread", hz=251.0)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if p.totals().get("samples", 0) >= 5:
                break
            time.sleep(0.01)
        assert p.totals().get("samples", 0) >= 5
        snap = profiler.uninstall()
        # uninstall keeps the final snapshot for late flight dumps
        assert snap is not None and snap["samples"] >= 5
        assert profiler.last_snapshot() is snap


# ------------------------------------------------- status + flight + story
class TestStatusAndFlight:
    def test_api_status_profile_section_and_metrics_family(self):
        from asyncframework_tpu.metrics.live import LiveUIServer

        profiler.install("t-status", hz=0)
        with profiler.zone("wire.encode"):
            pass
        srv = LiveUIServer(None, port=0, role="t-status").start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(f"{base}/api/status",
                                        timeout=3.0) as r:
                snap = json.loads(r.read())
            assert snap["profile"]["role"] == "t-status"
            assert snap["profile"]["zones"]["wire.encode"]["calls"] == 1
            with urllib.request.urlopen(f"{base}/metrics",
                                        timeout=3.0) as r:
                body = r.read().decode()
            assert "async_profile_" in body  # the registry family rides
        finally:
            srv.stop()
        # after uninstall the section is gone, not erroring
        profiler.uninstall()
        srv2 = LiveUIServer(None, port=0, role="t-status2").start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{srv2.port}/api/status",
                    timeout=3.0) as r:
                snap2 = json.loads(r.read())
            assert "profile" not in snap2
        finally:
            srv2.stop()

    def test_flight_dump_embeds_profile_snapshot(self, tmp_path):
        profiler.install("t-flight", hz=0)
        with profiler.zone("merge.drain"):
            pass
        rec = flightrec.install("t-flight", str(tmp_path))
        dump = rec.snapshot("test")
        assert dump["profile"]["zones"]["merge.drain"]["calls"] == 1
        # a dump AFTER uninstall still carries the final snapshot
        profiler.uninstall()
        dump2 = rec.snapshot("late")
        assert dump2["profile"]["zones"]["merge.drain"]["calls"] == 1
        # and with no profiler ever installed the key is absent
        profiler._last_final = None
        assert "profile" not in rec.snapshot("never")

    def test_observer_harvest_persist_roundtrip(self, tmp_path):
        from asyncframework_tpu.metrics.observer import (
            RunHistoryStore,
            load_run,
        )

        profiler.install("t-hist", hz=0)
        with profiler.zone("wire.xor"):
            pass
        snap = profiler.active().snapshot()
        store = RunHistoryStore(str(tmp_path), "prof-run")
        dump = {"schema": 1, "role": "worker", "pid": 4242,
                "dumped_s": snap["dumped_s"], "events": [],
                "profile": snap}
        assert store.harvest(dump, "flight-worker-4242.json")
        profs = store.profile_snapshots()
        assert len(profs) == 1
        key = next(iter(profs))
        assert profs[key]["zones"]["wire.xor"]["calls"] == 1
        # stale re-harvest is a no-op; fresher dumped_s re-harvests
        assert not store.harvest_profile(dict(snap), "again")
        fresher = dict(snap, dumped_s=snap["dumped_s"] + 5.0)
        assert store.harvest_profile(fresher, "again")
        rd = store.persist()
        assert rd and os.path.isfile(
            os.path.join(rd, "profile", f"{key}.json"))
        loaded = load_run(rd)
        assert loaded["profile"][key]["zones"]["wire.xor"]["calls"] == 1
        assert key in loaded["meta"]["profile_snapshots"]
        assert key in store.summary()["profile_snapshots"]

    def test_top_renders_compact_zone_share_row(self):
        from asyncframework_tpu.metrics.top import render_profile_row

        section = {"samples": 200, "zones": {
            "wire.encode": {"samples": 120, "share": 0.6},
            "gil.other": {"samples": 80, "share": 0.4},
        }, "compile": {"count": 2, "ns": 3_000_000}}
        row = render_profile_row(section)
        assert "samples=200" in row
        assert "wire.encode 60%" in row
        assert "compile=2" in row
        # the observer's compact per-role block carries bare share floats
        row2 = render_profile_row(
            {"samples": 10, "zones": {"serde": 1.0}})
        assert "serde 100%" in row2


# ----------------------------------------------------------------- CLI
def _snapshot_with_traffic(role, rng, quantize):
    """One arm's worth of exact-collector traffic -> its snapshot."""
    profiler.uninstall()
    profiler.install(role, hz=0)
    _pump_frames(n=2)
    if quantize:
        g = (0.1 * rng.normal(size=64)).astype(np.float32)
        hdr, payload, _ = wirecodec.encode_grad(g, wirecodec.INT8, None)
        wirecodec.decode_grad(hdr, payload, 64)
    prof = profiler.active()
    prof.sample_once()
    snap = prof.snapshot()
    profiler.uninstall()
    return snap


class TestCLI:
    def test_collapsed_output_is_valid_flamegraph_input(self, tmp_path,
                                                        capsys, rng):
        snap = _snapshot_with_traffic("arm-a", rng, quantize=False)
        f = tmp_path / "snap.json"
        f.write_text(json.dumps(snap))
        assert profiler.main([str(f), "--collapsed"]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert out
        for line in out:
            assert _COLLAPSED_RE.match(line), line
        # counts sum to the snapshot's resident-stack samples
        assert (sum(int(ln.rsplit(" ", 1)[1]) for ln in out)
                == sum(snap["stacks"].values()))

    def test_diff_codec_arms_quantize_only_in_codec_on(self, tmp_path,
                                                       capsys, rng):
        """THE --diff acceptance: codec-on vs codec-off bench arms show
        wire.quantize only in the codec arm."""
        on = _snapshot_with_traffic("arm-int8", rng, quantize=True)
        off = _snapshot_with_traffic("arm-off", rng, quantize=False)
        bench_out = {"codec": {"int8": {"profile": on},
                               "off": {"profile": off}}}
        f = tmp_path / "bench.json"
        f.write_text(json.dumps(bench_out))
        loaded = profiler.load_profiles(str(f))
        assert set(loaded) == {"codec/int8", "codec/off"}
        assert profiler.main([str(f), "--diff", "--arm", "codec/int8",
                              "--arm-b", "codec/off", "--json"]) == 0
        d = json.loads(capsys.readouterr().out)
        assert "wire.quantize" in d["only_in_a"]
        assert "wire.quantize" not in d["only_in_b"]
        assert d["zones"]["wire.quantize"]["ms_a"] > 0

    def test_diff_over_one_source_requires_both_arms(self, tmp_path, rng):
        snap = _snapshot_with_traffic("arm-x", rng, quantize=False)
        f = tmp_path / "one.json"
        f.write_text(json.dumps({"profile": snap}))
        assert profiler.main([str(f), "--diff"]) == 2

    def test_empty_source_exits_2(self, tmp_path):
        f = tmp_path / "empty.json"
        f.write_text(json.dumps({"nothing": "here"}))
        assert profiler.main([str(f)]) == 2

    def test_load_profiles_reads_flight_dump_and_run_dir(self, tmp_path,
                                                         rng):
        snap = _snapshot_with_traffic("arm-d", rng, quantize=False)
        (tmp_path / "flight-x.json").write_text(
            json.dumps({"role": "worker", "events": [], "profile": snap}))
        (tmp_path / "raw.json").write_text(json.dumps(snap))
        (tmp_path / "junk.json").write_text(json.dumps([1, 2, 3]))
        loaded = profiler.load_profiles(str(tmp_path))
        assert set(loaded) == {"flight-x", "raw"}

    def test_bench_profile_block_never_dark_and_xcheck(self, rng):
        import bench

        # no profiler installed: an error record, not an exception
        profiler.uninstall()
        profiler._last_final = None
        blk = bench.profile_block(profiler, {})
        assert "error" in blk
        # installed: zone ms + the trace cross-check at the stated tol
        profiler.install("t-bench", hz=0)
        _pump_frames(n=2)
        blk = bench.profile_block(profiler, {})
        assert blk["zone_ms"].get("wire.encode", 0) > 0
        assert blk["trace_xcheck"]["ok"] is None  # no stages to check
        wire_ms = sum(v for z, v in blk["zone_ms"].items()
                      if z.startswith("wire."))
        stages = {"push": {"p50": wire_ms, "count": 1}}
        ok_blk = bench.profile_block(profiler, stages)
        assert ok_blk["trace_xcheck"]["ok"] is True
        bad = {"push": {"p50": wire_ms
                        / (10 * bench.PROFILE_TRACE_TOLERANCE + 1e-9),
                        "count": 1}}
        assert bench.profile_block(profiler, bad)["trace_xcheck"]["ok"] \
            is False


# ------------------------------------------------------------- acceptance
def _make_cfg(**kw):
    from asyncframework_tpu.solvers import SolverConfig

    defaults = dict(
        num_workers=2, num_iterations=400, gamma=0.5, taw=2 ** 31 - 1,
        batch_rate=0.3, bucket_ratio=0.0, printer_freq=100, seed=42,
        calibration_iters=4, run_timeout_s=60.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


class TestDCNAcceptance:
    def test_delta_int8_run_attributes_five_wire_zones(self, devices8):
        """THE in-process acceptance: a delta-pull + int8-push run over
        real sockets decomposes into the five wire zones, each
        separately attributed and non-zero."""
        from asyncframework_tpu.conf import global_conf
        from asyncframework_tpu.parallel import ps_dcn

        global_conf().set("async.pull.mode", "delta")
        profiler.install("t-dcn", hz=197.0)
        d = 256
        ps = ps_dcn.ParameterServer(_make_cfg(), d, 256,
                                    device=devices8[0], port=0).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="delta",
                                 push_codec="int8")
            rng = np.random.default_rng(CHAOS_SEED)
            for i in range(15):
                ts, _w, _avg, _cal = cl.pull(0)
                # one-hot pushes keep the model delta genuinely sparse
                # (the test_dataplane pattern): XDELTA pays only when
                # nnz*8 < d*4, and a dense push changes every coordinate
                g = np.zeros(d, np.float32)
                g[int(rng.integers(0, d))] = 0.5
                cl.push(0, ts, g)
            assert cl.pull_wenc.get("xdelta", 0) > 0, cl.pull_wenc
            cl.bye()
        finally:
            ps.stop()
        snap = profiler.active().snapshot()
        for z in _FIVE_WIRE_ZONES:
            assert snap["zones"].get(z, {}).get("ns", 0) > 0, (
                z, sorted(snap["zones"]))
            assert snap["zones"][z]["calls"] > 0, z
        # and the sampler ran alongside (statistical: just non-empty)
        assert snap["samples"] > 0
        assert snap["stacks"]

    def _worker(self, port, tmp, flight_dir):
        env = dict(os.environ)
        env.update({
            "PS_ROLE": "worker", "PS_PORT": str(port),
            "PS_WORKER_ID": "0", "PS_NUM_WORKER_PROCS": "1",
            "PS_NUM_ITER": "1000000", "PS_EVAL": "0",
            "JAX_PLATFORMS": "cpu",
            "PS_METRICS": "1",
            "ASYNCTPU_ASYNC_METRICS_PORT": "0",
            "ASYNCTPU_ASYNC_FLIGHT_DIR": flight_dir,
            "ASYNCTPU_ASYNC_FLIGHT_FLUSH_S": "0.2",
            "ASYNCTPU_ASYNC_PROF_ENABLED": "1",
            "ASYNCTPU_ASYNC_PROF_HZ": "97",
            "ASYNCTPU_ASYNC_PULL_MODE": "delta",
            "ASYNCTPU_ASYNC_CODEC_PUSH": "int8",
        })
        return subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.PIPE,
            stderr=open(os.path.join(tmp, "w0.stderr.log"), "w"),
            text=True,
        )

    def test_two_process_status_then_sigkill_flight_profile(
            self, tmp_path, devices8):
        """THE two-process acceptance + the chaos rider in one run: a
        real worker child (delta pulls, int8 pushes, profiling on)
        serves a per-role zone decomposition on its /api/status with
        the wire zones separately non-zero; then a seeded SIGKILL, and
        the harvested flight dump carries a non-empty profile snapshot.
        Rides every bin/chaos_sweep.py seed."""
        from asyncframework_tpu.conf import global_conf
        from asyncframework_tpu.metrics.observer import ClusterObserver
        from asyncframework_tpu.parallel import ps_dcn

        global_conf().set("async.pull.mode", "delta")
        flight_dir = str(tmp_path / "flight")
        cfg = _make_cfg(num_workers=8, num_iterations=10 ** 6, gamma=1.2,
                        printer_freq=50, calibration_iters=20,
                        run_timeout_s=120.0)
        profiler.install("ps", hz=97.0)  # PS side of the two-process run
        ps = ps_dcn.ParameterServer(cfg, 24, 4096, device=devices8[0],
                                    port=0).start()
        obs = ClusterObserver(interval_s=0.0, history_dir="",
                              flight_dirs=[flight_dir])
        worker = None
        try:
            worker = self._worker(ps.port, str(tmp_path), flight_dir)
            first = json.loads(worker.stdout.readline())
            mport = first["metrics_port"]
            assert mport, "child never announced its telemetry port"
            # seeded progress gate: enough pushes that every codec and
            # delta path has run on both sides
            need = 40 + (CHAOS_SEED % 30)
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                if sum(ps.accepted_by_wid.values()) >= need:
                    break
                time.sleep(0.02)
            else:
                pytest.fail("run never reached the seeded progress gate")
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/api/status",
                    timeout=5.0) as r:
                status = json.loads(r.read())
            wz = status["profile"]["zones"]
            # worker side: frame pump both ways + int8 quantize -- the
            # zones where a WORKER actually burns wire cycles.  The
            # XOR/CRC work of this run lives on the PS (dense D=24
            # training pushes keep XDELTA from paying, so the worker
            # never decodes a delta -- the PS still encodes and CRCs
            # every have-pull).
            for z in ("wire.encode", "wire.decode", "wire.quantize"):
                assert wz.get(z, {}).get("ns", 0) > 0, (z, sorted(wz))
            assert status["profile"]["role"].startswith("worker")
            # PS side of the SAME run: all five wire zones, separately
            # attributed and non-zero (frame pump, delta XOR encode,
            # version CRC, int8 decode_grad)
            pz = profiler.active().snapshot()["zones"]
            for z in _FIVE_WIRE_ZONES:
                assert pz.get(z, {}).get("ns", 0) > 0, (z, sorted(pz))
            # one flush cadence so the dump on disk is fresh, then kill
            time.sleep(0.5)
            os.kill(worker.pid, signal.SIGKILL)
            worker.wait(timeout=30.0)
            assert obs.harvest_flight() >= 1, (
                f"no dump harvested from {flight_dir}: "
                f"{os.listdir(flight_dir) if os.path.isdir(flight_dir) else 'missing'}")
            dumps = [d for d in obs.history.flight_dumps().values()
                     if d.get("pid") == worker.pid]
            assert dumps, "no flight dump from the SIGKILLed child"
            prof = dumps[0].get("profile")
            assert isinstance(prof, dict) and prof.get("zones"), (
                "flight dump carries no profile snapshot")
            assert prof["samples"] > 0
            assert any(z.startswith("wire.") for z in prof["zones"])
            # the harvest also folded it into the profile store
            assert obs.history.profile_snapshots()
        finally:
            if worker is not None and worker.poll() is None:
                worker.kill()
                worker.wait(timeout=10.0)
            if worker is not None and worker.stdout:
                worker.stdout.close()
            ps.stop()
