"""Seeded chaos fabric for the DCN plane (ISSUE 1 acceptance).

The old soak story was "kill -9 and hope"; this suite drives the SAME
failure modes through ``net/faults.py``'s deterministic schedules instead:
connection-refused at the dial, requests cut mid-frame, replies stalled,
and the retry-poison case -- replies dropped strictly AFTER the server
applied the op.  Every run asserts the exactly-once ledger (server-side
dedup counters) and the flagship run replays byte-identically.

Determinism discipline: chaos legs run ONE client op-stream per endpoint
(single DCN worker, serial topic/master clients), because the schedule
keys on (endpoint, op, nth-occurrence) and a deterministic nth needs a
deterministic op order.  Multi-worker chaos stays in the (slow-marked)
kill -9 soak, which asserts liveness rather than bytes.
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.net import faults, retry
from asyncframework_tpu.net import frame as frame_mod
from asyncframework_tpu.net.faults import (
    CONNECT_OP,
    CONNECT_REFUSED,
    CUT_MID_FRAME,
    DROP_REPLY,
    STALL_READ,
    FaultSchedule,
)
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.solvers import SolverConfig

pytestmark = pytest.mark.chaos

# One knob pins the whole suite's schedules + retry jitter; the nightly
# sweep (bin/chaos_sweep.py) runs the suite across a seed range via env.
CHAOS_SEED = int(os.environ.get("ASYNC_CHAOS_SEED", "7"))


@pytest.fixture(autouse=True)
def _clean_net_state():
    """Breakers are process-global by endpoint and ephemeral ports recycle;
    chaos runs must neither inherit nor leak trip state (or schedules)."""
    retry.reset_breakers()
    faults.clear()
    yield
    retry.reset_breakers()
    faults.clear()


@pytest.fixture(autouse=True)
def _lockwatch_on():
    """Debug lock watchdog (net/lockwatch.py) armed for the whole chaos
    suite: every PS constructed here gets a watched model lock, so any
    socket send/recv under it -- the contention the lock-free PULL path
    removed -- fails the test at the frame choke point instead of
    surviving as a silent regression.  Teardown additionally asserts the
    lock-order race detector saw NO acquisition-order cycle among the
    watched locks (ps.model / ps.stats / ps.versions /
    supervisor.members): a cycle is a potential deadlock that a chaos
    interleaving would eventually hit for real."""
    from asyncframework_tpu.net import lockwatch

    lockwatch.reset_totals()
    lockwatch.enable(True)
    try:
        yield
        lockwatch.assert_no_cycles()
    finally:
        lockwatch.enable(False)
        lockwatch.reset_totals()


def make_cfg(**kw):
    defaults = dict(
        num_workers=1, num_iterations=30, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.0, printer_freq=10, seed=42,
        calibration_iters=4, run_timeout_s=60.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


def _chaos_asgd_run(devices, extra_events=None):
    """One single-worker ASGD-over-DCN run under a seeded schedule hitting
    the PS with all four fault kinds.  Returns the replay fingerprint."""
    cfg = make_cfg()
    n, d = 256, 8
    ds = ShardedDataset.generate_on_device(n, d, 1, devices=devices[:1],
                                           seed=11, noise=0.01)
    ps = ps_dcn.ParameterServer(cfg, d, n, device=devices[0], port=0).start()
    ep = f"127.0.0.1:{ps.port}"
    sched = FaultSchedule(seed=CHAOS_SEED)
    sched.add(ep, CONNECT_OP, 1, CONNECT_REFUSED)   # first dial refused
    sched.add(ep, "PULL", 3, STALL_READ)            # model reply stalls
    sched.add(ep, "PUSH", 2, CUT_MID_FRAME)         # gradient cut on wire
    sched.add(ep, "PUSH", 5, DROP_REPLY)            # applied, ACK eaten
    for ev in extra_events or ():
        sched.add(ep, *ev)
    try:
        with faults.injected(sched) as inj:
            counts = ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, [0], {0: ds.shard(0)}, cfg, d, n,
                deadline_s=60.0,
            )
            assert ps.wait_done(timeout_s=5.0)
        _times, W = ps.snapshot_stack()
        fired = tuple((e["op"], e["nth"], e["kind"]) for e in inj.fired)
        return {
            "final_w": W[-1].tobytes(),
            "accepted": ps.accepted,
            "dropped": ps.dropped,
            "max_staleness": ps.max_staleness,
            "dedup_hits": ps.dedup_hits,
            "counts": dict(counts),
            "fired": fired,
            "remaining": len(inj.remaining()),
        }
    finally:
        ps.stop()


def _chaos_asaga_run(devices, extra_events=None):
    """Single-worker DCN-ASAGA under a schedule keyed on the SAGA verbs
    (PULL_SAGA/PUSH_SAGA ride their own ops precisely so schedules can
    target them).  Exercises the PS-owned sampling + history-table commit
    under every fault kind; returns the replay fingerprint."""
    cfg = make_cfg(gamma=0.35)
    n, d = 256, 8
    ds = ShardedDataset.generate_on_device(n, d, 1, devices=devices[:1],
                                           seed=11, noise=0.01)
    ps = ps_dcn.ParameterServer(cfg, d, n, device=devices[0], port=0,
                                algo="asaga").start()
    ep = f"127.0.0.1:{ps.port}"
    sched = FaultSchedule(seed=CHAOS_SEED)
    sched.add(ep, CONNECT_OP, 1, CONNECT_REFUSED)     # first dial refused
    sched.add(ep, "PULL_SAGA", 3, STALL_READ)         # sampled reply stalls
    sched.add(ep, "PUSH_SAGA", 2, CUT_MID_FRAME)      # gradient+scalars cut
    sched.add(ep, "PUSH_SAGA", 5, DROP_REPLY)         # applied, ACK eaten
    for ev in extra_events or ():
        sched.add(ep, *ev)
    try:
        with faults.injected(sched) as inj:
            counts = ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, [0], {0: ds.shard(0)}, cfg, d, n,
                deadline_s=60.0, algo="asaga",
            )
            assert ps.wait_done(timeout_s=5.0)
        _times, W = ps.snapshot_stack()
        fired = tuple((e["op"], e["nth"], e["kind"]) for e in inj.fired)
        table = ps._table.get(0)
        return {
            "final_w": W[-1].tobytes(),
            "table": table.tobytes() if table is not None else b"",
            "accepted": ps.accepted,
            "dropped": ps.dropped,
            "max_staleness": ps.max_staleness,
            "dedup_hits": ps.dedup_hits,
            "counts": dict(counts),
            "fired": fired,
            "remaining": len(inj.remaining()),
        }
    finally:
        ps.stop()


class _FakeWorkerDaemon:
    """ACKs the master's LAUNCH/KILL orders without forking anything --
    the master leg of the chaos fabric needs a schedulable worker, not a
    real executor."""

    def __init__(self):
        self._srv = socket.create_server(("127.0.0.1", 0))
        self._srv.settimeout(0.2)
        self.host, self.port = self._srv.getsockname()
        self._stop = threading.Event()
        self.launches = []
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                msg, _ = frame_mod.recv_msg(conn)
                if msg.get("op") == "LAUNCH":
                    self.launches.append(msg["app_id"])
                frame_mod.send_msg(conn, {"op": "ACK"})
            except (ConnectionError, OSError):
                pass
            finally:
                conn.close()

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


class TestChaosAcceptance:
    def test_chaos_fabric_across_ps_topic_and_master(self, devices8,
                                                     tmp_path):
        """The acceptance run: one seeded schedule with >=1
        connection-refused, >=1 mid-frame cut, and >=1
        reply-dropped-after-apply spread across the PS, the topic server,
        and the master -- ASGD completes with correct final state and the
        dedup ledgers show zero duplicated APPENDs / PUSHes / SUBMITs."""
        from asyncframework_tpu.deploy.client import MasterClient
        from asyncframework_tpu.deploy.master import Master
        from asyncframework_tpu.streaming.log_net import (
            LogTopicServer,
            RemoteLogTopic,
        )

        # --- PS leg: the full four-kind schedule, run to completion
        out = _chaos_asgd_run(devices8)
        assert out["remaining"] == 0, "every scheduled fault must fire"
        assert out["accepted"] == 30
        assert out["dedup_hits"] == 1     # exactly the DROP_REPLY push
        kinds = {k for (_op, _n, k) in out["fired"]}
        assert kinds == {CONNECT_REFUSED, STALL_READ, CUT_MID_FRAME,
                         DROP_REPLY}
        # the run actually descended (correct final state, not just "done")
        assert np.isfinite(
            np.frombuffer(out["final_w"], np.float32)
        ).all()

        # --- topic leg: drop the APPENDED reply after apply, cut the
        # retry mid-frame, refuse one reconnect dial -- the log must hold
        # each record exactly once (the round-5 duplicate-APPEND bug)
        srv = LogTopicServer(str(tmp_path / "topics"), host="127.0.0.1")
        srv.start()
        tep = f"127.0.0.1:{srv.port}"
        tsched = (FaultSchedule(seed=CHAOS_SEED)
                  .add(tep, "APPEND", 1, DROP_REPLY)
                  .add(tep, CONNECT_OP, 2, CONNECT_REFUSED)
                  .add(tep, "APPEND", 2, CUT_MID_FRAME))
        try:
            with faults.injected(tsched) as inj:
                t = RemoteLogTopic("127.0.0.1", srv.port, "orders")
                first, nxt = t.append_many([{"i": i} for i in range(10)])
                assert (first, nxt) == (0, 10)
                first2, nxt2 = t.append_many([{"i": i} for i in range(10, 20)])
                assert (first2, nxt2) == (10, 20)
                records, _ = t.read(0)
                t.close()
            assert inj.remaining() == []
            assert [r["i"] for r in records] == list(range(20))
            assert srv.dedup_hits == 1  # the dropped-reply APPEND's retry
        finally:
            srv.stop()

        # --- master leg: SUBMITTED reply dropped after the app was
        # scheduled; the retried SUBMIT must be answered from the dedup
        # window -- exactly one app, same app_id
        master = Master(port=0)
        fake = _FakeWorkerDaemon()
        try:
            master.start()
            mep = f"127.0.0.1:{master.port}"
            # register the fake worker through the real protocol
            with frame_mod.connect((master.host, master.port)) as s:
                frame_mod.send_msg(s, {
                    "op": "REGISTER_WORKER", "worker_id": "fw-1",
                    "host": fake.host, "port": fake.port, "cores": 1,
                })
                reply, _ = frame_mod.recv_msg(s)
            assert reply["op"] == "REGISTERED"
            msched = FaultSchedule(seed=CHAOS_SEED).add(
                mep, "SUBMIT_APP", 1, DROP_REPLY)
            with faults.injected(msched) as inj:
                cl = MasterClient(master.host, master.port)
                app_id = cl.submit(["--quiet", "noop"], num_processes=1)
            assert inj.remaining() == []
            assert list(master.apps) == [app_id]  # exactly one app
            assert master.dedup_hits == 1
            assert fake.launches == [app_id]      # launched exactly once
        finally:
            fake.stop()
            master.stop()

    def test_chaos_replay_is_byte_identical(self, devices8):
        """Same schedule, same seeds -> same fired-fault journal, same
        accept/drop/staleness ledger, byte-identical final weights."""
        a = _chaos_asgd_run(devices8)
        retry.reset_breakers()
        b = _chaos_asgd_run(devices8)
        assert a["fired"] == b["fired"]
        assert (a["accepted"], a["dropped"], a["max_staleness"],
                a["dedup_hits"]) == (b["accepted"], b["dropped"],
                                     b["max_staleness"], b["dedup_hits"])
        assert a["counts"] == b["counts"]
        assert a["final_w"] == b["final_w"]


class TestHeartbeatShardRecoveryChaos:
    def test_ps_cut_mid_wave_replays_same_ledger(self, devices8):
        """A PULL cut mid-frame while the cohort wave is forming, plus a
        stalled wave reply: the degraded run must reach the same
        accepted/dropped/max-staleness counts on replay (MULTICHIP-style
        determinism)."""
        extra = [("PULL", 5, CUT_MID_FRAME), ("PULL", 7, STALL_READ)]
        a = _chaos_asgd_run(devices8, extra_events=extra)
        retry.reset_breakers()
        b = _chaos_asgd_run(devices8, extra_events=extra)
        assert a["remaining"] == b["remaining"] == 0
        assert (a["accepted"], a["dropped"], a["max_staleness"]) == \
               (b["accepted"], b["dropped"], b["max_staleness"])
        assert a["final_w"] == b["final_w"]

    def test_heartbeat_loss_and_recovery_deterministic_under_faults(
            self, devices8):
        """Engine-plane failure handling keeps working (and stays
        deterministic) while a network fault injector is live: a killed
        executor is declared lost by the HeartbeatMonitor and its shard
        re-homes to the same adopter on every run.  The pending network
        events must NOT fire -- the engine plane never touches the DCN
        framing."""
        from asyncframework_tpu.engine import JobScheduler, ShardRecovery
        from asyncframework_tpu.engine import plan_reassignment
        from asyncframework_tpu.engine.heartbeat import HeartbeatMonitor

        def run_once():
            with faults.injected(FaultSchedule().add(
                    "*", "PUSH", 1, CUT_MID_FRAME)) as inj:
                ds = ShardedDataset.generate_on_device(
                    64, 4, 4, devices=devices8[:4], seed=5)
                rec = ShardRecovery(ds, devices8[:4])
                js = JobScheduler(num_workers=4)
                lost = []
                try:
                    mon = HeartbeatMonitor(js.pool, on_executor_lost=lost.append,
                                           timeout_ms=1000.0)
                    js.pool.executors[1].kill()
                    js.pool.executors[3].kill()
                    flagged = mon.check_once()
                    plan = plan_reassignment(range(4), dead=flagged)
                    rec.apply(plan)
                    owners = {sid: rec.owner(sid) for sid in range(4)}
                finally:
                    js.shutdown()
                assert inj.fired == []  # engine plane is DCN-fault-proof
                return tuple(sorted(flagged)), tuple(sorted(plan.moves.items())), \
                    tuple(sorted(owners.items()))

        assert run_once() == run_once()


class TestSagaChaos:
    """PR 1 left the ASAGA wire untested under faults; the SAGA ops now
    ride their own verbs (PULL_SAGA/PUSH_SAGA) so schedules can hit them
    without counting ASGD traffic."""

    def test_saga_ops_survive_all_four_fault_kinds(self, devices8):
        out = _chaos_asaga_run(devices8)
        assert out["remaining"] == 0, "every scheduled fault must fire"
        assert out["accepted"] == 30
        # exactly the DROP_REPLY push answered from the dedup window: the
        # retried gradient+scalars were NOT committed twice
        assert out["dedup_hits"] == 1
        ops = {op for (op, _n, _k) in out["fired"] if op != CONNECT_OP}
        assert ops == {"PULL_SAGA", "PUSH_SAGA"}
        kinds = {k for (_op, _n, k) in out["fired"]}
        assert kinds == {CONNECT_REFUSED, STALL_READ, CUT_MID_FRAME,
                         DROP_REPLY}
        assert np.isfinite(np.frombuffer(out["final_w"], np.float32)).all()
        assert np.any(np.frombuffer(out["table"], np.float32) != 0.0)

    def test_saga_chaos_replay_is_byte_identical(self, devices8):
        """Same schedule, same seeds -> same fired journal, same ledger,
        byte-identical final weights AND history table (the PS-side RNG
        chain advanced identically through the faults)."""
        a = _chaos_asaga_run(devices8)
        retry.reset_breakers()
        b = _chaos_asaga_run(devices8)
        assert a["fired"] == b["fired"]
        assert (a["accepted"], a["dropped"], a["max_staleness"],
                a["dedup_hits"]) == (b["accepted"], b["dropped"],
                                     b["max_staleness"], b["dedup_hits"])
        assert a["counts"] == b["counts"]
        assert a["final_w"] == b["final_w"]
        assert a["table"] == b["table"]

    def test_op_alternation_matches_either_saga_or_dense_push(self):
        ev_sched = FaultSchedule().add("*", "PUSH|PUSH_SAGA", 2, DROP_REPLY)
        ev = ev_sched.events[0]
        assert ev.matches("h:1", "PUSH") and ev.matches("h:1", "PUSH_SAGA")
        assert not ev.matches("h:1", "PULL_SAGA")


class TestReplyDropSpansPSRestart:
    def test_push_retry_across_restart_applied_exactly_once(
            self, devices8, tmp_path):
        """The case PR 1 explicitly left open (its dedup windows were
        in-memory): a PUSH is applied, its reply is DROPPED by the fault
        injector, the PS is killed (nothing flushed past its cadence
        checkpoint) and restarted from that checkpoint -- the retried
        (sid, seq) PUSH must be answered from the RESTORED dedup window,
        applied exactly once across both lives."""
        from asyncframework_tpu.net.session import ClientSession

        cfg = make_cfg(printer_freq=1)   # checkpoint after every accept
        n, d = 256, 8
        ckpt = str(tmp_path / "ps.npz")
        ps1 = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0], port=0,
                                     checkpoint_path=ckpt).start()
        ep = f"127.0.0.1:{ps1.port}"
        sess = ClientSession()
        hdr = sess.stamp({"op": "PUSH", "wid": 0, "ts": 0})
        g = np.full(d, 0.25, np.float32).tobytes()
        sched = FaultSchedule(seed=CHAOS_SEED).add(ep, "PUSH", 1, DROP_REPLY)
        with faults.injected(sched) as inj:
            s = frame_mod.connect(("127.0.0.1", ps1.port))
            frame_mod.send_msg(s, hdr, g)
            with pytest.raises((ConnectionError, OSError)):
                frame_mod.recv_msg(s)   # applied server-side; reply eaten
            s.close()
            assert inj.remaining() == []
        # wait for the cadence checkpoint that CONTAINS the applied push
        # (model k=1 and its dedup entry captured under one lock)
        deadline = time.monotonic() + 30
        meta = None
        while time.monotonic() < deadline:
            if os.path.exists(ckpt):
                try:
                    with np.load(ckpt, allow_pickle=False) as z:
                        meta = json.loads(str(z["__meta__"]))
                    if meta["k"] >= 1 and meta.get("dedup", {}).get(
                            "sessions"):
                        break
                except (OSError, ValueError, KeyError):
                    pass
            time.sleep(0.02)
        assert meta is not None and meta["k"] == 1, meta
        w1 = np.asarray(ps1._w).copy()
        ps1.stop()   # kill -9 analog: nothing beyond the checkpoint

        ps2 = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0], port=0,
                                     checkpoint_path=ckpt).start()
        try:
            assert ps2.resumed_from_k == 1
            # the retry, spanning the restart: same (sid, seq), same bytes
            s2 = frame_mod.connect(("127.0.0.1", ps2.port))
            frame_mod.send_msg(s2, hdr, g)
            ack, _ = frame_mod.recv_msg(s2)
            s2.close()
            assert ack["op"] == "ACK" and ack["accepted"] is True
            assert ps2.dedup_hits == 1          # answered from the window
            assert ps2.accepted == 1            # applied exactly once
            assert ps2._clock == 1              # not even a merge tick
            np.testing.assert_array_equal(np.asarray(ps2._w), w1)
        finally:
            ps2.stop()
