"""Round-3 SQL language breadth (VERDICT item 5), pandas-oracle tested:
set operations, CASE WHEN, subqueries (scalar / IN / FROM), CTEs,
LIKE/BETWEEN/CAST/IS NULL, the scalar function library, UDFs, and reader
projection/predicate pushdown.

Parity: AstBuilder.scala constructs + Optimizer.scala:38's data-source
pruning rules (pushdown happens in the readers here -- the execution layer
is eager, so reader-level pruning IS the optimizer surface that matters).
"""

import numpy as np
import pandas as pd
import pytest

from asyncframework_tpu.sql import ColumnarFrame, SQLContext
from asyncframework_tpu.sql.expressions import col, lit, when
from asyncframework_tpu.sql.io import read_csv, read_parquet


@pytest.fixture()
def ctx():
    c = SQLContext()
    c.register("t", ColumnarFrame({
        "k": np.asarray(["a", "b", "c", "d", "a"], object),
        "v": np.asarray([1.0, 2.0, 3.0, 4.0, 5.0], np.float32),
        "n": np.asarray([10, 20, 30, 40, 50], np.int32),
    }))
    c.register("u", ColumnarFrame({
        "k": np.asarray(["a", "b", "x"], object),
        "v": np.asarray([1.0, 9.0, 9.0], np.float32),
        "n": np.asarray([10, 99, 99], np.int32),
    }))
    return c


def pdf(frame) -> pd.DataFrame:
    return pd.DataFrame({c: np.asarray(frame[c]) for c in frame.columns})


class TestSetOps:
    def test_union_all_and_union(self, ctx):
        out = ctx.sql("SELECT k, v FROM t UNION ALL SELECT k, v FROM u")
        a = pd.DataFrame({"k": ["a", "b", "c", "d", "a"],
                          "v": [1.0, 2, 3, 4, 5]})
        b = pd.DataFrame({"k": ["a", "b", "x"], "v": [1.0, 9, 9]})
        want = pd.concat([a, b], ignore_index=True)
        pd.testing.assert_frame_equal(
            pdf(out), want, check_dtype=False
        )
        out2 = ctx.sql("SELECT k, v FROM t UNION SELECT k, v FROM u")
        want2 = want.drop_duplicates()
        assert sorted(map(tuple, pdf(out2).values.tolist())) == sorted(
            map(tuple, want2.values.tolist())
        )

    def test_except_and_intersect(self, ctx):
        out = ctx.sql("SELECT k, v FROM t EXCEPT SELECT k, v FROM u")
        got = sorted(map(tuple, pdf(out).values.tolist()))
        assert got == [("a", 5.0), ("b", 2.0), ("c", 3.0), ("d", 4.0)]
        out2 = ctx.sql("SELECT k, v FROM t INTERSECT SELECT k, v FROM u")
        assert sorted(map(tuple, pdf(out2).values.tolist())) == [("a", 1.0)]

    def test_union_column_name_mismatch_rejected(self, ctx):
        with pytest.raises(ValueError, match="matching columns"):
            ctx.sql("SELECT k FROM t UNION SELECT v FROM u")


class TestCaseWhen:
    def test_searched_case(self, ctx):
        out = ctx.sql(
            "SELECT k, CASE WHEN v < 2 THEN 0 WHEN v < 4 THEN 1 "
            "ELSE 2 END AS bucket FROM t"
        )
        v = np.array([1.0, 2, 3, 4, 5])
        want = np.where(v < 2, 0, np.where(v < 4, 1, 2))
        np.testing.assert_array_equal(np.asarray(out["bucket"]), want)

    def test_simple_case(self, ctx):
        out = ctx.sql(
            "SELECT CASE k WHEN 'a' THEN 1 ELSE 0 END AS is_a FROM t"
        )
        np.testing.assert_array_equal(
            np.asarray(out["is_a"]), [1, 0, 0, 0, 1]
        )

    def test_case_without_else_yields_nan(self, ctx):
        out = ctx.sql("SELECT CASE WHEN v > 4 THEN v END AS big FROM t")
        got = np.asarray(out["big"])
        assert np.isnan(got[:4]).all() and got[4] == 5.0

    def test_case_in_where(self, ctx):
        out = ctx.sql(
            "SELECT k FROM t WHERE CASE WHEN v > 3 THEN 1 ELSE 0 END = 1"
        )
        assert list(np.asarray(out["k"])) == ["d", "a"]


class TestSubqueries:
    def test_scalar_subquery(self, ctx):
        out = ctx.sql("SELECT k, v FROM t WHERE v > (SELECT AVG(v) FROM t)")
        assert list(np.asarray(out["k"])) == ["d", "a"]

    def test_in_subquery(self, ctx):
        out = ctx.sql("SELECT k, v FROM t WHERE k IN (SELECT k FROM u)")
        assert list(np.asarray(out["k"])) == ["a", "b", "a"]

    def test_not_in_subquery(self, ctx):
        out = ctx.sql("SELECT k FROM t WHERE k NOT IN (SELECT k FROM u)")
        assert list(np.asarray(out["k"])) == ["c", "d"]

    def test_from_subquery(self, ctx):
        out = ctx.sql(
            "SELECT k, doubled FROM "
            "(SELECT k, v * 2 AS doubled FROM t WHERE v >= 3) big "
            "ORDER BY doubled DESC"
        )
        assert list(np.asarray(out["doubled"])) == [10.0, 8.0, 6.0]

    def test_in_literal_list(self, ctx):
        out = ctx.sql("SELECT v FROM t WHERE k IN ('a', 'c')")
        assert list(np.asarray(out["v"])) == [1.0, 3.0, 5.0]


class TestCTE:
    def test_single_cte(self, ctx):
        out = ctx.sql(
            "WITH big AS (SELECT k, v FROM t WHERE v > 2) "
            "SELECT SUM(v) AS s FROM big"
        )
        assert float(np.asarray(out["s"])[0]) == 12.0

    def test_chained_ctes_and_shadowing(self, ctx):
        out = ctx.sql(
            "WITH a AS (SELECT k, v FROM t WHERE v > 1), "
            "     b AS (SELECT k, v FROM a WHERE v < 5) "
            "SELECT k FROM b ORDER BY k"
        )
        assert list(np.asarray(out["k"])) == ["b", "c", "d"]
        # 'a' shadowed any registered table only within that statement
        with pytest.raises(KeyError):
            ctx.sql("SELECT * FROM a")

    def test_cte_with_set_op(self, ctx):
        out = ctx.sql(
            "WITH all_rows AS (SELECT k FROM t UNION SELECT k FROM u) "
            "SELECT COUNT(*) AS c FROM "
            "(SELECT k, 1 AS one FROM all_rows) x"
        )
        assert int(np.asarray(out["c"])[0]) == 5  # a b c d x


class TestPredicates:
    def test_between(self, ctx):
        out = ctx.sql("SELECT k FROM t WHERE v BETWEEN 2 AND 4")
        assert list(np.asarray(out["k"])) == ["b", "c", "d"]
        out2 = ctx.sql("SELECT k FROM t WHERE v NOT BETWEEN 2 AND 4")
        assert list(np.asarray(out2["k"])) == ["a", "a"]

    def test_like(self, ctx):
        c = SQLContext()
        c.register("s", ColumnarFrame({
            "name": np.asarray(
                ["spark", "flink", "sparrow", "stork", "ray"], object
            ),
            "x": np.arange(5, dtype=np.int32),
        }))
        out = c.sql("SELECT name FROM s WHERE name LIKE 'spar%'")
        assert list(np.asarray(out["name"])) == ["spark", "sparrow"]
        out2 = c.sql("SELECT name FROM s WHERE name LIKE '_tork'")
        assert list(np.asarray(out2["name"])) == ["stork"]
        out3 = c.sql("SELECT name FROM s WHERE name NOT LIKE '%r%'")
        assert list(np.asarray(out3["name"])) == ["flink"]

    def test_cast(self, ctx):
        out = ctx.sql("SELECT CAST(v AS int) AS vi FROM t")
        assert list(np.asarray(out["vi"])) == [1, 2, 3, 4, 5]
        out2 = ctx.sql("SELECT CAST(n AS string) AS ns FROM t LIMIT 2")
        assert list(np.asarray(out2["ns"])) == ["10", "20"]

    def test_is_null(self):
        c = SQLContext()
        c.register("m", ColumnarFrame({
            "v": np.asarray([1.0, np.nan, 3.0], np.float32),
            "i": np.asarray([1, 2, 3], np.int32),
        }))
        out = c.sql("SELECT i FROM m WHERE v IS NULL")
        assert list(np.asarray(out["i"])) == [2]
        out2 = c.sql("SELECT i FROM m WHERE v IS NOT NULL")
        assert list(np.asarray(out2["i"])) == [1, 3]


class TestFunctionsAndUDFs:
    def test_math_functions(self, ctx):
        out = ctx.sql(
            "SELECT ABS(1 - v) AS a, SQRT(v) AS s, ROUND(v / 2) AS r FROM t"
        )
        v = np.array([1.0, 2, 3, 4, 5], np.float32)
        np.testing.assert_allclose(np.asarray(out["a"]), np.abs(1 - v))
        np.testing.assert_allclose(
            np.asarray(out["s"]), np.sqrt(v), rtol=1e-6
        )
        np.testing.assert_allclose(np.asarray(out["r"]), np.round(v / 2))

    def test_string_functions(self, ctx):
        out = ctx.sql(
            "SELECT UPPER(k) AS ku, LENGTH(k) AS kl, "
            "CONCAT(k, '_', CAST(n AS string)) AS tag FROM t LIMIT 2"
        )
        assert list(np.asarray(out["ku"])) == ["A", "B"]
        assert list(np.asarray(out["kl"])) == [1, 1]
        assert list(np.asarray(out["tag"])) == ["a_10", "b_20"]

    def test_substr_and_coalesce(self):
        c = SQLContext()
        c.register("s", ColumnarFrame({
            "w": np.asarray(["hello", "world"], object),
            "v": np.asarray([np.nan, 2.0], np.float32),
        }))
        out = c.sql("SELECT SUBSTR(w, 2, 3) AS mid, "
                    "COALESCE(v, 0) AS v0 FROM s")
        assert list(np.asarray(out["mid"])) == ["ell", "orl"]
        np.testing.assert_allclose(np.asarray(out["v0"]), [0.0, 2.0])

    def test_udf(self, ctx):
        ctx.register_udf("plus_bang", lambda s: str(s) + "!")
        ctx.register_udf("sq", lambda x: float(x) * float(x))
        out = ctx.sql("SELECT plus_bang(k) AS kb, sq(v) AS v2 FROM t LIMIT 2")
        assert list(np.asarray(out["kb"])) == ["a!", "b!"]
        np.testing.assert_allclose(np.asarray(out["v2"]), [1.0, 4.0])

    def test_udf_in_where(self, ctx):
        ctx.register_udf("is_vowel", lambda s: s in "aeiou")
        out = ctx.sql("SELECT k FROM t WHERE is_vowel(k)")
        assert list(np.asarray(out["k"])) == ["a", "a"]


class TestReaderPushdown:
    def test_csv_projection_skips_unselected(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("a,b,junk\n1,x,zz\n2,y,zz\n3,z,zz\n")
        out = read_csv(p, select=["a"])
        assert out.columns == ["a"]
        assert list(np.asarray(out["a"])) == [1, 2, 3]

    def test_csv_predicate_filters_before_device(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("a,b\n1,x\n2,y\n3,z\n")
        out = read_csv(p, select=["b"], where=col("a") >= 2)
        assert out.columns == ["b"]
        assert list(np.asarray(out["b"])) == ["y", "z"]
        assert len(out) == 2  # rows pruned at read time

    def test_csv_unknown_select_rejected(self, tmp_path):
        p = tmp_path / "d.csv"
        p.write_text("a\n1\n")
        with pytest.raises(KeyError):
            read_csv(p, select=["nope"])

    def test_parquet_pushdown(self, tmp_path):
        pa = pytest.importorskip("pyarrow")
        import pyarrow.parquet as pq

        p = tmp_path / "d.parquet"
        pq.write_table(
            pa.table({"a": [1, 2, 3], "b": [1.0, 2.0, 3.0],
                      "c": ["x", "y", "z"]}),
            p,
        )
        out = read_parquet(p, select=["c"], where=col("b") > 1.5)
        assert out.columns == ["c"]
        assert list(np.asarray(out["c"])) == ["y", "z"]


class TestPandasOracleEndToEnd:
    def test_composed_query(self, ctx):
        """Everything at once: CTE + CASE + function + set op + order."""
        out = ctx.sql(
            "WITH scored AS ("
            "  SELECT k, CASE WHEN v >= 3 THEN 'hi' ELSE 'lo' END AS band,"
            "         SQRT(v * v) AS av FROM t"
            ") "
            "SELECT band, av FROM scored WHERE band LIKE 'h%' "
            "UNION ALL "
            "SELECT band, av FROM scored WHERE av < 2 "
            "ORDER BY av"
        )
        df = pd.DataFrame({"k": ["a", "b", "c", "d", "a"],
                           "v": [1.0, 2, 3, 4, 5]})
        df["band"] = np.where(df.v >= 3, "hi", "lo")
        df["av"] = np.abs(df.v)
        want = pd.concat([
            df[df.band.str.startswith("h")][["band", "av"]],
            df[df.av < 2][["band", "av"]],
        ]).sort_values("av")
        got = pdf(out)
        np.testing.assert_allclose(got["av"], want["av"])
        assert list(got["band"]) == list(want["band"])


class TestReviewRegressions3:
    def test_cte_scope_does_not_leak_from_subquery(self, ctx):
        out = ctx.sql(
            "WITH w AS (SELECT k, v FROM t WHERE v > 3) "
            "SELECT k FROM "
            "(WITH w AS (SELECT k, v FROM t WHERE v < 2) SELECT k, v FROM w) x "
            "JOIN w ON k"
        )
        # outer JOIN w must see the OUTER CTE (v > 3): inner rows k='a'(v=1)
        # intersected with outer {'d','a'} -> only 'a'
        assert list(np.asarray(out["k"])) == ["a"]

    def test_udf_all_literal_args_broadcasts(self, ctx):
        ctx.register_udf("inc", lambda x: x + 1)
        out = ctx.sql("SELECT k, inc(2) AS y FROM t")
        assert len(out) == 5
        assert list(np.asarray(out["y"])) == [3] * 5

    def test_int_min_max_reduce(self):
        from asyncframework_tpu.data.dataset import DistributedDataset
        from asyncframework_tpu.engine.scheduler import JobScheduler

        sched = JobScheduler(num_workers=2)
        blocks = {
            0: (np.asarray([1, 2, 1], np.int32),
                np.asarray([5, 7, 3], np.int32)),
            1: (np.asarray([2], np.int32), np.asarray([-9], np.int32)),
        }
        ds = DistributedDataset.from_array_pairs(sched, blocks)
        got_max = {}
        for row in ds.reduce_by_key("max").collect():
            for k, v in zip(np.asarray(row[0]), np.asarray(row[1])):
                got_max[int(k)] = int(v)
        ds2 = DistributedDataset.from_array_pairs(sched, blocks)
        got_min = {}
        for row in ds2.reduce_by_key("min").collect():
            for k, v in zip(np.asarray(row[0]), np.asarray(row[1])):
                got_min[int(k)] = int(v)
        sched.shutdown()
        assert got_max == {1: 5, 2: 7}
        assert got_min == {1: 3, 2: -9}
