"""Child for the two-process distributed TRAINING test (test_multihost.py).

Each process joins jax.distributed through the multihost wrapper, builds the
global mesh over both hosts' devices, and runs the fused-SPMD MiniBatchSGD
training step over it -- the same mesh/pjit code that rides ICI in a slice
rides DCN here (loopback gRPC).  Prints the resulting weights so the parent
can check both processes agree AND match a single-process run bit-for-bit
modulo float tolerance.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from asyncframework_tpu.parallel import make_mesh, multihost  # noqa: E402
from asyncframework_tpu.solvers import MiniBatchSGD  # noqa: E402


def problem():
    rs = np.random.default_rng(7)
    X = rs.normal(size=(256, 16)).astype(np.float32)
    w = rs.normal(size=(16,)).astype(np.float32)
    y = (X @ w + 0.01 * rs.normal(size=(256,))).astype(np.float32)
    return X, y


def main() -> None:
    active = multihost.ensure_initialized()
    pid, pc = multihost.process_info()
    multihost.sync_hosts("train-start")
    X, y = problem()  # every process holds the same global host arrays
    mesh = make_mesh(jax.device_count(), devices=jax.devices())
    sgd = MiniBatchSGD(gamma=0.5, batch_rate=0.5, num_iterations=40, seed=3)
    w, losses, _ = sgd.run(X, y, mesh=mesh)
    multihost.sync_hosts("train-end")
    print(json.dumps({
        "active": bool(active),
        "pid": int(pid),
        "pc": int(pc),
        "mesh": int(mesh.devices.size),
        "w": np.asarray(w).tolist(),
        "final_loss": float(losses[-1]),
    }))


if __name__ == "__main__":
    main()
