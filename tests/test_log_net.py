"""Network-attached LogTopic (VERDICT r4 #6): a broker-less streaming
source served over the framework's own DCN framing.

Parity target: the reference's direct Kafka stream consumes a REMOTE
broker (``external/kafka-0-10/.../DirectKafkaInputDStream.scala``) --
offset-ranged fetches and group-offset commits against a network service.
Here the topic server is a separate OS PROCESS; consumers/producers use
:class:`RemoteLogTopic` over TCP; :class:`DirectLogStream` drives it
unchanged (commit-after-output, replay across consumer restarts with the
offsets living server-side).
"""

import subprocess
import sys
import time

import pytest

from asyncframework_tpu.streaming import (
    DirectLogStream,
    LogTopicServer,
    RemoteLogTopic,
    StreamingContext,
)
from asyncframework_tpu.utils.clock import ManualClock


def _ssc():
    return StreamingContext(batch_interval_ms=100, clock=ManualClock())


@pytest.fixture
def server(tmp_path):
    """In-process server (thread) -- separate-socket coverage; the OS
    process split is exercised by TestTwoProcess."""
    srv = LogTopicServer(str(tmp_path / "topics"))
    srv.start()
    yield srv
    srv.stop()


class TestRemoteTopicSurface:
    def test_append_read_roundtrip(self, server):
        t = RemoteLogTopic(server.host, server.port, "t1")
        first, nxt = t.append_many([{"i": i} for i in range(10)])
        assert (first, nxt) == (0, 10)
        vals, nxt = t.read(0)
        assert vals == [{"i": i} for i in range(10)] and nxt == 10
        vals, nxt = t.read(7, max_records=2)
        assert vals == [{"i": 7}, {"i": 8}] and nxt == 9
        assert t.end_offset() == 10

    def test_offsets_commit_server_side(self, server):
        t = RemoteLogTopic(server.host, server.port, "t2")
        t.append_many(list(range(5)))
        assert t.committed_offset("g") == 0
        t.commit_offset("g", 3)
        # a DIFFERENT client (fresh socket) sees the commit
        t2 = RemoteLogTopic(server.host, server.port, "t2")
        assert t2.committed_offset("g") == 3

    def test_topics_isolated(self, server):
        a = RemoteLogTopic(server.host, server.port, "a")
        b = RemoteLogTopic(server.host, server.port, "b")
        a.append_many([1, 2])
        b.append_many([9])
        assert a.end_offset() == 2 and b.end_offset() == 1
        assert a.read(0)[0] == [1, 2] and b.read(0)[0] == [9]

    def test_bad_topic_name_is_connection_safe(self, server):
        t = RemoteLogTopic(server.host, server.port, "../escape")
        with pytest.raises(RuntimeError, match="bad topic name"):
            t.end_offset()
        # the connection (and server) survive the rejected request
        ok = RemoteLogTopic(server.host, server.port, "fine")
        ok.append(1)
        assert ok.end_offset() == 1

    def test_concurrent_producers_serialize(self, server):
        import threading

        def produce(tag):
            t = RemoteLogTopic(server.host, server.port, "many")
            for i in range(50):
                t.append(f"{tag}-{i}")

        threads = [threading.Thread(target=produce, args=(k,))
                   for k in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        t = RemoteLogTopic(server.host, server.port, "many")
        vals, nxt = t.read(0)
        assert nxt == 200 and len(vals) == 200
        for k in range(4):  # per-producer order preserved
            mine = [v for v in vals if v.startswith(f"{k}-")]
            assert mine == [f"{k}-{i}" for i in range(50)]


class TestDirectStreamOverNetwork:
    def test_batches_commit_and_resume(self, server):
        producer = RemoteLogTopic(server.host, server.port, "s")
        producer.append_many(list(range(25)))
        seen = []
        ssc = _ssc()
        ds = DirectLogStream(
            ssc, RemoteLogTopic(server.host, server.port, "s"),
            group="g", max_per_batch=10,
        )
        ds.foreach_batch(lambda t, b: seen.append(list(b)))
        for i in range(1, 4):
            ssc.generate_batch(i * 100)
        assert seen == [list(range(10)), list(range(10, 20)),
                        list(range(20, 25))]
        assert producer.committed_offset("g") == 25

        # consumer restart (new context + new client): resumes past the
        # SERVER-side commit
        producer.append_many([100, 101])
        seen2 = []
        ssc2 = _ssc()
        ds2 = DirectLogStream(
            ssc2, RemoteLogTopic(server.host, server.port, "s"), group="g",
        )
        ds2.foreach_batch(lambda t, b: seen2.append(list(b)))
        ssc2.generate_batch(100)
        assert seen2 == [[100, 101]]

    def test_failed_output_replays(self, server):
        RemoteLogTopic(server.host, server.port, "f").append_many(
            ["a", "b", "c"]
        )
        ssc = _ssc()
        ds = DirectLogStream(
            ssc, RemoteLogTopic(server.host, server.port, "f"), group="g",
        )

        def failing(_t, _b):
            raise RuntimeError("output failed")

        ds.foreach_batch(failing)
        with pytest.raises(RuntimeError):
            ssc.generate_batch(100)
        assert RemoteLogTopic(
            server.host, server.port, "f"
        ).committed_offset("g") == 0  # nothing committed

        seen = []
        ssc2 = _ssc()
        ds2 = DirectLogStream(
            ssc2, RemoteLogTopic(server.host, server.port, "f"), group="g",
        )
        ds2.foreach_batch(lambda t, b: seen.append(list(b)))
        ssc2.generate_batch(100)
        assert seen == [["a", "b", "c"]]  # full replay


class TestTwoProcess:
    """The VERDICT's bar: topic-server PROCESS + remote consumer with
    offsets, commit-after-output, and replay across a consumer restart."""

    @pytest.fixture
    def server_proc(self, tmp_path):
        proc = subprocess.Popen(
            [sys.executable, "-m", "asyncframework_tpu.streaming.log_net",
             "--root", str(tmp_path / "topics"), "--host", "127.0.0.1"],
            stdout=subprocess.PIPE, text=True,
        )
        line = proc.stdout.readline().strip()  # LISTENING host port
        assert line.startswith("LISTENING"), line
        _tag, host, port = line.split()
        yield host, int(port)
        proc.kill()
        proc.wait(timeout=10)

    def test_produce_consume_restart_across_processes(self, server_proc):
        host, port = server_proc
        producer = RemoteLogTopic(host, port, "events")
        producer.append_many([{"n": i} for i in range(12)])

        # consumer 1: two intervals of 5, then "crashes" (discarded before
        # consuming the tail)
        seen = []
        ssc = _ssc()
        ds = DirectLogStream(
            ssc, RemoteLogTopic(host, port, "events"),
            group="g", max_per_batch=5,
        )
        ds.foreach_batch(lambda t, b: seen.append([r["n"] for r in b]))
        ssc.generate_batch(100)
        ssc.generate_batch(200)
        assert seen == [[0, 1, 2, 3, 4], [5, 6, 7, 8, 9]]

        # consumer 2 (fresh "process" state, same group): resumes at the
        # server-side commit = 10, and picks up live appends
        producer.append_many([{"n": 12}])
        seen2 = []
        ssc2 = _ssc()
        ds2 = DirectLogStream(
            ssc2, RemoteLogTopic(host, port, "events"), group="g",
        )
        ds2.foreach_batch(lambda t, b: seen2.append([r["n"] for r in b]))
        ssc2.generate_batch(100)
        assert seen2 == [[10, 11, 12]]

    def test_retried_append_after_dropped_reply_is_exactly_once(
            self, server):
        """Regression (ISSUE 1 satellite, round-5 ADVICE): _call used to
        re-send APPEND after a lost reply and the topic grew duplicate
        records.  With (sid, seq) dedup the retry is answered from the
        server's window -- the log length must equal the records produced.
        """
        from asyncframework_tpu.net import faults, retry

        retry.reset_breakers()
        # the server binds 0.0.0.0; the client's peername says 127.0.0.1 --
        # match by port, which is what identifies the endpoint here
        sched = faults.FaultSchedule().add(
            f"*:{server.port}", "APPEND", 1, faults.DROP_REPLY)
        try:
            with faults.injected(sched) as inj:
                t = RemoteLogTopic(server.host, server.port, "dedup")
                first, nxt = t.append_many([{"i": i} for i in range(5)])
                t.close()
            assert inj.remaining() == []          # the fault really fired
            assert (first, nxt) == (0, 5)         # retry saw the SAME offsets
            check = RemoteLogTopic(server.host, server.port, "dedup")
            assert check.end_offset() == 5        # 5 records, not 10
            records, _ = check.read(0)
            assert [r["i"] for r in records] == list(range(5))
            check.close()
            assert server.dedup_hits == 1
        finally:
            faults.clear()

    def test_server_restart_client_reconnects(self, tmp_path):
        root = str(tmp_path / "topics")

        def spawn(port=0):
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 "asyncframework_tpu.streaming.log_net",
                 "--root", root, "--host", "127.0.0.1",
                 "--port", str(port)],
                stdout=subprocess.PIPE, text=True,
            )
            line = proc.stdout.readline().strip()
            _tag, host, got_port = line.split()
            return proc, host, int(got_port)

        proc, host, port = spawn()
        try:
            client = RemoteLogTopic(host, port, "t")
            client.append_many([1, 2, 3])
            proc.kill()
            proc.wait(timeout=10)
            time.sleep(0.2)
            proc, _h, _p = spawn(port)  # same port, same on-disk topics
            # the SAME client object reconnects and sees durable state
            assert client.end_offset() == 3
            first, nxt = client.append_many([4])
            assert (first, nxt) == (3, 4)
        finally:
            proc.kill()
            proc.wait(timeout=10)
