"""Hot-standby shard replication (ISSUE 13).

The correctness spine:

- a standby bootstrapped by REPL_SYNC and fed REPL_APPENDs is the
  primary's state, exactly: model bytes, merge clock, accept/drop
  ledgers, snapshot cadence, AND the dedup window -- so a promoted
  standby answers replayed worker pushes from the REPLICATED window
  (exactly-once across the failover), never by re-applying;
- the stream's idempotence is the clock compare: duplicate appends
  re-ACK, gaps refuse with resync (re-bootstrap), nothing applies twice
  or out of order;
- promotion is epoch-fenced: the deposed primary's post-promotion
  stream appends are REJECT_FENCED, the bounce folds back into its
  worker-facing admission (note_fenced_above), its clients heal onto
  the minted epoch and RE-RESOLVE the moved endpoint from any live
  member;
- the acceptance runs (`repl` marker, ride every bin/chaos_sweep.py
  seed): a real 3-shard group with warm standbys survives SIGKILL of a
  primary mid-run by PROMOTION (restarts stay zero, no checkpoint
  replay on the recovery path, availability gap bounded by suspicion
  time), and a PARTITIONED (not killed) primary's healed zombie has its
  stream appends counted REJECT_FENCED while accept accounting proves
  exactly-once.
"""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu import conf as conf_mod
from asyncframework_tpu.conf import AsyncConf, set_global_conf
from asyncframework_tpu.net import faults, reset_net_totals
from asyncframework_tpu.net.retry import reset_breakers
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.parallel import replication as repl_mod
from asyncframework_tpu.parallel import shardgroup as sg
from asyncframework_tpu.solvers import SolverConfig

pytestmark = pytest.mark.repl

CHILD = Path(__file__).parent / "ps_dcn_child.py"
CHAOS_SEED = int(os.environ.get("ASYNC_CHAOS_SEED", "7"))


def make_cfg(**kw):
    defaults = dict(
        num_workers=2, num_iterations=10**6, gamma=1.0, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.0, printer_freq=10, seed=42,
        calibration_iters=10**9, run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture(autouse=True)
def _clean_state():
    reset_net_totals()
    sg.reset_shard_totals()
    repl_mod.reset_repl_totals()
    reset_breakers()
    faults.clear()
    set_global_conf(AsyncConf({"async.fence.enabled": True}))
    yield
    faults.clear()
    reset_net_totals()
    sg.reset_shard_totals()
    repl_mod.reset_repl_totals()
    reset_breakers()
    set_global_conf(None)


def _mirrored_pair(cfg=None, d=8, n=64):
    """One primary + one attached standby, both in-process."""
    cfg = cfg or make_cfg()
    prim = ps_dcn.ParameterServer(cfg, d, n, port=0).start()
    sb = ps_dcn.ParameterServer(cfg, d, n, port=0, standby=True).start()
    prim.attach_standby("127.0.0.1", sb.port)
    return prim, sb


def _wait_caught_up(prim, sb, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if sb._clock >= prim._clock and prim.repl.synced:
            return
        time.sleep(0.02)
    raise AssertionError(
        f"standby never caught up: {sb._clock} < {prim._clock}")


# ------------------------------------------------------------ mirror units
class TestMirror:
    def test_sync_then_appends_mirror_state_exactly(self):
        prim, sb = _mirrored_pair()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", prim.port)
            rng = np.random.default_rng(3)
            for _ in range(25):
                ts, _w, _a, _c = cl.pull(0)
                cl.push(0, ts, rng.normal(size=8).astype(np.float32))
            _wait_caught_up(prim, sb)
            assert (sb._clock, sb._k, sb.accepted, sb.dropped) == (
                prim._clock, prim._k, prim.accepted, prim.dropped)
            # the model is the SAME bytes (same kernel, same order)
            np.testing.assert_array_equal(np.asarray(prim._w),
                                          np.asarray(sb._w))
            # snapshot cadence mirrored: the promoted trajectory would
            # continue seamlessly
            assert len(sb._snapshots) == len(prim._snapshots)
            # the dedup window is REPLICATED: the client's session is in
            # the standby's window with every applied seq
            state = sb._dedup.state()["sessions"]
            assert cl.session.sid in state
            assert len(state[cl.session.sid]) == 25
            # per-wid ledgers mirrored
            assert sb.accepted_by_wid == prim.accepted_by_wid
            totals = repl_mod.repl_totals()
            assert totals.get("syncs_sent", 0) >= 1
            assert totals.get("appends_applied", 0) >= 1
            cl.bye()
        finally:
            prim.stop()
            sb.stop()

    def test_standby_refuses_training_plane_serves_reads(self):
        prim, sb = _mirrored_pair()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", prim.port)
            for _ in range(5):
                ts, _w, _a, _c = cl.pull(0)
                cl.push(0, ts, np.ones(8, np.float32))
            _wait_caught_up(prim, sb)
            # PULL/PUSH against the standby surface as a dead endpoint
            # (ConnectionError), so loops pace and facades re-resolve
            probe = ps_dcn.PSClient("127.0.0.1", sb.port)
            with pytest.raises(ConnectionError):
                probe.pull(1)
            with pytest.raises(ConnectionError):
                probe.push(1, 0, np.zeros(8, np.float32))
            # ...but SUBSCRIBE is served from the mirrored snapshot,
            # byte-identical to the primary's at the same version
            sub = ps_dcn.PSClient("127.0.0.1", sb.port,
                                  pull_mode="delta")
            got = sub.subscribe(0)
            assert got is not None
            ts_sb, w_sb, clock_sb, _k, age_ms, _done = got
            direct = ps_dcn.PSClient("127.0.0.1", prim.port,
                                     pull_mode="delta").subscribe(0)
            assert ts_sb == direct[0] and clock_sb == direct[2]
            np.testing.assert_array_equal(w_sb, direct[1])
            assert age_ms >= 0.0
            sub.bye()
        finally:
            prim.stop()
            sb.stop()

    def test_append_gap_refuses_resync_duplicate_reacks(self):
        prim, sb = _mirrored_pair()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", prim.port)
            for _ in range(3):
                ts, _w, _a, _c = cl.pull(0)
                cl.push(0, ts, np.ones(8, np.float32))
            _wait_caught_up(prim, sb)
            ep = sb.epoch
            # a GAP (pre ahead of the applied clock) refuses with resync
            rep = sg._oneshot(
                "127.0.0.1", sb.port,
                {"op": "REPL_APPEND", "ep": ep,
                 "pre": sb._clock + 5,
                 "items": [[0, 0, 0, None, None, {}, 0]],
                 "cal": [0, 0, 0.0]}, 5.0)
            assert rep["op"] == "ERR" and rep.get("resync") is True
            # a DUPLICATE (entirely at-or-below the clock) re-ACKs and
            # applies nothing
            k_before = sb._k
            rep = sg._oneshot(
                "127.0.0.1", sb.port,
                {"op": "REPL_APPEND", "ep": ep,
                 "pre": sb._clock - 1,
                 "items": [[0, 0, 0, None, None, {}, 0]],
                 "cal": [0, 0, 0.0]}, 5.0)
            assert rep["op"] == "ACK" and rep.get("dup") is True
            assert sb._k == k_before
            assert repl_mod.repl_totals().get("resyncs_requested", 0) >= 1
        finally:
            prim.stop()
            sb.stop()

    def test_stream_rebootstraps_after_standby_blip(self):
        """Cut the stream mid-run (drop every connection to the standby
        via a fault schedule): the sender reconnects, re-SYNCs, and the
        standby converges again -- flapping costs bandwidth, never
        correctness."""
        prim, sb = _mirrored_pair()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", prim.port)
            for _ in range(5):
                ts, _w, _a, _c = cl.pull(0)
                cl.push(0, ts, np.ones(8, np.float32))
            _wait_caught_up(prim, sb)
            sched = faults.FaultSchedule(seed=CHAOS_SEED)
            sched.add_partition([f"*:{sb.port}"], duration_s=1.0)
            faults.install(faults.FaultInjector(sched))
            for _ in range(10):
                ts, _w, _a, _c = cl.pull(0)
                cl.push(0, ts, np.ones(8, np.float32))
            time.sleep(1.2)  # partition heals on schedule
            faults.clear()
            for _ in range(5):
                ts, _w, _a, _c = cl.pull(0)
                cl.push(0, ts, np.ones(8, np.float32))
            _wait_caught_up(prim, sb, timeout_s=15.0)
            np.testing.assert_array_equal(np.asarray(prim._w),
                                          np.asarray(sb._w))
            assert (sb.accepted, sb.dropped) == (prim.accepted,
                                                 prim.dropped)
            cl.bye()
        finally:
            faults.clear()
            prim.stop()
            sb.stop()


# ------------------------------------------------------- promotion units
class TestPromotion:
    def test_promote_fences_zombie_and_serves_training_plane(self):
        prim, sb = _mirrored_pair()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", prim.port)
            for _ in range(10):
                ts, _w, _a, _c = cl.pull(0)
                cl.push(0, ts, np.ones(8, np.float32))
            _wait_caught_up(prim, sb)
            rep = sg._oneshot("127.0.0.1", sb.port,
                              {"op": "PROMOTE", "epoch": 2}, 5.0)
            assert rep["op"] == "ACK" and rep["epoch"] == 2
            assert sb.promoted and not sb._standby
            # idempotent: re-delivery (same or older epoch) re-ACKs
            rep = sg._oneshot("127.0.0.1", sb.port,
                              {"op": "PROMOTE", "epoch": 2}, 5.0)
            assert rep["op"] == "ACK" and rep["epoch"] == 2
            # THE promotion-safety admission: the deposed primary's
            # stream appends carry epoch 1 and bounce REJECT_FENCED
            rep = sg._oneshot(
                "127.0.0.1", sb.port,
                {"op": "REPL_APPEND", "ep": 1, "pre": sb._clock,
                 "items": [], "cal": [0, 0, 0.0]}, 5.0)
            assert rep["op"] == "REJECT_FENCED" and rep["epoch"] == 2
            # the zombie's OWN stream hits the same wall, parks, and
            # folds the foreign epoch into its worker-facing admission
            ts, _w, _a, _c = cl.pull(0)
            cl.push(0, ts, np.ones(8, np.float32))
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and not prim.repl.fenced:
                time.sleep(0.05)
            assert prim.repl.fenced
            assert prim._fenced_above == 2
            with pytest.raises(ps_dcn.FencedError):
                cl.pull(0)
            assert cl.epoch == 2  # healed onto the minted epoch
            # the promoted standby serves the training plane now
            c2 = ps_dcn.PSClient("127.0.0.1", sb.port, epoch=2)
            ts2, _w2, _a2, _c2 = c2.pull(0)
            acc, _dn = c2.push(0, ts2, np.ones(8, np.float32))
            assert acc
            assert repl_mod.repl_totals().get("promotions", 0) == 1
            assert repl_mod.repl_totals().get("fenced_streams", 0) == 1
            c2.bye()
        finally:
            prim.stop()
            sb.stop()

    def test_stale_promote_refused_on_fresh_standby(self):
        """Review regression: a STALE PROMOTE (late operator retry /
        re-delivery after the standby was respawned) must not flip a
        fresh mirror -- it would orphan it from its primary's stream.
        The refusal is an ERR, which the controller's _promote treats
        as a failed promotion (fallback to relaunch)."""
        prim, sb = _mirrored_pair()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", prim.port)
            ts, _w, _a, _c = cl.pull(0)
            cl.push(0, ts, np.ones(8, np.float32))
            _wait_caught_up(prim, sb)
            # standby runs at epoch 1 (the stream's epoch): a promote
            # at epoch <= 1 is stale and refused
            rep = sg._oneshot("127.0.0.1", sb.port,
                              {"op": "PROMOTE", "epoch": 1}, 5.0)
            assert rep["op"] == "ERR" and "stale" in rep["msg"]
            assert sb._standby and not sb.promoted
            # the stream is still healthy: a further push mirrors
            ts, _w, _a, _c = cl.pull(0)
            cl.push(0, ts, np.ones(8, np.float32))
            _wait_caught_up(prim, sb)
            cl.bye()
        finally:
            prim.stop()
            sb.stop()

    def test_exactly_once_replay_against_replicated_window(self):
        """An applied-but-unACKed windowed push replayed against the
        PROMOTED standby is answered from the REPLICATED dedup window --
        the accepted count does not move, the verdict is the cached
        one."""
        prim, sb = _mirrored_pair()
        try:
            wcl = ps_dcn.PSClient("127.0.0.1", prim.port)
            ts, _w, _a, _c = wcl.pull(1)
            wcl.push_start(1, ts, np.ones(8, np.float32))
            # the primary applies + streams; the ACK stays unreaped
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and sb.accepted < 1:
                time.sleep(0.02)
            assert sb.accepted == prim.accepted == 1
            sg._oneshot("127.0.0.1", sb.port,
                        {"op": "PROMOTE", "epoch": 2}, 5.0)
            prim.stop()
            # transplant the unacked window onto a same-session client
            # of the promoted standby (what ShardedPSClient._rebuild_
            # client does) and reap: dedup wins over fencing
            nc = ps_dcn.PSClient("127.0.0.1", sb.port,
                                 session=wcl.session, epoch=2)
            with wcl._win_lock:
                entries = list(wcl._push_window)
                wcl._push_window.clear()
            nc._push_window.extend(entries)
            nc._drop_sock()  # reconnect REPLAYS the window
            acc, _done = nc.push_finish()
            assert acc is True          # the CACHED verdict
            assert sb.dedup_hits >= 1   # answered from the window
            assert sb.accepted == 1     # never re-applied
        finally:
            prim.stop()
            sb.stop()

    def test_facade_re_resolves_promoted_endpoint(self):
        """ShardedPSClient follows a promotion: primary 1 dies, every
        surviving member learns the new map via SETMAP, and the facade's
        next faulting round rebuilds the moved sub-client (same session)
        and keeps training."""
        cfg = make_cfg()
        d, n = 24, 256
        ps_list, smap = sg.launch_inprocess_group(cfg, d, n, 3)
        ranges = smap.ranges()
        lo1, hi1 = ranges[1]
        sb = ps_dcn.ParameterServer(
            sg.secondary_cfg(cfg), hi1 - lo1, n, port=0,
            standby=True).start()
        try:
            ps_list[1].attach_standby("127.0.0.1", sb.port)
            cl = sg.ShardedPSClient(smap, epochs=[1, 1, 1], proc="w")
            for _ in range(10):
                ts, _w, _a, _c = cl.pull(0)
                cl.push(0, ts, np.ones(d, np.float32))
            deadline = time.monotonic() + 10.0
            while (time.monotonic() < deadline
                   and sb._clock < ps_list[1]._clock):
                time.sleep(0.02)
            # the controller's moves, by hand: promote, install the new
            # map on the surviving members, kill the old primary
            new_entries = [list(e) for e in smap.entries]
            new_entries[1] = ["127.0.0.1", sb.port, lo1, hi1]
            epochs = [1, 2, 1]
            sg._oneshot("127.0.0.1", sb.port,
                        {"op": "PROMOTE", "epoch": 2, "index": 1,
                         "shards": new_entries, "epochs": epochs}, 5.0)
            for ps in (ps_list[0], ps_list[2]):
                sg._oneshot("127.0.0.1", ps.port,
                            {"op": "SETMAP", "index": ps.shard_index,
                             "shards": new_entries,
                             "epochs": epochs}, 5.0)
            ps_list[1].stop()
            # an in-process stop leaves lingering per-connection
            # handlers that answer DONE during teardown; a real dead
            # shard's sockets just die -- simulate that
            cl.clients[1]._drop_sock()
            # the next faulting rounds re-resolve and keep training
            ok_rounds = 0
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline and ok_rounds < 5:
                try:
                    ts, _w, _a, _c = cl.pull(0)
                    cl.push(0, ts, np.ones(d, np.float32))
                    ok_rounds += 1
                except (ConnectionError, OSError):
                    time.sleep(0.1)
            assert ok_rounds >= 5
            assert cl.clients[1].port == sb.port
            assert cl.clients[1].epoch == 2
            assert sg.shard_totals().get("map_re_resolves", 0) >= 1
            cl.bye()
        finally:
            for ps in ps_list:
                ps.stop()
            sb.stop()


    def test_subscriber_follows_simultaneous_promotions(self):
        """Review regression: TWO ranges promoted before the subscriber
        notices.  _maybe_re_resolve must rebuild EVERY moved range in
        one sweep, judged against each CLIENT's endpoint -- adopting
        the new map while rebuilding only the range that triggered it
        used to strand the other one dark forever."""
        cfg = make_cfg()
        d, n = 24, 256
        ps_list, smap = sg.launch_inprocess_group(cfg, d, n, 3)
        ranges = smap.ranges()
        sbs = []
        for i in (0, 1):
            lo, hi = ranges[i]
            shard_cfg = cfg if i == 0 else sg.secondary_cfg(cfg)
            sb = ps_dcn.ParameterServer(shard_cfg, hi - lo, n, port=0,
                                        standby=True).start()
            ps_list[i].attach_standby("127.0.0.1", sb.port)
            sbs.append(sb)
        try:
            cl = sg.ShardedPSClient(smap, epochs=[1, 1, 1], proc="w")
            for _ in range(5):
                ts, _w, _a, _c = cl.pull(0)
                cl.push(0, ts, np.ones(d, np.float32))
            cl.bye()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and any(
                    sbs[i]._clock < ps_list[i]._clock for i in (0, 1)):
                time.sleep(0.02)
            sub = sg.ShardedSubscriber(smap, epochs=[1, 1, 1])
            assert sub.subscribe()[1].shape == (d,)
            # promote BOTH standbys; shard 2 (the only survivor) learns
            # the new map
            new_entries = [list(e) for e in smap.entries]
            for i in (0, 1):
                lo, hi = ranges[i]
                new_entries[i] = ["127.0.0.1", sbs[i].port, lo, hi]
            epochs = [2, 2, 1]
            for i in (0, 1):
                sg._oneshot("127.0.0.1", sbs[i].port,
                            {"op": "PROMOTE", "epoch": 2, "index": i,
                             "shards": new_entries,
                             "epochs": epochs}, 5.0)
            sg._oneshot("127.0.0.1", ps_list[2].port,
                        {"op": "SETMAP", "index": 2,
                         "shards": new_entries, "epochs": epochs}, 5.0)
            for i in (0, 1):
                ps_list[i].stop()
                sub.clients[i]._drop_sock()
            # drive refresh rounds until both dark ranges re-home (the
            # 3rd consecutive dark round triggers the sweep)
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                try:
                    sub.subscribe()
                except (ConnectionError, OSError):
                    pass
                if (sub.clients[0].port == sbs[0].port
                        and sub.clients[1].port == sbs[1].port):
                    break
                time.sleep(0.05)
            assert sub.clients[0].port == sbs[0].port
            assert sub.clients[1].port == sbs[1].port
            # and the next round serves a fresh assembled model again
            got = sub.subscribe()
            assert got[1].shape == (d,)
            assert sub.stale_ranges(10_000.0) == []
            sub.bye()
        finally:
            for ps in ps_list:
                ps.stop()
            for sb in sbs:
                sb.stop()


# --------------------------------------------- conf / SLO / k8s surfaces
class TestSurfaces:
    def test_protocol_rows_declare_obligations(self):
        from asyncframework_tpu.net import protocol

        tbl = protocol.table()
        assert tbl["REPL_APPEND"].mutating
        assert not tbl["REPL_APPEND"].dedup_gated  # clock-compare idem.
        assert tbl["REPL_APPEND"].fence_stamped
        assert tbl["REPL_SYNC"].fence_stamped
        assert tbl["PROMOTE"].mutating
        assert not tbl["PROMOTE"].fence_stamped  # it RAISES the epoch

    def test_default_rules_include_standby_lag(self):
        from asyncframework_tpu.metrics.slo import parse_rules

        rules = parse_rules(AsyncConf().get(conf_mod.SLO_RULES))
        byname = {r.name: r for r in rules}
        assert "standby_lag" in byname
        assert byname["standby_lag"].series == "ps.standby_lag"
        assert byname["standby_lag"].unless_series == "ps.done"

    def test_registry_has_replication_family(self):
        from asyncframework_tpu.metrics import registry, reset_totals

        assert "replication" in registry.families()
        repl_mod.bump("batches_streamed")
        reset_totals()
        assert repl_mod.repl_totals() == {}

    def test_primary_telemetry_reports_standby_lag(self):
        prim, sb = _mirrored_pair()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", prim.port)
            for _ in range(3):
                ts, _w, _a, _c = cl.pull(0)
                cl.push(0, ts, np.ones(8, np.float32))
            _wait_caught_up(prim, sb)
            # the lag series reads the ACKed clock (primary side),
            # which trails the standby's apply by one ACK round trip:
            # wait on the signal the assertion reads
            deadline = time.monotonic() + 5.0
            while (time.monotonic() < deadline
                   and prim.repl.lag_versions() > 0):
                time.sleep(0.02)
            src = prim._telemetry_source()
            assert src["standby_synced"] == 1.0
            assert src["standby_lag"] == 0.0
            assert sb._telemetry_source().get("standby") == 1.0
        finally:
            prim.stop()
            sb.stop()

    def test_k8s_renders_standby_pods(self):
        from asyncframework_tpu.deploy.k8s import (
            PS_SHARD_PORT,
            render_ps_shards,
        )

        objs = render_ps_shards(3, 24, 2048, workers=8, standbys=1)
        kinds = [o["kind"] for o in objs]
        assert kinds.count("Deployment") == 6   # 3 primaries + 3 standbys
        assert kinds.count("Service") == 6
        assert kinds.count("PersistentVolumeClaim") == 3  # primaries only
        deps = {o["metadata"]["name"]: o for o in objs
                if o["kind"] == "Deployment"}
        for i in range(3):
            prim = deps[f"async-ps-shard-{i}"]
            env = {e["name"]: e["value"] for e in
                   prim["spec"]["template"]["spec"]["containers"][0]["env"]}
            sbs = json.loads(env["ASYNC_SHARD_STANDBYS"])
            assert sbs[i] == [f"async-ps-shard-{i}-standby",
                              PS_SHARD_PORT]
            sb = deps[f"async-ps-shard-{i}-standby"]
            sb_env = {e["name"]: e["value"] for e in
                      sb["spec"]["template"]["spec"]["containers"][0]["env"]}
            assert sb_env["ASYNC_SHARD_ROLE"] == "standby"
            assert sb_env["ASYNC_SHARD_CKPT"] == ""  # stream-synced
            meta = sb["spec"]["template"]["metadata"]
            assert meta["labels"]["role"] == "standby"
        # default rendering is unchanged (9 objects, no standby names)
        base = render_ps_shards(3, 24, 2048, workers=8)
        assert len(base) == 9
        assert not any("standby" in o["metadata"]["name"] for o in base)


# ------------------------------------------- THE acceptance (real procs)
class TestFailoverAcceptance:
    """Real OS processes end to end: a 3-shard group with warm standbys
    under the controller, two worker processes, and a primary taken out
    mid-run -- by SIGKILL (promotion, availability gap bounded by
    suspicion time) and by PARTITION (the healed zombie's stream appends
    are REJECT_FENCED and nothing applies twice)."""

    NW, N, D = 8, 4096, 24
    ITERS = 900

    def _worker(self, port, wpid, tmp):
        env = dict(os.environ)
        env.update({
            "PS_ROLE": "worker", "PS_PORT": str(port),
            "PS_WORKER_ID": str(wpid), "PS_NUM_WORKER_PROCS": "2",
            "PS_NUM_ITER": str(self.ITERS),
            "JAX_PLATFORMS": "cpu",
        })
        return subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.PIPE,
            stderr=open(os.path.join(tmp, f"worker{wpid}.stderr.log"),
                        "w"),
            text=True,
        )

    def _group(self, tmp_path):
        # cfg MUST mirror tests/ps_dcn_child.py::config()
        cfg = SolverConfig(
            num_workers=self.NW, num_iterations=self.ITERS, gamma=1.2,
            taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5,
            printer_freq=50, seed=42, calibration_iters=20,
            run_timeout_s=120.0,
        )
        return sg.ShardGroup(
            cfg, self.D, self.N, 3, checkpoint_dir=str(tmp_path),
            worker_procs=2, dead_after_s=1.0, check_interval_s=0.2,
            stderr_dir=str(tmp_path),
            conf_overlays={"async.fence.enabled": True,
                           "async.ps.standby": 1},
        ).start()

    def _wait_threshold(self, port, threshold, what):
        watch = ps_dcn.PSClient("127.0.0.1", port)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            got = watch.subscribe(0)
            if got is not None and got[2] >= threshold:
                break
            time.sleep(0.02)
        else:
            pytest.fail(f"{what} never reached the threshold")
        try:
            watch.bye()
        except (ConnectionError, OSError):
            pass

    def test_sigkill_primary_promotes_not_restarts(self, tmp_path):
        group = self._group(tmp_path)
        workers = []
        try:
            assert group.standbys_wire() and all(group.standbys_wire())
            port0 = group.port_of(0)
            workers = [self._worker(port0, 0, str(tmp_path)),
                       self._worker(port0, 1, str(tmp_path))]
            kill_after = 60 + (CHAOS_SEED % 50)
            self._wait_threshold(group.port_of(1), kill_after, "shard 1")
            os.kill(group.pid_of(1), signal.SIGKILL)
            t_kill = time.monotonic()
            # availability probe through the failover: time every read
            # of range 1 at its CURRENT endpoint; the gap is bounded by
            # suspicion (lease 1 s) + promotion RPC, NOT by a process
            # relaunch + checkpoint replay
            gap_end = None
            latencies = []
            probe_deadline = time.monotonic() + 45.0
            while time.monotonic() < probe_deadline:
                t0 = time.monotonic()
                try:
                    sg._oneshot("127.0.0.1", group.port_of(1),
                                {"op": "SHARDMAP"}, timeout_s=1.0)
                    latencies.append(time.monotonic() - t0)
                    if group.promotions_of(1) >= 1:
                        gap_end = time.monotonic()
                        break
                except (ConnectionError, OSError):
                    pass
                time.sleep(0.02)
            assert gap_end is not None, "range 1 never came back"
            gap_s = gap_end - t_kill
            # THE acceptance: promotion, not restart -- no spawn, no
            # checkpoint replay on the recovery path
            assert group.promotions_of(1) >= 1
            assert group.restarts_of(1) == 0
            assert sg.shard_totals().get("shards_restarted", 0) == 0
            assert sg.shard_totals().get("standby_promotions", 0) >= 1
            # suspicion (1 s lease) + scan tick + one RPC, with wide
            # scheduling headroom -- a relaunch would add process boot
            # (jax import alone is several seconds) + checkpoint replay
            assert gap_s < 20.0, f"availability gap {gap_s:.1f}s"
            # the run completes through the failover with full coverage
            result0 = group.result_of(0, timeout_s=90.0)
            assert result0 is not None and result0["done"] is True
            assert result0["accepted"] == self.ITERS
            assert set(map(int, result0["accepted_by_wid"])) == set(
                range(self.NW))
            traj = result0.get("trajectory")
            assert traj, "no trajectory (eval plane died?)"
            assert traj[-1][1] < traj[0][1] * 0.2, traj
            group.finish()
            # the promoted member reports itself: promoted, never
            # resumed from a checkpoint, exactly-once accounting intact
            result1 = group.result_of(1, timeout_s=30.0)
            assert result1 is not None, "promoted shard never reported"
            assert result1.get("promoted") is True
            assert result1.get("resumed_from") is None
            assert (result1["accepted"] + result1["dropped"]
                    == result1["clock"])
            for w in workers:
                rc = w.wait(timeout=60.0)
                assert rc == 0, f"worker exited rc={rc}"
            out = [json.loads(w.stdout.read().splitlines()[-1])
                   for w in workers]
            assert sum(o["gradients"] for o in out) >= self.ITERS
        finally:
            for w in workers:
                if w.poll() is None:
                    w.kill()
            group.stop()

    def test_partition_primary_zombie_stream_fenced(self, tmp_path):
        """PARTITION (not SIGKILL) shard 1's primary away from the
        controller past lease expiry: the standby promotes; the zombie
        -- alive, still fed by workers until they heal -- has its
        stream appends REJECT_FENCED by the promoted standby, folds the
        bounce into its own admission, and its deposed clients
        re-resolve.  No accepted push is applied twice (accept
        accounting on the promoted member is exact)."""
        group = self._group(tmp_path)
        workers = []
        try:
            port0 = group.port_of(0)
            port1 = group.port_of(1)
            workers = [self._worker(port0, 0, str(tmp_path)),
                       self._worker(port0, 1, str(tmp_path))]
            cut_after = 60 + (CHAOS_SEED % 40)
            self._wait_threshold(port1, cut_after, "shard 1")
            # blackhole the CONTROLLER's view of shard 1's primary (the
            # workers and the standby keep talking to it -- the zombie
            # stays live and streaming).  wan profile overlays when the
            # sweep asks for it.
            sched = faults.FaultSchedule(seed=CHAOS_SEED)
            sched.add_partition([f"*:{port1}"], duration_s=6.0)
            sched = faults.merge_schedules(
                sched, faults.profile_schedule_from_env(CHAOS_SEED))
            faults.install(faults.FaultInjector(sched))
            deadline = time.monotonic() + 45.0
            while time.monotonic() < deadline:
                if group.promotions_of(1) >= 1:
                    break
                time.sleep(0.1)
            assert group.promotions_of(1) >= 1, \
                "partitioned primary was never promoted over"
            assert group.sup.counters()["lease_expiries"] >= 1
            assert group.restarts_of(1) == 0
            faults.clear()  # heal: the zombie is reachable again
            # the zombie keeps draining worker pushes and streaming
            # them -- every post-promotion append bounces REJECT_FENCED
            # at the promoted member (counted server-side)
            deadline = time.monotonic() + 30.0
            fenced = 0
            while time.monotonic() < deadline:
                try:
                    hdr = sg._oneshot("127.0.0.1", group.port_of(1),
                                      {"op": "SHARDMAP"}, timeout_s=2.0)
                    fenced = int(hdr.get("fenced_rejects", 0))
                    if fenced >= 1:
                        break
                except (ConnectionError, OSError):
                    pass
                time.sleep(0.2)
            assert fenced >= 1, \
                "zombie's post-promotion writes were never fenced"
            # the run completes through the partition: full coverage,
            # decreasing assembled trajectory
            result0 = group.result_of(0, timeout_s=90.0)
            assert result0 is not None and result0["done"] is True
            assert result0["accepted"] == self.ITERS
            assert set(map(int, result0["accepted_by_wid"])) == set(
                range(self.NW))
            traj = result0.get("trajectory")
            assert traj and traj[-1][1] < traj[0][1] * 0.2, traj
            group.finish()
            # exactly-once across the failover: every item the promoted
            # member ever counted ticked its clock exactly once
            result1 = group.result_of(1, timeout_s=30.0)
            assert result1 is not None
            assert result1.get("promoted") is True
            assert (result1["accepted"] + result1["dropped"]
                    == result1["clock"])
            for w in workers:
                rc = w.wait(timeout=60.0)
                assert rc == 0, f"worker exited rc={rc}"
        finally:
            faults.clear()
            for w in workers:
                if w.poll() is None:
                    w.kill()
            group.stop()
