"""Logical-plan optimizer (VERDICT r3 item 5): plan-shape assertions.

Parity targets: ``Optimizer.scala:38`` rules that move data -- predicate
pushdown through joins/aggregates into readers, projection pruning,
constant folding -- plus join build-side selection by size (an execution
rule in ``frame.join``).
"""

import numpy as np
import pytest

from asyncframework_tpu.sql import ColumnarFrame, col, lit, sql
from asyncframework_tpu.sql.expressions import Column
from asyncframework_tpu.sql.parser import SQLContext
from asyncframework_tpu.sql.plan import (
    Aggregate,
    Filter,
    Join,
    Project,
    Scan,
    execute,
    optimize,
    split_conjuncts,
)


def frame_a():
    return ColumnarFrame({
        "k": np.asarray([1, 2, 3, 4], np.int32),
        "a": np.asarray([10.0, 20.0, 30.0, 40.0], np.float32),
        "unused_a": np.asarray([0.0, 0.0, 0.0, 0.0], np.float32),
    })


def frame_b():
    return ColumnarFrame({
        "k": np.asarray([2, 3, 4, 5], np.int32),
        "b": np.asarray([1.0, 2.0, 3.0, 4.0], np.float32),
        "unused_b": np.asarray([9.0, 9.0, 9.0, 9.0], np.float32),
    })


class TestColumnMetadata:
    def test_refs_union_through_operators(self):
        e = (col("x") + col("y")) > lit(3)
        assert e.refs == frozenset({"x", "y"})

    def test_literals_have_no_refs(self):
        assert lit(5).refs == frozenset()

    def test_conjunct_split(self):
        p = (col("x") > 1) & (col("y") < 2) & (col("z") == 3)
        parts = split_conjuncts(p)
        assert [sorted(c.refs) for c in parts] == [["x"], ["y"], ["z"]]

    def test_constant_folding_at_construction(self):
        e = lit(2) + lit(3)
        # folded: evaluating against an EMPTY column dict succeeds because
        # the tree is a literal now
        assert e({}) == 5
        assert e.refs == frozenset()

    def test_folding_mixed_stays_lazy(self):
        e = col("x") + (lit(2) * lit(5))
        assert e.refs == frozenset({"x"})
        assert float(e({"x": np.asarray([1.0])})[0]) == 11.0

    def test_udf_marked_volatile_blocks_fold(self):
        from asyncframework_tpu.sql.expressions import udf_column

        e = udf_column(lambda: 7, [], "f")
        assert e.volatile


class TestPushdownThroughJoin:
    def test_inner_join_filter_splits_to_both_sides(self):
        plan = Filter(
            Join(Scan("a", frame=frame_a()), Scan("b", frame=frame_b()),
                 on="k"),
            (col("a") > 15) & (col("b") < 3),
        )
        opt = optimize(plan, required=["k", "a", "b"])
        # the Filter above the join dissolved; each side got its conjunct
        assert isinstance(opt, Join)
        assert isinstance(opt.left, Filter) and opt.left.predicate.refs == {
            "a"
        }
        assert isinstance(opt.right, Filter) and opt.right.predicate.refs == {
            "b"
        }
        out = execute(opt)
        rows = sorted(out.collect())
        # k=2 (a=20,b=1) and k=3 (a=30,b=2) survive; k=4 fails b=3<3
        assert [r[0] for r in rows] == [2, 3]

    def test_left_join_pushes_left_only(self):
        plan = Filter(
            Join(Scan("a", frame=frame_a()), Scan("b", frame=frame_b()),
                 on="k", how="left"),
            (col("a") > 15) & (col("b") < 3),
        )
        opt = optimize(plan, required=["k", "a", "b"])
        # left conjunct sank; right conjunct must stay above the join
        assert isinstance(opt, Filter)
        assert opt.predicate.refs == {"b"}
        assert isinstance(opt.child, Join)
        assert isinstance(opt.child.left, Filter)
        assert opt.child.left.predicate.refs == {"a"}
        assert not isinstance(opt.child.right, Filter)

    def test_full_join_pushes_nothing(self):
        plan = Filter(
            Join(Scan("a", frame=frame_a()), Scan("b", frame=frame_b()),
                 on="k", how="full"),
            col("a") > 15,
        )
        opt = optimize(plan, required=["k", "a", "b"])
        assert isinstance(opt, Filter) and isinstance(opt.child, Join)
        assert not isinstance(opt.child.left, Filter)

    def test_pushdown_equivalence_all_join_types(self):
        for how in ("inner", "left", "right", "full", "semi", "anti"):
            pred = (col("a") > 15) if how in ("semi", "anti") else (
                (col("a") > 15) & (col("b") < 3)
            )
            plan = Filter(
                Join(Scan("a", frame=frame_a()), Scan("b", frame=frame_b()),
                     on="k", how=how),
                pred,
            )
            naive = execute(plan)
            opt = execute(optimize(plan, required=None))
            assert sorted(map(repr, naive.collect())) == sorted(
                map(repr, opt.collect())
            ), how


class TestPushdownThroughAggregate:
    def test_group_key_predicate_sinks_below_aggregate(self):
        plan = Filter(
            Aggregate(Scan("a", frame=frame_a()), key="k",
                      spec={"total": ("a", "sum")}),
            col("k") > 2,
        )
        opt = optimize(plan, required=["k", "total"])
        assert isinstance(opt, Aggregate)
        assert isinstance(opt.child, Filter)
        assert opt.child.predicate.refs == {"k"}
        out = execute(opt)
        assert sorted(out.collect()) == [(3, 30.0), (4, 40.0)]

    def test_aggregate_output_predicate_stays_above(self):
        plan = Filter(
            Aggregate(Scan("a", frame=frame_a()), key="k",
                      spec={"total": ("a", "sum")}),
            col("total") > 25,
        )
        opt = optimize(plan, required=["k", "total"])
        assert isinstance(opt, Filter)  # HAVING-shaped: cannot sink


class TestPruning:
    def test_scan_pruned_to_required_closure(self):
        plan = Filter(
            Join(Scan("a", frame=frame_a()), Scan("b", frame=frame_b()),
                 on="k"),
            col("a") > 15,
        )
        opt = optimize(plan, required=["k", "b"])
        # unused_a / unused_b never materialize: the scans sit under
        # Projects (in-memory) restricted to the needed closure
        txt = opt.explain()
        assert "unused_a" not in txt and "unused_b" not in txt

    def test_reader_scan_receives_select_and_where(self, tmp_path):
        path = tmp_path / "t.csv"
        path.write_text("k,v,unused\n1,10,0\n2,20,0\n3,30,0\n")
        calls = {}

        def reader(select=None, where=None):
            from asyncframework_tpu.sql.io import read_csv

            calls["select"] = select
            calls["where"] = where
            return read_csv(str(path), select=select, where=where)

        plan = Filter(
            Scan("t", reader=reader, schema=["k", "v", "unused"]),
            col("v") > 15,
        )
        opt = optimize(plan, required=["k", "v"])
        out = execute(opt)
        assert calls["where"] is not None  # predicate reached the reader
        assert set(calls["select"]) == {"k", "v"}  # projection pruned
        assert sorted(out.collect()) == [(2, 20), (3, 30)]


class TestPruningEdgeCases:
    def test_right_suffix_keeps_left_collision_alive(self):
        """Pruning must not drop the left copy of a colliding column when
        only its _right counterpart is selected -- the suffix exists only
        while the names collide."""
        ta = ColumnarFrame({
            "k": np.asarray([1, 2], np.int32),
            "c": np.asarray([10.0, 20.0], np.float32),
        })
        tb = ColumnarFrame({
            "k": np.asarray([1, 2], np.int32),
            "c": np.asarray([0.5, 0.25], np.float32),
        })
        plan = Join(Scan("a", frame=ta), Scan("b", frame=tb), on="k")
        opt = optimize(plan, required=["c_right"])
        out = execute(opt)
        assert "c_right" in out.columns
        assert sorted(np.asarray(out["c_right"]).tolist()) == [0.25, 0.5]

    def test_no_referenced_columns_keeps_row_count(self, tmp_path):
        """SELECT 1 FROM t: zero referenced columns must not collapse the
        reader scan to zero columns/rows."""
        path = tmp_path / "t.csv"
        path.write_text("k,v\n1,10\n2,20\n3,30\n")
        ctx = SQLContext()
        ctx.register_csv("t", str(path))
        out = ctx.sql("SELECT 1 AS one FROM t")
        assert len(out) == 3
        assert np.asarray(out["one"]).tolist() == [1, 1, 1]

    def test_folded_constant_and_carries_no_parts(self):
        e = lit(1) & lit(2)
        assert not getattr(e, "_and_parts", None)
        assert split_conjuncts(e) == [e]


class TestMultiKeyGroupBy:
    def test_frame_two_keys(self):
        f = ColumnarFrame({
            "a": np.asarray([1, 1, 2, 2, 1], np.int32),
            "b": np.asarray(["x", "y", "x", "x", "x"], object),
            "v": np.asarray([1.0, 2.0, 3.0, 4.0, 5.0], np.float32),
        })
        out = f.groupby(["a", "b"]).agg(s=("v", "sum"), n=("v", "count"))
        rows = sorted(out.collect())
        assert rows == [(1, "x", 6.0, 2), (1, "y", 2.0, 1),
                        (2, "x", 7.0, 2)]
        # lexicographic group order over (a, b)
        assert [tuple(r[:2]) for r in out.collect()] == [
            (1, "x"), (1, "y"), (2, "x"),
        ]

    def test_frame_count_multi(self):
        f = ColumnarFrame({
            "a": np.asarray([1, 1, 2], np.int32),
            "b": np.asarray([0, 1, 0], np.int32),
        })
        out = f.groupby(["a", "b"]).count()
        assert sorted(out.collect()) == [(1, 0, 1), (1, 1, 1), (2, 0, 1)]

    def test_sql_group_by_two_keys(self):
        f = ColumnarFrame({
            "region": np.asarray(["e", "w", "e", "w", "e"], object),
            "year": np.asarray([1, 1, 2, 2, 1], np.int32),
            "amt": np.asarray([10.0, 20.0, 30.0, 40.0, 50.0], np.float32),
        })
        out = sql(
            "SELECT region, year, SUM(amt) AS total FROM t "
            "GROUP BY region, year", t=f,
        )
        assert sorted(out.collect()) == [
            ("e", 1, 60.0), ("e", 2, 30.0), ("w", 1, 20.0), ("w", 2, 40.0),
        ]

    def test_sql_non_key_select_rejected(self):
        f = ColumnarFrame({
            "a": np.asarray([1], np.int32),
            "b": np.asarray([2], np.int32),
            "v": np.asarray([1.0], np.float32),
        })
        with pytest.raises(ValueError, match="GROUP BY key"):
            sql("SELECT b, SUM(v) AS s FROM t GROUP BY a", t=f)

    def test_matches_pandas_on_random_data(self):
        import pandas as pd

        rs = np.random.default_rng(3)
        a = rs.integers(0, 7, 5000).astype(np.int32)
        b = rs.integers(0, 11, 5000).astype(np.int32)
        v = rs.normal(size=5000).astype(np.float32)
        f = ColumnarFrame({"a": a, "b": b, "v": v})
        out = f.groupby(["a", "b"]).agg(s=("v", "sum"))
        got = {(int(r[0]), int(r[1])): r[2] for r in out.collect()}
        expect = pd.DataFrame({"a": a, "b": b, "v": v}).groupby(
            ["a", "b"]
        )["v"].sum()
        assert set(got) == set(expect.index)
        for key, val in expect.items():
            assert abs(got[key] - val) < 1e-2, key


class TestMultiKeyJoin:
    def _frames(self):
        ta = ColumnarFrame({
            "a": np.asarray([1, 1, 2, 3], np.int32),
            "b": np.asarray(["x", "y", "x", "z"], object),
            "v": np.asarray([10.0, 20.0, 30.0, 40.0], np.float32),
        })
        tb = ColumnarFrame({
            "a": np.asarray([1, 2, 2, 9], np.int32),
            "b": np.asarray(["x", "x", "q", "z"], object),
            "w": np.asarray([0.1, 0.2, 0.3, 0.4], np.float32),
        })
        return ta, tb

    def test_inner_two_keys(self):
        ta, tb = self._frames()
        j = ta.join(tb, on=["a", "b"], how="inner")
        assert sorted(j.collect()) == [
            (1, "x", 10.0, pytest.approx(0.1)),
            (2, "x", 30.0, pytest.approx(0.2)),
        ]

    def test_left_two_keys_fills(self):
        ta, tb = self._frames()
        j = ta.join(tb, on=["a", "b"], how="left")
        rows = {(r[0], r[1]): r[3] for r in j.collect()}
        assert rows[(1, "x")] == pytest.approx(0.1)
        assert np.isnan(rows[(1, "y")]) and np.isnan(rows[(3, "z")])

    def test_semi_anti_two_keys(self):
        ta, tb = self._frames()
        semi = ta.join(tb, on=["a", "b"], how="semi")
        anti = ta.join(tb, on=["a", "b"], how="anti")
        assert sorted((r[0], r[1]) for r in semi.collect()) == [
            (1, "x"), (2, "x"),
        ]
        assert sorted((r[0], r[1]) for r in anti.collect()) == [
            (1, "y"), (3, "z"),
        ]

    def test_full_two_keys_includes_right_misses(self):
        ta, tb = self._frames()
        j = ta.join(tb, on=["a", "b"], how="full")
        keys = sorted((int(r[0]), r[1]) for r in j.collect())
        assert (2, "q") in keys and (9, "z") in keys  # right-only rows

    def test_sql_on_and_chain(self):
        ta, tb = self._frames()
        out = sql(
            "SELECT a, b, v, w FROM ta JOIN tb ON a = a AND b = b "
            "ORDER BY a", ta=ta, tb=tb,
        )
        assert [r[0] for r in out.collect()] == [1, 2]

    def test_matches_pandas_merge(self):
        import pandas as pd

        rs = np.random.default_rng(5)
        ta = ColumnarFrame({
            "a": rs.integers(0, 6, 300).astype(np.int32),
            "b": rs.integers(0, 4, 300).astype(np.int32),
            "v": np.arange(300, dtype=np.float32),
        })
        tb = ColumnarFrame({
            "a": rs.integers(0, 6, 200).astype(np.int32),
            "b": rs.integers(0, 4, 200).astype(np.int32),
            "w": np.arange(200, dtype=np.float32),
        })
        j = ta.join(tb, on=["a", "b"], how="inner")
        pj = pd.merge(
            pd.DataFrame(ta.to_dict()), pd.DataFrame(tb.to_dict()),
            on=["a", "b"], how="inner",
        )
        assert len(j) == len(pj)
        got = sorted(map(tuple, np.asarray(j.collect())))
        exp = sorted(map(tuple, pj[["a", "b", "v", "w"]].itertuples(
            index=False, name=None
        )))
        assert got == [tuple(map(float, t)) for t in exp]


class TestMultiKeyWindowPartition:
    def test_row_number_over_two_keys(self):
        f = ColumnarFrame({
            "a": np.asarray([1, 1, 1, 2, 2], np.int32),
            "b": np.asarray(["x", "x", "y", "x", "x"], object),
            "v": np.asarray([5.0, 3.0, 9.0, 2.0, 7.0], np.float32),
        })
        out = sql(
            "SELECT a, b, v, ROW_NUMBER() OVER "
            "(PARTITION BY a, b ORDER BY v) AS rn FROM t", t=f,
        )
        got = {(r[0], r[1], r[2]): r[3] for r in out.collect()}
        assert got[(1, "x", 3.0)] == 1 and got[(1, "x", 5.0)] == 2
        assert got[(1, "y", 9.0)] == 1
        assert got[(2, "x", 2.0)] == 1 and got[(2, "x", 7.0)] == 2

    def test_sum_over_two_key_partition_matches_pandas(self):
        import pandas as pd

        rs = np.random.default_rng(4)
        a = rs.integers(0, 5, 400).astype(np.int32)
        b = rs.integers(0, 3, 400).astype(np.int32)
        v = rs.normal(size=400).astype(np.float32)
        f = ColumnarFrame({"a": a, "b": b, "v": v})
        out = f.with_window("s", "sum", "v", partition_by=["a", "b"])
        exp = pd.DataFrame({"a": a, "b": b, "v": v}).groupby(
            ["a", "b"]
        )["v"].transform("sum")
        np.testing.assert_allclose(
            np.asarray(out["s"]), exp.values, rtol=1e-4
        )


class TestMultiColumnOrderBy:
    def test_two_columns_mixed_direction(self):
        f = ColumnarFrame({
            "a": np.asarray([2, 1, 2, 1], np.int32),
            "b": np.asarray([1.0, 2.0, 3.0, 4.0], np.float32),
        })
        out = sql("SELECT a, b FROM t ORDER BY a ASC, b DESC", t=f)
        assert out.collect() == [(1, 4.0), (1, 2.0), (2, 3.0), (2, 1.0)]

    def test_group_by_then_order_by_two_outputs(self):
        f = ColumnarFrame({
            "region": np.asarray(["w", "e", "w", "e"], object),
            "year": np.asarray([2, 2, 1, 1], np.int32),
            "amt": np.asarray([1.0, 2.0, 3.0, 4.0], np.float32),
        })
        out = sql(
            "SELECT region, year, SUM(amt) AS t FROM t "
            "GROUP BY region, year ORDER BY region DESC, year", t=f,
        )
        assert out.collect() == [
            ("w", 1, 3.0), ("w", 2, 1.0), ("e", 1, 4.0), ("e", 2, 2.0),
        ]

    def test_order_by_mixes_alias_and_source_column(self):
        f = ColumnarFrame({
            "a": np.asarray([1, 2, 3, 4], np.int32),
            "b": np.asarray([0, 1, 0, 1], np.int32),
        })
        out = sql("SELECT a AS x FROM t ORDER BY b, x DESC", t=f)
        assert out.columns == ["x"]
        assert [x for (x,) in out.collect()] == [3, 1, 4, 2]

    def test_set_op_order_by_two_columns(self):
        f = ColumnarFrame({
            "a": np.asarray([2, 1], np.int32),
            "b": np.asarray([1.0, 2.0], np.float32),
        })
        g = ColumnarFrame({
            "a": np.asarray([1, 2], np.int32),
            "b": np.asarray([9.0, 1.0], np.float32),
        })
        out = sql("SELECT a, b FROM t UNION SELECT a, b FROM u "
                  "ORDER BY a, b DESC", t=f, u=g)
        assert out.collect() == [(1, 9.0), (1, 2.0), (2, 1.0)]

    def test_frame_sort_string_desc(self):
        f = ColumnarFrame({
            "k": np.asarray(["b", "a", "c"], object),
            "v": np.asarray([1, 2, 3], np.int32),
        })
        out = f.sort(["k"], ascending=[False])
        assert [r[0] for r in out.collect()] == ["c", "b", "a"]


class TestGroupCoding:
    def test_nan_keys_form_their_own_group(self):
        """pd.factorize's -1 NaN sentinel must not wrap into a real group
        (remap[-1] would): NaN keys aggregate into their own group, sorted
        last, like np.unique gave."""
        f = ColumnarFrame({
            "k": np.asarray([1.0, np.nan, 2.0, np.nan, 1.0], np.float32),
            "v": np.asarray([10.0, 1.0, 20.0, 2.0, 30.0], np.float32),
        })
        out = f.groupby("k").agg(s=("v", "sum"))
        ks = np.asarray(out["k"])
        ss = np.asarray(out["s"])
        assert len(ks) == 3
        assert np.isnan(ks[-1])  # NaN group exists, sorted last
        assert ss[np.where(ks == 1.0)[0][0]] == 40.0
        assert ss[np.where(ks == 2.0)[0][0]] == 20.0
        assert ss[-1] == 3.0  # the NaN rows' own sum

    def test_host_agg_dtype_matches_device_contract(self):
        f = ColumnarFrame({
            "k": np.asarray([1, 1, 2], np.int32),
            "v": np.asarray([1.0, 2.0, 3.0], np.float32),
        })
        out = f.groupby("k").agg(s=("v", "sum"), m=("v", "mean"),
                                 c=("v", "count"))
        assert np.asarray(out["s"]).dtype == np.float32
        assert np.asarray(out["m"]).dtype == np.float32
        assert np.asarray(out["c"]).dtype == np.int32


class TestConstantFolding:
    def test_tautology_dropped(self):
        plan = Filter(Scan("a", frame=frame_a()), lit(1) < lit(2))
        opt = optimize(plan, required=None)
        assert isinstance(opt, Scan)

    def test_parser_folds_arithmetic(self):
        out = sql("SELECT a FROM t WHERE a > 10 + 15", t=frame_a())
        assert sorted(v for (v,) in out.collect()) == [30.0, 40.0]


class TestSQLIntegration:
    """The SQL front door builds plans and optimizes before executing."""

    def test_join_query_correct_after_optimization(self):
        out = sql(
            "SELECT k, a, b FROM ta JOIN tb ON k "
            "WHERE a > 15 AND b < 3",
            ta=frame_a(), tb=frame_b(),
        )
        assert sorted(out.collect()) == [(2, 20.0, 1.0), (3, 30.0, 2.0)]

    def test_registered_csv_pushdown(self, tmp_path):
        path = tmp_path / "t.csv"
        path.write_text("k,v,s\n1,10,x\n2,20,y\n3,30,z\n")
        ctx = SQLContext()
        ctx.register_csv("t", str(path))
        out = ctx.sql("SELECT k FROM t WHERE v > 15")
        assert sorted(k for (k,) in out.collect()) == [2, 3]

    def test_group_by_after_join_with_where(self):
        ta = ColumnarFrame({
            "k": np.asarray([1, 1, 2, 2, 3], np.int32),
            "v": np.asarray([1.0, 2.0, 3.0, 4.0, 5.0], np.float32),
        })
        tb = ColumnarFrame({
            "k": np.asarray([1, 2, 3], np.int32),
            "w": np.asarray([10.0, 20.0, 30.0], np.float32),
        })
        out = sql(
            "SELECT k, SUM(v) AS sv FROM ta JOIN tb ON k "
            "WHERE w > 15 GROUP BY k ORDER BY k",
            ta=ta, tb=tb,
        )
        assert out.collect() == [(2, 7.0), (3, 5.0)]


class TestJoinBuildSide:
    def test_inner_join_result_independent_of_sizes(self):
        # the smaller side becomes the index-build side internally; results
        # and column order must be unchanged
        big = ColumnarFrame({
            "k": np.arange(1000, dtype=np.int32) % 7,
            "x": np.arange(1000, dtype=np.float32),
        })
        small = ColumnarFrame({
            "k": np.asarray([1, 3], np.int32),
            "y": np.asarray([0.5, 0.25], np.float32),
        })
        j = big.join(small, on="k", how="inner")
        assert j.columns == ["k", "x", "y"]
        rows = j.collect()
        assert len(rows) == len([v for v in range(1000) if v % 7 in (1, 3)])
        assert all(
            (k == 1 and y == 0.5) or (k == 3 and y == 0.25)
            for k, _x, y in rows
        )
