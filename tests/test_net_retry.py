"""Unit tests for the shared robustness layer (asyncframework_tpu/net/):
retry policy + decorrelated jitter + deadline, per-endpoint circuit
breakers, exactly-once client sessions / dedup windows, and the
deterministic fault-schedule machinery (ISSUE 1 tentpole)."""

import socket
import threading

import pytest

from asyncframework_tpu.conf import AsyncConf
from asyncframework_tpu.net import faults, retry, session
from asyncframework_tpu.net.retry import (
    CircuitBreaker,
    CircuitOpenError,
    RetryError,
    RetryPolicy,
)
from asyncframework_tpu.net.session import ClientSession, DedupWindow


@pytest.fixture(autouse=True)
def _clean_net_state():
    retry.reset_breakers()
    faults.clear()
    yield
    retry.reset_breakers()
    faults.clear()


def no_sleep_policy(**kw):
    kw.setdefault("sleep", lambda s: None)
    return RetryPolicy(**kw)


class TestRetryPolicy:
    def test_first_success_no_retry(self):
        calls = []
        out = no_sleep_policy().call(lambda: calls.append(1) or "ok")
        assert out == "ok" and len(calls) == 1

    def test_retries_transport_errors_then_succeeds(self):
        attempts = []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise ConnectionResetError("boom")
            return 7

        assert no_sleep_policy(max_attempts=5).call(flaky) == 7
        assert len(attempts) == 3

    def test_gives_up_with_retry_error_chaining_cause(self):
        def dead():
            raise ConnectionRefusedError("nope")

        with pytest.raises(RetryError) as ei:
            no_sleep_policy(max_attempts=3).call(dead)
        assert isinstance(ei.value.__cause__, ConnectionRefusedError)
        # RetryError IS a ConnectionError: old call sites need no new
        # except clauses
        assert isinstance(ei.value, ConnectionError)

    def test_non_transport_errors_surface_immediately(self):
        attempts = []

        def bad_request():
            attempts.append(1)
            raise RuntimeError("protocol error")

        with pytest.raises(RuntimeError):
            no_sleep_policy(max_attempts=5).call(bad_request)
        assert len(attempts) == 1

    def test_socket_timeout_is_retryable(self):
        attempts = []

        def stalls_once():
            attempts.append(1)
            if len(attempts) == 1:
                raise socket.timeout("stalled")
            return "late"

        assert no_sleep_policy().call(stalls_once) == "late"

    def test_backoff_walk_is_seeded_and_bounded(self):
        p = RetryPolicy(base_ms=50.0, max_ms=400.0, seed=7)
        gen = p.backoffs_ms()
        walk = [next(gen) for _ in range(20)]
        gen2 = RetryPolicy(base_ms=50.0, max_ms=400.0, seed=7).backoffs_ms()
        assert walk == [next(gen2) for _ in range(20)]  # replayable
        assert all(50.0 <= b <= 400.0 for b in walk)
        other = RetryPolicy(base_ms=50.0, max_ms=400.0, seed=8).backoffs_ms()
        assert walk != [next(other) for _ in range(20)]

    def test_overall_deadline_stops_before_max_attempts(self):
        attempts = []
        # deadline already passed after the first failure -> no 2nd attempt
        p = no_sleep_policy(max_attempts=100, deadline_s=1e-9)

        def dead():
            attempts.append(1)
            raise ConnectionError("x")

        with pytest.raises(RetryError):
            p.call(dead)
        assert len(attempts) == 1

    def test_on_retry_hook_sees_attempt_and_error(self):
        seen = []

        def flaky():
            if len(seen) < 2:
                raise ConnectionError("x")
            return 1

        no_sleep_policy().call(
            flaky, on_retry=lambda a, e: seen.append((a, type(e))))
        assert seen == [(1, ConnectionError), (2, ConnectionError)]

    def test_counters_accumulate(self):
        retry.reset_retry_totals()
        attempts = []

        def flaky():
            attempts.append(1)
            if len(attempts) < 3:
                raise ConnectionError("x")
            return 1

        no_sleep_policy().call(flaky)
        with pytest.raises(RetryError):
            no_sleep_policy(max_attempts=2).call(
                lambda: (_ for _ in ()).throw(ConnectionError("y")))
        t = retry.retry_totals()
        assert t["retries"] == 2 + 1 and t["giveups"] == 1

    def test_from_conf_reads_registered_entries(self):
        conf = AsyncConf({
            "async.net.retry.max.attempts": 9,
            "async.net.retry.base.ms": "10",
            "async.net.breaker.threshold": 3,
        })
        p = RetryPolicy.from_conf(conf)
        assert p.max_attempts == 9
        assert p.base_ms == 10.0
        assert p.breaker_threshold == 3
        assert p.max_ms == 2000.0  # registered default


class TestCircuitBreaker:
    def test_trips_after_threshold_and_fails_fast(self):
        t = [0.0]
        br = CircuitBreaker(threshold=3, cooldown_s=10.0,
                            clock=lambda: t[0])
        for _ in range(2):
            assert not br.record_failure()
            assert br.allow()
        assert br.record_failure()  # third consecutive -> trip
        assert not br.allow() and br.open

    def test_half_open_probe_closes_on_success(self):
        t = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=5.0, clock=lambda: t[0])
        br.record_failure()
        assert not br.allow()
        t[0] = 5.1  # cooldown over: half-open probe allowed
        assert br.allow()
        br.record_success()
        assert br.allow() and not br.open

    def test_half_open_probe_reopens_on_failure(self):
        t = [0.0]
        br = CircuitBreaker(threshold=1, cooldown_s=5.0, clock=lambda: t[0])
        br.record_failure()
        t[0] = 5.1
        assert br.allow()
        br.record_failure()  # probe failed
        assert not br.allow()  # open again, fresh cooldown from t=5.1
        t[0] = 9.0
        assert not br.allow()
        t[0] = 10.3
        assert br.allow()

    def test_policy_fails_fast_while_endpoint_open(self):
        p = no_sleep_policy(max_attempts=2, breaker_threshold=2,
                            breaker_cooldown_s=60.0)

        def dead():
            raise ConnectionError("x")

        with pytest.raises(RetryError):
            p.call(dead, endpoint="1.2.3.4:9")
        # the two failures tripped the shared breaker: next call does not
        # even run fn
        ran = []
        with pytest.raises(CircuitOpenError):
            p.call(lambda: ran.append(1), endpoint="1.2.3.4:9")
        assert ran == []
        # a different endpoint is unaffected
        assert p.call(lambda: "ok", endpoint="5.6.7.8:9") == "ok"

    def test_breakers_shared_per_endpoint_across_policies(self):
        a = no_sleep_policy(max_attempts=1, breaker_threshold=1,
                            breaker_cooldown_s=60.0)
        b = no_sleep_policy(max_attempts=1, breaker_threshold=1,
                            breaker_cooldown_s=60.0)
        with pytest.raises(RetryError):
            a.call(lambda: (_ for _ in ()).throw(ConnectionError("x")),
                   endpoint="ps:1")
        with pytest.raises(CircuitOpenError):
            b.call(lambda: "never", endpoint="ps:1")


class TestSessionDedup:
    def test_stamp_monotonic_and_thread_safe(self):
        s = ClientSession(sid="abc")
        seen = []

        def mint():
            for _ in range(200):
                seen.append(s.stamp({"op": "X"})["seq"])

        ts = [threading.Thread(target=mint) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sorted(seen) == list(range(1, 801))  # no seq ever reused

    def test_duplicate_returns_cached_reply_without_reapply(self):
        w = DedupWindow(window=8)
        h = ClientSession(sid="s1").stamp({"op": "APPEND"})
        assert w.check(h) is None  # first time: apply
        w.record(h, {"op": "APPENDED", "first": 3}, b"body")
        assert w.check(h) == ({"op": "APPENDED", "first": 3}, b"body")
        assert w.hits == 1

    def test_unstamped_headers_pass_through(self):
        w = DedupWindow()
        assert w.check({"op": "APPEND"}) is None
        w.record({"op": "APPEND"}, {"op": "APPENDED"})  # no-op
        assert w.check({"op": "APPEND"}) is None
        assert w.hits == 0

    def test_window_evicts_oldest_seq(self):
        w = DedupWindow(window=2)
        s = ClientSession(sid="s")
        hs = [s.stamp({"op": "A"}) for _ in range(3)]
        for h in hs:
            w.record(h, {"op": "OK", "seq": h["seq"]})
        assert w.check(hs[0]) is None      # evicted
        assert w.check(hs[1]) is not None  # still inside the window
        assert w.check(hs[2]) is not None

    def test_sessions_evict_lru(self):
        w = DedupWindow(window=4, max_sessions=2)
        ha = ClientSession(sid="a").stamp({"op": "A"})
        hb = ClientSession(sid="b").stamp({"op": "A"})
        hc = ClientSession(sid="c").stamp({"op": "A"})
        for h in (ha, hb, hc):
            w.record(h, {"op": "OK"})
        assert w.check(ha) is None      # LRU session dropped
        assert w.check(hc) is not None


class TestFaultSchedule:
    def test_json_round_trip(self):
        sched = (faults.FaultSchedule(seed=9)
                 .add("127.0.0.1:77", "PUSH", 2, faults.DROP_REPLY)
                 .add("*", faults.CONNECT_OP, 1, faults.CONNECT_REFUSED))
        back = faults.FaultSchedule.from_json(sched.to_json())
        assert back.seed == 9
        assert [(e.endpoint, e.op, e.nth, e.kind) for e in back.events] == [
            ("127.0.0.1:77", "PUSH", 2, faults.DROP_REPLY),
            ("*", faults.CONNECT_OP, 1, faults.CONNECT_REFUSED),
        ]

    def test_bad_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults.FaultEvent("*", "PUSH", 1, "meteor_strike")

    def test_nth_occurrence_matching_fires_once(self):
        sched = faults.FaultSchedule().add(
            "h:1", "PUSH", 3, faults.CUT_MID_FRAME)
        inj = faults.FaultInjector(sched)
        assert inj.check_send("h:1", "PUSH") is None
        assert inj.check_send("h:1", "PULL") is None  # other op: no count
        assert inj.check_send("h:2", "PUSH") is None  # other endpoint
        assert inj.check_send("h:1", "PUSH") is None
        assert inj.check_send("h:1", "PUSH") == faults.CUT_MID_FRAME
        assert inj.check_send("h:1", "PUSH") is None  # fired exactly once
        assert inj.fired == [{"endpoint": "h:1", "op": "PUSH", "nth": 3,
                              "kind": faults.CUT_MID_FRAME}]
        assert inj.remaining() == []

    def test_wildcard_port_pattern(self):
        sched = faults.FaultSchedule().add(
            "*:7077", "SUBMIT_APP", 1, faults.DROP_REPLY)
        inj = faults.FaultInjector(sched)
        assert inj.check_send("10.0.0.9:7078", "SUBMIT_APP") is None
        assert (inj.check_send("10.0.0.9:7077", "SUBMIT_APP")
                == faults.DROP_REPLY)

    def test_connect_refused_raises_at_dial(self):
        sched = faults.FaultSchedule().add(
            "h:5", faults.CONNECT_OP, 1, faults.CONNECT_REFUSED)
        inj = faults.FaultInjector(sched)
        with pytest.raises(ConnectionRefusedError):
            inj.check_connect("h:5")
        inj.check_connect("h:5")  # second dial: clean

    def test_install_from_conf_inline_json(self):
        sched = faults.FaultSchedule(seed=3).add(
            "*", "PUSH", 1, faults.STALL_READ)
        conf = AsyncConf({"async.net.fault.schedule": sched.to_json()})
        inj = faults.maybe_install_from_conf(conf)
        try:
            assert inj is faults.active()
            assert inj.schedule.seed == 3
        finally:
            faults.clear()
        assert faults.maybe_install_from_conf(AsyncConf()) is None
