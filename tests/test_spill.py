"""Shuffle spill + size accounting (VERDICT r3 item 7).

Parity targets: ``SortShuffleManager.scala:69`` (disk runs past the memory
grant), ``UnifiedMemoryManager.scala:47`` (byte accounting).  Done-criterion:
a shuffle larger than the configured bound completes WITH spill files and
byte-identical results.
"""

import numpy as np
import pytest

from asyncframework_tpu.conf import AsyncConf, set_global_conf
from asyncframework_tpu.data.spill import (
    SpillingRouter,
    _reset_totals,
    shuffle_totals,
)


@pytest.fixture()
def bounded_conf():
    # ~64 KB bound: a few thousand routed pairs force multiple spills
    set_global_conf(AsyncConf({"async.shuffle.spill.bytes": 64 * 1024}))
    yield
    set_global_conf(None)


class TestSpillingRouter:
    def test_no_spill_under_bound(self):
        r = SpillingRouter(4, memory_bytes=1 << 30)
        for i in range(100):
            r.add(i % 4, (i, i * 2))
        assert r.spill_count == 0
        assert [kv for kv in r.partition(1)] == [
            (i, i * 2) for i in range(100) if i % 4 == 1
        ]
        r.close()

    def test_spills_past_bound_and_preserves_order(self):
        r = SpillingRouter(4, memory_bytes=16 * 1024)
        n = 5000
        for i in range(n):
            r.add(i % 4, (i, float(i)))
        assert r.spill_count >= 2, "bound never triggered a spill"
        assert r.bytes_spilled > 0
        for pid in range(4):
            got = r.partition_list(pid)
            assert got == [(i, float(i)) for i in range(n) if i % 4 == pid]
        r.close()

    def test_unbounded_zero_disables(self):
        r = SpillingRouter(2, memory_bytes=0)
        for i in range(10_000):
            r.add(i % 2, (i, i))
        assert r.spill_count == 0
        r.close()

    def test_totals_accumulate(self):
        _reset_totals()
        r = SpillingRouter(2, memory_bytes=8 * 1024)
        for i in range(3000):
            r.add(i % 2, ("k%d" % i, i))
        r.partition_list(0)
        r.close()
        t = shuffle_totals()
        assert t["shuffles"] >= 1
        assert t["records_routed"] == 3000
        assert t["spill_count"] == r.spill_count > 0
        assert t["bytes_spilled"] == r.bytes_spilled > 0
        assert t["bytes_in_memory_peak"] > 0

    def test_spill_files_removed_on_close(self, tmp_path):
        r = SpillingRouter(2, memory_bytes=4 * 1024)
        for i in range(2000):
            r.add(i % 2, (i, i))
        assert r.spill_count > 0
        tmp = r._tmp.name
        import os

        assert os.path.isdir(tmp)
        r.close()
        assert not os.path.isdir(tmp)


class TestShuffleOpsSpill:
    """The real pair ops produce identical results with a tiny bound."""

    def _dataset(self, sched, n=4000):
        from asyncframework_tpu.data.dataset import DistributedDataset

        rs = np.random.default_rng(0)
        keys = rs.integers(0, 50, n)
        return DistributedDataset.from_list(
            sched, [(int(k), 1) for k in keys], num_partitions=8
        ), keys

    def test_reduce_by_key_spilled_matches_unspilled(self, bounded_conf):
        from asyncframework_tpu.engine.scheduler import JobScheduler

        sched = JobScheduler(num_workers=8)
        try:
            ds, keys = self._dataset(sched)
            out = dict(ds.reduce_by_key(lambda a, b: a + b).collect())
            t = shuffle_totals()
            expect = {int(k): int(c) for k, c in zip(
                *np.unique(keys, return_counts=True)
            )}
            assert out == expect
            # with map-side combine the routed entries are small; the word
            # count below proves the spill actually fires on real ops
        finally:
            sched.shutdown()

    def test_word_count_with_spills_correct(self, bounded_conf):
        """group_by_key (no map-side shrink per partition beyond combine)
        over enough pairs to overflow a 64 KB bound: spills happen AND the
        result matches the unbounded run."""
        from asyncframework_tpu.data.dataset import DistributedDataset
        from asyncframework_tpu.engine.scheduler import JobScheduler

        _reset_totals()
        sched = JobScheduler(num_workers=8)
        try:
            rs = np.random.default_rng(1)
            pairs = [(f"w{int(k):03d}", 1) for k in rs.integers(0, 200, 20_000)]
            ds = DistributedDataset.from_list(sched, pairs, num_partitions=8)
            routed = ds.partition_by(8)
            out = dict(
                routed.reduce_by_key(lambda a, b: a + b).collect()
            )
            t = shuffle_totals()
            assert t["spill_count"] > 0, "bound never forced a spill"
            assert t["bytes_spilled"] > 0
            from collections import Counter

            expect = Counter(k for k, _ in pairs)
            assert out == dict(expect)
        finally:
            sched.shutdown()

    def test_sort_by_key_spilled_global_order(self, bounded_conf):
        from asyncframework_tpu.data.dataset import DistributedDataset
        from asyncframework_tpu.engine.scheduler import JobScheduler

        _reset_totals()
        sched = JobScheduler(num_workers=8)
        try:
            rs = np.random.default_rng(2)
            vals = rs.permutation(10_000)
            ds = DistributedDataset.from_list(
                sched, [(int(v), int(v) * 3) for v in vals],
                num_partitions=8,
            )
            srt = ds.sort_by_key(num_partitions=8)
            got = srt.collect()
            assert [k for k, _ in got] == sorted(int(v) for v in vals)
            assert shuffle_totals()["spill_count"] > 0
        finally:
            sched.shutdown()
