"""Partition-tolerant membership: leases, epoch fencing, gray failures
(ISSUE 9).

The correctness spine:

- **partitions and delays are first-class faults** (net/faults.py): a
  scheduled ``PartitionEvent`` blackholes an endpoint set bidirectionally
  at the frame choke point and heals on schedule (or explicitly); a
  ``delay`` event adds seeded latency while letting ops through -- the
  slow-but-alive gray member;
- **leases, not pid probes, decide death**: silence past the suspect
  threshold marks SUSPECT (no replacement!), only lease expiry (or
  verified process exit) escalates to DEAD, and the pid probe checks the
  process START TIME so a recycled pid can never impersonate a member;
- **epoch fencing makes replacements safe**: every incarnation runs at a
  minted monotonic epoch (checkpoint-persisted, controller-passed),
  clients stamp it on every PULL/PUSH/SUBSCRIBE, and a server answers
  stale-epoch ops REJECT_FENCED -- a deposed client self-heals by
  adopting the minted epoch, while a zombie server (one that has seen a
  successor's epoch) refuses everything.  Fencing OFF is the
  byte-identical legacy wire: no ``ep`` keys anywhere;
- **the acceptance run** (``fence`` marker, rides every
  bin/chaos_sweep.py seed): a 3-shard group of REAL OS processes is
  PARTITIONED (not killed) from its controller past lease expiry; the
  controller suspects, expires the lease, fences the epoch, and
  relaunches the range; stale-epoch pushes are rejected REJECT_FENCED
  (counted), and the run completes with full coverage and a decreasing
  loss trajectory.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu.conf import AsyncConf, set_global_conf
from asyncframework_tpu.net import faults
from asyncframework_tpu.net import frame
from asyncframework_tpu.net import health
from asyncframework_tpu.net import reset_net_totals
from asyncframework_tpu.net.retry import (
    RetryError,
    RetryPolicy,
    remaining_deadline_s,
    reset_breakers,
)
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.parallel import shardgroup as sg
from asyncframework_tpu.parallel import supervisor as sup_mod
from asyncframework_tpu.solvers import SolverConfig
from asyncframework_tpu.utils.clock import ManualClock

pytestmark = pytest.mark.fence

CHILD = Path(__file__).parent / "ps_dcn_child.py"
CHAOS_SEED = int(os.environ.get("ASYNC_CHAOS_SEED", "7"))


def make_cfg(**kw):
    defaults = dict(
        num_workers=2, num_iterations=60, gamma=0.5, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.0, printer_freq=20, seed=42,
        calibration_iters=5, run_timeout_s=60.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture(autouse=True)
def _clean_state():
    """Injectors, breakers, counters, and the global conf are
    process-global; fencing tests must neither inherit nor leak them."""
    faults.clear()
    reset_net_totals()
    reset_breakers()
    sg.reset_shard_totals()
    sup_mod.reset_recovery_totals()
    health.reset_gray_totals()
    set_global_conf(AsyncConf())
    yield
    faults.clear()
    reset_net_totals()
    reset_breakers()
    sg.reset_shard_totals()
    sup_mod.reset_recovery_totals()
    health.reset_gray_totals()
    set_global_conf(None)


def _snappy_retry(**kw):
    kw.setdefault("max_attempts", 2)
    kw.setdefault("base_ms", 5.0)
    kw.setdefault("max_ms", 20.0)
    kw.setdefault("attempt_timeout_s", 2.0)
    return RetryPolicy(**kw)


# ---------------------------------------------------- partition/delay faults
class TestPartitionDelayFaults:
    def test_schedule_json_round_trip(self):
        s = faults.FaultSchedule(seed=11)
        s.add("*:70", "PUSH", 2, faults.DROP_REPLY)
        s.add_delay("h:1", "PULL|SUBSCRIBE", 25.0, jitter_ms=10.0,
                    nth=3, count=0)
        s.add_partition(["*:70", "h:2"], start_s=0.5, duration_s=2.0)
        s2 = faults.FaultSchedule.from_json(s.to_json())
        assert s2.seed == 11
        assert len(s2.events) == 2 and len(s2.partitions) == 1
        d = s2.events[1]
        assert d.kind == faults.DELAY and d.delay_ms == 25.0
        assert d.jitter_ms == 10.0 and d.nth == 3 and d.count == 0
        p = s2.partitions[0]
        assert p.endpoints == ["*:70", "h:2"]
        assert p.start_s == 0.5 and p.duration_s == 2.0
        # legacy schedules (no partitions key, no delay fields) still load
        legacy = faults.FaultSchedule.from_json(
            '{"seed": 1, "events": [{"endpoint": "*", "op": "PULL", '
            '"nth": 1, "kind": "drop_reply"}]}'
        )
        assert len(legacy.events) == 1 and not legacy.partitions

    def test_partition_blackholes_until_healed(self):
        cfg = make_cfg()
        ps = ps_dcn.ParameterServer(cfg, 6, 64, port=0).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port,
                                 retry=_snappy_retry())
            assert cl.pull(0) is not None  # healthy before the cut
            sched = faults.FaultSchedule(seed=CHAOS_SEED)
            sched.add_partition([f"*:{ps.port}"])  # until healed
            inj = faults.install(faults.FaultInjector(sched))
            with pytest.raises((ConnectionError, OSError)):
                cl.pull(0)
            assert any(f["kind"] == faults.PARTITION for f in inj.fired)
            inj.heal_partitions()
            reset_breakers()  # the storm tripped the endpoint breaker
            got = cl.pull(0)
            assert got is not None, "healed partition must serve again"
            cl.bye()
        finally:
            faults.clear()
            ps.stop()

    def test_partition_heals_on_schedule(self):
        cfg = make_cfg()
        ps = ps_dcn.ParameterServer(cfg, 6, 64, port=0).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port,
                                 retry=_snappy_retry())
            sched = faults.FaultSchedule(seed=CHAOS_SEED)
            sched.add_partition([f"*:{ps.port}"], start_s=0.0,
                                duration_s=0.5)
            inj = faults.install(faults.FaultInjector(sched))
            assert inj.partition_active(f"127.0.0.1:{ps.port}")
            with pytest.raises((ConnectionError, OSError)):
                cl.pull(0)
            time.sleep(0.6)
            assert not inj.partition_active(f"127.0.0.1:{ps.port}")
            reset_breakers()
            assert cl.pull(0) is not None
            cl.bye()
        finally:
            faults.clear()
            ps.stop()

    def test_delay_fault_adds_latency_and_lets_op_through(self):
        cfg = make_cfg()
        ps = ps_dcn.ParameterServer(cfg, 6, 64, port=0).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port)
            t0 = time.monotonic()
            assert cl.pull(0) is not None
            base = time.monotonic() - t0
            sched = faults.FaultSchedule(seed=CHAOS_SEED)
            sched.add_delay(f"*:{ps.port}", "PULL", 80.0, count=0)
            faults.install(faults.FaultInjector(sched))
            t0 = time.monotonic()
            assert cl.pull(0) is not None  # delayed, not dropped
            delayed = time.monotonic() - t0
            assert delayed >= base + 0.06, (base, delayed)
            assert faults.faults_fired_total() >= 1
            cl.bye()
        finally:
            faults.clear()
            ps.stop()

    def test_delay_jitter_is_seeded_deterministic(self):
        def draws(seed):
            s = faults.FaultSchedule(seed=seed)
            s.add_delay("*", "*", 1.0, jitter_ms=50.0, count=0)
            inj = faults.FaultInjector(s)
            return [inj.delay_for("e:1", "PULL") for _ in range(5)]

        assert draws(3) == draws(3)
        assert draws(3) != draws(4)

    def test_wan_profile_and_merge(self):
        wan = faults.wan_profile_schedule(9)
        assert wan.to_json() == faults.wan_profile_schedule(9).to_json()
        assert wan.to_json() != faults.wan_profile_schedule(10).to_json()
        assert any(e.kind == faults.DELAY for e in wan.events)
        assert any(e.kind == faults.DROP_REPLY for e in wan.events)
        base = faults.FaultSchedule(seed=1).add("*", "PULL", 1,
                                                faults.DROP_REPLY)
        merged = faults.merge_schedules(base, wan)
        assert len(merged.events) == 1 + len(wan.events)
        assert merged.seed == 1
        assert faults.merge_schedules(base, None) is base
        # env-driven selection (bin/chaos_sweep.py --net-profile)
        os.environ["ASYNC_CHAOS_NET_PROFILE"] = "wan"
        try:
            prof = faults.profile_schedule_from_env(9)
            assert prof is not None and prof.to_json() == wan.to_json()
        finally:
            del os.environ["ASYNC_CHAOS_NET_PROFILE"]
        assert faults.profile_schedule_from_env(9) is None


# ------------------------------------------------------- leases + suspicion
class TestLeaseSuspicion:
    def _sup(self, **kw):
        kw.setdefault("dead_after_s", 10.0)
        # fence on: epochs are only minted under fencing (a fence-off
        # run must not report fencing activity), and these tests assert
        # the minting
        kw.setdefault("fence", True)
        clock = ManualClock()
        sup = sup_mod.ElasticSupervisor(2, clock=clock, **kw)
        return sup, clock

    def test_silence_suspects_then_expires_lease_then_fences(self):
        sup, clock = self._sup()
        sup.register("p1", [0, 1], pid=None)
        sup.touch(0, "p1")
        sup.touch(1, "p1")
        # inside the suspect window: live
        clock.advance(4_000)
        assert sup.check_once() == []
        assert sup.membership()[0]["state"] == sup_mod.LIVE
        # past suspect threshold (half the lease), inside the lease:
        # SUSPECT -- surfaced, but NO replacement yet
        clock.advance(2_000)
        assert sup.check_once() == []
        m = sup.membership()[0]
        assert m["state"] == sup_mod.SUSPECT
        assert m["epoch"] == 0
        assert sup.counters()["suspicions"] >= 1
        assert sup.live_worker_count() == 2  # suspects count live
        # contact clears silence-suspicion (the lease renewal)
        sup.touch(0, "p1")
        assert sup.membership()[0]["state"] == sup_mod.LIVE
        # lease expiry: DEAD + fencing epoch minted BEFORE replacement
        clock.advance(11_000)
        dead = sup.check_once()
        assert set(dead) == {0, 1}
        m = sup.membership()[0]
        assert m["state"] == sup_mod.DEAD
        assert m["epoch"] == 1 and sup.epoch_of(0) == 1
        assert sup.counters()["lease_expiries"] >= 2
        # a second expiry episode mints a HIGHER epoch
        sup.register("p2", [0], pid=None)
        clock.advance(11_000)
        sup.check_once()
        assert sup.epoch_of(0) == 2

    def test_fence_off_supervisor_mints_no_epochs(self):
        sup, clock = self._sup(fence=False)
        sup.register("p1", [0], pid=None)
        sup.touch(0, "p1")
        clock.advance(11_000)
        assert 0 in sup.check_once()
        assert sup.epoch_of(0) == 0
        assert sup.membership()[0]["epoch"] == 0

    def test_lease_s_overrides_dead_after(self):
        sup, clock = self._sup(lease_s=3.0)
        assert sup.lease_ms == 3_000.0
        assert sup.suspect_after_ms == 1_500.0
        sup.register("p1", [0], pid=None)
        sup.touch(0, "p1")
        clock.advance(3_100)
        assert 0 in sup.check_once()

    def test_rtt_suspicion_overlays_and_survives_contact(self):
        sup, clock = self._sup()
        sup.register("p1", [0], pid=None)
        sup.touch(0, "p1")
        sup.suspect(0, reason="rtt")
        assert sup.state_of(0) == sup_mod.SUSPECT
        # contact does NOT clear latency suspicion (a gray member's whole
        # signature is that it keeps answering)
        sup.touch(0, "p1")
        assert sup.state_of(0) == sup_mod.SUSPECT
        # suspects still count LIVE (never-contacted slots do too):
        # suspicion demotes routing, it does not shrink cohorts
        assert sup.live_worker_count() == 2
        sup.unsuspect(0)
        assert sup.state_of(0) == sup_mod.LIVE
        # DEAD dominates any suspicion
        sup.suspect(0)
        clock.advance(11_000)
        sup.check_once()
        assert sup.state_of(0) == sup_mod.DEAD

    def test_rtt_suspector_cohort_detection(self):
        det = health.RttSuspector(factor=3.0, min_ms=1.0, alpha=0.5,
                                  min_samples=3)
        for _ in range(6):
            det.observe("a:1", 10.0)
            det.observe("b:1", 12.0)
            sus = det.observe("c:1", 200.0)
        assert sus and det.is_suspect("c:1")
        assert not det.is_suspect("a:1")
        assert health.gray_totals().get("suspicions", 0) >= 1
        # recovery: the outlier normalizes and un-suspects itself
        for _ in range(20):
            det.observe("c:1", 10.0)
        assert not det.is_suspect("c:1")
        assert health.gray_totals().get("recoveries", 0) >= 1

    def test_rtt_suspector_needs_a_cohort(self):
        det = health.RttSuspector(factor=3.0, min_ms=1.0, min_samples=2)
        for _ in range(10):
            assert not det.observe("only:1", 5_000.0)


# ------------------------------------------------- pid reuse (satellite 1)
class TestPidReuseProbe:
    def test_start_time_mismatch_is_exited(self):
        """A live pid whose /proc start time no longer matches the
        registered member's is a RECYCLED pid: the probe must report the
        member dead, not false-alive."""
        me = os.getpid()
        real = sup_mod.proc_start_time(me)
        assert real is not None
        host = socket.gethostname()
        honest = sup_mod._ProcRecord("p", 0.0, pid=me, host=host,
                                     pid_start=real)
        assert not honest.pid_gone()
        imposter = sup_mod._ProcRecord("p", 0.0, pid=me, host=host,
                                       pid_start=real + 12345.0)
        assert imposter.pid_gone()

    def test_supervisor_declares_recycled_pid_dead_immediately(self):
        clock = ManualClock()
        sup = sup_mod.ElasticSupervisor(1, dead_after_s=1e6, clock=clock)
        sup.register("p1", [0], pid=os.getpid(),
                     host=socket.gethostname(),
                     pid_start=sup_mod.proc_start_time(os.getpid())
                     + 99.0)
        sup.touch(0, "p1")
        clock.advance(10)  # far inside the lease: only the pid says dead
        assert sup.check_once() == [0]

    def test_registration_reads_local_start_time(self):
        rec = sup_mod._ProcRecord("p", 0.0, pid=os.getpid(),
                                  host=socket.gethostname())
        assert rec.pid_start == sup_mod.proc_start_time(os.getpid())

    def test_hello_carries_pstart_end_to_end(self):
        cfg = make_cfg()
        sup = sup_mod.ElasticSupervisor(2, dead_after_s=30.0)
        ps = ps_dcn.ParameterServer(cfg, 6, 64, port=0,
                                    supervisor=sup).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, proc="me")
            cl.hello("me", [0], pid=os.getpid())
            rec = sup._procs["me"]
            assert rec.pid_start == sup_mod.proc_start_time(os.getpid())
            cl.bye()
        finally:
            ps.stop()


# -------------------------------------------- socket deadline (satellite 2)
class TestSocketDeadline:
    def test_real_stall_cannot_outlive_the_deadline(self):
        """A server that accepts, reads the request, and never replies --
        the real gray peer (stall_read's honest sibling).  The policy's
        deadline must bound the call even though the per-attempt socket
        timeout (30 s here) is far larger: the socket layer caps its
        blocking reads to the remaining deadline."""
        srv = socket.create_server(("127.0.0.1", 0))
        srv.settimeout(5.0)
        stop = threading.Event()

        def stall():
            conns = []
            while not stop.is_set():
                try:
                    c, _ = srv.accept()
                    conns.append(c)  # read nothing, reply nothing
                except socket.timeout:
                    continue
                except OSError:
                    break
            for c in conns:
                c.close()

        t = threading.Thread(target=stall, daemon=True)
        t.start()
        policy = RetryPolicy(max_attempts=5, base_ms=5.0, max_ms=20.0,
                             attempt_timeout_s=30.0, deadline_s=1.0)
        addr = srv.getsockname()

        def attempt():
            s = frame.connect(addr, timeout=30.0)
            try:
                frame.send_msg(s, {"op": "PULL", "wid": 0})
                return frame.recv_msg(s)
            finally:
                s.close()

        t0 = time.monotonic()
        with pytest.raises((RetryError, ConnectionError, OSError)):
            policy.call(attempt, endpoint=f"stall:{addr[1]}")
        elapsed = time.monotonic() - t0
        assert elapsed < 5.0, (
            f"stalled read held the caller {elapsed:.1f}s past its "
            f"1s deadline"
        )
        stop.set()
        srv.close()

    def test_deadline_cap_does_not_ratchet_reused_sockets(self):
        """A cap tightens a REUSED socket's timeout to the dying call's
        remaining deadline; the next op must re-derive from the socket's
        RESTING timeout (restore with no deadline, min(resting, fresh
        remaining) with one) -- not inherit the stale near-zero value."""
        a, b = socket.socketpair()
        try:
            a.settimeout(30.0)
            policy = RetryPolicy(max_attempts=1, attempt_timeout_s=30.0,
                                 deadline_s=0.3)
            with pytest.raises((RetryError, OSError)):
                policy.call(lambda: frame.recv_msg(a))  # blocks -> cap
            assert a.gettimeout() is not None and a.gettimeout() <= 0.3
            # next op with NO deadline: resting timeout restored
            frame._deadline_cap(a)
            assert a.gettimeout() == 30.0
            # next op with a FRESH deadline: min(resting, remaining),
            # never the previous call's leftovers
            fresh = RetryPolicy(max_attempts=1, deadline_s=10.0)
            seen = []
            fresh.call(lambda: seen.append(
                (frame._deadline_cap(a), a.gettimeout())))
            assert 0 < seen[0][1] <= 10.0
            frame._deadline_cap(a)
            assert a.gettimeout() == 30.0
        finally:
            a.close()
            b.close()

    def test_deadline_tls_is_scoped_to_the_call(self):
        assert remaining_deadline_s() is None
        policy = RetryPolicy(max_attempts=1, deadline_s=5.0)
        seen = []
        policy.call(lambda: seen.append(remaining_deadline_s()))
        assert seen[0] is not None and 0 < seen[0] <= 5.0
        assert remaining_deadline_s() is None

    def test_no_deadline_means_no_tls(self):
        policy = RetryPolicy(max_attempts=1)
        seen = []
        policy.call(lambda: seen.append(remaining_deadline_s()))
        assert seen[0] is None


# ----------------------------------------------------------- epoch fencing
class TestEpochFencing:
    def test_fence_off_is_legacy_wire_no_ep_keys(self):
        cfg = make_cfg()
        ps = ps_dcn.ParameterServer(cfg, 6, 64, port=0).start()
        try:
            assert ps.epoch == 0
            s = frame.connect(("127.0.0.1", ps.port))
            frame.send_msg(s, {"op": "PULL", "wid": 0})
            hdr, _ = frame.recv_msg(s)
            assert hdr["op"] == "MODEL" and "ep" not in hdr
            frame.send_msg(s, {"op": "HELLO", "proc": "x", "wids": [0]})
            hdr, _ = frame.recv_msg(s)
            assert "epoch" not in hdr and "epochs" not in hdr
            s.close()
            assert ps.fenced_rejects == 0
        finally:
            ps.stop()

    def test_conf_derives_epoch(self):
        set_global_conf(AsyncConf({"async.fence.enabled": True}))
        ps = ps_dcn.ParameterServer(make_cfg(), 6, 64, port=0)
        assert ps.epoch == 1
        ps.stop()
        set_global_conf(AsyncConf())
        ps2 = ps_dcn.ParameterServer(make_cfg(), 6, 64, port=0)
        assert ps2.epoch == 0
        ps2.stop()

    def test_stale_pull_self_heals(self):
        ps = ps_dcn.ParameterServer(make_cfg(), 6, 64, port=0,
                                    epoch=2).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, epoch=1)
            got = cl.pull(0)
            assert got is not None
            assert cl.epoch == 2 and cl.fenced_replies == 1
            assert ps.fenced_rejects == 1
            # welcome advertises the epoch for fresh joiners
            welcome = cl.hello("p", [0])
            assert welcome.get("epoch") == 2
            cl.bye()
        finally:
            ps.stop()

    def test_stale_push_dropped_then_healed_next_round(self):
        ps = ps_dcn.ParameterServer(make_cfg(), 6, 64, port=0,
                                    epoch=2).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, epoch=1)
            acc, done = cl.push(0, 0, np.zeros(6, np.float32))
            assert (acc, done) == (False, False)
            assert cl.epoch == 2 and ps.fenced_rejects == 1
            acc, _done = cl.push(0, 0, np.zeros(6, np.float32))
            assert acc, "current-epoch push must be admitted"
            # the fenced gradient was never merged: exactly one accept
            assert ps.accepted == 1
            cl.bye()
        finally:
            ps.stop()

    def test_zombie_server_refuses_everything_stamped(self):
        """A server that has SEEN a successor epoch is a zombie: it
        refuses every stamped op -- even from same-epoch stragglers --
        so it can neither mutate nor serve the range."""
        ps = ps_dcn.ParameterServer(make_cfg(), 6, 64, port=0,
                                    epoch=1).start()
        try:
            ahead = ps_dcn.PSClient("127.0.0.1", ps.port, epoch=2)
            with pytest.raises(ps_dcn.FencedError):
                ahead.pull(0)
            assert ps._fenced_above == 2
            peer = ps_dcn.PSClient("127.0.0.1", ps.port, epoch=1)
            with pytest.raises(ps_dcn.FencedError):
                peer.pull(0)
            # a same-epoch PUSH is refused too; the reply names the
            # successor epoch, so the pusher heals toward the real owner
            pusher = ps_dcn.PSClient("127.0.0.1", ps.port, epoch=1)
            acc, done = pusher.push(0, 0, np.zeros(6, np.float32))
            assert (acc, done) == (False, False)
            assert pusher.epoch == 2
            assert ps.accepted == 0, "the zombie merged a gradient"
            assert ps.fenced_rejects >= 3
        finally:
            ps.stop()

    def test_fenced_push_retry_reanswers_from_dedup(self):
        """A fenced PUSH verdict is recorded in the dedup window: a
        retry of the same (sid, seq) re-answers REJECT_FENCED instead of
        racing a fresh admission."""
        ps = ps_dcn.ParameterServer(make_cfg(), 6, 64, port=0,
                                    epoch=2).start()
        try:
            s = frame.connect(("127.0.0.1", ps.port))
            hdr = {"op": "PUSH", "wid": 0, "ts": 0, "ep": 1,
                   "sid": "abc", "seq": 1}
            payload = np.zeros(6, np.float32).tobytes()
            frame.send_msg(s, hdr, payload)
            r1, _ = frame.recv_msg(s)
            assert r1["op"] == "REJECT_FENCED" and r1["epoch"] == 2
            frame.send_msg(s, hdr, payload)  # same stamp, retried
            r2, _ = frame.recv_msg(s)
            assert r2["op"] == "REJECT_FENCED"
            assert ps.fenced_rejects == 1, "dedup answered the retry"
            s.close()
        finally:
            ps.stop()

    def test_whole_stale_window_drops_without_zombie_misread(self):
        """>= 2 in-flight pushes stamped under a deposed epoch (the
        windowed replay onto a fenced range's replacement): the FIRST
        fence advances the client epoch, and the remaining stale entries
        must still drop gracefully -- judged against their OWN stamps --
        instead of misreading the healthy replacement as a zombie."""
        ps = ps_dcn.ParameterServer(make_cfg(), 6, 64, port=0,
                                    epoch=2).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, epoch=1)
            cl.push_start(0, 0, np.zeros(6, np.float32))
            cl.push_start(0, 0, np.ones(6, np.float32))
            assert cl.push_finish() == (False, False)
            assert cl.epoch == 2  # healed by the first fence
            assert cl.push_finish() == (False, False)  # NOT FencedError
            assert ps.fenced_rejects == 2 and ps.accepted == 0
            # and the healed client's next windowed push is admitted
            cl.push_start(0, 0, np.zeros(6, np.float32))
            acc, _done = cl.push_finish()
            assert acc
            cl.bye()
        finally:
            ps.stop()

    def test_subscribe_is_fenced_and_heals(self):
        ps = ps_dcn.ParameterServer(make_cfg(), 6, 64, port=0,
                                    epoch=3).start()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, epoch=1,
                                 pull_mode="delta")
            got = cl.subscribe(0)
            assert got is not None and cl.epoch == 3
            assert ps.fenced_rejects == 1
            cl.bye()
        finally:
            ps.stop()

    def test_checkpoint_restart_bumps_incarnation(self, tmp_path):
        p = str(tmp_path / "ps.npz")
        cfg = make_cfg()
        ps = ps_dcn.ParameterServer(cfg, 6, 64, port=0, epoch=1,
                                    checkpoint_path=p).start()
        ps.save_checkpoint()
        ps.stop()
        ps2 = ps_dcn.ParameterServer(cfg, 6, 64, port=0, epoch=1,
                                     checkpoint_path=p)
        assert ps2.epoch == 2, "every incarnation is a new epoch"
        ps2.save_checkpoint()
        ps2.stop()
        # a controller that already counted MORE fences wins via max
        ps3 = ps_dcn.ParameterServer(cfg, 6, 64, port=0, epoch=7,
                                     checkpoint_path=p)
        assert ps3.epoch == 7
        ps3.stop()
        # fencing off: the checkpoint's epoch is inert
        ps4 = ps_dcn.ParameterServer(cfg, 6, 64, port=0, epoch=0,
                                     checkpoint_path=p)
        assert ps4.epoch == 0
        ps4.stop()

    def test_fence_on_is_step_identical_to_off(self, devices8):
        """Fencing changes header bytes, never semantics: the same seeded
        run converges to the same model with the same accept/drop record
        whether fencing is on or off (the acceptance criterion's
        byte/step-identity-with-conf-off, asserted from the ON side)."""
        from asyncframework_tpu.data.sharded import ShardedDataset

        results = []
        for fence in (False, True):
            set_global_conf(AsyncConf({"async.fence.enabled": fence}))
            # ONE worker: the strictly serial pull->push loop makes the
            # whole run deterministic, so the two arms are comparable
            cfg = make_cfg(num_workers=1, num_iterations=40)
            ds = ShardedDataset.generate_on_device(
                256, 6, 1, devices=devices8[:1], seed=5, noise=0.01)
            ps = ps_dcn.ParameterServer(cfg, 6, 256, port=0,
                                        device=devices8[0]).start()
            shards = {0: ds.shard(0)}
            ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, [0], shards, cfg, 6, 256,
                deadline_s=60.0)
            assert ps.wait_done(timeout_s=10.0)
            results.append((ps.accepted, ps.dropped, ps._clock,
                            np.asarray(ps._w).copy(), ps.epoch))
            ps.stop()
        (a0, d0, c0, w0, e0), (a1, d1, c1, w1, e1) = results
        assert (e0, e1) == (0, 1)
        assert (a0, d0, c0) == (a1, d1, c1)
        np.testing.assert_array_equal(w0, w1)


# ------------------------------------------------------ sharded group fence
class TestShardedFencing:
    def test_welcome_hands_out_epoch_vector(self, devices8):
        set_global_conf(AsyncConf({"async.fence.enabled": True}))
        cfg = make_cfg(num_workers=2)
        ps_list, smap = sg.launch_inprocess_group(
            cfg, 9, 256, 3, device=devices8[0])
        try:
            assert [p.epoch for p in ps_list] == [1, 1, 1]
            cl = ps_dcn.PSClient("127.0.0.1", ps_list[0].port, proc="w")
            welcome = cl.hello("w", [0, 1], pid=os.getpid())
            assert welcome.get("epochs") == [1, 1, 1]
            cl.bye()
            smap2, epochs, _ep = sg.fetch_group_info(
                "127.0.0.1", ps_list[1].port)
            assert smap2 is not None and epochs == [1, 1, 1]
        finally:
            for p in ps_list:
                p.stop()

    def test_per_shard_fence_heals_independently(self, devices8):
        set_global_conf(AsyncConf({"async.fence.enabled": True}))
        cfg = make_cfg(num_workers=2)
        ps_list, smap = sg.launch_inprocess_group(
            cfg, 9, 256, 3, device=devices8[0])
        try:
            cl = sg.ShardedPSClient(smap, epochs=[1, 1, 1], proc="w")
            got = cl.pull(0)
            assert got is not None
            ts, w, _avg, _cal = got
            # shard 1 is fenced out from under the client (a relaunch)
            ps_list[1].epoch = 2
            acc, done = cl.push(0, ts, np.zeros(9, np.float32))
            # the round lands (primary's verdict); shard 1's sub-push was
            # fenced + the sub-client adopted the minted epoch
            assert cl.clients[1].epoch == 2
            assert ps_list[1].fenced_rejects >= 1
            got = cl.pull(0)
            assert got is not None
            acc, _done = cl.push(0, got[0], np.zeros(9, np.float32))
            assert acc, "healed client's next round is admitted"
            assert cl.clients[1].fenced_replies >= 1
            cl.bye()
        finally:
            for p in ps_list:
                p.stop()


# ---------------------------------------- THE acceptance run (real procs)
class TestPartitionFenceRelaunch:
    """Partition (not kill) one shard of a real 3-shard group past lease
    expiry: the controller suspects, expires the lease, mints a fencing
    epoch, and relaunches the range; stale-epoch pushes are rejected
    REJECT_FENCED; the run completes with full coverage and a decreasing
    loss trajectory."""

    NW, N, D = 8, 4096, 24
    # a longer run than the SIGKILL acceptance (test_shardgroup.py): the
    # fence needs a full LEASE of probe silence before it fires, and the
    # partition must land while the run is still in flight even on a
    # fast rig -- 500 iters can finish inside the lease window
    ITERS = 1500

    def _worker(self, port, wpid, tmp):
        env = dict(os.environ)
        env.update({
            "PS_ROLE": "worker", "PS_PORT": str(port),
            "PS_WORKER_ID": str(wpid), "PS_NUM_WORKER_PROCS": "2",
            "PS_NUM_ITER": str(self.ITERS),
            "JAX_PLATFORMS": "cpu",
        })
        return subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.PIPE,
            stderr=open(os.path.join(tmp, f"worker{wpid}.stderr.log"),
                        "w"),
            text=True,
        )

    def test_partition_shard_fence_and_relaunch(self, tmp_path):
        # cfg MUST mirror tests/ps_dcn_child.py::config()
        cfg = SolverConfig(
            num_workers=self.NW, num_iterations=self.ITERS, gamma=1.2,
            taw=2**31 - 1, batch_rate=0.3, bucket_ratio=0.5,
            printer_freq=50, seed=42, calibration_iters=20,
            run_timeout_s=120.0,
        )
        group = sg.ShardGroup(
            cfg, self.D, self.N, 3, checkpoint_dir=str(tmp_path),
            worker_procs=2, dead_after_s=1.0, check_interval_s=0.2,
            stderr_dir=str(tmp_path),
            conf_overlays={"async.fence.enabled": True},
        ).start()
        assert group.fence and group.epochs_wire() == [1, 1, 1]
        workers = []
        try:
            port0 = group.port_of(0)
            port1 = group.port_of(1)
            workers = [self._worker(port0, 0, str(tmp_path)),
                       self._worker(port0, 1, str(tmp_path))]
            # let shard 1 make durable progress first (its cadence
            # checkpoint must exist so the relaunch recovers state);
            # threshold seeded so every sweep seed cuts at a different
            # point of the run
            cut_after = 60 + (CHAOS_SEED % 40)
            watch = ps_dcn.PSClient("127.0.0.1", port1)
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                got = watch.subscribe(0)
                if got is not None and got[2] >= cut_after:
                    break
                time.sleep(0.02)
            else:
                pytest.fail("shard 1 never reached the cut threshold")
            try:
                watch.bye()
            except (ConnectionError, OSError):
                pass
            # PARTITION shard 1 away from this (controller) process: its
            # process stays alive and serving -- the zombie.  Workers are
            # separate processes and deliberately NOT partitioned: they
            # keep talking to the zombie until the fence.  The wan
            # profile (chaos_sweep --net-profile) overlays here when set.
            sched = faults.FaultSchedule(seed=CHAOS_SEED)
            sched.add_partition([f"*:{port1}"], duration_s=4.0)
            sched = faults.merge_schedules(
                sched, faults.profile_schedule_from_env(CHAOS_SEED))
            faults.install(faults.FaultInjector(sched))
            # the controller's probes now fail: SUSPECT at half the
            # lease, lease expiry at 1 s, epoch fence, relaunch
            deadline = time.monotonic() + 45.0
            while time.monotonic() < deadline:
                if group.restarts_of(1) >= 1:
                    break
                time.sleep(0.1)
            assert group.restarts_of(1) >= 1, \
                "partitioned shard was never fenced + relaunched"
            # death was declared by LEASE EXPIRY, not process exit: the
            # zombie's pid was still alive when the fence fired (the
            # controller kills it only afterwards, to reclaim the pinned
            # port -- cross-host the zombie would simply stay fenced)
            assert group.sup.counters()["lease_expiries"] >= 1
            assert group.epoch_of(1) >= 2, "no fencing epoch was minted"
            faults.clear()  # heal: the controller sees the group again
            # wait until the relaunched shard 1 answers and is stable
            deadline = time.monotonic() + 30.0
            epoch1 = 0
            while time.monotonic() < deadline:
                try:
                    hdr = sg._oneshot("127.0.0.1", group.port_of(1),
                                      {"op": "SHARDMAP"}, timeout_s=2.0)
                    epoch1 = int(hdr.get("epoch", 0))
                    break
                except (ConnectionError, OSError):
                    time.sleep(0.2)
            assert epoch1 >= 2, f"relaunched shard epoch {epoch1}"
            # THE fencing assertion: a push stamped with the deposed
            # epoch -- exactly what the zombie's clients replay after
            # the heal -- is rejected REJECT_FENCED, counted, and the
            # client self-heals onto the minted epoch
            lo, hi = sg.shard_ranges(self.D, 3)[1]
            stale = ps_dcn.PSClient("127.0.0.1", group.port_of(1),
                                    epoch=1)
            acc, done = stale.push(0, 0, np.zeros(hi - lo, np.float32))
            assert (acc, done) == (False, False)
            assert stale.fenced_replies >= 1
            assert stale.epoch == epoch1
            hdr = sg._oneshot("127.0.0.1", group.port_of(1),
                              {"op": "SHARDMAP"}, timeout_s=2.0)
            assert int(hdr.get("fenced_rejects", 0)) >= 1
            # the run COMPLETES through the partition: full coverage,
            # decreasing assembled loss trajectory
            result0 = group.result_of(0, timeout_s=90.0)
            assert result0 is not None, "primary never finished"
            assert result0["done"] is True
            assert result0["accepted"] == self.ITERS
            assert set(map(int, result0["accepted_by_wid"])) == set(
                range(self.NW))
            traj = result0.get("trajectory")
            assert traj, "no trajectory (eval plane died?)"
            assert traj[-1][1] < traj[0][1] * 0.2, traj
            group.finish()
            # observability: the controller counted the fence + restart
            totals = sg.shard_totals()
            assert totals.get("shard_deaths", 0) >= 1
            assert totals.get("shards_restarted", 0) >= 1
            assert totals.get("fence_epoch_bumps", 0) >= 1
            for w in workers:
                rc = w.wait(timeout=60.0)
                assert rc == 0, f"worker exited rc={rc}"
            out = [json.loads(w.stdout.read().splitlines()[-1])
                   for w in workers]
            assert sum(o["gradients"] for o in out) >= self.ITERS
        finally:
            faults.clear()
            for w in workers:
                if w.poll() is None:
                    w.kill()
            group.stop()
