"""async-lint suite (ISSUE 10): every rule fires on a minimal bad
example, stays silent on the clean tree, and the whole repo self-lints
clean -- plus the acceptance mutations (deleting a dedup gate, an ``ep``
stamp, or a conf declaration makes the lint fail) and the dynamic
lock-order race detector.

Fixture trees are built under tmp_path with the repo's directory shape;
``LintContext`` takes an explicit path list, so fixtures never touch the
real tree.  The protocol-rule acceptance tests lint a MUTATED COPY of
the real ``ps_dcn.py`` (never the live file), so they also prove the
rule still understands the real dispatch code's shape.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from asyncframework_tpu.analysis import core as lint_core
from asyncframework_tpu.analysis import (
    rules_conf,
    rules_locks,
    rules_metrics,
    rules_protocol,
    rules_threads,
)
from asyncframework_tpu.analysis.core import Allow, LintContext, run_lint
from asyncframework_tpu.net import lockwatch, protocol

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_tree(tmp_path, files):
    """Write {relpath: source} under tmp_path; returns (root, paths)."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return str(tmp_path), list(files)


def ctx_of(tmp_path, files):
    root, paths = make_tree(tmp_path, files)
    return LintContext(root, paths=paths)


def rule_tokens(findings, rule):
    return sorted(f.token for f in findings if f.rule == rule)


# --------------------------------------------------------------- conf rule
CONF_FIXTURE = '''
class ConfigEntry:
    def __init__(self, *a, **k):
        pass

LIVE = ConfigEntry("async.live.knob", 1, int, "read elsewhere")
DEAD = ConfigEntry("async.dead.knob", 2, int, "read nowhere")
'''


class TestConfRule:
    def test_undeclared_read_fires(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/conf.py": CONF_FIXTURE,
            "asyncframework_tpu/user.py":
                'x = conf.get("async.live.knob")\n'
                'y = conf.get("async.bogus.knob")\n',
        })
        f = rules_conf.check(ctx)
        assert rule_tokens(f, "conf-undeclared-read") == ["async.bogus.knob"]

    def test_dead_knob_fires_and_reference_silences(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/conf.py": CONF_FIXTURE,
            "asyncframework_tpu/user.py":
                'x = conf.get("async.live.knob")\n',
        })
        f = rules_conf.check(ctx)
        assert rule_tokens(f, "conf-dead-knob") == ["async.dead.knob"]
        # referencing the entry CONSTANT (not the literal) also counts
        ctx2 = ctx_of(tmp_path / "b", {
            "asyncframework_tpu/conf.py": CONF_FIXTURE,
            "asyncframework_tpu/user.py":
                'from asyncframework_tpu.conf import DEAD, LIVE\n'
                'a = conf.get(DEAD)\nb = conf.get(LIVE)\n',
        })
        assert rule_tokens(rules_conf.check(ctx2), "conf-dead-knob") == []

    def test_env_alias_mismatch_fires(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/conf.py": CONF_FIXTURE,
            "asyncframework_tpu/user.py":
                'import os\n'
                'ok = os.environ.get("ASYNCTPU_ASYNC_LIVE_KNOB")\n'
                'bad = os.environ.get("ASYNCTPU_ASYNC_TYPO_KNOB")\n',
        })
        f = rules_conf.check(ctx)
        assert rule_tokens(f, "conf-env-alias") == [
            "ASYNCTPU_ASYNC_TYPO_KNOB"]

    def test_conf_to_field_checks(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/conf.py": CONF_FIXTURE,
            "asyncframework_tpu/cli.py":
                'CONF_TO_FIELD = {"async.live.knob": "nope",\n'
                '                 "async.unknown.knob": "taw"}\n',
            "asyncframework_tpu/solvers/base.py":
                'class SolverConfig:\n    taw: int = 1\n',
        })
        f = rules_conf.check(ctx)
        toks = rule_tokens(f, "conf-field-map")
        assert "async.unknown.knob" in toks      # unregistered key
        assert "async.live.knob" in toks         # missing field

    def test_conf_to_field_parses_annotated_assignment(self, tmp_path):
        """The real cli.py declares `CONF_TO_FIELD: Dict[str, str] =
        {...}` (ast.AnnAssign) -- the rule must parse that shape, or it
        is vacuous on the actual tree (caught in review by mapping a
        key to a nonexistent field with zero findings)."""
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/conf.py": CONF_FIXTURE,
            "asyncframework_tpu/cli.py":
                'from typing import Dict\n'
                'CONF_TO_FIELD: Dict[str, str] = {\n'
                '    "async.live.knob": "no_such_field_xyz"}\n',
            "asyncframework_tpu/solvers/base.py":
                'class SolverConfig:\n    taw: int = 1\n',
        })
        toks = rule_tokens(rules_conf.check(ctx), "conf-field-map")
        assert toks == ["async.live.knob"]

    def test_underscore_key_declaration_violates_grammar(self, tmp_path):
        """Underscore-bearing key segments make the ASYNCTPU_ env-alias
        reverse mapping ambiguous, so declaring one is itself a finding
        -- and its mechanically-correct env literal is NOT flagged as a
        bad alias (the declaration is the bug, not the literal)."""
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/conf.py": CONF_FIXTURE.replace(
                '"async.dead.knob"', '"async.win_max.knob"'),
            "asyncframework_tpu/user.py":
                'x = conf.get("async.live.knob")\n',
        })
        f = rules_conf.check(ctx)
        assert rule_tokens(f, "conf-key-grammar") == ["async.win_max.knob"]

    # -------------------------------------- tunable discipline (ISSUE 15)
    TUNABLE_CONF = '''
class ConfigEntry:
    def __init__(self, *a, **k):
        pass

STEP = ConfigEntry("async.step.size", 0.1, float, "gamma",
                   tunable=True, floor=0.05, ceiling=1.0)
OTHER = ConfigEntry("async.other.knob", 1, int, "not tunable")
'''

    def test_tunable_without_bounds_fires(self, tmp_path):
        """Un-declaring a bound (or the whole marker, below) is the
        mutation the rule exists for: a tunable the controller cannot be
        clamped against must fail the lint."""
        mutated = self.TUNABLE_CONF.replace(", floor=0.05", "")
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/conf.py": mutated,
            "asyncframework_tpu/user.py":
                'x = conf.get("async.step.size")\n'
                'y = conf.get("async.other.knob")\n',
        })
        f = rules_conf.check(ctx)
        assert rule_tokens(f, "conf-tunable") == ["async.step.size"]

    def test_actuating_undeclared_tunable_fires(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/conf.py": self.TUNABLE_CONF,
            "asyncframework_tpu/parallel/controller.py":
                'CONTROLLER_TUNABLES = {"async.step.size": "damp"}\n'
                'class C:\n'
                '    def go(self, knob, now):\n'
                '        self._actuate("async.step.size", knob, 1.0,\n'
                '                      now, "ok", 0.05, 1.0)\n'
                '        self._actuate("async.other.knob", knob, 2.0,\n'
                '                      now, "bad", 1.0, 8.0)\n',
        })
        f = rules_conf.check(ctx)
        assert rule_tokens(f, "conf-tunable") == ["async.other.knob"]

    def test_undeclaring_a_tunable_fails_lint(self, tmp_path):
        """The other mutation direction: the controller's declared
        surface (CONTROLLER_TUNABLES) names a key whose ConfigEntry
        lost its tunable=True marker."""
        mutated = self.TUNABLE_CONF.replace("tunable=True, ", "")
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/conf.py": mutated,
            "asyncframework_tpu/parallel/controller.py":
                'CONTROLLER_TUNABLES = {"async.step.size": "damp"}\n',
        })
        f = rules_conf.check(ctx)
        assert rule_tokens(f, "conf-tunable") == ["async.step.size"]

    def test_real_controller_surface_is_declared(self):
        """Every tunable the REAL controller actuates is a registered
        tunable ConfigEntry with bounds (the runtime twin lives in
        AsyncController.__init__)."""
        from asyncframework_tpu.analysis.rules_conf import (
            _actuated_keys,
            declared_tunables,
        )
        from asyncframework_tpu.parallel.controller import (
            CONTROLLER_TUNABLES,
        )

        ctx = LintContext(REPO)
        tunables = declared_tunables(ctx)
        actuated = {k for k, _line in _actuated_keys(ctx)}
        assert actuated, "controller actuation surface not parsed"
        assert set(CONTROLLER_TUNABLES) <= actuated
        for key in actuated:
            assert key in tunables, key
            has_floor, has_ceiling, _line = tunables[key]
            assert has_floor and has_ceiling, key

    def test_clean_tree_is_silent_for_conf(self):
        result = run_lint(REPO, rules=["conf"])
        assert result.findings == [], [f.format() for f in result.findings]


# ----------------------------------------------------------- protocol rule
PS_DCN_REAL = os.path.join(REPO, "asyncframework_tpu/parallel/ps_dcn.py")


def real_ps_src():
    with open(PS_DCN_REAL) as f:
        return f.read()


def protocol_findings_for(tmp_path, ps_src):
    """Protocol-rule findings over a tree whose ps_dcn.py is ``ps_src``
    (every other protocol module absent -- the rule skips missing
    files)."""
    ctx = ctx_of(tmp_path, {
        "asyncframework_tpu/parallel/ps_dcn.py": ps_src,
    })
    return rules_protocol.check(ctx)


class TestProtocolRule:
    def test_table_is_sane(self):
        tbl = protocol.table()
        # the planes' load-bearing verbs are declared with the
        # obligations the engine's correctness story rests on
        assert tbl["PUSH"].dedup_gated and tbl["PUSH"].fence_stamped
        assert tbl["APPEND"].dedup_gated
        assert tbl["SUBMIT_APP"].dedup_gated
        assert tbl["SUBSCRIBE"].fence_stamped
        assert not tbl["MODEL"].mutating
        assert protocol.dedup_gated_ops(protocol.TOPIC) == {
            "APPEND", "COMMIT"}
        assert protocol.dedup_gated_ops(protocol.MASTER) == {
            "SUBMIT_APP", "KILL_APP"}

    def test_dedup_gated_implies_mutating_enforced(self):
        with pytest.raises(ValueError):
            protocol.WireOp("X", protocol.PS, dedup_gated=True)

    def test_undeclared_op_fires(self, tmp_path):
        f = protocol_findings_for(
            tmp_path,
            'def serve(conn, header):\n'
            '    op = header["op"]\n'
            '    if op == "FROBNICATE":\n'
            '        send(conn, {"op": "ACK"})\n')
        assert "FROBNICATE" in rule_tokens(f, "proto-undeclared-op")

    def test_unhandled_op_fires_on_stub_server(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/serving/frontend.py":
                'def handle_op(conn, op, header, payload):\n'
                '    if op == "HELLO":\n'
                '        return True\n'
                '    return False\n',
        })
        toks = rule_tokens(rules_protocol.check(ctx), "proto-unhandled-op")
        assert "PREDICT" in toks and "STATUS" in toks

    def test_deleting_push_dedup_gate_fails_lint(self, tmp_path):
        src = real_ps_src()
        mutated = src.replace("cached = self._dedup.check(header)",
                              "cached = None", 1)
        assert mutated != src
        f = protocol_findings_for(tmp_path, mutated)
        assert set(rule_tokens(f, "proto-dedup-gate")) >= {
            "PUSH", "PUSH_SAGA"}
        # the unmutated real file is clean
        assert rule_tokens(
            protocol_findings_for(tmp_path / "clean", src),
            "proto-dedup-gate") == []

    def test_deleting_fence_admission_fails_lint(self, tmp_path):
        src = real_ps_src()
        # remove the PULL branch's fencing admission call (it follows
        # the standby guard inside the same branch)
        mutated = src.replace(
            "                    if self._fence_reject(conn, header):\n"
            "                        continue\n"
            "                    self._handle_pull(conn, header)\n",
            "                    self._handle_pull(conn, header)\n", 1)
        assert mutated != src
        f = protocol_findings_for(tmp_path, mutated)
        assert set(rule_tokens(f, "proto-fence-gate")) >= {
            "PULL", "PULL_SAGA"}

    def test_deleting_client_ep_stamp_fails_lint(self, tmp_path):
        src = real_ps_src()
        i = src.index("def _proc_hdr")
        j = src.index('hdr["ep"] = self.epoch', i)
        mutated = (src[:j] + "pass"
                   + src[j + len('hdr["ep"] = self.epoch'):])
        f = protocol_findings_for(tmp_path, mutated)
        assert rule_tokens(f, "proto-fence-gate") == ["ep-stamp"]

    def test_deleting_relay_fence_admission_fails_lint(self, tmp_path):
        """ISSUE 12 acceptance mutation: the relaycast node's dispatch
        must run fencing admission on every fence-stamped relay verb --
        deleting the admission call is a lint failure, not a chaos
        lottery."""
        with open(os.path.join(
                REPO, "asyncframework_tpu/relaycast/node.py")) as f:
            src = f.read()
        mutated = src.replace(
            'if op == "RELAY_FETCH":\n'
            '            if not self._fence_reject(conn, header):\n'
            '                self._handle_fetch(conn, header)\n',
            'if op == "RELAY_FETCH":\n'
            '            self._handle_fetch(conn, header)\n', 1)
        assert mutated != src
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/relaycast/node.py": mutated,
        })
        toks = rule_tokens(rules_protocol.check(ctx), "proto-fence-gate")
        assert "RELAY_FETCH" in toks
        # the unmutated real file is clean
        ctx = ctx_of(tmp_path / "clean", {
            "asyncframework_tpu/relaycast/node.py": src,
        })
        assert rule_tokens(rules_protocol.check(ctx),
                           "proto-fence-gate") == []

    def test_deleting_relay_client_ep_stamp_fails_lint(self, tmp_path):
        """And the client half: RelaySource._stamped is the relay
        plane's ep-stamp choke point, pinned like PSClient._proc_hdr."""
        with open(os.path.join(
                REPO, "asyncframework_tpu/relaycast/source.py")) as f:
            src = f.read()
        i = src.index("def _stamped")
        j = src.index('hdr["ep"] = self.node.epoch', i)
        mutated = (src[:j] + "pass"
                   + src[j + len('hdr["ep"] = self.node.epoch'):])
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/relaycast/source.py": mutated,
        })
        toks = rule_tokens(rules_protocol.check(ctx), "proto-fence-gate")
        assert toks == ["ep-stamp"]

    def test_deleting_standby_fence_admission_fails_lint(self, tmp_path):
        """ISSUE 13 acceptance mutation: the standby's REPL_APPEND/
        REPL_SYNC dispatch must run fencing admission -- it is THE
        promotion-safety gate (a deposed primary's post-promotion
        stream appends bounce REJECT_FENCED).  Deleting the admission
        call is a lint failure, not a chaos lottery."""
        src = real_ps_src()
        mutated = src.replace(
            "                    if self._fence_reject(conn, header):\n"
            "                        continue\n"
            "                    if not self._standby:\n",
            "                    if not self._standby:\n", 1)
        assert mutated != src
        f = protocol_findings_for(tmp_path, mutated)
        assert set(rule_tokens(f, "proto-fence-gate")) >= {
            "REPL_APPEND", "REPL_SYNC"}
        # the unmutated real file is clean
        assert rule_tokens(
            protocol_findings_for(tmp_path / "clean", src),
            "proto-fence-gate") == []

    def test_deleting_repl_stream_ep_stamp_fails_lint(self, tmp_path):
        """And the client half: ReplicationStream._stamped is the
        replication plane's ep-stamp choke point, pinned like
        PSClient._proc_hdr -- without it a deposed primary's appends
        would arrive unstamped and a standby could apply them."""
        with open(os.path.join(
                REPO, "asyncframework_tpu/parallel/replication.py")) as f:
            src = f.read()
        i = src.index("def _stamped")
        j = src.index('hdr["ep"] = self.ps.epoch', i)
        mutated = (src[:j] + "pass"
                   + src[j + len('hdr["ep"] = self.ps.epoch'):])
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/parallel/replication.py": mutated,
        })
        toks = rule_tokens(rules_protocol.check(ctx), "proto-fence-gate")
        assert toks == ["ep-stamp"]
        # the unmutated real file is clean
        ctx = ctx_of(tmp_path / "clean", {
            "asyncframework_tpu/parallel/replication.py": src,
        })
        assert rule_tokens(rules_protocol.check(ctx),
                           "proto-fence-gate") == []

    def test_clean_tree_is_silent_for_protocol(self):
        result = run_lint(REPO, rules=["protocol"])
        assert result.findings == [], [f.format() for f in result.findings]


# --------------------------------------------------------------- lock rule
class TestLockRule:
    def test_sleep_under_lock_fires(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/x.py":
                'import time\n'
                'def f(self):\n'
                '    with self._lock:\n'
                '        time.sleep(1.0)\n',
        })
        assert rule_tokens(rules_locks.check(ctx),
                           "lock-blocking-call") == ["_lock:sleep"]

    def test_socket_and_frame_io_under_lock_fire(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/x.py":
                'def f(self, conn, hdr):\n'
                '    with self._model_lock:\n'
                '        conn.sendall(b"x")\n'
                '        _send_msg(conn, hdr)\n',
        })
        toks = rule_tokens(rules_locks.check(ctx), "lock-blocking-call")
        assert toks == ["_model_lock:_send_msg", "_model_lock:sendall"]

    def test_nested_def_is_excluded_and_cv_wait_allowed(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/x.py":
                'import time\n'
                'def f(self):\n'
                '    with self._lock:\n'
                '        def later():\n'
                '            time.sleep(1.0)\n'   # runs outside the hold
                '        return later\n'
                'def g(self):\n'
                '    with self._wave_cv:\n'
                '        self._wave_cv.wait(0.1)\n',  # releases the lock
        })
        assert rules_locks.check(ctx) == []

    def test_str_join_not_flagged_thread_join_flagged(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/x.py":
                'def f(self, parts):\n'
                '    with self._lock:\n'
                '        s = ",".join(parts)\n'     # 1 positional: str.join
                '        self._ckpt_thread.join()\n',
        })
        assert rule_tokens(rules_locks.check(ctx),
                           "lock-blocking-call") == ["_lock:join"]

    def test_clean_tree_lock_findings_all_suppressed(self):
        result = run_lint(REPO, rules=["locks"])
        assert result.findings == [], [f.format() for f in result.findings]
        # the known client-channel locks ride the allowlist, with reasons
        assert all(a.reason.strip() for _f, a in result.suppressed)

    def test_allowlist_tokens_are_lock_scoped(self):
        """An entry written for one lock's documented contract must not
        suppress the same callee under a DIFFERENT lock in the same
        file: tokens carry the lock name, so a hypothetical model-lock
        connect in ps_dcn.py escapes the _win_lock:connect entry."""
        from asyncframework_tpu.analysis.allowlist import ALLOWLIST

        hot = lint_core.Finding(
            "lock-blocking-call",
            "asyncframework_tpu/parallel/ps_dcn.py", 1,
            "_lock:connect", "socket .connect() under the model lock")
        assert not any(a.matches(hot) for a in ALLOWLIST)
        win = lint_core.Finding(
            "lock-blocking-call",
            "asyncframework_tpu/parallel/ps_dcn.py", 1,
            "_win_lock:connect", "push-window reconnect")
        assert any(a.matches(win) for a in ALLOWLIST)


# ------------------------------------------------------------- thread rule
class TestThreadRule:
    def test_bad_site_fires_all_three(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/x.py":
                'import threading\n'
                'def f(target):\n'
                '    threading.Thread(target=target).start()\n',
        })
        rules = sorted(f.rule for f in rules_threads.check(ctx))
        assert rules == ["thread-implicit-daemon", "thread-unguarded",
                         "thread-unnamed"]

    def test_assigning_start_result_is_not_retained(self, tmp_path):
        """`t = threading.Thread(...).start()` binds None, not the
        thread -- the object is lost and unguarded, so the rule must
        fire (review repro: this passed as 'retained' before)."""
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/x.py":
                'import threading\n'
                'def f(target):\n'
                '    t = threading.Thread(target=target, name="x",\n'
                '                         daemon=True).start()\n',
        })
        rules = sorted(f.rule for f in rules_threads.check(ctx))
        assert rules == ["thread-unguarded"]

    def test_named_daemon_retained_is_clean(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/x.py":
                'import threading\n'
                'def f(self, target):\n'
                '    self._t = threading.Thread(target=target,\n'
                '                               name="x", daemon=True)\n'
                '    self._t.start()\n',
        })
        assert rules_threads.check(ctx) == []

    def test_guarded_fire_and_forget_is_clean(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/x.py":
                'import threading\n'
                'from asyncframework_tpu.utils.threads import guarded\n'
                'def f(target):\n'
                '    threading.Thread(target=guarded(target, "w"),\n'
                '                     name="x", daemon=True).start()\n',
        })
        assert rules_threads.check(ctx) == []

    def test_clean_tree_is_silent_for_threads(self):
        result = run_lint(REPO, rules=["threads"])
        assert result.findings == [], [f.format() for f in result.findings]

    def test_guarded_reports_and_swallows(self, capsys):
        from asyncframework_tpu.utils.threads import guarded

        hits = []

        def boom():
            hits.append(1)
            raise RuntimeError("kaboom")

        t = threading.Thread(target=guarded(boom, "boom-test"),
                             name="boom-test", daemon=True)
        t.start()
        t.join(timeout=10.0)
        assert hits == [1] and not t.is_alive()
        err = capsys.readouterr().err
        assert "boom-test" in err and "kaboom" in err


# ------------------------------------------------------------ metrics rule
class TestMetricsRule:
    def test_unregistered_totals_fires(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/rogue.py":
                'def rogue_totals():\n    return {"n": 1}\n'
                'def reset_rogue_totals():\n    pass\n'
                'def _private_totals():\n    return {}\n',
        })
        toks = rule_tokens(rules_metrics.check(ctx),
                           "metrics-unregistered-totals")
        assert toks == ["rogue_totals"]  # reset_* and _private excluded

    def test_clean_tree_metrics_findings_all_suppressed(self):
        result = run_lint(REPO, rules=["metrics"])
        assert result.findings == [], [f.format() for f in result.findings]

    def test_series_family_undeclared_fires(self, tmp_path):
        """metrics-series-family (ISSUE 14): every literal series key --
        a register_source family, a record_flat prefix, a dotted record
        key -- must carry a family declared in metrics/registry.py."""
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/rogue_series.py":
                'from asyncframework_tpu.metrics import timeseries\n'
                'timeseries.register_source("roguefam", lambda: {})\n'
                'def f(st):\n'
                '    st.record_flat("rogueflat", {"a": 1})\n'
                '    st.record("roguekey.metric", 1.0)\n'
                '    st.record("ps.accepted", 1.0)\n'       # declared
                '    dedup.record(header, reply)\n'          # not a key
                '    cal.record(5, 1.0)\n',                  # not a str
        })
        toks = rule_tokens(rules_metrics.check(ctx),
                           "metrics-series-family")
        assert toks == ["roguefam", "rogueflat", "roguekey"]

    def test_series_family_mutation_deleting_declaration_fails(
            self, monkeypatch):
        """Acceptance mutation: un-declare the ``ps`` dynamic family ->
        the REAL tree's PS register_source site becomes a finding."""
        from asyncframework_tpu.metrics import registry

        full = registry.series_families()
        mutated = tuple(f for f in full if f != "ps")
        monkeypatch.setattr(registry, "series_families", lambda: mutated)
        result = run_lint(REPO, rules=["metrics"])
        toks = rule_tokens(result.findings, "metrics-series-family")
        assert "ps" in toks, [f.format() for f in result.findings]
        assert any("ps_dcn" in f.path for f in result.findings
                   if f.rule == "metrics-series-family")


# ------------------------------------------------------------ prof-zone rule
#: mini zone table: the rule reads ZONES from metrics/profiler.py's AST
PROF_FIXTURE = '''
ZONES = (
    "wire.live",
    "wire.dead",
)
'''


class TestProfZoneRule:
    def test_undeclared_and_unattributed_both_fire(self, tmp_path):
        """prof-zone, both directions on one fixture tree: an undeclared
        literal at an attribution site (zone() and the wrap_dispatch
        zone arg) fires, and a declared zone with no attribution site
        anywhere fires at the ZONES table."""
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/metrics/profiler.py": PROF_FIXTURE,
            "asyncframework_tpu/rogue_prof.py":
                'from asyncframework_tpu.metrics import profiler as _prof\n'
                'def f(fn):\n'
                '    with _prof.zone("wire.live"):\n'
                '        pass\n'
                '    with _prof.zone("wire.bogus"):\n'
                '        pass\n'
                '    return _prof.wrap_dispatch(fn, "bad.zone", "lbl")\n',
        })
        findings = [f for f in rules_metrics.check(ctx)
                    if f.rule == "prof-zone"]
        assert rule_tokens(findings, "prof-zone") == \
            ["bad.zone", "wire.bogus", "wire.dead"]
        dead = next(f for f in findings if f.token == "wire.dead")
        assert dead.path.endswith("metrics/profiler.py")

    def test_dotless_literal_on_generic_zone_callee_is_skipped(
            self, tmp_path):
        """``zone`` is a common method name: a dotless literal that is
        not a declared zone (a k8s zone selector, say) must not fire."""
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/metrics/profiler.py": PROF_FIXTURE,
            "asyncframework_tpu/uses.py":
                'from asyncframework_tpu.metrics import profiler as _prof\n'
                'def f(client):\n'
                '    client.zone("us-east1")\n'
                '    _prof.zone_ns("zone9", 5)\n'
                '    with _prof.zone("wire.live"):\n'
                '        pass\n'
                '    _prof.zone_ns("wire.dead", 1)\n',
        })
        assert rule_tokens(rules_metrics.check(ctx), "prof-zone") == []

    def test_tree_without_zone_table_skips_the_rule(self, tmp_path):
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/uses.py":
                'from asyncframework_tpu.metrics import profiler as _prof\n'
                'def f():\n'
                '    with _prof.zone("wire.bogus"):\n'
                '        pass\n',
        })
        assert rule_tokens(rules_metrics.check(ctx), "prof-zone") == []

    def test_mutation_both_directions_on_the_real_tree(self, monkeypatch):
        """Acceptance mutations against the REAL repo: un-declare a zone
        the tree attributes -> its wirecodec sites become findings;
        declare a zone nothing attributes -> a finding at the table."""
        orig = rules_metrics._declared_zones

        def without_quantize(ctx):
            zones, line = orig(ctx)
            return zones - {"wire.quantize"}, line

        monkeypatch.setattr(rules_metrics, "_declared_zones",
                            without_quantize)
        result = run_lint(REPO, rules=["metrics"])
        toks = rule_tokens(result.findings, "prof-zone")
        assert "wire.quantize" in toks, toks
        assert any("wirecodec" in f.path for f in result.findings
                   if f.rule == "prof-zone")

        def with_phantom(ctx):
            zones, line = orig(ctx)
            return zones | {"wire.phantom"}, line

        monkeypatch.setattr(rules_metrics, "_declared_zones", with_phantom)
        result = run_lint(REPO, rules=["metrics"])
        toks = rule_tokens(result.findings, "prof-zone")
        assert toks == ["wire.phantom"], toks


# ------------------------------------------------- allowlist + whole tree
class TestAllowlistPolicy:
    def test_empty_reason_is_refused(self):
        with pytest.raises(ValueError, match="reason"):
            run_lint(REPO, rules=["conf"],
                     allowlist=[Allow("conf-dead-knob", "*", "*", "  ")])

    def test_repo_allowlist_entries_all_carry_reasons(self):
        from asyncframework_tpu.analysis.allowlist import ALLOWLIST

        assert all(a.reason.strip() for a in ALLOWLIST)

    def test_allow_matching_is_exact_on_rule_and_token(self):
        f = lint_core.Finding("conf-dead-knob",
                              "asyncframework_tpu/conf.py", 1,
                              "async.x", "m")
        assert Allow("conf-dead-knob", "asyncframework_tpu/*",
                     "async.x", "r").matches(f)
        assert not Allow("conf-dead-knob", "asyncframework_tpu/*",
                         "async.y", "r").matches(f)
        assert not Allow("lock-blocking-call", "asyncframework_tpu/*",
                         "async.x", "r").matches(f)


class TestWholeTreeSelfLint:
    def test_whole_tree_self_lints_clean(self):
        """THE acceptance test: every rule over the whole repo, zero
        findings beyond the reason-carrying allowlist."""
        result = run_lint(REPO)
        assert result.ok, "\n".join(f.format() for f in result.findings)
        assert result.files_scanned > 150

    def test_cli_json_clean_and_machine_readable(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "async-lint"),
             "--json"],
            capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        assert payload["ok"] is True
        assert payload["findings"] == []
        for s in payload["suppressed"]:
            assert s["reason"].strip()

    def test_deleting_a_conf_declaration_fails_lint(self, tmp_path):
        """Acceptance mutation: drop ONE ConfigEntry declaration from a
        copy of conf.py next to the real cli.py -> the CONF_TO_FIELD
        read of that key becomes an undeclared read."""
        with open(os.path.join(REPO, "asyncframework_tpu/conf.py")) as f:
            conf_src = f.read()
        with open(os.path.join(REPO, "asyncframework_tpu/cli.py")) as f:
            cli_src = f.read()
        target = ('TAW = ConfigEntry("async.taw", 2**31 - 1, int, '
                  '"Staleness bound tau.")')
        assert target in conf_src
        ctx = ctx_of(tmp_path, {
            "asyncframework_tpu/conf.py": conf_src.replace(target, ""),
            "asyncframework_tpu/cli.py": cli_src,
        })
        toks = rule_tokens(rules_conf.check(ctx), "conf-undeclared-read")
        assert "async.taw" in toks


# ------------------------------------------------- lock-order race detector
class TestLockOrderDetector:
    def setup_method(self):
        lockwatch.reset_totals()
        # snapshot AFTER the fold above: if an earlier armed suite left
        # a real cycle (live or already-folded), it is in this snapshot
        # and teardown's restore preserves it for the session-wide gate
        self._prior_history = lockwatch.cycle_history()
        lockwatch.enable(True)

    def teardown_method(self):
        lockwatch.enable(False)
        lockwatch.reset_totals()
        # this class drives cycles DELIBERATELY: restore the pre-test
        # history (dropping only OUR cycles) instead of wholesale
        # clearing, which would also hide an earlier suite's real
        # potential deadlock from the session-wide conftest gate
        lockwatch.set_cycle_history(self._prior_history)

    def test_reversed_acquisition_two_threads_reports_cycle(self):
        """The satellite's required unit: two threads, two locks,
        reversed acquisition order -> exactly one potential-deadlock
        cycle, surfaced in totals() and fatal via assert_no_cycles."""
        a = lockwatch.WatchedLock("t.alpha")
        b = lockwatch.WatchedLock("t.beta")

        def fwd():
            with a:
                with b:
                    pass

        def rev():
            with b:
                with a:
                    pass

        for fn, name in ((fwd, "lo-fwd"), (rev, "lo-rev")):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            t.join(timeout=10.0)
        cycles = lockwatch.lock_order_cycles()
        assert len(cycles) == 1
        assert "t.alpha" in cycles[0] and "t.beta" in cycles[0]
        tot = lockwatch.totals()
        assert tot["order_cycles"] == 1 and tot["order_edges"] == 2
        assert tot["cycles"] == cycles
        with pytest.raises(AssertionError, match="t.alpha"):
            lockwatch.assert_no_cycles()

    def test_consistent_order_reports_no_cycle(self):
        a = lockwatch.WatchedLock("c.alpha")
        b = lockwatch.WatchedLock("c.beta")
        c = lockwatch.WatchedLock("c.gamma")
        for first, second in ((a, b), (a, c), (b, c)):
            def fn(x=first, y=second):
                with x:
                    with y:
                        pass
            t = threading.Thread(target=fn, name="lo-ok", daemon=True)
            t.start()
            t.join(timeout=10.0)
        assert lockwatch.lock_order_cycles() == []
        lockwatch.assert_no_cycles()
        assert lockwatch.totals()["order_edges"] == 3

    def test_three_lock_transitive_cycle_detected(self):
        a = lockwatch.WatchedLock("tr.a")
        b = lockwatch.WatchedLock("tr.b")
        c = lockwatch.WatchedLock("tr.c")
        for first, second in ((a, b), (b, c), (c, a)):
            def fn(x=first, y=second):
                with x:
                    with y:
                        pass
            t = threading.Thread(target=fn, name="lo-tri", daemon=True)
            t.start()
            t.join(timeout=10.0)
        cycles = lockwatch.lock_order_cycles()
        assert len(cycles) == 1
        for name in ("tr.a", "tr.b", "tr.c"):
            assert name in cycles[0]

    def test_reset_clears_graph(self):
        a = lockwatch.WatchedLock("r.a")
        b = lockwatch.WatchedLock("r.b")
        with a:
            with b:
                pass
        assert lockwatch.totals()["order_edges"] == 1
        lockwatch.reset_totals()
        t = lockwatch.totals()
        assert t["order_edges"] == 0 and t["order_cycles"] == 0

    def test_reset_folds_cycles_into_sticky_history(self):
        """A suite that reset_totals() for isolation must not erase
        another suite's recorded cycle before the session-wide conftest
        gate sees it: reset folds cycles into cycle_history(), which
        only clear_cycle_history() drops."""
        a = lockwatch.WatchedLock("h.a")
        b = lockwatch.WatchedLock("h.b")

        def fwd():
            with a:
                with b:
                    pass

        def rev():
            with b:
                with a:
                    pass

        for fn in (fwd, rev):
            t = threading.Thread(target=fn, name="lo-hist", daemon=True)
            t.start()
            t.join(timeout=10.0)
        assert len(lockwatch.lock_order_cycles()) == 1
        lockwatch.reset_totals()  # the bystander reset
        assert lockwatch.lock_order_cycles() == []      # live graph gone
        assert len(lockwatch.cycle_history()) == 1      # verdict survives
        lockwatch.assert_no_cycles()                    # current-only: ok
        with pytest.raises(AssertionError, match="h.a"):
            lockwatch.assert_no_cycles(include_history=True)
        lockwatch.clear_cycle_history()
        lockwatch.assert_no_cycles(include_history=True)

    def test_named_lock_resolution(self):
        assert isinstance(lockwatch.named_lock("x"),
                          lockwatch.WatchedLock)
        lockwatch.enable(False)
        assert not isinstance(lockwatch.named_lock("x"),
                              lockwatch.WatchedLock)
