"""Distributed tracing for the async update loop (metrics/trace.py).

Acceptance (ISSUE 3): a two-process DCN ASGD run over real sockets
produces >= 1 complete cross-process trace -- pull.rtt / compute /
push.rtt spans sharing one trace_id -- with staleness reported in both
versions and milliseconds, visible in the live UI's /api/status,
reconstructed by bin/async-trace from the event log, and exported as
valid Chrome tracing JSON.  Sampling off => zero wire header and
byte-identical frames.

Satellites covered here: process-global counter reset / per-run delta
capture, truncated-event-log tolerance (kill -9 mid-write), live UI under
chaos (faults + SIGKILL, no 500s, monotonic sections), and the
Histogram nearest-rank percentile fix.
"""

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.metrics import reset_totals
from asyncframework_tpu.metrics import trace
from asyncframework_tpu.metrics.bus import GradientMerged, ListenerBus, TraceSpan
from asyncframework_tpu.metrics.eventlog import EventLogReader, EventLogWriter
from asyncframework_tpu.metrics.live import LiveStateListener, LiveUIServer
from asyncframework_tpu.metrics.system import Histogram
from asyncframework_tpu.net import frame, net_totals
from asyncframework_tpu.net.faults import (
    CONNECT_OP,
    CONNECT_REFUSED,
    CUT_MID_FRAME,
    DROP_REPLY,
    STALL_READ,
    FaultSchedule,
)
from asyncframework_tpu.net import faults, retry
from asyncframework_tpu.net.session import DedupWindow
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.parallel import supervisor as supervisor_mod
from asyncframework_tpu.parallel.supervisor import (
    ElasticSupervisor,
    recovery_totals,
)
from asyncframework_tpu.solvers import SolverConfig

CHILD = Path(__file__).parent / "ps_dcn_child.py"


def make_cfg(**kw):
    defaults = dict(
        num_workers=8, num_iterations=300, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.5, printer_freq=50, seed=42,
        calibration_iters=20, run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture(autouse=True)
def _clean_trace_state():
    """Tracing state is ambient (TLS context, process-global aggregator)
    and breakers/schedules are process-global -- no test may inherit or
    leak any of it."""
    trace.set_current(None)
    retry.reset_breakers()
    faults.clear()
    yield
    trace.set_current(None)
    retry.reset_breakers()
    faults.clear()


def _get_json(url: str, timeout: float = 2.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, json.loads(r.read().decode())


# --------------------------------------------------------------- wire format
class TestWireFormat:
    def test_frames_byte_identical_when_tracing_off(self):
        """Sampling off => no ambient context => the frame bytes are
        EXACTLY the pre-trace encoding (zero wire-header overhead)."""
        assert trace.wire_header() is None
        a, b = socket.socketpair()
        try:
            header = {"op": "PULL", "wid": 3}
            frame.send_msg(a, header)
            head = json.dumps(header).encode()
            expected = (struct.pack("!I", len(head)) + head
                        + struct.pack("!I", 0))
            got = b.recv(65536)
            assert got == expected
            assert b"tc" not in got
        finally:
            a.close()
            b.close()

    def test_tc_header_stamped_from_ambient_context(self):
        ctx = trace.TraceContext("deadbeefdeadbeef", 5, 17)
        trace.set_current(ctx)
        try:
            a, b = socket.socketpair()
            try:
                frame.send_msg(a, {"op": "PULL", "wid": 5})
                hdr, _ = frame.recv_msg(b)
            finally:
                a.close()
                b.close()
        finally:
            trace.set_current(None)
        assert hdr["tc"] == ["deadbeefdeadbeef", ctx.span_id, 5, 17]
        rt = trace.TraceContext.from_wire(hdr["tc"])
        assert (rt.trace_id, rt.worker_id, rt.model_version) == (
            "deadbeefdeadbeef", 5, 17)

    def test_caller_header_never_mutated(self):
        """Stamping copies: retries re-send the caller's header verbatim
        (the dedup (sid, seq) contract must survive tracing)."""
        ctx = trace.TraceContext("t" * 16, 0, 0)
        trace.set_current(ctx)
        try:
            a, b = socket.socketpair()
            try:
                header = {"op": "PUSH", "wid": 0, "sid": "s", "seq": 9}
                frame.send_msg(a, header)
                assert "tc" not in header
            finally:
                a.close()
                b.close()
        finally:
            trace.set_current(None)

    def test_span_wire_round_trip(self):
        sp = trace.Span(
            stage=trace.PUSH_RTT, trace_id="t" * 16, span_id="abcd1234",
            parent_id=None, worker_id=2, model_version=40,
            start_ms=123.5, dur_ms=4.25, staleness=3, staleness_ms=9.5,
            accepted=True,
        )
        rt = trace.Span.from_wire(sp.to_wire())
        assert rt == sp

    def test_span_wire_round_trip_preserves_zeros(self):
        """model_version 0 is the PS's FIRST served clock -- exactly the
        update counter-based sampling always traces -- and worker 0 /
        start 0.0 are equally legitimate; none may collapse to sentinels."""
        sp = trace.Span(
            stage=trace.PULL_RTT, trace_id="t" * 16, span_id="00000001",
            parent_id=None, worker_id=0, model_version=0,
            start_ms=0.0, dur_ms=1.0,
        )
        rt = trace.Span.from_wire(sp.to_wire())
        assert rt.model_version == 0
        assert rt.worker_id == 0
        assert rt.start_ms == 0.0

    def test_junk_tc_header_yields_none_not_crash(self):
        """Wire junk (a dict, a short list, None) must never escape
        from_wire -- a KeyError would kill the PS connection handler."""
        for junk in ({}, {"a": 1}, [], ["only-one"], None, 7):
            assert trace.TraceContext.from_wire(junk) is None


# ----------------------------------------------------------------- sampling
class TestSampling:
    def test_rate_zero_is_fully_off(self):
        rec = trace.TraceRecorder(sample_rate=0.0, capacity=16)
        assert not rec.enabled
        assert rec.start_update(0) is None
        assert rec.drain_wire() == []

    def test_counter_sampling_first_update_always_traced(self):
        rec = trace.TraceRecorder(sample_rate=0.25, capacity=64)
        hits = [rec.start_update(0) is not None for _ in range(8)]
        assert hits == [True, False, False, False, True, False, False,
                        False]
        # independent counters per worker: a late-joining worker's first
        # update is still traced
        assert rec.start_update(7) is not None

    def test_ring_is_bounded_and_counts_drops(self):
        rec = trace.TraceRecorder(sample_rate=1.0, capacity=4)
        for i in range(10):
            ut = rec.start_update(0)
            ut.add(trace.COMPUTE, 0.0, 1.0)
        assert rec.dropped_spans == 6
        assert len(rec.drain_wire()) == 4
        assert rec.drain_wire() == []

    def test_requeue_restores_undelivered_spans_in_order(self):
        """A push that spends its whole retry budget re-queues its drained
        piggyback: the spans ride the next push instead of vanishing."""
        rec = trace.TraceRecorder(sample_rate=1.0, capacity=8)
        ut = rec.start_update(3)
        ut.add(trace.PULL_RTT, 0.0, 1.0)
        ut.add(trace.COMPUTE, 1.0, 2.0)
        drained = rec.drain_wire()
        assert len(drained) == 2 and rec.drain_wire() == []
        rec.requeue(drained)           # the send terminally failed
        again = rec.drain_wire()
        assert again == drained        # same spans, same order


# --------------------------------------------- Histogram nearest-rank (sat 6)
class TestHistogramPercentiles:
    def test_small_n_p95_is_not_max(self):
        h = Histogram()
        for v in range(1, 21):   # 1..20; old int(0.95*20)=19 -> max
            h.update(float(v))
        snap = h.snapshot()
        assert snap["p95"] == 19.0
        assert snap["p99"] == 20.0
        assert snap["p50"] == 10.0
        assert snap["max"] == 20.0

    def test_single_value(self):
        h = Histogram()
        h.update(7.0)
        snap = h.snapshot()
        assert snap["p50"] == snap["p95"] == snap["p99"] == 7.0

    def test_nearest_rank_definition(self):
        # nearest-rank: smallest value with cdf >= q
        assert Histogram._pct([1.0, 2.0, 3.0, 4.0], 0.5) == 2.0
        assert Histogram._pct([1.0, 2.0, 3.0, 4.0], 0.75) == 3.0
        assert Histogram._pct([1.0, 2.0, 3.0, 4.0], 0.76) == 4.0


# ----------------------------------------- totals reset + per-run delta (sat 1)
class TestTotalsResetAndDelta:
    def test_reset_totals_zeroes_every_subsystem(self):
        supervisor_mod.bump_total("rejoins")
        w = DedupWindow()
        hdr = {"sid": "s1", "seq": 1}
        w.record(hdr, {"op": "ACK"})
        assert w.check(hdr) is not None
        trace.aggregator().add(trace.Span(
            stage=trace.COMPUTE, trace_id="t" * 16, span_id="s",
            parent_id=None, worker_id=0, model_version=0, start_ms=0.0,
            dur_ms=1.0,
        ))
        assert recovery_totals()["rejoins"] >= 1
        assert net_totals()["dedup_hits"] >= 1
        assert trace.aggregator().spans_total >= 1
        reset_totals()
        assert recovery_totals()["rejoins"] == 0
        assert net_totals()["dedup_hits"] == 0
        assert trace.aggregator().spans_total == 0
        from asyncframework_tpu.data.spill import shuffle_totals

        assert all(v == 0 for v in shuffle_totals().values())

    def test_live_ui_captures_per_run_delta(self):
        """Regression: a second run's live UI must not inherit the first
        run's process-global counts."""
        supervisor_mod.bump_total("rejoins", 5)
        listener = LiveStateListener(num_workers=2)  # "second run" starts
        snap = listener.snapshot()
        assert snap["recovery"]["rejoins"] == 0
        supervisor_mod.bump_total("rejoins", 2)      # progress IN this run
        snap = listener.snapshot()
        assert snap["recovery"]["rejoins"] == 2
        assert snap["net"]["retries"] >= 0  # delta view, never negative


# ------------------------------------------------ truncated event log (sat 2)
class TestTruncatedEventLog:
    def _write_log(self, path, n=5):
        wr = EventLogWriter(path)
        for i in range(n):
            wr.on_event(GradientMerged(
                time_ms=float(i), worker_id=i % 2, staleness=i,
                accepted=True, iteration=i,
            ))
        wr.close()

    def test_torn_final_record_skip_and_count(self, tmp_path):
        log = tmp_path / "run.jsonl"
        self._write_log(log, n=5)
        # crash mid-write: cut the file in the middle of the last record
        data = log.read_bytes()
        log.write_bytes(data[: len(data) - 20])
        reader = EventLogReader(log)
        events = list(reader.replay(strict=False))
        assert len(events) == 4
        assert reader.truncated_records == 1
        # strict mode still surfaces the corruption
        with pytest.raises(json.JSONDecodeError):
            list(EventLogReader(log).replay(strict=True))
        # the summary (report path) surfaces the count
        summary = EventLogReader(log).summary()
        assert summary["truncated_records"] == 1
        assert summary["merges"] == 4

    def test_writer_killed_9_mid_record_replay_survives(self, tmp_path):
        """THE kill -9 world: a writer process SIGKILLed while streaming
        events leaves an arbitrary tail; the tolerant replay must never
        raise and must count at most the one torn record."""
        log = tmp_path / "killed.jsonl"
        code = (
            "import sys\n"
            "sys.path.insert(0, %r)\n"
            "from asyncframework_tpu.metrics.eventlog import EventLogWriter\n"
            "from asyncframework_tpu.metrics.bus import GradientMerged\n"
            "wr = EventLogWriter(%r)\n"
            "i = 0\n"
            "while True:\n"
            "    wr.on_event(GradientMerged(time_ms=float(i), worker_id=0,\n"
            "                staleness=i, accepted=True, iteration=i,\n"
            "                batch_size=10**6))\n"
            "    i += 1\n"
        ) % (str(Path(__file__).parent.parent), str(log))
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        try:
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if log.exists() and log.stat().st_size > 20_000:
                    break
                time.sleep(0.02)
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
        finally:
            if proc.poll() is None:
                proc.kill()
        reader = EventLogReader(log)
        events = list(reader.replay(strict=False))  # must not raise
        assert len(events) > 0
        assert reader.truncated_records in (0, 1)

    def test_history_index_reports_truncation(self, tmp_path):
        from asyncframework_tpu.metrics.history import build_history

        log = tmp_path / "torn.jsonl"
        self._write_log(log, n=6)
        data = log.read_bytes()
        log.write_bytes(data[: len(data) - 15])
        index = build_history(tmp_path)
        html = index.read_text()
        assert "truncated record" in html


# ---------------------------------------------- single-process solver tracing
class TestSingleProcessTracing:
    def test_run_instruments_emits_lifecycle_spans(self, tmp_path):
        from asyncframework_tpu.solvers.instrumentation import RunInstruments

        log = tmp_path / "sp.jsonl"
        cfg = SolverConfig(num_workers=2, trace_sample=1.0,
                           event_log=str(log))
        inst = RunInstruments(cfg, 2)
        inst.on_gradient_merged(0, staleness=2, accepted=True, iteration=7,
                                task_ms=3.0, queue_ms=1.0, apply_ms=0.5)
        inst.close()
        spans, _ = trace.load_trace_events(log)
        stages = {s.stage for s in spans}
        assert stages == {trace.COMPUTE, trace.MERGE_QUEUE,
                          trace.MERGE_APPLY}
        (apply_span,) = [s for s in spans if s.stage == trace.MERGE_APPLY]
        assert apply_span.staleness == 2
        assert apply_span.staleness_ms == pytest.approx(4.0)
        assert apply_span.accepted is True
        assert apply_span.model_version == 7
        # all three share one trace
        assert len({s.trace_id for s in spans}) == 1

    def test_asgd_run_traced_end_to_end(self, tiny_problem, tmp_path):
        from asyncframework_tpu.solvers import ASGD

        X, y, _w = tiny_problem
        log = tmp_path / "asgd.jsonl"
        cfg = SolverConfig(
            num_workers=4, num_iterations=40, gamma=0.4, taw=2**31 - 1,
            batch_rate=0.3, bucket_ratio=0.5, printer_freq=20, seed=42,
            calibration_iters=8, run_timeout_s=60.0, event_log=str(log),
            trace_sample=1.0, heartbeat=False,
        )
        res = ASGD(X, y, cfg).run()
        assert res.accepted == 40
        spans, _ = trace.load_trace_events(log)
        stages = {s.stage for s in spans}
        assert trace.COMPUTE in stages and trace.MERGE_APPLY in stages
        applies = [s for s in spans if s.stage == trace.MERGE_APPLY]
        assert applies and all(s.staleness is not None
                               and s.staleness_ms is not None
                               for s in applies)


class TestPSFoldDedup:
    def test_piggyback_refold_is_deduped_by_span_id(self, devices8):
        """A push delivered but never ACKed re-queues its piggyback under
        a fresh (sid, seq); the PS must not fold the same spans twice."""
        cfg = make_cfg(num_workers=1, num_iterations=10)
        ps = ps_dcn.ParameterServer(cfg, 8, 64, device=devices8[0], port=0)
        try:
            wire = trace.Span(
                stage=trace.COMPUTE, trace_id="t" * 16,
                span_id="aabbccdd", parent_id=None, worker_id=0,
                model_version=1, start_ms=1.0, dur_ms=2.0,
            ).to_wire()
            ps._fold_wire_spans([wire])
            ps._fold_wire_spans([wire])  # the re-queued re-delivery
            assert ps.trace_spans == 1
        finally:
            ps.stop()


class TestCliExitCodes:
    def test_json_mode_flags_traceless_log(self, tmp_path, capsys):
        """--json must agree with table mode: a trace-less log (sampling
        off / no event log attached) exits 1 so CI can gate on it."""
        log = tmp_path / "empty.jsonl"
        EventLogWriter(log).close()
        rc = trace.main([str(log), "--json"])
        out = capsys.readouterr().out.strip()
        assert rc == 1
        assert json.loads(out)["spans"] == 0


# ------------------------------------------------- THE acceptance scenario
class TestCrossProcessAcceptance:
    def test_two_process_dcn_trace_end_to_end(self, devices8, tmp_path,
                                              monkeypatch, capsys):
        """Two OS processes (PS child + this process's workers) over real
        loopback sockets: >= 1 complete span chain (pull.rtt / compute /
        push.rtt under one trace_id), staleness in versions AND ms,
        visible in /api/status, reconstructed by bin/async-trace, exported
        as valid Chrome tracing JSON."""
        log = tmp_path / "dcn.jsonl"
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        env.update(
            PS_ROLE="ps", PS_NUM_WORKER_PROCS="1", PS_NUM_ITER="300",
            PS_UI="1", PS_EVENT_LOG=str(log),
            ASYNCTPU_ASYNC_TRACE_SAMPLE="1.0",
        )
        ps_proc = subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        )
        snapshots = []
        poll_errors = []
        stop_poll = threading.Event()
        try:
            hello = json.loads(ps_proc.stdout.readline())
            port, ui_port = hello["port"], hello["ui_port"]

            def poll():
                url = f"http://127.0.0.1:{ui_port}/api/status"
                while not stop_poll.is_set():
                    try:
                        status, snap = _get_json(url)
                        if status != 200:
                            poll_errors.append(status)
                        else:
                            snapshots.append(snap)
                    except Exception:
                        pass  # server not up yet / already down
                    time.sleep(0.05)

            poller = threading.Thread(target=poll, daemon=True)
            poller.start()

            # this process IS the second process: real sockets to the PS
            monkeypatch.setenv("ASYNCTPU_ASYNC_TRACE_SAMPLE", "1.0")
            cfg = make_cfg()
            n, d = 4096, 24
            ds = ShardedDataset.generate_on_device(
                n, d, 8, devices=devices8, seed=11, noise=0.01)
            shards = {w: ds.shard(w) for w in range(8)}
            ps_dcn.run_worker_process(
                "127.0.0.1", port, list(range(8)), shards, cfg, d, n,
                eval_wid=0, deadline_s=120.0, proc_token="trace-test",
            )
            out, _ = ps_proc.communicate(timeout=120)
        finally:
            stop_poll.set()
            if ps_proc.poll() is None:
                ps_proc.kill()
        final = json.loads(out.strip().splitlines()[-1])
        assert final["done"], final
        assert final["accepted"] == 300
        assert final["trace_spans"] > 0, final

        # --- live UI: the trace section carried spans and staleness-in-ms
        assert not poll_errors, poll_errors
        traced = [s for s in snapshots if s["trace"]["spans"] > 0]
        assert traced, "no /api/status snapshot ever showed trace spans"
        last = traced[-1]["trace"]
        assert last["staleness_ms"]["count"] > 0
        assert last["staleness_versions"]["count"] > 0
        assert "p95" in last["stages_ms"][trace.MERGE_APPLY]

        # --- event log: >= 1 complete cross-process chain
        spans, truncated = trace.load_trace_events(log)
        assert truncated == 0
        traces = trace.build_traces(spans)
        complete = trace.complete_traces(traces)
        assert len(complete) >= 1
        tid, chain = next(iter(complete.items()))
        chain_stages = {s.stage for s in chain}
        assert {trace.PULL_RTT, trace.COMPUTE,
                trace.PUSH_RTT} <= chain_stages
        assert all(s.trace_id == tid for s in chain)
        # the server saw the same trace ids the workers minted (wire
        # propagation, not correlation): PS-side stages joined the chains
        server_stages = {s.stage for s in spans}
        assert trace.MERGE_APPLY in server_stages
        assert trace.PULL_WAIT in server_stages
        joined = [t for t, ss in complete.items()
                  if any(s.stage == trace.MERGE_APPLY for s in ss)]
        assert joined, "no chain carried both client and server spans"
        # staleness in BOTH units on the merge spans
        merge = [s for s in spans if s.stage == trace.MERGE_APPLY]
        assert any(s.staleness is not None and s.staleness_ms is not None
                   for s in merge)

        # --- bin/async-trace reconstruction + chrome export
        chrome_path = tmp_path / "chrome.json"
        rc = trace.main([str(log), "--chrome", str(chrome_path), "--json"])
        assert rc == 0
        summary = json.loads(capsys.readouterr().out.strip())
        assert summary["complete_traces"] >= 1
        assert summary["decomposition"]["stages_ms"][trace.PUSH_RTT][
            "count"] > 0
        assert summary["stragglers"]
        chrome = json.loads(chrome_path.read_text())
        assert chrome["traceEvents"]
        for ev in chrome["traceEvents"][:50]:
            assert ev["ph"] == "X"
            assert {"name", "ts", "dur", "pid", "tid"} <= set(ev)

    def test_sampling_off_no_trace_work(self, devices8, monkeypatch):
        """async.trace.sample = 0: no recorder, no wire context, no spans
        -- the hot path does zero tracing work."""
        monkeypatch.setenv("ASYNCTPU_ASYNC_TRACE_SAMPLE", "0.0")
        cfg = make_cfg(num_iterations=60, num_workers=4)
        n, d = 1024, 16
        ds = ShardedDataset.generate_on_device(
            n, d, 4, devices=devices8[:4], seed=3, noise=0.01)
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        shards = {w: ds.shard(w) for w in range(4)}
        before = trace.aggregator().spans_total
        ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(4)), shards, cfg, d, n,
            deadline_s=60.0,
        )
        done = ps.wait_done(timeout_s=5.0)
        ps.stop()
        assert done
        assert ps.trace_spans == 0
        assert trace.aggregator().spans_total == before


# --------------------------------------------- live UI under chaos (sat 3)
class TestLiveUIUnderChaos:
    def test_api_status_survives_faults_and_sigkill(self, devices8,
                                                    monkeypatch):
        """Poll /api/status continuously while a seeded fault schedule
        fires and a worker process is SIGKILLed: the server never 500s,
        every snapshot is JSON-valid, and the trace/recovery sections stay
        monotonic."""
        monkeypatch.setenv("ASYNCTPU_ASYNC_TRACE_SAMPLE", "1.0")
        sup = ElasticSupervisor(8, dead_after_s=1.0, check_interval_s=0.2,
                                boot_grace_s=60.0)
        cfg = make_cfg(num_iterations=1200, printer_freq=300,
                       run_timeout_s=240.0)
        n, d = 4096, 24
        ds = ShardedDataset.generate_on_device(n, d, 8, devices=devices8,
                                               seed=11, noise=0.01)
        bus = ListenerBus()
        state = LiveStateListener(8)
        bus.add_listener(state)
        bus.start()
        ui = LiveUIServer(state, port=0).start()
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0], port=0,
                                    supervisor=sup, bus=bus).start()
        ep = f"127.0.0.1:{ps.port}"
        sched = FaultSchedule(seed=11)
        sched.add(ep, CONNECT_OP, 3, CONNECT_REFUSED)
        sched.add(ep, "PULL", 7, STALL_READ)
        sched.add(ep, "PUSH", 5, DROP_REPLY)
        sched.add(ep, "PUSH", 11, CUT_MID_FRAME)

        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        env.update(
            PS_ROLE="worker", PS_PORT=str(ps.port), PS_WORKER_ID="1",
            PS_NUM_WORKER_PROCS="2", PS_WIDS="4,5,6,7", PS_EVAL="0",
            PS_NUM_ITER="1200",
        )
        doomed = subprocess.Popen(
            [sys.executable, str(CHILD)], env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        polled = []
        bad_status = []
        stop_poll = threading.Event()

        def poll():
            url = f"http://127.0.0.1:{ui.port}/api/status"
            while not stop_poll.is_set():
                try:
                    status, snap = _get_json(url)
                    if status != 200:
                        bad_status.append(status)
                    else:
                        polled.append(snap)
                except (urllib.error.HTTPError,) as e:  # a 500 lands here
                    bad_status.append(e.code)
                except Exception:
                    pass  # transient connect issues are not the UI's fault
                time.sleep(0.03)

        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        counts = {}
        try:
            with faults.injected(sched):
                t_surv = threading.Thread(
                    target=lambda: counts.update(ps_dcn.run_worker_process(
                        "127.0.0.1", ps.port, [0, 1, 2, 3],
                        {w: ds.shard(w) for w in range(4)}, cfg, d, n,
                        eval_wid=0, deadline_s=240.0,
                        shard_factory=ds.shard, proc_token="survivor")),
                    daemon=True,
                )
                t_surv.start()
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    with ps._lock:
                        if all(ps.pushes_by_wid.get(w, 0) >= 2
                               for w in (4, 5, 6, 7)):
                            break
                    time.sleep(0.05)
                doomed.send_signal(signal.SIGKILL)
                doomed.wait(timeout=10)
                t_surv.join(timeout=240)
                assert not t_surv.is_alive(), "survivor never finished"
                res = ps.wait_done(timeout_s=30.0)
                assert res, str(res)
        finally:
            stop_poll.set()
            poller.join(timeout=5)
            if doomed.poll() is None:
                doomed.kill()
            ps.stop()
            ui.stop()
            bus.stop()

        # the UI never errored and every snapshot parsed (parsing happened
        # in the poller; reaching here with entries proves it)
        assert not bad_status, bad_status
        assert len(polled) > 10
        # monotonic sections: trace span counts and recovery counters only
        # ever grow within one run
        spans_seq = [s["trace"]["spans"] for s in polled]
        assert all(a <= b for a, b in zip(spans_seq, spans_seq[1:]))
        lost_seq = [s["recovery"]["workers_lost"] for s in polled]
        assert all(a <= b for a, b in zip(lost_seq, lost_seq[1:]))
        assert lost_seq[-1] >= 4  # the SIGKILLed process's four wids
        adopted_seq = [s["recovery"]["shards_adopted"] for s in polled]
        assert all(a <= b for a, b in zip(adopted_seq, adopted_seq[1:]))
        # chaos fired and the dashboard saw it (per-run delta view)
        assert polled[-1]["net"]["faults_fired"] >= 1
        # and the trace section ended populated despite the chaos
        assert polled[-1]["trace"]["spans"] > 0
