"""Streaming layer tests: micro-batch DStreams with deterministic clocks.

Parity with the reference's streaming test strategy (SURVEY.md section 4):
virtual time via ManualClock drives the job generator, so every interval and
window is exactly reproducible; WAL crash-recovery mirrors
``WriteAheadLogSuite``.
"""

import threading

import numpy as np
import pytest

from asyncframework_tpu.streaming import StreamingContext, WriteAheadLog
from asyncframework_tpu.streaming.dstream import EMPTY
from asyncframework_tpu.utils.clock import ManualClock


def collect_sink():
    out = []
    lock = threading.Lock()

    def sink(t, batch):
        with lock:
            out.append((t, batch))

    return out, sink


class TestDStreamGraph:
    def test_map_filter_pipeline_deterministic(self):
        ssc = StreamingContext(batch_interval_ms=100, clock=ManualClock())
        batches = [np.arange(4) + 10 * i for i in range(5)]
        out, sink = collect_sink()
        (
            ssc.queue_stream(batches)
            .map_batch(lambda b: b * 2)
            .filter_batch(lambda b: b.sum() > 12)  # drops the first batch
            .foreach_batch(sink)
        )
        # drive intervals synchronously -- no threads, pure logic
        for k in range(1, 6):
            ssc.generate_batch(k * 100)
        assert [t for t, _ in out] == [200, 300, 400, 500]
        np.testing.assert_array_equal(out[0][1], (np.arange(4) + 10) * 2)

    def test_window_concats_last_n(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out, sink = collect_sink()
        src = ssc.queue_stream([np.array([i]) for i in range(6)])
        src.window(3).map_batch(lambda bs: np.concatenate(bs)).foreach_batch(sink)
        for k in range(1, 7):
            ssc.generate_batch(k * 10)
        # at t=30 the last 3 batches are [0],[1],[2]
        got = {t: list(b) for t, b in out}
        assert got[30] == [0, 1, 2]
        assert got[60] == [3, 4, 5]
        assert got[10] == [0]  # partial window at the start

    def test_window_slide(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out, sink = collect_sink()
        src = ssc.queue_stream([np.array([i]) for i in range(8)])
        src.window(2, slide=2).foreach_batch(sink)
        for k in range(1, 9):
            ssc.generate_batch(k * 10)
        assert [t for t, _ in out] == [20, 40, 60, 80]

    def test_reduce_by_window_and_count(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        sums, sum_sink = collect_sink()
        counts, count_sink = collect_sink()
        src = ssc.queue_stream([np.full(3, i, np.float32) for i in range(4)])
        src.reduce_by_window(lambda a, b: a + b, 2).foreach_batch(sum_sink)
        src.count().foreach_batch(count_sink)
        for k in range(1, 5):
            ssc.generate_batch(k * 10)
        np.testing.assert_array_equal(sums[1][1], np.full(3, 0 + 1, np.float32))
        np.testing.assert_array_equal(sums[3][1], np.full(3, 2 + 3, np.float32))
        assert [c for _, c in counts] == [3, 3, 3, 3]

    def test_union_merges_sources(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out, sink = collect_sink()
        a = ssc.queue_stream([np.array([1]), np.array([2])])
        b = ssc.queue_stream([np.array([10])])
        a.union(b).foreach_batch(sink)
        ssc.generate_batch(10)
        ssc.generate_batch(20)
        np.testing.assert_array_equal(out[0][1], [1, 10])
        np.testing.assert_array_equal(out[1][1], [2])  # b exhausted

    def test_shared_parent_computed_once_per_interval(self):
        """get_or_compute memoization: two consumers, one evaluation."""
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        calls = {"n": 0}

        def expensive(b):
            calls["n"] += 1
            return b

        src = ssc.queue_stream([np.array([1])])
        mapped = src.map_batch(expensive)
        out1, sink1 = collect_sink()
        out2, sink2 = collect_sink()
        mapped.count().foreach_batch(sink1)
        mapped.map_batch(lambda b: b * 2).foreach_batch(sink2)
        ssc.generate_batch(10)
        assert calls["n"] == 1
        assert out1 and out2

    def test_empty_interval_fires_nothing(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out, sink = collect_sink()
        ssc.queue_stream([]).foreach_batch(sink)
        assert ssc.generate_batch(10) == 0
        assert out == []


class TestClockedGeneration:
    def test_manual_clock_drives_generator_thread(self):
        clock = ManualClock()
        ssc = StreamingContext(batch_interval_ms=100, clock=clock)
        out, sink = collect_sink()
        src = ssc.queue_stream([np.array([i]) for i in range(3)])
        src.foreach_batch(sink)
        ssc.start()
        try:
            clock.advance(100)
            ssc.await_intervals(1)
            assert len(out) == 1
            clock.advance(200)
            ssc.await_intervals(3)
            assert [int(b[0]) for _, b in out] == [0, 1, 2]
        finally:
            ssc.stop()

    def test_push_after_start(self):
        clock = ManualClock()
        ssc = StreamingContext(batch_interval_ms=100, clock=clock)
        out, sink = collect_sink()
        src = ssc.queue_stream()
        src.foreach_batch(sink)
        ssc.start()
        try:
            src.push(np.array([7]))
            clock.advance(100)
            ssc.await_intervals(1)
            assert int(out[0][1][0]) == 7
        finally:
            ssc.stop()

    def test_start_without_outputs_rejected(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        ssc.queue_stream([np.array([1])])
        with pytest.raises(RuntimeError, match="no output operations"):
            ssc.start()


class TestWriteAheadLog:
    def test_append_replay_arrays_and_objects(self, tmp_path):
        with WriteAheadLog(tmp_path / "wal") as wal:
            wal.append(100, np.arange(4, dtype=np.float32))
            wal.append(200, {"rows": [1, 2, 3]})
        with WriteAheadLog(tmp_path / "wal") as wal:
            got = list(wal.replay())
        assert got[0][0] == 100
        np.testing.assert_array_equal(got[0][1], [0, 1, 2, 3])
        assert got[1] == (200, {"rows": [1, 2, 3]})

    def test_torn_tail_truncated(self, tmp_path):
        p = tmp_path / "wal"
        with WriteAheadLog(p) as wal:
            wal.append(1, np.array([1.0]))
        with open(p, "ab") as f:
            f.write(b"\xff\x00\x00\x00garbage")  # torn record
        with WriteAheadLog(p) as wal:
            assert len(list(wal.replay())) == 1
            wal.append(2, np.array([2.0]))
            assert len(list(wal.replay())) == 2

    def test_stream_recovery_end_to_end(self, tmp_path):
        """Batches logged before processing replay after a 'restart'."""
        wal = WriteAheadLog(tmp_path / "wal")
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out, sink = collect_sink()
        src = ssc.queue_stream(
            [np.array([i], np.float32) for i in range(3)], wal=wal
        )
        src.map_batch(lambda b: b + 1).foreach_batch(sink)
        for k in range(1, 4):
            ssc.generate_batch(k * 10)
        assert len(out) == 3
        wal.close()

        # "restart": a fresh context replays the WAL through the same graph
        wal2 = WriteAheadLog(tmp_path / "wal")
        ssc2 = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out2, sink2 = collect_sink()
        ssc2.recovered_stream(wal2).map_batch(lambda b: b + 1).foreach_batch(sink2)
        for k in range(1, 4):
            ssc2.generate_batch(k * 10)
        assert [float(b[0]) for _, b in out2] == [1.0, 2.0, 3.0]

    def test_clear(self, tmp_path):
        wal = WriteAheadLog(tmp_path / "wal")
        wal.append(1, np.array([1.0]))
        wal.clear()
        assert list(wal.replay()) == []
        wal.append(2, np.array([2.0]))
        assert len(list(wal.replay())) == 1
        wal.close()
