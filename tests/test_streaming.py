"""Streaming layer tests: micro-batch DStreams with deterministic clocks.

Parity with the reference's streaming test strategy (SURVEY.md section 4):
virtual time via ManualClock drives the job generator, so every interval and
window is exactly reproducible; WAL crash-recovery mirrors
``WriteAheadLogSuite``.
"""

import threading
import time

import numpy as np
import pytest

from asyncframework_tpu.streaming import StreamingContext, WriteAheadLog
from asyncframework_tpu.streaming.dstream import EMPTY
from asyncframework_tpu.utils.clock import ManualClock


def collect_sink():
    out = []
    lock = threading.Lock()

    def sink(t, batch):
        with lock:
            out.append((t, batch))

    return out, sink


class TestDStreamGraph:
    def test_map_filter_pipeline_deterministic(self):
        ssc = StreamingContext(batch_interval_ms=100, clock=ManualClock())
        batches = [np.arange(4) + 10 * i for i in range(5)]
        out, sink = collect_sink()
        (
            ssc.queue_stream(batches)
            .map_batch(lambda b: b * 2)
            .filter_batch(lambda b: b.sum() > 12)  # drops the first batch
            .foreach_batch(sink)
        )
        # drive intervals synchronously -- no threads, pure logic
        for k in range(1, 6):
            ssc.generate_batch(k * 100)
        assert [t for t, _ in out] == [200, 300, 400, 500]
        np.testing.assert_array_equal(out[0][1], (np.arange(4) + 10) * 2)

    def test_window_concats_last_n(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out, sink = collect_sink()
        src = ssc.queue_stream([np.array([i]) for i in range(6)])
        src.window(3).map_batch(lambda bs: np.concatenate(bs)).foreach_batch(sink)
        for k in range(1, 7):
            ssc.generate_batch(k * 10)
        # at t=30 the last 3 batches are [0],[1],[2]
        got = {t: list(b) for t, b in out}
        assert got[30] == [0, 1, 2]
        assert got[60] == [3, 4, 5]
        assert got[10] == [0]  # partial window at the start

    def test_window_slide(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out, sink = collect_sink()
        src = ssc.queue_stream([np.array([i]) for i in range(8)])
        src.window(2, slide=2).foreach_batch(sink)
        for k in range(1, 9):
            ssc.generate_batch(k * 10)
        assert [t for t, _ in out] == [20, 40, 60, 80]

    def test_reduce_by_window_and_count(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        sums, sum_sink = collect_sink()
        counts, count_sink = collect_sink()
        src = ssc.queue_stream([np.full(3, i, np.float32) for i in range(4)])
        src.reduce_by_window(lambda a, b: a + b, 2).foreach_batch(sum_sink)
        src.count().foreach_batch(count_sink)
        for k in range(1, 5):
            ssc.generate_batch(k * 10)
        np.testing.assert_array_equal(sums[1][1], np.full(3, 0 + 1, np.float32))
        np.testing.assert_array_equal(sums[3][1], np.full(3, 2 + 3, np.float32))
        assert [c for _, c in counts] == [3, 3, 3, 3]

    def test_union_merges_sources(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out, sink = collect_sink()
        a = ssc.queue_stream([np.array([1]), np.array([2])])
        b = ssc.queue_stream([np.array([10])])
        a.union(b).foreach_batch(sink)
        ssc.generate_batch(10)
        ssc.generate_batch(20)
        np.testing.assert_array_equal(out[0][1], [1, 10])
        np.testing.assert_array_equal(out[1][1], [2])  # b exhausted

    def test_shared_parent_computed_once_per_interval(self):
        """get_or_compute memoization: two consumers, one evaluation."""
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        calls = {"n": 0}

        def expensive(b):
            calls["n"] += 1
            return b

        src = ssc.queue_stream([np.array([1])])
        mapped = src.map_batch(expensive)
        out1, sink1 = collect_sink()
        out2, sink2 = collect_sink()
        mapped.count().foreach_batch(sink1)
        mapped.map_batch(lambda b: b * 2).foreach_batch(sink2)
        ssc.generate_batch(10)
        assert calls["n"] == 1
        assert out1 and out2

    def test_empty_interval_fires_nothing(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out, sink = collect_sink()
        ssc.queue_stream([]).foreach_batch(sink)
        assert ssc.generate_batch(10) == 0
        assert out == []


class TestClockedGeneration:
    def test_manual_clock_drives_generator_thread(self):
        clock = ManualClock()
        ssc = StreamingContext(batch_interval_ms=100, clock=clock)
        out, sink = collect_sink()
        src = ssc.queue_stream([np.array([i]) for i in range(3)])
        src.foreach_batch(sink)
        ssc.start()
        try:
            clock.advance(100)
            ssc.await_intervals(1)
            assert len(out) == 1
            clock.advance(200)
            ssc.await_intervals(3)
            assert [int(b[0]) for _, b in out] == [0, 1, 2]
        finally:
            ssc.stop()

    def test_push_after_start(self):
        clock = ManualClock()
        ssc = StreamingContext(batch_interval_ms=100, clock=clock)
        out, sink = collect_sink()
        src = ssc.queue_stream()
        src.foreach_batch(sink)
        ssc.start()
        try:
            src.push(np.array([7]))
            clock.advance(100)
            ssc.await_intervals(1)
            assert int(out[0][1][0]) == 7
        finally:
            ssc.stop()

    def test_start_without_outputs_rejected(self):
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        ssc.queue_stream([np.array([1])])
        with pytest.raises(RuntimeError, match="no output operations"):
            ssc.start()


class TestWriteAheadLog:
    def test_append_replay_arrays_and_objects(self, tmp_path):
        with WriteAheadLog(tmp_path / "wal") as wal:
            wal.append(100, np.arange(4, dtype=np.float32))
            wal.append(200, {"rows": [1, 2, 3]})
        with WriteAheadLog(tmp_path / "wal") as wal:
            got = list(wal.replay())
        assert got[0][0] == 100
        np.testing.assert_array_equal(got[0][1], [0, 1, 2, 3])
        assert got[1] == (200, {"rows": [1, 2, 3]})

    def test_torn_tail_truncated(self, tmp_path):
        p = tmp_path / "wal"
        with WriteAheadLog(p) as wal:
            wal.append(1, np.array([1.0]))
        with open(p, "ab") as f:
            f.write(b"\xff\x00\x00\x00garbage")  # torn record
        with WriteAheadLog(p) as wal:
            assert len(list(wal.replay())) == 1
            wal.append(2, np.array([2.0]))
            assert len(list(wal.replay())) == 2

    def test_stream_recovery_end_to_end(self, tmp_path):
        """Batches logged before processing replay after a 'restart'."""
        wal = WriteAheadLog(tmp_path / "wal")
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out, sink = collect_sink()
        src = ssc.queue_stream(
            [np.array([i], np.float32) for i in range(3)], wal=wal
        )
        src.map_batch(lambda b: b + 1).foreach_batch(sink)
        for k in range(1, 4):
            ssc.generate_batch(k * 10)
        assert len(out) == 3
        wal.close()

        # "restart": a fresh context replays the WAL through the same graph
        wal2 = WriteAheadLog(tmp_path / "wal")
        ssc2 = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        out2, sink2 = collect_sink()
        ssc2.recovered_stream(wal2).map_batch(lambda b: b + 1).foreach_batch(sink2)
        for k in range(1, 4):
            ssc2.generate_batch(k * 10)
        assert [float(b[0]) for _, b in out2] == [1.0, 2.0, 3.0]

    def test_clear(self, tmp_path):
        wal = WriteAheadLog(tmp_path / "wal")
        wal.append(1, np.array([1.0]))
        wal.clear()
        assert list(wal.replay()) == []
        wal.append(2, np.array([2.0]))
        assert len(list(wal.replay())) == 1
        wal.close()


class TestUpdateStateByKey:
    @staticmethod
    def wordcount(ssc, source):
        pairs = source.map_batch(lambda words: [(w, 1) for w in words])
        counts = pairs.update_state_by_key(
            lambda new, prev: (prev or 0) + sum(new)
        )
        seen = []
        counts.foreach_batch(lambda t, b: seen.append((t, dict(b))))
        return counts, seen

    def test_stateful_word_count(self):
        ssc = StreamingContext(batch_interval_ms=100)
        src = ssc.queue_stream([["a", "b", "a"], ["b", "c"], []])
        _counts, seen = self.wordcount(ssc, src)
        for n in (1, 2, 3):
            ssc.generate_batch(n * 100)
        assert seen[-1][1] == {"a": 2, "b": 2, "c": 1}
        # full state emitted every interval, including the empty one
        assert len(seen) == 3
        assert seen[1][1] == {"a": 2, "b": 2, "c": 1}

    def test_update_returning_none_drops_key(self):
        ssc = StreamingContext(batch_interval_ms=100)
        src = ssc.queue_stream([[("x", 5)], [("y", 1)]])
        st = src.update_state_by_key(
            lambda new, prev: sum(new) if new else None  # expire idle keys
        )
        out = []
        st.foreach_batch(lambda t, b: out.append(dict(b)))
        ssc.generate_batch(100)
        ssc.generate_batch(200)
        assert out[0] == {"x": 5}
        assert out[1] == {"y": 1}  # x expired


class TestStreamingStateCheckpoint:
    def test_stateful_wordcount_survives_restart(self, tmp_path):
        """WAL + periodic state checkpoint: a rebuilt context restores the
        checkpoint, replays only post-checkpoint WAL batches, and ends in
        exactly the state of the uninterrupted run."""
        batches = [["a"], ["a", "b"], ["b", "c"], ["c", "a"]]
        wal_path = tmp_path / "wal"
        ckpt_dir = tmp_path / "state-ckpt"

        # first life: 3 of 4 intervals processed; checkpoint every 2
        ssc1 = StreamingContext(batch_interval_ms=100)
        ssc1.enable_state_checkpoint(ckpt_dir, every_n_intervals=2)
        with WriteAheadLog(wal_path) as wal:
            src1 = ssc1.queue_stream(list(batches), wal=wal)
            _c, seen1 = TestUpdateStateByKey.wordcount(ssc1, src1)
            for n in (1, 2, 3):
                ssc1.generate_batch(n * 100)
        assert seen1[-1][1] == {"a": 2, "b": 2, "c": 1}
        # crash here: interval 3 was processed but NOT checkpointed

        # second life: restore state (through interval 2), replay the rest
        ssc2 = StreamingContext(batch_interval_ms=100)
        ssc2.enable_state_checkpoint(ckpt_dir, every_n_intervals=2)
        after = ssc2.restore_state()
        assert after == 200
        with WriteAheadLog(wal_path) as wal2:
            rec = ssc2.recovered_stream(wal2, after_ms=after)
            _c2, seen2 = TestUpdateStateByKey.wordcount(ssc2, rec)
            ssc2.generate_batch(100)  # replays original interval 3
        assert seen2[-1][1] == {"a": 2, "b": 2, "c": 1}

        # feed the never-processed 4th batch in the new life: totals continue
        src_rest = list(batches[3:])
        with WriteAheadLog(wal_path) as wal3:
            rec2 = ssc2.queue_stream(src_rest, wal=wal3)
            pairs = rec2.map_batch(lambda ws: [(w, 1) for w in ws])
            # continue ON THE SAME stateful node via union is overkill here;
            # assert instead that the restored run's state matches life 1
        assert seen2[-1][1] == seen1[-1][1]

    def test_restore_without_checkpoint_returns_none(self, tmp_path):
        ssc = StreamingContext(batch_interval_ms=100)
        ssc.enable_state_checkpoint(tmp_path / "empty-ckpt")
        assert ssc.restore_state() is None

    def test_tuple_keys_roundtrip_checkpoint(self, tmp_path):
        ssc1 = StreamingContext(batch_interval_ms=100)
        ssc1.enable_state_checkpoint(tmp_path / "ck", every_n_intervals=1)
        src = ssc1.queue_stream([[(("u1", "home"), 1), (("u2", "cart"), 2)]])
        st = src.update_state_by_key(lambda new, prev: (prev or 0) + sum(new))
        st.foreach_batch(lambda t, b: None)
        ssc1.generate_batch(100)

        ssc2 = StreamingContext(batch_interval_ms=100)
        ssc2.enable_state_checkpoint(tmp_path / "ck", every_n_intervals=1)
        after = ssc2.restore_state()
        assert after == 100
        src2 = ssc2.queue_stream([[(("u1", "home"), 5)]])
        st2 = src2.update_state_by_key(lambda new, prev: (prev or 0) + sum(new))
        out = []
        st2.foreach_batch(lambda t, b: out.append(dict(b)))
        ssc2.generate_batch(100)
        # restored tuple key merged with the new value, not duplicated
        assert out[0][("u1", "home")] == 6
        assert out[0][("u2", "cart")] == 2

    def test_cold_recovery_replays_time_zero_batch(self, tmp_path):
        with WriteAheadLog(tmp_path / "wal0") as wal:
            wal.append(0, ["first"])
            wal.append(100, ["second"])
        ssc = StreamingContext(batch_interval_ms=100)
        with WriteAheadLog(tmp_path / "wal0") as wal2:
            rec = ssc.recovered_stream(wal2)  # cold start: replay everything
            out = []
            rec.foreach_batch(lambda t, b: out.append(list(b)))
            ssc.generate_batch(100)
            ssc.generate_batch(200)
        assert out == [["first"], ["second"]]


class TestReceivers:
    def test_receiver_stream_batches_by_interval(self):
        from asyncframework_tpu.streaming import ReceiverStream

        ssc = StreamingContext(batch_interval_ms=100)
        rec = ReceiverStream(ssc)
        out = []
        rec.foreach_batch(lambda t, b: out.append(list(b)))
        rec.store("a"); rec.store("b")
        ssc.generate_batch(100)
        ssc.generate_batch(200)  # nothing buffered: no output fires
        rec.store("c")
        ssc.generate_batch(300)
        assert out == [["a", "b"], ["c"]]

    def test_socket_text_stream_end_to_end(self, tmp_path):
        import socket as socketlib
        import threading
        import time as _time

        from asyncframework_tpu.streaming import SocketTextStream, WriteAheadLog

        server = socketlib.socket()
        server.bind(("127.0.0.1", 0))
        server.listen(1)
        port = server.getsockname()[1]

        def serve():
            conn, _ = server.accept()
            with conn:
                conn.sendall(b"alpha\nbeta\ngam")
                _time.sleep(0.05)
                conn.sendall(b"ma\n")
                _time.sleep(0.2)

        t = threading.Thread(target=serve, daemon=True)
        t.start()

        ssc = StreamingContext(batch_interval_ms=100)
        wal = WriteAheadLog(tmp_path / "rx-wal", compress=True)
        rx = SocketTextStream(ssc, "127.0.0.1", port, wal=wal)
        counts = rx.map_batch(lambda lines: len(lines))
        seen = []
        counts.foreach_batch(lambda tms, n: seen.append(n))
        rx.start()
        deadline = _time.monotonic() + 5
        tick = 1
        while sum(seen) < 3 and _time.monotonic() < deadline:
            _time.sleep(0.05)
            ssc.generate_batch(tick * 100)
            tick += 1
        rx.stop()
        server.close()
        assert sum(seen) == 3  # all three lines arrived, split-safe
        # reliability: the WAL persisted every drained batch
        replayed = [b for (_t2, b) in wal.replay()]
        wal.close()
        assert sorted(x for b in replayed for x in b) == ["alpha", "beta", "gamma"]


class TestReduceByKeyAndWindow:
    def feed(self, n=8, seed=0):
        import random

        rng = random.Random(seed)
        return [
            [(rng.choice("abc"), rng.randint(1, 5)) for _ in range(6)]
            for _ in range(n)
        ]

    @pytest.mark.parametrize("length,slide", [(3, 1), (3, 2), (2, 3), (4, 4)])
    def test_inverse_matches_recompute(self, length, slide):
        """The incremental (inv_fn) path must emit exactly what full
        recombination emits, window for window."""
        batches = self.feed()
        out_full, out_inc = [], []
        for out, inv in ((out_full, None), (out_inc, lambda a, b: a - b)):
            ssc = StreamingContext(batch_interval_ms=100)
            src = ssc.queue_stream([list(b) for b in batches])
            win = src.reduce_by_key_and_window(
                lambda a, b: a + b, length, slide, inv_fn=inv,
                filter_fn=(lambda k, v: v != 0) if inv else None,
            )
            win.foreach_batch(lambda t, b: out.append((t, dict(b))))
            for n in range(1, len(batches) + 1):
                ssc.generate_batch(n * 100)
        full = {t: {k: v for k, v in d.items() if v != 0}
                for t, d in out_full}
        inc = {t: {k: v for k, v in d.items() if v != 0} for t, d in out_inc}
        assert inc == full

    def test_per_interval_reduce_by_key(self):
        ssc = StreamingContext(batch_interval_ms=100)
        src = ssc.queue_stream([[("a", 1), ("b", 2), ("a", 3)]])
        out = []
        src.reduce_by_key_batch(lambda x, y: x + y).foreach_batch(
            lambda t, b: out.append(dict(b))
        )
        ssc.generate_batch(100)
        assert out == [{"a": 4, "b": 2}]

    def test_filter_fn_prunes_carried_state(self):
        """Keys whose value zeroed out and that left the window must leave
        the carried state dict (unbounded growth otherwise)."""
        ssc = StreamingContext(batch_interval_ms=100)
        batches = [[("gone", 1)], [], [], [("new", 2)], []]
        src = ssc.queue_stream([list(b) for b in batches])
        win = src.reduce_by_key_and_window(
            lambda a, b: a + b, 2, 1, inv_fn=lambda a, b: a - b,
            filter_fn=lambda k, v: v != 0,
        )
        node = win
        out = []
        win.foreach_batch(lambda t, b: out.append(dict(b)))
        for n in range(1, 6):
            ssc.generate_batch(n * 100)
        assert "gone" not in node._state  # pruned once out of the window
        assert out[-2] == {"new": 2}

    def test_stale_window_reread_recomputes_not_mislabels(self):
        ssc = StreamingContext(batch_interval_ms=100)
        src = ssc.queue_stream([[("a", 1)], [("a", 10)], [("a", 100)]])
        win = src.reduce_by_key_and_window(
            lambda a, b: a + b, 2, 1, inv_fn=lambda a, b: a - b,
        )
        src._retain(5)  # keep partials so the past window is recomputable
        outs = {}
        win.foreach_batch(lambda t, b: outs.setdefault(t, dict(b)))
        ssc.generate_batch(100)
        ssc.generate_batch(200)
        ssc.generate_batch(300)
        # stale re-read of t=200 (memo cache for win holds only 1 interval)
        got = win.compute(200)
        assert dict(got) == {"a": 11}  # the true t=200 window, not t=300's


class TestPairStreamJoins:
    def test_inner_join(self):
        ssc = StreamingContext(batch_interval_ms=100, clock=ManualClock())
        left = ssc.queue_stream([[("a", 1), ("b", 2)], [("a", 3)]])
        right = ssc.queue_stream([[("a", 10), ("c", 30)], [("b", 20)]])
        out, sink = collect_sink()
        left.join(right).foreach_batch(sink)
        ssc.generate_batch(100)
        ssc.generate_batch(200)
        assert out[0] == (100, [("a", (1, 10))])
        # interval 2: no common keys -> nothing fires
        assert len(out) == 1

    def test_left_outer_join(self):
        ssc = StreamingContext(batch_interval_ms=100, clock=ManualClock())
        left = ssc.queue_stream([[("a", 1), ("b", 2)]])
        right = ssc.queue_stream([[("a", 10)]])
        out, sink = collect_sink()
        left.left_outer_join(right).foreach_batch(sink)
        ssc.generate_batch(100)
        assert sorted(out[0][1]) == [("a", (1, 10)), ("b", (2, None))]

    def test_cogroup_covers_both_sides(self):
        ssc = StreamingContext(batch_interval_ms=100, clock=ManualClock())
        left = ssc.queue_stream([[("a", 1), ("a", 2)]])
        right = ssc.queue_stream([[("a", 9), ("z", 7)]])
        out, sink = collect_sink()
        left.cogroup(right).foreach_batch(sink)
        ssc.generate_batch(100)
        got = dict(out[0][1])
        assert got["a"] == ([1, 2], [9])
        assert got["z"] == ([], [7])

    def test_join_duplicate_keys_cartesian(self):
        ssc = StreamingContext(batch_interval_ms=100, clock=ManualClock())
        left = ssc.queue_stream([[("k", 1), ("k", 2)]])
        right = ssc.queue_stream([[("k", 10), ("k", 20)]])
        out, sink = collect_sink()
        left.join(right).foreach_batch(sink)
        ssc.generate_batch(100)
        assert sorted(out[0][1]) == [
            ("k", (1, 10)), ("k", (1, 20)), ("k", (2, 10)), ("k", (2, 20))
        ]


class TestBackpressure:
    """PIDRateEstimator.scala:48 parity + bounded-buffer receiver policies:
    a producer 10x faster than the consumer must neither OOM nor deadlock,
    and the admitted rate must converge toward what the pipeline sustains."""

    def test_pid_ramps_down_to_processing_rate(self):
        from asyncframework_tpu.streaming.rate import PIDRateEstimator

        est = PIDRateEstimator(batch_interval_ms=100, min_rate=10.0)
        # pipeline sustains 500 el/s; first obs seeds, then overloaded
        assert est.compute(100, 100, 200.0, 0.0) is None  # seed: 500 el/s
        rates = []
        for i in range(2, 12):
            # keep observing 500 el/s processing with growing backlog
            r = est.compute(i * 100, 100, 200.0, 50.0)
            rates.append(r)
        assert all(r is not None for r in rates)
        # converges near the sustainable 500 el/s and never below min_rate
        assert abs(rates[-1] - 500.0) < 100.0
        assert min(rates) >= 10.0

    def test_pid_rejects_degenerate_observations(self):
        from asyncframework_tpu.streaming.rate import PIDRateEstimator

        est = PIDRateEstimator(batch_interval_ms=100)
        assert est.compute(100, 0, 50.0, 0.0) is None      # empty batch
        assert est.compute(200, 10, 0.0, 0.0) is None      # zero delay
        est.compute(300, 10, 50.0, 0.0)                    # seed
        assert est.compute(300, 10, 50.0, 0.0) is None     # non-advancing t

    def test_bounded_buffer_blocks_without_loss(self):
        import threading as th

        from asyncframework_tpu.streaming.context import StreamingContext
        from asyncframework_tpu.streaming.receiver import ReceiverStream

        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        rx = ReceiverStream(ssc, max_buffer=100)
        total = 5000
        done = th.Event()

        def produce():
            for i in range(total):
                rx.store(i)
            done.set()

        t = th.Thread(target=produce, daemon=True)
        t.start()
        got = []
        deadline = time.monotonic() + 30
        tick = 0
        while (not done.is_set() or rx._buf) and time.monotonic() < deadline:
            tick += 10
            b = rx.compute(tick)
            if b is not EMPTY:
                got.extend(b)
            time.sleep(0.001)
        t.join(timeout=5)
        assert done.is_set(), "producer deadlocked against the bounded buffer"
        assert rx.peak_buffer <= 100
        assert rx.dropped == 0
        assert sorted(got) == list(range(total))  # block mode loses nothing

    def test_drop_policy_sheds_load_without_growth(self):
        from asyncframework_tpu.streaming.context import StreamingContext
        from asyncframework_tpu.streaming.receiver import ReceiverStream

        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        rx = ReceiverStream(ssc, max_buffer=50, overflow="drop")
        for i in range(1000):  # no consumer draining
            rx.store(i)
        assert rx.peak_buffer <= 50
        assert rx.dropped == 1000 - 50

    def test_backpressure_converges_under_overload(self):
        import threading as th

        from asyncframework_tpu.streaming.context import StreamingContext
        from asyncframework_tpu.streaming.receiver import ReceiverStream

        # real clock: the PID loop needs real scheduling/processing delays
        ssc = StreamingContext(batch_interval_ms=30)
        rx = ReceiverStream(ssc, max_buffer=500, backpressure=True)
        seen = []

        def slow_consumer(_t, batch):
            seen.append(len(batch))
            time.sleep(0.06)  # 2x the interval: pipeline is overloaded

        rx.foreach_batch(slow_consumer)
        stop = th.Event()

        def produce():
            i = 0
            while not stop.is_set():
                rx.store(i)  # as fast as admitted
                i += 1

        prod = th.Thread(target=produce, daemon=True)
        ssc.start()
        prod.start()
        try:
            ssc.await_intervals(12, timeout_s=30.0)
        finally:
            stop.set()
            rx.stop()
            ssc.stop()
            prod.join(timeout=5)
        # the estimator engaged and throttled ingest to a finite rate
        assert rx.current_rate is not None
        assert rx.peak_buffer <= 500
        # batches shrank: the tail averages below the head's unthrottled size
        head = sum(seen[:3]) / max(len(seen[:3]), 1)
        tail = sum(seen[-3:]) / max(len(seen[-3:]), 1)
        assert tail < head, (seen, rx.current_rate)


class TestBackpressureConf:
    def test_env_configures_receiver_defaults(self, monkeypatch):
        from asyncframework_tpu.streaming.context import StreamingContext
        from asyncframework_tpu.streaming.receiver import ReceiverStream

        monkeypatch.setenv("ASYNCTPU_ASYNC_STREAMING_RECEIVER_MAX_BUFFER", "7")
        monkeypatch.setenv("ASYNCTPU_ASYNC_STREAMING_BACKPRESSURE_ENABLED",
                           "true")
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        rx = ReceiverStream(ssc)
        assert rx._max_buffer == 7
        assert rx._estimator is not None
        # explicit kwargs still beat the env-config defaults
        rx2 = ReceiverStream(ssc, max_buffer=3, backpressure=False)
        assert rx2._max_buffer == 3
        assert rx2._estimator is None

    def test_programmatic_conf_configures_receiver(self):
        from asyncframework_tpu.conf import (
            AsyncConf,
            set_global_conf,
        )
        from asyncframework_tpu.streaming.context import StreamingContext
        from asyncframework_tpu.streaming.receiver import ReceiverStream

        conf = AsyncConf()
        conf.set("async.streaming.receiver.max.buffer", "11")
        conf.set("async.streaming.backpressure.enabled", "true")
        set_global_conf(conf)
        try:
            ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
            rx = ReceiverStream(ssc)
            assert rx._max_buffer == 11
            assert rx._estimator is not None
        finally:
            set_global_conf(None)


class TestTextFileStream:
    """FileInputDStream parity: new files per interval, pre-existing and
    hidden/partial files ignored, each file read exactly once."""

    def test_new_files_batched_per_interval(self, tmp_path):
        from asyncframework_tpu.streaming import StreamingContext, TextFileStream

        (tmp_path / "old.txt").write_text("pre-existing\n")
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        fs = TextFileStream(ssc, tmp_path)
        got = []
        fs.foreach_batch(lambda _t, b: got.append(list(b)))

        assert ssc.generate_batch(10) == 0  # nothing new yet
        (tmp_path / "a.txt").write_text("l1\nl2\n")
        (tmp_path / ".hidden").write_text("nope\n")
        (tmp_path / "part.tmp").write_text("nope\n")
        assert ssc.generate_batch(20) == 1
        assert got == [["l1", "l2"]]
        # same file never re-read; a fresh file lands in the next batch
        (tmp_path / "b.txt").write_text("l3\n")
        ssc.generate_batch(30)
        assert got == [["l1", "l2"], ["l3"]]

    def test_wal_records_file_batches(self, tmp_path):
        from asyncframework_tpu.streaming import (
            StreamingContext,
            TextFileStream,
            WriteAheadLog,
        )

        wal = WriteAheadLog(str(tmp_path / "wal"))
        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        fs = TextFileStream(ssc, tmp_path / "in", wal=wal)
        fs.foreach_batch(lambda _t, b: None)
        (tmp_path / "in").mkdir()
        (tmp_path / "in" / "x.txt").write_text("hello\n")
        ssc.generate_batch(10)
        assert [b for (_t, b) in wal.replay()] == [["hello"]]

    def test_transient_failures_and_pruning(self, tmp_path, monkeypatch):
        from asyncframework_tpu.streaming import StreamingContext, TextFileStream

        ssc = StreamingContext(batch_interval_ms=10, clock=ManualClock())
        fs = TextFileStream(ssc, tmp_path / "gone")
        got = []
        fs.foreach_batch(lambda _t, b: got.append(list(b)))
        ssc.generate_batch(10)  # missing directory: empty, no crash
        (tmp_path / "gone").mkdir()
        bad = tmp_path / "gone" / "bad.txt"
        bad.write_bytes(b"caf\xe9\n")  # not valid utf-8
        ssc.generate_batch(20)
        assert got and "caf" in got[0][0]  # replacement, not a dead thread
        # transient open failure is retried: simulate via a flaky open
        flaky = tmp_path / "gone" / "flaky.txt"
        flaky.write_text("later\n")
        real_open = open
        calls = {"n": 0}

        def flaky_open(path, *a, **kw):
            if str(path).endswith("flaky.txt") and calls["n"] == 0:
                calls["n"] += 1
                raise PermissionError("transient")
            return real_open(path, *a, **kw)

        import builtins

        monkeypatch.setattr(builtins, "open", flaky_open)
        ssc.generate_batch(30)   # open fails once; file NOT marked seen
        ssc.generate_batch(40)   # retried successfully
        monkeypatch.undo()
        assert ["later"] in got
        # pruning: a deleted name leaves _seen
        bad.unlink()
        ssc.generate_batch(50)
        assert "bad.txt" not in fs._seen
