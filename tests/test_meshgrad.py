"""Multi-chip mesh gradient plane (ISSUE 11): shard_map batch-parallel
worker steps, the resolve_shard_map compat shim, donated-buffer fused
apply, and the async.mesh.devices knob.

The correctness spine:

- the mesh ASGD worker step is numerically EQUAL (f32 tolerance 0) to
  the single-device computation of the same batch: identical Bernoulli
  draw (replicated full-length mask, device-count-invariant) and a
  ``lax.psum`` whose CPU all-reduce is a sequential device-order fold --
  the oracle reproduces both on one device, bit for bit;
- the mesh ASAGA step's candidate scalars are EXACTLY the single-device
  step's (each sampled slot has one owning device; psum adds zeros);
- ``async.mesh.devices=0`` is byte-identical on the wire and
  step-identical to the knob being absent (per-op frame-byte totals
  under a fixed seed);
- the donated fused-apply kernels are bit-identical to the undonated
  ones (donation changes aliasing, never values);
- mesh workers ride the serial AND pipelined loops to full coverage,
  clamp cleanly when the conf asks for more chips than the rig has, and
  keep exactly-once push semantics under seeded PUSH chaos.
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from asyncframework_tpu.conf import AsyncConf, set_global_conf
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.net import faults, frame, reset_net_totals
from asyncframework_tpu.net.faults import DROP_REPLY, FaultSchedule
from asyncframework_tpu.ops import steps
from asyncframework_tpu.ops.gradients import least_squares_grad_sum, mm_f32
from asyncframework_tpu.parallel import ps_dcn
from asyncframework_tpu.parallel.mesh import (
    make_mesh,
    pad_and_shard,
    resolve_shard_map,
)
from asyncframework_tpu.solvers import SolverConfig

pytestmark = pytest.mark.mesh


def make_cfg(**kw):
    defaults = dict(
        num_workers=2, num_iterations=60, gamma=1.2, taw=2**31 - 1,
        batch_rate=0.3, bucket_ratio=0.0, printer_freq=20, seed=42,
        calibration_iters=8, run_timeout_s=120.0,
    )
    defaults.update(kw)
    return SolverConfig(**defaults)


@pytest.fixture(autouse=True)
def _clean_state():
    ps_dcn.reset_pipeline_totals()
    reset_net_totals()
    faults.clear()
    yield
    ps_dcn.reset_pipeline_totals()
    reset_net_totals()
    faults.clear()
    set_global_conf(None)


def run_dcn(devices, cfg, conf, nw=None, n=1024, d=16, seed=11,
            algo="asgd", deadline_s=120.0):
    """One in-process PS + worker-process run under ``conf``."""
    nw = nw if nw is not None else cfg.num_workers
    set_global_conf(conf)
    ds = ShardedDataset.generate_on_device(n, d, nw, devices=devices[:nw],
                                           seed=seed, noise=0.01)
    ps = ps_dcn.ParameterServer(cfg, d, n, device=devices[0], port=0,
                                algo=algo).start()
    try:
        shards = {w: ds.shard(w) for w in range(nw)}
        counts = ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(nw)), shards, cfg, d, n,
            deadline_s=deadline_s, algo=algo,
        )
        done = ps.wait_done(timeout_s=10.0)
        return ps, counts, done
    finally:
        ps.stop()


# ----------------------------------------------------------- compat shim
class TestResolveShardMap:
    def test_resolves_on_this_install(self):
        """The shim must hand back a WORKING shard_map on whatever jax
        the container has -- native ``jax.shard_map`` or the
        ``jax.experimental.shard_map`` fallback with ``check_vma``
        translated away."""
        smap = resolve_shard_map()
        assert callable(smap)
        if hasattr(jax, "shard_map"):
            assert smap is jax.shard_map

    def test_shimmed_psum_program_runs(self, devices8):
        import functools

        from jax.sharding import PartitionSpec as P

        mesh = make_mesh(8, devices=devices8)

        @functools.partial(
            resolve_shard_map(), mesh=mesh, in_specs=P("dp"),
            out_specs=P(None), check_vma=True,
        )
        def total(x):
            return jax.lax.psum(jnp.sum(x), "dp")

        out = jax.jit(total)(np.arange(64, dtype=np.float32))
        assert float(out) == float(np.arange(64).sum())


# ------------------------------------------------------- make_mesh clamp
class TestMakeMeshClamp:
    def test_default_still_raises_on_overask(self):
        avail = len(jax.devices())
        with pytest.raises(ValueError, match="devices are available"):
            make_mesh(avail + 1)

    def test_clamp_logs_and_degrades(self, caplog):
        avail = len(jax.devices())
        with caplog.at_level(logging.WARNING,
                             logger="asyncframework_tpu.parallel.mesh"):
            mesh = make_mesh(avail + 5, clamp=True)
        assert mesh.devices.size == avail
        assert any("clamping" in r.message for r in caplog.records)


# -------------------------------------------------------- step numerics
class TestMeshStepNumerics:
    def _problem(self, n=1024, d=64, seed=0):
        rng = np.random.default_rng(seed)
        X = rng.standard_normal((n, d)).astype(np.float32)
        y = rng.standard_normal(n).astype(np.float32)
        w = rng.standard_normal(d).astype(np.float32)
        return X, y, w

    @pytest.mark.parametrize("n_dev", [2, 8])
    def test_asgd_mesh_step_equals_single_device_tol0(self, devices8,
                                                      n_dev):
        """The mesh step's gradient == the single-device computation of
        the same batch at f32 tolerance ZERO.  The oracle reproduces the
        two mesh mechanics on one device: (a) the replicated full-length
        Bernoulli draw (so the sampled rows are identical by
        construction -- and identical to make_asgd_worker_step's dense
        mask on an unpadded shard), and (b) psum's reduction order,
        which on this backend is a sequential device-order fold of the
        per-block partials (each partial computed by the SAME grad_sum
        XLA program at the block shape)."""
        X, y, w = self._problem()
        n = X.shape[0]
        assert n % n_dev == 0  # unpadded: draw identical to serial step
        mesh = make_mesh(n_dev, devices=devices8[:n_dev])
        Xs, ys, vs, _n = pad_and_shard(mesh, X, y)
        key = jax.random.fold_in(jax.random.PRNGKey(42), 7)
        step = steps.make_mesh_asgd_worker_step(0.3, mesh)
        g, key_out = step(Xs, ys, vs, jnp.asarray(w), key)
        g = np.asarray(g)

        # single-device oracle: same draw, per-block partials, seq fold
        key_ref, sub = jax.random.split(key)
        mask = np.asarray(
            jax.random.bernoulli(sub, 0.3, (n,))
        ).astype(np.float32)
        blk = n // n_dev
        parts = [
            np.asarray(least_squares_grad_sum(
                X[p * blk:(p + 1) * blk], y[p * blk:(p + 1) * blk], w,
                mask[p * blk:(p + 1) * blk],
            ))
            for p in range(n_dev)
        ]
        acc = parts[0].copy()
        for part in parts[1:]:
            acc = (acc + part).astype(np.float32)
        np.testing.assert_array_equal(g, acc)  # tolerance 0
        # the PRNG chain advances exactly like the single-device step
        np.testing.assert_array_equal(np.asarray(key_out),
                                      np.asarray(key_ref))
        # sanity: the fold is the full-batch gradient up to f32
        # reassociation noise
        g_full = np.asarray(least_squares_grad_sum(X, y, w, mask))
        np.testing.assert_allclose(g, g_full, rtol=5e-5, atol=5e-4)

    def test_saga_mesh_step_matches_single_device(self, devices8):
        """Candidate scalars are EXACT (one owner per sampled slot; the
        psum adds zeros to the owner's value) and the fused gradient
        matches the single-device step to f32 reassociation noise."""
        X, y, w = self._problem(n=1024, d=32, seed=3)
        n = X.shape[0]
        rng = np.random.default_rng(5)
        cap = 160
        idx = np.sort(rng.choice(n, cap, replace=False)).astype(np.int32)
        alpha = rng.standard_normal(cap).astype(np.float32)
        n_valid = np.int32(130)
        mesh = make_mesh(8, devices=devices8)
        Xs, ys, _vs, _n = pad_and_shard(mesh, X, y)
        mstep = steps.make_mesh_saga_dcn_worker_step(mesh)
        g, diff = mstep(Xs, ys, jnp.asarray(w), jnp.asarray(idx),
                        jnp.asarray(alpha), n_valid)
        ref = steps.make_saga_dcn_worker_step()
        g_ref, diff_ref = ref(X, y, w, idx, alpha, n_valid)
        np.testing.assert_array_equal(np.asarray(diff),
                                      np.asarray(diff_ref))
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=5e-4, atol=5e-4)
        # padding slots (>= n_valid) contribute exactly nothing
        assert not np.any(np.asarray(diff)[int(n_valid):])

    def test_mesh_step_sampling_is_device_count_invariant(self, devices8):
        """The replicated full-length draw makes the sampled row set a
        function of (key, padded length) alone: dp=2 and dp=8 meshes on
        an unpadded batch produce gradients from the SAME sample (both
        fold the same per-row terms, so they agree to reassociation
        noise -- a different sample would diverge at O(1))."""
        X, y, w = self._problem(n=512, d=16, seed=9)
        key = jax.random.fold_in(jax.random.PRNGKey(1), 0)
        outs = []
        for n_dev in (2, 8):
            mesh = make_mesh(n_dev, devices=devices8[:n_dev])
            Xs, ys, vs, _n = pad_and_shard(mesh, X, y)
            step = steps.make_mesh_asgd_worker_step(0.2, mesh)
            g, _ = step(Xs, ys, vs, jnp.asarray(w), key)
            outs.append(np.asarray(g))
        np.testing.assert_allclose(outs[0], outs[1], rtol=5e-5, atol=5e-4)


# ------------------------------------------------------- donated kernels
class TestDonatedApply:
    def test_asgd_merge_donated_bit_identical_to_undonated(self):
        rng = np.random.default_rng(0)
        d, m, n = 96, 8, 4096
        w = rng.standard_normal(d).astype(np.float32)
        G = rng.standard_normal((m, d)).astype(np.float32)
        mask = (rng.random(m) < 0.75).astype(np.float32)
        plain = steps.make_asgd_apply_merge(0.5, 0.1, n, 4)
        donated = steps.make_asgd_apply_merge(0.5, 0.1, n, 4,
                                              donate_model=True)
        w1, k1 = plain(jnp.asarray(w), jnp.asarray(G), jnp.asarray(mask),
                       jnp.float32(17.0))
        w2, k2 = donated(jnp.asarray(w), jnp.asarray(G),
                         jnp.asarray(mask), jnp.float32(17.0))
        np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
        assert float(k1) == float(k2) == 17.0 + float(mask.sum())

    def test_saga_merge_donated_bit_identical_to_undonated(self):
        rng = np.random.default_rng(1)
        d, m, n = 64, 6, 2048
        w = rng.standard_normal(d).astype(np.float32)
        ab = rng.standard_normal(d).astype(np.float32)
        G = rng.standard_normal((m, d)).astype(np.float32)
        mask = (rng.random(m) < 0.75).astype(np.float32)
        plain = steps.make_saga_apply_merge(0.3, 0.1, n, 4)
        donated = steps.make_saga_apply_merge(0.3, 0.1, n, 4,
                                              donate_model=True)
        r1 = plain(jnp.asarray(w), jnp.asarray(ab), jnp.asarray(G),
                   jnp.asarray(mask))
        r2 = donated(jnp.asarray(w), jnp.asarray(ab), jnp.asarray(G),
                     jnp.asarray(mask))
        for a, b in zip(r1, r2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_fused_drain_engages_on_contended_run(self, devices8):
        """A contended run must still exercise the (now donated) fused
        merge path -- and serve pulls / finish exactly -- proving the
        basis-redirect donation discipline holds on a live PS."""
        conf = (AsyncConf().set("async.push.merge", 8)
                .set("async.trace.sample", 0.0))
        cfg = make_cfg(num_workers=4, num_iterations=200,
                       bucket_ratio=0.5)
        ps, counts, done = run_dcn(devices8, cfg, conf, nw=4)
        assert done and ps.accepted == 200
        assert ps.merge_merged == 200
        assert ps.merge_batch_max >= 2, "fused path never engaged"


# ------------------------------------------------- knob=0 byte identity
class TestMeshKnobZeroIdentity:
    def test_devices0_conf_set_matches_unset_byte_identical(self,
                                                            devices8):
        """``async.mesh.devices=0`` is byte-identical on the wire and
        step-identical (accepted/dropped/staleness/clock) to the knob
        being absent, under a fixed seed -- the mesh plane off IS the
        legacy worker, not a lookalike."""
        results = []
        for mesh_conf in (None, "0"):
            conf = (AsyncConf().set("async.pull.mode", "full")
                    .set("async.trace.sample", 0.0))
            if mesh_conf is not None:
                conf.set("async.mesh.devices", mesh_conf)
            reset_net_totals()
            cfg = make_cfg(num_workers=1, num_iterations=40,
                           calibration_iters=10**9)
            ps, counts, done = run_dcn(devices8, cfg, conf, nw=1)
            assert done, "run did not finish"
            results.append({
                "accepted": ps.accepted,
                "dropped": ps.dropped,
                "max_staleness": ps.max_staleness,
                "clock": ps._clock,
                "pull_replies": dict(ps.pull_replies),
                "bytes": frame.bytes_totals(),
            })
        unset, zero = results
        assert unset["accepted"] == zero["accepted"] == 40
        assert unset["dropped"] == zero["dropped"]
        assert unset["max_staleness"] == zero["max_staleness"]
        assert unset["clock"] == zero["clock"]
        assert unset["pull_replies"] == zero["pull_replies"]
        assert unset["bytes"] == zero["bytes"], (unset["bytes"],
                                                 zero["bytes"])


# ------------------------------------------------------------ mesh runs
class TestMeshRuns:
    def test_serial_mesh_run_full_coverage(self, devices8):
        """Mesh workers on the serial loop: run completes exactly, every
        logical worker contributed accepted gradients, and the model
        stays finite."""
        conf = (AsyncConf().set("async.mesh.devices", 8)
                .set("async.trace.sample", 0.0))
        cfg = make_cfg(num_workers=4, num_iterations=160,
                       bucket_ratio=0.5)
        ps, counts, done = run_dcn(devices8, cfg, conf, nw=4, d=32)
        assert done and ps.accepted == 160
        for w in range(4):
            assert ps.accepted_by_wid.get(w, 0) > 0, ps.accepted_by_wid
        _times, W = ps.snapshot_stack()
        assert np.all(np.isfinite(W[-1]))

    def test_asaga_mesh_run_full_coverage(self, devices8):
        conf = (AsyncConf().set("async.mesh.devices", 8)
                .set("async.trace.sample", 0.0))
        cfg = make_cfg(num_workers=2, num_iterations=60, gamma=0.5)
        ps, counts, done = run_dcn(devices8, cfg, conf, nw=2, n=512,
                                   d=12, algo="asaga")
        assert done and ps.accepted == 60
        for w in range(2):
            assert ps.accepted_by_wid.get(w, 0) > 0, ps.accepted_by_wid

    def test_overask_clamps_and_still_completes(self, devices8):
        """A conf asking for more chips than the rig has (the dead-TPU /
        small-rig reality) must clamp and run, not crash the worker."""
        conf = (AsyncConf().set("async.mesh.devices", 64)
                .set("async.trace.sample", 0.0))
        cfg = make_cfg(num_workers=2, num_iterations=50)
        ps, counts, done = run_dcn(devices8, cfg, conf, nw=2)
        assert done and ps.accepted == 50

    def test_pipelined_mesh_run_full_coverage(self, devices8):
        """Mesh x pipelining (the PR 5 interaction): prefetched pulls
        stage the replicated model over the mesh while the previous
        step's psum runs; the run completes exactly with every worker
        contributing and the pipeline counters engaged."""
        conf = (AsyncConf().set("async.pull.mode", "delta")
                .set("async.pipeline.depth", 2)
                .set("async.mesh.devices", 8)
                .set("async.trace.sample", 0.0))
        cfg = make_cfg(num_workers=4, num_iterations=200,
                       bucket_ratio=0.5)
        ps, counts, done = run_dcn(devices8, cfg, conf, nw=4, d=32)
        assert done, "pipelined mesh run did not finish"
        assert ps.accepted == 200
        for w in range(4):
            assert ps.accepted_by_wid.get(w, 0) > 0, ps.accepted_by_wid
        pl = ps_dcn.pipeline_totals()
        assert pl.get("pushes_async", 0) >= 200
        assert (pl.get("prefetch_hits", 0)
                + pl.get("prefetch_waits", 0)) >= 200


class TestMeshConvergenceTelemetry:
    def test_conv_samples_fold_with_mesh_on(self, devices8):
        """Regression (review finding): the convergence sampler's
        shard-loss eval runs on the shard's own device -- handing it the
        mesh-replicated model handle raised an incompatible-devices
        error that conv_sample's protective except swallowed, silently
        blanking the PR 7 loss curves for every mesh run.  A mesh run
        with sampling on must fold a non-empty convergence history."""
        from asyncframework_tpu.metrics import timeseries as ts_mod

        ts_mod.convergence().reset()
        conf = (AsyncConf().set("async.mesh.devices", 8)
                .set("async.convergence.sample", 5)
                .set("async.trace.sample", 0.0))
        cfg = make_cfg(num_workers=2, num_iterations=60)
        try:
            ps, counts, done = run_dcn(devices8, cfg, conf, nw=2, d=32)
            assert done and ps.accepted == 60
            curves = ts_mod.convergence().curves()
            pts = curves.get("loss_vs_version") or curves.get(
                next(iter(curves), ""), [])
            assert pts, f"no convergence samples folded: {curves}"
            assert all(np.isfinite(p[1]) for p in pts)
        finally:
            ts_mod.convergence().reset()


# -------------------------------------------------------------- chaos
class TestMeshChaos:
    def test_push_drop_reply_exactly_once_with_mesh_worker(self,
                                                           devices8):
        """Seeded drop_reply on PUSH against a mesh worker: the retried
        push must be answered from the dedup window, never re-applied --
        the mesh plane changes WHERE the gradient is computed, not the
        wire's exactly-once contract."""
        conf = (AsyncConf().set("async.mesh.devices", 8)
                .set("async.trace.sample", 0.0))
        set_global_conf(conf)
        n, d, nw = 1024, 16, 2
        cfg = make_cfg(num_workers=nw, num_iterations=80)
        ds = ShardedDataset.generate_on_device(
            n, d, nw, devices=devices8[:nw], seed=11, noise=0.01,
        )
        ps = ps_dcn.ParameterServer(cfg, d, n, device=devices8[0],
                                    port=0).start()
        ep = f"127.0.0.1:{ps.port}"
        sched = (FaultSchedule(seed=13)
                 .add(ep, "PUSH", 4, DROP_REPLY)
                 .add(ep, "PUSH", 11, DROP_REPLY)
                 .add(ep, "PUSH", 17, DROP_REPLY))
        try:
            with faults.injected(sched) as inj:
                shards = {w: ds.shard(w) for w in range(nw)}
                counts = ps_dcn.run_worker_process(
                    "127.0.0.1", ps.port, list(range(nw)), shards, cfg,
                    d, n, deadline_s=120.0,
                )
                done = ps.wait_done(timeout_s=10.0)
                assert done, "mesh chaos run did not finish"
                assert ps.accepted == 80
                # exactly-once: every merged push maps to one computed
                # gradient (a double-applied retry would break this)
                assert ps._clock <= sum(counts.values()), (
                    ps._clock, counts,
                )
                # dropped ACKs forced retries of already-applied pushes:
                # the dedup window must have answered them
                assert ps.dedup_hits >= 1
                assert inj.remaining() == [], "all faults must fire"
        finally:
            ps.stop()
