"""SQL front door + data sources + extended joins (VERDICT item 6).

The acceptance bar: ``sql("SELECT k, SUM(v) FROM t GROUP BY k")`` over a
CSV-loaded frame matches pandas on a fixture; plus right/full/semi/anti
joins, WHERE/ORDER BY/LIMIT lowering, and CSV/JSON/Parquet readers.
"""

import numpy as np
import pandas as pd
import pytest

from asyncframework_tpu.sql import (
    ColumnarFrame,
    SQLContext,
    read_csv,
    read_json,
    read_parquet,
    sql,
    write_csv,
)

CSV_FIXTURE = """k,v,w
a,1,0.5
b,2,1.5
a,3,2.5
c,4,3.5
b,5,4.5
a,6,5.5
"""


@pytest.fixture()
def csv_path(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text(CSV_FIXTURE)
    return p


class TestReaders:
    def test_read_csv_types(self, csv_path):
        f = read_csv(csv_path)
        assert f.columns == ["k", "v", "w"]
        assert len(f) == 6
        assert np.asarray(f["v"]).dtype == np.int32
        assert np.asarray(f["w"]).dtype == np.float32
        assert np.asarray(f["k"]).dtype == object

    def test_csv_round_trip(self, csv_path, tmp_path):
        f = read_csv(csv_path)
        out = tmp_path / "copy.csv"
        write_csv(f, out)
        f2 = read_csv(out)
        np.testing.assert_allclose(np.asarray(f2["w"]), np.asarray(f["w"]))
        assert list(np.asarray(f2["k"])) == list(np.asarray(f["k"]))

    def test_read_json_lines(self, tmp_path):
        p = tmp_path / "t.jsonl"
        p.write_text('{"a": 1, "b": "x"}\n{"a": 2.5}\n{"b": "y", "a": 3}\n')
        f = read_json(p)
        np.testing.assert_allclose(np.asarray(f["a"]), [1.0, 2.5, 3.0])
        assert list(np.asarray(f["b"])) == ["x", "", "y"]

    def test_read_parquet(self, tmp_path):
        df = pd.DataFrame({"x": [1.0, 2.0, 3.0], "name": ["p", "q", "r"]})
        p = tmp_path / "t.parquet"
        df.to_parquet(p)
        f = read_parquet(p)
        np.testing.assert_allclose(np.asarray(f["x"]), [1.0, 2.0, 3.0])
        assert list(np.asarray(f["name"])) == ["p", "q", "r"]

    def test_csv_ragged_row_rejected(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("a,b\n1,2\n3\n")
        with pytest.raises(ValueError, match="row 2"):
            read_csv(p)

    def test_csv_extra_field_row_rejected(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text("a,b\n1,2\n3,4,5\n")
        with pytest.raises(ValueError, match="row 2"):
            read_csv(p)

    def test_fast_path_matches_python_path(self, tmp_path):
        """The pandas-C fast path must reproduce the python csv path's
        inference EXACTLY: missing-cell handling, int32 downcast, wide-int
        host columns, float32 rounding, string preservation."""
        from asyncframework_tpu.sql import io as sqlio

        body = (
            "i,f,s,m,wide,neg\n"
            "1,0.1,tag0,,99999999999,-3\n"
            "2,2.5,,7,88888888888,+4\n"
            "3,nan,x y,9,77777777777,0\n"
        )
        p = tmp_path / "t.csv"
        p.write_text(body)
        fast = sqlio._read_csv_fast(str(p), True, None, ",", None, None)
        # quoting forces the python path on an equivalent file (quotes
        # around a value that needs none parse away identically)
        p2 = tmp_path / "t2.csv"
        p2.write_text(body.replace("tag0", '"tag0"'))
        slow = read_csv(p2)
        assert fast.columns == slow.columns
        for c in fast.columns:
            a, b = np.asarray(fast[c]), np.asarray(slow[c])
            assert a.dtype == b.dtype, (c, a.dtype, b.dtype)
            if a.dtype.kind == "f":
                np.testing.assert_array_equal(
                    np.isnan(a), np.isnan(b)
                )
                np.testing.assert_array_equal(
                    a[~np.isnan(a)], b[~np.isnan(b)]
                )
            else:
                assert list(a) == list(b), c
        # dtypes follow the documented rules
        assert np.asarray(fast["i"]).dtype == np.int32
        assert np.asarray(fast["f"]).dtype == np.float32
        assert np.asarray(fast["s"]).dtype == object
        assert np.asarray(fast["m"]).dtype == np.float32  # nullable narrow
        assert np.asarray(fast["wide"]).dtype == object   # > 2**31 ids
        assert list(np.asarray(fast["s"])) == ["tag0", "", "x y"]


class TestSQLQueries:
    def test_group_by_sum_matches_pandas(self, csv_path):
        f = read_csv(csv_path)
        got = sql("SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY k", t=f)
        pdf = pd.read_csv(csv_path).groupby("k")["v"].sum().reset_index()
        assert list(np.asarray(got["k"])) == list(pdf["k"])
        np.testing.assert_allclose(np.asarray(got["s"]), pdf["v"].to_numpy())

    def test_where_and_expressions(self, csv_path):
        f = read_csv(csv_path)
        got = sql(
            "SELECT v * 2 + 1 AS z FROM t WHERE w > 1.0 AND v < 6", t=f
        )
        pdf = pd.read_csv(csv_path)
        expect = (pdf[(pdf.w > 1.0) & (pdf.v < 6)]["v"] * 2 + 1).to_numpy()
        np.testing.assert_allclose(np.asarray(got["z"]), expect)

    def test_string_predicate(self, csv_path):
        got = sql("SELECT v FROM t WHERE k = 'a'", t=read_csv(csv_path))
        np.testing.assert_allclose(sorted(np.asarray(got["v"])), [1, 3, 6])

    def test_whole_frame_aggregates(self, csv_path):
        got = sql("SELECT SUM(v) AS s, AVG(w) AS m, COUNT(*) AS n FROM t",
                  t=read_csv(csv_path))
        assert float(np.asarray(got["s"])[0]) == 21
        assert float(np.asarray(got["n"])[0]) == 6
        np.testing.assert_allclose(np.asarray(got["m"])[0], 3.0, rtol=1e-6)

    def test_order_by_desc_limit(self, csv_path):
        got = sql("SELECT v FROM t ORDER BY v DESC LIMIT 3",
                  t=read_csv(csv_path))
        np.testing.assert_allclose(np.asarray(got["v"]), [6, 5, 4])

    def test_group_count_star(self, csv_path):
        got = sql("SELECT k, COUNT(*) AS n FROM t GROUP BY k ORDER BY k",
                  t=read_csv(csv_path))
        np.testing.assert_allclose(np.asarray(got["n"]), [3, 2, 1])

    def test_join_in_sql(self, csv_path):
        f = read_csv(csv_path)
        dims = ColumnarFrame({"k": np.asarray(["a", "b"], object),
                              "scale": np.asarray([10.0, 100.0], np.float32)})
        got = sql(
            "SELECT k, v * scale AS sv FROM t JOIN d ON k ORDER BY sv",
            t=f, d=dims,
        )
        pdf = pd.read_csv(csv_path).merge(
            pd.DataFrame({"k": ["a", "b"], "scale": [10.0, 100.0]}), on="k"
        )
        expect = np.sort((pdf.v * pdf.scale).to_numpy())
        np.testing.assert_allclose(np.asarray(got["sv"]), expect)

    def test_context_registry_and_errors(self, csv_path):
        ctx = SQLContext()
        ctx.register("t", read_csv(csv_path))
        assert len(ctx.sql("SELECT * FROM t")) == 6
        with pytest.raises(KeyError, match="no table"):
            ctx.sql("SELECT * FROM missing")
        with pytest.raises(ValueError):
            ctx.sql("SELECT v FROM t WHERE")  # truncated expression
        with pytest.raises(ValueError, match="needs GROUP BY"):
            ctx.sql("SELECT v, SUM(v) FROM t")


class TestJoinFlavors:
    L = {"k": np.asarray(["a", "b", "c"], object),
         "x": np.asarray([1.0, 2.0, 3.0], np.float32)}
    R = {"k": np.asarray(["a", "b", "d"], object),
         "y": np.asarray([10.0, 20.0, 40.0], np.float32)}

    def frames(self):
        return ColumnarFrame(dict(self.L)), ColumnarFrame(dict(self.R))

    def pandas_join(self, how):
        return pd.DataFrame(self.L).merge(pd.DataFrame(self.R), on="k",
                                          how=how)

    @pytest.mark.parametrize("how", ["inner", "left", "right"])
    def test_matches_pandas(self, how):
        lf, rf = self.frames()
        got = lf.join(rf, on="k", how=how)
        pdf = self.pandas_join(how).sort_values("k").reset_index(drop=True)
        gk = np.asarray(got["k"])
        order = np.argsort(gk)
        assert list(gk[order]) == list(pdf["k"])
        np.testing.assert_allclose(
            np.asarray(got["x"])[order], pdf["x"].to_numpy(), equal_nan=True
        )
        np.testing.assert_allclose(
            np.asarray(got["y"])[order], pdf["y"].to_numpy(), equal_nan=True
        )

    def test_full_outer_matches_pandas(self):
        lf, rf = self.frames()
        got = lf.join(rf, on="k", how="full")
        pdf = self.pandas_join("outer").sort_values("k").reset_index(drop=True)
        gk = np.asarray(got["k"])
        order = np.argsort(gk)
        assert list(gk[order]) == list(pdf["k"])
        np.testing.assert_allclose(
            np.asarray(got["x"])[order], pdf["x"].to_numpy(), equal_nan=True
        )
        np.testing.assert_allclose(
            np.asarray(got["y"])[order], pdf["y"].to_numpy(), equal_nan=True
        )

    def test_semi_and_anti(self):
        lf, rf = self.frames()
        semi = lf.join(rf, on="k", how="semi")
        anti = lf.join(rf, on="k", how="anti")
        assert list(np.asarray(semi["k"])) == ["a", "b"]
        assert semi.columns == ["k", "x"]  # no right columns
        assert list(np.asarray(anti["k"])) == ["c"]

    def test_semi_does_not_duplicate(self):
        lf = ColumnarFrame({"k": np.asarray(["a"], object),
                            "x": np.asarray([1.0], np.float32)})
        rf = ColumnarFrame({"k": np.asarray(["a", "a", "a"], object),
                            "y": np.asarray([1, 2, 3], np.float32)})
        assert len(lf.join(rf, on="k", how="semi")) == 1

    def test_right_join_collision_keeps_left_bare(self):
        lf = ColumnarFrame({"k": np.asarray(["a", "b"], object),
                            "v": np.asarray([1.0, 2.0], np.float32)})
        rf = ColumnarFrame({"k": np.asarray(["a", "c"], object),
                            "v": np.asarray([10.0, 30.0], np.float32)})
        got = lf.join(rf, on="k", how="right")
        idx = {k: i for i, k in enumerate(np.asarray(got["k"]))}
        # same convention as every other flavor: bare = left, _right = right
        assert np.asarray(got["v"])[idx["a"]] == 1.0
        assert np.asarray(got["v_right"])[idx["a"]] == 10.0
        assert np.asarray(got["v_right"])[idx["c"]] == 30.0
        assert np.isnan(np.asarray(got["v"])[idx["c"]])

    def test_select_star_group_by_rejected(self, csv_path):
        with pytest.raises(ValueError, match="SELECT \\*"):
            sql("SELECT * FROM t GROUP BY k", t=read_csv(csv_path))
        with pytest.raises(ValueError, match="SELECT \\*"):
            sql("SELECT *, SUM(v) FROM t GROUP BY k", t=read_csv(csv_path))


class TestReviewRegressions:
    def test_wide_ints_survive_csv(self, tmp_path):
        p = tmp_path / "ids.csv"
        p.write_text("id,v\n3000000000,1\n9007199254740993,2\n")
        f = read_csv(p)
        ids = np.asarray(f["id"])
        assert ids.dtype == object  # host column: no silent wraparound
        assert ids[0] == 3000000000 and ids[1] == 9007199254740993

    def test_wide_ints_survive_json(self, tmp_path):
        p = tmp_path / "ids.jsonl"
        p.write_text('{"id": 20000001}\n{"id": 3000000000}\n')
        f = read_json(p)
        ids = np.asarray(f["id"])
        assert ids[0] == 20000001  # NOT the float32-rounded 20000000
        assert ids[1] == 3000000000

    def test_wide_ints_survive_parquet(self, tmp_path):
        df = pd.DataFrame({"id": np.asarray([3_000_000_000, 1], np.int64)})
        p = tmp_path / "ids.parquet"
        df.to_parquet(p)
        ids = np.asarray(read_parquet(p)["id"])
        assert ids[0] == 3_000_000_000

    def test_order_by_unprojected_column(self, csv_path):
        got = sql("SELECT v FROM t ORDER BY w DESC LIMIT 2",
                  t=read_csv(csv_path))
        np.testing.assert_allclose(np.asarray(got["v"]), [6, 5])

    def test_order_by_missing_from_aggregate_rejected(self, csv_path):
        with pytest.raises(ValueError, match="ORDER BY"):
            sql("SELECT k, SUM(v) AS s FROM t GROUP BY k ORDER BY w",
                t=read_csv(csv_path))

    def test_aggregate_over_expression(self, csv_path):
        got = sql("SELECT SUM(v * 2) AS s FROM t", t=read_csv(csv_path))
        assert float(np.asarray(got["s"])[0]) == 42
        grouped = sql(
            "SELECT k, SUM(v + w) AS s FROM t GROUP BY k ORDER BY k",
            t=read_csv(csv_path),
        )
        pdf = pd.read_csv(csv_path)
        expect = (pdf.v + pdf.w).groupby(pdf.k).sum()
        np.testing.assert_allclose(
            np.asarray(grouped["s"]), expect.to_numpy(), rtol=1e-6
        )

    def test_count_one_literal(self, csv_path):
        got = sql("SELECT COUNT(1) AS n FROM t", t=read_csv(csv_path))
        assert int(np.asarray(got["n"])[0]) == 6

    def test_two_unaliased_expression_aggs_both_survive(self, csv_path):
        got = sql("SELECT SUM(v * 2), SUM(v + 1) FROM t", t=read_csv(csv_path))
        assert len(got.columns) == 2
        vals = sorted(float(np.asarray(got[c])[0]) for c in got.columns)
        assert vals == [27.0, 42.0]  # sum(v)+6 and 2*sum(v)

    def test_nullable_wide_ints_stay_exact(self, tmp_path):
        p = tmp_path / "n.jsonl"
        p.write_text('{"id": 20000001}\n{"id": null}\n{"id": 3000000000}\n')
        ids = np.asarray(read_json(p)["id"])
        assert ids.dtype == object
        assert ids[0] == 20000001 and ids[1] is None and ids[2] == 3000000000

        c = tmp_path / "n.csv"
        c.write_text("id\n3000000000\n\n")
        got = np.asarray(read_csv(c)["id"])
        assert got.dtype == object and got[0] == 3000000000


class TestDistinctAndHaving:
    def _ctx(self):
        from asyncframework_tpu.sql.parser import SQLContext
        from asyncframework_tpu.sql.frame import ColumnarFrame
        import numpy as np

        ctx = SQLContext()
        ctx.register("t", ColumnarFrame({
            "k": np.array(["a", "a", "b", "b", "b", "c"]),
            "v": np.array([1.0, 1.0, 2.0, 3.0, 5.0, 9.0], np.float32),
        }))
        return ctx

    def test_select_distinct(self):
        ctx = self._ctx()
        out = ctx.sql("SELECT DISTINCT k, v FROM t")
        assert len(out) == 5  # the duplicate (a, 1.0) row collapses
        # first-seen order preserved
        assert list(np.asarray(out["k"])[:2]) == ["a", "b"]

    def test_having_with_alias(self):
        ctx = self._ctx()
        out = ctx.sql(
            "SELECT k, SUM(v) AS s FROM t GROUP BY k HAVING s > 2 "
            "ORDER BY s DESC"
        )
        assert list(np.asarray(out["k"])) == ["b", "c"]
        np.testing.assert_allclose(np.asarray(out["s"]), [10.0, 9.0])

    def test_having_with_aggregate_syntax(self):
        ctx = self._ctx()
        out = ctx.sql(
            "SELECT k, SUM(v) FROM t GROUP BY k HAVING SUM(v) > 2"
        )
        assert sorted(np.asarray(out["k"])) == ["b", "c"]

    def test_having_count_star(self):
        ctx = self._ctx()
        out = ctx.sql(
            "SELECT k, COUNT(*) FROM t GROUP BY k HAVING COUNT(*) >= 2"
        )
        assert sorted(np.asarray(out["k"])) == ["a", "b"]

    def test_distinct_matches_pandas(self):
        import pandas as pd

        ctx = self._ctx()
        out = ctx.sql("SELECT DISTINCT k FROM t")
        want = pd.DataFrame({"k": ["a", "a", "b", "b", "b", "c"]})[
            "k"
        ].drop_duplicates()
        assert list(np.asarray(out["k"])) == list(want)

    def test_having_aggregate_syntax_with_alias(self):
        ctx = self._ctx()
        out = ctx.sql(
            "SELECT k, SUM(v) AS s FROM t GROUP BY k HAVING SUM(v) > 2"
        )
        assert sorted(np.asarray(out["k"])) == ["b", "c"]
        assert sorted(out.columns) == ["k", "s"]  # bridge column dropped

    def test_distinct_collapses_nan(self):
        from asyncframework_tpu.sql.frame import ColumnarFrame
        from asyncframework_tpu.sql.parser import SQLContext

        ctx = SQLContext()
        ctx.register("f", ColumnarFrame({
            "v": np.array([np.nan, np.nan, 1.0, -0.0, 0.0], np.float32),
        }))
        out = ctx.sql("SELECT DISTINCT v FROM f")
        assert len(out) == 3  # {nan, 1.0, 0.0}: NaNs and both zeros collapse


class TestWindowFunctions:
    """Window functions vs pandas oracles (WindowExec / Window.partitionBy
    parity: ranking, offsets, whole-partition and running aggregates)."""

    def _fixture(self):
        from asyncframework_tpu.sql.frame import ColumnarFrame
        from asyncframework_tpu.sql.parser import SQLContext
        import pandas as pd

        rs = np.random.default_rng(0)
        k = np.array(list("abab" * 5))
        v = rs.integers(0, 8, 20).astype(np.float64)
        ctx = SQLContext()
        ctx.register("t", ColumnarFrame({"k": k, "v": v}))
        return ctx, pd.DataFrame({"k": k, "v": v})

    def test_row_number_rank_dense_rank(self):
        ctx, df = self._fixture()
        out = ctx.sql(
            "SELECT k, v, ROW_NUMBER() OVER (PARTITION BY k ORDER BY v) "
            "AS rn, RANK() OVER (PARTITION BY k ORDER BY v) AS r, "
            "DENSE_RANK() OVER (PARTITION BY k ORDER BY v) AS dr FROM t"
        )
        g = df.groupby("k")["v"]
        np.testing.assert_array_equal(
            np.asarray(out["rn"]), g.rank(method="first").astype(int)
        )
        np.testing.assert_array_equal(
            np.asarray(out["r"]), g.rank(method="min").astype(int)
        )
        np.testing.assert_array_equal(
            np.asarray(out["dr"]), g.rank(method="dense").astype(int)
        )

    def test_partition_and_running_aggregates(self):
        ctx, df = self._fixture()
        out = ctx.sql(
            "SELECT k, v, SUM(v) OVER (PARTITION BY k) AS tot, "
            "SUM(v) OVER (PARTITION BY k ORDER BY v) AS run, "
            "AVG(v) OVER (PARTITION BY k) AS m FROM t"
        )
        np.testing.assert_allclose(
            np.asarray(out["tot"]), df.groupby("k")["v"].transform("sum")
        )
        want_run = (
            df.sort_values(["k", "v"], kind="stable")
            .groupby("k")["v"].cumsum().sort_index()
        )
        np.testing.assert_allclose(np.asarray(out["run"]), want_run)
        np.testing.assert_allclose(
            np.asarray(out["m"]), df.groupby("k")["v"].transform("mean")
        )

    def test_lag_lead(self):
        ctx, df = self._fixture()
        out = ctx.sql(
            "SELECT k, v, LAG(v) OVER (PARTITION BY k ORDER BY v) AS p, "
            "LEAD(v, 2) OVER (PARTITION BY k ORDER BY v) AS nx FROM t"
        )
        s = df.sort_values(["k", "v"], kind="stable")
        np.testing.assert_allclose(
            np.asarray(out["p"]),
            s.groupby("k")["v"].shift(1).sort_index(), equal_nan=True,
        )
        np.testing.assert_allclose(
            np.asarray(out["nx"]),
            s.groupby("k")["v"].shift(-2).sort_index(), equal_nan=True,
        )

    def test_running_min_desc_and_global_window(self):
        ctx, df = self._fixture()
        # no PARTITION BY: one global partition; DESC running max = cummax
        out = ctx.sql(
            "SELECT v, MAX(v) OVER (ORDER BY v DESC) AS mx FROM t"
        )
        s = df.sort_values("v", ascending=False, kind="stable")
        want = s["v"].cummax().sort_index()
        np.testing.assert_allclose(np.asarray(out["mx"]), want)

    def test_window_rejects_group_by_mix(self):
        ctx, _ = self._fixture()
        with pytest.raises(ValueError):
            ctx.sql(
                "SELECT k, ROW_NUMBER() OVER (ORDER BY v) FROM t GROUP BY k"
            )

    def test_frame_level_api(self):
        from asyncframework_tpu.sql.frame import ColumnarFrame

        f = ColumnarFrame({
            "g": np.array(["x", "x", "y"]),
            "v": np.array([3.0, 1.0, 2.0]),
        })
        out = f.with_window("c", "count", None, partition_by="g")
        np.testing.assert_array_equal(np.asarray(out["c"]), [2, 2, 1])

    def test_window_on_empty_result(self):
        ctx, _ = self._fixture()
        out = ctx.sql(
            "SELECT k, ROW_NUMBER() OVER (PARTITION BY k ORDER BY v) AS rn "
            "FROM t WHERE v > 99"
        )
        assert len(out) == 0

    def test_star_plus_expr_plus_window(self):
        ctx, df = self._fixture()
        out = ctx.sql(
            "SELECT *, v + 1 AS w, ROW_NUMBER() OVER "
            "(PARTITION BY k ORDER BY v) AS rn FROM t"
        )
        assert sorted(out.columns) == ["k", "rn", "v", "w"]
        np.testing.assert_allclose(
            np.asarray(out["w"]), df["v"].to_numpy() + 1
        )

    def test_desc_order_large_int64_keys(self):
        from asyncframework_tpu.sql.frame import ColumnarFrame

        # distinct int64 keys above 2^53 must keep distinct ranks
        base = 1_700_000_000_000_000_000
        f = ColumnarFrame({"ts": np.array([base, base + 1, base + 2],
                                          np.int64)})
        out = f.with_window("rn", "row_number", None, order_by="ts",
                            ascending=False)
        np.testing.assert_array_equal(np.asarray(out["rn"]), [3, 2, 1])
