"""Round-2 library breadth: bisecting/streaming k-means, PrefixSpan,
association rules, kernel density, chi-sq selection, ranking/multilabel
metrics, random datasets, SCC, SVD++.
"""

import numpy as np
import pytest

from asyncframework_tpu.data import random_datasets
from asyncframework_tpu.engine.scheduler import JobScheduler
from asyncframework_tpu.ml import (
    AssociationRules,
    BisectingKMeans,
    ChiSqSelector,
    ElementwiseProduct,
    FPGrowth,
    KernelDensity,
    MultilabelMetrics,
    PrefixSpan,
    RankingMetrics,
    StreamingKMeans,
)


@pytest.fixture()
def blobs():
    rs = np.random.default_rng(0)
    centers = np.array([[-5, -5], [5, 5], [5, -5], [-5, 5]], np.float32)
    X = np.concatenate([
        c + 0.3 * rs.normal(size=(50, 2)).astype(np.float32)
        for c in centers
    ])
    return X, centers


class TestBisectingKMeans:
    def test_recovers_blobs(self, blobs):
        X, centers = blobs
        model = BisectingKMeans(k=4, seed=1).fit(X)
        assert model.k == 4
        # every true center has a recovered center nearby
        d = np.linalg.norm(
            model.centers[:, None, :] - centers[None, :, :], axis=2
        )
        assert d.min(axis=0).max() < 1.0
        # predictions separate the blobs perfectly
        labels = model.predict(X)
        for b in range(4):
            blk = labels[50 * b: 50 * (b + 1)]
            assert len(np.unique(blk)) == 1

    def test_fewer_than_k_when_indivisible(self):
        X = np.zeros((3, 2), np.float32)  # all identical: nothing to split
        model = BisectingKMeans(k=4).fit(X)
        assert model.k <= 4

    def test_min_divisible_gate(self, blobs):
        X, _ = blobs
        model = BisectingKMeans(k=4, min_divisible_cluster_size=1000).fit(X)
        assert model.k == 1  # nothing large enough to split


class TestStreamingKMeans:
    def test_tracks_moving_centers(self):
        rs = np.random.default_rng(1)
        skm = StreamingKMeans(k=2, decay_factor=0.5, seed=3)
        skm.set_initial_centers(
            np.array([[-1.0], [1.0]], np.float32), [1.0, 1.0]
        )
        for _ in range(20):
            batch = np.concatenate([
                -4 + 0.1 * rs.normal(size=(20, 1)),
                4 + 0.1 * rs.normal(size=(20, 1)),
            ]).astype(np.float32)
            skm.update(batch)
        c = np.sort(skm.centers.ravel())
        np.testing.assert_allclose(c, [-4.0, 4.0], atol=0.3)

    def test_decay_forgets_history(self):
        # decay=0.01/batch: after the data jumps, one batch dominates
        skm = StreamingKMeans(k=1, decay_factor=0.01)
        skm.set_initial_centers(np.array([[0.0]], np.float32), [1.0])
        skm.update(np.full((50, 1), 10.0, np.float32))
        skm.update(np.full((50, 1), -10.0, np.float32))
        assert abs(float(skm.centers[0, 0]) + 10.0) < 0.5

    def test_update_rule_exact(self):
        # c' = (c*n*a + sum) / (n*a + m) checked by hand
        skm = StreamingKMeans(k=1, decay_factor=0.5)
        skm.set_initial_centers(np.array([[2.0]], np.float32), [4.0])
        skm.update(np.array([[8.0], [10.0]], np.float32))
        # (2*4*0.5 + 18) / (4*0.5 + 2) = 22/4 = 5.5
        assert abs(float(skm.centers[0, 0]) - 5.5) < 1e-5
        assert abs(float(skm.weights[0]) - 4.0) < 1e-9

    def test_predict(self):
        skm = StreamingKMeans(k=2).set_initial_centers(
            np.array([[0.0], [10.0]], np.float32)
        )
        lab = skm.predict(np.array([[1.0], [9.0]], np.float32))
        assert lab[0] != lab[1]

    def test_no_point_center_unchanged(self):
        # a zero-weight user-supplied center that receives no points must
        # stay put (reference updates only clusters present in pointStats)
        skm = StreamingKMeans(k=2, decay_factor=0.5)
        skm.set_initial_centers(
            np.array([[0.0], [100.0]], np.float32), [1.0, 1.0]
        )
        skm.update(np.full((10, 1), 1.0, np.float32))  # all go to center 0
        assert abs(float(skm.centers[1, 0]) - 100.0) < 1e-6

    def test_dying_threshold_is_relative(self):
        # check is minWeight < 1e-8 * maxWeight: a weight of 10 is "dying"
        # next to a 1e10 heavyweight even though it passes any absolute bound
        skm = StreamingKMeans(k=2, decay_factor=1.0)
        skm.set_initial_centers(
            np.array([[0.0], [5.0]], np.float32), [10.0, 1e10]
        )
        skm.update(np.array([[0.0], [5.0]], np.float32))
        # cluster 0 was reseeded by splitting the heavy cluster
        assert abs(float(skm.weights[0]) - float(skm.weights[1])) < 1e-3
        assert abs(float(skm.centers[0, 0]) - 5.0) < 0.1


class TestPrefixSpan:
    def test_spark_docs_example(self):
        # the reference documentation's canonical example
        seqs = [
            [[1, 2], [3]],
            [[1], [3, 2], [1, 2]],
            [[1, 2], [5]],
            [[6]],
        ]
        out = PrefixSpan(min_support=0.5).run(seqs)
        found = {
            (tuple(sorted(s)) for s in f.sequence) and
            tuple(tuple(sorted(s)) for s in f.sequence): f.freq
            for f in out
        }
        assert found[((1,),)] == 3
        assert found[((2,),)] == 3
        assert found[((3,),)] == 2
        assert found[((1, 2),)] == 3
        assert found[((1,), (3,))] == 2
        # infrequent items never appear
        assert all(
            5 not in s and 6 not in s for pat in found for s in pat
        )

    def test_max_pattern_length(self):
        seqs = [[[1], [1], [1], [1]]] * 2
        out = PrefixSpan(min_support=1.0, max_pattern_length=2).run(seqs)
        assert max(sum(len(s) for s in f.sequence) for f in out) <= 2


class TestAssociationRules:
    def test_standalone_runner_matches_model(self):
        txs = [["a", "b"], ["a", "b", "c"], ["a", "c"], ["a"]]
        model = FPGrowth(min_support=0.5).run(txs)
        direct = model.association_rules(0.6)
        standalone = AssociationRules(0.6).run(
            model.itemsets(), model.num_transactions
        )
        assert direct == standalone
        # a -> nothing (a is in every tx but nothing implies from it at .6+)
        antecedents = {tuple(sorted(r.antecedent)) for r in standalone}
        assert ("b",) in antecedents  # b -> a with confidence 1.0
        conf = {
            (tuple(sorted(r.antecedent)), tuple(r.consequent)): r.confidence
            for r in standalone
        }
        assert conf[(("b",), ("a",))] == 1.0


class TestKernelDensity:
    def test_matches_scipy_oracle(self):
        rs = np.random.default_rng(5)
        sample = rs.normal(size=400)
        pts = np.linspace(-3, 3, 7)
        est = KernelDensity(bandwidth=0.5).set_sample(sample).estimate(pts)
        # direct numpy oracle
        z = (pts[None, :] - sample[:, None]) / 0.5
        want = (np.exp(-0.5 * z * z) / (0.5 * np.sqrt(2 * np.pi))).mean(0)
        np.testing.assert_allclose(est, want, rtol=1e-4, atol=1e-6)

    def test_validation(self):
        with pytest.raises(ValueError):
            KernelDensity(bandwidth=0.0)
        with pytest.raises(ValueError):
            KernelDensity().estimate([0.0])


class TestFeatureSelection:
    def test_chisq_selector_finds_informative(self):
        rs = np.random.default_rng(6)
        n = 400
        y = rs.integers(0, 2, n)
        X = np.zeros((n, 5))
        X[:, 1] = y  # perfectly informative
        X[:, 3] = y ^ (rs.random(n) < 0.1)  # mostly informative
        X[:, 0] = rs.integers(0, 3, n)
        X[:, 2] = rs.integers(0, 3, n)
        X[:, 4] = rs.integers(0, 2, n)
        model = ChiSqSelector(num_top_features=2).fit(X, y)
        assert set(model.selected) == {1, 3}
        out = np.asarray(model.transform(X))
        assert out.shape == (n, 2)
        np.testing.assert_array_equal(out[:, 0], X[:, 1])

    def test_elementwise_product(self):
        ep = ElementwiseProduct([1.0, 2.0, 3.0])
        out = np.asarray(ep.transform([[1.0, 1.0, 1.0], [2.0, 2.0, 2.0]]))
        np.testing.assert_allclose(out, [[1, 2, 3], [2, 4, 6]])
        np.testing.assert_allclose(
            np.asarray(ep.transform([1.0, 1.0, 1.0])), [1, 2, 3]
        )


class TestRankingMetrics:
    def test_reference_semantics(self):
        # adapted from the reference's RankingMetricsSuite fixture
        pairs = [
            ([1, 6, 2, 7, 8, 3, 9, 10, 4, 5], {1, 2, 3, 4, 5}),
            ([4, 1, 5, 6, 2, 7, 3, 8, 9, 10], {1, 2, 3}),
            ([1, 2, 3, 4, 5], set()),
        ]
        m = RankingMetrics(pairs)
        assert abs(m.precision_at(1) - 1 / 3) < 1e-9
        assert abs(m.precision_at(2) - (0.5 + 0.5 + 0.0) / 3) < 1e-9
        # MAP contribution of the empty-truth query is 0
        assert 0.0 < m.mean_average_precision() < 1.0
        assert m.ndcg_at(3) >= 0.0
        # perfect ranking: every metric is 1
        perfect = RankingMetrics([([1, 2, 3], {1, 2, 3})])
        assert perfect.precision_at(3) == 1.0
        assert perfect.mean_average_precision() == 1.0
        assert abs(perfect.ndcg_at(3) - 1.0) < 1e-9


class TestMultilabelMetrics:
    def test_hand_computed(self):
        pairs = [
            ({0, 1}, {0, 2}),
            ({0, 2}, {0, 2}),
            ({0}, {0, 1}),
        ]
        m = MultilabelMetrics(pairs)
        assert abs(m.subset_accuracy - 1 / 3) < 1e-9
        # doc accuracy: (1/3 + 1 + 1/2) / 3
        assert abs(m.accuracy - (1 / 3 + 1.0 + 0.5) / 3) < 1e-9
        assert abs(m.precision - (0.5 + 1.0 + 1.0) / 3) < 1e-9
        assert abs(m.recall - (0.5 + 1.0 + 0.5) / 3) < 1e-9
        tp = 1 + 2 + 1
        fp = 1 + 0 + 0
        fn = 1 + 0 + 1
        assert abs(m.micro_precision - tp / (tp + fp)) < 1e-9
        assert abs(m.micro_recall - tp / (tp + fn)) < 1e-9


class TestRandomDatasets:
    def test_generators_shapes_and_stats(self):
        sched = JobScheduler(num_workers=4)
        try:
            ds = random_datasets.normal_dataset(sched, 4000, seed=1)
            vals = np.asarray(ds.collect())
            assert vals.shape == (4000,)
            assert abs(vals.mean()) < 0.1 and abs(vals.std() - 1) < 0.1
            u = np.asarray(
                random_datasets.uniform_dataset(sched, 2000, seed=2).collect()
            )
            assert 0 <= u.min() and u.max() < 1
            p = np.asarray(
                random_datasets.poisson_dataset(sched, 2000, 3.0, seed=3)
                .collect()
            )
            assert abs(p.mean() - 3.0) < 0.3
            v = random_datasets.normal_vector_dataset(
                sched, 100, 8, seed=4
            ).collect()
            assert len(v) == 100 and v[0].shape == (8,)
        finally:
            sched.shutdown()


class TestReviewRegressions:
    def test_bisecting_continues_past_degenerate_leaf(self):
        # 40 identical rows (indivisible once split fails) + two separable
        # clusters: the degenerate leaf must not abort the whole loop
        rs = np.random.default_rng(11)
        X = np.concatenate([
            np.zeros((40, 2), np.float32),
            np.float32([10, 10]) + 0.1 * rs.normal(size=(6, 2)).astype(np.float32),
            np.float32([-10, 10]) + 0.1 * rs.normal(size=(6, 2)).astype(np.float32),
        ])
        model = BisectingKMeans(k=4, seed=0).fit(X)
        assert model.k == 4

    def test_association_rules_requires_count(self):
        with pytest.raises(ValueError):
            AssociationRules(0.5).run([(frozenset("ab"), 2)], 0)

    def test_map_counts_duplicate_predictions(self):
        m = RankingMetrics([([1, 1], {1})])
        assert abs(m.mean_average_precision() - 2.0) < 1e-9

    def test_svdpp_validates_bounds(self):
        from asyncframework_tpu.graph import svd_plus_plus

        with pytest.raises(ValueError):
            svd_plus_plus([0, 70], [0, 1], [1.0, 2.0], num_users=50,
                          num_iterations=1)


class TestSVDPPPersistence:
    def test_roundtrip(self, tmp_path):
        from asyncframework_tpu.graph import svd_plus_plus
        from asyncframework_tpu.ml import load_model, save_model

        m = svd_plus_plus([0, 0, 1], [0, 1, 1], [5.0, 1.0, 4.0],
                          rank=2, num_iterations=50)
        p = save_model(m, tmp_path / "svdpp")
        m2 = load_model(p)
        np.testing.assert_allclose(
            m.predict([0, 1], [0, 1]), m2.predict([0, 1], [0, 1])
        )


class TestStreamingRegression:
    def _stream(self, batches):
        from asyncframework_tpu.streaming import StreamingContext
        from asyncframework_tpu.utils.clock import ManualClock

        ssc = StreamingContext(batch_interval_ms=100, clock=ManualClock())
        return ssc, ssc.queue_stream(batches)

    def test_linear_tracks_drifting_weights(self):
        """The named behavior: the truth CHANGES mid-stream and the
        warm-started model must follow it to the new target."""
        from asyncframework_tpu.ml import StreamingLinearRegression

        rs = np.random.default_rng(0)
        d = 8
        w_a = rs.normal(size=(d,)).astype(np.float32)
        w_b = rs.normal(size=(d,)).astype(np.float32)
        batches = []
        for t in range(24):
            w_true = w_a if t < 12 else w_b  # drift at the midpoint
            X = rs.normal(size=(200, d)).astype(np.float32)
            batches.append((X, (X @ w_true).astype(np.float32)))
        ssc, stream = self._stream(batches)
        model = StreamingLinearRegression(step_size=0.5, num_iterations=20)
        model.train_on(stream)
        for k in range(1, 13):
            ssc.generate_batch(k * 100)
        np.testing.assert_allclose(
            model.latest_weights(), w_a, rtol=0.05, atol=0.02
        )
        for k in range(13, 25):
            ssc.generate_batch(k * 100)
        np.testing.assert_allclose(
            model.latest_weights(), w_b, rtol=0.05, atol=0.02
        )

    def test_logistic_predict_on_uses_interval_model(self):
        from asyncframework_tpu.ml import StreamingLogisticRegression

        rs = np.random.default_rng(1)
        d = 6
        w_true = np.zeros(d, np.float32)
        w_true[0] = 4.0
        train = []
        for _ in range(10):
            X = rs.normal(size=(300, d)).astype(np.float32)
            y = (X @ w_true > 0).astype(np.float32)
            train.append((X, y))
        ssc, stream = self._stream(train)
        model = StreamingLogisticRegression(step_size=1.0, num_iterations=20)
        model.set_initial_weights(np.zeros(d, np.float32))
        model.train_on(stream)
        preds = []
        Xq = rs.normal(size=(100, d)).astype(np.float32)
        pred_stream = ssc.queue_stream([Xq] * 10)
        model.predict_on(pred_stream).foreach_batch(
            lambda _t, p: preds.append(np.asarray(p))
        )
        for k in range(1, 11):
            ssc.generate_batch(k * 100)
        want = (Xq @ w_true > 0).astype(np.int32)
        acc = (preds[-1] == want).mean()
        assert acc > 0.95

    def test_warm_start_and_validation(self):
        from asyncframework_tpu.ml import StreamingLinearRegression

        m = StreamingLinearRegression()
        with pytest.raises(ValueError):
            m.latest_weights()
        m.set_initial_weights(np.ones(3, np.float32))
        np.testing.assert_allclose(m.latest_weights(), [1, 1, 1])


    def test_predict_on_requires_initialized_model(self):
        from asyncframework_tpu.ml import StreamingLinearRegression

        ssc, stream = self._stream([np.zeros((4, 3), np.float32)])
        with pytest.raises(ValueError, match="not initialized"):
            StreamingLinearRegression().predict_on(stream)

    def test_malformed_batch_raises(self):
        from asyncframework_tpu.ml import StreamingLinearRegression

        m = StreamingLinearRegression()
        with pytest.raises(ValueError, match="feature matrices"):
            m._update((np.zeros(5, np.float32), np.zeros(5, np.float32)))
